"""Standalone C reproducer generation.

Capability parity with reference csource/csource.go:23-130: replay the
*exec bytecode* (not the arg tree) into a self-contained C program, so
the reproducer performs byte-for-byte the same copyins/calls/copyouts
the executor did; options Threaded/Collide/Repeat/Procs/Sandbox select
which runtime scaffolding is emitted (the reference #ifdef-prunes its
embedded common.h; we emit only the helpers the options need).
`build` compiles with gcc -static (ref csource.Build), falling back to
dynamic linking.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
from dataclasses import dataclass

import numpy as np

from syzkaller_tpu.prog import encodingexec as EE
from syzkaller_tpu.prog import model as M
from syzkaller_tpu.sys import types as T


@dataclass
class Options:
    threaded: bool = False
    collide: bool = False
    repeat: bool = False
    procs: int = 1
    sandbox: str = "none"     # none | setuid | namespace
    pid: int = 0
    tun: bool = False         # set up the syzt<pid> tap device first


class BuildError(Exception):
    pass


# -- bytecode decode (mirror of native/executor.cc decode_prog) -------------


@dataclass
class _Copyin:
    addr: int
    size: int
    value: "int | None" = None       # const
    ref: "tuple[int, int, int] | None" = None  # (idx, div, add)
    data: "bytes | None" = None


@dataclass
class _Call:
    nr: int
    name: str
    result_idx: "int | None"
    args: list  # ("const", size, v) | ("result", size, idx, div, add)
    copyins: list
    copyouts: list  # (result_idx, addr, size)


def _decode(p: M.Prog, pid: int) -> list[_Call]:
    words = np.frombuffer(EE.serialize_for_exec(p, pid), "<u8").tolist()
    pos = 0

    def rd():
        nonlocal pos
        w = words[pos]
        pos += 1
        return w

    def rd_arg():
        kind = rd()
        size = rd()
        if kind == EE.ARG_CONST:
            return ("const", size, rd())
        if kind == EE.ARG_RESULT:
            return ("result", size, rd(), rd(), rd())
        if kind == EE.ARG_DATA:
            n = size
            nw = (n + 7) // 8
            raw = b"".join(int(rd()).to_bytes(8, "little") for _ in range(nw))
            return ("data", size, raw[:n])
        raise ValueError(f"bad arg kind {kind}")

    calls: list[_Call] = []
    pending_copyins: list[_Copyin] = []
    ci = 0
    while True:
        w = rd()
        if w == EE.INSTR_EOF:
            break
        if w == EE.INSTR_COPYIN:
            addr = rd()
            a = rd_arg()
            if a[0] == "const":
                pending_copyins.append(_Copyin(addr, a[1], value=a[2]))
            elif a[0] == "result":
                pending_copyins.append(
                    _Copyin(addr, a[1], ref=(a[2], a[3], a[4])))
            else:
                pending_copyins.append(_Copyin(addr, a[1], data=a[2]))
            continue
        if w == EE.INSTR_COPYOUT:
            ridx, addr, size = rd(), rd(), rd()
            calls[-1].copyouts.append((ridx, addr, size))
            continue
        ridx = rd()
        nargs = rd()
        args = [rd_arg() for _ in range(nargs)]
        name = p.calls[ci].meta.name if ci < len(p.calls) else f"nr_{w}"
        calls.append(_Call(
            nr=w, name=name,
            result_idx=None if ridx == EE.NO_RESULT else ridx,
            args=args, copyins=pending_copyins, copyouts=[]))
        pending_copyins = []
        ci += 1
    return calls


# -- C emission -------------------------------------------------------------


def _c_bytes(data: bytes) -> str:
    return '"' + "".join(f"\\x{b:02x}" for b in data) + '"'


def _arg_expr(a) -> str:
    if a[0] == "const":
        return f"0x{a[2]:x}ul"
    if a[0] == "result":
        _, _size, idx, div, add = a
        e = f"r[{idx}]"
        if div:
            e = f"({e}/0x{div:x}ul)"
        if add:
            e = f"({e}+0x{add:x}ul)"
        return e
    raise ValueError("data arg at call position")


def generate(p: M.Prog, opts: "Options | None" = None) -> str:
    opts = opts or Options()
    calls = _decode(p, opts.pid)
    nresults = 0
    for c in calls:
        if c.result_idx is not None:
            nresults = max(nresults, c.result_idx + 1)
        for a in c.args:
            if a[0] == "result":
                nresults = max(nresults, a[2] + 1)
        for ridx, _, _ in c.copyouts:
            nresults = max(nresults, ridx + 1)
        for cin in c.copyins:
            if cin.ref is not None:
                nresults = max(nresults, cin.ref[0] + 1)
    nresults = max(nresults, 1)

    body: list[str] = []
    for i, c in enumerate(calls):
        body.append(f"\tcase {i}:")
        for cin in c.copyins:
            if cin.data is not None:
                body.append(f"\t\tNONFAILING(memcpy((void*)0x{cin.addr:x}, "
                            f"{_c_bytes(cin.data)}, {len(cin.data)}));")
            else:
                expr = (f"0x{cin.value:x}ul" if cin.value is not None else
                        _arg_expr(("result", cin.size, *cin.ref)))
                ctyp = {1: "uint8_t", 2: "uint16_t", 4: "uint32_t",
                        8: "uint64_t"}.get(cin.size, "uint64_t")
                body.append(f"\t\tNONFAILING(*(volatile {ctyp}*)"
                            f"0x{cin.addr:x} = ({ctyp})({expr}));")
        argv = ", ".join(_arg_expr(a) for a in c.args)
        if c.nr < 1000000:
            call_expr = f"syscall(0x{c.nr:x}ul{', ' if argv else ''}{argv})"
        elif c.nr in _PSEUDO_NR_SET:
            padded = [_arg_expr(a) for a in c.args] + ["0"] * (9 - len(c.args))
            call_expr = f"syz_pseudo(0x{c.nr:x}ul, {', '.join(padded)})"
        else:
            call_expr = "0 /* pseudo no-op: " + c.name + " */"
        if c.result_idx is not None:
            body.append(f"\t\tr[{c.result_idx}] = {call_expr}; "
                        f"/* {c.name} */")
        else:
            body.append(f"\t\t{call_expr}; /* {c.name} */")
        for ridx, addr, size in c.copyouts:
            ctyp = {1: "uint8_t", 2: "uint16_t", 4: "uint32_t",
                    8: "uint64_t"}.get(size, "uint64_t")
            body.append(f"\t\tNONFAILING(r[{ridx}] = "
                        f"*(volatile {ctyp}*)0x{addr:x});")
        body.append("\t\tbreak;")

    parts = [_HEADER, f"static uint64_t r[{nresults}];",
             f"#define NCALLS {len(calls)}",
             _SEGV_HELPERS]
    if opts.tun or any(c.nr in _PSEUDO_NR_SET for c in calls):
        helpers = _PSEUDO_HELPERS
        for token, name in (("%NR_OPEN_DEV%", "syz_open_dev"),
                            ("%NR_OPEN_PTS%", "syz_open_pts"),
                            ("%NR_FUSE_MOUNT%", "syz_fuse_mount"),
                            ("%NR_FUSEBLK_MOUNT%", "syz_fuseblk_mount"),
                            ("%NR_EMIT_ETHERNET%", "syz_emit_ethernet")):
            helpers = helpers.replace(token, str(T.PSEUDO_NRS[name]))
        parts.append(helpers)
    else:
        parts.append("static void initialize_tun(int proc) { (void)proc; }")
    if opts.threaded or opts.collide:
        parts.append(_THREADED_RUNNER.replace(
            "%COLLIDE%", "1" if opts.collide else "0"))
    else:
        parts.append(_SEQUENTIAL_RUNNER)
    parts.append("static void execute_call(int call)\n{\n\tswitch (call) {")
    parts.extend(body)
    parts.append("\t}\n}")
    if opts.sandbox == "setuid":
        parts.append(_SANDBOX_SETUID)
    elif opts.sandbox == "namespace":
        parts.append(_SANDBOX_NAMESPACE)
    else:
        parts.append("static void sandbox(void) {}")
    parts.append(_main_fn(opts))
    return "\n".join(parts) + "\n"


_HEADER = """// autogenerated by syzkaller-tpu prog2c; do not edit
#define _GNU_SOURCE
#include <pthread.h>
#include <sched.h>
#include <setjmp.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/prctl.h>
#include <sys/syscall.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>
#include <grp.h>
"""

_SEGV_HELPERS = """
static __thread sigjmp_buf segv_env;
static __thread int segv_armed;
static void segv_handler(int sig) { if (segv_armed) siglongjmp(segv_env, 1); _exit(sig); }
static void install_segv(void) {
\tsignal(SIGSEGV, segv_handler);
\tsignal(SIGBUS, segv_handler);
}
#define NONFAILING(...) do { segv_armed = 1; \\
\tif (!sigsetjmp(segv_env, 1)) { __VA_ARGS__; } segv_armed = 0; } while (0)
"""

_SEQUENTIAL_RUNNER = """
static void execute_call(int call);
static void execute_prog(void) {
\tfor (int i = 0; i < NCALLS; i++)
\t\texecute_call(i);
}
"""

_THREADED_RUNNER = """
static void execute_call(int call);
struct thread_t { pthread_t th; int created; int call; volatile int ready, done; };
static struct thread_t threads[16];
static void* thr(void* arg) {
\tstruct thread_t* t = (struct thread_t*)arg;
\tinstall_segv();
\tfor (;;) {
\t\twhile (!__atomic_load_n(&t->ready, __ATOMIC_ACQUIRE)) usleep(200);
\t\t__atomic_store_n(&t->ready, 0, __ATOMIC_RELAXED);
\t\texecute_call(t->call);
\t\t__atomic_store_n(&t->done, 1, __ATOMIC_RELEASE);
\t}
\treturn 0;
}
static void execute_prog(void) {
\tint collide = %COLLIDE%;
\tfor (int pass = 0; pass < 1 + collide; pass++) {
\t\tfor (int i = 0; i < NCALLS; i++) {
\t\t\tstruct thread_t* t = &threads[i % 16];
\t\t\tif (!t->created) { t->created = 1; t->done = 1; pthread_create(&t->th, 0, thr, t); }
\t\t\tfor (int w = 0; w < 225 && !__atomic_load_n(&t->done, __ATOMIC_ACQUIRE); w++) usleep(200);
\t\t\tt->call = i; t->done = 0;
\t\t\t__atomic_store_n(&t->ready, 1, __ATOMIC_RELEASE);
\t\t\tif (!(pass == 1 && collide && (i % 2)))
\t\t\t\tfor (int w = 0; w < 225 && !__atomic_load_n(&t->done, __ATOMIC_ACQUIRE); w++) usleep(200);
\t\t}
\t}
\tusleep(100*1000);
}
"""

# Pinned pseudo-syscall numbers (syzkaller_tpu/sys/types.py PSEUDO_NRS);
# the emitted helpers mirror native/executor.cc behavior.
_PSEUDO_NR_SET = frozenset(T.PSEUDO_NRS.values())

_PSEUDO_HELPERS = """
#include <arpa/inet.h>
#include <fcntl.h>
#include <linux/if.h>
#include <linux/if_tun.h>
#include <net/if_arp.h>
#include <sys/ioctl.h>
#if defined(__x86_64__) && __has_include(<linux/kvm.h>)
#include <linux/kvm.h>
#endif
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/sysmacros.h>
#include <termios.h>
#include <errno.h>

static int tun_fd = -1;

static void initialize_tun(int proc)
{
\tif (geteuid() != 0) return;
\ttun_fd = open("/dev/net/tun", O_RDWR);
\tif (tun_fd == -1) return;
\tchar name[IFNAMSIZ];
\tsnprintf(name, sizeof(name), "syzt%d", proc);
\tstruct ifreq ifr;
\tmemset(&ifr, 0, sizeof(ifr));
\tstrncpy(ifr.ifr_name, name, IFNAMSIZ - 1);
\tifr.ifr_flags = IFF_TAP | IFF_NO_PI;
\tif (ioctl(tun_fd, TUNSETIFF, &ifr) < 0) { close(tun_fd); tun_fd = -1; return; }
\tint ctl = socket(AF_INET, SOCK_DGRAM, 0);
\tif (ctl == -1) return;
\tuint32_t subnet = (172u << 24) | (20u << 16) | (((uint32_t)proc & 0xff) << 8);
\tmemset(&ifr, 0, sizeof(ifr)); strncpy(ifr.ifr_name, name, IFNAMSIZ - 1);
\tifr.ifr_hwaddr.sa_family = ARPHRD_ETHER; memset(ifr.ifr_hwaddr.sa_data, 0xaa, 6);
\tioctl(ctl, SIOCSIFHWADDR, &ifr);
\tmemset(&ifr, 0, sizeof(ifr)); strncpy(ifr.ifr_name, name, IFNAMSIZ - 1);
\tstruct sockaddr_in* sin = (struct sockaddr_in*)&ifr.ifr_addr;
\tsin->sin_family = AF_INET; sin->sin_addr.s_addr = htonl(subnet | 170);
\tioctl(ctl, SIOCSIFADDR, &ifr);
\tmemset(&ifr, 0, sizeof(ifr)); strncpy(ifr.ifr_name, name, IFNAMSIZ - 1);
\tsin = (struct sockaddr_in*)&ifr.ifr_netmask;
\tsin->sin_family = AF_INET; sin->sin_addr.s_addr = htonl(0xffffff00);
\tioctl(ctl, SIOCSIFNETMASK, &ifr);
\tmemset(&ifr, 0, sizeof(ifr)); strncpy(ifr.ifr_name, name, IFNAMSIZ - 1);
\tif (ioctl(ctl, SIOCGIFFLAGS, &ifr) == 0) {
\t\tifr.ifr_flags |= IFF_UP | IFF_RUNNING;
\t\tioctl(ctl, SIOCSIFFLAGS, &ifr);
\t}
\tstruct arpreq arp;
\tmemset(&arp, 0, sizeof(arp));
\tsin = (struct sockaddr_in*)&arp.arp_pa;
\tsin->sin_family = AF_INET; sin->sin_addr.s_addr = htonl(subnet | 187);
\tarp.arp_ha.sa_family = ARPHRD_ETHER; memset(arp.arp_ha.sa_data, 0xbb, 6);
\tarp.arp_flags = ATF_PERM | ATF_COM;
\tstrncpy(arp.arp_dev, name, sizeof(arp.arp_dev) - 1);
\tioctl(ctl, SIOCSARP, &arp);
\tclose(ctl);
}

static long syz_pseudo(uint64_t nr, uint64_t a0, uint64_t a1, uint64_t a2,
\t\tuint64_t a3, uint64_t a4, uint64_t a5, uint64_t a6,
\t\tuint64_t a7, uint64_t a8)
{
\t(void)a8;
\tswitch (nr) {
\tcase 1000001: { /* syz_open_dev */
\t\tif (a0 == 0xc || a0 == 0xb) {
\t\t\tchar p[64];
\t\t\tsnprintf(p, sizeof(p), "/dev/%s/%u:%u",
\t\t\t\ta0 == 0xc ? "char" : "block", (unsigned)(uint8_t)a1,
\t\t\t\t(unsigned)(uint8_t)a2);
\t\t\treturn open(p, O_RDWR, 0);
\t\t}
\t\tchar p[512]; p[0] = 0;
\t\tNONFAILING(strncpy(p, (const char*)a0, sizeof(p) - 1));
\t\tp[sizeof(p) - 1] = 0;
\t\tfor (char* c = p; *c; c++)
\t\t\tif (*c == '#') { *c = '0' + (char)(a1 % 10); a1 /= 10; }
\t\treturn open(p, a2, 0);
\t}
\tcase 1000002: { /* syz_open_pts */
\t\tint pts = -1;
\t\tif (ioctl(a0, TIOCGPTN, &pts)) return -1;
\t\tchar p[32];
\t\tsnprintf(p, sizeof(p), "/dev/pts/%d", pts);
\t\treturn open(p, a1, 0);
\t}
\tcase 1000003:   /* syz_fuse_mount */
\tcase 1000004: { /* syz_fuseblk_mount */
\t\tint blk = nr == 1000004;
\t\tuint64_t mode = blk ? a2 : a1, uid = blk ? a3 : a2;
\t\tuint64_t gid = blk ? a4 : a3, maxread = blk ? a5 : a4;
\t\tuint64_t blksize = blk ? a6 : 0, mf = blk ? a7 : a5;
\t\tint fd = open("/dev/fuse", O_RDWR);
\t\tif (fd == -1) return -1;
\t\tchar opts[256];
\t\tint n = snprintf(opts, sizeof(opts),
\t\t\t"fd=%d,user_id=%llu,group_id=%llu,rootmode=0%o", fd,
\t\t\t(unsigned long long)uid, (unsigned long long)gid,
\t\t\t(unsigned)mode & ~3u);
\t\tif (maxread) n += snprintf(opts + n, sizeof(opts) - n, ",max_read=%llu", (unsigned long long)maxread);
\t\tif (blksize) n += snprintf(opts + n, sizeof(opts) - n, ",blksize=%llu", (unsigned long long)blksize);
\t\tif (mode & 1) n += snprintf(opts + n, sizeof(opts) - n, ",default_permissions");
\t\tif (mode & 2) n += snprintf(opts + n, sizeof(opts) - n, ",allow_other");
\t\tchar target[256]; target[0] = 0;
\t\tNONFAILING(strncpy(target, (const char*)a0, sizeof(target) - 1));
\t\ttarget[sizeof(target) - 1] = 0;
\t\tmkdir(target, 0777);
\t\tif (blk) {
\t\t\tchar bdev[256]; bdev[0] = 0;
\t\t\tNONFAILING(strncpy(bdev, (const char*)a1, sizeof(bdev) - 1));
\t\t\tbdev[sizeof(bdev) - 1] = 0;
\t\t\tmknod(bdev, S_IFBLK | 0666, makedev(7, 199));
\t\t\tNONFAILING(syscall(SYS_mount, bdev, target, "fuseblk", mf, opts));
\t\t} else {
\t\t\tNONFAILING(syscall(SYS_mount, "", target, "fuse", mf, opts));
\t\t}
\t\treturn fd;
\t}
\tcase 1000005: { /* syz_emit_ethernet */
\t\tif (tun_fd < 0) return -1;
\t\tlong res = -1;
\t\tNONFAILING(res = write(tun_fd, (const void*)a0, a1));
\t\treturn res;
\t}
#if defined(__x86_64__) && __has_include(<linux/kvm.h>)
\tcase 1000006: { /* syz_kvm_setup_cpu (mirrors native/executor.cc) */
\t\tchar* mem = (char*)a2;
\t\tif (!mem) return -1;
\t\tstruct kvm_userspace_memory_region reg;
\t\tmemset(&reg, 0, sizeof(reg));
\t\treg.memory_size = 24 * 4096;
\t\treg.userspace_addr = a2;
\t\tif (ioctl(a0, KVM_SET_USER_MEMORY_REGION, &reg)) return -1;
\t\tuint64_t mode = a5 & 3, tp = 0, tl = 0;
\t\tif (a4) { NONFAILING(mode = ((uint64_t*)a3)[0] & 3;
\t\t\ttp = ((uint64_t*)a3)[1]; tl = ((uint64_t*)a3)[2]); }
\t\tuint64_t oc0 = 0, oc4 = 0, oef = 0, ofl = 0;
\t\tfor (uint64_t i = 0; i < a7 && i < 8; i++) {
\t\t\tuint64_t ot = 0, ov = 0;
\t\t\tNONFAILING(ot = ((uint64_t*)a6)[2*i]; ov = ((uint64_t*)a6)[2*i+1]);
\t\t\tif (ot == 1) oc0 |= ov; else if (ot == 2) oc4 |= ov;
\t\t\telse if (ot == 3) oef |= ov; else if (ot == 4) ofl |= ov;
\t\t}
\t\tif (tl > 16 * 4096) tl = 16 * 4096;
\t\tNONFAILING(memcpy(mem + 0x8000, (void*)tp, tl));
\t\tuint64_t* gdt = (uint64_t*)(mem + 0x4000);
\t\tuint64_t code = 0x00009b000000ffffULL, data = 0x000093000000ffffULL;
\t\tif (mode == 2) { code |= (0xfULL << 48) | (3ULL << 54);
\t\t\tdata |= (0xfULL << 48) | (3ULL << 54); }
\t\telse if (mode == 3) code |= 1ULL << 53;
\t\tgdt[0] = 0; gdt[1] = code; gdt[2] = data;
\t\tif (mode == 3) {
\t\t\tuint64_t* pml4 = (uint64_t*)(mem + 0x1000);
\t\t\tuint64_t* pdpt = (uint64_t*)(mem + 0x2000);
\t\t\tuint64_t* pd = (uint64_t*)(mem + 0x3000);
\t\t\tmemset(pml4, 0, 4096); memset(pdpt, 0, 4096); memset(pd, 0, 4096);
\t\t\tpml4[0] = 0x2000 | 3; pdpt[0] = 0x3000 | 3; pd[0] = 0x80 | 3;
\t\t}
\t\tmemset(mem + 0x5000, 0, 4096);
\t\tstruct kvm_sregs sr;
\t\tif (ioctl(a1, KVM_GET_SREGS, &sr)) return -1;
\t\tsr.gdt.base = 0x4000; sr.gdt.limit = 23;
\t\tsr.idt.base = 0x5000; sr.idt.limit = 0;
\t\tmemset(&sr.cs, 0, sizeof(sr.cs));
\t\tsr.cs.present = 1; sr.cs.s = 1; sr.cs.type = 0xb;
\t\tsr.ds = sr.cs; sr.ds.type = 0x3;
\t\tswitch (mode) {
\t\tcase 0: sr.cr0 &= ~1ULL; sr.cs.limit = sr.ds.limit = 0xffff; break;
\t\tcase 1: sr.cr0 |= 1; sr.cs.selector = 8; sr.ds.selector = 16;
\t\t\tsr.cs.limit = sr.ds.limit = 0xffff; break;
\t\tcase 2: sr.cr0 |= 1; sr.cs.selector = 8; sr.ds.selector = 16;
\t\t\tsr.cs.db = sr.ds.db = 1; sr.cs.g = sr.ds.g = 1;
\t\t\tsr.cs.limit = sr.ds.limit = 0xfffff; break;
\t\tcase 3: sr.cr3 = 0x1000; sr.cr4 |= 1 << 5; sr.efer |= 0x501;
\t\t\tsr.cr0 |= 0x80000001ULL; sr.cs.selector = 8; sr.ds.selector = 16;
\t\t\tsr.cs.l = 1; sr.ds.db = 1; sr.cs.g = sr.ds.g = 1;
\t\t\tsr.cs.limit = sr.ds.limit = 0xfffff; break;
\t\t}
\t\tsr.es = sr.ss = sr.fs = sr.gs = sr.ds;
\t\tsr.cr0 |= oc0; sr.cr4 |= oc4; sr.efer |= oef;
\t\tif (ioctl(a1, KVM_SET_SREGS, &sr)) return -1;
\t\tstruct kvm_regs rg;
\t\tmemset(&rg, 0, sizeof(rg));
\t\trg.rip = 0x8000; rg.rsp = 0x7000; rg.rflags = 2 | ofl;
#if defined(KVM_VCPUEVENT_VALID_SMM)
\t\tif (a5 & 8) {
\t\t\tstruct kvm_vcpu_events ev;
\t\t\tmemset(&ev, 0, sizeof(ev));
\t\t\tif (ioctl(a1, KVM_GET_VCPU_EVENTS, &ev) == 0) {
\t\t\t\tev.flags |= KVM_VCPUEVENT_VALID_SMM; ev.smi.smm = 1;
\t\t\t\tioctl(a1, KVM_SET_VCPU_EVENTS, &ev);
\t\t\t}
\t\t}
#endif
\t\treturn ioctl(a1, KVM_SET_REGS, &rg);
\t}
#endif
\t}
\treturn 0;
}
"""

_SANDBOX_SETUID = """
static void sandbox(void) {
\tprctl(PR_SET_PDEATHSIG, SIGKILL);
\tsetgroups(0, NULL);
\tsetresgid(65534, 65534, 65534);
\tsetresuid(65534, 65534, 65534);
}
"""

_SANDBOX_NAMESPACE = """
static void sandbox(void) {
\tprctl(PR_SET_PDEATHSIG, SIGKILL);
\tunshare(CLONE_NEWUSER | CLONE_NEWNS | CLONE_NEWNET);
}
"""


def _main_fn(opts: Options) -> str:
    one_run = f"""\
\t\tint pid = fork();
\t\tif (pid == 0) {{
\t\t\tinstall_segv();
\t\t\tinitialize_tun({opts.pid});
\t\t\tsandbox();
\t\t\tmmap((void*)0x20000000ul, 16 << 20, PROT_READ | PROT_WRITE,
\t\t\t     MAP_PRIVATE | MAP_ANONYMOUS | MAP_FIXED, -1, 0);
\t\t\texecute_prog();
\t\t\t_exit(0);
\t\t}}
\t\tint status;
\t\twhile (waitpid(pid, &status, 0) != pid) {{}}"""
    if opts.repeat:
        loop = f"\tfor (;;) {{\n{one_run}\n\t}}"
    else:
        loop = f"\t{{\n{one_run}\n\t}}"
    procs = ""
    if opts.procs > 1:
        procs = (f"\tfor (int p = 0; p < {opts.procs - 1}; p++)\n"
                 "\t\tif (fork() == 0) break;\n")
    return f"int main(void)\n{{\n{procs}{loop}\n\treturn 0;\n}}"


def build(source: str, out_path: "str | None" = None) -> str:
    """Compile a generated reproducer (ref csource.Build: gcc -static)."""
    if out_path is None:
        out_path = tempfile.mktemp(prefix="syz-repro-")
    with tempfile.NamedTemporaryFile("w", suffix=".c", delete=False) as f:
        f.write(source)
        src_path = f.name
    try:
        base = ["gcc", "-o", out_path, src_path, "-lpthread", "-O1", "-w"]
        for cmd in (base + ["-static"], base):
            r = subprocess.run(cmd, capture_output=True, text=True)
            if r.returncode == 0:
                return out_path
        raise BuildError(r.stderr)
    finally:
        os.unlink(src_path)
