"""Standalone C reproducer generation.

Capability parity with reference csource/csource.go:23-130: replay the
*exec bytecode* (not the arg tree) into a self-contained C program, so
the reproducer performs byte-for-byte the same copyins/calls/copyouts
the executor did; options Threaded/Collide/Repeat/Procs/Sandbox select
which runtime scaffolding is emitted (the reference #ifdef-prunes its
embedded common.h; we emit only the helpers the options need).
`build` compiles with gcc -static (ref csource.Build), falling back to
dynamic linking.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
from dataclasses import dataclass

import numpy as np

from syzkaller_tpu.prog import encodingexec as EE
from syzkaller_tpu.prog import model as M


@dataclass
class Options:
    threaded: bool = False
    collide: bool = False
    repeat: bool = False
    procs: int = 1
    sandbox: str = "none"     # none | setuid | namespace
    pid: int = 0


class BuildError(Exception):
    pass


# -- bytecode decode (mirror of native/executor.cc decode_prog) -------------


@dataclass
class _Copyin:
    addr: int
    size: int
    value: "int | None" = None       # const
    ref: "tuple[int, int, int] | None" = None  # (idx, div, add)
    data: "bytes | None" = None


@dataclass
class _Call:
    nr: int
    name: str
    result_idx: "int | None"
    args: list  # ("const", size, v) | ("result", size, idx, div, add)
    copyins: list
    copyouts: list  # (result_idx, addr, size)


def _decode(p: M.Prog, pid: int) -> list[_Call]:
    words = np.frombuffer(EE.serialize_for_exec(p, pid), "<u8").tolist()
    pos = 0

    def rd():
        nonlocal pos
        w = words[pos]
        pos += 1
        return w

    def rd_arg():
        kind = rd()
        size = rd()
        if kind == EE.ARG_CONST:
            return ("const", size, rd())
        if kind == EE.ARG_RESULT:
            return ("result", size, rd(), rd(), rd())
        if kind == EE.ARG_DATA:
            n = size
            nw = (n + 7) // 8
            raw = b"".join(int(rd()).to_bytes(8, "little") for _ in range(nw))
            return ("data", size, raw[:n])
        raise ValueError(f"bad arg kind {kind}")

    calls: list[_Call] = []
    pending_copyins: list[_Copyin] = []
    ci = 0
    while True:
        w = rd()
        if w == EE.INSTR_EOF:
            break
        if w == EE.INSTR_COPYIN:
            addr = rd()
            a = rd_arg()
            if a[0] == "const":
                pending_copyins.append(_Copyin(addr, a[1], value=a[2]))
            elif a[0] == "result":
                pending_copyins.append(
                    _Copyin(addr, a[1], ref=(a[2], a[3], a[4])))
            else:
                pending_copyins.append(_Copyin(addr, a[1], data=a[2]))
            continue
        if w == EE.INSTR_COPYOUT:
            ridx, addr, size = rd(), rd(), rd()
            calls[-1].copyouts.append((ridx, addr, size))
            continue
        ridx = rd()
        nargs = rd()
        args = [rd_arg() for _ in range(nargs)]
        name = p.calls[ci].meta.name if ci < len(p.calls) else f"nr_{w}"
        calls.append(_Call(
            nr=w, name=name,
            result_idx=None if ridx == EE.NO_RESULT else ridx,
            args=args, copyins=pending_copyins, copyouts=[]))
        pending_copyins = []
        ci += 1
    return calls


# -- C emission -------------------------------------------------------------


def _c_bytes(data: bytes) -> str:
    return '"' + "".join(f"\\x{b:02x}" for b in data) + '"'


def _arg_expr(a) -> str:
    if a[0] == "const":
        return f"0x{a[2]:x}ul"
    if a[0] == "result":
        _, _size, idx, div, add = a
        e = f"r[{idx}]"
        if div:
            e = f"({e}/0x{div:x}ul)"
        if add:
            e = f"({e}+0x{add:x}ul)"
        return e
    raise ValueError("data arg at call position")


def generate(p: M.Prog, opts: "Options | None" = None) -> str:
    opts = opts or Options()
    calls = _decode(p, opts.pid)
    nresults = 0
    for c in calls:
        if c.result_idx is not None:
            nresults = max(nresults, c.result_idx + 1)
        for a in c.args:
            if a[0] == "result":
                nresults = max(nresults, a[2] + 1)
        for ridx, _, _ in c.copyouts:
            nresults = max(nresults, ridx + 1)
        for cin in c.copyins:
            if cin.ref is not None:
                nresults = max(nresults, cin.ref[0] + 1)
    nresults = max(nresults, 1)

    body: list[str] = []
    for i, c in enumerate(calls):
        body.append(f"\tcase {i}:")
        for cin in c.copyins:
            if cin.data is not None:
                body.append(f"\t\tNONFAILING(memcpy((void*)0x{cin.addr:x}, "
                            f"{_c_bytes(cin.data)}, {len(cin.data)}));")
            else:
                expr = (f"0x{cin.value:x}ul" if cin.value is not None else
                        _arg_expr(("result", cin.size, *cin.ref)))
                ctyp = {1: "uint8_t", 2: "uint16_t", 4: "uint32_t",
                        8: "uint64_t"}.get(cin.size, "uint64_t")
                body.append(f"\t\tNONFAILING(*(volatile {ctyp}*)"
                            f"0x{cin.addr:x} = ({ctyp})({expr}));")
        argv = ", ".join(_arg_expr(a) for a in c.args)
        call_expr = (f"syscall(0x{c.nr:x}ul{', ' if argv else ''}{argv})"
                     if c.nr < 1000000 else "0 /* pseudo: " + c.name + " */")
        if c.result_idx is not None:
            body.append(f"\t\tr[{c.result_idx}] = {call_expr}; "
                        f"/* {c.name} */")
        else:
            body.append(f"\t\t{call_expr}; /* {c.name} */")
        for ridx, addr, size in c.copyouts:
            ctyp = {1: "uint8_t", 2: "uint16_t", 4: "uint32_t",
                    8: "uint64_t"}.get(size, "uint64_t")
            body.append(f"\t\tNONFAILING(r[{ridx}] = "
                        f"*(volatile {ctyp}*)0x{addr:x});")
        body.append("\t\tbreak;")

    parts = [_HEADER, f"static uint64_t r[{nresults}];",
             f"#define NCALLS {len(calls)}",
             _SEGV_HELPERS]
    if opts.threaded or opts.collide:
        parts.append(_THREADED_RUNNER.replace(
            "%COLLIDE%", "1" if opts.collide else "0"))
    else:
        parts.append(_SEQUENTIAL_RUNNER)
    parts.append("static void execute_call(int call)\n{\n\tswitch (call) {")
    parts.extend(body)
    parts.append("\t}\n}")
    if opts.sandbox == "setuid":
        parts.append(_SANDBOX_SETUID)
    elif opts.sandbox == "namespace":
        parts.append(_SANDBOX_NAMESPACE)
    else:
        parts.append("static void sandbox(void) {}")
    parts.append(_main_fn(opts))
    return "\n".join(parts) + "\n"


_HEADER = """// autogenerated by syzkaller-tpu prog2c; do not edit
#define _GNU_SOURCE
#include <pthread.h>
#include <sched.h>
#include <setjmp.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/prctl.h>
#include <sys/syscall.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>
#include <grp.h>
"""

_SEGV_HELPERS = """
static __thread sigjmp_buf segv_env;
static __thread int segv_armed;
static void segv_handler(int sig) { if (segv_armed) siglongjmp(segv_env, 1); _exit(sig); }
static void install_segv(void) {
\tsignal(SIGSEGV, segv_handler);
\tsignal(SIGBUS, segv_handler);
}
#define NONFAILING(...) do { segv_armed = 1; \\
\tif (!sigsetjmp(segv_env, 1)) { __VA_ARGS__; } segv_armed = 0; } while (0)
"""

_SEQUENTIAL_RUNNER = """
static void execute_call(int call);
static void execute_prog(void) {
\tfor (int i = 0; i < NCALLS; i++)
\t\texecute_call(i);
}
"""

_THREADED_RUNNER = """
static void execute_call(int call);
struct thread_t { pthread_t th; int created; int call; volatile int ready, done; };
static struct thread_t threads[16];
static void* thr(void* arg) {
\tstruct thread_t* t = (struct thread_t*)arg;
\tinstall_segv();
\tfor (;;) {
\t\twhile (!__atomic_load_n(&t->ready, __ATOMIC_ACQUIRE)) usleep(200);
\t\t__atomic_store_n(&t->ready, 0, __ATOMIC_RELAXED);
\t\texecute_call(t->call);
\t\t__atomic_store_n(&t->done, 1, __ATOMIC_RELEASE);
\t}
\treturn 0;
}
static void execute_prog(void) {
\tint collide = %COLLIDE%;
\tfor (int pass = 0; pass < 1 + collide; pass++) {
\t\tfor (int i = 0; i < NCALLS; i++) {
\t\t\tstruct thread_t* t = &threads[i % 16];
\t\t\tif (!t->created) { t->created = 1; t->done = 1; pthread_create(&t->th, 0, thr, t); }
\t\t\tfor (int w = 0; w < 225 && !__atomic_load_n(&t->done, __ATOMIC_ACQUIRE); w++) usleep(200);
\t\t\tt->call = i; t->done = 0;
\t\t\t__atomic_store_n(&t->ready, 1, __ATOMIC_RELEASE);
\t\t\tif (!(pass == 1 && collide && (i % 2)))
\t\t\t\tfor (int w = 0; w < 225 && !__atomic_load_n(&t->done, __ATOMIC_ACQUIRE); w++) usleep(200);
\t\t}
\t}
\tusleep(100*1000);
}
"""

_SANDBOX_SETUID = """
static void sandbox(void) {
\tprctl(PR_SET_PDEATHSIG, SIGKILL);
\tsetgroups(0, NULL);
\tsetresgid(65534, 65534, 65534);
\tsetresuid(65534, 65534, 65534);
}
"""

_SANDBOX_NAMESPACE = """
static void sandbox(void) {
\tprctl(PR_SET_PDEATHSIG, SIGKILL);
\tunshare(CLONE_NEWUSER | CLONE_NEWNS | CLONE_NEWNET);
}
"""


def _main_fn(opts: Options) -> str:
    one_run = """\
\t\tint pid = fork();
\t\tif (pid == 0) {
\t\t\tinstall_segv();
\t\t\tsandbox();
\t\t\tmmap((void*)0x20000000ul, 16 << 20, PROT_READ | PROT_WRITE,
\t\t\t     MAP_PRIVATE | MAP_ANONYMOUS | MAP_FIXED, -1, 0);
\t\t\texecute_prog();
\t\t\t_exit(0);
\t\t}
\t\tint status;
\t\twhile (waitpid(pid, &status, 0) != pid) {}"""
    if opts.repeat:
        loop = f"\tfor (;;) {{\n{one_run}\n\t}}"
    else:
        loop = f"\t{{\n{one_run}\n\t}}"
    procs = ""
    if opts.procs > 1:
        procs = (f"\tfor (int p = 0; p < {opts.procs - 1}; p++)\n"
                 "\t\tif (fork() == 0) break;\n")
    return f"int main(void)\n{{\n{procs}{loop}\n\treturn 0;\n}}"


def build(source: str, out_path: "str | None" = None) -> str:
    """Compile a generated reproducer (ref csource.Build: gcc -static)."""
    if out_path is None:
        out_path = tempfile.mktemp(prefix="syz-repro-")
    with tempfile.NamedTemporaryFile("w", suffix=".c", delete=False) as f:
        f.write(source)
        src_path = f.name
    try:
        base = ["gcc", "-o", out_path, src_path, "-lpthread", "-O1", "-w"]
        for cmd in (base + ["-static"], base):
            r = subprocess.run(cmd, capture_output=True, text=True)
            if r.returncode == 0:
                return out_path
        raise BuildError(r.stderr)
    finally:
        os.unlink(src_path)
