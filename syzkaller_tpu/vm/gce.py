"""GCE adapter: fuzz on Google Compute Engine VMs.

Capability parity with reference vm/gce/gce.go (258 LoC) without the
bespoke API wrapper: instance lifecycle (create from image, delete on
close), scp-based copy, ssh command execution, and the serial console
merged into the output stream via periodic `get-serial-port-output`
polling (GCE has no streaming console; the reference's console reader
does the same incremental-offset dance).

All control goes through the `gcloud` CLI as subprocesses — the
environment-portable equivalent of the reference's raw REST calls
(gce/gce.go) — so construction and argument shapes are testable with a
mocked subprocess layer.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time

from syzkaller_tpu.utils import log
from syzkaller_tpu.vm import base


class GceInstance(base.Instance):
    def __init__(self, cfg, index: int):
        self.cfg = cfg
        self.index = index
        self.name = f"{getattr(cfg, 'name', 'syzkaller-tpu')}-{index}"
        self.zone = getattr(cfg, "gce_zone", "") or "us-central1-b"
        self.machine = getattr(cfg, "machine_type", "") or "e2-standard-2"
        self.image = getattr(cfg, "gce_image", "")
        if not self.image:
            raise ValueError("gce: config needs 'gce_image'")
        self.gcloud = getattr(cfg, "gcloud", "") or "gcloud"
        self._merger = base.OutputMerger()
        self._console_stop = threading.Event()
        self._create()

    # -- lifecycle ---------------------------------------------------------

    def _gcloud(self, *args: str, timeout: float = 300.0,
                check: bool = True) -> subprocess.CompletedProcess:
        cmd = [self.gcloud, "compute", *args, "--zone", self.zone]
        log.logf(2, "gce-%d: %s", self.index, " ".join(cmd))
        return subprocess.run(cmd, capture_output=True, timeout=timeout,
                              check=check)

    def _create(self) -> None:
        # delete any leftover instance of the same name, then create
        self._gcloud("instances", "delete", self.name, "--quiet",
                     check=False, timeout=600.0)
        self._gcloud("instances", "create", self.name,
                     "--image", self.image,
                     "--machine-type", self.machine,
                     "--no-restart-on-failure", timeout=600.0)
        self._wait_ssh(getattr(self.cfg, "boot_timeout", 600.0))
        threading.Thread(target=self._console_poll, daemon=True).start()

    def _wait_ssh(self, timeout: float) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            r = self._gcloud("ssh", self.name, "--command", "true",
                             check=False, timeout=60.0)
            if r.returncode == 0:
                return
            time.sleep(10.0)
        raise TimeoutError(f"gce-{self.index}: ssh did not come up")

    def _console_poll(self) -> None:
        """Incremental serial-console tail (ref gce console reader):
        get-serial-port-output --start=<offset> every few seconds."""
        offset = 0

        class _Stream:
            def __init__(s):
                s.buf = b""

            def readline(s):
                nonlocal offset
                while not self._console_stop.is_set():
                    nl = s.buf.find(b"\n")
                    if nl >= 0:
                        line, s.buf = s.buf[: nl + 1], s.buf[nl + 1:]
                        return line
                    r = self._gcloud(
                        "instances", "get-serial-port-output", self.name,
                        "--start", str(offset), check=False, timeout=60.0)
                    if r.returncode == 0 and r.stdout:
                        offset += len(r.stdout)
                        s.buf += r.stdout
                    else:
                        time.sleep(5.0)
                return b""

            def close(s):
                pass

        self._merger.add("console", _Stream())

    # -- Instance interface ------------------------------------------------

    def copy(self, host_path: str) -> str:
        dst = "/" + os.path.basename(host_path)
        self._gcloud("scp", host_path, f"{self.name}:{dst}", timeout=600.0)
        return dst

    def forward(self, port: int) -> str:
        # reverse tunnel: guest's localhost:port -> manager host port
        subprocess.Popen(
            [self.gcloud, "compute", "ssh", self.name, "--zone", self.zone,
             "--", "-N", "-R", f"{port}:127.0.0.1:{port}"],
            stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL, start_new_session=True)
        return f"127.0.0.1:{port}"

    def run(self, command: str, timeout: float) -> base.RunHandle:
        proc = subprocess.Popen(
            [self.gcloud, "compute", "ssh", self.name, "--zone", self.zone,
             "--command", command],
            stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, start_new_session=True)
        self._merger.add("ssh", proc.stdout)

        def stop():
            try:
                proc.kill()
            except ProcessLookupError:
                pass

        return base.RunHandle(output=self._merger.output, stop=stop,
                              is_alive=lambda: proc.poll() is None)

    def close(self) -> None:
        self._console_stop.set()
        self._gcloud("instances", "delete", self.name, "--quiet",
                     check=False, timeout=600.0)


base.register("gce", GceInstance)
