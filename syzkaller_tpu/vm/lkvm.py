"""lkvm (kvmtool) adapter: lightweight sandbox VMs without disk images.

Capability parity with reference vm/kvm/kvm.go (268 LoC): `lkvm setup`
creates a host-shared sandbox rootfs under ~/.lkvm/<name>, the VM boots
`lkvm sandbox --kernel ...` running a poll-loop bootstrap script, copy
drops files straight into the shared rootfs, run hands the guest a
command by renaming it into the shared /syz-cmd path (completion =
file gone), and forward uses kvmtool's fixed user-network host address.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import threading
import time

from syzkaller_tpu.utils import log
from syzkaller_tpu.vm import base

HOST_ADDR = "192.168.33.1"   # kvmtool user-mode network host address

BOOTSTRAP = """#!/bin/sh
mount -t debugfs none /sys/kernel/debug/ 2>/dev/null
while true; do
    if [ -e /syz-cmd ]; then
        /syz-cmd
        rm -f /syz-cmd
    else
        sleep 1
    fi
done
"""


class LkvmInstance(base.Instance):
    def __init__(self, cfg, index: int):
        self.cfg = cfg
        self.index = index
        if not getattr(cfg, "kernel", ""):
            raise ValueError("lkvm requires kernel")
        self.bin = getattr(cfg, "lkvm", "") or "lkvm"
        self.sandbox = f"syz-{index}"
        self.sandbox_path = os.path.join(
            os.path.expanduser("~"), ".lkvm", self.sandbox)
        self._merger = base.OutputMerger()
        self._proc: "subprocess.Popen | None" = None
        self._boot()

    def _boot(self) -> None:
        shutil.rmtree(self.sandbox_path, ignore_errors=True)
        try:
            os.remove(self.sandbox_path + ".sock")
        except OSError:
            pass
        r = subprocess.run([self.bin, "setup", self.sandbox],
                           capture_output=True, timeout=120)
        if r.returncode != 0:
            raise RuntimeError(f"lkvm setup failed: {r.stdout[-200:]!r}")
        script = os.path.join(self.cfg.workdir, f"lkvm-boot-{self.index}.sh")
        with open(script, "w") as f:
            f.write(BOOTSTRAP)
        os.chmod(script, 0o700)
        args = [self.bin, "sandbox",
                "--disk", self.sandbox,
                "--kernel", self.cfg.kernel,
                "--params", "slub_debug=UZ " + getattr(self.cfg, "cmdline", ""),
                "--mem", str(getattr(self.cfg, "mem", 1024)),
                "--cpus", str(getattr(self.cfg, "cpu", 1)),
                "--network", "mode=user",
                "--sandbox", script]
        log.logf(1, "lkvm-%d: %s", self.index, " ".join(args))
        self._proc = subprocess.Popen(
            args, stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, start_new_session=True)
        self._merger.add("console", self._proc.stdout)
        # the poll loop answering proves the guest is up
        h = self.run("true", getattr(self.cfg, "boot_timeout", 600.0))
        deadline = time.time() + getattr(self.cfg, "boot_timeout", 600.0)
        while os.path.exists(self._cmd_path()):
            if time.time() > deadline:
                raise TimeoutError(f"lkvm-{self.index}: guest did not boot")
            if self._proc.poll() is not None:
                raise RuntimeError(f"lkvm-{self.index} exited during boot")
            time.sleep(1.0)
        h.stop()

    def _cmd_path(self) -> str:
        return os.path.join(self.sandbox_path, "syz-cmd")

    def copy(self, host_path: str) -> str:
        guest = "/" + os.path.basename(host_path)
        dst = os.path.join(self.sandbox_path, os.path.basename(host_path))
        shutil.copyfile(host_path, dst)
        os.chmod(dst, 0o777)
        return guest

    def forward(self, port: int) -> str:
        return f"{HOST_ADDR}:{port}"

    def run(self, command: str, timeout: float) -> base.RunHandle:
        tmp = self._cmd_path() + "-tmp"
        with open(tmp, "w") as f:
            f.write("#!/bin/sh\n" + command + "\n")
        os.chmod(tmp, 0o700)
        os.rename(tmp, self._cmd_path())   # atomic handoff to the guest
        done = threading.Event()

        def watch():
            deadline = time.time() + timeout
            while not done.is_set() and time.time() < deadline:
                if not os.path.exists(self._cmd_path()):
                    break  # guest consumed and finished the command
                if self._proc is None or self._proc.poll() is not None:
                    break
                time.sleep(1.0)
            done.set()

        threading.Thread(target=watch, daemon=True).start()
        return base.RunHandle(
            output=self._merger.output,
            stop=done.set,
            is_alive=lambda: (not done.is_set()
                              and self._proc is not None
                              and self._proc.poll() is None))

    def close(self) -> None:
        if self._proc is not None:
            try:
                os.killpg(self._proc.pid, 9)
            except (ProcessLookupError, PermissionError):
                self._proc.kill()
            self._proc.wait()
            self._proc = None
        shutil.rmtree(self.sandbox_path, ignore_errors=True)
        try:
            os.remove(self.sandbox_path + ".sock")
        except OSError:
            pass


base.register("lkvm", LkvmInstance)
base.register("kvm", LkvmInstance)   # the reference registers it as "kvm"
