"""VM abstraction: Instance interface + plugin registry.

Capability parity with reference vm/vm.go:20-75: the Instance seam
{Copy, Forward, Run, Close} behind a constructor registry, so schedulers
(qemu/local/adb/gce — and the BASELINE's 'tpu' type) plug in without
touching the manager.
"""

from __future__ import annotations

import queue
import threading
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Callable

_registry: dict[str, Callable[..., "Instance"]] = {}


def register(typ: str, ctor: Callable[..., "Instance"]) -> None:
    _registry[typ] = ctor


def create(typ: str, cfg, index: int) -> "Instance":
    ctor = _registry.get(typ)
    if ctor is None:
        raise ValueError(f"unknown VM type {typ!r} (known: {sorted(_registry)})")
    return ctor(cfg, index)


def types() -> list[str]:
    return sorted(_registry)


@dataclass
class RunHandle:
    """A running guest command: a merged output stream + liveness.
    Output chunks (bytes) arrive on `output`; EOF/errors push a sentinel
    (None = clean EOF, Exception = error)."""

    output: "queue.Queue[bytes | None | Exception]"
    stop: Callable[[], None]       # terminate the command
    is_alive: Callable[[], bool]


class Instance(ABC):
    """One test machine (ref vm/vm.go:20-36)."""

    index: int = 0

    @abstractmethod
    def copy(self, host_path: str) -> str:
        """Copy a file into the machine; returns the guest path."""

    @abstractmethod
    def forward(self, port: int) -> str:
        """Expose a manager-side TCP port to the guest; returns the
        address the guest should dial."""

    @abstractmethod
    def run(self, command: str, timeout: float) -> RunHandle:
        """Run a command in the machine, console+ssh output merged."""

    @abstractmethod
    def close(self) -> None:
        ...


class VmPool:
    """Resizable thread-per-instance VM pool — the autopilot's capacity
    seam.

    `runner(index, retire)` is the per-instance loop (the manager's VM
    loop: create instance, run fuzzer, monitor, reboot) and must return
    promptly once `retire` (a threading.Event) is set.  `resize(n)`
    moves the pool toward n instances: indices >= n are retired, and any
    index < n whose thread is missing OR dead is (re)spawned — so
    `resize(target)` doubles as the REPAIR operation that restores
    capacity after VM-loop threads die (the autopilot calls it when
    `live` falls below `target`)."""

    def __init__(self, runner: Callable, name: str = "vm-loop"):
        self._runner = runner
        self._name = name
        self._mu = threading.Lock()
        # index -> (thread, retire event); retired slots are dropped
        self._slots: dict[int, tuple[threading.Thread, threading.Event]] = {}
        self._target = 0

    @property
    def target(self) -> int:
        with self._mu:
            return self._target

    @property
    def live(self) -> int:
        """Threads currently alive and not retiring."""
        with self._mu:
            return sum(1 for t, ev in self._slots.values()
                       if t.is_alive() and not ev.is_set())

    def indices(self) -> "list[int]":
        with self._mu:
            return sorted(i for i, (t, ev) in self._slots.items()
                          if t.is_alive() and not ev.is_set())

    def resize(self, target: int) -> "dict[str, list[int]]":
        """Grow/shrink/repair to `target` instances; returns the
        {"spawned": [...], "retired": [...]} delta."""
        target = max(0, int(target))
        spawned: list[int] = []
        retired: list[int] = []
        with self._mu:
            self._target = target
            for i in sorted(self._slots):
                if i >= target:
                    t, ev = self._slots.pop(i)
                    ev.set()
                    retired.append(i)
            for i in range(target):
                cur = self._slots.get(i)
                if cur is not None and cur[0].is_alive() \
                        and not cur[1].is_set():
                    continue
                ev = threading.Event()
                t = threading.Thread(target=self._runner, args=(i, ev),
                                     name=f"{self._name}-{i}", daemon=True)
                self._slots[i] = (t, ev)
                t.start()
                spawned.append(i)
        return {"spawned": spawned, "retired": retired}

    def repair(self) -> "list[int]":
        """Respawn dead threads below the current target."""
        with self._mu:
            target = self._target
        return self.resize(target)["spawned"]

    def threads(self) -> "list[threading.Thread]":
        with self._mu:
            return [t for t, _ev in self._slots.values()]

    def stop_all(self, timeout: float = 10.0) -> int:
        """Retire every slot and join; returns how many threads failed
        to stop in time (leaked — the caller counts them)."""
        with self._mu:
            slots, self._slots = list(self._slots.values()), {}
            self._target = 0
        for _t, ev in slots:
            ev.set()
        leaked = 0
        for t, _ev in slots:
            t.join(timeout=timeout)
            if t.is_alive():
                leaked += 1
        return leaked


class OutputMerger:
    """Multiplex several byte streams into one queue, tee'd to an
    optional file (ref vm/merger.go:13-76)."""

    def __init__(self, tee_path: "str | None" = None):
        self.output: "queue.Queue[bytes | None | Exception]" = queue.Queue()
        self._active = 0
        self._mu = threading.Lock()
        self._tee = open(tee_path, "ab") if tee_path else None

    def add(self, name: str, stream) -> None:
        """stream: a file-like object with .read1/.readline returning bytes."""
        with self._mu:
            self._active += 1
        t = threading.Thread(target=self._pump, args=(name, stream), daemon=True)
        t.start()

    def _pump(self, name: str, stream) -> None:
        try:
            while True:
                chunk = stream.readline()
                if not chunk:
                    break
                if self._tee:
                    self._tee.write(chunk)
                    self._tee.flush()
                self.output.put(chunk)
        except (OSError, ValueError):
            pass
        finally:
            with self._mu:
                self._active -= 1
                if self._active == 0:
                    self.output.put(None)
            try:
                stream.close()
            except OSError:
                pass
