"""VM abstraction: Instance interface + plugin registry.

Capability parity with reference vm/vm.go:20-75: the Instance seam
{Copy, Forward, Run, Close} behind a constructor registry, so schedulers
(qemu/local/adb/gce — and the BASELINE's 'tpu' type) plug in without
touching the manager.
"""

from __future__ import annotations

import queue
import threading
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Callable

_registry: dict[str, Callable[..., "Instance"]] = {}


def register(typ: str, ctor: Callable[..., "Instance"]) -> None:
    _registry[typ] = ctor


def create(typ: str, cfg, index: int) -> "Instance":
    ctor = _registry.get(typ)
    if ctor is None:
        raise ValueError(f"unknown VM type {typ!r} (known: {sorted(_registry)})")
    return ctor(cfg, index)


def types() -> list[str]:
    return sorted(_registry)


@dataclass
class RunHandle:
    """A running guest command: a merged output stream + liveness.
    Output chunks (bytes) arrive on `output`; EOF/errors push a sentinel
    (None = clean EOF, Exception = error)."""

    output: "queue.Queue[bytes | None | Exception]"
    stop: Callable[[], None]       # terminate the command
    is_alive: Callable[[], bool]


class Instance(ABC):
    """One test machine (ref vm/vm.go:20-36)."""

    index: int = 0

    @abstractmethod
    def copy(self, host_path: str) -> str:
        """Copy a file into the machine; returns the guest path."""

    @abstractmethod
    def forward(self, port: int) -> str:
        """Expose a manager-side TCP port to the guest; returns the
        address the guest should dial."""

    @abstractmethod
    def run(self, command: str, timeout: float) -> RunHandle:
        """Run a command in the machine, console+ssh output merged."""

    @abstractmethod
    def close(self) -> None:
        ...


class OutputMerger:
    """Multiplex several byte streams into one queue, tee'd to an
    optional file (ref vm/merger.go:13-76)."""

    def __init__(self, tee_path: "str | None" = None):
        self.output: "queue.Queue[bytes | None | Exception]" = queue.Queue()
        self._active = 0
        self._mu = threading.Lock()
        self._tee = open(tee_path, "ab") if tee_path else None

    def add(self, name: str, stream) -> None:
        """stream: a file-like object with .read1/.readline returning bytes."""
        with self._mu:
            self._active += 1
        t = threading.Thread(target=self._pump, args=(name, stream), daemon=True)
        t.start()

    def _pump(self, name: str, stream) -> None:
        try:
            while True:
                chunk = stream.readline()
                if not chunk:
                    break
                if self._tee:
                    self._tee.write(chunk)
                    self._tee.flush()
                self.output.put(chunk)
        except (OSError, ValueError):
            pass
        finally:
            with self._mu:
                self._active -= 1
                if self._active == 0:
                    self.output.put(None)
            try:
                stream.close()
            except OSError:
                pass
