"""Local machine adapter: run the fuzzer on the host, no VM.

Capability parity with reference vm/local/local.go:151 — the CI /
development adapter. Crashes of the host kernel obviously aren't
recoverable, so this type is for pipeline testing and non-kernel
targets; it is also the seam the driver's hermetic manager test uses.
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess

from syzkaller_tpu.vm import base


class LocalInstance(base.Instance):
    def __init__(self, cfg, index: int):
        self.index = index
        self.workdir = os.path.join(cfg.workdir, f"local-{index}")
        os.makedirs(self.workdir, exist_ok=True)
        self._procs: list[subprocess.Popen] = []

    def copy(self, host_path: str) -> str:
        dst = os.path.join(self.workdir, os.path.basename(host_path))
        if os.path.abspath(host_path) != os.path.abspath(dst):
            shutil.copy2(host_path, dst)
            os.chmod(dst, 0o755)
        return dst

    def forward(self, port: int) -> str:
        return f"127.0.0.1:{port}"

    def run(self, command: str, timeout: float) -> base.RunHandle:
        merger = base.OutputMerger()
        # The fuzzer is launched as `python -m syzkaller_tpu...` with the
        # instance workdir as cwd; make the package importable from there
        # regardless of how the test process itself found it.
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        proc = subprocess.Popen(
            command, shell=True, cwd=self.workdir, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)
        self._procs.append(proc)
        merger.add("local", proc.stdout)

        def stop():
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()

        return base.RunHandle(output=merger.output, stop=stop,
                              is_alive=lambda: proc.poll() is None)

    def close(self) -> None:
        for p in self._procs:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                try:
                    p.kill()
                except ProcessLookupError:
                    pass
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass


base.register("local", LocalInstance)
