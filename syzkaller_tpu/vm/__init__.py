"""Machine layer: Instance plugin registry + adapters + monitor."""

from syzkaller_tpu.vm.base import (  # noqa: F401
    Instance, OutputMerger, RunHandle, VmPool, create, register, types,
)
from syzkaller_tpu.vm.monitor import Outcome, monitor_execution  # noqa: F401
from syzkaller_tpu.vm import local  # noqa: F401  (registers "local")
from syzkaller_tpu.vm import qemu  # noqa: F401   (registers "qemu")
from syzkaller_tpu.vm import adb  # noqa: F401    (registers "adb")
from syzkaller_tpu.vm import gce  # noqa: F401    (registers "gce")
from syzkaller_tpu.vm import lkvm  # noqa: F401   (registers "lkvm"/"kvm")
