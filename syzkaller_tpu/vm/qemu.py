"""QEMU adapter: boot kernels under qemu-system, drive them over ssh.

Capability parity with reference vm/qemu/qemu.go:41-180: boot with
kernel+initrd or disk image, user-mode networking with ssh port
forwarding, serial console piped into the output merger, scp-based file
copy, and hostfwd-based manager-port forwarding.
"""

from __future__ import annotations

import os
import socket
import subprocess
import time

from syzkaller_tpu.utils import log
from syzkaller_tpu.vm import base


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class QemuInstance(base.Instance):
    SSH_OPTS = ["-o", "StrictHostKeyChecking=no",
                "-o", "UserKnownHostsFile=/dev/null",
                "-o", "BatchMode=yes", "-o", "IdentitiesOnly=yes",
                "-o", "ConnectTimeout=10"]

    def __init__(self, cfg, index: int):
        self.cfg = cfg
        self.index = index
        self.workdir = os.path.join(cfg.workdir, f"qemu-{index}")
        os.makedirs(self.workdir, exist_ok=True)
        self.ssh_port = _free_port()
        self._fwd: dict[int, int] = {}  # manager port -> guest-visible port
        self._qemu: "subprocess.Popen | None" = None
        self._merger = base.OutputMerger(
            tee_path=os.path.join(self.workdir, "console.log"))
        self._boot()

    def _boot(self) -> None:
        c = self.cfg
        bin_ = getattr(c, "qemu", "") or "qemu-system-x86_64"
        args = [bin_,
                "-m", str(getattr(c, "mem", 1024)),
                "-smp", str(getattr(c, "cpu", 1)),
                "-display", "none", "-serial", "stdio", "-no-reboot",
                "-device", "virtio-rng-pci",
                "-enable-kvm" if os.path.exists("/dev/kvm") else "-accel",
                ]
        if not os.path.exists("/dev/kvm"):
            args.append("tcg")
        net = (f"user,id=net0,restrict=on,"
               f"hostfwd=tcp:127.0.0.1:{self.ssh_port}-:22")
        args += ["-netdev", net, "-device", "virtio-net-pci,netdev=net0"]
        kernel = getattr(c, "kernel", "")
        image = getattr(c, "image", "")
        if kernel:
            args += ["-kernel", kernel, "-append",
                     getattr(c, "cmdline",
                             "console=ttyS0 root=/dev/sda rw")]
        if image:
            if getattr(c, "image_9p", False):
                args += ["-fsdev",
                         f"local,id=fsdev0,path={image},security_model=none",
                         "-device",
                         "virtio-9p-pci,fsdev=fsdev0,mount_tag=/dev/root"]
            else:
                args += ["-drive", f"file={image},format=raw,if=ide"]
        if getattr(c, "initrd", ""):
            args += ["-initrd", c.initrd]
        log.logf(1, "qemu-%d: %s", self.index, " ".join(args))
        self._qemu = subprocess.Popen(
            args, cwd=self.workdir,
            stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, start_new_session=True)
        self._merger.add("console", self._qemu.stdout)
        self._wait_ssh(getattr(self.cfg, "boot_timeout", 10 * 60.0))

    def _ssh_base(self) -> list[str]:
        key = getattr(self.cfg, "sshkey", "")
        opts = list(self.SSH_OPTS)
        if key:
            opts += ["-i", key]
        return opts + ["-p", str(self.ssh_port)]

    def _wait_ssh(self, timeout: float) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self._qemu.poll() is not None:
                raise RuntimeError(f"qemu-{self.index} exited during boot")
            r = subprocess.run(
                ["ssh", *self._ssh_base(), "root@127.0.0.1", "true"],
                capture_output=True, timeout=30)
            if r.returncode == 0:
                return
            time.sleep(5)
        raise TimeoutError(f"qemu-{self.index}: ssh did not come up")

    def copy(self, host_path: str) -> str:
        dst = "/" + os.path.basename(host_path)
        subprocess.run(
            ["scp", *self._ssh_base(), "-P", str(self.ssh_port),
             host_path, f"root@127.0.0.1:{dst}"],
            check=True, capture_output=True, timeout=300)
        return dst

    def forward(self, port: int) -> str:
        # remote port forward: guest's localhost:port -> host port
        remote = self._fwd.get(port)
        if remote is None:
            remote = port
            subprocess.Popen(
                ["ssh", *self._ssh_base(), "-N",
                 "-R", f"{remote}:127.0.0.1:{port}", "root@127.0.0.1"],
                stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL, start_new_session=True)
            self._fwd[port] = remote
        return f"127.0.0.1:{remote}"

    def run(self, command: str, timeout: float) -> base.RunHandle:
        proc = subprocess.Popen(
            ["ssh", *self._ssh_base(), "root@127.0.0.1", command],
            stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, start_new_session=True)
        self._merger.add("ssh", proc.stdout)

        def stop():
            try:
                proc.kill()
            except ProcessLookupError:
                pass

        alive = (lambda: proc.poll() is None and
                 self._qemu is not None and self._qemu.poll() is None)
        return base.RunHandle(output=self._merger.output, stop=stop,
                              is_alive=alive)

    def close(self) -> None:
        if self._qemu is not None:
            try:
                os.killpg(self._qemu.pid, 9)
            except (ProcessLookupError, PermissionError):
                self._qemu.kill()
            self._qemu.wait()
            self._qemu = None


base.register("qemu", QemuInstance)
