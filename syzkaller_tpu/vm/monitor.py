"""Execution monitoring: classify a machine's console stream.

Capability parity with reference vm/vm.go:90-191 (MonitorExecution):
streaming oops scan via the report package over a bounded context
window, "no output" and overall timeouts, lost-connection and
"not executing programs" classification, and the preemption marker.
"""

from __future__ import annotations

import queue
import time
from dataclasses import dataclass

from syzkaller_tpu import report as report_pkg
from syzkaller_tpu.vm.base import RunHandle

NO_OUTPUT_TIMEOUT = 3 * 60.0      # ref vm.go: 3-min liveness
WAIT_FOR_REPORT = 5.0             # collect the full oops after detection
CONTEXT_WINDOW = 256 << 10        # ref vm.go 256KB window
TAIL_OVERLAP = 1 << 10            # re-scan this much before each new chunk
EXECUTING_MARKER = b"executing program"
PREEMPTED_MARKER = b"PREEMPTED"


@dataclass
class Outcome:
    title: str                       # crash description or timeout class
    report: "report_pkg.Report | None"
    output: bytes                    # full captured output
    crashed: bool
    timed_out: bool = False


def _classify(outcome: Outcome) -> str:
    """Telemetry label for a run outcome (stable, low-cardinality)."""
    if outcome.title == "preempted":
        return "preempted"
    if outcome.timed_out:
        return "timeout"
    if not outcome.crashed:
        return "ok"
    if outcome.title == "no output from test machine":
        return "no_output"
    if outcome.title == "lost connection to test machine":
        return "lost_connection"
    return "crash"


def monitor_execution(handle: RunHandle, timeout: float,
                      ignores=None, need_executing: bool = True,
                      outcomes=None) -> Outcome:
    """Consume the run's output until crash/timeout/EOF (ref vm.go:90).

    `outcomes`, when set, is a labeled telemetry counter family
    (labels=("outcome",)); every return increments its class —
    timeout / no_output / lost_connection / preempted / crash / ok —
    so fleet health is a /metrics query instead of a log grep."""
    out = _monitor(handle, timeout, ignores, need_executing)
    if outcomes is not None:
        try:
            outcomes.labels(outcome=_classify(out)).inc()
        except Exception:
            pass          # telemetry must never break run monitoring
    return out


def _monitor(handle: RunHandle, timeout: float,
             ignores=None, need_executing: bool = True) -> Outcome:
    buf = bytearray()
    window_start = 0
    deadline = time.time() + timeout
    last_output = time.time()
    saw_executing = not need_executing
    crashed_report: "report_pkg.Report | None" = None
    crash_deadline = None

    def window() -> bytes:
        return bytes(buf[window_start:])

    while True:
        now = time.time()
        if crash_deadline is not None and now >= crash_deadline:
            break
        if now >= deadline:
            # the normal outcome of a long run (ref manager.go:376-385)
            return Outcome(title="timed out", report=None, output=bytes(buf),
                           crashed=False, timed_out=True)
        if now - last_output > NO_OUTPUT_TIMEOUT:
            return Outcome(title="no output from test machine",
                           report=None, output=bytes(buf), crashed=True)
        try:
            chunk = handle.output.get(timeout=0.5)
        except queue.Empty:
            continue
        if chunk is None or isinstance(chunk, Exception):
            # stream closed: connection lost or clean exit
            if crashed_report is not None:
                break
            rep = report_pkg.parse(window(), ignores)
            if rep is not None:
                return _crash_outcome(rep, buf, window_start)
            title = ("lost connection to test machine"
                     if isinstance(chunk, Exception) else
                     ("no output from test machine" if not saw_executing
                      else "lost connection to test machine"))
            return Outcome(title=title, report=None, output=bytes(buf),
                           crashed=True)
        last_output = time.time()
        buf.extend(chunk)
        if EXECUTING_MARKER in chunk:
            saw_executing = True
        if PREEMPTED_MARKER in chunk:
            return Outcome(title="preempted", report=None, output=bytes(buf),
                           crashed=False, timed_out=True)
        if len(buf) - window_start > CONTEXT_WINDOW:
            window_start = len(buf) - CONTEXT_WINDOW // 2
        # Scan the accumulated tail (new chunk + overlap), not the raw
        # chunk: an oops anchor split across two console reads would
        # otherwise be missed and a non-fatal oops silently dropped.
        scan_start = max(window_start, len(buf) - len(chunk) - TAIL_OVERLAP)
        if crashed_report is None and report_pkg.contains_crash(
                bytes(buf[scan_start:]), ignores):
            # grab the full report: keep reading a little while
            crash_deadline = time.time() + WAIT_FOR_REPORT
            crashed_report = report_pkg.parse(window(), ignores)
    rep = report_pkg.parse(window(), ignores) or crashed_report
    assert rep is not None
    return _crash_outcome(rep, buf, window_start)


def _crash_outcome(rep, buf: bytearray, window_start: int) -> Outcome:
    return Outcome(title=rep.description, report=rep, output=bytes(buf),
                   crashed=True)
