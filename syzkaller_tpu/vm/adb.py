"""ADB adapter: fuzz Android devices over adb, console over USB serial.

Capability parity with reference vm/adb/adb.go (389 LoC): device-id
validation, repair cycle (`adb root`, reboot on unresponsive device),
temp cleanup, push-based copy, reverse port forwarding, shell command
execution with the serial console (or logcat fallback) merged into the
output stream, and battery-level gating before long runs.

All device interaction goes through subprocess `adb -s <dev>` calls, so
construction is testable with a mocked Popen/run (no hardware in CI).
"""

from __future__ import annotations

import os
import re
import subprocess
import time

from syzkaller_tpu.utils import log
from syzkaller_tpu.vm import base

_DEVICE_RE = re.compile(r"^[0-9A-Za-z.:\-]+$")


class AdbInstance(base.Instance):
    def __init__(self, cfg, index: int):
        self.cfg = cfg
        self.index = index
        devices = [d.strip() for d in
                   getattr(cfg, "devices", "").split(",") if d.strip()]
        if not devices:
            raise ValueError("adb: config needs 'devices' (comma-separated "
                             "serials, one per VM index)")
        if index >= len(devices):
            raise ValueError(f"adb: index {index} >= {len(devices)} devices")
        self.device = devices[index]
        if not _DEVICE_RE.match(self.device):
            raise ValueError(f"adb: invalid device id {self.device!r}")
        self.bin = getattr(cfg, "adb", "") or "adb"
        self.console = getattr(cfg, "console", "")  # /dev/ttyUSB* if cabled
        self._merger = base.OutputMerger()
        self._console_proc: "subprocess.Popen | None" = None
        self._repair()
        self._check_battery()
        self._adb("shell", "rm -rf /data/syzkaller*")

    # -- plumbing ----------------------------------------------------------

    def _adb(self, *args: str, timeout: float = 60.0,
             check: bool = True) -> subprocess.CompletedProcess:
        cmd = [self.bin, "-s", self.device, *args]
        log.logf(2, "adb-%d: %s", self.index, " ".join(cmd))
        return subprocess.run(cmd, capture_output=True, timeout=timeout,
                              check=check)

    def _repair(self) -> None:
        """Get the device into a usable rooted state; reboot it if adb is
        unresponsive (ref adb.go repair)."""
        try:
            self._adb("wait-for-device", timeout=120.0)
            self._adb("root", check=False)
            self._adb("wait-for-device", timeout=60.0)
        except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
            log.logf(0, "adb-%d: unresponsive, rebooting", self.index)
            self._adb("reboot", check=False, timeout=30.0)
            self._adb("wait-for-device", timeout=10 * 60.0)
            self._adb("root", check=False)

    def _check_battery(self) -> None:
        """Refuse to start a fuzz session on a draining battery
        (ref adb.go checkBatteryLevel, min 20%)."""
        try:
            out = self._adb("shell", "dumpsys battery",
                            check=False).stdout.decode(errors="replace")
        except (OSError, subprocess.TimeoutExpired):
            return
        m = re.search(r"level: (\d+)", out)
        if m and int(m.group(1)) < 20:
            raise RuntimeError(
                f"adb-{self.index}: battery at {m.group(1)}% (<20%)")

    # -- Instance interface ------------------------------------------------

    def copy(self, host_path: str) -> str:
        dst = "/data/" + os.path.basename(host_path)
        self._adb("push", host_path, dst, timeout=300.0)
        return dst

    def forward(self, port: int) -> str:
        # reverse forward: guest's localhost:port -> host port
        self._adb("reverse", f"tcp:{port}", f"tcp:{port}")
        return f"127.0.0.1:{port}"

    def run(self, command: str, timeout: float) -> base.RunHandle:
        if self.console and os.path.exists(self.console):
            # USB serial console carries the kernel oops text
            self._console_proc = subprocess.Popen(
                ["cat", self.console], stdin=subprocess.DEVNULL,
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                start_new_session=True)
        else:
            # no console cable: stream the kernel log via logcat
            self._console_proc = subprocess.Popen(
                [self.bin, "-s", self.device, "logcat", "-b", "kernel"],
                stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, start_new_session=True)
        self._merger.add("console", self._console_proc.stdout)
        proc = subprocess.Popen(
            [self.bin, "-s", self.device, "shell", command],
            stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, start_new_session=True)
        self._merger.add("adb", proc.stdout)

        def stop():
            for p in (proc, self._console_proc):
                if p is not None:
                    try:
                        p.kill()
                    except ProcessLookupError:
                        pass

        return base.RunHandle(output=self._merger.output, stop=stop,
                              is_alive=lambda: proc.poll() is None)

    def close(self) -> None:
        for p in (self._console_proc,):
            if p is not None:
                try:
                    p.kill()
                except ProcessLookupError:
                    pass
        self._console_proc = None


base.register("adb", AdbInstance)
