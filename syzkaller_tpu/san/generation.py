"""Generation counters for host buffers handed to async dispatches.

The ticket pattern (`submit_slabs` → resolve, `DecisionStream._cycle`
→ `_publish`) keeps a host numpy buffer referenced across an async
device dispatch.  On CPU backends the dispatch may read the buffer
ZERO-COPY, so a host mutation between submit and resolve silently
feeds the dispatch future values (PR 15).  `stamp()` checksums the
buffer at submit; `verify()` re-checksums at resolve and raises
`MutationInFlightError` carrying BOTH stacks — where the buffer was
handed off and where the corruption was detected.

Large buffers are sampled (head + tail + shape/dtype) so the armed
cost stays O(KB) per dispatch, not O(buffer)."""

from __future__ import annotations

import traceback
import zlib

import numpy as np

from syzkaller_tpu.san.errors import MutationInFlightError
from syzkaller_tpu.san.report import report

# full-checksum threshold: beyond this, sample head+tail windows
_FULL_BYTES = 1 << 16
_WINDOW = 4096


class GenToken:
    __slots__ = ("label", "digest", "buf", "stack")

    def __init__(self, label: str, digest: int, buf, stack: str):
        self.label = label
        self.digest = digest
        self.buf = buf
        self.stack = stack


def _digest(buf: np.ndarray) -> int:
    flat = buf.reshape(-1)
    meta = f"{buf.shape}|{buf.dtype}".encode()
    if buf.nbytes <= _FULL_BYTES:
        body = np.ascontiguousarray(flat).tobytes()
    else:
        n = max(1, _WINDOW // max(1, buf.itemsize))
        body = np.ascontiguousarray(flat[:n]).tobytes() \
            + np.ascontiguousarray(flat[-n:]).tobytes()
    return zlib.crc32(body, zlib.crc32(meta))


class GenerationTracker:
    """stamp/verify pairs over one report sink (the module-level
    `stamp`/`verify` ride the global report)."""

    def __init__(self, sink=None):
        self._report = sink if sink is not None else report

    def stamp(self, buf, label: str = "buffer") -> "GenToken | None":
        """Checksum a host buffer at dispatch-submit time.  None for
        non-ndarray handoffs (device arrays are XLA's problem)."""
        if not isinstance(buf, np.ndarray) or buf.size == 0:
            return None
        stack = "".join(traceback.format_stack(limit=12))
        return GenToken(label, _digest(buf), buf, stack)

    def verify(self, token: "GenToken | None") -> None:
        """Re-checksum at resolve time; a moved digest means the host
        mutated the buffer while the dispatch could still read it."""
        if token is None:
            return
        now = _digest(token.buf)
        if now == token.digest:
            return
        here = "".join(traceback.format_stack(limit=12))
        msg = (f"host buffer `{token.label}` mutated while its dispatch "
               f"was in flight (generation {token.digest:#010x} -> "
               f"{now:#010x}): the dispatch may have read future values")
        self._report.record("mutation-in-flight", msg, stacks={
            "submit": token.stack, "resolve": here})
        raise MutationInFlightError(
            f"{msg}\n--- handed off at ---\n{token.stack}"
            f"--- detected at ---\n{here}")


_tracker = GenerationTracker()


def stamp(buf, label: str = "buffer") -> "GenToken | None":
    return _tracker.stamp(buf, label)


def verify(token: "GenToken | None") -> None:
    _tracker.verify(token)
