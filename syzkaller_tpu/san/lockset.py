"""Runtime lockset audit over the gate/mutex seams.

syz-vet's lock-discipline pass proves statically that no device work
runs under a lock; this is its runtime twin — `audit_lock` swaps a
lock attribute for a recording wrapper, the shadow checker asks
`on_dispatch()` at every wrapped dispatch, and holding a non-dispatch
lock there raises `LockAuditError`.  The engine's `_state_mu` is the
DOCUMENTED exception (donated-buffer serialization requires the hold),
so it registers with `allow_dispatch=True`.

Lock-order edges are recorded per acquisition pair; an inversion
(A→B observed after B→A) is logged to the report as `lock-order`
(recorded, not raised: an inversion is a deadlock RISK, and killing
the storm that exposed it would hide the evidence)."""

from __future__ import annotations

import threading
import traceback

from syzkaller_tpu.san.errors import LockAuditError
from syzkaller_tpu.san.report import report as _report

_tls = threading.local()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


class AuditedLock:
    """Context-manager/acquire-release wrapper recording per-thread
    holds.  Transparent for Lock and RLock (re-entrant holds stack)."""

    def __init__(self, inner, name: str, audit: "LocksetAudit",
                 allow_dispatch: bool = False):
        self._inner = inner
        self.name = name
        self.allow_dispatch = allow_dispatch
        self._audit = audit

    def acquire(self, *args, **kwargs):
        ok = self._inner.acquire(*args, **kwargs)
        if ok:
            self._audit._on_acquire(self)
            _held().append(self)
        return ok

    def release(self):
        h = _held()
        if self in h:
            # remove the innermost hold (RLock re-entry unwinds LIFO)
            for i in range(len(h) - 1, -1, -1):
                if h[i] is self:
                    del h[i]
                    break
        return self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()


class LocksetAudit:
    """Order-edge bookkeeping + the dispatch-time lockset check."""

    def __init__(self, sink=None):
        self._report = sink if sink is not None else _report
        self._mu = threading.Lock()
        self._edges: dict[tuple, str] = {}
        self._inversions: set[tuple] = set()

    def wrap(self, owner, attr: str, name: str,
             allow_dispatch: bool = False) -> AuditedLock:
        """Swap `owner.<attr>` for an audited wrapper (idempotent)."""
        cur = getattr(owner, attr)
        if isinstance(cur, AuditedLock):
            return cur
        lk = AuditedLock(cur, name, self, allow_dispatch=allow_dispatch)
        setattr(owner, attr, lk)
        return lk

    def _on_acquire(self, lock: AuditedLock) -> None:
        held = _held()
        if not held:
            return
        with self._mu:
            for h in held:
                if h is lock:
                    continue            # RLock re-entry, not an edge
                edge = (h.name, lock.name)
                rev = (lock.name, h.name)
                if edge not in self._edges:
                    self._edges[edge] = "".join(
                        traceback.format_stack(limit=8))
                if rev in self._edges and edge not in self._inversions \
                        and rev not in self._inversions:
                    self._inversions.add(edge)
                    self._report.record(
                        "lock-order",
                        f"lock-order inversion: {h.name} -> {lock.name} "
                        f"observed after {lock.name} -> {h.name} "
                        "(deadlock risk)",
                        stacks={"this": self._edges[edge],
                                "reverse": self._edges[rev]})

    def on_dispatch(self, dispatch: str) -> None:
        """Called by the shadow checker inside every wrapped dispatch:
        holding a non-dispatch audited lock here is the race the static
        pass calls device-sync-under-lock."""
        foreign = [l.name for l in _held() if not l.allow_dispatch]
        if not foreign:
            return
        here = "".join(traceback.format_stack(limit=12))
        msg = (f"device dispatch `{dispatch}` issued while holding "
               f"{', '.join(foreign)} — locks must never be held "
               "across device work")
        self._report.record("dispatch-under-lock", msg,
                            stacks={"dispatch": here})
        raise LockAuditError(msg)

    def held_names(self) -> list[str]:
        return [l.name for l in _held()]


# the process-global audit the shadow checker consults
audit = LocksetAudit()


def audit_lock(owner, attr: str, name: str,
               allow_dispatch: bool = False) -> AuditedLock:
    return audit.wrap(owner, attr, name, allow_dispatch=allow_dispatch)
