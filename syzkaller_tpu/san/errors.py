"""syz-san hard-error types.

Deliberately NOT subclasses of RuntimeError: the resilience
supervisor's FAULT_TYPES treats RuntimeError as a device-flap worth
failing over for, and a sanitizer finding must never be absorbed by a
failover retry — it has to surface to the harness that armed the
sanitizer."""

from __future__ import annotations


class SanError(Exception):
    """Base class for sanitizer findings raised as errors."""


class UseAfterDonateError(SanError):
    """A Python reference passed in a donated slot was touched after
    the dispatch (its device buffer belongs to XLA)."""


class MutationInFlightError(SanError):
    """A host buffer handed to an async dispatch was mutated before
    the dispatch resolved (the PR-15 aliasing corruption class)."""


class LockAuditError(SanError):
    """Device work dispatched while holding a lock the lock-discipline
    contract says must never be held across dispatches."""
