"""Shadow checker: the live-object half of the donation-flow pass.

`ShadowChecker.attach` wraps the engine's jitted dispatch closures
(the same `DISPATCH_ATTRS` surface `DispatchProfiler.attach` wraps,
honouring the same idempotence contract so the two compose in either
order).  Around each dispatch it:

  * asks the lockset audit whether a non-dispatch lock is held
    (`dispatch-under-lock`, the runtime device-sync-under-lock);
  * refuses operands that are poison proxies or already-deleted jax
    arrays (use-after-donate caught AT the reuse site, with the
    donation stack);
  * after a donating dispatch, remembers which engine attributes still
    reference the donated operands; at the NEXT dispatch — by which
    point the donated-carry idiom must have rebound them — any
    attribute still holding the stale object is swapped for a
    `PoisonProxy`, so the first later touch raises with both stacks.

Donation specs are derived by parsing `cover/engine.py` with the vet
donation pass's own index helpers — the static pass is the single
source of truth for which `_*_fn` slots donate which argnums, so the
two planes can never drift apart.
"""

from __future__ import annotations

import ast
import threading
import traceback
import weakref

from syzkaller_tpu.observe.profile import DISPATCH_ATTRS
from syzkaller_tpu.san.errors import UseAfterDonateError
from syzkaller_tpu.san.lockset import audit, audit_lock
from syzkaller_tpu.san.report import report as _default_report

# pending-poison entries surviving to the next dispatch, per checker
_MAX_PENDING = 64
_STACK_LIMIT = 12


class PoisonProxy:
    """Guard standing in for a donated buffer that was never rebound.
    Any data access — attribute, item, iteration, array conversion —
    raises `UseAfterDonateError` carrying the donation stack.  `repr`
    stays safe so debuggers and log formatting survive."""

    def __init__(self, label: str, stack: str):
        object.__setattr__(self, "_poison_label", label)
        object.__setattr__(self, "_poison_stack", stack)

    def _poison_boom(self):
        raise UseAfterDonateError(
            f"use-after-donate: `{self._poison_label}` was passed in a "
            "donated slot and never rebound from the dispatch result — "
            "its device buffer belongs to XLA\n--- donated at ---\n"
            f"{self._poison_stack}")

    def __repr__(self):
        return f"<PoisonProxy donated:{self._poison_label}>"

    def __getattr__(self, name):
        self._poison_boom()

    def __setattr__(self, name, value):
        self._poison_boom()

    def __getitem__(self, key):
        self._poison_boom()

    def __setitem__(self, key, value):
        self._poison_boom()

    def __array__(self, *args, **kwargs):
        self._poison_boom()

    def __len__(self):
        self._poison_boom()

    def __iter__(self):
        self._poison_boom()

    def __bool__(self):
        self._poison_boom()

    def __float__(self):
        self._poison_boom()

    def __int__(self):
        self._poison_boom()

    def __index__(self):
        self._poison_boom()


def check_operands(args, dispatch: str = "kernel") -> None:
    """Raise if any operand is a poisoned (donated, never-rebound)
    reference.  Kernel seams (`kernels/registry`) call this so a
    poisoned buffer can't slip into a fused dispatch unnoticed."""
    for a in args:
        if isinstance(a, PoisonProxy):
            raise UseAfterDonateError(
                f"poisoned buffer `{a._poison_label}` passed to "
                f"`{dispatch}`\n--- donated at ---\n{a._poison_stack}")


_spec_mu = threading.Lock()
_specs: "dict[str, tuple[int, ...]] | None" = None


def _donation_specs() -> "dict[str, tuple[int, ...]]":
    """attr name (`_update_fn`) -> donated argnums, parsed once from
    cover/engine.py via the vet donation index helpers."""
    global _specs
    with _spec_mu:
        if _specs is not None:
            return _specs
        specs: dict[str, tuple[int, ...]] = {}
        try:
            import inspect

            from syzkaller_tpu.cover import engine as engine_mod
            from syzkaller_tpu.vet import donation

            tree = ast.parse(inspect.getsource(engine_mod))
            for fdef, spec in donation._file_defs(tree).items():
                for attr in donation._attr_bindings(tree, fdef.name):
                    prev = specs.get(attr, ())
                    specs[attr] = tuple(sorted(set(prev) | set(spec)))
        except (OSError, SyntaxError, TypeError):
            pass                    # frozen/stripped install: no specs
        _specs = specs
        return _specs


class ShadowChecker:
    """Per-process shadow checker; attach to each engine (and re-attach
    after a failover rebuild — wrapping is idempotent)."""

    def __init__(self, sink=None, specs=None):
        self._report = sink if sink is not None else _default_report
        self._mu = threading.Lock()
        # (engine weakref | None, attr | None, donated obj, label, stack)
        self._pending: list = []
        self._specs_override = specs

    # -- wiring ------------------------------------------------------------

    def attach(self, engine) -> "list[str]":
        specs = self._specs_override if self._specs_override is not None \
            else _donation_specs()
        if getattr(engine, "__dict__", None) is not None and \
                "_state_mu" in engine.__dict__:
            # the documented held-across-dispatch exception: _state_mu
            # SERIALIZES donated-buffer rebinds, so it must be held
            audit_lock(engine, "_state_mu", "engine._state_mu",
                       allow_dispatch=True)
        wrapped = []
        for attr in DISPATCH_ATTRS:
            fn = getattr(engine, attr, None)
            if fn is None or not callable(fn):
                continue
            name = attr.strip("_")
            if name.endswith("_fn"):
                name = name[:-3]
            if _already_san(fn):
                wrapped.append(name)
                continue
            setattr(engine, attr,
                    self._wrap(engine, attr, name, fn, specs.get(attr, ())))
            wrapped.append(name)
        return wrapped

    def _wrap(self, engine, attr, name, fn, spec):
        def sanitized(*args, **kwargs):
            self._pre_dispatch(name, args)
            out = fn(*args, **kwargs)
            if spec:
                self._post_dispatch(engine, name, spec, args)
            return out

        sanitized._syz_san = name
        # propagate the profiler marker so ITS attach stays idempotent
        # when it ran first; when san runs first the marker is absent
        # and the profiler is still free to wrap on top
        inner = getattr(fn, "_syz_dispatch", None)
        if inner is not None:
            sanitized._syz_dispatch = inner
        sanitized.__wrapped__ = fn
        return sanitized

    # -- checks ------------------------------------------------------------

    def _pre_dispatch(self, name: str, args) -> None:
        audit.on_dispatch(name)
        check_operands(args, dispatch=name)
        for a in args:
            deleted = getattr(a, "is_deleted", None)
            if callable(deleted):
                try:
                    gone = bool(deleted())
                except Exception:
                    gone = False
                if gone:
                    msg = (f"deleted (donated) jax array passed to "
                           f"`{name}` — its buffer was handed to XLA by "
                           "an earlier dispatch")
                    self._report.record("use-after-donate", msg)
                    raise UseAfterDonateError(msg)
        self._sweep(args, name)

    def _sweep(self, args, name: str) -> None:
        """Settle last dispatch's donations: by now the donated-carry
        idiom must have rebound every donated reference."""
        with self._mu:
            if not self._pending:
                return
            pend, self._pending = self._pending, []
        for eref, attr, obj, label, stack in pend:
            if any(a is obj for a in args):
                here = "".join(traceback.format_stack(limit=_STACK_LIMIT))
                msg = (f"use-after-donate: `{label}` was donated and is "
                       f"being passed to `{name}` again without a rebind")
                self._report.record("use-after-donate", msg, stacks={
                    "donated": stack, "reused": here})
                raise UseAfterDonateError(
                    f"{msg}\n--- donated at ---\n{stack}"
                    f"--- reused at ---\n{here}")
            eng = eref() if eref is not None else None
            if eng is None or attr is None:
                continue
            if eng.__dict__.get(attr) is obj:
                self._report.record(
                    "donated-ref-unrebound",
                    f"engine.{attr} still references the buffer donated "
                    f"by `{label}`; poisoning it", stacks={"donated": stack})
                setattr(eng, attr, PoisonProxy(f"engine.{attr}", stack))

    def _post_dispatch(self, engine, name, spec, args) -> None:
        stack = "".join(traceback.format_stack(limit=_STACK_LIMIT))
        try:
            eref = weakref.ref(engine)
        except TypeError:
            eref = None
        entries = []
        attrs = getattr(engine, "__dict__", {})
        for i in spec:
            if i >= len(args):
                continue
            obj = args[i]
            if obj is None or isinstance(obj, (bool, int, float, str,
                                               bytes, PoisonProxy)):
                continue
            label = f"{name} arg{i}"
            bound = [a for a, v in list(attrs.items()) if v is obj]
            if bound:
                entries.extend(
                    (eref, a, obj, f"{label} (engine.{a})", stack)
                    for a in bound)
            else:
                entries.append((None, None, obj, label, stack))
        if entries:
            with self._mu:
                self._pending.extend(entries)
                del self._pending[:-_MAX_PENDING]


def _already_san(fn) -> bool:
    """True if `fn` (or anything below it in the __wrapped__ chain —
    the profiler may have wrapped on top) is already sanitized."""
    seen = 0
    while fn is not None and seen < 8:
        if getattr(fn, "_syz_san", None) is not None:
            return True
        fn = getattr(fn, "__wrapped__", None)
        seen += 1
    return False
