"""syz-san: the runtime half of the device-buffer lifetime sanitizer.

The static plane (syz-vet's donation/aliasing/epoch passes) proves the
SHAPES are right; this plane watches the live objects, so each plane
cross-checks the other's false-negative space — exactly the
KASAN-next-to-lockdep layering the reference fuzzer assumes on the
kernel side.  Opt-in via `SYZ_SAN=1` (or `attach(force=True)` from a
harness); unarmed, every hook is a single falsy branch and ZERO extra
device dispatches.

Components:

  * shadow checker (`attach`) — wraps the engine's jitted dispatch
    closures (riding the DispatchProfiler wrapper contract, so the two
    compose in either order), verifies no operand is a deleted/donated
    buffer, and POISONS engine attributes still referencing a donated
    array at the next dispatch (guard proxy raising with the donation
    stack on any access);
  * generation tracker (`stamp`/`verify`) — checksums host buffers
    handed to async dispatches and re-verifies at resolve time:
    mutation-in-flight is a hard error carrying both stacks (the
    runtime twin of the aliasing pass / PR-15 bug);
  * lockset audit (`audit_lock`) — runtime confirmation of the static
    lock-discipline pass over the gate/mutex seams: dispatching device
    work while holding a non-dispatch lock raises.

Findings are hard errors AND are recorded in the process-global
`report` (tools/ci.py publishes its summary as a build artifact).
"""

from __future__ import annotations

import os

from syzkaller_tpu.san.report import Report, report  # noqa: F401
from syzkaller_tpu.san.errors import (                # noqa: F401
    LockAuditError, MutationInFlightError, SanError, UseAfterDonateError)
from syzkaller_tpu.san.generation import GenerationTracker, stamp, verify
from syzkaller_tpu.san.lockset import LocksetAudit, audit_lock
from syzkaller_tpu.san.shadow import PoisonProxy, ShadowChecker, \
    check_operands

__all__ = [
    "armed", "attach", "report", "Report", "stamp", "verify",
    "audit_lock", "check_operands", "summary", "SanError",
    "UseAfterDonateError", "MutationInFlightError", "LockAuditError",
    "GenerationTracker", "LocksetAudit", "ShadowChecker", "PoisonProxy",
]


def armed() -> bool:
    """True when the sanitizer is opted in (`SYZ_SAN=1`)."""
    return os.environ.get("SYZ_SAN", "0") not in ("", "0")


_checker: "ShadowChecker | None" = None


def attach(engine, force: bool = False) -> list:
    """Arm the shadow checker on one engine (idempotent; re-run after a
    failover rebuild).  No-op returning [] unless armed or `force` —
    the unarmed cost is this one branch."""
    if not (force or armed()):
        return []
    global _checker
    if _checker is None:
        _checker = ShadowChecker(report)
    return _checker.attach(engine)


def summary() -> dict:
    """The sanitizer summary tools/ci.py publishes as an artifact."""
    out = report.summary()
    out["armed"] = armed()
    return out
