"""Process-global sanitizer finding log.

Every hard error the sanitizer raises is also recorded here, so a
harness that catches (or a chaos storm that absorbs) the exception
still leaves an auditable trail, and tools/ci.py can publish the
summary as a build artifact next to the vet JSON report."""

from __future__ import annotations

import threading
import time


class Report:
    """Thread-safe append-only finding log + counters."""

    MAX_FINDINGS = 256          # bounded: a storm must not OOM the host

    def __init__(self):
        self._mu = threading.Lock()
        self._findings: list[dict] = []
        self._counts: dict[str, int] = {}
        self._dropped = 0

    def record(self, kind: str, message: str, *,
               stacks: "dict[str, str] | None" = None) -> dict:
        entry = {"kind": kind, "message": message,
                 "time": time.time(), "stacks": stacks or {}}
        with self._mu:
            self._counts[kind] = self._counts.get(kind, 0) + 1
            if len(self._findings) < self.MAX_FINDINGS:
                self._findings.append(entry)
            else:
                self._dropped += 1
        return entry

    def findings(self) -> list[dict]:
        with self._mu:
            return list(self._findings)

    def counts(self) -> dict:
        with self._mu:
            return dict(self._counts)

    @property
    def total(self) -> int:
        with self._mu:
            return sum(self._counts.values())

    def clear(self) -> None:
        with self._mu:
            self._findings.clear()
            self._counts.clear()
            self._dropped = 0

    def summary(self) -> dict:
        with self._mu:
            return {
                "total": sum(self._counts.values()),
                "counts": dict(self._counts),
                "dropped": self._dropped,
                "findings": [
                    {k: v for k, v in f.items() if k != "stacks"}
                    for f in self._findings],
            }


# the process-global log every component records into
report = Report()
