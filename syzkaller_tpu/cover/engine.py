"""Device-resident coverage + sampling engine (the TPU hot loop).

This is the BASELINE north-star component: the reference's CPU hot loops
become fixed-shape array programs that live in HBM and run under jit:

  - signal diff per exec (ref cover.Difference + syz-fuzzer/fuzzer.go:460-478)
    → `update_batch`: (B, W) uint32 bitmap & ~max_cover[call], any-reduce.
  - corpus / max-cover merge (ref cover.Union, syz-manager corpus merge)
    → bitwise-or scan into the per-call matrices.
  - corpus minimization (ref cover.Minimize greedy set cover,
    syz-manager/manager.go:504-550) → iterative argmax over
    population_count inside lax.while_loop.
  - ChoiceTable sampling (ref prog/prio.go:202-249 one draw at a time)
    → one batched categorical draw over the priority matrix.
  - dynamic priorities (ref prog/prio.go:137-154 pairwise corpus loop)
    → one (N×C)·(C×N) matmul on the MXU.

Layout: coverage is a packed bitmap — PC index p lives in word p>>5 bit
p&31, uint32 words, shape (ncalls, W) where W = ceil(npcs/32).  The PC
axis (last dim) is the long axis (64k–1M PCs, SURVEY §5 long-context):
`shard(mesh)` shards it across devices so elementwise diff/merge stays
local and only the tiny any-reduce / popcount verdicts cross ICI.

Variable-length KCOV PC lists are fed as fixed-shape (B, K) index
batches with a validity mask (sparse→dense mapping, SURVEY §7 hard
parts); out-of-range/masked entries are dropped by scatter mode="drop".
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pc_mesh(n_devices: int, platform: str = "",
            process_local: bool = True) -> Mesh:
    """1D device mesh over the PC (bitmap word) axis — the long-axis
    sharding of SURVEY §5.  Production entry point for the config `mesh`
    knob (BASELINE config #4): elementwise diff/merge stays chip-local,
    verdict reductions ride ICI.

    Under a multi-process runtime (jax.distributed initialized — a pod
    slice), `process_local=True` builds the mesh from THIS process's
    addressable slice (`jax.local_devices()`): per-host engines shard
    their own chips and federate through the hub's program exchange
    (mesh/dist.py owns the topology math).  Asking for more devices
    than the slice addresses fails with a ConfigError naming the slice
    — not the opaque XLA "device not addressable" crash that used to
    surface mid-dispatch.

    `platform` pins the device platform ("cpu" for virtual-device tests
    and dryruns — avoids constructing an accelerator client at all);
    empty means the default platform, with a LOUD fallback to virtual
    CPU devices when it has too few — a silent fallback would quietly
    turn the device-resident matrices into host-RAM arrays."""
    from syzkaller_tpu.manager.config import ConfigError
    from syzkaller_tpu.utils import log

    multiproc = process_local and jax.process_count() > 1
    if multiproc:
        devs = jax.local_devices()
        if platform:
            devs = [d for d in devs if d.platform == platform]
    else:
        devs = jax.devices(platform) if platform else jax.devices()
    if len(devs) < n_devices and not platform and not multiproc:
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= n_devices:
            log.logf(0, "WARNING: mesh=%d exceeds the %d default-platform "
                     "device(s); falling back to %d virtual CPU devices — "
                     "the coverage engine will run on host CPU",
                     n_devices, len(devs), n_devices)
            devs = cpu
    if len(devs) < n_devices:
        where = (f"process {jax.process_index()}/{jax.process_count()} "
                 f"addresses" if multiproc else "have")
        raise ConfigError(
            f"mesh wants {n_devices} devices but {where} only "
            f"{len(devs)} {platform or 'default-platform'} device(s); "
            "lower the `mesh` knob, or on a pod slice set "
            "`mesh_devices_per_host` to this host's addressable slice")
    return Mesh(np.array(devs[:n_devices]), ("pc",))


def nwords_for(npcs: int, align: int = 64) -> int:
    # 64-word alignment: pack_pcs factors words as (hi, 64-lo) for its
    # MXU one-hot matmuls
    w = (npcs + 31) // 32
    return (w + align - 1) // align * align


# ---------------------------------------------------------------------------
# Pure jittable kernels (shapes static; engine closes over them).


def pack_pcs(pc_idx: jax.Array, valid: jax.Array, npcs: int,
             assume_unique: bool = False) -> jax.Array:
    """(B, K) int32 PC indices + mask → (B, W) uint32 packed bitmaps.
    Invalid/masked indices are dropped.

    Two formulations, picked by backend at trace time: the MXU one-hot
    matmul below for accelerators (scatter measured ~25M elems/s there),
    and a scatter-add for the CPU backend (`_pack_pcs_scatter` — the
    one-hot operands cost ~12x more than the scatter on CPU, and the
    presubmit/smoke/fallback paths all run CPU).  Both are bit-exact
    for the same inputs.

    MXU formulation — no gather/scatter (measured at only ~25M random
    elems/s on this backend, the old bottleneck): factor each word index
    as (hi, lo) with 64 words per hi-group and split each word into 5
    planes of ≤7 bits, build two small int8 one-hots, and let ONE
    batched s8×s8→s32 matmul accumulate the bits:  M[b,hi,col] =
    Σ_k onehot_hi × (onehot_col · 2^bit_in_plane).  Plane sums ≤ 127
    are exact in int8×int8→int32, so recombining the 5 planes with
    integer shifts reproduces the exact uint32 words.  (The 7-bit plane
    split keeps every one-hot value ≤ 64 so the operands fit int8 —
    int8 one-hots halve the materialized-operand HBM traffic vs bf16,
    which dominates this kernel's cost.)  Requires each row's indices
    to be unique (duplicate bits would ADD) — per-exec covers are
    already sort-deduped by the executor/PcMap; pass
    assume_unique=False to sort-dedup here."""
    B, K = pc_idx.shape
    W = nwords_for(npcs)
    HI, NPL = W // 64, 5
    COL = 64 * NPL
    ok = valid & (pc_idx >= 0) & (pc_idx < npcs)
    if assume_unique:
        s = jnp.where(ok, pc_idx, jnp.int32(npcs))
        keep = ok
    else:
        s = jnp.sort(jnp.where(ok, pc_idx, jnp.int32(npcs)), axis=1)
        dup = jnp.concatenate(
            [jnp.zeros((B, 1), bool), s[:, 1:] == s[:, :-1]], axis=1)
        keep = (s < npcs) & ~dup
    if jax.default_backend() == "cpu":
        return _pack_pcs_scatter(s, keep, npcs)
    word = s >> 5
    sub = s & 31
    hi = word >> 6
    plane = jnp.minimum(sub // 7, 4)       # bit planes 0-6,7-13,...,28-31
    inplane = sub - plane * 7
    col = (word & 63) * NPL + plane
    bitv = (jnp.int32(1) << inplane).astype(jnp.int8)
    onehot_hi = ((hi[:, :, None] == jnp.arange(HI)[None, None, :])
                 & keep[:, :, None]).astype(jnp.int8)
    onehot_col = jnp.where(
        (col[:, :, None] == jnp.arange(COL)[None, None, :])
        & keep[:, :, None], bitv[:, :, None], 0).astype(jnp.int8)
    M = jax.lax.dot_general(onehot_hi, onehot_col,
                            (((1,), (1,)), ((0,), (0,))),
                            preferred_element_type=jnp.int32)
    planes = M.reshape(B, HI, 64, NPL).astype(jnp.uint32)
    words = (planes[..., 0] | (planes[..., 1] << 7) | (planes[..., 2] << 14)
             | (planes[..., 3] << 21) | (planes[..., 4] << 28))
    return words.reshape(B, W)


def _pack_pcs_scatter(pc: jax.Array, keep: jax.Array,
                      npcs: int) -> jax.Array:
    """CPU-backend pack: one scatter-ADD of per-PC bit values.  The
    caller guarantees kept indices are unique per row, so two kept PCs
    sharing a word always carry different bits — add IS bitwise-or.
    Dropped entries scatter out of bounds (mode='drop')."""
    B, K = pc.shape
    W = nwords_for(npcs)
    word = jnp.where(keep, pc >> 5, jnp.int32(W))
    bitv = jnp.where(keep,
                     jnp.uint32(1) << (pc & 31).astype(jnp.uint32),
                     jnp.uint32(0))
    rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, K))
    out = jnp.zeros((B, W), jnp.uint32)
    return out.at[rows, word].add(bitv, mode="drop")


def scatter_or(base: jax.Array, call_ids: jax.Array,
               bitmaps: jax.Array) -> jax.Array:
    """base[call_ids[i]] |= bitmaps[i] for all i, duplicate-safe.
    Sequential scan: B tiny dynamic-slice ORs — compiles to a fused loop,
    the heavy (B, W) work stays in the vectorized ops around it."""

    def body(i, acc):
        cid = call_ids[i]
        return acc.at[cid].set(jnp.bitwise_or(acc[cid], bitmaps[i]))

    return jax.lax.fori_loop(0, call_ids.shape[0], body, base)


def diff_merge(base: jax.Array, call_ids: jax.Array, bitmaps: jax.Array,
               group: int = 32) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Diff-then-merge over the batch: row i's new-signal is computed
    against base ∪ rows[0..i) of the same call, so two identical
    new-coverage execs in one batch yield exactly one has_new verdict
    (matching the reference, which processes execs one at a time).

    Fully vectorized, TWO-LEVEL: stable-sort rows by call id (runs
    become contiguous), build the EXCLUSIVE per-segment prefix-OR
    within groups of `group` rows (log2 G Hillis-Steele passes over the
    (B, W) matrix), then chain group tails with a segmented scan over
    the (B/G, W) tail matrix whose flag is `boundary-linked AND group
    is single-run` (a tail may only flow through a group that belongs
    entirely to the same run), and apply each group's carry to its
    leading run.  log2(G) + 1 full-width passes instead of log2(B) —
    at B=2048 that is 6 passes instead of 11, and the big-batch/large-W
    configs are bandwidth-bound on exactly these passes.
    Returns (merged base, (B, W) new bitmaps, (B,) has_new)."""
    B, W = bitmaps.shape
    order = jnp.argsort(call_ids, stable=True)
    cid_s = call_ids[order]
    bm_s = bitmaps[order]

    G = group
    if B % G or B <= G:
        excl = _seg_prefix_or_flat(cid_s, bm_s)
    else:
        Bg = B // G
        cg = cid_s.reshape(Bg, G)
        bg = bm_s.reshape(Bg, G, W)
        # within-group exclusive segmented prefix-OR
        same_prev = jnp.concatenate(
            [jnp.zeros((Bg, 1), bool), cg[:, 1:] == cg[:, :-1]], axis=1)
        pre = jnp.where(
            same_prev[:, :, None],
            jnp.concatenate([jnp.zeros((Bg, 1, W), bm_s.dtype), bg[:, :-1]],
                            axis=1),
            jnp.uint32(0))
        excl = pre
        s = 1
        while s < G:
            sh = jnp.concatenate(
                [jnp.zeros((Bg, min(s, G), W), excl.dtype), excl[:, :-s]],
                axis=1)[:, :G]
            sm = jnp.concatenate(
                [jnp.zeros((Bg, min(s, G)), bool), cg[:, s:] == cg[:, :-s]],
                axis=1)[:, :G]
            excl = jnp.where(sm[:, :, None], excl | sh, excl)
            s *= 2
        # group tails: OR of each group's trailing run
        tail = excl[:, -1] | bg[:, -1]
        cid_last = cg[:, -1]
        link = jnp.concatenate(
            [jnp.zeros((1,), bool), cid_last[:-1] == cg[1:, 0]])
        pure = cg[:, 0] == cg[:, -1]
        # segmented inclusive scan of tails; flag = link & pure (a tail
        # may only pass THROUGH a group that is one single run)
        flag = link & pure
        u = tail
        s = 1
        Bg_ = Bg
        while s < Bg_:
            sh = jnp.concatenate(
                [jnp.zeros((min(s, Bg_), W), u.dtype), u[:-s]], axis=0)[:Bg_]
            u = jnp.where(flag[:, None], u | sh, u)
            flag = flag & jnp.concatenate(
                [jnp.zeros((min(s, Bg_),), bool), flag[:-s]])[:Bg_]
            s *= 2
        carry = jnp.where(
            link[:, None],
            jnp.concatenate([jnp.zeros((1, W), u.dtype), u[:-1]], axis=0),
            jnp.uint32(0))
        lead = cg == cg[:, :1]
        excl = jnp.where(lead[:, :, None], excl | carry[:, None, :],
                         excl).reshape(B, W)

    prev = jnp.bitwise_or(base[cid_s], excl)
    new_s = jnp.bitwise_and(bm_s, jnp.bitwise_not(prev))
    full = jnp.bitwise_or(prev, bm_s)
    # one scatter per segment: the last row of each run holds base|seg-OR
    last = jnp.concatenate([cid_s[1:] != cid_s[:-1], jnp.ones((1,), bool)])
    idx = jnp.where(last, cid_s, base.shape[0])          # drop non-last
    merged = base.at[idx].set(full, mode="drop")
    # unsort the per-row outputs back to submission order
    inv = jnp.argsort(order)
    new = new_s[inv]
    return merged, new, jnp.any(new != 0, axis=-1)


def _seg_prefix_or_flat(cid_s: jax.Array, bm_s: jax.Array) -> jax.Array:
    """Single-level exclusive segmented prefix-OR (for batches too small
    or oddly-shaped for the grouped path)."""
    B, W = bm_s.shape
    same_prev = jnp.concatenate(
        [jnp.zeros((1,), bool), cid_s[1:] == cid_s[:-1]])
    pre = jnp.where(
        same_prev[:, None],
        jnp.concatenate([jnp.zeros((1, W), bm_s.dtype), bm_s[:-1]], axis=0),
        jnp.uint32(0))
    excl = pre
    s = 1
    while s < B:
        shifted = jnp.concatenate(
            [jnp.zeros((min(s, B), W), excl.dtype), excl[:-s]], axis=0)[:B]
        same = jnp.concatenate(
            [jnp.zeros((min(s, B),), bool), cid_s[s:] == cid_s[:-s]])[:B]
        excl = jnp.where(same[:, None], jnp.bitwise_or(excl, shifted), excl)
        s *= 2
    return excl


def touched_blocks(pc_idx: np.ndarray, valid: np.ndarray, npcs: int,
                   block_words: int, max_blocks: int) -> "np.ndarray | None":
    """Host side of the word-block-sparse step: the sorted unique block
    ids a (B, K) index batch touches, padded with the sentinel NB (the
    one-past-the-end block) to a fixed (max_blocks,) shape.  Returns
    None when the batch touches more than max_blocks blocks — the
    caller falls back to the dense full-width step, so sparseness is a
    fast path, never a semantics change."""
    bits = block_words * 32
    nb = nwords_for(npcs) // block_words
    ok = np.asarray(valid, bool) & (pc_idx >= 0) & (pc_idx < npcs)
    blk = np.unique(np.asarray(pc_idx)[ok] // bits)
    if len(blk) > max_blocks:
        return None
    out = np.full((max_blocks,), nb, np.int32)
    out[: len(blk)] = blk
    return out


def sparse_update(max_cover: jax.Array, call_ids: jax.Array,
                  pc_idx: jax.Array, valid: jax.Array, blocks: jax.Array,
                  npcs: int, block_words: int
                  ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Word-block-sparse pack→diff→merge: gather only the word blocks
    the batch touches, run the exact dense kernels at the (much
    narrower) gathered width, and scatter the merged blocks back.
    Per-step work then scales with the batch's live signal footprint
    instead of the full bitmap width — the 1M-PC configs are
    bandwidth-bound on exactly the full-width (B, W) passes this
    removes.

    `blocks` is the (MB,) sorted unique touched-block list from
    `touched_blocks` (sentinel NB pads the tail); MB * block_words must
    be 64-word aligned for pack_pcs's MXU factoring.  Semantics are
    exactly `pack_pcs + diff_merge` at full width: the block-local
    index remap is a bijection on touched blocks, untouched blocks
    cannot gain or lose bits, and in-batch dedup sequencing is
    unchanged.  Returns (merged max_cover, (B, MB*block_words)
    block-local new bitmaps, (B,) has_new)."""
    ncalls, W = max_cover.shape
    NB = W // block_words
    MB = blocks.shape[0]
    bits = block_words * 32
    # gather: clamp pad entries onto the last real block — their columns
    # carry no valid indices, so they pass through diff_merge unchanged
    # and the write-back drops them (sentinel NB, mode="drop")
    gblk = jnp.minimum(blocks, NB - 1)
    sub = max_cover.reshape(ncalls, NB, block_words)[:, gblk]
    sub = sub.reshape(ncalls, MB * block_words)
    blk = pc_idx // bits
    pos = jnp.clip(jnp.searchsorted(blocks, blk), 0, MB - 1)
    ok = valid & (pc_idx >= 0) & (pc_idx < npcs) & (blocks[pos] == blk)
    local = pos * bits + pc_idx % bits
    bitmaps = pack_pcs(local, ok, MB * bits, assume_unique=True)
    merged_sub, new, has_new = diff_merge(sub, call_ids, bitmaps)
    mc = max_cover.reshape(ncalls, NB, block_words).at[:, blocks].set(
        merged_sub.reshape(ncalls, MB, block_words), mode="drop")
    return mc.reshape(ncalls, W), new, has_new


# translate_slab_rows, popcount_rows, and the extracted signal_diff /
# synth_gather oracles now live in kernels/oracles.py (re-exported here
# for the long-standing import sites); the engine resolves the plane-
# selected implementation through kernels.KERNELS at _build() time.
from syzkaller_tpu.kernels import KERNELS  # noqa: E402
from syzkaller_tpu.utils.shapes import pow2_bucket  # noqa: E402
from syzkaller_tpu.kernels.oracles import (popcount_rows,  # noqa: E402,F401
                                           signal_diff, synth_gather,
                                           translate_slab_rows)


def minimize_cover(corpus: jax.Array, active: jax.Array) -> jax.Array:
    """Greedy set cover over corpus rows (C, W); returns (C,) keep mask.
    Iterative argmax-of-gain inside a while_loop (ref cover.Minimize)."""
    C, W = corpus.shape

    def gains(covered):
        fresh = jnp.bitwise_and(corpus, jnp.bitwise_not(covered)[None, :])
        return jnp.where(active, popcount_rows(fresh), 0)

    def cond(state):
        covered, keep = state
        return jnp.any(gains(covered) > 0)

    def body(state):
        covered, keep = state
        g = gains(covered)
        best = jnp.argmax(g)
        covered = jnp.bitwise_or(covered, corpus[best])
        return covered, keep.at[best].set(True)

    covered0 = jnp.zeros((W,), jnp.uint32)
    keep0 = jnp.zeros((C,), jnp.bool_)
    _, keep = jax.lax.while_loop(cond, body, (covered0, keep0))
    return keep


def minimize_cover_scan(corpus: jax.Array, active: jax.Array) -> jax.Array:
    """Set-cover for large corpora (C ≳ 4k): visit rows in popcount-
    descending order, keep a row iff it still contributes fresh bits.
    One lax.scan of C tiny steps instead of O(kept) full argmax passes
    over the (C, W) matrix — same first pick as exact greedy, a valid
    cover always (any bit's first contributor in order is kept)."""
    C, W = corpus.shape
    sizes = jnp.where(active, popcount_rows(corpus), -1)
    order = jnp.argsort(-sizes)

    def body(covered, i):
        row = corpus[i]
        fresh = jnp.any(jnp.bitwise_and(row, jnp.bitwise_not(covered)) != 0)
        keep_i = fresh & active[i]
        covered = jnp.where(keep_i, jnp.bitwise_or(covered, row), covered)
        return covered, keep_i

    _, keep_perm = jax.lax.scan(body, jnp.zeros((W,), jnp.uint32), order)
    return jnp.zeros((C,), jnp.bool_).at[order].set(keep_perm)


def sample_calls(key: jax.Array, probs: jax.Array, prev: jax.Array,
                 enabled: jax.Array) -> jax.Array:
    """Batched ChoiceTable draw: (B,) prev call ids (-1 = no context) →
    (B,) next call ids ~ probs[prev] restricted to enabled calls.
    The flat (overlay-free) draw: a neutral all-ones boost."""
    return sample_calls_boosted(key, probs, prev, enabled,
                                jnp.ones((probs.shape[0],), probs.dtype))


def sample_calls_boosted(key: jax.Array, probs: jax.Array, prev: jax.Array,
                         enabled: jax.Array,
                         boost: jax.Array) -> jax.Array:
    """`sample_calls` with a campaign-overlay column multiplier.

    Prefix-CDF formulation — exactly the reference's Choose (one draw
    into the prefix-sum row, prog/prio.go:230-249) vectorized: ONE
    uniform per draw and a compare-and-sum instead of a Gumbel trick
    that needs B×C random bits (RNG generation measures ~160M u32/s on
    this backend, so the Gumbel path was RNG-bound).

    `boost` is the overlay's (C,) float32 column multiplier: it
    reweights every context row INCLUDING the no-context uniform row,
    so a steered stream biases generation even before a prev context
    exists.  All-ones reproduces the flat draw bit-for-bit."""
    C = probs.shape[0]
    rows = jnp.where(prev[:, None] >= 0,
                     probs[jnp.clip(prev, 0, C - 1)],
                     jnp.ones((1, C), probs.dtype))
    w = jnp.where(enabled[None, :], rows, 0.0) * boost[None, :]
    cdf = jnp.cumsum(w, axis=1)
    u = jax.random.uniform(key, (prev.shape[0],)) * cdf[:, -1]
    # index of the first cdf entry > u; interior zero-weight (disabled)
    # slots have flat cdf and can't be selected.  f32 rounding can push
    # u up to exactly the row total (count == C), so clamp to the LAST
    # nonzero-weight index — a bare C-1 clamp could emit a disabled id.
    idx = jnp.sum((u[:, None] >= cdf).astype(jnp.int32), axis=1)
    last_ok = C - 1 - jnp.argmax((w > 0)[:, ::-1], axis=1)
    return jnp.minimum(idx, last_ok)


def sample_calls_rows(key: jax.Array, probs: jax.Array, enabled: jax.Array,
                      per_row: int) -> jax.Array:
    """All-contexts draw with the neutral (flat) boost."""
    return sample_calls_rows_boosted(
        key, probs, enabled, per_row,
        jnp.ones((probs.shape[0],), probs.dtype))


def sample_calls_rows_boosted(key: jax.Array, probs: jax.Array,
                              enabled: jax.Array, per_row: int,
                              boost: jax.Array) -> jax.Array:
    """All-contexts ChoiceTable draw: per_row samples for EVERY previous-
    call context in one shot — row 0 is the no-context (-1) row, row r+1
    conditions on prev call r.  Returns (C+1, per_row) int32 draws.

    This is the decision-stream formulation of `sample_calls`: that path
    gathers a cdf row PER DRAW (O(3C) work each — gather + cumsum +
    compare dominated the ~500k/s legacy draw rate), while here the
    (C+1, C) cdf matrix is materialized ONCE and every draw is one
    uniform plus a vectorized binary search (O(log C)).  Distribution is
    identical: prefix-cdf with side='right' selection means interior
    zero-weight (disabled) slots have flat cdf runs and cannot be
    selected, and the same last-nonzero clamp absorbs f32 round-up to
    the row total."""
    C = probs.shape[0]
    rows = jnp.concatenate([jnp.ones((1, C), probs.dtype), probs], axis=0)
    w = jnp.where(enabled[None, :], rows, 0.0) * boost[None, :]
    cdf = jnp.cumsum(w, axis=1)
    u = jax.random.uniform(key, (C + 1, per_row)) * cdf[:, -1:]
    idx = jax.vmap(
        lambda c, uu: jnp.searchsorted(c, uu, side="right"))(cdf, u)
    last_ok = C - 1 - jnp.argmax((w > 0)[:, ::-1], axis=1)
    return jnp.minimum(idx.astype(jnp.int32),
                       last_ok[:, None].astype(jnp.int32))


def dynamic_prios(call_matrix: jax.Array) -> jax.Array:
    """(C, N) multi-hot corpus occurrence → (N, N) dampened co-occurrence.
    One MXU matmul replaces the reference's pairwise Python/Go loops."""
    x = call_matrix.astype(jnp.bfloat16)
    co = jnp.matmul(x.T, x, preferred_element_type=jnp.float32)
    co = co * (1.0 - jnp.eye(co.shape[0], dtype=jnp.float32))
    return jnp.sqrt(co)


def normalize_prios(prios: jax.Array) -> jax.Array:
    """Row-normalize to [0.1, 1] (ref prio.go:158-192)."""
    mx = prios.max(axis=1, keepdims=True)
    return jnp.where(mx > 0, 0.1 + 0.9 * prios / jnp.maximum(mx, 1e-9), 1.0)


def fuzz_step(max_cover: jax.Array, prios: jax.Array, enabled: jax.Array,
              key: jax.Array, call_ids: jax.Array, pc_idx: jax.Array,
              valid: jax.Array, npcs: int, assume_unique: bool = False):
    """The fused per-batch device step — the framework's 'forward pass':
    B execs' raw KCOV indices in → per-exec new-signal verdicts, merged
    max cover, and the next batch of ChoiceTable decisions out.  One jit
    call covers what the reference does per-exec in cover.Difference +
    cover.Union + prio.Choose (fuzzer.go:460-478, prio.go:230-249)."""
    bitmaps = pack_pcs(pc_idx, valid, npcs, assume_unique=assume_unique)
    merged, new, has_new = diff_merge(max_cover, call_ids, bitmaps)
    next_calls = sample_calls(key, prios, call_ids, enabled)
    return merged, new, has_new, next_calls


def _combine_words(bits) -> np.ndarray:
    """(2, n) uint32 halves → (n,) uint64 words."""
    hi, lo = np.asarray(bits[0], np.uint64), np.asarray(bits[1], np.uint64)
    return (hi << np.uint64(32)) | lo


def random_words(key: jax.Array, n: int) -> np.ndarray:
    """One device call → n uint64 words for prog.rand.Rand.refill."""
    return _combine_words(jax.random.bits(key, (2, n), dtype=jnp.uint32))


# ---------------------------------------------------------------------------
# Campaign overlays + word-block-sparse frontier views.


@dataclass(frozen=True)
class DeviceOverlay:
    """A campaign's steering operands, device-resident and fixed-shape:
    a (C,) float32 priority-column multiplier and a (C,) bool enabled
    restriction.  Shapes never vary (always the full call axis), so
    swapping one overlay for another changes operand CONTENTS only — a
    warm decision megakernel never recompiles across campaign swaps."""
    name: str
    boost: jax.Array            # (C,) float32, device-resident
    enabled: jax.Array          # (C,) bool, device-resident


class SparseView:
    """Word-block-sparse accumulation view over the shared coverage
    bitmap: one campaign's frontier, stored as {block id -> (ncalls,
    block_words) uint32} so N concurrent steered frontiers share one
    device bitmap while each tracks only the blocks ITS execs lit up.
    Absorbs the per-batch new-signal diffs the update dispatches
    already compute (no extra device work); `merge`d views reproduce
    the global bitmap exactly (every new bit is attributed to exactly
    one batch by diff_merge's sequencing).

    Host-side and lock-free of device work: callers absorb OUTSIDE the
    engine's state lock (the diff arrays are plain fetch targets)."""

    def __init__(self, tag: str, ncalls: int, nwords: int,
                 block_words: int):
        self.tag = tag
        self.ncalls = ncalls
        self.W = nwords
        self.block_words = max(1, block_words)
        self._blocks: dict[int, np.ndarray] = {}
        self._mu = threading.Lock()

    def _block(self, b: int) -> np.ndarray:
        blk = self._blocks.get(b)
        if blk is None:
            blk = self._blocks[b] = np.zeros(
                (self.ncalls, self.block_words), np.uint32)
        return blk

    def absorb(self, call_ids, result) -> None:
        """Fold one update result's new-signal bits in.  Accepts an
        UpdateResult (dense full-width diffs) or a SparseUpdateResult
        (block-local diffs + touched-block list; its dense fallback has
        blocks=None and full-width diffs)."""
        new = np.asarray(result.new_bits)
        blocks = getattr(result, "blocks", None)
        call_ids = np.asarray(call_ids, np.int64)
        bw = self.block_words
        with self._mu:
            if blocks is None:
                nb = self.W // bw
                for i, cid in enumerate(call_ids):
                    row = new[i]
                    for b in np.nonzero(
                            row.reshape(nb, bw).any(axis=1))[0]:
                        self._block(int(b))[cid] |= \
                            row[b * bw: (b + 1) * bw]
            else:
                nb = self.W // bw
                for i, cid in enumerate(call_ids):
                    row = new[i]
                    for k, b in enumerate(blocks):
                        if b >= nb:
                            continue            # sentinel padding
                        seg = row[k * bw: (k + 1) * bw]
                        if seg.any():
                            self._block(int(b))[cid] |= seg

    def mark(self, indices, call_id: int = 0) -> None:
        """Set bits by global bitmap index (the transition-coverage
        use: indices are dense transition ids)."""
        idx = np.asarray(indices, np.int64).ravel()
        idx = idx[(idx >= 0) & (idx < self.W * 32)]
        with self._mu:
            for x in idx:
                b = int(x) >> 5
                self._block(b // self.block_words)[
                    call_id, b % self.block_words] |= \
                    np.uint32(1) << np.uint32(x & 31)

    def to_dense(self) -> np.ndarray:
        """(ncalls, W) uint32 — the view materialized full-width."""
        out = np.zeros((self.ncalls, self.W), np.uint32)
        bw = self.block_words
        with self._mu:
            for b, blk in self._blocks.items():
                out[:, b * bw: (b + 1) * bw] |= blk
        return out

    def popcount(self) -> int:
        with self._mu:
            if not self._blocks:
                return 0
            stack = np.stack(list(self._blocks.values()))
        return int(np.unpackbits(stack.view(np.uint8)).sum())

    def export_blocks(self) -> "tuple[np.ndarray, np.ndarray]":
        """Snapshot serialization: sorted touched-block ids plus their
        (ncalls, block_words) slabs stacked along axis 0."""
        with self._mu:
            ids = np.array(sorted(self._blocks), np.int64)
            data = (np.stack([self._blocks[int(b)] for b in ids])
                    if len(ids) else
                    np.zeros((0, self.ncalls, self.block_words), np.uint32))
        return ids, data

    def import_blocks(self, ids, data) -> None:
        """OR a serialized block set back in (restore path)."""
        with self._mu:
            for b, blk in zip(np.asarray(ids, np.int64),
                              np.asarray(data, np.uint32)):
                self._block(int(b))[:] |= blk

    def touched_block_count(self) -> int:
        with self._mu:
            return len(self._blocks)

    def merge(self, other: "SparseView") -> None:
        with other._mu:
            items = [(b, blk.copy()) for b, blk in other._blocks.items()]
        with self._mu:
            for b, blk in items:
                self._block(b)[:] |= blk


def merge_views(views) -> np.ndarray:
    """OR-union of several views' dense bitmaps (the 'frontiers merge
    back to the global bitmap' acceptance check)."""
    views = list(views)
    if not views:
        raise ValueError("no views")
    out = views[0].to_dense()
    for v in views[1:]:
        out |= v.to_dense()
    return out


# ---------------------------------------------------------------------------
# The stateful engine: device arrays + jitted steps.


def _locked(fn):
    """Serialize stateful engine ops: they donate buffers to XLA, so a
    second thread entering mid-call would touch a deleted array."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._state_mu:
            return fn(self, *args, **kwargs)

    return wrapper


@dataclass
class UpdateResult:
    has_new: np.ndarray     # (B,) bool — new signal vs max cover
    new_bits: jax.Array     # (B, W) device-resident diff bitmaps
    bitmaps: jax.Array      # (B, W) device-resident full exec bitmaps


@dataclass
class DecisionBlock:
    """One decision-stream megakernel emission — every field is a
    device array the caller fetches later (JAX async dispatch), so the
    dispatch itself never blocks the issuing thread."""
    base: jax.Array         # (ncalls+1, per_row) int32 choice draws;
    #                         row r conditions on prev call r-1
    hot: jax.Array          # (H,) int32 draws for the adaptive hot-row
    #                         prev composition (cached device operand)
    corpus_rows: jax.Array  # (n_rows,) int32 signal-weighted corpus picks
    entropy: jax.Array      # (2, n_entropy) uint32 halves → uint64 words


@dataclass
class SynthBlock:
    """One program-synthesis megakernel emission: B complete exec-
    bytecode programs as a fixed-layout (B, 2L) uint32 slab matrix
    (u64 words split little-endian lo/hi — exactly the program-ring
    wire format) plus the per-program operator provenance the host
    needs for attribution, replay (slab→Prog for triage/csource), and
    the distribution-equivalence tests.  Every field is a device array
    fetched at resolve time — the dispatch never blocks."""
    out32: jax.Array        # (B, 2L) uint32 program slabs (EOF included)
    lens32: jax.Array       # (B,) int32 live u32 words per slab
    op: jax.Array           # (B,) operator (prog.synth OP_*)
    r1: jax.Array           # (B,) primary corpus row
    r2: jax.Array           # (B,) splice donor row
    cut: jax.Array          # (B,) splice insertion call index
    pos: jax.Array          # (B,) insert-call position
    dele: jax.Array         # (B,) squash removed call (-1 = no-op)
    k: jax.Array            # (B,) generate call count
    gen_cids: jax.Array     # (B, GMAX) generate call-id chain
    ins_cid: jax.Array      # (B,) insert-call drawn call id
    slot: jax.Array         # (B,) mutate slot ordinal (-1 = no-op)
    mut_kind: jax.Array     # (B,) mutate kind (rand/delta/flip)
    mut_lo: jax.Array       # (B,) mutated value halves (masked)
    mut_hi: jax.Array
    n_entries: jax.Array    # (B,) kept segment entries


@dataclass
class SparseUpdateResult:
    has_new: jax.Array          # (B,) device bool — fetch with np.asarray
    new_bits: jax.Array         # (B, MB*block_words) block-LOCAL diffs,
    #                             or full-width on the dense fallback
    blocks: "np.ndarray | None"  # (MB,) touched block ids; None = dense


@dataclass
class FuzzTickResult:
    """One fused fuzz tick (engine.fuzz_tick): the union of an
    IngestResult (signal plane) and an admit_slabs return (admission +
    draws), produced by ONE dispatch.  Signal-plane fields stay device
    arrays so DeviceSignal can keep its async resolve/absorb contract;
    admission fields are host values (the caller needs them
    synchronously for corpus bookkeeping anyway)."""
    sig_has_new: jax.Array       # (B,) bool device — vs max cover
    sig_new_bits: jax.Array      # (B, W) device diff bitmaps
    has_new: np.ndarray          # (B,) bool host — admission verdicts
    rows: "np.ndarray | None"    # assigned corpus rows (None: cap fallback)
    choices: np.ndarray          # (P,) pre-drawn next-call ids
    new_bits: np.ndarray         # (B,) per-input new-bit counts
    miss_rows: jax.Array         # (B,) bool device — first-sight rows
    fused: bool = True           # False when the cap fallback ran unfused
    n_evicted: int = 0           # hot rows demoted warm (tiered only)

    def signal_view(self) -> "IngestResult":
        """The signal-plane slice as an IngestResult — what
        SparseView.absorb and the DeviceSignal resolve path consume."""
        return IngestResult(has_new=self.sig_has_new,
                            new_bits=self.sig_new_bits,
                            miss_rows=self.miss_rows)


@dataclass
class IngestResult:
    """One zero-copy slab-batch ingest dispatch (translate + pack +
    diff/merge fused): every field is a device array the caller fetches
    at resolve time — the dispatch itself never syncs.  `miss_rows`
    marks slabs that contained first-sight PCs (the direct table had
    room, so the kernel could not assign them): the caller resolves
    those rows host-side once per batch and fixes up with one bounded
    extra dispatch."""
    has_new: jax.Array          # (B,) bool
    new_bits: jax.Array         # (B, W) full-width diff bitmaps
    miss_rows: jax.Array        # (B,) bool — rows needing host key resolve
    blocks: None = None         # SparseView.absorb compatibility (dense)


class CoverageEngine:
    """Device-resident fuzzing state (SURVEY §7 architecture stance).

    Holds per-call max-cover / corpus-cover / flakes bitmaps, the corpus
    signal matrix, and the priority/choice state.  All updates are jitted
    fixed-shape steps; multi-chip sharding over the PC axis via shard().
    """

    def __init__(self, npcs: int, ncalls: int, corpus_cap: int = 4096,
                 batch: int = 64, max_pcs_per_exec: int = 512,
                 mesh: "Mesh | None" = None, seed: int = 0,
                 block_words: int = 2, max_touched_blocks: int = 0,
                 telemetry=None, kernel_plane: str = "auto"):
        self.npcs = npcs
        # which implementation the registered hot kernels resolve to
        # (kernels.KERNELS planes: auto/jnp/pallas/pallas-interpret).
        # Resolution happens ONCE per _build(), so every jitted closure
        # keeps one signature per plane and a ResilientEngine standby
        # built with kernel_plane="jnp" swaps in compile-free.
        self.kernel_plane = kernel_plane
        self.active_plane = KERNELS.resolve_plane(kernel_plane)
        # telemetry: a telemetry.device.DeviceStats whose fixed-slot
        # int32 vector the fused dispatches bump in place (.at[].add
        # inside the jit) — hot-loop counting without extra round trips.
        # None disables instrumentation entirely (the bumps are not
        # traced at all, so the disabled path compiles unchanged).
        self.tstats = telemetry
        self.ncalls = ncalls
        self.W = nwords_for(npcs)
        self.cap = corpus_cap
        self.batch = batch
        self.K = max_pcs_per_exec
        self.mesh = mesh
        # word-block-sparse config: 0 max_touched_blocks disables the
        # sparse fast path (update_batch_sparse degrades to the dense
        # step).  MB * block_words must stay 64-word aligned for
        # pack_pcs's MXU factoring, so round MB up.
        self.block_words = block_words
        if max_touched_blocks > 0:
            per = max(1, 64 // block_words)
            max_touched_blocks = -(-max_touched_blocks // per) * per
            if self.W % block_words:
                max_touched_blocks = 0      # bitmap not block-divisible
            elif max_touched_blocks * block_words >= self.W:
                max_touched_blocks = 0      # sparse wouldn't be narrower
        self.max_touched_blocks = max_touched_blocks
        self.key = jax.random.PRNGKey(seed)
        # the decision stream's own key chain: carried through the
        # megakernel via a donated buffer so refills move zero host
        # operands (split off the main chain lazily on first block)
        self._ds_key: "jax.Array | None" = None
        # the synth megakernel's donated key chain (same pattern)
        self._synth_key: "jax.Array | None" = None
        self._key_mu = threading.Lock()
        self._state_mu = threading.RLock()

        shape_cover = (ncalls, self.W)
        self.max_cover = jnp.zeros(shape_cover, jnp.uint32)
        self.corpus_cover = jnp.zeros(shape_cover, jnp.uint32)
        self.flakes = jnp.zeros(shape_cover, jnp.uint32)
        self.corpus_mat = jnp.zeros((corpus_cap, self.W), jnp.uint32)
        self.corpus_call = np.zeros((corpus_cap,), np.int32)  # host-read only
        self.corpus_len = 0
        # per-row last-admit tick: the recency input of the eviction
        # score.  Only the fused tick and swap_rows maintain it (other
        # admit paths leave 0 = maximally old) — a row that never rode
        # the tiered paths is simply first in line to demote.
        self.corpus_seen = jnp.zeros((corpus_cap,), jnp.int32)
        self._tick = 0
        # tiered corpus hierarchy (corpus/tiers.py TierManager) — when
        # attached, admission past corpus_cap demotes the
        # lowest-retention rows to the warm store instead of falling
        # back unfused/dropping
        self.tiers = None
        self.prios = jnp.full((ncalls, ncalls), 1.0, jnp.float32)
        self.enabled = jnp.ones((ncalls,), jnp.bool_)
        # dummy stat-vector operands for the telemetry-disabled mode:
        # the jitted steps keep one signature either way
        self._ts_dummy = jnp.zeros((1,), jnp.int32)
        # the flat (no-campaign) overlay: all-ones boost + all-true
        # enabled restriction.  Campaign overlays share these shapes,
        # so a swap changes operand contents only — never a signature.
        self._ov_neutral = DeviceOverlay(
            name="", boost=jnp.ones((ncalls,), jnp.float32),
            enabled=jnp.ones((ncalls,), jnp.bool_))
        # per-campaign frontier views over the shared bitmap
        self._frontiers: dict[str, SparseView] = {}
        self._frontier_mu = threading.Lock()

        if mesh is not None:
            self.shard(mesh)
        self._build()

    # -- sharding ------------------------------------------------------------

    def shard(self, mesh: Mesh) -> None:
        """Shard the PC (word) axis across `mesh`'s 'pc' axis; call-indexed
        small state is replicated.  Elementwise diff/merge then runs fully
        local per chip; cross-chip traffic is the any()/popcount verdicts
        (psum over ICI), per SURVEY §5's long-axis plan."""
        self.mesh = mesh
        row = NamedSharding(mesh, P(None, "pc"))
        rep = NamedSharding(mesh, P())
        self.max_cover = jax.device_put(self.max_cover, row)
        self.corpus_cover = jax.device_put(self.corpus_cover, row)
        self.flakes = jax.device_put(self.flakes, row)
        self.corpus_mat = jax.device_put(self.corpus_mat, row)
        self.corpus_seen = jax.device_put(self.corpus_seen, rep)
        self.prios = jax.device_put(self.prios, rep)
        self.enabled = jax.device_put(self.enabled, rep)
        self._ts_dummy = jax.device_put(self._ts_dummy, rep)
        self._ov_neutral = DeviceOverlay(
            name="",
            boost=jax.device_put(self._ov_neutral.boost, rep),
            enabled=jax.device_put(self._ov_neutral.enabled, rep))
        if self.tstats is not None:
            self.tstats.device_put(mesh)
        self._build()

    # -- jit closures ----------------------------------------------------

    def _build(self) -> None:
        npcs = self.npcs
        ds = self.tstats
        # plane-selected hot kernels: every closure below closes over
        # these callables, resolved ONCE here (registry.fn is a
        # build-time decision — see kernels/registry.py).  On TPU-like
        # backends these are the pallas twins; everywhere else the jnp
        # oracles, which double as the bit-exactness reference.
        self.active_plane = KERNELS.resolve_plane(self.kernel_plane)
        k_translate = KERNELS.fn("translate_slab_rows", self.kernel_plane)
        k_sigdiff = KERNELS.fn("signal_diff", self.kernel_plane)
        k_sgather = KERNELS.fn("synth_gather", self.kernel_plane)
        k_evict = KERNELS.fn("evict_score", self.kernel_plane)

        def _bump(svec, hinc, batch_slot, rows_slot, new_slot,
                  valid, has_new, extra=()):
            """Fold the ride-along host increments and this dispatch's
            own counts into the stat vector — INSIDE the jit, so
            telemetry costs a few scalar adds on a tiny replicated
            vector, never a transfer of its own.  Traced only when
            telemetry is enabled (ds closure)."""
            svec = svec + hinc
            svec = svec.at[ds.slot(batch_slot)].add(1)
            svec = svec.at[ds.slot(rows_slot)].add(
                jnp.sum(valid.any(axis=-1), dtype=jnp.int32))
            svec = svec.at[ds.slot(new_slot)].add(
                jnp.sum(has_new, dtype=jnp.int32))
            for slot, n in extra:
                svec = svec.at[ds.slot(slot)].add(jnp.int32(n))
            return svec

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _update(max_cover, call_ids, pc_idx, valid, svec, hinc):
            # PcMap.map_batch guarantees unique indices per row
            bitmaps = pack_pcs(pc_idx, valid, npcs, assume_unique=True)
            merged, new, has_new = diff_merge(max_cover, call_ids, bitmaps)
            if ds is not None:
                svec = _bump(svec, hinc, "dense_batches", "dense_rows",
                             "dense_newsig", valid, has_new)
            return merged, new, has_new, bitmaps, svec

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _or_rows(base, call_ids, bitmaps):
            return scatter_or(base, call_ids, bitmaps)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _update_sparse(max_cover, call_ids, pc_idx, valid, blocks,
                          svec, hinc):
            merged, new, has_new = sparse_update(
                max_cover, call_ids, pc_idx, valid, blocks, npcs,
                self.block_words)
            if ds is not None:
                svec = _bump(svec, hinc, "sparse_batches", "sparse_rows",
                             "sparse_newsig", valid, has_new)
            return merged, new, has_new, svec

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def _admit_if_new(corpus_cover, corpus_mat, flakes, call_ids,
                          pc_idx, valid, start, svec, hinc):
            """Fused admission gate + merge in ONE dispatch: the manager
            used to pay two tunnel round-trips per NewInput (diff, then
            merge) while holding its admission lock.  In-batch
            sequencing is exact (diff_merge): two identical new-coverage
            entries in one batch admit exactly one row, matching the
            sequential two-step semantics."""
            bitmaps = pack_pcs(pc_idx, valid, npcs, assume_unique=True)
            gate = jnp.bitwise_or(corpus_cover, flakes)
            _g, _new, has_new = diff_merge(gate, call_ids, bitmaps)
            # per-input new-bit counts (submission order): the frontier
            # productivity signal the campaign scheduler's
            # new_cov_per_1k_exec EWMA folds — free here, the diff rows
            # are already materialized
            rowbits = popcount_rows(_new)
            rows = jnp.where(has_new[:, None], bitmaps, jnp.uint32(0))
            cover = scatter_or(corpus_cover, call_ids, rows)
            idx = jnp.cumsum(has_new.astype(jnp.int32)) - 1 + start
            idx = jnp.where(has_new, idx, corpus_mat.shape[0])
            mat = corpus_mat.at[idx].set(bitmaps, mode="drop")
            if ds is not None:
                svec = _bump(svec, hinc, "admit_batches", "admit_inputs",
                             "admit_admitted", valid, has_new)
            return cover, mat, has_new, rowbits, svec

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def _admit_if_new_choices(corpus_cover, corpus_mat, flakes,
                                  call_ids, pc_idx, valid, start, key,
                                  prios, enabled, prev, svec, hinc):
            """The coalescer's fused step: the batched admission gate +
            merge PLUS a batch of ChoiceTable draws in the SAME
            dispatch, so Poll responses are fed from a pre-drawn ring
            instead of paying a separate sample_next_calls round trip
            per poll."""
            bitmaps = pack_pcs(pc_idx, valid, npcs, assume_unique=True)
            gate = jnp.bitwise_or(corpus_cover, flakes)
            _g, _new, has_new = diff_merge(gate, call_ids, bitmaps)
            rowbits = popcount_rows(_new)
            rows = jnp.where(has_new[:, None], bitmaps, jnp.uint32(0))
            cover = scatter_or(corpus_cover, call_ids, rows)
            idx = jnp.cumsum(has_new.astype(jnp.int32)) - 1 + start
            idx = jnp.where(has_new, idx, corpus_mat.shape[0])
            mat = corpus_mat.at[idx].set(bitmaps, mode="drop")
            draws = sample_calls(key, prios, prev, enabled)
            if ds is not None:
                svec = _bump(svec, hinc, "admit_batches", "admit_inputs",
                             "admit_admitted", valid, has_new,
                             extra=[("admit_draws", prev.shape[0])])
            return cover, mat, has_new, rowbits, draws, svec

        @jax.jit
        def _diff_vs(base, call_ids, pc_idx, valid, flakes):
            bitmaps = pack_pcs(pc_idx, valid, npcs, assume_unique=True)
            prev = jnp.bitwise_or(base[call_ids], flakes[call_ids])
            new, has_new, _nbits = k_sigdiff(prev, bitmaps)
            return new, has_new, bitmaps

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _admit(corpus_mat, bitmaps, admit_mask, start):
            # append admitted rows at positions start.. ; start is traced
            # (it changes every admission — static would recompile each time)
            idx = jnp.cumsum(admit_mask.astype(jnp.int32)) - 1 + start
            idx = jnp.where(admit_mask, idx, corpus_mat.shape[0])  # drop
            return corpus_mat.at[idx].set(bitmaps, mode="drop")

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def _admit_selected(corpus_cover, corpus_mat, bitmaps, call_ids,
                            row_idx, mask, start):
            """Fused corpus admission for selected exec rows, fixed shape:
            row_idx/mask select which bitmap rows get admitted."""
            rows = jnp.where(mask[:, None], bitmaps[row_idx], jnp.uint32(0))
            sel_ids = call_ids[row_idx]
            cover = scatter_or(corpus_cover, sel_ids, rows)
            idx = jnp.cumsum(mask.astype(jnp.int32)) - 1 + start
            idx = jnp.where(mask, idx, corpus_mat.shape[0])
            mat = corpus_mat.at[idx].set(rows, mode="drop")
            return cover, mat

        @jax.jit
        def _minimize(corpus_mat, active):
            return minimize_cover(corpus_mat, active)

        @jax.jit
        def _minimize_scan(corpus_mat, active):
            return minimize_cover_scan(corpus_mat, active)

        @functools.partial(jax.jit, static_argnums=(2,))
        def _sample_rows(key, weights, n):
            logits = jnp.where(weights > 0, jnp.log(weights.astype(
                jnp.float32)), -jnp.inf)
            return jax.random.categorical(key, logits[None, :], axis=-1,
                                          shape=(1, n))[0]

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _compact(corpus_mat, keep_mask, corpus_call):
            # compact kept rows to the front; rebuild per-call cover as
            # the or-union of the survivors
            idx = jnp.cumsum(keep_mask.astype(jnp.int32)) - 1
            idx = jnp.where(keep_mask, idx, corpus_mat.shape[0])
            rows = jnp.where(keep_mask[:, None], corpus_mat, jnp.uint32(0))
            new_mat = jnp.zeros_like(corpus_mat).at[idx].set(
                corpus_mat, mode="drop")
            cover = scatter_or(
                jnp.zeros((self.ncalls, corpus_mat.shape[1]), jnp.uint32),
                corpus_call, rows)
            return new_mat, cover

        @jax.jit
        def _sample(key, probs, prev, enabled, ov_boost, ov_enabled):
            return sample_calls_boosted(
                key, probs, prev, jnp.logical_and(enabled, ov_enabled),
                ov_boost)

        @jax.jit
        def _prio_update(static_prios, call_matrix):
            dyn = normalize_prios(dynamic_prios(call_matrix))
            return normalize_prios(static_prios * dyn)

        @functools.partial(jax.jit, static_argnums=(1,))
        def _random_bits(key, n):
            return jax.random.bits(key, (2, n), dtype=jnp.uint32)

        ncalls = self.ncalls

        @functools.partial(jax.jit, donate_argnums=(0,),
                           static_argnums=(9, 10, 11))
        def _decision(key, prios, enabled, corpus_mat, hot_prev,
                      ov_boost, ov_enabled, svec, hinc,
                      per_row, n_rows, n_entropy):
            """The decision-stream megakernel: ONE dispatch emits a
            structured decision block — per-context choice-table draws
            for every prev row (cdf materialized once, draws are
            vectorized binary searches), a hot-row extension over the
            adaptive prev composition, a batch of signal-weighted
            corpus-row picks, and a slab of raw entropy for Rand.refill.
            The PRNG key is donated: steady-state refills move no host
            operands in (prios/enabled/corpus_mat/hot_prev are already
            device-resident) and the ring-refill stats are bumped in
            place on the device stat vector.

            `ov_boost`/`ov_enabled` are the campaign overlay: fixed
            (C,) shapes (the neutral overlay is ones/trues), applied
            INSIDE the dispatch so retargeting the stream at a
            subsystem swaps operand contents, never the kernel."""
            key, k1, k2, k3, k4 = jax.random.split(key, 5)
            en = jnp.logical_and(enabled, ov_enabled)
            base = sample_calls_rows_boosted(k1, prios, en, per_row,
                                             ov_boost)
            hot = sample_calls_boosted(k2, prios, hot_prev, en, ov_boost)
            wts = popcount_rows(corpus_mat)
            logits = jnp.where(wts > 0,
                               jnp.log(wts.astype(jnp.float32)), -jnp.inf)
            # empty corpus: flat logits keep categorical finite; the
            # host consumer drops rows >= corpus_len anyway
            logits = jnp.where(jnp.any(wts > 0), logits,
                               jnp.zeros_like(logits))
            crows = jax.random.categorical(
                k3, logits[None, :], axis=-1,
                shape=(1, n_rows))[0].astype(jnp.int32)
            ent = jax.random.bits(k4, (2, n_entropy), dtype=jnp.uint32)
            if ds is not None:
                svec = svec + hinc
                svec = svec.at[ds.slot("ring_refill")].add(1)
                svec = svec.at[ds.slot("ring_draws")].add(
                    jnp.int32((ncalls + 1) * per_row + hot_prev.shape[0]))
            return key, base, hot, crows, ent, svec

        # -- zero-copy slab ingest: the PcMap translation runs ON DEVICE
        # (sorted-mirror binary search, translate_slab_rows) inside the
        # same fused dispatch as pack/diff/merge, so a slab batch goes
        # ring view → device with no host packing at all.  direct_cap/
        # overflow are static (one PcMap config per engine lifetime).

        @functools.partial(jax.jit, donate_argnums=(0,),
                           static_argnums=(8, 9))
        def _ingest_update(max_cover, win, counts, call_ids, skeys,
                           svals, meta, svec, direct_cap, overflow, hinc):
            idx, valid, miss = k_translate(
                win, counts, skeys, svals, meta, direct_cap, overflow)
            # overflow aliasing can duplicate an index within a row —
            # sort-dedup inside the pack (host map_rows dedups too)
            bitmaps = pack_pcs(idx, valid, npcs, assume_unique=False)
            merged, new, has_new = diff_merge(max_cover, call_ids, bitmaps)
            miss_rows = jnp.any(miss, axis=1)
            if ds is not None:
                svec = _bump(svec, hinc, "dense_batches", "dense_rows",
                             "dense_newsig", valid, has_new)
                svec = svec.at[ds.slot("ingest_batches")].add(1)
                svec = svec.at[ds.slot("ingest_slabs")].add(
                    jnp.sum(counts > 0, dtype=jnp.int32))
                svec = svec.at[ds.slot("ingest_bytes")].add(
                    jnp.sum(counts, dtype=jnp.int32) * 4)
            return merged, new, has_new, miss_rows, svec

        @functools.partial(jax.jit, donate_argnums=(0, 1),
                           static_argnums=(15, 16))
        def _ingest_admit(corpus_cover, corpus_mat, flakes, win, counts,
                          call_ids, start, key, prios, enabled, prev,
                          skeys, svals, meta, svec, direct_cap, overflow,
                          hinc):
            """The coalescer's zero-copy step: on-device translation
            fused with the batched admission gate + merge + choice
            draws — the host-side map_batch scatter/dedup/pad is
            retired.  The caller pre-resolves first-sight keys
            (DeviceKeyMirror.ensure), so misses cannot occur; the mask
            still rides back as a cheap invariant check."""
            idx, valid, miss = k_translate(
                win, counts, skeys, svals, meta, direct_cap, overflow)
            bitmaps = pack_pcs(idx, valid, npcs, assume_unique=False)
            gate = jnp.bitwise_or(corpus_cover, flakes)
            _g, _new, has_new = diff_merge(gate, call_ids, bitmaps)
            rowbits = popcount_rows(_new)
            rows = jnp.where(has_new[:, None], bitmaps, jnp.uint32(0))
            cover = scatter_or(corpus_cover, call_ids, rows)
            ridx = jnp.cumsum(has_new.astype(jnp.int32)) - 1 + start
            ridx = jnp.where(has_new, ridx, corpus_mat.shape[0])
            mat = corpus_mat.at[ridx].set(bitmaps, mode="drop")
            draws = sample_calls(key, prios, prev, enabled)
            miss_rows = jnp.any(miss, axis=1)
            if ds is not None:
                svec = _bump(svec, hinc, "admit_batches", "admit_inputs",
                             "admit_admitted", valid, has_new,
                             extra=[("admit_draws", prev.shape[0])])
                svec = svec.at[ds.slot("ingest_batches")].add(1)
                svec = svec.at[ds.slot("ingest_slabs")].add(
                    jnp.sum(counts > 0, dtype=jnp.int32))
                svec = svec.at[ds.slot("ingest_bytes")].add(
                    jnp.sum(counts, dtype=jnp.int32) * 4)
            return cover, mat, has_new, rowbits, draws, miss_rows, svec

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 18),
                           static_argnums=(16, 17, 20))
        def _fuzz_tick(max_cover, corpus_cover, corpus_mat, flakes, win,
                       counts, call_ids, start, key, prios, enabled,
                       prev, skeys, svals, meta, svec, direct_cap,
                       overflow, seen, tick, tiered, hinc):
            """ONE whole fuzz tick in ONE dispatch: ingest-translate →
            signal diff/merge into max cover → admission gate + corpus
            merge → tsdb slot bumps → decision draws.  The unfused
            path pays two host→device boundary crossings per batch
            (ingest_update_slabs for the signal plane, then admit_slabs
            for admission + draws); this closure is their exact
            composition — same kernels, same in-batch sequencing
            (diff_merge both times), same stat slots plus the
            tick_batches marker — so fused-vs-unfused stays frontier
            bit-exact while the host boundary is crossed once.

            With `tiered` (static — a per-engine-mode build decision,
            like the kernel plane), the admission stage fuses the
            eviction-score kernel: admits past the matrix cap redirect
            into the highest-score (most shadowed, stalest) rows
            instead of dropping, and the displaced contents ride out in
            the same dispatch for the host to demote warm.  A victim
            always scores ≥0 only when live (< start), so redirects
            never collide with the within-cap append indices (which
            are ≥ start); `attach_tiers` enforces cap ≥ 2·batch so a
            full batch of redirects still finds live victims.

            Donates the three big matrices plus the recency vector:
            steady-state ticks move only the slab window in and
            verdict vectors out."""
            idx, valid, miss = k_translate(
                win, counts, skeys, svals, meta, direct_cap, overflow)
            bitmaps = pack_pcs(idx, valid, npcs, assume_unique=False)
            merged, sig_new, sig_has = diff_merge(max_cover, call_ids,
                                                  bitmaps)
            gate = jnp.bitwise_or(corpus_cover, flakes)
            _g, _new, has_new = diff_merge(gate, call_ids, bitmaps)
            rowbits = popcount_rows(_new)
            rows = jnp.where(has_new[:, None], bitmaps, jnp.uint32(0))
            cover = scatter_or(corpus_cover, call_ids, rows)
            B = call_ids.shape[0]
            cap = corpus_mat.shape[0]
            raw = jnp.cumsum(has_new.astype(jnp.int32)) - 1 + start
            if tiered:
                scores = k_evict(corpus_mat, seen, start, tick)
                _sv, victims = jax.lax.top_k(scores, B)
                evicted = corpus_mat[victims]
                ovpos = jnp.clip(raw - cap, 0, B - 1)
                ridx = jnp.where(raw < cap, raw, victims[ovpos])
                n_evict = jnp.sum(has_new & (raw >= cap),
                                  dtype=jnp.int32)
            else:
                victims = jnp.zeros((B,), jnp.int32)
                evicted = jnp.zeros_like(bitmaps)
                ridx = raw
                n_evict = jnp.int32(0)
            ridx = jnp.where(has_new, ridx, cap)
            mat = corpus_mat.at[ridx].set(bitmaps, mode="drop")
            seen = seen.at[ridx].set(tick, mode="drop")
            draws = sample_calls(key, prios, prev, enabled)
            miss_rows = jnp.any(miss, axis=1)
            if ds is not None:
                svec = _bump(svec, hinc, "admit_batches", "admit_inputs",
                             "admit_admitted", valid, has_new,
                             extra=[("admit_draws", prev.shape[0])])
                svec = svec.at[ds.slot("dense_batches")].add(1)
                svec = svec.at[ds.slot("dense_rows")].add(
                    jnp.sum(valid.any(axis=-1), dtype=jnp.int32))
                svec = svec.at[ds.slot("dense_newsig")].add(
                    jnp.sum(sig_has, dtype=jnp.int32))
                svec = svec.at[ds.slot("ingest_batches")].add(1)
                svec = svec.at[ds.slot("ingest_slabs")].add(
                    jnp.sum(counts > 0, dtype=jnp.int32))
                svec = svec.at[ds.slot("ingest_bytes")].add(
                    jnp.sum(counts, dtype=jnp.int32) * 4)
                svec = svec.at[ds.slot("tick_batches")].add(1)
                if tiered:
                    svec = svec.at[ds.slot("tier_evictions")].add(
                        n_evict)
            return (merged, cover, mat, seen, sig_has, sig_new, has_new,
                    rowbits, draws, miss_rows, victims, evicted,
                    n_evict, svec)

        @jax.jit
        def _evict_scores(corpus_mat, seen, nlive, tick):
            return k_evict(corpus_mat, seen, nlive, tick)

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def _swap_rows(corpus_cover, corpus_mat, seen, ridx, call_ids,
                       new_rows, tick):
            """Contents-only row replacement — the promotion half of
            the tier swap.  ridx is padded with `cap` (out of range;
            mode="drop" skips) and padded new_rows are zero (a no-op
            under scatter_or), so every batch size dispatches through
            ONE pow2-bucketed signature.  Returns the displaced row
            contents for demotion."""
            old = corpus_mat[jnp.clip(ridx, 0, corpus_mat.shape[0] - 1)]
            mat = corpus_mat.at[ridx].set(new_rows, mode="drop")
            seen = seen.at[ridx].set(tick, mode="drop")
            cover = scatter_or(corpus_cover, call_ids, new_rows)
            return cover, mat, seen, old

        @functools.partial(jax.jit, static_argnums=(8, 9))
        def _ingest_diff(base, flakes, win, counts, call_ids, skeys,
                         svals, meta, direct_cap, overflow):
            """Translate + diff-vs-(base ∪ flakes), no state mutation —
            the triage-gate slab path.  Returns the translated index
            rows too: the caller reads each PC's verdict through its
            own index (overflow aliasing degrades to a shared verdict,
            matching the host path)."""
            idx, valid, miss = k_translate(
                win, counts, skeys, svals, meta, direct_cap, overflow)
            bitmaps = pack_pcs(idx, valid, npcs, assume_unique=False)
            prev = jnp.bitwise_or(base[call_ids], flakes[call_ids])
            new, has_new, _nbits = k_sigdiff(prev, bitmaps)
            return new, has_new, bitmaps, idx, jnp.any(miss, axis=1)

        @functools.partial(jax.jit, static_argnums=(5, 6))
        def _ingest_pack(win, counts, skeys, svals, meta, direct_cap,
                         overflow):
            idx, valid, _miss = k_translate(
                win, counts, skeys, svals, meta, direct_cap, overflow)
            return pack_pcs(idx, valid, npcs, assume_unique=False)

        @functools.partial(jax.jit, static_argnums=(5, 6))
        def _ingest_pack_or(win, counts, skeys, svals, meta, direct_cap,
                            overflow):
            idx, valid, _miss = k_translate(
                win, counts, skeys, svals, meta, direct_cap, overflow)
            bm = pack_pcs(idx, valid, npcs, assume_unique=False)
            return jax.lax.reduce(bm, jnp.uint32(0), jax.lax.bitwise_or,
                                  [0])[None, :]

        @jax.jit
        def _popcount(mat):
            return popcount_rows(mat)

        @jax.jit
        def _pack(pc_idx, valid):
            return pack_pcs(pc_idx, valid, npcs, assume_unique=True)

        @jax.jit
        def _pack_or(pc_idx, valid, rowmask):
            # pack rows then OR-fold the selected ones into a single
            # (1, W) bitmap (rows are full-width, so they compose)
            bm = pack_pcs(pc_idx, valid, npcs, assume_unique=True)
            bm = jnp.where(rowmask[:, None], bm, jnp.uint32(0))
            return jax.lax.reduce(bm, jnp.uint32(0), jax.lax.bitwise_or,
                                  [0])[None, :]

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _update_stream(max_cover, frames):
            """S chained update steps in ONE dispatch: frames is
            (S, B, K+4) uint16 — [:, :, :K] front-packed PC indices,
            [:, :, K] valid count, [:, :, K+1|K+2] call id lo|hi.
            The compact wire format matters: the host↔device transport
            is the bottleneck (per-transfer fixed cost ~0.1s, ~50MB/s),
            so the whole stream ships as one 2-byte-per-PC buffer and
            the per-batch verdicts come back in one fetch."""
            K = frames.shape[2] - 4

            def body(mc, fr):
                idx = fr[:, :K].astype(jnp.int32)
                counts = fr[:, K].astype(jnp.int32)
                cid = (fr[:, K + 1].astype(jnp.int32)
                       | (fr[:, K + 2].astype(jnp.int32) << 16))
                va = jnp.arange(K)[None, :] < counts[:, None]
                bm = pack_pcs(idx, va, npcs, assume_unique=True)
                mc, _new, has_new = diff_merge(mc, cid, bm)
                return mc, has_new

            mc, hn = jax.lax.scan(body, max_cover, frames)
            return mc, hn

        @functools.partial(jax.jit, donate_argnums=(0,))
        def _update_stream32(max_cover, call_ids, pc_idx, counts):
            """int32 variant for npcs > 2^16 (indices don't fit uint16)."""
            K = pc_idx.shape[2]

            def body(mc, x):
                cid, idx, cnt = x
                va = jnp.arange(K)[None, :] < cnt[:, None]
                bm = pack_pcs(idx, va, npcs, assume_unique=True)
                mc, _new, has_new = diff_merge(mc, cid, bm)
                return mc, has_new

            mc, hn = jax.lax.scan(body, max_cover, (call_ids, pc_idx, counts))
            return mc, hn

        # -- device-resident program synthesis: one dispatch emits a
        # batch of COMPLETE exec-bytecode programs assembled from the
        # synth tables (fixed-capacity corpus rows + single-call
        # template bank, the DeviceKeyMirror growth pattern), edited by
        # the five host-mutator operators.  Tables/shapes are fixed, so
        # table growth and operator mix changes move operand contents
        # only — zero warm recompiles.  The operator spec (index-draw
        # formulas, truncation rule) is written down in prog/synth.py;
        # this kernel and prog.synth.HostSynth implement the same text.

        @functools.partial(jax.jit, donate_argnums=(0,),
                           static_argnums=(21, 22))
        def _synth(key, prios, enabled, ov_boost, ov_enabled, opw,
                   rows_lo, rows_hi, call_off, row_ncalls, slot_off,
                   slot_size, row_nslots, row_cids, t_lo, t_hi, t_len,
                   call2tmpl, meta, svec, hinc, B, GMAX):
            R, L = rows_lo.shape
            CO = call_off.shape[1] - 1
            A = slot_off.shape[1]
            Tn, LT = t_lo.shape
            (key, k_op, k_r, k_k, k_gen, k_cut, k_pos, k_ins, k_mut,
             k_rnd, k_sq) = jax.random.split(key, 11)
            nrows = meta[0]
            have = nrows > 0

            # operator draw (prefix-cdf, like every choice draw here);
            # an empty corpus forces generate — branch-free via where
            cdf_op = jnp.cumsum(opw)
            u_op = jax.random.uniform(k_op, (B,)) * cdf_op[-1]
            opv = jnp.sum((u_op[:, None] >= cdf_op[None, :])
                          .astype(jnp.int32), axis=1)
            op = jnp.where(have, jnp.minimum(opv, 4), 0)

            # corpus row picks: floor(u * nrows) — the written-down
            # index-draw formula (real uniforms, not modulo)
            u_r = jax.random.uniform(k_r, (B, 2))
            den = jnp.maximum(nrows, 1).astype(jnp.float32)
            r1 = jnp.minimum((u_r[:, 0] * den).astype(jnp.int32),
                             nrows - 1).clip(0)
            r2 = jnp.minimum((u_r[:, 1] * den).astype(jnp.int32),
                             nrows - 1).clip(0)
            n1 = row_ncalls[r1]
            n2 = row_ncalls[r2]

            # generate: chained per-context choice draws over calls
            # that HAVE templates (sample_calls_boosted per step — the
            # exact decision-stream categorical)
            has_t = call2tmpl >= 0
            en_t = jnp.logical_and(jnp.logical_and(enabled, ov_enabled),
                                   has_t)
            kcount = 1 + (jax.random.uniform(k_k, (B,))
                          * GMAX).astype(jnp.int32)

            def gen_step(prev, kk):
                cid = sample_calls_boosted(kk, prios, prev, en_t,
                                           ov_boost)
                return cid, cid

            _, cids = jax.lax.scan(gen_step,
                                   jnp.full((B,), -1, jnp.int32),
                                   jax.random.split(k_gen, GMAX))
            cids = cids.T                       # (B, GMAX)
            tg = jnp.maximum(call2tmpl[cids], 0)
            tgp = jnp.concatenate(
                [tg, jnp.zeros((B, CO - GMAX), jnp.int32)], axis=1) \
                if CO > GMAX else tg[:, :CO]

            # splice cut / insert position (biased_rand k=5) / squash
            cut = (jax.random.uniform(k_cut, (B,))
                   * (n1 + 1).astype(jnp.float32)).astype(jnp.int32)
            u_pos = jax.random.uniform(k_pos, (B,))
            pos = jnp.minimum(
                ((n1 + 1).astype(jnp.float32)
                 * u_pos ** 0.2).astype(jnp.int32), n1)
            prev_ins = jnp.where(
                pos > 0, row_cids[r1, jnp.maximum(pos - 1, 0)], -1)
            ins_cid = sample_calls_boosted(k_ins, prios, prev_ins,
                                           en_t, ov_boost)
            t_ins = jnp.maximum(call2tmpl[ins_cid], 0)
            u_sq = jax.random.uniform(k_sq, (B,))
            dele = jnp.where(
                n1 > 1,
                (u_sq * n1.astype(jnp.float32)).astype(jnp.int32), -1)

            # per-op entry plans → one branch-free select
            jj = jnp.arange(CO, dtype=jnp.int32)[None, :]
            in1 = jj < cut[:, None]
            in2 = jnp.logical_and(~in1, jj < (cut + n2)[:, None])
            s_row = jnp.where(in2, r2[:, None], r1[:, None])
            s_call = jnp.where(in1, jj,
                               jnp.where(in2, jj - cut[:, None],
                                         jj - n2[:, None]))
            s_val = jj < jnp.minimum(n1 + n2, CO)[:, None]
            at = jj == pos[:, None]
            i_tbl = jnp.where(at, 1, 0)
            i_row = jnp.where(at, t_ins[:, None], r1[:, None])
            i_call = jnp.where(jj < pos[:, None], jj,
                               jnp.maximum(jj - 1, 0))
            i_call = jnp.where(at, 0, i_call)
            i_val = jj < jnp.minimum(n1 + 1, CO)[:, None]
            d_eff = jnp.where(dele >= 0, dele, CO)[:, None]
            q_call = jj + (jj >= d_eff).astype(jnp.int32)
            q_val = jj < jnp.where(n1 > 1, n1 - 1, n1)[:, None]
            m_val = jj < n1[:, None]

            o = op[:, None]

            def sel(g, s, i, m, q):
                return jnp.where(
                    o == 0, g, jnp.where(o == 1, s, jnp.where(
                        o == 2, i, jnp.where(o == 3, m, q))))

            zero = jnp.zeros((B, CO), jnp.int32)
            tbl = sel(jnp.ones((B, CO), jnp.int32), zero, i_tbl, zero,
                      zero)
            row = sel(tgp, s_row, i_row,
                      jnp.broadcast_to(r1[:, None], (B, CO)),
                      jnp.broadcast_to(r1[:, None], (B, CO)))
            call = sel(zero, s_call, i_call,
                       jnp.broadcast_to(jj, (B, CO)), q_call)
            val = sel(jj < kcount[:, None], s_val, i_val, m_val, q_val)

            # segment lengths + the written-down truncation rule: the
            # longest entry prefix whose words fit L-1 (EOF reserved)
            rowc = jnp.clip(row, 0, R - 1)
            rowt = jnp.clip(row, 0, Tn - 1)
            callc = jnp.clip(call, 0, CO - 1)
            c_start = call_off[rowc, callc]
            c_len = call_off[rowc, callc + 1] - c_start
            is_t = tbl == 1
            seglen = jnp.where(val, jnp.where(is_t, t_len[rowt], c_len),
                               0)
            ends0 = jnp.cumsum(seglen, axis=1)
            keep = jnp.logical_and(val, ends0 <= L - 1)
            seglen = jnp.where(keep, seglen, 0)
            ends = jnp.cumsum(seglen, axis=1)
            starts = ends - seglen
            total = ends[:, -1]
            nkept = keep.sum(axis=1, dtype=jnp.int32)
            sstart = jnp.where(is_t, 0, c_start)

            # the assembly gather: out word j ← segment e covering j
            # (kernels.synth_gather — jnp oracle or its pallas twin,
            # whichever this engine's plane resolved)
            lo, hi = k_sgather(ends, starts, sstart, row, is_t, total,
                               rows_lo, rows_hi, t_lo, t_hi)

            # mutate-arg post-edit: one const value word rewritten
            u_mut = jax.random.uniform(k_mut, (B, 5))
            ns = row_nslots[r1]
            a = (u_mut[:, 0] * jnp.maximum(ns, 1).astype(jnp.float32)
                 ).astype(jnp.int32)
            has_slot = jnp.logical_and(op == 3, ns > 0)
            ac = jnp.clip(a, 0, A - 1)
            woff = slot_off[r1, ac]
            sz = slot_size[r1, ac]
            woffc = jnp.clip(woff, 0, L - 1)
            old_lo = rows_lo[r1, woffc]
            old_hi = rows_hi[r1, woffc]
            kind = (u_mut[:, 1] * 3).astype(jnp.int32)
            rbits = jax.random.bits(k_rnd, (B, 2), dtype=jnp.uint32)
            delta = (1 + (u_mut[:, 2] * 16).astype(jnp.int32)
                     ).astype(jnp.uint32)
            add_lo = old_lo + delta
            add_hi = old_hi + (add_lo < old_lo).astype(jnp.uint32)
            sub_lo = old_lo - delta
            sub_hi = old_hi - (old_lo < delta).astype(jnp.uint32)
            sign_pos = u_mut[:, 3] < 0.5
            d_lo = jnp.where(sign_pos, add_lo, sub_lo)
            d_hi = jnp.where(sign_pos, add_hi, sub_hi)
            bit = (u_mut[:, 4] * 64).astype(jnp.uint32)
            one = jnp.uint32(1)
            f_lo = old_lo ^ jnp.where(bit < 32,
                                      jnp.left_shift(one, bit),
                                      jnp.uint32(0))
            f_hi = old_hi ^ jnp.where(bit >= 32,
                                      jnp.left_shift(
                                          one, bit - jnp.uint32(32)),
                                      jnp.uint32(0))
            new_lo = jnp.where(kind == 0, rbits[:, 0],
                               jnp.where(kind == 1, d_lo, f_lo))
            new_hi = jnp.where(kind == 0, rbits[:, 1],
                               jnp.where(kind == 1, d_hi, f_hi))
            full = jnp.uint32(0xFFFFFFFF)
            mask_lo = jnp.where(sz >= 4, full,
                                jnp.left_shift(
                                    one,
                                    jnp.clip(8 * sz, 0, 31)
                                    .astype(jnp.uint32)) - one)
            hi_bits = jnp.clip(8 * (sz - 4), 0, 31).astype(jnp.uint32)
            mask_hi = jnp.where(sz <= 4, jnp.uint32(0),
                                jnp.where(sz >= 8, full,
                                          jnp.left_shift(one, hi_bits)
                                          - one))
            new_lo = new_lo & mask_lo
            new_hi = new_hi & mask_hi
            bidx = jnp.arange(B)
            widx = jnp.where(has_slot, woffc, 0)
            lo = lo.at[bidx, widx].set(
                jnp.where(has_slot, new_lo, lo[bidx, widx]))
            hi = hi.at[bidx, widx].set(
                jnp.where(has_slot, new_hi, hi[bidx, widx]))

            out32 = jnp.stack([lo, hi], axis=-1).reshape(B, 2 * L)
            lens32 = (total + 1) * 2
            if ds is not None:
                svec = svec + hinc
                svec = svec.at[ds.slot("synth_batches")].add(1)
                svec = svec.at[ds.slot("synth_programs")].add(
                    jnp.int32(B))
            return (key, out32, lens32, op, r1, r2, cut, pos, dele,
                    kcount, cids, ins_cid,
                    jnp.where(has_slot, a, -1), kind, new_lo, new_hi,
                    nkept, svec)

        self._fuzz_tick_fn = _fuzz_tick
        self._evict_scores_fn = _evict_scores
        self._swap_rows_fn = _swap_rows
        self._synth_fn = _synth
        self._random_bits_fn = _random_bits
        self._ingest_update_fn = _ingest_update
        self._ingest_admit_fn = _ingest_admit
        self._ingest_diff_fn = _ingest_diff
        self._ingest_pack_fn = _ingest_pack
        self._ingest_pack_or_fn = _ingest_pack_or
        self._decision_fn = _decision
        self._popcount_fn = _popcount
        self._pack_fn = _pack
        self._pack_or_fn = _pack_or
        self._update_stream_fn = _update_stream
        self._update_stream32_fn = _update_stream32
        self._admit_selected_fn = _admit_selected
        self._update_fn = _update
        self._update_sparse_fn = _update_sparse
        self._admit_if_new_fn = _admit_if_new
        self._admit_choices_fn = _admit_if_new_choices
        self._or_rows_fn = _or_rows
        self._diff_vs_fn = _diff_vs
        self._admit_fn = _admit
        self._minimize_fn = _minimize
        self._minimize_scan_fn = _minimize_scan
        self._sample_rows_fn = _sample_rows
        self._compact_fn = _compact
        self._sample_fn = _sample
        self._prio_update_fn = _prio_update

        # syz-san: under SYZ_SAN=1 every rebuilt closure set re-arms the
        # shadow checker (attach is idempotent and composes with the
        # dispatch profiler); unarmed this is one falsy branch
        from syzkaller_tpu import san as _san
        if _san.armed():
            _san.attach(self)

    # -- public ops ------------------------------------------------------

    def _fit(self, call_ids, pc_idx, valid):
        call_ids = jnp.asarray(call_ids, jnp.int32)
        pc_idx = jnp.asarray(pc_idx, jnp.int32)
        valid = jnp.asarray(valid, jnp.bool_)
        return call_ids, pc_idx, valid

    def _ts_in(self):
        """(svec, hinc) operands for an instrumented dispatch.  With
        telemetry disabled both are a persistent 1-element dummy (the
        jitted fns keep one signature; the bumps are never traced)."""
        if self.tstats is None:
            return self._ts_dummy, self._ts_dummy
        return self.tstats.vec, self.tstats.take_pending_device()

    def _ts_out(self, svec) -> None:
        if self.tstats is not None:
            self.tstats.commit(svec)

    @_locked
    def telemetry_flush(self, reset: bool = False):
        """One-transfer readback of the device stat vector (int64
        totals), optionally folding into host cumulatives and zeroing
        the device slots; None when telemetry is disabled.  Runs under
        the state lock so a reset cannot race an in-flight dispatch."""
        if self.tstats is None:
            return None
        return self.tstats.flush(reset=reset)

    @_locked
    def update_batch_async(self, call_ids, pc_idx, valid) -> UpdateResult:
        """Dispatch the hot step WITHOUT a host sync: result.has_new is a
        device array the caller fetches later (np.asarray).  The state
        merge is sequenced on-device, so pipelined callers keep exact
        reference semantics while the tunnel round-trip overlaps with
        host work."""
        call_ids, pc_idx, valid = self._fit(call_ids, pc_idx, valid)
        svec, hinc = self._ts_in()
        self.max_cover, new, has_new, bitmaps, svec = self._update_fn(
            self.max_cover, call_ids, pc_idx, valid, svec, hinc)
        self._ts_out(svec)
        return UpdateResult(has_new=has_new, new_bits=new, bitmaps=bitmaps)

    def update_batch(self, call_ids, pc_idx, valid) -> UpdateResult:
        """The hot step: B execs' coverage in, per-exec new-signal verdicts
        out; max-cover merged in place (single fused jit call).
        Keep the batch shape constant across calls — each new shape costs
        an XLA compile (pad with valid=False rows instead)."""
        res = self.update_batch_async(call_ids, pc_idx, valid)
        return UpdateResult(has_new=np.asarray(res.has_new),
                            new_bits=res.new_bits, bitmaps=res.bitmaps)

    @_locked
    def update_batch_sparse(self, call_ids, pc_idx, valid
                            ) -> SparseUpdateResult:
        """The hot step at word-block granularity: gather only the
        blocks this batch touches, diff/merge at the gathered width,
        scatter back — per-step cost scales with the batch's signal
        footprint, not the bitmap width (the 1M-PC gap).  Falls back to
        the dense full-width step when sparse is disabled, the batch
        touches more than max_touched_blocks blocks, or the engine is
        sharded (the block gather would cross the PC-axis shards).
        Verdicts and the merged max cover are bit-identical either way.
        No host sync: has_new is a device array the caller fetches."""
        pc_idx = np.asarray(pc_idx)
        valid = np.asarray(valid)
        blocks = None
        sparse_cfg = bool(self.max_touched_blocks) and self.mesh is None
        if sparse_cfg:
            blocks = touched_blocks(pc_idx, valid, self.npcs,
                                    self.block_words,
                                    self.max_touched_blocks)
        if blocks is None:
            if sparse_cfg and self.tstats is not None:
                # footprint overflowed max_touched_blocks: the dense
                # fallback ran where sparse was configured
                self.tstats.inc("sparse_fallback")
            cs, ps, vs = self._fit(call_ids, pc_idx, valid)
            svec, hinc = self._ts_in()
            self.max_cover, new, has_new, _bm, svec = self._update_fn(
                self.max_cover, cs, ps, vs, svec, hinc)
            self._ts_out(svec)
            return SparseUpdateResult(has_new=has_new, new_bits=new,
                                      blocks=None)
        cs, ps, vs = self._fit(call_ids, pc_idx, valid)
        svec, hinc = self._ts_in()
        self.max_cover, new, has_new, svec = self._update_sparse_fn(
            self.max_cover, cs, ps, vs, jnp.asarray(blocks), svec, hinc)
        self._ts_out(svec)
        return SparseUpdateResult(has_new=has_new, new_bits=new,
                                  blocks=blocks)

    @_locked
    def update_stream(self, call_ids, pc_idx, valid):
        """S×B execs' coverage in ONE device dispatch + ONE transfer each
        way: host-packs (S, B, K) indices+mask into the compact uint16
        wire frame (or the int32 variant beyond 2^16 PCs), scans the S
        update steps on device, returns the (S, B) has-new verdicts as a
        device array (caller fetches).  This is the replay/aggregation
        path: per-dispatch overhead and transfer fixed costs amortize
        over the whole stream."""
        call_ids = np.asarray(call_ids, np.int64)
        pc_idx = np.asarray(pc_idx)
        valid = np.asarray(valid, bool)
        S, B, K = pc_idx.shape
        counts = valid.sum(-1)
        # front-pack valid entries (stable order) so validity rides as a
        # per-row count instead of a K-bool plane
        order = np.argsort(~valid, axis=-1, kind="stable")
        packed = np.take_along_axis(pc_idx, order, axis=-1)
        if self.npcs <= (1 << 16):
            frames = np.empty((S, B, K + 4), np.uint16)
            frames[:, :, :K] = packed.astype(np.uint16)
            frames[:, :, K] = counts.astype(np.uint16)
            frames[:, :, K + 1] = (call_ids & 0xFFFF).astype(np.uint16)
            frames[:, :, K + 2] = (call_ids >> 16).astype(np.uint16)
            frames[:, :, K + 3] = 0
            self.max_cover, has_new = self._update_stream_fn(
                self.max_cover, jnp.asarray(frames))
        else:
            self.max_cover, has_new = self._update_stream32_fn(
                self.max_cover, jnp.asarray(call_ids, jnp.int32),
                jnp.asarray(packed, jnp.int32),
                jnp.asarray(counts, jnp.int32))
        return has_new

    # -- zero-copy slab ingest (ring → device, PcMap translation fused) --

    @staticmethod
    def _mirror_ops(mirror):
        skeys, svals, meta = mirror.operands()
        pm = mirror.pcmap
        return skeys, svals, meta, pm.direct_cap, pm.overflow

    def _slab_fit(self, win, counts, call_ids=None):
        win = jnp.asarray(win)          # (B, K) uint32 ring view
        counts = jnp.asarray(counts, jnp.int32)
        if call_ids is None:
            return win, counts
        return win, counts, jnp.asarray(call_ids, jnp.int32)

    @_locked
    def ingest_update_slabs(self, win, counts, call_ids,
                            mirror) -> IngestResult:
        """The zero-copy hot step: one fused dispatch translates a raw
        slab window (on-device binary search over the PcMap mirror),
        packs, diffs vs max cover and merges — no host packing, no
        host sync (fields are device arrays the caller fetches later).
        Rows flagged in miss_rows carried first-sight PCs: resolve
        them host-side (PcMap keeps first-seen order) and fix up with
        update_batch — DeviceSignal.resolve does exactly that."""
        win, counts, call_ids = self._slab_fit(win, counts, call_ids)
        skeys, svals, meta, dc, ov = self._mirror_ops(mirror)
        svec, hinc = self._ts_in()
        (self.max_cover, new, has_new, miss_rows,
         svec) = self._ingest_update_fn(
            self.max_cover, win, counts, call_ids, skeys, svals, meta,
            svec, dc, ov, hinc)
        self._ts_out(svec)
        return IngestResult(has_new=has_new, new_bits=new,
                            miss_rows=miss_rows)

    @_locked
    def admit_slabs(self, win, counts, call_ids, choice_prev, mirror,
                    with_new_bits: bool = False):
        """admit_batch over a raw slab window: on-device translation
        fused with the admission gate + merge + choice draws.  The
        caller must pre-resolve first-sight keys (mirror.ensure) —
        unresolved misses raise, because silently dropping them would
        change the admitted set."""
        win, counts, call_ids = self._slab_fit(win, counts, call_ids)
        skeys, svals, meta, dc, ov = self._mirror_ops(mirror)
        n_in = int(call_ids.shape[0])
        prev = jnp.asarray(choice_prev, jnp.int32)
        if self.corpus_len + n_in > self.cap:
            # matrix cannot take the whole batch: gate-only verdicts;
            # untiered nothing merges (the serial drop-the-input
            # semantics), tiered the admitted entries take demoted rows
            new, has_new, bm, _idx, miss_rows = self._ingest_diff_fn(
                self.corpus_cover, self.flakes, win, counts, call_ids,
                skeys, svals, meta, dc, ov)
            if bool(np.asarray(miss_rows).any()):
                raise ValueError("admit_slabs: unresolved first-sight "
                                 "keys (call mirror.ensure first)")
            choices = self.sample_next_calls(np.asarray(prev))
            has_new = np.asarray(has_new)
            rows = None
            if self.tiers is not None:
                adm = np.nonzero(has_new)[0]
                if (0 < len(adm) <= self.cap
                        and self.corpus_len + len(adm) > self.cap):
                    got = self.merge_corpus(np.asarray(call_ids)[adm],
                                            np.asarray(bm)[adm])
                    if got is not None:
                        rows = np.asarray(got, np.int64)
            out = (has_new, rows, choices,
                   np.asarray(self._popcount_fn(new)))
            return out if with_new_bits else out[:3]
        svec, hinc = self._ts_in()
        (self.corpus_cover, self.corpus_mat, has_new, nbits, choices,
         miss_rows, svec) = self._ingest_admit_fn(
            self.corpus_cover, self.corpus_mat, self.flakes, win, counts,
            call_ids, jnp.int32(self.corpus_len), self._next_key(),
            self.prios, self.enabled, prev, skeys, svals, meta, svec,
            dc, ov, hinc)
        self._ts_out(svec)
        has_new = np.asarray(has_new)
        if bool(np.asarray(miss_rows).any()):
            raise ValueError("admit_slabs: unresolved first-sight keys "
                             "(call mirror.ensure first)")
        admitted = np.nonzero(has_new)[0]
        rows = np.arange(self.corpus_len, self.corpus_len + len(admitted))
        self.corpus_call[rows] = np.asarray(call_ids)[admitted]
        self.corpus_len += len(admitted)
        choices = np.asarray(choices)
        if with_new_bits:
            return has_new, rows, choices, np.asarray(nbits)
        return has_new, rows, choices

    @_locked
    def fuzz_tick(self, win, counts, call_ids, choice_prev,
                  mirror) -> FuzzTickResult:
        """One whole fuzz tick — signal merge + admission + decision
        draws — in ONE host→device dispatch (the _fuzz_tick closure).
        Semantically it IS ingest_update_slabs followed by admit_slabs
        on the same batch: fused-vs-unfused frontiers are bit-exact
        (presubmit gates this).  Like admit_slabs, first-sight keys
        must be pre-resolved (mirror.ensure) — unresolved misses raise
        AFTER the signal merge (which is miss-tolerant) but before any
        admission bookkeeping is reported.

        Without a tier manager attached, falls back to the unfused
        pair when the corpus matrix cannot take the whole batch (the
        serial drop-the-input semantics), marked fused=False so
        callers/bench can count it.  With tiers attached
        (attach_tiers) the fused dispatch always runs: over-cap admits
        redirect into the eviction kernel's victims in-dispatch and
        the displaced contents demote to the warm store — zero extra
        host crossings, zero recompiles."""
        win, counts, call_ids = self._slab_fit(win, counts, call_ids)
        skeys, svals, meta, dc, ov = self._mirror_ops(mirror)
        n_in = int(call_ids.shape[0])
        prev = jnp.asarray(choice_prev, jnp.int32)
        if self.corpus_len + n_in > self.cap and self.tiers is None:
            svec, hinc = self._ts_in()
            (self.max_cover, sig_new, sig_has, miss_rows,
             svec) = self._ingest_update_fn(
                self.max_cover, win, counts, call_ids, skeys, svals,
                meta, svec, dc, ov, hinc)
            self._ts_out(svec)
            new, has_new, _bm, _idx, miss2 = self._ingest_diff_fn(
                self.corpus_cover, self.flakes, win, counts, call_ids,
                skeys, svals, meta, dc, ov)
            if bool(np.asarray(miss2).any()):
                raise ValueError("fuzz_tick: unresolved first-sight "
                                 "keys (call mirror.ensure first)")
            choices = self.sample_next_calls(np.asarray(prev))
            return FuzzTickResult(
                sig_has_new=sig_has, sig_new_bits=sig_new,
                has_new=np.asarray(has_new), rows=None,
                choices=np.asarray(choices),
                new_bits=np.asarray(self._popcount_fn(new)),
                miss_rows=miss_rows, fused=False)
        tiered = self.tiers is not None
        tick = self._tick
        svec, hinc = self._ts_in()
        (self.max_cover, self.corpus_cover, self.corpus_mat,
         self.corpus_seen, sig_has, sig_new, has_new, nbits, choices,
         miss_rows, victims, evicted, _n_ev,
         svec) = self._fuzz_tick_fn(
            self.max_cover, self.corpus_cover, self.corpus_mat,
            self.flakes, win, counts, call_ids,
            jnp.int32(self.corpus_len), self._next_key(), self.prios,
            self.enabled, prev, skeys, svals, meta, svec, dc, ov,
            self.corpus_seen, jnp.int32(tick), tiered, hinc)
        self._ts_out(svec)
        self._tick = tick + 1
        has_new = np.asarray(has_new)
        if bool(np.asarray(miss_rows).any()):
            raise ValueError("fuzz_tick: unresolved first-sight keys "
                             "(call mirror.ensure first)")
        admitted = np.nonzero(has_new)[0]
        n_adm = len(admitted)
        free = self.cap - self.corpus_len
        n_over = 0
        if n_adm <= free:
            rows = np.arange(self.corpus_len, self.corpus_len + n_adm)
            self.corpus_len += n_adm
        else:
            # over-cap admits were redirected in-dispatch into the
            # eviction kernel's victims, in admission order; demote
            # the displaced contents before rebinding their slots
            n_over = n_adm - free
            victims_np = np.asarray(victims, np.int64)[:n_over]
            rows = np.empty((n_adm,), np.int64)
            rows[:free] = np.arange(self.corpus_len, self.cap)
            rows[free:] = victims_np
            self.tiers.on_evicted(
                victims_np, np.asarray(evicted)[:n_over],
                self.corpus_call[victims_np].copy(),
                np.full((n_over,), tick, np.int64))
            self.corpus_len = self.cap
        self.corpus_call[rows] = np.asarray(call_ids)[admitted]
        return FuzzTickResult(
            sig_has_new=sig_has, sig_new_bits=sig_new, has_new=has_new,
            rows=rows, choices=np.asarray(choices),
            new_bits=np.asarray(nbits), miss_rows=miss_rows,
            n_evicted=n_over)

    def triage_diff_slabs(self, win, counts, call_ids, mirror):
        """Slab-path triage gate: translate + diff vs corpus cover
        minus flakes, dispatch under the state lock, sync outside.
        Returns (has_new, new bitmaps, exec bitmaps, per-PC index rows,
        miss_rows)."""
        win, counts, call_ids = self._slab_fit(win, counts, call_ids)
        skeys, svals, meta, dc, ov = self._mirror_ops(mirror)
        with self._state_mu:
            new, has_new, bitmaps, idx, miss_rows = self._ingest_diff_fn(
                self.corpus_cover, self.flakes, win, counts, call_ids,
                skeys, svals, meta, dc, ov)
        return np.asarray(has_new), new, bitmaps, idx, miss_rows

    def pack_slabs(self, win, counts, mirror) -> jax.Array:
        """(B, K) slab window → (B, W) device bitmaps via on-device
        translation (no state)."""
        win, counts = self._slab_fit(win, counts)
        skeys, svals, meta, dc, ov = self._mirror_ops(mirror)
        return self._ingest_pack_fn(win, counts, skeys, svals, meta,
                                    dc, ov)

    def pack_or_slabs(self, win, counts, mirror) -> jax.Array:
        """Slab window → one (1, W) OR-folded bitmap (corpus-merge
        rows compose bitwise)."""
        win, counts = self._slab_fit(win, counts)
        skeys, svals, meta, dc, ov = self._mirror_ops(mirror)
        return self._ingest_pack_or_fn(win, counts, skeys, svals, meta,
                                       dc, ov)

    def pack_or_rows(self, pc_idx, valid, rowmask) -> jax.Array:
        """Pack rows and OR-fold the selected ones into one (1, W)
        bitmap (no state)."""
        return self._pack_or_fn(jnp.asarray(pc_idx, jnp.int32),
                                jnp.asarray(valid, jnp.bool_),
                                jnp.asarray(rowmask, jnp.bool_))

    @_locked
    def admit_rows(self, result: UpdateResult, call_ids,
                   rows) -> "np.ndarray | None":
        """Admit selected exec rows of an update_batch result into the
        corpus (cover + signal matrix) in one fused fixed-shape jit call.
        Returns assigned corpus indices, or None if the corpus is full."""
        B = int(result.bitmaps.shape[0])
        rows = np.asarray(rows, np.int32)
        n = len(rows)
        if n == 0:
            return np.zeros((0,), np.int64)
        if self.corpus_len + n > self.cap:
            return None
        row_idx = np.zeros((B,), np.int32)
        mask = np.zeros((B,), bool)
        row_idx[:n] = rows
        mask[:n] = True
        call_ids = jnp.asarray(call_ids, jnp.int32)
        self.corpus_cover, self.corpus_mat = self._admit_selected_fn(
            self.corpus_cover, self.corpus_mat, result.bitmaps, call_ids,
            jnp.asarray(row_idx), jnp.asarray(mask),
            jnp.int32(self.corpus_len))
        idx = np.arange(self.corpus_len, self.corpus_len + n)
        self.corpus_call[idx] = np.asarray(call_ids)[rows]
        self.corpus_len += n
        return idx

    def pack_batch(self, pc_idx, valid) -> jax.Array:
        """(B, K) indices + mask → (B, W) device bitmaps (no state)."""
        return self._pack_fn(jnp.asarray(pc_idx, jnp.int32),
                             jnp.asarray(valid, jnp.bool_))

    @_locked
    def admit_if_new(self, call_ids, pc_idx, valid,
                     with_new_bits: bool = False):
        """Admission gate + corpus merge in one fused dispatch: per-entry
        new-vs-(corpus cover ∪ flakes) verdicts; entries with new signal
        merge into corpus cover and append matrix rows.  Returns
        (has_new, assigned row indices aligned to the admitted entries
        in submission order) — rows is None when the matrix is full, in
        which case NOTHING merges (manager drop-the-input semantics)
        UNLESS a tier manager is attached: then the admitted entries
        take demoted rows (contents-only swap) and rows comes back.
        The capacity check is conservative — the whole batch must fit,
        since the admitted count is only known after the dispatch.
        With with_new_bits=True a third element is returned: (B,) int32
        per-input new-bit counts (submission order) — the frontier
        productivity signal behind syz_new_cov_per_1k_exec."""
        has_new, rows, _ch, nbits = self._admit_locked(
            call_ids, pc_idx, valid, None)
        if with_new_bits:
            return has_new, rows, nbits
        return has_new, rows

    @_locked
    def admit_batch(self, call_ids, pc_idx, valid, choice_prev,
                    with_new_bits: bool = False):
        """admit_if_new fused with a batch of ChoiceTable draws in the
        SAME device dispatch (the coalescer's step): returns (has_new,
        rows, choices) where choices is (len(choice_prev),) next-call
        ids drawn from the priority matrix; with_new_bits appends the
        (B,) per-input new-bit counts."""
        has_new, rows, choices, nbits = self._admit_locked(
            call_ids, pc_idx, valid, np.asarray(choice_prev, np.int32))
        if with_new_bits:
            return has_new, rows, choices, nbits
        return has_new, rows, choices

    def _admit_locked(self, call_ids, pc_idx, valid, choice_prev):
        call_ids, pc_idx, valid = self._fit(call_ids, pc_idx, valid)
        n_in = int(call_ids.shape[0])
        if self.corpus_len + n_in > self.cap:
            new, has_new, bm = self._diff_vs_fn(
                self.corpus_cover, call_ids, pc_idx, valid, self.flakes)
            choices = (self.sample_next_calls(choice_prev)
                       if choice_prev is not None else None)
            has_new = np.asarray(has_new)
            rows = None
            if self.tiers is not None:
                # tiered: admitted entries take demoted rows instead of
                # dropping (merge_corpus swaps through the pow2-padded
                # swap_rows dispatch — no new signatures).  The guard
                # keeps the subset on merge_corpus's swap branch; a
                # subset that still fits free rows drops as before
                # (transient: a saturated matrix never has free rows)
                adm = np.nonzero(has_new)[0]
                if (0 < len(adm) <= self.cap
                        and self.corpus_len + len(adm) > self.cap):
                    got = self.merge_corpus(np.asarray(call_ids)[adm],
                                            np.asarray(bm)[adm])
                    if got is not None:
                        rows = np.asarray(got, np.int64)
            return (has_new, rows, choices,
                    np.asarray(self._popcount_fn(new)))
        svec, hinc = self._ts_in()
        if choice_prev is None:
            (self.corpus_cover, self.corpus_mat, has_new, nbits,
             svec) = self._admit_if_new_fn(
                self.corpus_cover, self.corpus_mat, self.flakes,
                call_ids, pc_idx, valid, jnp.int32(self.corpus_len),
                svec, hinc)
            choices = None
        else:
            (self.corpus_cover, self.corpus_mat, has_new, nbits,
             choices, svec) = self._admit_choices_fn(
                self.corpus_cover, self.corpus_mat, self.flakes,
                call_ids, pc_idx, valid, jnp.int32(self.corpus_len),
                self._next_key(), self.prios, self.enabled,
                jnp.asarray(choice_prev, jnp.int32), svec, hinc)
            choices = np.asarray(choices)
        self._ts_out(svec)
        has_new = np.asarray(has_new)
        admitted = np.nonzero(has_new)[0]
        rows = np.arange(self.corpus_len, self.corpus_len + len(admitted))
        self.corpus_call[rows] = np.asarray(call_ids)[admitted]
        self.corpus_len += len(admitted)
        return has_new, rows, choices, np.asarray(nbits)

    def triage_diff(self, call_ids, pc_idx, valid):
        """Diff vs corpus cover minus flakes (ref triageInput
        fuzzer.go:384-386); no state mutation.  The dispatch runs under
        the state lock; the host sync happens OUTSIDE it, so a slow
        tunnel round-trip never serializes concurrent engine ops
        (retired syz-vet device-sync-under-lock P1)."""
        call_ids, pc_idx, valid = self._fit(call_ids, pc_idx, valid)
        with self._state_mu:
            new, has_new, bitmaps = self._diff_vs_fn(
                self.corpus_cover, call_ids, pc_idx, valid, self.flakes)
        return np.asarray(has_new), new, bitmaps

    @_locked
    def add_flakes(self, call_ids, bitmaps) -> None:
        call_ids = jnp.asarray(call_ids, jnp.int32)
        self.flakes = self._or_rows_fn(self.flakes, call_ids, bitmaps)

    @_locked
    def merge_corpus(self, call_ids, bitmaps,
                     cover_only_when_full: bool = False
                     ) -> "np.ndarray | None":
        """Admit execs into corpus cover + the corpus signal matrix.
        Returns indices assigned.  When the matrix is full: with a
        tier manager attached the lowest-retention rows demote to the
        warm store and the batch takes their slots (contents-only swap
        — never a recompile); otherwise with cover_only_when_full the
        cover bitmap still merges (callers that keep the program
        anyway need the gate to stay truthful) and None is returned,
        else nothing merges, so the coverage stays re-discoverable
        later (manager drop-the-input semantics)."""
        n = int(bitmaps.shape[0])
        if self.corpus_len + n > self.cap:
            if self.tiers is not None and n <= self.cap:
                free = self.cap - self.corpus_len
                n_over = n - free
                vict = np.empty((n,), np.int64)
                vict[:free] = np.arange(self.corpus_len, self.cap)
                order = np.argsort(self.evict_scores(),
                                   kind="stable")[::-1]
                vict[free:] = order[:n_over]
                old_calls = self.corpus_call[vict[free:]].copy()
                old_rows = self.swap_rows(vict, np.asarray(bitmaps),
                                          np.asarray(call_ids))
                self.tiers.on_evicted(
                    vict[free:], old_rows[free:], old_calls,
                    np.full((n_over,), self._tick, np.int64))
                self.corpus_len = self.cap
                return vict
            if cover_only_when_full:
                call_ids = jnp.asarray(call_ids, jnp.int32)
                self.corpus_cover = self._or_rows_fn(
                    self.corpus_cover, call_ids, bitmaps)
            return None
        call_ids = jnp.asarray(call_ids, jnp.int32)
        self.corpus_cover = self._or_rows_fn(self.corpus_cover, call_ids, bitmaps)
        mask = jnp.ones((n,), jnp.bool_)
        self.corpus_mat = self._admit_fn(self.corpus_mat, bitmaps, mask,
                                         jnp.int32(self.corpus_len))
        idx = np.arange(self.corpus_len, self.corpus_len + n)
        self.corpus_call[idx] = np.asarray(call_ids)
        self.corpus_len += n
        return idx

    # -- tiered corpus hierarchy (corpus/tiers.py) ------------------------

    def attach_tiers(self, tiers) -> None:
        """Attach a TierManager: admission past corpus_cap now demotes
        the lowest-retention rows warm instead of falling back unfused
        (fuzz_tick) or dropping (merge_corpus).  cap ≥ 2·batch keeps
        the fused redirect collision-free: a full batch of over-cap
        admits still finds its victims among live rows below the
        append window."""
        if self.cap < 2 * self.batch:
            raise ValueError(
                f"attach_tiers: corpus_cap {self.cap} < 2*batch "
                f"{2 * self.batch} cannot guarantee collision-free "
                "in-dispatch eviction")
        self.tiers = tiers
        tiers.bind(self)

    @property
    def tick(self) -> int:
        """Monotonic fused-tick counter — the recency clock the
        eviction score decays against."""
        return self._tick

    def evict_scores(self) -> np.ndarray:
        """(cap,) per-row eviction scores (one dispatch of the
        registered evict_score kernel; -1 marks dead slots).  Higher =
        evict first."""
        with self._state_mu:
            dev = self._evict_scores_fn(
                self.corpus_mat, self.corpus_seen,
                jnp.int32(self.corpus_len), jnp.int32(self._tick))
        return np.asarray(dev)

    @_locked
    def swap_rows(self, rows, bitmaps, call_ids) -> np.ndarray:
        """Replace corpus rows' CONTENTS in place (the DeviceKeyMirror
        contents-only growth pattern): the tier swap primitive.  Pads
        to a pow2 bucket so any batch size reuses one dispatch
        signature; merges the incoming rows into corpus cover; bumps
        the rows' recency to the current tick.  Returns the displaced
        (n, W) row contents (the demotion payload)."""
        rows = np.asarray(rows, np.int64)
        n = len(rows)
        if n == 0:
            return np.zeros((0, self.W), np.uint32)
        p2 = pow2_bucket(n, 8, max(8, self.cap))
        ridx = np.full((p2,), self.cap, np.int64)
        ridx[:n] = rows
        bm = np.zeros((p2, self.W), np.uint32)
        bm[:n] = np.asarray(bitmaps, np.uint32)
        cid = np.zeros((p2,), np.int32)
        cid[:n] = np.asarray(call_ids, np.int32)
        (self.corpus_cover, self.corpus_mat, self.corpus_seen,
         old) = self._swap_rows_fn(
            self.corpus_cover, self.corpus_mat, self.corpus_seen,
            jnp.asarray(ridx, jnp.int32), jnp.asarray(cid),
            jnp.asarray(bm), jnp.int32(self._tick))
        self.corpus_call[rows] = cid[:n]
        self.corpus_len = max(self.corpus_len, int(rows.max()) + 1)
        return np.asarray(old)[:n].copy()

    # above this row count the exact greedy's per-pick argmax passes over
    # the whole (C, W) matrix dominate; switch to the single-scan cover
    MINIMIZE_SCAN_THRESHOLD = 4096

    @_locked
    def minimize_corpus(self) -> np.ndarray:
        """(cap,) keep mask over the admitted corpus rows."""
        active = np.zeros((self.cap,), bool)
        active[: self.corpus_len] = True
        fn = (self._minimize_scan_fn
              if self.corpus_len > self.MINIMIZE_SCAN_THRESHOLD
              else self._minimize_fn)
        keep = fn(self.corpus_mat, jnp.asarray(active))
        return np.asarray(keep)

    def sample_corpus_rows(self, n: int) -> np.ndarray:
        """Batched weighted draw of corpus rows (which programs to
        mutate): categorical over per-row signal popcounts — the device
        analog of corpus[rnd] picks, biased toward signal-rich inputs."""
        if self.corpus_len == 0:
            return np.zeros((0,), np.int64)
        with self._state_mu:
            weights = self._popcount_fn(self.corpus_mat)
        rows = np.asarray(self._sample_rows_fn(self._next_key(), weights, n))
        return np.clip(rows, 0, max(self.corpus_len - 1, 0))

    @_locked
    def compact_corpus(self, keep_mask: np.ndarray) -> dict[int, int]:
        """Drop corpus rows not in keep_mask, compacting the signal matrix
        and rebuilding corpus cover from the survivors — this is what
        actually frees admission capacity after a minimize pass.
        Returns the old-row → new-row mapping."""
        keep_mask = np.asarray(keep_mask, bool).copy()
        keep_mask[self.corpus_len:] = False
        old_rows = np.nonzero(keep_mask)[0]
        mapping = {int(o): i for i, o in enumerate(old_rows)}
        n = len(old_rows)
        new_mat, new_cover = self._compact_fn(
            self.corpus_mat, jnp.asarray(keep_mask),
            jnp.asarray(self.corpus_call))
        self.corpus_mat = new_mat
        self.corpus_cover = new_cover
        new_call = np.zeros_like(self.corpus_call)
        new_call[:n] = self.corpus_call[old_rows]
        self.corpus_call = new_call
        seen = np.asarray(self.corpus_seen)
        new_seen = np.zeros_like(seen)
        new_seen[:n] = seen[old_rows]
        self.corpus_seen = self.put_replicated(new_seen)
        self.corpus_len = n
        if self.tiers is not None:
            self.tiers.on_compacted(mapping)
        return mapping

    def set_priorities(self, static_prios: np.ndarray,
                       call_matrix: "np.ndarray | None" = None) -> None:
        sp = jnp.asarray(static_prios, jnp.float32)
        if call_matrix is not None:
            self.prios = self._prio_update_fn(sp, jnp.asarray(call_matrix))
        else:
            self.prios = sp

    def set_enabled(self, enabled_ids) -> None:
        m = np.zeros((self.ncalls,), bool)
        m[np.asarray(list(enabled_ids), int)] = True
        self.enabled = jnp.asarray(m)

    def _next_key(self):
        # proc threads share the engine: split under a lock or two threads
        # get identical PRNG streams
        with self._key_mu:
            self.key, sub = jax.random.split(self.key)
        return sub

    def sample_next_calls(self, prev_call_ids,
                          overlay: "DeviceOverlay | None" = None
                          ) -> np.ndarray:
        """One device call → a whole batch of ChoiceTable decisions,
        optionally steered by a campaign overlay (fixed-shape operands;
        the flat path passes the cached neutral overlay)."""
        sub = self._next_key()
        prev = jnp.asarray(prev_call_ids, jnp.int32)
        ov = overlay if overlay is not None else self._ov_neutral
        return np.asarray(self._sample_fn(sub, self.prios, prev,
                                          self.enabled, ov.boost,
                                          ov.enabled))

    def make_overlay(self, name: str, boost, enabled_ids) -> DeviceOverlay:
        """Compile a campaign overlay into cached device operands:
        (C,) boost multipliers and the (C,) enabled restriction.  Built
        once per campaign and reused — a warm swap moves two small
        replicated buffers and compiles nothing."""
        b = np.asarray(boost, np.float32)
        if b.shape != (self.ncalls,):
            raise ValueError(f"boost shape {b.shape} != ({self.ncalls},)")
        m = np.zeros((self.ncalls,), bool)
        m[np.asarray(list(enabled_ids), int)] = True
        return DeviceOverlay(name=name,
                             boost=self.put_replicated(b),
                             enabled=self.put_replicated(m))

    def frontier_view(self, tag: str) -> SparseView:
        """The per-campaign word-block-sparse frontier view over this
        engine's shared bitmap (created on first use).  Callers absorb
        update results into it OUTSIDE the engine lock."""
        bw = self.block_words if self.W % self.block_words == 0 else 1
        with self._frontier_mu:
            v = self._frontiers.get(tag)
            if v is None:
                v = self._frontiers[tag] = SparseView(
                    tag, self.ncalls, self.W, bw)
            return v

    def frontier_views(self) -> "dict[str, SparseView]":
        with self._frontier_mu:
            return dict(self._frontiers)

    def put_replicated(self, arr) -> jax.Array:
        """Place a small dispatch operand on the engine's device(s)
        (replicated under a mesh) so callers can cache it and
        steady-state dispatches move zero host operands in."""
        a = jnp.asarray(arr)
        if self.mesh is not None:
            a = jax.device_put(a, NamedSharding(self.mesh, P()))
        return a

    def put_row_sharded(self, arr) -> jax.Array:
        """Place a (R, ...) table operand with its ROW axis sharded over
        the mesh's 'pc' axis — the synth corpus rows ride the SAME
        device set as the bitmap (the PR 12 fold-in:
        `NamedSharding(P("pc", None))` for (R, L) row tables, template
        bank replicated via put_replicated).  Falls back to replication
        when unmeshed or when the row count doesn't divide the mesh
        (a resharded gather would silently serialize)."""
        a = jnp.asarray(arr)
        if self.mesh is None or a.ndim == 0 \
                or a.shape[0] % self.mesh.devices.size:
            return self.put_replicated(a)
        spec = P(*(("pc",) + (None,) * (a.ndim - 1)))
        return jax.device_put(a, NamedSharding(self.mesh, spec))

    @_locked
    def decision_block(self, hot_prev: jax.Array, per_row: int,
                       n_rows: int, n_entropy: int,
                       overlay: "DeviceOverlay | None" = None
                       ) -> DecisionBlock:
        """Dispatch ONE decision-stream megakernel step (async — the
        returned block's fields are device arrays the caller fetches
        later).  `hot_prev` must be a device-cached int32 composition
        (put_replicated); per_row/n_rows/n_entropy are static dispatch
        shapes the caller keeps in a pow2-bucketed closed set.
        `overlay` steers the whole block at one campaign's subsystem
        (fixed-shape operands — the flat path passes the cached
        neutral overlay, so campaign swaps never recompile)."""
        svec, hinc = self._ts_in()
        ov = overlay if overlay is not None else self._ov_neutral
        if self._ds_key is None:
            self._ds_key = self._next_key()
        (self._ds_key, base, hot, crows, ent, svec) = self._decision_fn(
            self._ds_key, self.prios, self.enabled, self.corpus_mat,
            hot_prev, ov.boost, ov.enabled, svec, hinc,
            per_row, n_rows, n_entropy)
        self._ts_out(svec)
        return DecisionBlock(base=base, hot=hot, corpus_rows=crows,
                             entropy=ent)

    def random_words(self, n: int) -> np.ndarray:
        return _combine_words(self._random_bits_fn(self._next_key(), n))

    @_locked
    def synth_block(self, tables: dict, B: int, gen_max: int,
                    overlay: "DeviceOverlay | None" = None
                    ) -> SynthBlock:
        """Dispatch ONE program-synthesis megakernel step (async — the
        block's fields are device arrays the caller fetches later).
        `tables` is the fuzzer.synth.DeviceSynth operand dict: fixed-
        capacity device arrays whose CONTENTS grow (the DeviceKeyMirror
        pattern), so warm dispatches never recompile.  B/gen_max are
        static dispatch shapes the caller keeps in a small closed set.
        The PRNG key is donated (its own chain, like the decision
        stream's), and the synth stat slots are bumped in-dispatch."""
        svec, hinc = self._ts_in()
        ov = overlay if overlay is not None else self._ov_neutral
        if self._synth_key is None:
            self._synth_key = self._next_key()
        t = tables
        (self._synth_key, out32, lens32, op, r1, r2, cut, pos, dele,
         k, cids, ins_cid, slot, mkind, mlo, mhi, nkept,
         svec) = self._synth_fn(
            self._synth_key, self.prios, self.enabled, ov.boost,
            ov.enabled, t["op_weights"], t["rows_lo"], t["rows_hi"],
            t["call_off"], t["ncalls"], t["slot_off"], t["slot_size"],
            t["nslots"], t["call_ids"], t["t_lo"], t["t_hi"],
            t["t_len"], t["call2tmpl"], t["meta"], svec, hinc,
            B, gen_max)
        self._ts_out(svec)
        return SynthBlock(out32=out32, lens32=lens32, op=op, r1=r1,
                          r2=r2, cut=cut, pos=pos, dele=dele, k=k,
                          gen_cids=cids, ins_cid=ins_cid, slot=slot,
                          mut_kind=mkind, mut_lo=mlo, mut_hi=mhi,
                          n_entries=nkept)

    # -- introspection ---------------------------------------------------

    def cover_counts(self) -> np.ndarray:
        """(ncalls,) corpus-covered-PC counts (for stats/UI).  Dispatch
        under the state lock, host sync outside it (retired syz-vet
        device-sync-under-lock P1 — stats scrapes no longer stall the
        admission plane for a tunnel round-trip)."""
        with self._state_mu:
            dev = self._popcount_fn(self.corpus_cover)
        return np.asarray(dev)

    def max_cover_counts(self) -> np.ndarray:
        """(ncalls,) ever-seen-PC counts (max cover, for the /cover UI);
        same dispatch-locked/sync-unlocked split as cover_counts."""
        with self._state_mu:
            dev = self._popcount_fn(self.max_cover)
        return np.asarray(dev)

    @_locked
    def covered_indices(self, corpus: bool = True) -> np.ndarray:
        """Sorted bitmap indices covered by ANY call — the input to the
        line-coverage report (union over the call axis).  Defaults to
        corpus cover: that is the state the manager's admission path
        maintains (max cover is the fuzzer-side fast gate)."""
        mat = self.corpus_cover if corpus else self.max_cover
        union = np.bitwise_or.reduce(np.asarray(mat), axis=0)
        bits = np.unpackbits(union.view(np.uint8), bitorder="little")
        return np.nonzero(bits)[0].astype(np.int64)

    @_locked
    def cover_pcs(self, call_id: int, corpus: bool = True) -> np.ndarray:
        """Unpack one call's cover bitmap to sorted PC indices."""
        mat = self.corpus_cover if corpus else self.max_cover
        row = np.asarray(mat[call_id])
        bits = np.unpackbits(row.view(np.uint8), bitorder="little")
        return np.nonzero(bits)[0].astype(np.uint32)

    def max_cover_pcs(self, call_id: int) -> np.ndarray:
        return self.cover_pcs(call_id, corpus=False)

    # -- state migration (checkpoint / backend failover) -----------------

    @_locked
    def export_state(self) -> dict:
        """Host-side copy of every piece of engine state another engine
        (or a snapshot) needs to continue bit-exactly: the coverage
        bitmaps, the admitted corpus matrix rows, and the priority/
        choice-table operands.  Runs under the state lock so the copy
        is a consistent point-in-time cut; the arrays are plain numpy
        (no device references escape)."""
        n = self.corpus_len
        return {
            "npcs": self.npcs, "ncalls": self.ncalls, "W": self.W,
            "corpus_len": n,
            "max_cover": np.asarray(self.max_cover),
            "corpus_cover": np.asarray(self.corpus_cover),
            "flakes": np.asarray(self.flakes),
            # full fetch + HOST slice: a device-side [:n] slice would
            # compile a new kernel per corpus length (one per
            # snapshot/failover — a slow retrace treadmill)
            "corpus_mat": np.asarray(self.corpus_mat)[:n].copy(),
            "corpus_call": self.corpus_call[:n].copy(),
            "corpus_seen": np.asarray(self.corpus_seen)[:n].copy(),
            "tick": self._tick,
            "prios": np.asarray(self.prios),
            "enabled": np.asarray(self.enabled),
        }

    @_locked
    def import_state(self, state: dict) -> None:
        """Install an `export_state` cut into THIS engine (same npcs/
        ncalls config required; corpus must fit this engine's cap).
        Device placement follows this engine's mesh, so a CPU-backed
        failover engine and the original device engine exchange state
        through the same dict."""
        for k in ("npcs", "ncalls", "W"):
            if int(state[k]) != getattr(self, k):
                raise ValueError(
                    f"engine state mismatch: {k}={state[k]} != "
                    f"{getattr(self, k)}")
        n = int(state["corpus_len"])
        if n > self.cap:
            raise ValueError(f"corpus_len {n} > cap {self.cap}")
        row = (NamedSharding(self.mesh, P(None, "pc"))
               if self.mesh is not None else None)
        rep = NamedSharding(self.mesh, P()) if self.mesh is not None else None

        def put(arr, sharding):
            a = jnp.asarray(arr)
            return jax.device_put(a, sharding) if sharding is not None else a

        self.max_cover = put(np.asarray(state["max_cover"], np.uint32), row)
        self.corpus_cover = put(np.asarray(state["corpus_cover"],
                                           np.uint32), row)
        self.flakes = put(np.asarray(state["flakes"], np.uint32), row)
        mat = np.zeros((self.cap, self.W), np.uint32)
        mat[:n] = np.asarray(state["corpus_mat"], np.uint32)
        self.corpus_mat = put(mat, row)
        self.corpus_call = np.zeros((self.cap,), np.int32)
        self.corpus_call[:n] = np.asarray(state["corpus_call"], np.int32)
        # pre-tier snapshots (codec v1) carry no recency state: zeros =
        # maximally old, so restored rows are simply first to demote
        seen = np.zeros((self.cap,), np.int32)
        if "corpus_seen" in state:
            seen[:n] = np.asarray(state["corpus_seen"], np.int32)
        self.corpus_seen = put(seen, rep)
        self._tick = int(state.get("tick", 0))
        self.corpus_len = n
        self.prios = put(np.asarray(state["prios"], np.float32), rep)
        self.enabled = put(np.asarray(state["enabled"], bool), rep)
        # pre-drawn decision state conditioned on the old arrays is
        # stale; the streams rebuild their chains lazily off the main key
        self._ds_key = None
        self._synth_key = None

    def adopt_frontiers(self, views: "dict[str, SparseView]") -> None:
        """Carry per-campaign frontier views across an engine swap: the
        views are host-side objects, so adopting them is a dict update
        — accumulated campaign attribution survives a failover."""
        with self._frontier_mu:
            for tag, v in views.items():
                self._frontiers.setdefault(tag, v)
