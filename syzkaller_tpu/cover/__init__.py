"""Coverage: device-resident bitmap engine + host sorted-set reference."""

from syzkaller_tpu.cover import sets  # noqa: F401
from syzkaller_tpu.cover.engine import (  # noqa: F401
    CoverageEngine, nwords_for, pack_pcs, sample_calls, signal_diff,
)
