"""Coverage: device-resident bitmap engine + host sorted-set reference.

The jax-backed engine lives in syzkaller_tpu.cover.engine and is
imported directly by device-side components (manager, stress, bench);
this package init stays jax-free so guest-side code (the in-VM fuzzer)
can use the numpy sorted-set algebra without pulling in jax.
"""

from syzkaller_tpu.cover import sets  # noqa: F401
