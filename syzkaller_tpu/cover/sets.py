"""Sorted-set coverage algebra (host/CPU reference implementation).

Capability parity with reference cover/cover.go:11-131: a cover is a
sorted unique array of PC identifiers; Canonicalize, Difference,
SymmetricDifference, Union, Intersection are merge-based set ops, and
Minimize is the greedy set cover used for corpus minimization.

This numpy version is (a) the semantic reference the device engine
(syzkaller_tpu/cover/engine.py) is cross-checked against in tests, and
(b) the CPU baseline that bench.py compares device throughput to
(BASELINE.md: "CPU cover.Merge baseline").
"""

from __future__ import annotations

import numpy as np

Cover = np.ndarray  # sorted unique uint32 PCs


def canonicalize(pcs) -> Cover:
    return np.unique(np.asarray(pcs, dtype=np.uint32))


def difference(a: Cover, b: Cover) -> Cover:
    return np.setdiff1d(a, b, assume_unique=True)


def symmetric_difference(a: Cover, b: Cover) -> Cover:
    return np.setxor1d(a, b, assume_unique=True)


def union(a: Cover, b: Cover) -> Cover:
    return np.union1d(a, b)


def intersection(a: Cover, b: Cover) -> Cover:
    return np.intersect1d(a, b, assume_unique=True)


def minimize(covers: "list[Cover]") -> list[int]:
    """Greedy set cover: indices of a subset of `covers` that together
    cover the union (ref cover/cover.go:105-131).  Largest-first greedy:
    repeatedly take the cover contributing the most uncovered PCs."""
    if not covers:
        return []
    total = canonicalize(np.concatenate([c for c in covers]) if covers else [])
    covered = np.zeros(0, dtype=np.uint32)
    chosen: list[int] = []
    remaining = set(range(len(covers)))
    while len(covered) < len(total) and remaining:
        best, best_gain = -1, 0
        for i in remaining:
            gain = len(difference(covers[i], covered))
            if gain > best_gain:
                best, best_gain = i, gain
        if best < 0:
            break
        chosen.append(best)
        remaining.discard(best)
        covered = union(covered, covers[best])
    return sorted(chosen)


def restore_pc(pc32: int, base: int = 0xFFFFFFFF00000000) -> int:
    """32→64-bit PC widening (ref cover/cover.go:23)."""
    return base | pc32
