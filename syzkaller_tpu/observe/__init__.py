"""Fleet observatory: the production observability tier above the
per-manager telemetry plane (README "Fleet observatory").

Three parts:

- tsdb:     a device-resident time-series ring store in the
            DeviceKeyMirror fixed-capacity style — an (S, W) window
            matrix fed from the DeviceStats slot vector (bumped inside
            the engine's already-fused dispatches), rolled up by ONE
            fused kernel into 1s/15s/5min retention tiers, scraped in
            one transfer, bit-exact against a numpy host shadow, and
            persisted through the crash-only snapshot path.
- profile:  named-dispatch profiling over the engine's jitted closures
            (per-dispatch wall-latency log2 histograms + per-site
            recompile attribution) and the syz_slo_* burn-rate gauges
            the fleet autopilot consumes.
- console:  the live fleet console aggregating /metrics + /telemetry +
            /healthz (+ /tsdb) from N managers and the hub through the
            HttpSource seam, with cross-host trace stitching rendered
            as waterfalls (tools/console.py is the CLI).
"""

from syzkaller_tpu.observe.console import FleetConsole, HostClient
from syzkaller_tpu.observe.profile import (
    DISPATCH_ATTRS, DispatchProfiler, register_slo_gauges, subkernel)
from syzkaller_tpu.observe.tsdb import (
    TIERS, DeviceTsdb, HostTsdb, window_width)

__all__ = [
    "DISPATCH_ATTRS", "DeviceTsdb", "DispatchProfiler", "FleetConsole",
    "HostClient", "HostTsdb", "TIERS", "register_slo_gauges",
    "subkernel", "window_width",
]
