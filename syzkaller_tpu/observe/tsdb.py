"""Device-resident time-series ring store over the DeviceStats vector.

The telemetry plane's gap before this module: every /metrics scrape was
a point-in-time snapshot — coverage-growth HISTORY (what the bandit
scheduler trains on, what a console sparkline renders) evaporated
between scrapes.  This store retains it device-side in the
DeviceKeyMirror fixed-capacity style: one (S, W) int32 window matrix
whose S axis is the DeviceStats slot layout and whose W axis is three
concatenated retention tiers,

    tier 0:  64 columns x  1s   (the last ~minute, full resolution)
    tier 1:  60 columns x 15s   (the last ~15 minutes)
    tier 2:  48 columns x 300s  (the last ~4 hours)

The hot path adds NOTHING: counters are bumped inside the engine's
already-fused dispatches (telemetry/device.py contract), and this
module only READS that vector — one fused rollup kernel per sampling
interval (1 Hz from the manager run loop), never per exec.  The kernel
takes the tick's column indices and tier-fold flags as traced int32/bool
operands, so a warmed store never recompiles (CompileCounter-pinned in
tests).  Scrape is ONE device->host transfer of the whole matrix,
cached ~1s so gauge closures and /tsdb don't multiply transfers.

Delta rule (the part the host shadow must reproduce bit-exactly):

    delta = where(vec >= last, vec - last, vec);  last' = vec

The device vector is monotonic between flushes and drops to zero on
`flush(reset=True)` (int32 roll-over protection): the `vec < last` arm
re-bases on the fresh vector.  Counts folded into host cumulatives by
the reset itself are clipped from at most one sampling interval — the
series is a rate view, the registry keeps exact totals.

Snapshot/restore: `export_state`/`import_state` ride the PR 9
checkpoint arrays, so a crash-only restart resumes the rings instead of
starting a blank history.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from syzkaller_tpu.telemetry.device import SCALAR_SLOTS, _nslots

# (seconds per column, columns) per retention tier; tier 0 must be the
# base sampling cadence and later tiers exact multiples of it
TIERS = ((1, 64), (15, 60), (300, 48))

_W0, _W1, _W2 = (w for _s, w in TIERS)
_OFF1 = _W0
_OFF2 = _W0 + _W1
_SLOT = {key: i for i, (key, _n, _l) in enumerate(SCALAR_SLOTS)}


def window_width() -> int:
    """Total W of the (S, W) ring matrix."""
    return sum(w for _s, w in TIERS)


def _tick_operands(t: int):
    """Column indices + fold flags for sample tick `t`, as numpy
    scalars (traced jit operands — Python ints would also trace, but a
    consistent dtype avoids weak-type retraces)."""
    return (np.int32(t % _W0),
            np.int32(_OFF1 + (t // 15) % _W1),
            np.int32(_OFF2 + (t // 300) % _W2),
            np.bool_(t % 15 == 14),
            np.bool_(t % 300 == 299))


def _build_kernel(nvec: int):
    """The fused rollup: tier-0 delta write + 15s/300s accumulator
    folds in one dispatch.  Fold writes are computed unconditionally
    and selected by the traced flags (fixed shapes, zero warm
    recompiles); the discarded write targets a live column's FUTURE
    slot, so selecting it away is exact, not approximate."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def step(ring, last, acc15, acc300, c0, c1, c2, f15, f300, *vecs):
        vec = vecs[0]
        for v in vecs[1:]:
            vec = vec + v
        delta = jnp.where(vec >= last, vec - last, vec)
        ring = lax.dynamic_update_slice(ring, delta[:, None],
                                        (jnp.int32(0), c0))
        acc15 = acc15 + delta
        acc300 = acc300 + delta
        ring = jnp.where(
            f15, lax.dynamic_update_slice(ring, acc15[:, None],
                                          (jnp.int32(0), c1)), ring)
        acc15 = jnp.where(f15, jnp.zeros_like(acc15), acc15)
        ring = jnp.where(
            f300, lax.dynamic_update_slice(ring, acc300[:, None],
                                           (jnp.int32(0), c2)), ring)
        acc300 = jnp.where(f300, jnp.zeros_like(acc300), acc300)
        return ring, vec, acc15, acc300

    return jax.jit(step)


class HostTsdb:
    """Pure-numpy shadow of the device store: same (S, W) layout, same
    delta rule, same fold schedule.  Tests drive both with identical
    vector snapshots and compare rings bit-exactly; it is also the
    store a telemetry-off component could run host-side."""

    def __init__(self, nslots: "int | None" = None):
        self.nslots = int(nslots or _nslots())
        self.ring = np.zeros((self.nslots, window_width()), np.int32)
        self.last = np.zeros((self.nslots,), np.int32)
        self.acc15 = np.zeros((self.nslots,), np.int32)
        self.acc300 = np.zeros((self.nslots,), np.int32)
        self.tick = 0

    def sample(self, vec) -> None:
        vec = np.asarray(vec, np.int32)
        delta = np.where(vec >= self.last, vec - self.last, vec)
        t = self.tick
        self.ring[:, t % _W0] = delta
        self.acc15 += delta
        self.acc300 += delta
        if t % 15 == 14:
            self.ring[:, _OFF1 + (t // 15) % _W1] = self.acc15
            self.acc15[:] = 0
        if t % 300 == 299:
            self.ring[:, _OFF2 + (t // 300) % _W2] = self.acc300
            self.acc300[:] = 0
        self.last = vec.copy()
        self.tick = t + 1


class DeviceTsdb:
    """The device-resident store over one or more DeviceStats vectors
    (engine + triage; the kernel sums them — /metrics merges the same
    way, so the series matches the exposition totals' rates)."""

    def __init__(self, stats, interval: float = 1.0, put=None):
        if not isinstance(stats, (list, tuple)):
            stats = [stats]
        self.sources = [s for s in stats if s is not None]
        self.interval = float(interval)
        self.nslots = (self.sources[0].nslots if self.sources
                       else _nslots())
        self._put = put
        self._mu = threading.Lock()
        self._fn = None
        self.tick = 0
        self.samples = 0            # successful rollup dispatches
        self.errors = 0             # sampling failures (failover edge)
        self.last_wall = 0.0
        self._last_mono: "float | None" = None
        self._scrape: "np.ndarray | None" = None
        self.ring = self._place(
            np.zeros((self.nslots, window_width()), np.int32))
        self.last = self._place(np.zeros((self.nslots,), np.int32))
        self.acc15 = self._place(np.zeros((self.nslots,), np.int32))
        self.acc300 = self._place(np.zeros((self.nslots,), np.int32))

    def _place(self, arr: np.ndarray):
        if self._put is not None:
            return self._put(arr)
        import jax.numpy as jnp
        return jnp.asarray(arr)

    # -- sampling ----------------------------------------------------------

    def sample_now(self) -> None:
        """Advance exactly one tick: ONE fused dispatch reading the
        live stat vectors (no host transfer of the vectors)."""
        with self._mu:
            if self._fn is None:
                self._fn = _build_kernel(max(1, len(self.sources)))
            vecs = [s.vec for s in self.sources]
            if not vecs:
                vecs = [self.last]      # degenerate: flat series
            ops = _tick_operands(self.tick)
            self.ring, self.last, self.acc15, self.acc300 = self._fn(
                self.ring, self.last, self.acc15, self.acc300,
                *ops, *vecs)
            self.tick += 1
            self.samples += 1
            self.last_wall = time.time()
            self._scrape = None

    def maybe_sample(self, now: "float | None" = None) -> bool:
        """Tick-gated sampling for the manager run loop: at most one
        rollup per interval, failure-isolated (a quarantined backend
        mid-failover must not take the run loop down with it)."""
        now = time.monotonic() if now is None else now
        with self._mu:
            if self._last_mono is not None \
                    and now - self._last_mono < self.interval:
                return False
            self._last_mono = now
        try:
            self.sample_now()
            return True
        except Exception:
            with self._mu:
                self.errors += 1
            return False

    # -- scrape + views ----------------------------------------------------

    def scrape(self) -> np.ndarray:
        """The whole (S, W) ring, ONE device->host transfer, cached
        until the next sample so stacked gauge reads don't multiply
        transfers."""
        with self._mu:
            if self._scrape is None:
                self._scrape = np.asarray(self.ring)
            return self._scrape

    def _row(self, key: str) -> np.ndarray:
        return self.scrape()[_SLOT[key]]

    def window(self, key: str, tier: int = 0) -> np.ndarray:
        """One slot's tier window, oldest -> newest, only the columns
        that have actually been written."""
        row = self._row(key)
        t = self.tick
        if tier == 0:
            ticks = range(max(0, t - _W0), t)
            return np.array([row[i % _W0] for i in ticks], np.int64)
        if tier == 1:
            folds = t // 15
            return np.array([row[_OFF1 + f % _W1]
                             for f in range(max(0, folds - _W1), folds)],
                            np.int64)
        folds = t // 300
        return np.array([row[_OFF2 + f % _W2]
                         for f in range(max(0, folds - _W2), folds)],
                        np.int64)

    def window_rate(self, key: str, seconds: float = 15.0) -> float:
        """Mean per-second rate of a slot over the last `seconds` of
        tier-0 history (the SLO burn-rate view)."""
        w = self.window(key, tier=0)
        n = min(len(w), max(1, int(round(seconds / self.interval))))
        if n == 0:
            return 0.0
        return float(w[-n:].sum()) / (n * self.interval)

    def stall_seconds(self, key: str) -> float:
        """Seconds since a slot last moved, scanning fine-to-coarse
        tiers (tier spans are the resolution bound; clamped to the
        store's uptime)."""
        uptime = self.tick * self.interval
        w0 = self.window(key, tier=0)
        nz = np.nonzero(w0)[0]
        if len(nz):
            return min(uptime, (len(w0) - 1 - nz[-1]) * self.interval)
        stall = len(w0) * self.interval
        for tier, span in ((1, 15.0), (2, 300.0)):
            w = self.window(key, tier=tier)
            nz = np.nonzero(w)[0]
            if len(nz):
                return min(uptime, stall + (len(w) - 1 - nz[-1]) * span)
            stall += len(w) * span
        return min(uptime, stall)

    def snapshot_json(self, keys: "list[str] | None" = None) -> dict:
        """JSON body of the manager's /tsdb endpoint: per-tier series
        for the scalar slots (histogram slot rows stay device/scrape-
        only — 24 buckets x 3 tiers of JSON per histogram is console
        noise)."""
        if keys is None:
            keys = [k for k, _n, _l in SCALAR_SLOTS]
        tiers = []
        for tier, (sec, cols) in enumerate(TIERS):
            tiers.append({
                "seconds": sec, "columns": cols,
                "series": {k: [int(x) for x in self.window(k, tier)]
                           for k in keys},
            })
        return {"interval": self.interval, "tick": self.tick,
                "ts": self.last_wall, "samples": self.samples,
                "errors": self.errors, "tiers": tiers}

    # -- checkpoint plane --------------------------------------------------

    def export_state(self) -> "tuple[dict, dict]":
        """(meta, arrays) for the snapshot writer — host-canonical, so
        the restore side re-places on whatever mesh it has."""
        with self._mu:
            arrays = {
                "tsdb_ring": np.asarray(self.ring).astype(np.int32),
                "tsdb_last": np.asarray(self.last).astype(np.int32),
                "tsdb_acc15": np.asarray(self.acc15).astype(np.int32),
                "tsdb_acc300": np.asarray(self.acc300).astype(np.int32),
            }
            meta = {"tick": int(self.tick), "last_wall": self.last_wall,
                    "interval": self.interval}
        return meta, arrays

    def import_state(self, meta: dict, arrays: dict) -> None:
        """Resume rings from a snapshot; a layout-mismatched snapshot
        (slot vector grew since) is skipped — history is an
        observability aid, never worth bricking a restore."""
        ring = np.asarray(arrays.get("tsdb_ring"))
        if ring.shape != (self.nslots, window_width()):
            return
        with self._mu:
            self.ring = self._place(ring.astype(np.int32))
            self.last = self._place(
                np.asarray(arrays["tsdb_last"], np.int32))
            self.acc15 = self._place(
                np.asarray(arrays["tsdb_acc15"], np.int32))
            self.acc300 = self._place(
                np.asarray(arrays["tsdb_acc300"], np.int32))
            self.tick = int(meta.get("tick", 0))
            self.last_wall = float(meta.get("last_wall", 0.0))
            self._scrape = None
