"""The live fleet console: the production UI tier above per-manager
pages (ref syz-manager/html.go stays per-host; this is the roll-up).

Aggregates /metrics + /telemetry + /healthz (+ /tsdb) from N managers
and the hub through the same seam the fleet autopilot scrapes
(autopilot/controller.HttpSource — parse_prometheus_text over a URL),
and renders:

  - per-manager coverage-growth sparklines (tsdb tier-0 window of the
    device admission-gate counter),
  - crash-cluster / repro / VM / autopilot health summaries,
  - hub sync ages + corpus, with SLO flags computed by the SAME code
    the autopilot runs (mesh/fleet.HubWatch + mesh/fleet.slo_flags), so
    a console flag always matches the autopilot's own verdict,
  - cross-host trace lineage: spans whose `links` point at a trace
    recorded on another manager (a program shipped A -> hub -> B) are
    stitched into one waterfall.

Crash-only semantics: when a host stops answering, its panel flips to
host_down and its last-seen series FREEZE (kept from the previous
scrape) — history is never dropped because a host died.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

from syzkaller_tpu.telemetry import expo


class HostClient:
    """One scrape target.  `fetch(url) -> bytes` is injectable so tests
    and the chaos harness drive the console without sockets."""

    def __init__(self, name: str, base_url: str, fetch=None,
                 timeout: float = 5.0):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._fetch = fetch

    def _get(self, path: str) -> bytes:
        url = self.base_url + path
        if self._fetch is not None:
            return self._fetch(url)
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            return resp.read()

    def metrics(self) -> dict:
        return expo.parse_prometheus_text(self._get("/metrics").decode())

    def telemetry(self) -> dict:
        return json.loads(self._get("/telemetry").decode())

    def healthz(self) -> dict:
        # non-200 still carries the health body; urllib raises on it,
        # so read the error payload too
        try:
            return json.loads(self._get("/healthz").decode())
        except urllib.error.HTTPError as e:        # degraded = 503
            return json.loads(e.read().decode())

    def tsdb(self) -> dict:
        try:
            return json.loads(self._get("/tsdb").decode())
        except Exception:
            return {}               # pre-observatory manager


def _metric(sample: dict, name: str, default: float = 0.0) -> float:
    v = sample.get(name)
    return default if v is None else float(v)


class FleetConsole:
    """Scrape-state machine over N managers + one hub."""

    def __init__(self, managers, hub_url: "str | None" = None,
                 sync_age_threshold: float = 300.0,
                 coverage_stall_threshold: float = 300.0,
                 fetch=None, timeout: float = 5.0):
        self.clients = [HostClient(name, url, fetch=fetch,
                                   timeout=timeout)
                        for name, url in managers]
        self.hub_url = hub_url.rstrip("/") if hub_url else None
        self.sync_age_threshold = float(sync_age_threshold)
        self.coverage_stall_threshold = float(coverage_stall_threshold)
        self._hub_watch = None
        if self.hub_url:
            from syzkaller_tpu.autopilot.controller import HttpSource
            from syzkaller_tpu.mesh.fleet import HubWatch
            src = HttpSource(self.hub_url + "/metrics", timeout=timeout)
            if fetch is not None:
                src.sample = lambda u=self.hub_url + "/metrics": \
                    expo.parse_prometheus_text(fetch(u).decode())
            self._hub_watch = HubWatch(
                src, sync_age_threshold=self.sync_age_threshold)
        self._hub_client = (HostClient("hub", self.hub_url, fetch=fetch,
                                       timeout=timeout)
                            if self.hub_url else None)
        # frozen per-host state survives scrape failures
        self._state: "dict[str, dict]" = {}
        self._hub_state: "dict | None" = None

    # -- scraping ----------------------------------------------------------

    def _scrape_host(self, cli: HostClient) -> dict:
        prev = self._state.get(cli.name)
        try:
            sample = cli.metrics()
            telem = cli.telemetry()
            health = cli.healthz()
            tsdb = cli.tsdb()
        except Exception as e:
            if prev is not None:
                # crash-only console: freeze, don't lose
                out = dict(prev)
                out.update(host_down=True, frozen=True, error=str(e))
                return out
            return {"host": cli.name, "url": cli.base_url,
                    "host_down": True, "frozen": False, "error": str(e),
                    "sample": {}, "traces": [], "spark": [],
                    "summary": {}, "slo": {}, "slo_flags": []}
        from syzkaller_tpu.mesh.fleet import slo_flags
        slo = {k.split("{", 1)[0]: float(v) for k, v in sample.items()
               if k.startswith("syz_slo_")}
        spark = []
        for tier in tsdb.get("tiers", []):
            if tier.get("seconds") == 1:
                spark = tier.get("series", {}).get("admit_admitted", [])
        summary = {
            "corpus": int(_metric(sample, "syz_corpus_size")),
            "corpus_rows": int(_metric(sample, "syz_engine_corpus_rows")),
            "exec_rate": round(_metric(sample, "syz_exec_rate"), 2),
            "fuzzers": int(_metric(sample, "syz_fuzzers_connected")),
            "crashes": int(_metric(sample, "syz_crash_total")),
            "crash_clusters": int(_metric(sample, "syz_crash_clusters")),
            "vm_live": int(_metric(sample, "syz_vm_pool_live")),
            "vm_target": int(_metric(sample, "syz_vm_pool_target")),
            "uptime": round(_metric(sample, "syz_uptime_seconds"), 1),
        }
        return {
            "host": cli.name, "url": cli.base_url, "host_down": False,
            "frozen": False, "sample": sample,
            "traces": telem.get("traces", []),
            "health": health, "spark": spark, "summary": summary,
            "slo": slo,
            "slo_flags": slo_flags(
                slo, coverage_stall=self.coverage_stall_threshold,
                sync_stall=self.sync_age_threshold),
            "tsdb_tick": tsdb.get("tick", 0),
            "scraped_at": time.time(),
        }

    def _scrape_hub(self) -> "dict | None":
        if self._hub_client is None:
            return None
        try:
            sample = expo.parse_prometheus_text(
                self._hub_client._get("/metrics").decode())
            health = self._hub_client.healthz()
        except Exception as e:
            out = dict(self._hub_state or {"sample": {}, "health": {}})
            out.update(host_down=True, frozen=self._hub_state is not None,
                       error=str(e), flags=out.get("flags", []))
            return out
        flags = []
        watch = {}
        if self._hub_watch is not None:
            try:
                # the autopilot's OWN verdict function over the same
                # /metrics body — console flags match by construction
                watch = self._hub_watch.check()
                flags = watch.get("flags", [])
            except Exception:
                pass
        ages = {}
        for k, v in sample.items():
            if k.startswith("syz_hub_sync_age_seconds"):
                mgr = "?"
                if "{" in k:
                    mgr = k.split('manager="', 1)[-1].split('"', 1)[0]
                ages[mgr] = round(float(v), 1)
        return {"host": "hub", "url": self.hub_url, "host_down": False,
                "frozen": False, "sample": sample, "health": health,
                "sync_ages": ages, "flags": flags, "watch": watch,
                "corpus": int(_metric(sample, "syz_hub_corpus_size")),
                "managers": int(_metric(sample, "syz_hub_managers"))}

    def scrape(self) -> dict:
        for cli in self.clients:
            self._state[cli.name] = self._scrape_host(cli)
        self._hub_state = self._scrape_hub()
        return self.fleet_json()

    # -- views -------------------------------------------------------------

    def _lineage(self) -> "list[dict]":
        """Stitch cross-host span chains: any trace whose `links` name
        a trace recorded on ANOTHER host becomes one lineage entry
        (program admitted on origin, shipped via the hub, replayed
        here)."""
        by_id: "dict[str, tuple[str, dict]]" = {}
        for host, st in self._state.items():
            for tr in st.get("traces", []):
                tid = tr.get("trace_id")
                if tid:
                    by_id[tid] = (host, tr)
        out = []
        for host, st in self._state.items():
            for tr in st.get("traces", []):
                for link in tr.get("links", []):
                    origin = by_id.get(link)
                    if origin is None or origin[0] == host:
                        continue
                    out.append({
                        "host": host, "trace": tr.get("trace_id"),
                        "origin_host": origin[0], "origin_trace": link,
                        "hops": tr.get("hops", []),
                        "origin_hops": origin[1].get("hops", []),
                    })
        return out

    def fleet_json(self) -> dict:
        flags = []
        for name, st in self._state.items():
            if st.get("host_down"):
                flags.append({"host": name, "issue": "host_down"})
            for f in st.get("slo_flags", []):
                flags.append({"host": name, "issue": f})
        hub = self._hub_state
        if hub:
            for f in hub.get("flags", []):
                f = dict(f)
                f.setdefault("host", "hub")
                flags.append(f)
            if hub.get("host_down"):
                flags.append({"host": "hub", "issue": "host_down"})
        return {
            "ts": time.time(),
            "managers": {n: {k: v for k, v in st.items()
                             if k not in ("sample", "traces")}
                         for n, st in self._state.items()},
            "hub": ({k: v for k, v in hub.items() if k != "sample"}
                    if hub else None),
            "lineage": self._lineage(),
            "flags": flags,
        }

    # -- HTML --------------------------------------------------------------

    def render_html(self) -> str:
        import html as H
        fleet = self.fleet_json()

        def spark_svg(vals, w=180, h=28) -> str:
            vals = [float(v) for v in (vals or [])][-60:]
            if not vals:
                return "<svg width='%d' height='%d'></svg>" % (w, h)
            top = max(max(vals), 1.0)
            n = max(len(vals) - 1, 1)
            pts = " ".join(
                f"{i * w / n:.1f},{h - 2 - (v / top) * (h - 4):.1f}"
                for i, v in enumerate(vals))
            return (f"<svg width='{w}' height='{h}'>"
                    f"<polyline points='{pts}' fill='none' "
                    f"stroke='#2a7' stroke-width='1.5'/></svg>")

        rows = []
        for name, st in sorted(fleet["managers"].items()):
            s = st.get("summary", {})
            state = "HOST_DOWN" if st.get("host_down") else \
                st.get("health", {}).get("status", "?")
            cls = "down" if st.get("host_down") else ""
            frozen = " (frozen series)" if st.get("frozen") else ""
            flags = ", ".join(st.get("slo_flags", [])) or "-"
            rows.append(
                f"<tr class='{cls}'><td><a href='{H.escape(st.get('url', ''))}'>"
                f"{H.escape(name)}</a></td>"
                f"<td>{H.escape(str(state))}{frozen}</td>"
                f"<td>{s.get('corpus', '?')}</td>"
                f"<td>{s.get('exec_rate', '?')}</td>"
                f"<td>{s.get('crash_clusters', '?')}/"
                f"{s.get('crashes', '?')}</td>"
                f"<td>{s.get('vm_live', '?')}/{s.get('vm_target', '?')}</td>"
                f"<td>{spark_svg(st.get('spark'))}</td>"
                f"<td>{H.escape(flags)}</td></tr>")

        hub = fleet.get("hub")
        hub_html = "<p>no hub configured</p>"
        if hub:
            ages = ", ".join(f"{H.escape(k)}: {v}s"
                             for k, v in sorted(
                                 hub.get("sync_ages", {}).items())) or "-"
            hflags = ", ".join(f.get("issue", "?")
                               for f in hub.get("flags", [])) or "-"
            state = "HOST_DOWN" if hub.get("host_down") else \
                hub.get("health", {}).get("status", "?")
            hub_html = (f"<p>hub <b>{H.escape(str(state))}</b> — corpus "
                        f"{hub.get('corpus', '?')}, managers "
                        f"{hub.get('managers', '?')}; sync ages: {ages}; "
                        f"flags: {H.escape(hflags)}</p>")

        waterfalls = []
        for ln in fleet["lineage"][:16]:
            bars = []
            for who, hops in ((ln["origin_host"], ln["origin_hops"]),
                              (ln["host"], ln["hops"])):
                for hop in hops:
                    us = int(hop.get("dur_us", 0))
                    wpx = min(300, max(2, us // 100))
                    bars.append(
                        f"<div class='hop'><span class='who'>"
                        f"{H.escape(str(who))}</span> "
                        f"{H.escape(str(hop.get('name', '?')))} "
                        f"<span class='bar' style='width:{wpx}px'></span> "
                        f"{us}&micro;s</div>")
            waterfalls.append(
                f"<div class='trace'><b>{H.escape(str(ln['origin_trace']))}"
                f"</b> @{H.escape(str(ln['origin_host']))} &rarr; hub "
                f"&rarr; <b>{H.escape(str(ln['trace']))}</b> "
                f"@{H.escape(str(ln['host']))}{''.join(bars)}</div>")

        fleet_flags = ", ".join(
            f"{f.get('host', '?')}:{f.get('issue', '?')}"
            for f in fleet["flags"]) or "none"
        return f"""<!doctype html><html><head><title>fleet console</title>
<style>
body {{ font-family: monospace; margin: 1em; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #999; padding: 2px 8px; text-align: left; }}
tr.down td {{ background: #fdd; }}
.trace {{ border: 1px solid #ccc; margin: 4px 0; padding: 4px; }}
.hop .bar {{ display: inline-block; height: 8px; background: #47a; }}
.hop .who {{ color: #888; }}
</style></head><body>
<h2>fleet console</h2>
<p>flags: {H.escape(fleet_flags)}</p>
{hub_html}
<h3>managers ({len(fleet['managers'])})</h3>
<table><tr><th>manager</th><th>state</th><th>corpus</th>
<th>exec/s</th><th>clusters/crashes</th><th>vms</th>
<th>new cov (60s)</th><th>slo flags</th></tr>
{''.join(rows)}</table>
<h3>cross-host lineage ({len(fleet['lineage'])})</h3>
{''.join(waterfalls) or '<p>no hub-shipped traces yet</p>'}
</body></html>"""
