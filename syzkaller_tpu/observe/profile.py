"""Dispatch-level profiling + the syz_slo_* burn-rate gauges.

The engine's _build() assigns its jitted closures to ~27 well-known
`_*_fn` attributes; `DispatchProfiler.attach` wraps each present one
with a timing shim so every device dispatch gets

  - a per-dispatch wall-latency log2 histogram (dispatch-call time:
    argument staging + enqueue; first call includes the compile), and
  - per-site recompile attribution: a process-global jax.monitoring
    listener (the CompileCounter mechanism — register once, never
    unregister) charges each backend_compile event to the dispatch
    name active on the compiling thread, or "other" when none is.

The wrapper passes *args/**kwargs straight through, so donation and
sharding semantics of the wrapped jit are untouched, and re-running
`attach` after an engine `shard()`/failover rebuild is idempotent
(already-wrapped attributes are skipped by marker).

`register_slo_gauges` publishes the burn-rate views HubWatch and the
fleet autopilot consume instead of recomputing ad hoc:

  syz_slo_coverage_stall_seconds   time since the device admission gate
                                   last admitted new coverage (tsdb
                                   tier scan, so it spans ~4h)
  syz_slo_ingest_ring_full_rate    ingest ring-full drops/s over the
                                   last 15s tsdb window
  syz_slo_shed_rate                coalescer sheds/s, self-sampled
                                   scrape-to-scrape
  syz_slo_hub_sync_stall_seconds   time since the last successful
                                   Hub.Sync (0 when no hub configured)
"""

from __future__ import annotations

import contextlib
import threading
import time

import numpy as np

from syzkaller_tpu.telemetry.registry import log2_bucket

NBUCKETS = 24
HIST_BASE = 1e-6

# every jitted closure cover/engine.py:_build() publishes; attach()
# skips names a particular engine build doesn't have
DISPATCH_ATTRS = (
    "_fuzz_tick_fn", "_evict_scores_fn", "_swap_rows_fn",
    "_synth_fn", "_random_bits_fn", "_ingest_update_fn",
    "_ingest_admit_fn", "_ingest_diff_fn", "_ingest_pack_fn",
    "_ingest_pack_or_fn", "_decision_fn", "_popcount_fn", "_pack_fn",
    "_pack_or_fn", "_update_stream_fn", "_update_stream32_fn",
    "_admit_selected_fn", "_update_fn", "_update_sparse_fn",
    "_admit_if_new_fn", "_admit_choices_fn", "_or_rows_fn",
    "_diff_vs_fn", "_admit_fn", "_minimize_fn", "_minimize_scan_fn",
    "_sample_rows_fn", "_compact_fn", "_sample_fn", "_prio_update_fn",
)

_COMPILE_EVENT = "backend_compile"
_reg_mu = threading.Lock()
_registered = False
_profilers: "list[DispatchProfiler]" = []

# nested-kernel attribution: the kernel plane enters this scope while a
# registered pallas twin runs, so a compile fired from INSIDE a fused
# dispatch (a lazy pallas lowering, an interpret-mode inner jit) lands
# on a "<dispatch>/<label>" child instead of being charged to the outer
# closure wholesale — the misattribution that made fused-tick recompile
# counts unreadable.  Module-global thread-local: one subkernel scope
# serves every profiler instance on the thread.
_sub_tls = threading.local()


@contextlib.contextmanager
def subkernel(label: str = "subkernel"):
    """Attribute compiles in this scope to the active dispatch's
    `/{label}` child (nests: inner labels win, restored on exit)."""
    prev = getattr(_sub_tls, "label", None)
    _sub_tls.label = label
    try:
        yield
    finally:
        _sub_tls.label = prev


def _listener(event: str, duration: float = 0.0, **kwargs) -> None:
    if _COMPILE_EVENT not in event:
        return
    for p in list(_profilers):
        p._on_compile()


def _ensure_listener() -> None:
    global _registered
    with _reg_mu:
        if _registered:
            return
        _registered = True
    import jax.monitoring
    jax.monitoring.register_event_duration_secs_listener(_listener)


class DispatchProfiler:
    """Named-dispatch wall-latency histograms + recompile attribution."""

    def __init__(self):
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._hist: "dict[str, np.ndarray]" = {}
        self._sum: "dict[str, float]" = {}
        self._count: "dict[str, int]" = {}
        self._recompiles: "dict[str, int]" = {}
        self._families = None
        _ensure_listener()
        with _reg_mu:
            _profilers.append(self)

    # -- wrapping ----------------------------------------------------------

    def _ensure(self, name: str) -> None:
        if name not in self._hist:
            self._hist[name] = np.zeros((NBUCKETS,), np.int64)
            self._sum[name] = 0.0
            self._count[name] = 0

    def wrap(self, name: str, fn):
        def wrapped(*args, **kwargs):
            tls = self._tls
            prev = getattr(tls, "name", None)
            tls.name = name
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                dt = time.perf_counter() - t0
                tls.name = prev
                b = log2_bucket(dt, HIST_BASE, NBUCKETS)
                with self._mu:
                    self._ensure(name)
                    self._hist[name][b] += 1
                    self._sum[name] += dt
                    self._count[name] += 1

        wrapped._syz_dispatch = name
        wrapped.__wrapped__ = fn
        return wrapped

    def attach(self, engine) -> "list[str]":
        """Wrap every present dispatch attribute on `engine`
        (idempotent); returns the dispatch names now instrumented."""
        wrapped = []
        for attr in DISPATCH_ATTRS:
            fn = getattr(engine, attr, None)
            if fn is None or not callable(fn):
                continue
            name = attr.strip("_")
            if name.endswith("_fn"):
                name = name[:-3]
            if getattr(fn, "_syz_dispatch", None) is not None:
                wrapped.append(name)
                continue
            setattr(engine, attr, self.wrap(name, fn))
            wrapped.append(name)
            with self._mu:
                self._ensure(name)
        if self._families is not None:
            self._seed_children(wrapped)
        return wrapped

    def _on_compile(self) -> None:
        name = getattr(self._tls, "name", None) or "other"
        sub = getattr(_sub_tls, "label", None)
        if sub:
            name = f"{name}/{sub}"
        with self._mu:
            self._recompiles[name] = self._recompiles.get(name, 0) + 1

    # -- exposition --------------------------------------------------------

    def register_metrics(self, registry) -> None:
        """Per-dispatch gauge families on `registry` (call before
        attach so children exist from the first scrape; the full log2
        histograms stay on the /profile/dispatches JSON view — 27x24
        bucket series would drown /metrics)."""
        self._families = (
            registry.gauge("syz_dispatch_calls",
                           "device dispatches by jitted-closure name",
                           labels=("dispatch",)),
            registry.gauge("syz_dispatch_seconds_sum",
                           "cumulative dispatch-call wall seconds",
                           labels=("dispatch",)),
            registry.gauge("syz_dispatch_recompiles",
                           "XLA compilations attributed to this "
                           "dispatch site ('other' = unattributed)",
                           labels=("dispatch",)),
        )
        self._seed_children(["other"])

    def _seed_children(self, names) -> None:
        calls, secs, recomp = self._families
        for n in names:
            calls.labels(dispatch=n).set_function(
                lambda n=n: float(self._count.get(n, 0)))
            secs.labels(dispatch=n).set_function(
                lambda n=n: self._sum.get(n, 0.0))
            recomp.labels(dispatch=n).set_function(
                lambda n=n: float(self._recompiles.get(n, 0)))

    def snapshot(self) -> dict:
        """JSON body of /profile/dispatches."""
        import math
        bounds = [HIST_BASE * (1 << i) for i in range(NBUCKETS - 1)] \
            + [math.inf]
        with self._mu:
            return {
                "upper_bounds": [b if math.isfinite(b) else "+Inf"
                                 for b in bounds],
                "dispatches": {
                    n: {"count": self._count[n],
                        "sum_seconds": self._sum[n],
                        "buckets": [int(x) for x in self._hist[n]]}
                    for n in sorted(self._hist)},
                "recompiles": dict(sorted(self._recompiles.items())),
            }


def register_slo_gauges(registry, mgr) -> None:
    """The syz_slo_* burn-rate gauges over one manager.  Closures read
    live state at scrape time and degrade to 0.0 when the backing
    plane (tsdb, coalescer, hub) isn't configured."""
    start = time.time()
    shed_state = {"t": time.monotonic(), "v": 0.0}
    shed_mu = threading.Lock()

    def coverage_stall() -> float:
        ts = getattr(mgr, "tsdb", None)
        if ts is None or ts.tick == 0:
            return 0.0
        return ts.stall_seconds("admit_admitted")

    def ring_full_rate() -> float:
        ts = getattr(mgr, "tsdb", None)
        if ts is None or ts.tick == 0:
            return 0.0
        return ts.window_rate("ingest_ring_full", seconds=15.0)

    def shed_rate() -> float:
        now = time.monotonic()
        v = float(mgr._c_shed.value)
        with shed_mu:
            dt = now - shed_state["t"]
            dv = v - shed_state["v"]
            if dt >= 1.0:
                shed_state["t"], shed_state["v"] = now, v
        if dt < 1.0:
            return 0.0          # back-to-back scrapes reuse the window
        return max(0.0, dv) / dt

    def sync_stall() -> float:
        if not getattr(mgr.cfg, "hub_addr", ""):
            return 0.0
        last = getattr(mgr, "_last_hub_sync_wall", 0.0)
        return time.time() - (last or start)

    registry.gauge(
        "syz_slo_coverage_stall_seconds",
        "seconds since the admission gate last admitted new coverage "
        "(tsdb tier scan)", fn=coverage_stall)
    registry.gauge(
        "syz_slo_ingest_ring_full_rate",
        "ingest ring-full drops per second over the last 15s window",
        fn=ring_full_rate)
    registry.gauge(
        "syz_slo_shed_rate",
        "coalescer admissions shed per second (scrape-to-scrape)",
        fn=shed_rate)
    registry.gauge(
        "syz_slo_hub_sync_stall_seconds",
        "seconds since the last successful Hub.Sync (0 without a hub)",
        fn=sync_stall)
