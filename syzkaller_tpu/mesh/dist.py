"""Multi-process mesh topology: the jax.distributed init seam and the
process-local device-slice math behind the config's `mesh_hosts` /
`mesh_devices_per_host` knobs.

On a pod slice every manager process addresses only its own chips;
`jax.distributed.initialize` forms the global device view (SNIPPETS.md
pjit exemplar: "on multi-process platforms such as TPU pods, pjit can
be used to run computations across all available devices across
processes").  The engine's bitmap shards over the PROCESS-LOCAL slice
(elementwise diff/merge never needed to cross hosts — the PC axis plan
of SURVEY §5), and the cross-host direction rides the hub's frontier-
aware program exchange (mesh/sketch.py): programs + covered-block
sketches are the durable state the per-host matrices are rebuilt from.

CPU-backend caveat (pinned by tools/mesh_smoke.py): jaxlib through at
least 0.4.37 forms the global multi-process device view on the CPU
backend but rejects cross-process COMPUTATIONS ("Multiprocess
computations aren't implemented on the CPU backend"), so CI validates
the init handshake + the process-local slice + sharded-vs-serial
bit-exactness per process; global-collective dispatches are a TPU-pod
runtime path behind the same seam.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from syzkaller_tpu.utils import log

_init_mu = threading.Lock()
_initialized = False


def init_distributed(coordinator: str = "", num_processes: int = 0,
                     process_id: int = -1) -> bool:
    """Idempotent jax.distributed bring-up.  Arguments fall back to the
    SYZ_MESH_COORDINATOR / SYZ_MESH_NPROCS / SYZ_MESH_PROC env seam so
    orchestrators can inject topology without touching the config file.
    Returns True when a multi-process runtime is (now) active, False
    for the single-process fallback (missing topology is NOT an error:
    a 1-host config runs the same code)."""
    global _initialized
    import jax

    coordinator = coordinator or os.environ.get("SYZ_MESH_COORDINATOR", "")
    num_processes = num_processes or int(
        os.environ.get("SYZ_MESH_NPROCS", "0"))
    if process_id < 0:
        process_id = int(os.environ.get("SYZ_MESH_PROC", "-1"))
    with _init_mu:
        # NB: the already-up probe must not touch jax.process_count()
        # — that initializes the backend, after which
        # jax.distributed.initialize refuses to run at all
        from jax._src import distributed as _dist
        if _initialized or getattr(_dist.global_state, "client",
                                   None) is not None:
            _initialized = True
            return True
        if not coordinator or num_processes < 2 or process_id < 0:
            return False
        # jax 0.4.x keyword is process_id (NOT process_index)
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
        _initialized = True
        log.logf(0, "mesh: distributed runtime up — process %d/%d, "
                 "%d local / %d global devices", jax.process_index(),
                 jax.process_count(), len(jax.local_devices()),
                 len(jax.devices()))
        return True


def process_topology() -> dict:
    """The topology snapshot tests/smokes assert on (and /metrics could
    export): process index/count plus local/global device counts."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def local_mesh_size(cfg) -> int:
    """How many devices THIS process's engine mesh spans: the whole
    `mesh` knob single-process, the per-host slice under a pod
    topology.  Pure arithmetic — validated by Config.validate, no
    accelerator runtime touched."""
    if cfg.mesh < 2:
        return cfg.mesh
    if cfg.mesh_devices_per_host:
        return cfg.mesh_devices_per_host
    return cfg.mesh // max(1, cfg.mesh_hosts)


def mesh_from_config(cfg):
    """The manager's engine-mesh entry point: bring up the distributed
    runtime when topology is configured (or injected via env), then
    build the PC-axis mesh over this process's addressable slice.
    Returns None for unmeshed configs.  Raises manager.config's
    ConfigError (via pc_mesh) when the slice is too small — a clear
    startup failure, not a mid-dispatch XLA crash."""
    if cfg.mesh < 2:
        return None
    from syzkaller_tpu.cover.engine import pc_mesh

    if cfg.mesh_hosts > 1:
        init_distributed(num_processes=cfg.mesh_hosts)
    n = local_mesh_size(cfg)
    return pc_mesh(n, cfg.mesh_platform)


# -- cross-host frontier spanning -------------------------------------------
#
# Per-campaign SparseView frontiers are host-side block dicts over the
# DENSE bitmap space, whose indices are PcMap first-seen key order —
# so spanning them across hosts is exact only between managers with
# aligned key orders (a preseeded PcMap: the vmlinux cover scan, or
# export_keys/preseed as the chaos/equivalence harnesses do).  The
# helpers below are that spanning seam; block-granular GLOBAL frontier
# convergence for unaligned managers rides the hub sketch instead
# (raw-PC blocks are key-order independent).


def export_frontiers(engine) -> dict:
    """{tag: (block ids, slabs)} for every live campaign frontier —
    the wire/snapshot form (SparseView.export_blocks)."""
    return {tag: v.export_blocks()
            for tag, v in engine.frontier_views().items()}


def absorb_frontiers(engine, fronts: dict) -> None:
    """OR peer frontier exports into this engine's views (creating
    them on first sight).  Caller guarantees key-order alignment."""
    for tag, (ids, data) in fronts.items():
        engine.frontier_view(tag).import_blocks(ids, data)


def spanned_popcount(engines) -> int:
    """Bits lit across a set of engines' merged frontier views — the
    'N hosts converge one global frontier' acceptance probe."""
    from syzkaller_tpu.cover.engine import merge_views

    views = [v for e in engines for v in e.frontier_views().values()]
    if not views:
        return 0
    dense = merge_views(views)
    return int(np.unpackbits(dense.view(np.uint8)).sum())
