"""Covered-block coverage sketch: the hub's frontier-aware exchange
filter (ISSUE 14 hub v2).

Managers publish which raw-PC blocks they have already covered; the hub
then ships a pending program to a manager only when the program's
touched blocks are NOT all inside that manager's covered set — i.e.
only programs plausibly carrying new signal travel.

Design note — why NOT a bloom filter (the obvious "sketch"): a bloom
over the covered set has the WRONG one-sided error for this filter.  A
bloom false positive means a genuinely NEW block tests as "covered", so
the program carrying it is filtered — a false negative of the exchange,
and the acceptance bar is FN = 0 (a program with new blocks must never
be withheld).  An exact set has the right error in both directions, and
its cost is small because the sync is DELTA-based: a manager's covered
set is derived from its PcMap keys, which are append-only (first-seen
insertion order, never evicted), so each Hub.Sync ships only the blocks
discovered since the last sync — steady-state traffic is proportional
to NEW coverage, not corpus size.  False positives (shipping a program
whose blocks the manager covered since the last sketch) are bounded by
sketch staleness, i.e. one sync interval of frontier growth, and decay
to zero as the frontier saturates.

Block identity must be RAW-PC based (`raw_pc >> BLOCK_SHIFT`): dense
bitmap indices are per-manager PcMap first-seen order, meaningless
across hosts.  64-byte blocks (shift 6) ≈ basic-block granularity —
the filter's FN=0 guarantee is at BLOCK granularity (a program whose
every touched block is covered can still carry a new PC inside a
covered block; it is filtered, and that PC arrives via the block's
discovering manager instead).

Wire format: sorted uint64 block ids, little-endian packed, base64
(the RPC plane's b64 convention) — `encode_blocks`/`decode_blocks`.
"""

from __future__ import annotations

import base64
import threading

import numpy as np

BLOCK_SHIFT = 6          # 64-byte raw-PC blocks


def blocks_of(pcs, shift: int = BLOCK_SHIFT) -> np.ndarray:
    """Sorted unique uint64 block ids for a raw-PC array."""
    a = np.asarray(pcs, np.uint64).ravel()
    if a.size == 0:
        return np.zeros((0,), np.uint64)
    return np.unique(a >> np.uint64(shift))


def encode_blocks(blocks) -> str:
    """Block array → wire string (LE uint64, base64)."""
    a = np.asarray(blocks, np.uint64).ravel()
    return base64.b64encode(a.astype("<u8").tobytes()).decode()


def decode_blocks(s: str) -> np.ndarray:
    """Wire string → uint64 block array (empty on empty/None)."""
    if not s:
        return np.zeros((0,), np.uint64)
    return np.frombuffer(base64.b64decode(s), dtype="<u8").copy()


class BlockSketch:
    """One manager's covered-raw-block set with append-only delta
    export (thread-safe).  `add_pcs` folds a cover in and returns the
    blocks that were new — exactly the delta the next Hub.Sync ships,
    so the wire cost tracks frontier growth."""

    def __init__(self, shift: int = BLOCK_SHIFT):
        self.shift = shift
        self._covered: set[int] = set()
        self._mu = threading.Lock()

    def __len__(self) -> int:
        with self._mu:
            return len(self._covered)

    def add_pcs(self, pcs) -> np.ndarray:
        """Fold a raw-PC cover in; returns the NEWLY covered blocks
        (sorted uint64 — the delta)."""
        return self.add_blocks(blocks_of(pcs, self.shift))

    def add_blocks(self, blocks) -> np.ndarray:
        bs = np.asarray(blocks, np.uint64).ravel()
        fresh = []
        with self._mu:
            for b in bs:
                ib = int(b)
                if ib not in self._covered:
                    self._covered.add(ib)
                    fresh.append(ib)
        return np.array(sorted(fresh), np.uint64)

    def covers(self, blocks) -> bool:
        """True iff EVERY block is already covered — the ship/skip
        verdict (skip only when nothing can be new)."""
        bs = np.asarray(blocks, np.uint64).ravel()
        with self._mu:
            return all(int(b) in self._covered for b in bs)

    def snapshot(self) -> np.ndarray:
        """The full covered set (sorted uint64) — the `reset=True`
        resync payload after a reconnect."""
        with self._mu:
            return np.array(sorted(self._covered), np.uint64)


def should_ship(prog_blocks: np.ndarray, covered: "set[int]") -> bool:
    """The hub-side filter verdict for one pending program: ship unless
    the program's block set is KNOWN (non-empty) and fully covered.
    Unknown block sets (legacy managers pushing bare programs) always
    ship — the FN=0 guarantee never leans on optional metadata."""
    if prog_blocks is None or len(prog_blocks) == 0:
        return True
    return any(int(b) not in covered for b in prog_blocks)
