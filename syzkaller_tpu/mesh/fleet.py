"""Fleet-level autopilot: ONE controller over N managers plus the hub,
composed from the existing per-manager control loop (autopilot/
controller.py) through its HttpSource/ReportExecutor seam.

Each managed host runs a full per-manager Autopilot in observe mode
(the manager's own in-process autopilot executes; this controller
watches the fleet).  On top of the per-host loops the fleet layer adds
the decisions only a cross-host view can make:

  * per-host health roll-up — an unreachable /metrics endpoint is
    itself a health signal (HOST_DOWN), not an exception;
  * shard-aware pool targeting — VM capacity per coverage shard, so a
    host driving an 8-chip slice isn't starved to the same VM count as
    a 2-chip one, with rebalance recommendations when a host's
    VMs-per-shard deviates from the fleet;
  * rotation arbitration — at most one campaign rotation recommendation
    per tick, aimed at the host with the weakest frontier productivity
    (N hosts all rotating at once would thrash the global frontier);
  * hub-exchange watchdog — the federation tier's liveness: a manager
    whose hub sync age exceeds the threshold, or a hub shipping nothing
    while programs are pending, is flagged before the frontiers drift.

Everything here is observe/recommend (ReportExecutor semantics): the
fleet controller has no remote seams to act through, and the per-host
autopilots already execute locally.  `tools/autopilot.py --fleet`
drives it; `health_json` keeps the PR 10 probe contract at L8.
"""

from __future__ import annotations

import time

from syzkaller_tpu.autopilot.controller import Autopilot, ReportExecutor
from syzkaller_tpu.autopilot.health import State
from syzkaller_tpu.autopilot.policy import SampleView

HOST_DOWN = "host_down"
SYNC_STALLED = "hub_sync_stalled"
SHIP_STALLED = "hub_ship_stalled"
COVERAGE_STALLED = "coverage_stalled"
RING_FULL = "ingest_ring_full"


def slo_flags(slo: dict, coverage_stall: float = 300.0,
              sync_stall: float = 300.0,
              ring_full_rate: float = 1.0) -> "list[str]":
    """Flag names raised by one manager's syz_slo_* gauge sample
    (observe/profile.py publishes them; the manager's own tsdb computes
    the windows).  This is THE verdict function: ManagedHost.tick and
    the fleet console both call it, so a console flag always matches
    the autopilot's."""
    flags = []
    if slo.get("syz_slo_coverage_stall_seconds", 0.0) > coverage_stall:
        flags.append(COVERAGE_STALLED)
    if sync_stall > 0 and \
            slo.get("syz_slo_hub_sync_stall_seconds", 0.0) > sync_stall:
        flags.append(SYNC_STALLED)
    if slo.get("syz_slo_ingest_ring_full_rate", 0.0) > ring_full_rate:
        flags.append(RING_FULL)
    return flags


class HubWatch:
    """Hub-exchange-rate watchdog over the hub's /metrics: flags
    managers whose sync age crossed the threshold and a hub that has
    pending programs but ships none between ticks."""

    def __init__(self, source, sync_age_threshold: float = 300.0):
        self.source = source
        self.sync_age_threshold = float(sync_age_threshold)
        self._prev: "dict | None" = None

    def check(self) -> dict:
        sample = self.source.sample()
        prev, self._prev = self._prev, sample
        view = SampleView(sample, prev)
        flags = []
        for key, val in sample.items():
            if key.startswith("syz_hub_sync_age_seconds") \
                    and val > self.sync_age_threshold:
                flags.append({"issue": SYNC_STALLED, "series": key,
                              "age": round(val, 1)})
        shipped = view.delta("syz_hub_progs_shipped_total")
        added = view.delta("syz_hub_progs_added_total")
        if prev is not None and added > 0 and shipped == 0 \
                and (sample.get("syz_hub_managers", 0) or 0) >= 2:
            flags.append({"issue": SHIP_STALLED,
                          "added": added, "shipped": shipped})
        return {
            "corpus": sample.get("syz_hub_corpus_size", 0),
            "managers": sample.get("syz_hub_managers", 0),
            "shipped_delta": shipped,
            "added_delta": added,
            "flags": flags,
        }


class ManagedHost:
    """One manager under fleet watch: its observe-mode control loop
    plus the shard weight (devices its engine mesh spans)."""

    def __init__(self, name: str, source, shards: int = 1,
                 interval: float = 5.0, now=None):
        self.name = name
        self.source = source
        self.shards = max(1, int(shards))
        self.pilot = Autopilot(source, ReportExecutor(),
                               interval=interval, now=now)
        self.last_sample: "dict | None" = None

    def tick(self) -> dict:
        """One per-host pass; an unreachable endpoint becomes a
        HOST_DOWN report instead of an exception."""
        try:
            sample = self.pilot.source.sample()
        except Exception as e:
            return {"host": self.name, "reachable": False,
                    "state": HOST_DOWN, "error": str(e)}
        self.last_sample = sample
        # feed the already-fetched sample through the pilot (one scrape
        # per tick, not two)
        orig = self.pilot.source
        try:
            self.pilot.source = _Stub(sample)
            report = self.pilot.tick()
        finally:
            self.pilot.source = orig
        worst = self.pilot.health.worst()
        # the syz_slo_* burn-rate gauges (observe/profile.py) ride the
        # same scrape: the manager's tsdb already computed the windows,
        # so the fleet layer consumes verdicts instead of recomputing
        slo = {k.split("{", 1)[0]: float(v) for k, v in sample.items()
               if k.startswith("syz_slo_")}
        return {"host": self.name, "reachable": True,
                "state": worst.name, "shards": self.shards,
                "vm_live": sample.get("syz_vm_pool_live"),
                "vm_target": sample.get("syz_vm_pool_target"),
                "exec_rate": sample.get("syz_exec_rate", 0.0),
                "slo": slo, "slo_flags": slo_flags(slo),
                "report": report}


class _Stub:
    def __init__(self, sample):
        self._s = sample

    def sample(self):
        return self._s


class FleetAutopilot:
    """The one-controller-over-N composition.  `managers` is a list of
    (name, MetricsSource-like, shards) triples ((name, source) pairs
    default to shards=1); `hub` an optional HubWatch."""

    # a host's VMs-per-shard deviating this far from the fleet mean
    # earns a rebalance recommendation
    REBALANCE_RATIO = 2.0

    def __init__(self, managers, hub: "HubWatch | None" = None,
                 interval: float = 5.0, now=None):
        self.hosts: "list[ManagedHost]" = []
        for entry in managers:
            name, source, *rest = entry
            shards = rest[0] if rest else 1
            self.hosts.append(ManagedHost(name, source, shards=shards,
                                          interval=interval, now=now))
        self.hub = hub
        self.interval = float(interval)
        self.stat_ticks = 0
        self._last: "dict | None" = None

    # -- one fleet pass -----------------------------------------------------

    def tick(self) -> dict:
        self.stat_ticks += 1
        per_host = [h.tick() for h in self.hosts]
        report = {
            "ts": time.time(),
            "hosts": per_host,
            "worst": self._worst(per_host),
            "pool": self._pool_decision(per_host),
            "rotation": self._rotation_decision(per_host),
            "slo_flags": [{"host": h["host"], "issue": f}
                          for h in per_host
                          for f in h.get("slo_flags", [])],
        }
        if self.hub is not None:
            try:
                report["hub"] = self.hub.check()
            except Exception as e:
                report["hub"] = {"error": str(e),
                                 "flags": [{"issue": HOST_DOWN}]}
        self._last = report
        return report

    @staticmethod
    def _worst(per_host) -> str:
        worst = State.HEALTHY.name
        rank = {s.name: int(s) for s in State}
        rank[HOST_DOWN] = max(rank.values()) + 1
        for h in per_host:
            if rank.get(h["state"], 0) > rank.get(worst, 0):
                worst = h["state"]
        return worst

    def _pool_decision(self, per_host) -> dict:
        """Shard-aware capacity view: total VMs vs total shards, plus
        per-host rebalance recommendations when a reachable host's
        VMs-per-shard falls outside REBALANCE_RATIO of the fleet
        mean."""
        live = {h["host"]: (h.get("vm_live") or 0.0)
                for h in per_host if h.get("reachable")}
        shards = {h["host"]: h.get("shards", 1)
                  for h in per_host if h.get("reachable")}
        total_vms = sum(live.values())
        total_shards = sum(shards.values()) or 1
        mean = total_vms / total_shards
        recs = []
        for name, n in live.items():
            per_shard = n / shards[name]
            if mean > 0 and per_shard > mean * self.REBALANCE_RATIO:
                recs.append({"host": name, "action": "shrink",
                             "vms_per_shard": round(per_shard, 2)})
            elif mean > 0 and per_shard < mean / self.REBALANCE_RATIO:
                recs.append({"host": name, "action": "grow",
                             "vms_per_shard": round(per_shard, 2)})
        return {"total_vms": total_vms, "total_shards": total_shards,
                "vms_per_shard": round(mean, 2), "rebalance": recs}

    def _rotation_decision(self, per_host) -> "dict | None":
        """At most one rotation recommendation per tick: the reachable
        host with the lowest exec-rate-weighted productivity whose own
        pilot already proposed a rotation.  Fleet-serialized so N hosts
        don't all churn their campaign assignments in the same tick."""
        candidates = []
        for h in per_host:
            if not h.get("reachable"):
                continue
            for a in h.get("report", {}).get("actions", []):
                if a["action"] == "rotate":
                    candidates.append((h.get("exec_rate") or 0.0, h, a))
        if not candidates:
            return None
        _, host, action = min(candidates, key=lambda t: t[0])
        return {"host": host["host"], "component": action["component"],
                "target": action["target"], "reason": action["reason"]}

    # -- /healthz-shaped probe ----------------------------------------------

    def health_json(self) -> "tuple[int, dict]":
        """(status, body) with the same contract as the manager's
        /healthz: 200 while every host answers below DEGRADED and no
        hub flag is raised."""
        report = self._last or self.tick()
        bad = report["worst"] in (State.DEGRADED.name,
                                  State.RESTARTING.name, HOST_DOWN)
        hub_flags = report.get("hub", {}).get("flags", [])
        code = 503 if bad or hub_flags else 200
        return code, {
            "status": "ok" if code == 200 else "degraded",
            "worst": report["worst"],
            "hosts": {h["host"]: h["state"] for h in report["hosts"]},
            "hub_flags": hub_flags,
            "ticks": self.stat_ticks,
        }
