"""Pod-scale mesh plane: the subsystem that crosses the host boundary
in both directions.

  * `dist` — jax.distributed init seam + process-local mesh topology
    (`mesh_from_config` is the production entry the manager builds its
    engine mesh through) and cross-host SparseView frontier spanning.
  * `sketch` — the covered-block coverage sketch the hub's frontier-
    aware corpus exchange keys on (exact delta-synced sets: provably
    zero false negatives, see the module docstring for why a bloom has
    the WRONG one-sided error here).
  * `fleet` — one autopilot over N managers + the hub, composed from
    the existing HttpSource/ReportExecutor seam.
"""

from syzkaller_tpu.mesh.dist import (
    absorb_frontiers, export_frontiers, init_distributed,
    mesh_from_config, process_topology)
from syzkaller_tpu.mesh.fleet import FleetAutopilot, HubWatch
from syzkaller_tpu.mesh.sketch import (
    BLOCK_SHIFT, BlockSketch, blocks_of, decode_blocks, encode_blocks)

__all__ = [
    "BLOCK_SHIFT", "BlockSketch", "FleetAutopilot", "HubWatch",
    "absorb_frontiers", "blocks_of", "decode_blocks", "encode_blocks",
    "export_frontiers", "init_distributed", "mesh_from_config",
    "process_topology",
]
