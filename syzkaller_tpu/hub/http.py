"""Hub HTTP status page (ref syz-hub/http.go, 259 LoC): global corpus
size plus a per-manager table of corpus/added/new counters, and the
in-memory log cache."""

from __future__ import annotations

import html as html_mod
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from syzkaller_tpu.utils import log

_STYLE = """<style>
body { font-family: monospace; margin: 1em; }
table { border-collapse: collapse; }
td, th { border: 1px solid #999; padding: 2px 8px; text-align: left; }
</style>"""


def summary(hub, start_time: float) -> str:
    st = hub.state
    up = int(time.time() - start_time)
    rows = []
    total_added = total_new = 0
    for name in sorted(st.managers):
        m = st.managers[name]
        new = max(0, len(st.seq) - m.cursor)
        total_added += m.added
        total_new += new
        age = st.sync_age(name)
        age_s = "never" if age == float("inf") else f"{age:.0f}s"
        rows.append(f"<tr><td>{html_mod.escape(name)}</td>"
                    f"<td>{m.cursor}</td><td>{m.added}</td>"
                    f"<td>{new}</td><td>{m.filtered}</td>"
                    f"<td>{len(m.covered)}</td><td>{age_s}</td></tr>")
    table = "".join(rows)
    return (f"{_STYLE}<h2>syz-hub</h2>"
            f"<p>uptime {up // 3600}h{(up % 3600) // 60}m, "
            f"corpus {len(st.seq)}, managers {len(st.managers)}, "
            f"added {total_added}, pending {total_new}</p>"
            f"<table><tr><th>manager</th><th>cursor</th><th>added</th>"
            f"<th>pending</th><th>filtered</th><th>covered</th>"
            f"<th>sync age</th></tr>{table}</table>"
            f"<p><a href='/metrics'>metrics</a> | "
            f"<a href='/origins'>origins</a> | "
            f"<a href='/log'>log</a></p>")


def serve(hub, host: str, port: int) -> ThreadingHTTPServer:
    start_time = time.time()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def _send(self, body: str, code: int = 200,
                  ctype: str = "text/html; charset=utf-8"):
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            try:
                if self.path.split("?")[0] == "/":
                    self._send(summary(hub, start_time))
                elif self.path.split("?")[0] == "/metrics":
                    from syzkaller_tpu.telemetry import expo
                    self._send(expo.prometheus_text([hub.registry]),
                               ctype=expo.CONTENT_TYPE)
                elif self.path.split("?")[0] == "/healthz":
                    # hub liveness for the same orchestrator probe
                    # contract as the manager's /healthz — 503 when a
                    # manager's sync age crossed the hub's threshold
                    # (a stalled exchange drifts the fleet frontiers)
                    import json
                    code, body = hub.health()
                    self._send(json.dumps(body), code,
                               ctype="application/json")
                elif self.path.split("?")[0] == "/origins":
                    # cross-host lineage index: sig -> first pusher's
                    # {"manager", "trace"} — what the fleet console
                    # stitches waterfalls from when a program's local
                    # span has expired from a manager's tracer ring
                    import json
                    st = hub.state
                    self._send(json.dumps(
                        {"count": len(st.origins),
                         "origins": dict(list(st.origins.items())[:256])}),
                        ctype="application/json")
                elif self.path.startswith("/log"):
                    self._send("<pre>%s</pre>" %
                               html_mod.escape(log.cached_log()))
                else:
                    self._send("not found", 404)
            except Exception as e:  # the UI must not kill the hub
                self._send(f"error: {html_mod.escape(str(e))}", 500)

    srv = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    log.logf(0, "hub http UI on http://%s:%d", *srv.server_address)
    return srv
