from syzkaller_tpu.hub.hub import main

main()
