"""Federation: corpus exchange across managers (syz-hub equivalent)."""

from syzkaller_tpu.hub.hub import Hub  # noqa: F401
from syzkaller_tpu.hub.state import HubState  # noqa: F401
