"""Hub state: the federated global corpus with per-manager cursors.

Capability parity with reference syz-hub/state/state.go:22-70: a global
content-deduplicated corpus persisted as an append-ordered directory,
per-manager sequence cursors (each manager pulls only what it hasn't
seen), and call-set filtering so managers only receive programs whose
calls they can execute.

Exchange v2 (frontier-aware, mesh/sketch.py): managers attach each
pushed program's covered raw-PC BLOCKS and delta-sync their own
covered-block sketch; `pending` then skips programs whose every block
the puller already covers.  The filter's error is strictly one-sided —
a skipped program can never carry a block the manager lacks, because
covered sets only grow (so advancing the cursor past a filtered entry
is safe forever), while programs with unknown block sets always ship.
Sketches are persisted beside the manager meta so a hub restart keeps
filtering instead of regressing to naive ship-everything.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass, field

import numpy as np

from syzkaller_tpu.mesh.sketch import should_ship
from syzkaller_tpu.prog.encoding import call_set
from syzkaller_tpu.utils import log


@dataclass
class ManagerState:
    name: str
    cursor: int = 0                  # index into the global sequence
    calls: "set[str] | None" = None  # None = accepts everything
    added: int = 0
    filtered: int = 0                # programs withheld by the sketch
    last_sync: float = 0.0           # wall clock of the last Hub.Sync
    # covered raw-PC blocks (the manager's sketch); persisted as a
    # sidecar, not in the JSON meta (it is a large flat u64 set)
    covered: "set[int]" = field(default_factory=set)

    def to_json(self) -> dict:
        return {"cursor": self.cursor, "added": self.added,
                "filtered": self.filtered, "last_sync": self.last_sync,
                "calls": sorted(self.calls) if self.calls is not None else None}


class HubState:
    def __init__(self, dirpath: str):
        self.dir = dirpath
        self.corpus_dir = os.path.join(dirpath, "corpus")
        self.mgr_dir = os.path.join(dirpath, "managers")
        self.blocks_dir = os.path.join(dirpath, "blocks")
        self.origin_dir = os.path.join(dirpath, "origins")
        os.makedirs(self.corpus_dir, exist_ok=True)
        os.makedirs(self.mgr_dir, exist_ok=True)
        os.makedirs(self.blocks_dir, exist_ok=True)
        os.makedirs(self.origin_dir, exist_ok=True)
        # global sequence: list of (sig, data); order = admission order
        self.seq: list[tuple[str, bytes]] = []
        self.sigs: set[str] = set()
        # sig -> covered raw-PC blocks (uint64), when the pusher sent them
        self.blocks: dict[str, np.ndarray] = {}
        # sig -> {"manager", "trace"}: the pushing manager's span
        # context, persisted as a sidecar so cross-host lineage survives
        # a hub restart (the resync path re-ships the same origin)
        self.origins: dict[str, dict] = {}
        self.managers: dict[str, ManagerState] = {}
        self._writes: list[tuple[str, bytes]] = []   # staged disk writes
        self._load()

    def _load(self) -> None:
        entries = []
        for name in os.listdir(self.corpus_dir):
            path = os.path.join(self.corpus_dir, name)
            if not os.path.isfile(path):
                continue
            # files are "<seq>-<sig>" so ordering survives restart
            try:
                seq_s, sig = name.split("-", 1)
                seqno = int(seq_s)
            except ValueError:
                continue
            with open(path, "rb") as f:
                entries.append((seqno, sig, f.read()))
        for _seqno, sig, data in sorted(entries):
            self.seq.append((sig, data))
            self.sigs.add(sig)
        for name in os.listdir(self.blocks_dir):
            if name not in self.sigs:
                continue
            try:
                with open(os.path.join(self.blocks_dir, name), "rb") as f:
                    self.blocks[name] = np.frombuffer(f.read(), "<u8").copy()
            except OSError:
                continue
        for name in os.listdir(self.origin_dir):
            if name not in self.sigs:
                continue
            try:
                with open(os.path.join(self.origin_dir, name)) as f:
                    origin = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            if isinstance(origin, dict) and origin.get("trace"):
                self.origins[name] = {
                    "manager": str(origin.get("manager", "")),
                    "trace": str(origin["trace"])}
        for name in os.listdir(self.mgr_dir):
            path = os.path.join(self.mgr_dir, name)
            if name.endswith(".covered"):
                continue
            try:
                with open(path) as f:
                    meta = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            m = ManagerState(
                name=name, cursor=int(meta.get("cursor", 0)),
                calls=set(meta["calls"]) if meta.get("calls") is not None else None,
                added=int(meta.get("added", 0)),
                filtered=int(meta.get("filtered", 0)),
                last_sync=float(meta.get("last_sync", 0.0)))
            try:
                with open(path + ".covered", "rb") as f:
                    m.covered = set(
                        np.frombuffer(f.read(), "<u8").tolist())
            except OSError:
                pass
            self.managers[name] = m
        if self.seq:
            log.logf(0, "hub: loaded %d corpus entries (%d with block "
                     "sketches), %d managers", len(self.seq),
                     len(self.blocks), len(self.managers))

    # Mutators stage disk writes instead of performing them: the hub's
    # RPC handlers hold the hub lock around the in-memory mutation, and
    # a handler holding a lock across file I/O serializes every
    # manager's sync on the disk (syz-vet lock pass, P0
    # blocking-under-lock).  Call `take_writes()` under the lock and
    # `flush_writes()` after releasing it.

    def _stage_manager(self, m: ManagerState) -> None:
        self._writes.append((os.path.join(self.mgr_dir, m.name),
                             json.dumps(m.to_json()).encode()))

    def take_writes(self) -> list[tuple[str, bytes]]:
        """Drain staged (path, content) disk writes (call locked)."""
        out, self._writes = self._writes, []
        return out

    @staticmethod
    def flush_writes(writes: list[tuple[str, bytes]]) -> None:
        """Apply staged writes (call unlocked).  Each write is atomic
        (tmp + rename); concurrent flushes may reorder two snapshots of
        the same manager meta, which at worst rewinds a cursor — the
        manager re-pulls a few programs it already dedups by sig."""
        for path, content in writes:
            tmp = os.path.join(os.path.dirname(path),
                               f".tmp.{os.path.basename(path)}")
            with open(tmp, "wb") as f:
                f.write(content)
            os.replace(tmp, path)

    def connect(self, name: str, fresh: bool,
                calls: "list[str] | None") -> None:
        m = self.managers.get(name)
        if m is None or fresh:
            m = ManagerState(name=name)
        m.calls = set(calls) if calls is not None else None
        self.managers[name] = m
        self._stage_manager(m)

    def add(self, name: str, progs: list[bytes],
            blocks: "list[np.ndarray | None] | None" = None,
            traces: "list[str] | None" = None) -> int:
        """Programs pushed by a manager (with optional per-program
        covered-block arrays and trace ids, parallel to `progs`);
        returns how many were fresh."""
        m = self.managers.setdefault(name, ManagerState(name=name))
        fresh = 0
        for i, data in enumerate(progs):
            sig = hashlib.sha1(data).hexdigest()
            bl = blocks[i] if blocks is not None and i < len(blocks) \
                else None
            tid = traces[i] if traces is not None and i < len(traces) \
                else ""
            if bl is not None and len(bl) and sig not in self.blocks:
                # a known program gaining a block sketch still helps:
                # it becomes filterable for future pulls
                self.blocks[sig] = np.asarray(bl, np.uint64)
                self._writes.append((
                    os.path.join(self.blocks_dir, sig),
                    self.blocks[sig].astype("<u8").tobytes()))
            if tid and sig not in self.origins:
                # first pusher wins: lineage points at the manager that
                # actually discovered the program
                self.origins[sig] = {"manager": name, "trace": str(tid)}
                self._writes.append((
                    os.path.join(self.origin_dir, sig),
                    json.dumps(self.origins[sig]).encode()))
            if sig in self.sigs:
                continue
            self.sigs.add(sig)
            self.seq.append((sig, data))
            m.added += 1
            fresh += 1
            self._writes.append((
                os.path.join(self.corpus_dir,
                             f"{len(self.seq) - 1:08d}-{sig}"), data))
        self._stage_manager(m)
        return fresh

    def observe_sketch(self, name: str, blocks,
                       reset: bool = False) -> int:
        """Fold a manager's covered-block delta (or full snapshot when
        `reset`) into its sketch; returns blocks newly covered.  The
        sketch is staged to a sidecar so a hub restart keeps
        filtering."""
        m = self.managers.setdefault(name, ManagerState(name=name))
        if reset:
            m.covered = set()
        before = len(m.covered)
        m.covered.update(int(b) for b in np.asarray(blocks,
                                                    np.uint64).ravel())
        new = len(m.covered) - before
        if new or reset:
            self._writes.append((
                os.path.join(self.mgr_dir, f"{name}.covered"),
                np.array(sorted(m.covered),
                         np.uint64).astype("<u8").tobytes()))
        return new

    def pending(self, name: str, max_progs: int = 100
                ) -> tuple[list[bytes], int, int]:
        """Programs this manager hasn't seen (call-set AND sketch
        filtered), a count of how many more are waiting (ref Sync's
        More field), and how many the sketch withheld this call.  A
        withheld program's every block is already covered by the
        puller, and covered sets only grow — so the cursor advances
        past it permanently without ever creating an exchange false
        negative."""
        m = self.managers.setdefault(name, ManagerState(name=name))
        out: list[bytes] = []
        filtered = 0
        while m.cursor < len(self.seq) and len(out) < max_progs:
            sig, data = self.seq[m.cursor]
            m.cursor += 1
            if m.calls is not None:
                try:
                    if not call_set(data) <= m.calls:
                        continue
                except Exception:
                    continue
            if m.covered and not should_ship(self.blocks.get(sig),
                                             m.covered):
                filtered += 1
                m.filtered += 1
                continue
            out.append(data)
        more = len(self.seq) - m.cursor
        m.last_sync = time.time()
        self._stage_manager(m)
        return out, more, filtered

    def origin_of(self, data: bytes) -> dict:
        """{"manager", "trace"} of the program's first pusher (empty
        dict when it arrived without a span context).  Plain dict read
        — safe to call after the hub lock is released."""
        return self.origins.get(hashlib.sha1(data).hexdigest(), {})

    def sync_age(self, name: str) -> float:
        """Seconds since the manager's last Hub.Sync (inf if never)."""
        m = self.managers.get(name)
        if m is None or not m.last_sync:
            return float("inf")
        return max(0.0, time.time() - m.last_sync)

    def global_frontier(self) -> "set[int]":
        """The fleet-wide covered-block union — what 'N managers
        converge one global frontier' means at hub granularity."""
        out: set[int] = set()
        for m in self.managers.values():
            out |= m.covered
        return out
