"""Hub state: the federated global corpus with per-manager cursors.

Capability parity with reference syz-hub/state/state.go:22-70: a global
content-deduplicated corpus persisted as an append-ordered directory,
per-manager sequence cursors (each manager pulls only what it hasn't
seen), and call-set filtering so managers only receive programs whose
calls they can execute.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field

from syzkaller_tpu.prog.encoding import call_set
from syzkaller_tpu.utils import log


@dataclass
class ManagerState:
    name: str
    cursor: int = 0                  # index into the global sequence
    calls: "set[str] | None" = None  # None = accepts everything
    added: int = 0

    def to_json(self) -> dict:
        return {"cursor": self.cursor, "added": self.added,
                "calls": sorted(self.calls) if self.calls is not None else None}


class HubState:
    def __init__(self, dirpath: str):
        self.dir = dirpath
        self.corpus_dir = os.path.join(dirpath, "corpus")
        self.mgr_dir = os.path.join(dirpath, "managers")
        os.makedirs(self.corpus_dir, exist_ok=True)
        os.makedirs(self.mgr_dir, exist_ok=True)
        # global sequence: list of (sig, data); order = admission order
        self.seq: list[tuple[str, bytes]] = []
        self.sigs: set[str] = set()
        self.managers: dict[str, ManagerState] = {}
        self._writes: list[tuple[str, bytes]] = []   # staged disk writes
        self._load()

    def _load(self) -> None:
        entries = []
        for name in os.listdir(self.corpus_dir):
            path = os.path.join(self.corpus_dir, name)
            if not os.path.isfile(path):
                continue
            # files are "<seq>-<sig>" so ordering survives restart
            try:
                seq_s, sig = name.split("-", 1)
                seqno = int(seq_s)
            except ValueError:
                continue
            with open(path, "rb") as f:
                entries.append((seqno, sig, f.read()))
        for _seqno, sig, data in sorted(entries):
            self.seq.append((sig, data))
            self.sigs.add(sig)
        for name in os.listdir(self.mgr_dir):
            path = os.path.join(self.mgr_dir, name)
            try:
                with open(path) as f:
                    meta = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            self.managers[name] = ManagerState(
                name=name, cursor=int(meta.get("cursor", 0)),
                calls=set(meta["calls"]) if meta.get("calls") is not None else None,
                added=int(meta.get("added", 0)))
        if self.seq:
            log.logf(0, "hub: loaded %d corpus entries, %d managers",
                     len(self.seq), len(self.managers))

    # Mutators stage disk writes instead of performing them: the hub's
    # RPC handlers hold the hub lock around the in-memory mutation, and
    # a handler holding a lock across file I/O serializes every
    # manager's sync on the disk (syz-vet lock pass, P0
    # blocking-under-lock).  Call `take_writes()` under the lock and
    # `flush_writes()` after releasing it.

    def _stage_manager(self, m: ManagerState) -> None:
        self._writes.append((os.path.join(self.mgr_dir, m.name),
                             json.dumps(m.to_json()).encode()))

    def take_writes(self) -> list[tuple[str, bytes]]:
        """Drain staged (path, content) disk writes (call locked)."""
        out, self._writes = self._writes, []
        return out

    @staticmethod
    def flush_writes(writes: list[tuple[str, bytes]]) -> None:
        """Apply staged writes (call unlocked).  Each write is atomic
        (tmp + rename); concurrent flushes may reorder two snapshots of
        the same manager meta, which at worst rewinds a cursor — the
        manager re-pulls a few programs it already dedups by sig."""
        for path, content in writes:
            tmp = os.path.join(os.path.dirname(path),
                               f".tmp.{os.path.basename(path)}")
            with open(tmp, "wb") as f:
                f.write(content)
            os.replace(tmp, path)

    def connect(self, name: str, fresh: bool,
                calls: "list[str] | None") -> None:
        m = self.managers.get(name)
        if m is None or fresh:
            m = ManagerState(name=name)
        m.calls = set(calls) if calls is not None else None
        self.managers[name] = m
        self._stage_manager(m)

    def add(self, name: str, progs: list[bytes]) -> int:
        """Programs pushed by a manager; returns how many were fresh."""
        m = self.managers.setdefault(name, ManagerState(name=name))
        fresh = 0
        for data in progs:
            sig = hashlib.sha1(data).hexdigest()
            if sig in self.sigs:
                continue
            self.sigs.add(sig)
            self.seq.append((sig, data))
            m.added += 1
            fresh += 1
            self._writes.append((
                os.path.join(self.corpus_dir,
                             f"{len(self.seq) - 1:08d}-{sig}"), data))
        self._stage_manager(m)
        return fresh

    def pending(self, name: str, max_progs: int = 100
                ) -> tuple[list[bytes], int]:
        """Programs this manager hasn't seen (call-set filtered), plus a
        count of how many more are waiting (ref Sync's More field)."""
        m = self.managers.setdefault(name, ManagerState(name=name))
        out: list[bytes] = []
        while m.cursor < len(self.seq) and len(out) < max_progs:
            sig, data = self.seq[m.cursor]
            m.cursor += 1
            if m.calls is not None:
                try:
                    if not call_set(data) <= m.calls:
                        continue
                except Exception:
                    continue
            out.append(data)
        more = len(self.seq) - m.cursor
        self._stage_manager(m)
        return out, more
