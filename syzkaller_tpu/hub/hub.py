"""syz-hub: corpus federation across managers.

Capability parity with reference syz-hub/hub.go:62-99: shared-key
authenticated RPC {Hub.Connect, Hub.Sync} over the same wire plane as
manager↔fuzzer, persistent per-manager state, and an HTTP summary page.
Cross-host federation rides DCN (SURVEY §2 TPU-native equivalent): each
manager keeps its device-resident coverage matrix; the hub exchanges
the *programs* (the durable state the matrices are rebuilt from).

    python -m syzkaller_tpu.hub -addr :7788 -key SECRET -workdir ./hub
"""

from __future__ import annotations

import argparse
import threading
import time

from syzkaller_tpu import rpc, telemetry
from syzkaller_tpu.hub.state import HubState
from syzkaller_tpu.mesh.sketch import decode_blocks
from syzkaller_tpu.utils import log


class Hub:
    def __init__(self, workdir: str, key: str = "",
                 addr: str = "127.0.0.1:0",
                 sync_age_threshold: float = 300.0):
        self.key = key
        self.state = HubState(workdir)
        self._mu = threading.Lock()
        # /healthz goes non-200 when any manager's sync age crosses
        # this (0 disables the check)
        self.sync_age_threshold = float(sync_age_threshold)
        # federation stat plane: same typed registry as the manager's,
        # served as /metrics by the hub's HTTP page
        self.registry = telemetry.Registry()
        r = self.registry
        self._c_auth_failed = r.counter(
            "syz_hub_auth_failures_total", "rejected shared-key auths")
        self._c_added = r.counter(
            "syz_hub_progs_added_total",
            "fresh programs accepted into the hub corpus")
        self._c_shipped = r.counter(
            "syz_hub_progs_shipped_total",
            "programs shipped to managers on Sync")
        self._c_filtered = r.counter(
            "syz_hub_progs_filtered_total",
            "programs withheld by the covered-block sketch filter")
        self._f_rpc = r.counter(
            "syz_hub_rpc_requests_total", "hub RPC requests by method",
            labels=("method",))
        self._h_rpc = r.histogram(
            "syz_hub_rpc_request_seconds", "hub RPC handling latency")
        r.gauge("syz_hub_corpus_size", "programs in the federated corpus",
                fn=lambda: len(self.state.seq))
        r.gauge("syz_hub_managers", "managers known to the hub",
                fn=lambda: len(self.state.managers))
        r.gauge("syz_hub_frontier_blocks",
                "covered raw-PC blocks in the fleet-wide union frontier",
                fn=lambda: len(self.state.global_frontier()))
        # per-manager families: children are registered lazily as
        # managers appear (loaded state included)
        self._f_mgr_corpus = r.gauge(
            "syz_hub_manager_corpus",
            "programs this manager has contributed to the hub corpus",
            labels=("manager",))
        self._f_mgr_age = r.gauge(
            "syz_hub_sync_age_seconds",
            "seconds since this manager's last Hub.Sync",
            labels=("manager",))
        self._f_mgr_covered = r.gauge(
            "syz_hub_manager_covered_blocks",
            "covered raw-PC blocks in this manager's sketch",
            labels=("manager",))
        self._gauged: set[str] = set()
        for name in self.state.managers:
            self._ensure_manager_gauges(name)
        host, _, port = addr.rpartition(":")
        self.server = rpc.RpcServer(host or "127.0.0.1", int(port or 0))
        self.server.register("Hub.Connect", self.rpc_connect)
        self.server.register("Hub.Sync", self.rpc_sync)
        self.server.observer = self._rpc_observer
        self.addr = self.server.addr

    def _rpc_observer(self, method: str, seconds: float,
                      params: dict) -> None:
        self._f_rpc.labels(method=method or "?").inc()
        self._h_rpc.observe(seconds)

    def _ensure_manager_gauges(self, name: str) -> None:
        """Register the per-manager gauge children once per name; the
        value closures read live hub state so /metrics never goes
        stale."""
        if name in self._gauged:
            return
        self._gauged.add(name)
        st = self.state
        self._f_mgr_corpus.labels(manager=name).set_function(
            lambda n=name: getattr(st.managers.get(n), "added", 0))
        self._f_mgr_age.labels(manager=name).set_function(
            lambda n=name: min(st.sync_age(n), 1e9))
        self._f_mgr_covered.labels(manager=name).set_function(
            lambda n=name: len(getattr(st.managers.get(n), "covered",
                                       ()) or ()))

    def health(self) -> "tuple[int, dict]":
        """(status_code, body) for /healthz: 503 when any manager that
        has ever synced now exceeds the sync-age threshold — a stalled
        exchange means the fleet's frontiers are drifting apart."""
        stale = {}
        if self.sync_age_threshold > 0:
            for name, m in list(self.state.managers.items()):
                if not m.last_sync:
                    continue        # connected but never synced yet
                age = self.state.sync_age(name)
                if age > self.sync_age_threshold:
                    stale[name] = round(age, 1)
        code = 503 if stale else 200
        return code, {
            "status": "ok" if code == 200 else "stale_sync",
            "corpus": len(self.state.seq),
            "managers": len(self.state.managers),
            "frontier_blocks": len(self.state.global_frontier()),
            "stale": stale,
        }

    def _auth(self, params: dict) -> str:
        if self.key and params.get("key") != self.key:
            self._c_auth_failed.inc()
            raise PermissionError("invalid hub key")
        name = params.get("name", "")
        if not name:
            raise ValueError("missing manager name")
        return name

    def rpc_connect(self, params: dict) -> dict:
        name = self._auth(params)
        self._ensure_manager_gauges(name)
        # the lock covers the in-memory mutation only; staged disk
        # writes flush after release so concurrent managers' syncs
        # don't serialize on file I/O (syz-vet lock pass)
        with self._mu:
            self.state.connect(name, bool(params.get("fresh")),
                               params.get("calls"))
            writes = self.state.take_writes()
        self.state.flush_writes(writes)
        log.logf(0, "hub: manager %s connected (fresh=%s)",
                 name, bool(params.get("fresh")))
        return {}

    def rpc_sync(self, params: dict) -> dict:
        """Exchange v2.  v1 fields: name/key/add -> progs/more.  v2
        adds (all optional, so v1 managers interop unchanged):

          sketch        b64 LE-u64 covered-block DELTA for this manager
          sketch_reset  bool: `sketch` is a full snapshot (resync after
                        a manager restore or a detected covered-count
                        mismatch) — replaces the stored set
          blocks        list parallel to `add`: each entry the b64
                        LE-u64 block set of that program ("" = unknown)
          traces        list parallel to `add`: each entry the pushing
                        manager's trace id ("" = untraced), persisted
                        so cross-host span lineage survives the hub

        and returns `filtered` (programs the sketch withheld this
        call) plus `covered` (hub-side sketch size — the echo managers
        compare against their sent count to detect a hub that lost
        their sketch and needs a snapshot resync) plus `traces` (list
        parallel to `progs`: each entry the {"manager", "trace"} origin
        of that program, {} when it arrived untraced)."""
        name = self._auth(params)
        self._ensure_manager_gauges(name)
        add = [rpc.unb64(p) for p in params.get("add", [])]
        blk_wire = params.get("blocks") or []
        blocks = [decode_blocks(b) if b else None for b in blk_wire] \
            if blk_wire else None
        traces = [str(t) for t in params.get("traces") or []] or None
        sketch = decode_blocks(params.get("sketch", ""))
        with self._mu:
            if len(sketch) or params.get("sketch_reset"):
                self.state.observe_sketch(
                    name, sketch, reset=bool(params.get("sketch_reset")))
            fresh = self.state.add(name, add, blocks, traces)
            progs, more, filtered = self.state.pending(name)
            covered = len(self.state.managers[name].covered)
            writes = self.state.take_writes()
        self.state.flush_writes(writes)
        self._c_added.inc(fresh)
        self._c_shipped.inc(len(progs))
        self._c_filtered.inc(filtered)
        log.logf(1, "hub: sync %s: +%d fresh, -> %d progs "
                 "(%d more, %d sketch-filtered, %d covered blocks)",
                 name, fresh, len(progs), more, filtered, covered)
        # origin lookup after the lock: origins is a plain dict keyed
        # by sig and entries are never mutated in place, so a read
        # racing a concurrent add at worst misses a brand-new origin
        return {"progs": [rpc.b64(p) for p in progs], "more": more,
                "filtered": filtered, "covered": covered,
                "traces": [self.state.origin_of(p) for p in progs]}

    def serve_background(self) -> None:
        self.server.serve_background()

    def close(self) -> None:
        self.server.close()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-addr", default="127.0.0.1:7788")
    ap.add_argument("-http", default="",
                    help="status page address, e.g. 127.0.0.1:7789")
    ap.add_argument("-key", default="")
    ap.add_argument("-workdir", default="./hub-workdir")
    ap.add_argument("-sync-age", type=float, default=300.0,
                    help="/healthz goes 503 when a manager's sync age "
                         "exceeds this many seconds (0 disables)")
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)
    log.set_verbosity(args.v)
    log.enable_log_caching()
    hub = Hub(args.workdir, args.key, args.addr,
              sync_age_threshold=args.sync_age)
    log.logf(0, "hub listening on %s:%d", *hub.addr)
    hub.server.serve_background()
    if args.http:
        from syzkaller_tpu.hub import http as hub_http
        host, _, port = args.http.rpartition(":")
        hub_http.serve(hub, host or "127.0.0.1", int(port or 0))
    while True:
        time.sleep(60)


if __name__ == "__main__":
    main()
