"""syz-hub: corpus federation across managers.

Capability parity with reference syz-hub/hub.go:62-99: shared-key
authenticated RPC {Hub.Connect, Hub.Sync} over the same wire plane as
manager↔fuzzer, persistent per-manager state, and an HTTP summary page.
Cross-host federation rides DCN (SURVEY §2 TPU-native equivalent): each
manager keeps its device-resident coverage matrix; the hub exchanges
the *programs* (the durable state the matrices are rebuilt from).

    python -m syzkaller_tpu.hub -addr :7788 -key SECRET -workdir ./hub
"""

from __future__ import annotations

import argparse
import threading
import time

from syzkaller_tpu import rpc, telemetry
from syzkaller_tpu.hub.state import HubState
from syzkaller_tpu.utils import log


class Hub:
    def __init__(self, workdir: str, key: str = "",
                 addr: str = "127.0.0.1:0"):
        self.key = key
        self.state = HubState(workdir)
        self._mu = threading.Lock()
        # federation stat plane: same typed registry as the manager's,
        # served as /metrics by the hub's HTTP page
        self.registry = telemetry.Registry()
        r = self.registry
        self._c_auth_failed = r.counter(
            "syz_hub_auth_failures_total", "rejected shared-key auths")
        self._c_added = r.counter(
            "syz_hub_progs_added_total",
            "fresh programs accepted into the hub corpus")
        self._c_shipped = r.counter(
            "syz_hub_progs_shipped_total",
            "programs shipped to managers on Sync")
        self._f_rpc = r.counter(
            "syz_hub_rpc_requests_total", "hub RPC requests by method",
            labels=("method",))
        self._h_rpc = r.histogram(
            "syz_hub_rpc_request_seconds", "hub RPC handling latency")
        r.gauge("syz_hub_corpus_size", "programs in the federated corpus",
                fn=lambda: len(self.state.seq))
        r.gauge("syz_hub_managers", "managers known to the hub",
                fn=lambda: len(self.state.managers))
        host, _, port = addr.rpartition(":")
        self.server = rpc.RpcServer(host or "127.0.0.1", int(port or 0))
        self.server.register("Hub.Connect", self.rpc_connect)
        self.server.register("Hub.Sync", self.rpc_sync)
        self.server.observer = self._rpc_observer
        self.addr = self.server.addr

    def _rpc_observer(self, method: str, seconds: float,
                      params: dict) -> None:
        self._f_rpc.labels(method=method or "?").inc()
        self._h_rpc.observe(seconds)

    def _auth(self, params: dict) -> str:
        if self.key and params.get("key") != self.key:
            self._c_auth_failed.inc()
            raise PermissionError("invalid hub key")
        name = params.get("name", "")
        if not name:
            raise ValueError("missing manager name")
        return name

    def rpc_connect(self, params: dict) -> dict:
        name = self._auth(params)
        # the lock covers the in-memory mutation only; staged disk
        # writes flush after release so concurrent managers' syncs
        # don't serialize on file I/O (syz-vet lock pass)
        with self._mu:
            self.state.connect(name, bool(params.get("fresh")),
                               params.get("calls"))
            writes = self.state.take_writes()
        self.state.flush_writes(writes)
        log.logf(0, "hub: manager %s connected (fresh=%s)",
                 name, bool(params.get("fresh")))
        return {}

    def rpc_sync(self, params: dict) -> dict:
        name = self._auth(params)
        add = [rpc.unb64(p) for p in params.get("add", [])]
        with self._mu:
            fresh = self.state.add(name, add)
            progs, more = self.state.pending(name)
            writes = self.state.take_writes()
        self.state.flush_writes(writes)
        self._c_added.inc(fresh)
        self._c_shipped.inc(len(progs))
        log.logf(1, "hub: sync %s: +%d fresh, -> %d progs (%d more)",
                 name, fresh, len(progs), more)
        return {"progs": [rpc.b64(p) for p in progs], "more": more}

    def serve_background(self) -> None:
        self.server.serve_background()

    def close(self) -> None:
        self.server.close()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-addr", default="127.0.0.1:7788")
    ap.add_argument("-http", default="",
                    help="status page address, e.g. 127.0.0.1:7789")
    ap.add_argument("-key", default="")
    ap.add_argument("-workdir", default="./hub-workdir")
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)
    log.set_verbosity(args.v)
    log.enable_log_caching()
    hub = Hub(args.workdir, args.key, args.addr)
    log.logf(0, "hub listening on %s:%d", *hub.addr)
    hub.server.serve_background()
    if args.http:
        from syzkaller_tpu.hub import http as hub_http
        host, _, port = args.http.rpartition(":")
        hub_http.serve(hub, host or "127.0.0.1", int(port or 0))
    while True:
        time.sleep(60)


if __name__ == "__main__":
    main()
