"""Persistent corpus: a directory of sha1-named serialized programs.

Capability parity with reference syz-manager/persistent.go:15-102:
verify-on-load (stale programs that no longer parse are garbage
collected), content-hash naming, add, and minimize-to-subset.

Crash-only hardening: writes go through a unique temp file + rename
(two managers or a crash mid-write can never leave a half-written
entry under its final name), orphaned temp files from a crashed writer
are swept on load, and an unreadable/corrupt entry is skipped and
counted (`syz_corpus_load_corrupt_total`) instead of aborting manager
startup — losing one program beats losing the whole corpus.
"""

from __future__ import annotations

import hashlib
import os

from syzkaller_tpu.utils import fileutil, log


def _sig(data: bytes) -> str:
    return hashlib.sha1(data).hexdigest()


class PersistentSet:
    def __init__(self, dirpath: str, verify=None, corrupt_counter=None,
                 persist_err_counter=None):
        """verify: fn(data) -> bool; failing entries are deleted.
        corrupt_counter / persist_err_counter: optional telemetry
        Counters for load-time corruption and write failures."""
        self.dir = dirpath
        self._c_persist_err = persist_err_counter
        os.makedirs(dirpath, exist_ok=True)
        self.entries: dict[str, bytes] = {}
        bad = 0
        for name in sorted(os.listdir(dirpath)):
            path = os.path.join(dirpath, name)
            if not os.path.isfile(path):
                continue
            if name.startswith("."):
                # orphaned temp file from a writer that died mid-write
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                bad += 1         # unreadable: skip, don't abort startup
                continue
            if _sig(data) != name or (verify is not None and not verify(data)):
                bad += 1
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            self.entries[name] = data
        if bad:
            log.logf(0, "corpus: skipped %d broken/stale programs", bad)
            if corrupt_counter is not None:
                corrupt_counter.inc(bad)

    def __len__(self) -> int:
        return len(self.entries)

    def __contains__(self, data: bytes) -> bool:
        return _sig(data) in self.entries

    def values(self) -> list[bytes]:
        return list(self.entries.values())

    def add(self, data: bytes) -> bool:
        sig = _sig(data)
        if sig in self.entries:
            return False
        self.entries[sig] = data
        try:
            # unique temp + rename (fileutil.write_file): concurrent
            # writers of the same sig race benignly — both temp files
            # hold identical bytes, the last rename wins
            fileutil.write_file(os.path.join(self.dir, sig), data)
        except OSError as e:
            # disk trouble must not kill the admission plane; the
            # program stays in memory and the snapshot/restore path
            # counts it as tail loss if the manager dies before a
            # successful re-add
            log.logf(0, "corpus persist failed for %s: %s", sig[:12], e)
            if self._c_persist_err is not None:
                self._c_persist_err.inc()
        return True

    def minimize(self, keep: "list[bytes]") -> int:
        """Drop everything not in `keep`; returns number removed."""
        keep_sigs = {_sig(d) for d in keep}
        removed = 0
        for sig in list(self.entries):
            if sig not in keep_sigs:
                del self.entries[sig]
                try:
                    os.unlink(os.path.join(self.dir, sig))
                except OSError:
                    pass
                removed += 1
        return removed
