"""Persistent corpus: a directory of sha1-named serialized programs.

Capability parity with reference syz-manager/persistent.go:15-102:
verify-on-load (stale programs that no longer parse are garbage
collected), content-hash naming, add, and minimize-to-subset.
"""

from __future__ import annotations

import hashlib
import os

from syzkaller_tpu.utils import log


def _sig(data: bytes) -> str:
    return hashlib.sha1(data).hexdigest()


class PersistentSet:
    def __init__(self, dirpath: str, verify=None):
        """verify: fn(data) -> bool; failing entries are deleted."""
        self.dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        self.entries: dict[str, bytes] = {}
        bad = 0
        for name in sorted(os.listdir(dirpath)):
            path = os.path.join(dirpath, name)
            if not os.path.isfile(path):
                continue
            with open(path, "rb") as f:
                data = f.read()
            if _sig(data) != name or (verify is not None and not verify(data)):
                bad += 1
                os.unlink(path)
                continue
            self.entries[name] = data
        if bad:
            log.logf(0, "corpus: removed %d broken/stale programs", bad)

    def __len__(self) -> int:
        return len(self.entries)

    def __contains__(self, data: bytes) -> bool:
        return _sig(data) in self.entries

    def values(self) -> list[bytes]:
        return list(self.entries.values())

    def add(self, data: bytes) -> bool:
        sig = _sig(data)
        if sig in self.entries:
            return False
        self.entries[sig] = data
        tmp = os.path.join(self.dir, f".tmp.{sig}")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, os.path.join(self.dir, sig))
        return True

    def minimize(self, keep: "list[bytes]") -> int:
        """Drop everything not in `keep`; returns number removed."""
        keep_sigs = {_sig(d) for d in keep}
        removed = 0
        for sig in list(self.entries):
            if sig not in keep_sigs:
                del self.entries[sig]
                try:
                    os.unlink(os.path.join(self.dir, sig))
                except OSError:
                    pass
                removed += 1
        return removed
