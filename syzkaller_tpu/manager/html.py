"""Manager HTTP UI: live stats, corpus browser, crash and prio views.

Capability parity with reference syz-manager/html.go:30-124: summary
page (uptime, stats, crash table, per-call corpus counts), /corpus,
/crash, /prio matrix view, and /log (the in-memory log cache).
"""

from __future__ import annotations

import html as html_mod
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from syzkaller_tpu.utils import log


def serve(mgr, host: str, port: int) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def _send(self, body: str, code: int = 200,
                  ctype: str = "text/html; charset=utf-8"):
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            u = urlparse(self.path)
            q = parse_qs(u.query)
            try:
                if u.path == "/":
                    self._send(summary(mgr))
                elif u.path == "/metrics":
                    # Prometheus text exposition (telemetry/expo.py)
                    from syzkaller_tpu.telemetry import expo
                    self._send(mgr.metrics_text(),
                               ctype=expo.CONTENT_TYPE)
                elif u.path == "/telemetry":
                    import json
                    self._send(json.dumps(mgr.telemetry_snapshot(),
                                          default=str),
                               ctype="application/json")
                elif u.path == "/healthz":
                    # the autopilot's per-component health: 200 while
                    # nothing is DEGRADED, 503 otherwise — the probe
                    # contract for external orchestrators (k8s-style
                    # probes, the gce tier) without scraping /metrics
                    import json
                    code, body = mgr.health_json()
                    self._send(json.dumps(body, default=str), code,
                               ctype="application/json")
                elif u.path == "/corpus":
                    self._send(corpus(mgr))
                elif u.path == "/crash":
                    self._send(crash(mgr, q.get("id", [""])[0]))
                elif u.path == "/prio":
                    self._send(prio(mgr, q.get("call", [""])[0]))
                elif u.path == "/cover":
                    self._send(cover(mgr, q.get("call", [""])[0]))
                elif u.path == "/tsdb":
                    # the observatory's retained time-series windows:
                    # one device->host transfer per scrape tick, served
                    # from the cached ring (observe/tsdb.py)
                    import json
                    ts = getattr(mgr, "tsdb", None)
                    self._send(json.dumps(
                        ts.snapshot_json() if ts is not None else {},
                        default=str), ctype="application/json")
                elif u.path == "/profile/dispatches":
                    # per-dispatch wall-latency histograms + recompile
                    # attribution (observe/profile.py)
                    import json
                    prof = getattr(mgr, "dispatch_profiler", None)
                    self._send(json.dumps(
                        prof.snapshot() if prof is not None else {},
                        default=str), ctype="application/json")
                elif u.path == "/profile":
                    self._send(profile(mgr, q.get("sec", ["3"])[0]))
                elif u.path == "/log":
                    self._send("<pre>%s</pre>" %
                               html_mod.escape(log.cached_log()))
                else:
                    self._send("not found", 404)
            except Exception as e:  # UI must not kill the manager
                self._send(f"error: {html_mod.escape(str(e))}", 500)

    srv = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    log.logf(0, "http UI on http://%s:%d", *srv.server_address)
    return srv


_STYLE = """<style>
body { font-family: monospace; margin: 1em; }
table { border-collapse: collapse; }
td, th { border: 1px solid #999; padding: 2px 8px; text-align: left; }
</style>"""


def _esc(s) -> str:
    return html_mod.escape(str(s))


def summary(mgr) -> str:
    up = int(time.time() - mgr.start_time)
    with mgr._mu:
        stats = dict(mgr.stats)
        crashes = dict(mgr.crash_types)
        ncorpus = len(mgr.corpus)
        fuzzers = list(mgr.fuzzers)
    cover = int(mgr.engine.cover_counts().sum())
    rows = "".join(f"<tr><td>{_esc(k)}</td><td>{v}</td></tr>"
                   for k, v in sorted(stats.items()))
    crows = "".join(
        f"<tr><td><a href='/crash?id={_esc(t)}'>{_esc(t)}</a></td>"
        f"<td>{n}</td></tr>" for t, n in sorted(crashes.items()))
    return (f"{_STYLE}<h2>{_esc(mgr.cfg.name)}</h2>"
            f"<p>uptime {up // 3600}h{(up % 3600) // 60}m, "
            f"corpus <a href='/corpus'>{ncorpus}</a>, cover {cover}, "
            f"fuzzers {_esc(fuzzers)}</p>"
            f"<p><a href='/prio'>priorities</a> | "
            f"<a href='/cover'>coverage</a> | "
            f"<a href='/metrics'>metrics</a> | "
            f"<a href='/telemetry'>telemetry</a> | "
            f"<a href='/tsdb'>tsdb</a> | "
            f"<a href='/profile'>profile</a> | "
            f"<a href='/profile/dispatches'>dispatches</a> | "
            f"<a href='/log'>log</a></p>"
            f"<h3>Stats</h3><table>{rows}</table>"
            f"<h3>Crashes</h3><table><tr><th>description</th><th>count</th>"
            f"</tr>{crows}</table>")


def corpus(mgr) -> str:
    with mgr._mu:
        items = list(mgr.corpus.values())[:1000]
    rows = "".join(
        f"<tr><td>{_esc(it.call)}</td>"
        f"<td><pre>{_esc(it.data.decode(errors='replace'))}</pre></td></tr>"
        for it in items)
    return f"{_STYLE}<h2>corpus ({len(items)} shown)</h2><table>{rows}</table>"


def crash(mgr, title: str) -> str:
    with mgr._mu:
        count = mgr.crash_types.get(title, 0)
    return (f"{_STYLE}<h2>{_esc(title)}</h2><p>count: {count}; "
            f"logs under workdir/crashes/</p>")


_cover_latest: dict = {}      # id(mgr) -> (covered-set key, report html)
_cover_busy: dict = {}        # id(mgr) -> regeneration in flight
_cover_cache_mu = threading.Lock()


def cover(mgr, call: str) -> str:
    """Coverage viewer (ref html.go corpus/cover pages + cover.go line
    report): per-call corpus-cover counts (the state the manager's
    admission path maintains), raw covered PCs for one call, and — when
    a vmlinux was scanned — the per-file line HTML, cached per covered
    set (symbolization costs minutes on a real kernel)."""
    table = mgr.table
    if call and call in table.call_map:
        cid = table.call_map[call].id
        idx = mgr.engine.cover_pcs(cid)
        pcs = mgr.pcmap.pcs_of(idx)
        shown = ", ".join(f"0x{int(p):x}" for p in pcs[:512])
        return (f"{_STYLE}<h2>cover for {_esc(call)}</h2>"
                f"<p>{len(idx)} PCs ({len(pcs)} mapped)</p>"
                f"<pre>{shown}</pre>")
    counts = mgr.engine.cover_counts()
    rows = "".join(
        f"<tr><td><a href='/cover?call={_esc(c.name)}'>{_esc(c.name)}</a>"
        f"</td><td>{int(counts[c.id])}</td></tr>"
        for c in table.calls if counts[c.id] > 0)
    body = (f"{_STYLE}<h2>coverage</h2>"
            f"<p>total covered PCs: {int(counts.sum())}, "
            f"pcmap {len(mgr.pcmap)} mapped / "
            f"{mgr.pcmap.overflow_hits} overflow hits</p>"
            f"<table><tr><th>call</th><th>PCs</th></tr>{rows}</table>")
    scan = getattr(mgr, "cover_scan", None)
    if scan is not None and scan.ready.is_set() and scan.pcs:
        from syzkaller_tpu.manager.kcov import (
            generate_cover_html, restore_pc, vm_offset)
        idx = mgr.engine.covered_indices()
        pcs32 = mgr.pcmap.pcs_of(idx)
        if len(pcs32):
            # Stale-while-revalidate: always serve the most recent
            # COMPLETED report (coverage moves faster than the
            # minutes-long symbolization, so exact-key caching would
            # never converge); at most ONE background regeneration runs
            # at a time, keyed on the covered SET (not its size — the
            # set can change without changing the count).  Failures are
            # logged, never cached, so the next request retries.
            import hashlib
            key = hashlib.sha1(np.sort(pcs32).tobytes()).hexdigest()
            start = False
            with _cover_cache_mu:
                latest_key, report = _cover_latest.get(id(mgr), (None, None))
                if key != latest_key and not _cover_busy.get(id(mgr)):
                    _cover_busy[id(mgr)] = True
                    start = True
            if start:
                def _generate(key=key, pcs32=pcs32):
                    try:
                        base = vm_offset(mgr.cfg.vmlinux)
                        covered = [restore_pc(int(p), base) for p in pcs32]
                        rep = generate_cover_html(mgr.cfg.vmlinux, covered,
                                                  scan.pcs)
                        with _cover_cache_mu:
                            _cover_latest[id(mgr)] = (key, rep)
                    except Exception as e:
                        log.logf(0, "cover line report failed: %s", e)
                    finally:
                        with _cover_cache_mu:
                            _cover_busy[id(mgr)] = False
                threading.Thread(target=_generate, daemon=True).start()
            if report is None:
                body += ("<p><i>line report is being generated — "
                         "reload in a moment</i></p>")
            else:
                if key != latest_key:
                    body += ("<p><i>line report below is from an earlier "
                             "coverage snapshot; a refresh is running"
                             "</i></p>")
                body += report
    return body


def profile(mgr, sec: str) -> str:
    """Kick off a JAX profiler capture of the device engine while the
    fuzzing pipeline keeps running (SURVEY §5 step-profiling hook)."""
    from syzkaller_tpu.utils import profiler

    seconds = min(max(float(sec or 3), 0.5), 60.0)
    out = profiler.capture_async(
        os.path.join(mgr.cfg.workdir, "profile"), seconds)
    return (f"{_STYLE}<h2>profiling</h2>"
            f"<p>capturing {seconds:g}s of device activity into "
            f"<code>{_esc(out)}</code> (tensorboard-loadable)</p>")


def prio(mgr, call: str) -> str:
    prios = np.asarray(mgr.engine.prios)
    table = mgr.table
    if call and call in table.call_map:
        cid = table.call_map[call].id
        pairs = sorted(((prios[cid, j], table.calls[j].name)
                        for j in range(table.count)), reverse=True)[:50]
        rows = "".join(f"<tr><td>{_esc(n)}</td><td>{p:.3f}</td></tr>"
                       for p, n in pairs)
        return (f"{_STYLE}<h2>priorities from {_esc(call)}</h2>"
                f"<table>{rows}</table>")
    links = "".join(f"<li><a href='/prio?call={_esc(c.name)}'>"
                    f"{_esc(c.name)}</a></li>" for c in table.calls[:500])
    return f"{_STYLE}<h2>priority matrix</h2><ul>{links}</ul>"
