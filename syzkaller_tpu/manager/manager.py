"""The manager: VM fleet orchestration, global corpus, crash triage.

Capability parity with reference syz-manager/manager.go: persistent
corpus loaded as re-triage candidates (:124-157), RPC service
{Connect, Check, Poll, NewInput} (:552-656), per-VM run loop with
monitor + reboot (:230-341), crash persistence with the 100-report cap
(:408-450), corpus minimization (:504-550), and stats aggregation
(:628-630).

TPU-native: the manager owns the device-resident global coverage
engine; NewInput admission is a device signal-diff, corpus minimization
is the device greedy set cover, and Poll hands fuzzers batches of
device-drawn choice-table decisions (BASELINE north star).
"""

from __future__ import annotations

import hashlib
import os
import shlex
import sys
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field

import numpy as np

from syzkaller_tpu import prog as P
from syzkaller_tpu import rpc, telemetry, vm
from syzkaller_tpu.cover.engine import CoverageEngine
from syzkaller_tpu.fuzzer import PcMap
from syzkaller_tpu.manager.config import Config
from syzkaller_tpu.manager.persistent import PersistentSet
from syzkaller_tpu.report import extract_frames, symbolize_report
from syzkaller_tpu.sys.table import load_table
from syzkaller_tpu.telemetry import expo
from syzkaller_tpu.triage import CrashIndex
from syzkaller_tpu.utils import log
from syzkaller_tpu.utils.gate import SharedExclusiveGate
from syzkaller_tpu.vm.monitor import monitor_execution

VM_RUN_TIME = 60 * 60.0       # reboot VMs hourly; normal outcome (ref :376)
MAX_CRASH_LOGS = 100          # ref manager.go:408-450
CANDIDATES_PER_POLL = 10
INPUTS_PER_POLL = 100
CHOICES_PER_POLL = 64
IDEM_CACHE = 4096             # replayed-NewInput dedup window
ORPHAN_INPUT_CAP = 1024       # reaped conns' undelivered inputs kept
#                               for the next fuzzer that connects

# back-compat name: the shared/exclusive pattern moved to utils.gate so
# the resilience supervisor reuses it (admitting()/maintenance() are
# aliases of shared()/exclusive())
AdmissionGate = SharedExclusiveGate


@dataclass
class FuzzerConn:
    name: str
    input_queue: deque = field(default_factory=deque)
    connected_at: float = field(default_factory=time.time)
    last_seen: float = field(default_factory=time.monotonic)
    calls: list = field(default_factory=list)


def _wire_blocks(cover) -> str:
    """Raw-PC cover -> covered-block wire string ('' when empty/bad);
    stored on the CorpusItem so hub sync ships it with the program."""
    try:
        from syzkaller_tpu.mesh.sketch import blocks_of, encode_blocks
        b = blocks_of(cover)
        return encode_blocks(b) if len(b) else ""
    except Exception:
        return ""


@dataclass
class CorpusItem:
    data: bytes
    call: str
    call_index: int
    corpus_row: int = -1
    trace_id: str = ""      # admitting input's trace (crash lineage)
    # covered raw-PC blocks (mesh/sketch.py wire string) — shipped with
    # the program on hub sync so the hub can frontier-filter pulls
    blocks: str = ""


class Manager:
    def __init__(self, cfg: Config, table=None):
        self.cfg = cfg
        os.makedirs(cfg.workdir, exist_ok=True)
        self.crashdir = os.path.join(cfg.workdir, "crashes")
        os.makedirs(self.crashdir, exist_ok=True)
        self.table = table or load_table(
            files=None if cfg.descriptions in ("all", "linux")
            else [cfg.descriptions])

        # telemetry plane: typed registry + trace ring always exist (the
        # legacy stats dict is a view over the registry); the DEVICE
        # stat vector and RPC observer follow the `telemetry` knob
        self.registry = telemetry.Registry()
        self.tracer = telemetry.Tracer(name=cfg.name)
        self.device_stats = telemetry.DeviceStats() if cfg.telemetry else None
        # triage plane: its similarity dispatches bump their own stat
        # vector (sharing the engine's would race the vec handoff
        # across the two subsystems' locks); /metrics merges both
        self.triage_stats = telemetry.DeviceStats() if cfg.telemetry else None
        self.crash_index = CrashIndex(telemetry=self.triage_stats)
        self.crash_types: dict[str, int] = {}
        self._build_metrics()

        # the config `mesh` knob shards the engine's PC axis over N
        # devices (BASELINE config #4: device-resident global coverage
        # matrix with on-mesh merges); 0/1 keeps a single-device engine.
        # Under a pod topology (`mesh_hosts` > 1) mesh_from_config
        # brings up jax.distributed first and shards over THIS
        # process's addressable slice.
        from syzkaller_tpu.mesh.dist import mesh_from_config
        mesh = mesh_from_config(cfg)
        self.engine = CoverageEngine(
            npcs=cfg.npcs, ncalls=self.table.count,
            corpus_cap=cfg.corpus_cap, batch=cfg.flush_batch, mesh=mesh,
            telemetry=self.device_stats)
        if mesh is not None:
            # the triage similarity matmul rides the same mesh (report
            # batch row-sharded; labels bit-exact either way)
            self.crash_index.kernel.shard(mesh)
        # fleet observatory (observe/): the device time-series store
        # samples the stat vectors the fused dispatches already bump
        # (one rollup dispatch per second from the run loop, never per
        # exec), and the dispatch profiler wraps the engine's jitted
        # closures BEFORE any failover proxy so every backend's
        # dispatches are attributed
        self.tsdb = None
        self.dispatch_profiler = None
        if cfg.telemetry:
            from syzkaller_tpu.observe import DeviceTsdb, DispatchProfiler
            self.tsdb = DeviceTsdb(
                [self.device_stats, self.triage_stats],
                put=self.engine.put_replicated)
            self.dispatch_profiler = DispatchProfiler()
            self.dispatch_profiler.register_metrics(self.registry)
            self.dispatch_profiler.attach(self.engine)
        from syzkaller_tpu.observe import register_slo_gauges
        register_slo_gauges(self.registry, self)
        if cfg.backend_failover:
            # the resilience supervisor: device dispatch faults
            # quarantine the backend, migrate engine state to a
            # CPU-backed engine behind the same seams, and probe for
            # recovery with promotion back (BENCH_r03–r05 failure mode
            # made survivable MID-RUN)
            from syzkaller_tpu.resilience import ResilientEngine
            self.engine = ResilientEngine(
                self.engine, fallback_factory=self._cpu_engine_factory,
                registry=self.registry, on_swap=self._on_backend_swap)
        self.static_prios = P.calculate_priorities(self.table)
        self.engine.set_priorities(self.static_prios)
        self.enabled_names = cfg.enabled_calls(self.table)
        self.engine.set_enabled(
            [self.table.call_map[n].id for n in self.enabled_names])
        self.pcmap = PcMap(cfg.npcs)
        # device-resident half of the PcMap: the coalescer's fused
        # admission dispatch translates covers on device against this
        # sorted key mirror (zero-copy ingest plane); first-sight keys
        # are resolved host-side before dispatch (exact first-seen
        # order — snapshots and export_keys stay bit-exact)
        from syzkaller_tpu.fuzzer.pcmap import DeviceKeyMirror
        self.pc_mirror = DeviceKeyMirror(self.pcmap,
                                         put=self.engine.put_replicated)
        # async vmlinux PC-universe scan (ref cover.go:57-69 initAllCover):
        # pre-seeds the PcMap for restart-stable bitmap indices and feeds
        # the /cover line report
        self.cover_scan = None
        if cfg.vmlinux and os.path.exists(cfg.vmlinux):
            from syzkaller_tpu.manager.kcov import CoverScanner
            self.cover_scan = CoverScanner(cfg.vmlinux, pcmap=self.pcmap)

        def verify(data: bytes) -> bool:
            try:
                return len(P.deserialize(data, self.table).calls) > 0
            except P.DeserializeError:
                return False

        self.persistent = PersistentSet(
            os.path.join(cfg.workdir, "corpus"), verify,
            corrupt_counter=self._c_corpus_corrupt,
            persist_err_counter=self._c_corpus_persist_err)
        self.corpus: dict[bytes, CorpusItem] = {}
        self.candidates: deque[bytes] = deque()
        self._snapshot_triage = None    # restore fallback for crash state

        self.fuzzers: dict[str, FuzzerConn] = {}
        # legacy dict[str,int] facade over the registry: Poll payload
        # aggregation and manager/html.py keep their dict idioms while
        # every increment lands in a typed series
        self.stats = telemetry.StatsView(self.registry, aliases={
            "manager new inputs": self._c_new_inputs,
            "rejected inputs": self._c_rejected,
            "crashes": self._c_crashes,
        })
        self.start_time = time.time()
        self._mu = threading.Lock()
        self._admit_gate = AdmissionGate()
        self._stop = False
        self._last_prio_update = 0.0
        self._instances: dict[int, vm.Instance] = {}
        self._hub_client: "rpc.RpcClient | None" = None
        self._hub_synced: set[bytes] = set()
        # frontier-aware exchange v2: delta cursor into the PcMap's
        # append-only key order (blocks up to here are published) and
        # the block ids already sent, for hub-loss detection/resync
        self._hub_sketch_sent = 0
        self._hub_blocks_sent: set[int] = set()
        # cross-host trace stitching: sig -> (origin trace id, origin
        # manager) for programs pulled from the hub, so the local
        # admission span links back to the admitting span on the origin
        # manager (bounded like the idempotency window)
        self._hub_origins: "OrderedDict[bytes, tuple[str, str]]" = \
            OrderedDict()
        self._last_hub_sync_wall = 0.0
        self._repro_active: set[str] = set()
        self._repro_block = 0          # unique index block per repro job
        # ONE shared batched-bisection service + VM pool for every
        # crash (triage/scheduler.py), built lazily on the first repro
        self._repro_sched = None
        self._repro_oracle = None
        self._repro_mu = threading.Lock()
        self._crash_traces: dict[str, str] = {}   # cluster id -> trace id
        # RPC fault envelope: replayed side-effecting requests (a
        # retried NewInput whose first reply was lost) dedup against a
        # bounded window of recently-seen idempotency keys
        self._idem: "OrderedDict[str, dict]" = OrderedDict()
        self._idem_mu = threading.Lock()
        # inputs queued at a reaped connection, re-delivered to the
        # next fuzzer that connects (bounded)
        self._orphan_inputs: deque = deque()

        # decision-stream plane: Poll choice top-ups drain pre-drawn
        # megakernel blocks via the async prefetcher instead of issuing
        # their own sampling dispatch (the coalescer's admission-fused
        # ring stays primary while admissions flow); warm_after keeps
        # one-shot consumers on the cheap direct path
        from syzkaller_tpu.fuzzer.device_ct import DecisionStream
        self.dstream = DecisionStream(self.engine, per_row=64,
                                      telemetry=self.device_stats,
                                      warm_after=3)

        # campaign plane: assignment + decay-triggered rotation + the
        # syz_new_cov_per_1k_exec gauge family (global label always
        # registered, per-campaign labels when campaigns are
        # configured).  Each active campaign gets its OWN decision
        # stream over the shared engine — N concurrent steered
        # frontiers, one device bitmap — with the overlay applied as
        # fixed-shape operands (warm swaps compile nothing).
        from syzkaller_tpu.campaign import CampaignScheduler
        self.campaign_sched = CampaignScheduler(
            cfg.campaigns, rotation=cfg.campaign_rotation,
            min_execs=cfg.campaign_min_execs, registry=self.registry)
        self.campaign_sched.restore(cfg.workdir)
        self._campaigns: dict = {}            # name -> campaign.Campaign
        self._campaign_streams: dict = {}     # name -> DecisionStream
        self._camp_mu = threading.Lock()

        # crash-only restart: restore the newest valid snapshot
        # (engine bitmaps + corpus table + campaign EWMAs + frontier
        # views) and queue only the persistent-corpus TAIL admitted
        # after it as re-triage candidates; no snapshot → cold path,
        # the whole corpus replays (ref manager.go:124-157)
        from syzkaller_tpu.resilience import Checkpointer
        self.checkpointer = Checkpointer(
            self, interval=cfg.snapshot_interval, keep=cfg.snapshot_keep,
            registry=self.registry)
        # tiered corpus: the TierManager is created inside the restore
        # path because the warm store wants the v2 snapshot's segment
        # refs (if any) to pin what it expects to resurface
        self.tiers = None
        self._restore_state()
        # dedup state survives restarts: rebuild crash_types and the
        # cluster index from workdir/crashes/ before VMs come up (the
        # snapshot's cluster index is the fallback when the dirs are
        # gone — e.g. a workdir restored from the snapshot tree alone)
        self._rebuild_crash_state()

        # batched admission plane: concurrent NewInput RPCs coalesce
        # into fused device dispatches instead of paying one device
        # round-trip per input (round-2 verdict weak #5).  The queue is
        # BOUNDED: past admit_queue_cap (or admit_shed_deadline of
        # waiting) the oldest pending admission is shed with a "shed"
        # reply instead of growing the queue toward an OOM — fuzzers
        # degrade to local-only triage and back off.
        self.coalescer = None
        if cfg.admit_batch > 1:
            self.coalescer = self._make_coalescer()

        # VM fleet capacity: a resizable thread-per-instance pool (the
        # autopilot's scale/repair seam); start() sizes it to cfg.count
        self.vm_pool = vm.VmPool(self._vm_runner)

        # fleet autopilot: the closed control loop over the telemetry
        # plane — health state machines per component, typed
        # rate-limited actions through the recovery seams, circuit
        # breaker to observe-only.  Ticks ride the run loop.
        self.autopilot = None
        if cfg.autopilot:
            from syzkaller_tpu.autopilot import Autopilot
            self.autopilot = Autopilot.for_manager(self, cfg)

        self.server = rpc.RpcServer(*self._split_addr(cfg.rpc))
        self.server.register("Manager.Connect", self.rpc_connect)
        self.server.register("Manager.Check", self.rpc_check)
        self.server.register("Manager.Poll", self.rpc_poll)
        self.server.register("Manager.NewInput", self.rpc_new_input)
        self.server.register("Manager.Ping", self.rpc_ping)
        if cfg.telemetry:
            self.server.observer = self._rpc_observer
        self.rpc_port = self.server.addr[1]
        self.http_server = None

    @staticmethod
    def _split_addr(addr: str) -> tuple[str, int]:
        host, _, port = addr.rpartition(":")
        return host or "127.0.0.1", int(port or 0)

    def _make_coalescer(self):
        from syzkaller_tpu.manager.coalescer import AdmissionCoalescer
        return AdmissionCoalescer(
            self, max_batch=self.cfg.admit_batch,
            queue_cap=self.cfg.admit_queue_cap,
            shed_deadline=self.cfg.admit_shed_deadline)

    @property
    def vm_threads(self) -> "list[threading.Thread]":
        """Back-compat view over the pool's threads."""
        return self.vm_pool.threads()

    # -- resilience plane --------------------------------------------------

    def _cpu_engine_factory(self) -> CoverageEngine:
        """The degraded-mode engine the supervisor fails over to:
        same shapes as the primary, pinned to the CPU platform when
        the default platform is an accelerator (a 1-device CPU mesh
        places every array host-side), plain default placement when
        CPU already IS the platform.  No device stat vector — the
        quarantined backend owns that buffer."""
        mesh = None
        try:
            import jax
            if jax.default_backend() != "cpu":
                from syzkaller_tpu.cover.engine import pc_mesh
                mesh = pc_mesh(1, "cpu")
        except Exception:
            mesh = None
        return CoverageEngine(
            npcs=self.cfg.npcs, ncalls=self.table.count,
            corpus_cap=self.cfg.corpus_cap, batch=self.cfg.flush_batch,
            mesh=mesh, telemetry=None)

    def _on_backend_swap(self, degraded: bool) -> None:
        """Failover/promotion listener: every decision stream re-homes
        its cached device operands on the now-active engine and drops
        pre-drawn blocks (they were drawn on the other backend's PRNG
        chain); campaign overlays rebuild through the same epoch path
        so steered Polls keep flowing without a recompile."""
        # the PcMap mirror's cached key arrays live on the swapped-out
        # backend: drop them so the next admission re-homes the mirror
        self.pc_mirror.invalidate()
        self.dstream.rebind()
        with self._camp_mu:
            streams = list(self._campaign_streams.items())
        for name, s in streams:
            c = self._campaigns.get(name)
            if c is not None:
                try:
                    s.set_overlay(self.engine.make_overlay(
                        c.name, c.boost, c.enabled_ids))
                except Exception as e:
                    log.logf(0, "campaign %s overlay rebuild failed: %s",
                             name, e)
            s.rebind()

    def _restore_state(self) -> None:
        """Crash-only restart: newest valid snapshot in, then queue the
        persistent-corpus tail (programs admitted after the snapshot)
        as re-triage candidates.  Any failure falls back to the cold
        full-corpus replay — restore must never be able to brick a
        manager a crash couldn't."""
        from syzkaller_tpu.resilience import load_latest_snapshot
        st = None
        try:
            st = load_latest_snapshot(self.cfg.workdir)
        except Exception as e:
            log.logf(0, "snapshot scan failed (%s); cold replay", e)
        if st is None:
            self.candidates = deque(self.persistent.values())
            self._f_restore.labels(outcome="cold").inc()
            self._attach_tiers(None)
            return
        if st.corrupt_skipped:
            self._c_snapshot_corrupt.inc(st.corrupt_skipped)
        # shard-layout stamp: host-canonical arrays restore into any
        # mesh shape (import_state re-shards on ingest), but a layout
        # change is worth an operator-visible line
        snap_layout = getattr(st, "shard_layout", None) or {}
        snap_devs = int(snap_layout.get("devices", 1))
        cur_mesh = getattr(self.engine, "mesh", None)
        cur_devs = (int(np.prod(cur_mesh.devices.shape))
                    if cur_mesh is not None else 1)
        if snap_devs != cur_devs:
            log.logf(0, "snapshot shard layout %d device(s) -> current "
                     "mesh %d device(s); re-sharding on ingest",
                     snap_devs, cur_devs)
        try:
            # the PcMap key order first: restored bitmap indices mean
            # the PCs the crashed manager assigned them to.  Preseeding
            # an already-populated map (async vmlinux scan racing in)
            # can diverge the mapping — flag it loudly.
            keys = st.arrays.get("pcmap_keys")
            if keys is not None and len(keys):
                if len(self.pcmap):
                    log.logf(0, "WARNING: pcmap already has %d entries "
                             "before snapshot restore (vmlinux scan?); "
                             "restored indices may not be bit-stable",
                             len(self.pcmap))
                self.pcmap.preseed(np.asarray(keys, np.uint64))
            self.engine.import_state(st.engine_state)
            # config is authoritative for the enabled set across a
            # restart (the operator may have changed it); prios keep
            # the snapshotted dynamic state
            self.engine.set_enabled(
                [self.table.call_map[n].id for n in self.enabled_names])
        except Exception as e:
            log.logf(0, "snapshot %s rejected by engine (%s); cold "
                     "replay", os.path.basename(st.path), e)
            self.candidates = deque(self.persistent.values())
            self._f_restore.labels(outcome="cold").inc()
            self._attach_tiers(None)
            return
        # warm tier: the v2 snapshot names the segments it expects the
        # warm store to resurface; a v1 snapshot has no refs and the
        # store simply mounts whatever valid segments are on disk
        self._attach_tiers(getattr(st, "warm_segments", None) or None)
        restored_sigs: set[str] = set()
        missing = 0
        for it in st.corpus_items:
            sig_hex = it["sig"]
            data = self.persistent.entries.get(sig_hex)
            if data is None:
                missing += 1       # data lost pre-crash; bits stay in
                continue           # the frontier, program is gone
            restored_sigs.add(sig_hex)
            self.corpus[bytes.fromhex(sig_hex)] = CorpusItem(
                data=data, call=it["call"], call_index=int(it["ci"]),
                corpus_row=int(it["row"]))
        # the tail: persisted programs the snapshot predates — replay
        # ONLY these (measurably faster than the cold full replay)
        self.candidates = deque(
            data for sig_hex, data in self.persistent.entries.items()
            if sig_hex not in restored_sigs)
        self._g_tail.set(len(self.candidates))
        self.campaign_sched.import_state(st.campaign)
        for tag, (ids, data) in st.frontiers.items():
            try:
                self.engine.frontier_view(tag).import_blocks(ids, data)
            except Exception as e:
                log.logf(1, "frontier view %s restore failed: %s", tag, e)
        self._snapshot_triage = st
        # tsdb rings ride the same snapshot: a crash-only restart
        # resumes the retained series instead of a blank history
        if self.tsdb is not None and st.meta.get("tsdb"):
            try:
                self.tsdb.import_state(st.meta["tsdb"], st.arrays)
            except Exception as e:
                log.logf(1, "tsdb restore failed (fresh rings): %s", e)
        # resume the snapshot cadence from the RESTORED snapshot's
        # timestamp, not from process start: restarting from zero made
        # the cadence drift by one restart each crash (and left a
        # just-restored manager un-snapshotted for a full interval even
        # when the restored state was already nearly interval-old)
        self.checkpointer.seed_cadence(st.meta.get("created_at"))
        self._f_restore.labels(outcome="snapshot").inc()
        log.logf(0, "restored snapshot %s: corpus %d, tail %d candidates"
                 "%s", os.path.basename(st.path), len(self.corpus),
                 len(self.candidates),
                 f", {missing} missing from disk" if missing else "")

    def _attach_tiers(self, refs: "list[dict] | None") -> None:
        """Tiered corpus attach (config `corpus_tiers`): warm segment
        log at workdir/warm, eviction-victim demotion fused into the
        admission tick, contents-only promotion swaps.  `refs` are the
        v2 snapshot's expected-segment descriptors (None on cold start
        or a v1 snapshot); a missing/corrupt segment is counted, never
        fatal — warm rows degrade to cold replay."""
        if not self.cfg.corpus_tiers or self.tiers is not None:
            return
        try:
            from syzkaller_tpu.corpus import TierManager, WarmStore
            store = WarmStore(os.path.join(self.cfg.workdir, "warm"),
                              expect_refs=refs)
            self.tiers = TierManager(store, telemetry=self.device_stats)
            self.engine.attach_tiers(self.tiers)
            if store.corrupt_skipped or store.ref_mismatches:
                log.logf(0, "warm store mounted with %d corrupt segment(s)"
                         " skipped, %d snapshot ref(s) missing",
                         store.corrupt_skipped, store.ref_mismatches)
        except Exception as e:
            self.tiers = None
            log.logf(0, "tiered corpus attach failed (%s); running "
                     "untiered", e)

    # -- autopilot action seams --------------------------------------------

    def scale_vms(self, target: int) -> int:
        """Capacity seam: resize/repair the VM pool.  `resize` also
        respawns dead vm-loop threads below the target, so the same
        call serves elastic scaling AND lost-capacity repair.  Returns
        the applied target (clamped to the config's own VM bound)."""
        target = max(0, min(1000, int(target)))
        r = self.vm_pool.resize(target)
        if r["spawned"] or r["retired"]:
            log.logf(0, "vm pool -> %d (spawned %s, retired %s)",
                     target, r["spawned"], r["retired"])
        return target

    def rotate_campaign(self, frm: str, to: str) -> "list[str]":
        """Rotation seam: move every LIVE connection assigned to the
        wedged campaign `frm` toward `to`.  The new assignment rides
        each connection's next Poll response (the fuzzer swaps overlays
        through the decision-stream epoch path).  Connections reaped in
        the same tick are skipped — their assignment already returned
        to the scheduler pool, exactly once."""
        with self._mu:
            live = list(self.fuzzers)
        return self.campaign_sched.rotate_toward(frm, to, conns=live)

    def restart_component(self, name: str) -> None:
        """Restart seam: checkpoint first (the autopilot never restarts
        what it hasn't snapshotted), then crash-only-restart one wedged
        in-process component by swapping a fresh instance in BEFORE
        stopping the old one — consumers never observe a stopped
        component."""
        self.checkpointer.snapshot_now()
        if name == "dstream":
            from syzkaller_tpu.fuzzer.device_ct import DecisionStream
            old = self.dstream
            self.dstream = DecisionStream(self.engine, per_row=64,
                                          telemetry=self.device_stats,
                                          warm_after=3)
            if not old.stop():
                self._f_thread_leaks.labels(thread="decision-stream").inc()
        elif name == "coalescer":
            old = self.coalescer
            if self.cfg.admit_batch > 1:
                self.coalescer = self._make_coalescer()
            if old is not None and not old.stop():
                self._f_thread_leaks.labels(thread="coalescer").inc()
        else:
            raise ValueError(f"unknown restartable component {name!r}")
        log.logf(0, "component %s restarted (snapshot taken first)", name)

    def health_json(self) -> "tuple[int, dict]":
        """/healthz body: the autopilot's per-component health report
        (non-200 while anything is DEGRADED) when the control loop
        runs; a minimal backend liveness report otherwise."""
        if self.autopilot is not None:
            return self.autopilot.health_json()
        degraded = bool(getattr(self.engine, "degraded", False))
        return (503 if degraded else 200), {
            "status": "degraded" if degraded else "ok",
            "autopilot": "off",
            "components": {"backend": {
                "state": "DEGRADED" if degraded else "HEALTHY"}},
        }

    def _touch(self, name: str) -> None:
        """Heartbeat: every RPC from a fuzzer refreshes its liveness
        watermark (the reaper's clock)."""
        with self._mu:
            conn = self.fuzzers.get(name)
            if conn is not None:
                conn.last_seen = time.monotonic()

    def rpc_ping(self, params: dict) -> dict:
        """Connection heartbeat: liveness without a Poll's payload."""
        self._touch(params.get("name", "?"))
        return {}

    def reap_dead_conns(self, now: "float | None" = None) -> "list[str]":
        """Drop fuzzer connections silent past cfg.conn_timeout: their
        campaign assignment returns to the scheduler's pool and their
        undelivered input queue re-enters circulation (to the remaining
        fuzzers, or stashed for the next Connect).  The per-campaign
        decision streams are keyed by campaign, not connection, so
        their in-flight choice blocks simply serve the next assignee."""
        if self.cfg.conn_timeout <= 0:
            return []
        now = time.monotonic() if now is None else now
        orphaned: list = []
        with self._mu:
            dead = [n for n, c in self.fuzzers.items()
                    if now - c.last_seen > self.cfg.conn_timeout]
            for n in dead:
                orphaned.extend(self.fuzzers.pop(n).input_queue)
            if dead:
                survivors = list(self.fuzzers.values())
                for i, wire in enumerate(orphaned):
                    if survivors:
                        survivors[i % len(survivors)].input_queue.append(
                            wire)
                    elif len(self._orphan_inputs) < ORPHAN_INPUT_CAP:
                        self._orphan_inputs.append(wire)
        for n in dead:
            self.campaign_sched.drop(n)
            self._c_reaped.inc()
            log.logf(0, "reaped dead fuzzer connection %s (%d queued "
                     "inputs returned to the pool)", n, len(orphaned))
        return dead

    # -- telemetry ---------------------------------------------------------

    def _build_metrics(self) -> None:
        """Pre-register the core series so /metrics serves the full
        shape from the first scrape (dashboards key on series presence,
        not just values)."""
        r = self.registry
        self._c_inputs = r.counter(
            "syz_admission_inputs_total", "NewInput RPCs received")
        self._c_new_inputs = r.counter(
            "syz_admission_new_inputs_total",
            "inputs admitted into the global corpus")
        self._c_rejected = r.counter(
            "syz_admission_rejected_total",
            "inputs rejected by the device diff gate (no new signal)")
        self._c_crashes = r.counter("syz_crash_total", "VM crashes saved")
        self._c_coal_batches = r.counter(
            "syz_admission_batches_total", "coalescer fused dispatches")
        self._c_coal_inputs = r.counter(
            "syz_admission_coalesced_total",
            "inputs that shared a fused admission dispatch")
        self._c_choices_served = r.counter(
            "syz_choice_ring_served_total",
            "Poll choices served from the pre-drawn admission ring")
        self._c_choices_topup = r.counter(
            "syz_choice_topup_total",
            "Poll choices drawn by the direct sampling dispatch")
        self._f_rpc = r.counter(
            "syz_rpc_requests_total", "RPC requests by method",
            labels=("method",))
        self._h_rpc = r.histogram(
            "syz_rpc_request_seconds", "server-side RPC handling latency")
        self._f_vm_execs = r.counter(
            "syz_vm_execs_total", "per-VM executed programs (Poll deltas)",
            labels=("vm",))
        self._f_vm_rate = r.ewma(
            "syz_vm_exec_rate", "per-VM exec throughput (EWMA, 1/s)",
            labels=("vm",), tau=60.0)
        self._e_exec_rate = r.ewma(
            "syz_exec_rate", "fleet exec throughput (EWMA, 1/s)", tau=60.0)
        self._e_admit_rate = r.ewma(
            "syz_admission_rate", "corpus admission rate (EWMA, 1/s)",
            tau=60.0)
        for m in ("Manager.Connect", "Manager.Check", "Manager.Poll",
                  "Manager.NewInput"):
            self._f_rpc.labels(method=m)
        r.gauge("syz_uptime_seconds", "manager uptime",
                fn=lambda: time.time() - self.start_time)
        r.gauge("syz_corpus_size", "programs in the global corpus",
                fn=lambda: len(self.corpus))
        r.gauge("syz_corpus_candidates", "re-triage candidates pending",
                fn=lambda: len(self.candidates))
        r.gauge("syz_fuzzers_connected", "connected fuzzer processes",
                fn=lambda: len(self.fuzzers))
        r.gauge("syz_engine_corpus_rows", "device corpus matrix rows",
                fn=lambda: self.engine.corpus_len)
        r.gauge("syz_crash_types", "distinct crash titles seen",
                fn=lambda: len(self.crash_types))
        self._f_vm_outcomes = r.counter(
            "syz_vm_outcomes_total", "VM run outcomes by class",
            labels=("outcome",))
        # crash-intelligence plane (triage/)
        r.gauge("syz_crash_clusters",
                "distinct crash clusters (signature kernel dedup)",
                fn=lambda: len(self.crash_index))
        self._c_triage_assigned = r.counter(
            "syz_triage_assigned_total",
            "crash reports assigned to clusters")
        self._c_repro_rounds = r.counter(
            "syz_repro_rounds_total",
            "batched-bisection VM-pool rounds")
        self._c_repro_tests = r.counter(
            "syz_repro_tests_total",
            "candidate tests executed by the repro service")
        self._f_repro_jobs = r.counter(
            "syz_repro_jobs_total", "repro jobs by outcome",
            labels=("outcome",))
        for o in ("found", "failed", "error"):
            self._f_repro_jobs.labels(outcome=o)
        r.gauge("syz_repro_jobs_active",
                "repro jobs queued or bisecting",
                fn=lambda: (self._repro_sched.depth
                            if self._repro_sched is not None else 0))
        # resilience plane (fault tolerance)
        self._c_corpus_corrupt = r.counter(
            "syz_corpus_load_corrupt_total",
            "corrupt/unreadable persistent-corpus entries skipped at load")
        self._c_corpus_persist_err = r.counter(
            "syz_corpus_persist_errors_total",
            "persistent-corpus writes that failed (entry kept in memory)")
        self._f_restore = r.counter(
            "syz_restore_total", "manager state restores by path",
            labels=("outcome",))
        for o in ("snapshot", "cold"):
            self._f_restore.labels(outcome=o)
        self._c_snapshot_corrupt = r.counter(
            "syz_snapshot_corrupt_total",
            "snapshot files skipped as corrupt/truncated at restore")
        self._g_tail = r.gauge(
            "syz_restore_tail_candidates",
            "persistent-corpus tail queued for replay after the last "
            "snapshot restore")
        self._c_replays = r.counter(
            "syz_rpc_replays_total",
            "replayed RPC requests deduped by idempotency key")
        self._c_reaped = r.counter(
            "syz_conn_reaped_total",
            "dead fuzzer connections reaped (assignment + queued "
            "inputs returned to the pool)")
        self._f_thread_leaks = r.counter(
            "syz_thread_leak_total",
            "shutdown joins that abandoned a wedged thread",
            labels=("thread",))
        # overload protection + autopilot capacity series
        self._c_shed = r.counter(
            "syz_admission_shed_total",
            "pending admissions shed under overload (bounded queue + "
            "deadline); callers got the 'shed' reply and degraded to "
            "local-only triage")
        r.gauge("syz_admission_queue_depth",
                "admissions waiting in the coalescer queue",
                fn=lambda: (float(len(self.coalescer._q))
                            if self.coalescer is not None else 0.0))
        r.gauge("syz_vm_pool_target", "intended VM pool size",
                fn=lambda: float(self.vm_pool.target))
        r.gauge("syz_vm_pool_live", "vm-loop threads alive",
                fn=lambda: float(self.vm_pool.live))

    def _rpc_observer(self, method: str, seconds: float,
                      params: dict) -> None:
        """RpcServer tap: per-method counters/latency + completed spans
        for traced Connect/Check/Poll requests (NewInput traces are
        recorded by the admission path with their full hop chain)."""
        self._f_rpc.labels(method=method or "?").inc()
        self._h_rpc.observe(seconds)
        if method != "Manager.NewInput":
            ctx = telemetry.SpanContext.from_wire(params.get("trace"))
            if ctx is not None:
                ctx.mark_transit()
                self.tracer.record(ctx, final_hop=f"manager:{method}",
                                   dur=seconds)

    def telemetry_snapshot(self, traces: int = 64) -> dict:
        """JSON-ready snapshot of the registry, device stat vectors
        (engine + triage, merged), and recent trace spans (the
        /telemetry endpoint + persistence body).  The trace window is
        sized so the fleet console can stitch cross-host lineage — an
        origin span must still be visible here when the pulling
        manager's linked span shows up on another host."""
        return expo.snapshot([self.registry],
                             [self.device_stats, self.triage_stats],
                             self.tracer, traces=traces)

    def metrics_text(self) -> str:
        """Prometheus text exposition (the /metrics endpoint body)."""
        return expo.prometheus_text(
            [self.registry], [self.device_stats, self.triage_stats])

    # -- RPC handlers (ref manager.go:552-656) -----------------------------

    def rpc_connect(self, params: dict) -> dict:
        name = params.get("name", "?")
        with self._mu:
            conn = self.fuzzers[name] = FuzzerConn(name=name)
            # inputs orphaned by reaped connections re-enter delivery
            while self._orphan_inputs:
                conn.input_queue.append(self._orphan_inputs.popleft())
            cands = self._pop_candidates(CANDIDATES_PER_POLL)
        camp = self.campaign_sched.assign(name)
        log.logf(0, "fuzzer %s connected%s", name,
                 f" (campaign {camp})" if camp else "")
        resp = {
            "prios": rpc.b64(np.asarray(self.engine.prios, np.float32)
                             .tobytes()),
            "enabled": self.enabled_names,
            "candidates": cands,
        }
        if camp is not None:
            resp["campaign"] = camp
        return resp

    def rpc_check(self, params: dict) -> dict:
        name = params.get("name", "?")
        with self._mu:
            conn = self.fuzzers.get(name)
            if conn is not None:
                conn.calls = params.get("calls", [])
                conn.last_seen = time.monotonic()
        log.logf(0, "fuzzer %s: %d enabled calls after closure",
                 name, len(params.get("calls", [])))
        return {}

    def _pop_candidates(self, n: int) -> list[dict]:
        out = []
        while self.candidates and len(out) < n:
            data = self.candidates.popleft()
            out.append({"prog": rpc.b64(data), "minimized": True})
        return out

    def rpc_poll(self, params: dict) -> dict:
        name = params.get("name", "?")
        for k, v in (params.get("stats") or {}).items():
            self.stats.bump(k, int(v))
            if k == "exec total" and int(v) > 0:
                # per-VM exec throughput: absolute counters + EWMA rates
                self._f_vm_execs.labels(vm=name).inc(int(v))
                self._f_vm_rate.labels(vm=name).add(int(v))
                self._e_exec_rate.add(int(v))
                # campaign productivity: the denominator of
                # new_cov_per_1k_exec (global + this conn's campaign)
                self.campaign_sched.note_execs(name, int(v))
        # decay-triggered rotation (cheap: two EWMA reads); the new
        # assignment rides this Poll response so the fuzzer swaps its
        # overlay via the invalidate() epoch path before the next gen
        self.campaign_sched.maybe_rotate(name)
        camp = self.campaign_sched.current(name)
        with self._mu:
            conn = self.fuzzers.get(name)
            if conn is None:
                conn = self.fuzzers[name] = FuzzerConn(name=name)
            conn.last_seen = time.monotonic()
            inputs = []
            while conn.input_queue and len(inputs) < INPUTS_PER_POLL:
                inputs.append(conn.input_queue.popleft())
            cands = (self._pop_candidates(CANDIDATES_PER_POLL)
                     if params.get("need_candidates") else [])
        if camp is not None:
            # steered connection: choices come from the campaign's own
            # decision stream (overlay applied inside the megakernel) —
            # the flat admission ring would leak out-of-campaign calls
            t0 = time.monotonic()
            choices = self._campaign_stream(camp).take(-1,
                                                       CHOICES_PER_POLL)
            if self.device_stats is not None:
                self.device_stats.observe("choice_draw_latency",
                                          time.monotonic() - t0)
            self._c_choices_topup.inc(CHOICES_PER_POLL)
            return {"candidates": cands, "new_inputs": inputs,
                    "choices": choices, "campaign": camp}
        # choices come from the coalescer's pre-drawn device ring when
        # admissions are flowing (the draws fused into admission
        # dispatches); the direct sampling dispatch only tops up the
        # remainder when the ring runs dry
        choices = (self.coalescer.pop_choices(CHOICES_PER_POLL)
                   if self.coalescer is not None else [])
        self._c_choices_served.inc(len(choices))
        short = CHOICES_PER_POLL - len(choices)
        if short > 0:
            t0 = time.monotonic()
            # top-up from the decision stream's pre-drawn blocks (its
            # underrun path is one fixed-shape direct draw, so the
            # retired per-poll sampling dispatch never comes back as a
            # compile treadmill — syz-vet retrace pass)
            choices += self.dstream.take(-1, short)
            if self.device_stats is not None:
                self.device_stats.observe("choice_draw_latency",
                                          time.monotonic() - t0)
            self._c_choices_topup.inc(short)
        return {"candidates": cands, "new_inputs": inputs,
                "choices": choices}

    # -- campaign plane ----------------------------------------------------

    def _campaign(self, name: str):
        """Lazily-loaded campaign runtime (description parse + glob
        resolution happen OUTSIDE _camp_mu — file I/O under a lock is
        a syz-vet P0 — with a double-checked insert)."""
        with self._camp_mu:
            c = self._campaigns.get(name)
        if c is not None:
            return c
        from syzkaller_tpu.campaign import load_campaign
        c = load_campaign(name, self.table)
        with self._camp_mu:
            return self._campaigns.setdefault(name, c)

    def _campaign_stream(self, name: str):
        """The campaign's decision stream over the shared engine,
        created on first use: overlay operands built once
        (make_overlay device_puts two small buffers), then every swap
        and refill moves contents only."""
        with self._camp_mu:
            s = self._campaign_streams.get(name)
        if s is not None:
            return s
        from syzkaller_tpu.fuzzer.device_ct import DecisionStream
        c = self._campaign(name)
        ov = self.engine.make_overlay(name, c.boost, c.enabled_ids)
        s = DecisionStream(self.engine, per_row=64,
                           telemetry=self.device_stats, warm_after=3)
        s.set_overlay(ov)
        with self._camp_mu:
            exist = self._campaign_streams.get(name)
            if exist is not None:
                stale = s
            else:
                self._campaign_streams[name] = s
                stale = None
        if stale is not None:
            stale.stop()
            return self._campaign_streams[name]
        return s

    def rpc_new_input(self, params: dict) -> dict:
        name = params.get("name", "?")
        self._touch(name)
        # RPC fault envelope: a retried NewInput whose first reply was
        # lost replays with the same idempotency key — dedup it here so
        # the side effects (admission counters, broadcast) run once
        idem = params.get("idem")
        if idem is not None:
            with self._idem_mu:
                hit = self._idem.get(idem)
            if hit is not None:
                self._c_replays.inc()
                return hit
        result = self._new_input(params)
        if idem is not None:
            with self._idem_mu:
                self._idem[idem] = result
                while len(self._idem) > IDEM_CACHE:
                    self._idem.popitem(last=False)
        return result

    def _new_input(self, params: dict) -> dict:
        name = params.get("name", "?")
        data = rpc.unb64(params.get("prog", ""))
        call = params.get("call", "")
        call_index = int(params.get("call_index", 0))
        cover = np.array(params.get("cover", []), dtype=np.uint64)
        sig = hashlib.sha1(data).digest()
        meta = self.table.call_map.get(call)
        if meta is None:
            return {}
        self._c_inputs.inc()
        trace = telemetry.SpanContext.from_wire(params.get("trace"))
        if trace is not None:
            trace.mark_transit()
            # cross-host stitching: a program pulled from the hub links
            # its local admission span to the admitting span on the
            # origin manager (A -> hub -> B keeps one lineage chain);
            # done here so the serial AND coalesced paths both record it
            with self._mu:
                origin = self._hub_origins.get(sig)
            if origin is not None and origin[0] not in trace.links:
                trace.links.append(origin[0])
                trace.add_hop(f"hub:from {origin[1] or '?'}", 0.0)
        if self.coalescer is not None:
            # batched admission plane: enqueue and block on the ticket;
            # the drainer aggregates concurrent NewInputs into one fused
            # dispatch (gate + merge + pre-drawn Poll choices)
            return self.coalescer.submit(
                name=name, sig=sig, data=data, call=call,
                call_index=call_index, call_id=meta.id, cover=cover,
                wire_prog=params.get("prog"),
                wire_cover=params.get("cover", []), trace=trace)
        return self._admit_serial(name, sig, data, call, call_index,
                                  meta.id, cover, params, trace)

    def _admit_serial(self, name: str, sig: bytes, data: bytes, call: str,
                      call_index: int, call_id: int, cover: np.ndarray,
                      params: dict, trace=None) -> dict:
        """The admit_batch<=1 path.  Concurrent duplicates both pass
        the dict check, but gate + merge run as ONE fused device call
        serialized inside the engine, so exactly one admits — the
        dispatch itself needs no manager lock.  The admission gate only
        excludes corpus maintenance (row compaction would remap the row
        id recorded below mid-flight)."""
        t_start = time.monotonic()
        with self._admit_gate.admitting():
            with self._mu:
                if sig in self.corpus:
                    return {}
            idx, valid = self.pcmap.map_batch([cover], K=256)
            t_disp = time.monotonic()
            has_new, rows, new_bits = self.engine.admit_if_new(
                np.array([call_id], np.int32), idx, valid,
                with_new_bits=True)
            if self.device_stats is not None:
                self.device_stats.observe("admission_latency",
                                          time.monotonic() - t_start)
            if trace is not None:
                trace.add_hop("manager:device dispatch",
                              time.monotonic() - t_disp)
                self.tracer.record(trace, final_hop="manager:admit",
                                   dur=time.monotonic() - t_start)
            if not has_new[0]:
                self._c_rejected.inc()
                return {}
            self.campaign_sched.note_new_cov(name, int(new_bits[0]),
                                             sig_hex=sig.hex())
            row = (int(rows[0]) if rows is not None and len(rows) else -1)
            with self._mu:
                self.corpus[sig] = CorpusItem(
                    data=data, call=call, call_index=call_index,
                    corpus_row=row,
                    trace_id=trace.trace_id if trace is not None else "",
                    blocks=_wire_blocks(cover))
                self._c_new_inputs.inc()
                self._e_admit_rate.add(1)
                # broadcast to the other fuzzers (ref manager.go:596-621)
                wire = {"prog": params.get("prog"), "call": call,
                        "call_index": call_index,
                        "cover": params.get("cover", [])}
                for other, conn in self.fuzzers.items():
                    if other != name:
                        conn.input_queue.append(wire)
        self.persistent.add(data)
        self._maybe_update_prios()
        return {}

    def _record_rejected(self, n: int = 1) -> None:
        self._c_rejected.inc(n)

    def _record_admit_rate(self, n: int) -> None:
        """Batch stat bookkeeping for the coalescer's drainer: one
        counter bump + one EWMA fold per fused dispatch, keeping the
        typed stat plane off the per-input hot path."""
        self._c_new_inputs.inc(n)
        self._e_admit_rate.add(n)

    def _record_admitted(self, p, row: int) -> None:
        """Corpus/broadcast bookkeeping for one admitted input (counts
        are folded per batch by _record_admit_rate).  Caller (the
        coalescer's drainer) holds _mu inside the admission gate."""
        self.corpus[p.sig] = CorpusItem(
            data=p.data, call=p.call, call_index=p.call_index,
            corpus_row=row,
            trace_id=p.trace.trace_id if p.trace is not None else "",
            blocks=_wire_blocks(p.cover))
        wire = {"prog": p.wire_prog, "call": p.call,
                "call_index": p.call_index, "cover": p.wire_cover}
        for other, conn in self.fuzzers.items():
            if other != p.name:
                conn.input_queue.append(wire)

    def _maybe_update_prios(self) -> None:
        """Periodic dynamic-priority refresh: one MXU matmul over the
        corpus occurrence matrix (ref CalculatePriorities, device-side)."""
        now = time.time()
        with self._mu:
            if now - self._last_prio_update < 30.0 or not self.corpus:
                return
            self._last_prio_update = now
            items = list(self.corpus.values())
        call_mat = np.zeros((len(items), self.table.count), np.float32)
        for i, item in enumerate(items):
            try:
                for cname in P.call_set(item.data):
                    m = self.table.call_map.get(cname)
                    if m is not None:
                        call_mat[i, m.id] = 1.0
            except Exception:
                continue
        self.engine.set_priorities(self.static_prios, call_mat)
        # drop pre-drawn decisions conditioned on the old matrix; the
        # stream schedules its redraw eagerly off-thread, so the next
        # Poll top-up finds a warm ring instead of a cold refill
        self.dstream.invalidate()
        with self._camp_mu:
            streams = list(self._campaign_streams.values())
        for s in streams:
            s.invalidate()

    # -- hub federation (ref manager.go:658-736) ---------------------------

    def _hub_sketch_delta(self) -> "tuple[str, bool]":
        """(wire sketch, reset) for this sync: the covered-block delta
        since the last publish, derived from the PcMap's append-only
        first-seen key order.  Sends a full snapshot (reset) on the
        first publish after (re)connect so a restored manager or a hub
        that lost our sketch re-aligns instead of staying stale."""
        from syzkaller_tpu.mesh.sketch import blocks_of, encode_blocks
        keys = self.pcmap.export_keys()
        reset = self._hub_sketch_sent == 0 and len(self._hub_blocks_sent) == 0
        fresh = blocks_of(keys if reset else keys[self._hub_sketch_sent:])
        self._hub_sketch_sent = len(keys)
        new = [int(b) for b in fresh if int(b) not in self._hub_blocks_sent]
        self._hub_blocks_sent.update(new)
        if not new and not reset:
            return "", False
        import numpy as _np
        return encode_blocks(_np.array(sorted(new), _np.uint64)), reset

    def hub_sync_once(self) -> None:
        """Push corpus programs the hub hasn't seen (with their
        covered-block sets) and this manager's sketch delta; pull fresh
        ones as candidates (coverage state is rebuilt locally by
        re-triage).  The hub withholds programs whose every block we
        already cover — exchange v2 ships only plausible new signal.
        Pulls are drained in batches while the hub reports more
        pending, so a freshly-joined manager converges in one sync."""
        if self._hub_client is None:
            self._hub_client = rpc.RpcClient(self.cfg.hub_addr)
            self._hub_client.call("Hub.Connect", {
                "name": self.cfg.name, "key": self.cfg.hub_key,
                "fresh": len(self.corpus) == 0,
                "calls": self.enabled_names})
            # new connection: re-publish the full sketch next
            self._hub_sketch_sent = 0
            self._hub_blocks_sent = set()
        with self._mu:
            fresh_items = [it for sig, it in self.corpus.items()
                           if sig not in self._hub_synced]
            new = [it.data for it in fresh_items]
            blocks = [it.blocks for it in fresh_items]
            for sig in self.corpus:
                self._hub_synced.add(sig)
        req = {"name": self.cfg.name, "key": self.cfg.hub_key,
               "add": [rpc.b64(d) for d in new]}
        # cross-host stitching: each pushed program's admitting trace id
        # rides beside it (parallel to `add`), so the hub can hand the
        # lineage to whichever manager pulls it later
        req["traces"] = [it.trace_id for it in fresh_items]
        if self.cfg.hub_sketch:
            req["blocks"] = blocks
            sketch, reset = self._hub_sketch_delta()
            if sketch:
                req["sketch"] = sketch
            if reset:
                req["sketch_reset"] = True
        pulled = filtered = 0
        rounds = 0
        while True:
            r = self._hub_client.call("Hub.Sync", req)
            filtered += int(r.get("filtered", 0))
            wire_traces = r.get("traces") or []
            for i, pd in enumerate(r.get("progs", [])):
                data = rpc.unb64(pd)
                sig = hashlib.sha1(data).digest()
                origin = wire_traces[i] if i < len(wire_traces) else None
                tid = (origin or {}).get("trace", "")
                omgr = (origin or {}).get("manager", "")
                with self._mu:
                    if tid:
                        # remember the origin span for the admission-
                        # time link, bounded like the idem window
                        self._hub_origins[sig] = (tid, omgr)
                        while len(self._hub_origins) > IDEM_CACHE:
                            self._hub_origins.popitem(last=False)
                    if sig in self.corpus:
                        continue
                    self.candidates.append(data)
                    pulled += 1
                if tid:
                    # pull-time lineage span: the cross-host chain is
                    # visible in /telemetry even before (or without)
                    # the replayed program re-admitting locally
                    ctx = self.tracer.new_trace(origin=self.cfg.name)
                    ctx.links.append(tid)
                    ctx.add_hop(f"hub:shipped from {omgr or '?'}", 0.0)
                    self.tracer.record(
                        ctx, final_hop="manager:candidate", dur=0.0)
            covered = r.get("covered")
            if self.cfg.hub_sketch and covered is not None \
                    and covered < len(self._hub_blocks_sent):
                # the hub lost (part of) our sketch — snapshot-resync
                # on the next sync instead of drifting into stale FPs
                log.logf(0, "hub sync: covered echo %d < sent %d; "
                         "scheduling sketch resync", int(covered),
                         len(self._hub_blocks_sent))
                self._hub_sketch_sent = 0
                self._hub_blocks_sent = set()
            rounds += 1
            if not int(r.get("more", 0)) or rounds >= 50:
                break
            # drain the backlog: pushes/sketch went with round one
            req = {"name": self.cfg.name, "key": self.cfg.hub_key,
                   "add": []}
        self._last_hub_sync_wall = time.time()
        if new or pulled or filtered:
            log.logf(0, "hub sync: sent %d, received %d "
                     "(%d sketch-filtered, %d more)", len(new), pulled,
                     filtered, int(r.get("more", 0)))

    def hub_sync_loop(self) -> None:
        interval = max(1, int(round(self.cfg.hub_sync_interval)))
        while not self._stop:
            try:
                self.hub_sync_once()
            except Exception as e:
                log.logf(0, "hub sync failed: %s", e)
                if self._hub_client is not None:
                    self._hub_client.close()
                    self._hub_client = None
            for _ in range(interval):
                if self._stop:
                    return
                time.sleep(min(1.0, self.cfg.hub_sync_interval))

    # -- corpus minimization (ref manager.go:504-550) ----------------------

    def minimize_corpus(self) -> int:
        """Greedy set cover on device; drops subsumed corpus programs and
        compacts the device matrix so admission capacity is reclaimed.
        Exclusive side of the admission gate: in-flight admissions
        drain first, none start while rows are being remapped."""
        with self._admit_gate.maintenance():
            if not self.corpus or self.engine.corpus_len == 0:
                return 0
            keep_mask = self.engine.minimize_corpus()
            mapping = self.engine.compact_corpus(keep_mask)
            removed = 0
            with self._mu:
                for sig, item in list(self.corpus.items()):
                    new_row = mapping.get(item.corpus_row)
                    if item.corpus_row >= 0 and new_row is None:
                        del self.corpus[sig]
                        removed += 1
                    elif new_row is not None:
                        item.corpus_row = new_row
                keep_data = [i.data for i in self.corpus.values()]
        if removed:
            self.persistent.minimize(keep_data)
            log.logf(0, "corpus minimized: removed %d programs", removed)
        return removed

    # -- crash persistence (ref manager.go:408-502) ------------------------

    def _rebuild_crash_state(self) -> None:
        """Restart path: rebuild crash_types and the cluster index from
        workdir/crashes/, so the syz_crash_types/syz_crash_clusters
        gauges and dedup state survive manager restarts instead of
        resetting to empty.  Dir names ARE cluster ids (and the legacy
        per-title sha1 dirs use the same scheme), so ids stay stable
        across the restart."""
        entries = []
        try:
            dirs = sorted(os.listdir(self.crashdir))
        except OSError:
            return
        for name in dirs:
            d = os.path.join(self.crashdir, name)
            desc = os.path.join(d, "description")
            if not os.path.isfile(desc):
                continue
            try:
                with open(desc) as f:
                    title = f.read().strip()
                count = len([x for x in os.listdir(d)
                             if x.startswith("log")])
                frames: list[str] = []
                rep0 = os.path.join(d, "report0")
                if os.path.isfile(rep0):
                    with open(rep0, "rb") as f:
                        frames = extract_frames(f.read())
            except OSError:
                continue
            if not title:
                continue
            entries.append((name, title, frames, max(1, count)))
            self.crash_types[title] = \
                self.crash_types.get(title, 0) + max(1, count)
        if entries:
            self.crash_index.rebuild(entries)
            log.logf(0, "crash state rebuilt: %d clusters, %d titles",
                     len(entries), len(self.crash_types))
        elif self._snapshot_triage is not None \
                and self._snapshot_triage.triage:
            # crash dirs gone but the snapshot carries the cluster
            # index (workdir restored from the snapshot tree alone):
            # restore representatives so dedup stays stable
            st = self._snapshot_triage
            self.crash_index.import_state(st.triage,
                                          st.arrays["triage_feats"])
            for _cid, title, count in st.triage:
                self.crash_types[title] = \
                    self.crash_types.get(title, 0) + int(count)
            log.logf(0, "crash state restored from snapshot: %d clusters",
                     len(st.triage))

    def _input_links(self, outcome) -> "list[str]":
        """Lineage: trace ids of corpus inputs whose programs appear in
        the crashing console log — the crash trace links back to the
        admissions that produced its suspects."""
        links: list[str] = []
        try:
            for entry in P.parse_log(outcome.output, self.table):
                sig = hashlib.sha1(P.serialize(entry.prog)).digest()
                with self._mu:
                    item = self.corpus.get(sig)
                if item is not None and item.trace_id \
                        and item.trace_id not in links:
                    links.append(item.trace_id)
                if len(links) >= 4:
                    break
        except Exception:
            pass
        return links

    def save_crash(self, outcome, vm_name: str = "") -> str:
        """Crash persistence keyed by CLUSTER: the signature kernel
        assigns the report to a cluster (title n-grams + stack frames,
        device-batched similarity), replacing title-string-equality
        dedup — noisy variants of one bug share a dir while distinct
        bugs keep separate ones.  The crash dir is the cluster id; its
        `description` keeps the founding title."""
        title = outcome.title
        frames = (outcome.report.frames
                  if outcome.report is not None else [])
        trace = self.tracer.new_trace()
        trace.links = self._input_links(outcome)
        t0 = time.monotonic()
        cid = self.crash_index.assign([(title, frames)])[0]
        self._c_triage_assigned.inc()
        # cluster-aware rotation signal: the crashing VM's campaign
        # gets the cluster attributed — campaigns whose clusters keep
        # GROWING are what the autopilot rotates toward
        self.campaign_sched.note_cluster(vm_name, cid)
        d = os.path.join(self.crashdir, cid)
        os.makedirs(d, exist_ok=True)
        desc = os.path.join(d, "description")
        if not os.path.exists(desc):
            with open(desc, "w") as f:
                f.write(title + "\n")
        for i in range(MAX_CRASH_LOGS):
            logp = os.path.join(d, f"log{i}")
            if not os.path.exists(logp):
                with open(logp, "wb") as f:
                    f.write(outcome.output)
                if outcome.report is not None:
                    text = outcome.report.text
                    if self.cfg.vmlinux:
                        try:
                            text = symbolize_report(text, self.cfg.vmlinux)
                        except Exception as e:
                            log.logf(1, "symbolization failed: %s", e)
                    with open(os.path.join(d, f"report{i}"), "wb") as f:
                        f.write(text)
                break
        with self._mu:
            self.crash_types[title] = self.crash_types.get(title, 0) + 1
            self._crash_traces[cid] = trace.trace_id
        self._c_crashes.inc()
        self.tracer.record(trace, final_hop=f"triage:cluster {cid[:12]}",
                           dur=time.monotonic() - t0)
        log.logf(0, "vm crash: %s (cluster %s)", title, cid[:12])
        return d

    # -- auto-repro (ref manager.go:269-280, 468-502) ----------------------

    REPRO_VMS = 4          # instances in the shared repro pool (ref :232)

    def maybe_schedule_repro(self, outcome, crash_dir: str) -> None:
        """Queue the crash into the batched-bisection service: ONE
        shared VM pool runs rounds that mix candidate tests from every
        active crash, so repro throughput scales with pool workers
        instead of crash count (the legacy path bisected one crash per
        dedicated thread+VM-block, serially)."""
        if not self.cfg.reproduce or outcome.report is None:
            return
        title = outcome.title
        with self._mu:
            if title in self._repro_active or \
                    os.path.exists(os.path.join(crash_dir, "repro.prog")):
                return
            self._repro_active.add(title)
            link = self._crash_traces.get(os.path.basename(crash_dir))
        sched = self._repro_service()
        if sched is None:
            log.logf(0, "repro for %r skipped: no spare devices", title)
            with self._mu:
                self._repro_active.discard(title)
            return
        if not sched.submit(outcome.output, title, crash_dir,
                            links=(link,) if link else ()):
            with self._mu:
                self._repro_active.discard(title)

    def _repro_service(self):
        """The lazily-built shared scheduler + VM oracle pool."""
        with self._repro_mu:
            if self._repro_sched is not None:
                return self._repro_sched
            indices = self._repro_indices()
            if indices is None:
                return None
            from syzkaller_tpu import repro as repro_mod
            from syzkaller_tpu.triage import ReproScheduler

            self._repro_oracle = repro_mod.VmOracle(
                self.cfg, self.table, indices,
                suppressions=self.cfg.compiled_suppressions())
            self._repro_sched = ReproScheduler(
                self._repro_oracle, self.table,
                on_done=self._repro_done, tracer=self.tracer,
                metrics={"rounds": self._c_repro_rounds,
                         "tests": self._c_repro_tests,
                         "jobs": self._f_repro_jobs})
            return self._repro_sched

    def _repro_done(self, title: str, crash_dir: str, result,
                    job) -> None:
        """Scheduler completion hook: persist artifacts next to the
        crash and release the per-title dedup slot."""
        try:
            if result is not None and result.prog is not None:
                with open(os.path.join(crash_dir, "repro.prog"), "wb") as f:
                    f.write(P.serialize(result.prog))
                if result.c_repro:
                    with open(os.path.join(crash_dir, "repro.cprog"),
                              "w") as f:
                        f.write(result.c_repro)
                log.logf(0, "repro for %r: %d calls in %d rounds%s",
                         title, len(result.prog.calls), job.rounds,
                         ", C repro" if result.c_repro else "")
            else:
                log.logf(0, "repro for %r failed (%d rounds)", title,
                         job.rounds)
        except Exception as e:
            log.logf(0, "repro artifacts for %r failed: %s", title, e)
        finally:
            with self._mu:
                self._repro_active.discard(title)

    def _repro_indices(self) -> "list[int] | None":
        """Instance indices for the shared repro pool.  Backends that
        can mint instances (qemu/gce/local) get a reserved block above
        the fleet, so the pool never shares workdirs/ports/prog files
        with fuzzing VMs; fixed-device backends (adb) can only use
        spare configured devices beyond the fleet — none spare means no
        auto-repro."""
        n = min(self.REPRO_VMS, max(1, self.cfg.count))
        if self.cfg.type == "adb":
            ndev = len([d for d in self.cfg.devices.split(",") if d.strip()])
            spare = list(range(self.cfg.count, min(ndev,
                                                   self.cfg.count + n)))
            return spare or None
        with self._mu:
            block = self._repro_block
            self._repro_block += 1
        base = self.cfg.count + 100 + block * self.REPRO_VMS
        return [base + i for i in range(n)]

    # -- VM loop (ref manager.go:230-341) ----------------------------------

    def fuzzer_cmdline(self, index: int, manager_addr: str) -> str:
        a = [sys.executable, "-m", "syzkaller_tpu.fuzzer.fuzzer",
             "-name", f"vm{index}", "-manager", manager_addr,
             "-procs", str(self.cfg.procs),
             "-descriptions", self.cfg.descriptions,
             "-output", "stdout", "-seed", str(index)]
        if self.cfg.sandbox != "none":
            a += ["-sandbox", self.cfg.sandbox]
        if self.cfg.threaded:
            a.append("-threaded")
        if self.cfg.collide:
            a.append("-collide")
        if not self.cfg.fake_cover:
            a.append("-real-cover")
        if self.cfg.leak:
            a.append("-leak")
        if self.cfg.fuzzer_device:
            # per-VM fuzzer batches are a fraction of the manager's own
            # admission batch: one VM sees 1/count of the exec stream
            a += ["-device", "-npcs", str(self.cfg.npcs),
                  "-flush-batch", str(max(8, self.cfg.flush_batch // 8)),
                  "-corpus-cap", str(self.cfg.corpus_cap)]
            if self.cfg.fuzzer_synth:
                # device-resident program synthesis rides the device
                # signal plane (synth tables + program ring per proc)
                a.append("-synth")
        return " ".join(shlex.quote(x) for x in a)

    def vm_loop(self, index: int) -> None:
        """Back-compat entry: one VM loop with no retire signal."""
        self._vm_runner(index, threading.Event())

    def _vm_runner(self, index: int, retire: threading.Event) -> None:
        """The VmPool runner: create-run-monitor-reboot until manager
        stop or pool retirement.  Retirement takes effect at the next
        reboot boundary (a VM run in flight finishes its cycle)."""
        suppressions = self.cfg.compiled_suppressions()
        while not self._stop and not retire.is_set():
            inst = None
            try:
                inst = vm.create(self.cfg.type, self.cfg, index)
                with self._mu:
                    self._instances[index] = inst
                addr = inst.forward(self.rpc_port)
                cmd = self.fuzzer_cmdline(index, addr)
                handle = inst.run(cmd, timeout=VM_RUN_TIME)
                outcome = monitor_execution(handle, VM_RUN_TIME,
                                            ignores=suppressions,
                                            outcomes=self._f_vm_outcomes)
                handle.stop()
                # shutdown kills the fuzzer: its EOF is not a crash
                if outcome.crashed and not self._stop:
                    crash_dir = self.save_crash(outcome,
                                                vm_name=f"vm{index}")
                    self.maybe_schedule_repro(outcome, crash_dir)
            except Exception as e:
                log.logf(0, "vm-%d error: %s", index, e)
                time.sleep(5.0)
            finally:
                with self._mu:
                    self._instances.pop(index, None)
                if inst is not None:
                    try:
                        inst.close()
                    except Exception:
                        pass
            with self._mu:
                self.fuzzers.pop(f"vm{index}", None)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.server.serve_background()
        if self.cfg.http:
            from syzkaller_tpu.manager import html
            self.http_server = html.serve(self, *self._split_addr(self.cfg.http))
        self.vm_pool.resize(self.cfg.count)
        if self.cfg.hub_addr:
            threading.Thread(target=self.hub_sync_loop, daemon=True).start()
        log.logf(0, "manager up: rpc :%d, %d %s VM(s), %d corpus candidates",
                 self.rpc_port, self.cfg.count, self.cfg.type,
                 len(self.candidates))

    def persist_telemetry(self) -> None:
        """One snapshot to workdir/telemetry.json(+.jsonl) — next to the
        corpus, so bench and post-mortems read metric trajectories.
        Folds the device stat vector into host cumulatives (int32
        roll-over protection) via the engine-locked flush."""
        try:
            self.engine.telemetry_flush(reset=True)
            expo.persist_snapshot(self.cfg.workdir, self.telemetry_snapshot())
        except Exception as e:
            log.logf(1, "telemetry persistence failed: %s", e)
        self.campaign_sched.persist(self.cfg.workdir)

    def run(self, duration: "float | None" = None) -> None:
        self.start()
        deadline = time.time() + duration if duration else None
        last_stats = time.time()
        last_minimize = time.time()
        last_telemetry = time.time()
        last_reap = time.time()
        try:
            while not self._stop:
                time.sleep(1.0)
                if deadline and time.time() > deadline:
                    break
                if self.tsdb is not None:
                    # one fused rollup dispatch per interval (wall-
                    # clock, never per exec): the retained series the
                    # console sparklines and SLO windows read
                    self.tsdb.maybe_sample()
                if time.time() - last_stats > 10.0:
                    last_stats = time.time()
                    execs = self.stats.get("exec total", 0)
                    crashes = self.stats.get("crashes", 0)
                    log.logf(0, "executed %d programs, %d crashes, "
                             "corpus %d, cover %d",
                             execs, crashes, len(self.corpus),
                             int(self.engine.cover_counts().sum()))
                if self.cfg.telemetry and \
                        time.time() - last_telemetry > self.cfg.telemetry_interval:
                    last_telemetry = time.time()
                    self.persist_telemetry()
                if time.time() - last_minimize > 300.0:
                    last_minimize = time.time()
                    self.minimize_corpus()
                # resilience cadences: crash-only snapshots and
                # dead-conn reaping stay on their own clocks
                self.checkpointer.maybe_snapshot()
                if time.time() - last_reap > 5.0:
                    last_reap = time.time()
                    self.reap_dead_conns()
                if self.autopilot is not None:
                    # the control loop owns recovery: backend probing
                    # rides its PROMOTE action (rate-limited) instead
                    # of the bare probe cadence below
                    self.autopilot.maybe_tick()
                else:
                    probe = getattr(self.engine, "maybe_probe", None)
                    if probe is not None:
                        probe()
        finally:
            self.stop()

    def stop(self) -> None:
        self._stop = True
        if self.coalescer is not None:
            if not self.coalescer.stop():
                self._f_thread_leaks.labels(thread="coalescer").inc()
        if not self.dstream.stop():
            self._f_thread_leaks.labels(thread="decision-stream").inc()
        with self._camp_mu:
            camp_streams = list(self._campaign_streams.values())
            self._campaign_streams.clear()
        for s in camp_streams:
            if not s.stop():
                self._f_thread_leaks.labels(thread="decision-stream").inc()
        self.campaign_sched.persist(self.cfg.workdir)
        with self._repro_mu:
            sched, oracle = self._repro_sched, self._repro_oracle
            self._repro_sched = self._repro_oracle = None
        if sched is not None:
            sched.stop()
        if oracle is not None:
            oracle.close()
        if self.cfg.telemetry:
            self.persist_telemetry()     # final post-mortem snapshot
        with self._mu:
            instances = list(self._instances.values())
        for inst in instances:
            try:
                inst.close()  # kills the fuzzer; monitor sees EOF and exits
            except Exception:
                pass
        self.server.close()
        if self.http_server is not None:
            self.http_server.shutdown()
        # a wedged VM thread must not hang shutdown forever — but
        # silently abandoning it hid real bugs; count + log instead
        leaked = self.vm_pool.stop_all(timeout=10.0)
        if leaked:
            self._f_thread_leaks.labels(thread="vm-loop").inc(leaked)
            log.logf(0, "shutdown leaked %d wedged vm-loop thread(s)",
                     leaked)
