"""The manager tier: orchestration, persistence, stats UI."""

from syzkaller_tpu.manager.config import Config, ConfigError, load, loads  # noqa: F401
from syzkaller_tpu.manager.manager import Manager  # noqa: F401
from syzkaller_tpu.manager.persistent import PersistentSet  # noqa: F401
