"""Kernel coverage reports: vmlinux PC-universe scan + line HTML.

Capability parity with reference syz-manager/cover.go: objdump scan of
`__sanitizer_cov_trace_pc` callsites = the set of all coverable PCs
(cover.go:274-312), readelf section-offset recovery for 32→64-bit PC
restoration (cover.go:199-230), addr2line symbolization of covered and
coverable-but-uncovered PCs, and a per-file covered/uncovered line HTML
report (cover.go:71-143).

TPU-native extra: the scanned PC universe pre-seeds `PcMap` so coverage
bitmap indices are *stable across restarts* (round-1 verdict: indices
depended on PC arrival order, reshuffling the mapping under the
persisted corpus) and real kernels never fall into the hashed overflow
region.
"""

from __future__ import annotations

import bisect
import html as html_mod
import os
import re
import subprocess
import threading

from syzkaller_tpu.report.symbolizer import Symbolizer, parse_nm
from syzkaller_tpu.utils import log

_CALL_RE = re.compile(
    rb"^\s*([0-9a-f]+):\s+call\S*\s+[0-9a-f]+ <__sanitizer_cov_trace_pc>")


def scan_cover_pcs(binary: str) -> list[int]:
    """All PCs with a `call __sanitizer_cov_trace_pc` in `binary` —
    the compiler instruments every basic block, so this is the universe
    of coverable PCs (ref cover.go:274-312's coveredPCs)."""
    proc = subprocess.Popen(
        ["objdump", "-d", "--no-show-raw-insn", binary],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
    pcs: list[int] = []
    assert proc.stdout is not None
    try:
        for line in proc.stdout:
            m = _CALL_RE.match(line)
            if m is not None:
                pcs.append(int(m.group(1), 16))
    finally:
        proc.stdout.close()
        proc.wait()
    pcs.sort()
    return pcs


def vm_offset(binary: str) -> int:
    """High 32 bits of the kernel's PROGBITS load addresses: cover is
    reported as 32-bit truncated PCs; this restores them
    (ref cover.go:199-230 getVmOffset + cover.RestorePC)."""
    out = subprocess.run(["readelf", "-SW", binary], capture_output=True,
                         check=True).stdout.decode(errors="replace")
    addr = 0
    for line in out.splitlines():
        parts = line.split()
        for i, p in enumerate(parts):
            if p != "PROGBITS":
                continue
            try:
                v = int(parts[i + 1], 16)
            except (IndexError, ValueError):
                continue
            if v == 0:
                continue
            hi = v >> 32
            if addr == 0:
                addr = hi
            elif addr != hi:
                raise ValueError("different section offsets in one binary")
    return addr


def restore_pc(pc32: int, base: int) -> int:
    return (base << 32) | (pc32 & 0xFFFFFFFF)


class CoverScanner:
    """Async objdump scan (20-30s on a real vmlinux, ref cover.go:57-69)
    with a ready event; optionally pre-seeds a PcMap on completion."""

    def __init__(self, binary: str, pcmap=None):
        self.binary = binary
        self.pcs: list[int] = []
        self.ready = threading.Event()
        self._pcmap = pcmap
        threading.Thread(target=self._scan, daemon=True).start()

    def _scan(self) -> None:
        try:
            self.pcs = scan_cover_pcs(self.binary)
            if self._pcmap is not None and self.pcs:
                # executor reports 32-bit truncated PCs — seed with those
                seed = sorted({pc & 0xFFFFFFFF for pc in self.pcs})
                spilled = self._pcmap.preseed(seed)
                if spilled:
                    # the universe exceeds direct capacity: the tail
                    # aliases into the tiny hashed overflow region —
                    # loud warning with a concretely sufficient size
                    # (direct entries now held + the spill + overflow)
                    need = (len(self._pcmap) + spilled
                            + self._pcmap.overflow)
                    log.logf(0, "WARNING: %d of %d scanned PCs spilled "
                             "into the %d-slot hashed overflow region — "
                             "coverage for them will alias.  Raise the "
                             "`npcs` config to the next power of two "
                             ">= %d for full direct mapping",
                             spilled, len(seed), self._pcmap.overflow,
                             need)
            log.logf(0, "cover scan: %d coverable PCs in %s",
                     len(self.pcs), self.binary)
        except (OSError, subprocess.SubprocessError) as e:
            log.logf(0, "cover scan of %s failed: %s", self.binary, e)
        finally:
            self.ready.set()


def _pcs_in_covered_funcs(symbols, all_pcs: list[int],
                          covered: list[int]) -> list[int]:
    """All coverable PCs inside functions containing a covered PC
    (ref cover.go allPcsInFuncs): shows uncovered lines only for code
    the fuzzer actually reached into, keeping reports focused."""
    spans = sorted((s.addr, s.addr + s.size)
                   for syms in symbols.values() for s in syms if s.size)
    out: set[int] = set()
    for pc in covered:
        i = bisect.bisect_right(spans, (pc, 1 << 64)) - 1
        if i < 0 or not (spans[i][0] <= pc < spans[i][1]):
            continue
        lo = bisect.bisect_left(all_pcs, spans[i][0])
        hi = bisect.bisect_right(all_pcs, spans[i][1])
        out.update(all_pcs[lo:hi])
    return sorted(out)


def generate_cover_html(vmlinux: str, covered_pcs: "list[int]",
                        all_pcs: "list[int] | None" = None) -> str:
    """Per-file line coverage HTML (ref cover.go:71-143).  `covered_pcs`
    are full 64-bit PCs; `all_pcs` the scanned universe (scanned here if
    None).  Files whose sources are missing degrade to line tables."""
    if not covered_pcs:
        raise ValueError("no coverage data available")
    if all_pcs is None:
        all_pcs = scan_cover_pcs(vmlinux)
    symbols = parse_nm(vmlinux)
    uncovered_pcs = _pcs_in_covered_funcs(symbols, all_pcs, covered_pcs)
    sym = Symbolizer(vmlinux)
    try:
        files: dict[str, dict[int, bool]] = {}
        covset = set(covered_pcs)
        for pc, is_cov in ([(p, True) for p in covered_pcs]
                           + [(p, False) for p in uncovered_pcs
                              if p not in covset]):
            frames = sym.symbolize(pc - 1)
            for f in frames:
                if not f.file or f.file.startswith("?"):
                    continue
                lines = files.setdefault(f.file, {})
                lines[f.line] = lines.get(f.line, False) or is_cov
    finally:
        sym.close()

    prefix = os.path.commonprefix([f for f in files]) if len(files) > 1 else ""
    parts = ["<style>body{font-family:monospace} "
             ".cov{background:#c0f0c0} .unc{background:#f0c0c0}</style>"]
    for fname in sorted(files):
        lines = files[fname]
        ncov = sum(1 for v in lines.values() if v)
        title = fname[len(prefix):] if prefix else fname
        parts.append(f"<h3>{html_mod.escape(title)} "
                     f"({ncov}/{len(lines)} lines covered)</h3><pre>")
        try:
            with open(fname, errors="replace") as f:
                src = f.read().splitlines()
        except OSError:
            for ln in sorted(lines):
                cls = "cov" if lines[ln] else "unc"
                parts.append(f"<span class='{cls}'>line {ln}</span>")
            parts.append("</pre>")
            continue
        for i, text in enumerate(src, start=1):
            esc = html_mod.escape(text)
            if i in lines:
                cls = "cov" if lines[i] else "unc"
                parts.append(f"<span class='{cls}'>{esc}</span>")
            else:
                parts.append(esc)
        parts.append("</pre>")
    return "\n".join(parts)
