"""CLI: python -m syzkaller_tpu.manager -config manager.cfg"""

import argparse

from syzkaller_tpu.manager import config as config_mod
from syzkaller_tpu.manager.manager import Manager
from syzkaller_tpu.utils import log


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-config", required=True)
    ap.add_argument("-duration", type=float, default=None,
                    help="seconds to run (default: forever)")
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)
    log.set_verbosity(args.v)
    log.enable_log_caching()
    cfg = config_mod.load(args.config)
    Manager(cfg).run(args.duration)
    # Skip interpreter teardown: in-flight RPC handler threads inside
    # device calls make the TPU runtime abort on normal exit.
    import os

    os._exit(0)


if __name__ == "__main__":
    main()
