"""Manager configuration: strict JSON config with defaults + validation.

Capability parity with reference config/config.go: enumerated fields
with unknown-field rejection (:292-346), defaulting and per-VM-type
validation (:80-181), syscall enable/disable with '*' globs (:183-229),
and builtin crash suppressions (:231-259).
"""

from __future__ import annotations

import fnmatch
import json
import re
from dataclasses import asdict, dataclass, field

from syzkaller_tpu.sys.table import SyscallTable


class ConfigError(ValueError):
    """Configuration rejected.  Subclasses ValueError so callers (and
    tests) that guard config-shaped failures with the broader type —
    e.g. `pc_mesh` refusing a mesh larger than the addressable device
    slice — keep working."""


@dataclass
class Config:
    name: str = "syzkaller-tpu"
    http: str = "127.0.0.1:0"          # stats UI address ("" = off)
    rpc: str = "127.0.0.1:0"           # fuzzer RPC bind address
    workdir: str = "./workdir"
    vmlinux: str = ""                  # for symbolization / real coverage
    type: str = "local"                # VM adapter (vm registry key)
    count: int = 1                     # VMs
    procs: int = 1                     # executor procs per VM
    sandbox: str = "none"              # none/setuid/namespace
    cover: bool = True
    fake_cover: bool = True            # synthetic signal when no KCOV
    leak: bool = False
    threaded: bool = False
    collide: bool = False
    descriptions: str = "all"          # description set for the table
    enable_syscalls: list = field(default_factory=list)
    disable_syscalls: list = field(default_factory=list)
    suppressions: list = field(default_factory=list)
    npcs: int = 1 << 16                # coverage bitmap size (PC axis)
    corpus_cap: int = 1 << 14
    flush_batch: int = 256
    admit_batch: int = 64              # NewInput coalescer batch size
    #                                    (<=1 = serial per-input admission)
    fuzzer_device: bool = False        # fuzzers run signal diffs on device
    fuzzer_synth: bool = False         # fuzzers assemble programs on
    #                                    device (synth_block megakernel +
    #                                    device→executor program ring);
    #                                    requires fuzzer_device
    telemetry: bool = True             # metrics registry + device stat
    #                                    vector + /metrics + trace spans
    telemetry_interval: float = 60.0   # snapshot persistence period (s)
    mesh: int = 0                      # shard the PC axis over N devices
    #                                    (0/1 = single-device engine;
    #                                    BASELINE config #4's device mesh)
    mesh_platform: str = ""            # pin mesh devices to a platform
    #                                    ("cpu" = virtual-device mesh)
    # pod-scale mesh plane (multi-process topology)
    mesh_hosts: int = 1                # manager processes in the pod
    #                                    slice (jax.distributed world
    #                                    size); 1 = single-process mesh
    mesh_devices_per_host: int = 0     # devices each process addresses
    #                                    (0 = derive mesh / mesh_hosts);
    #                                    the engine shards over THIS
    #                                    process's slice only
    campaigns: list = field(default_factory=list)
    #                                  # stateful-subsystem campaigns to
    #                                    rotate fuzzer connections over
    #                                    (names of descriptions/campaigns/
    #                                    *.campaign; [] = flat fuzzing)
    campaign_rotation: float = 0.0     # rotate a connection when its
    #                                    campaign's new_cov_per_1k_exec
    #                                    EWMA decays below this
    #                                    (0 = never rotate)
    campaign_min_execs: int = 2000     # rotation arms only after this
    #                                    many execs under the campaign
    # tiered corpus (hot device tables / warm mmap'd segment log /
    # cold persistent corpus)
    corpus_tiers: bool = False         # attach a TierManager: over-cap
    #                                    admissions demote eviction-kernel
    #                                    victims to workdir/warm through
    #                                    the fused tick instead of falling
    #                                    back to the unfused admit path;
    #                                    warm rows promote back by
    #                                    contents-only swaps (zero warm
    #                                    recompiles)
    # resilience plane (fault tolerance)
    snapshot_interval: float = 300.0   # crash-only state snapshot cadence
    #                                    (workdir/snapshots/; 0 = off —
    #                                    restart falls back to the cold
    #                                    full-corpus replay)
    snapshot_keep: int = 3             # newest snapshots retained
    backend_failover: bool = True      # wrap the cover engine in the
    #                                    ResilientEngine supervisor:
    #                                    device-flap → CPU fallback
    #                                    mid-run, probe + promote back
    conn_timeout: float = 120.0        # reap fuzzer connections silent
    #                                    this long: campaign assignment
    #                                    and queued inputs return to the
    #                                    pool (0 = never reap)
    # fleet autopilot (closed-loop control plane)
    autopilot: bool = True             # run the supervisor loop in the
    #                                    manager run loop: health state
    #                                    machines over /metrics + typed
    #                                    rate-limited recovery actions
    autopilot_interval: float = 5.0    # control-loop tick cadence (s)
    autopilot_min_vms: int = 0         # elastic scale-down floor
    #                                    (0 = scale-down disabled)
    autopilot_max_vms: int = 0         # elastic scale-up ceiling
    #                                    (0 = scale-up disabled; capacity
    #                                    REPAIR to target is always on)
    autopilot_actions_per_min: float = 6.0
    #                                  # token-bucket refill per action
    #                                    class (restart-storm limiter)
    autopilot_burst: int = 2           # token-bucket burst capacity
    autopilot_cooldown: float = 10.0   # min spacing between actions of
    #                                    one class (s)
    # admission overload protection (backpressure)
    admit_queue_cap: int = 4096        # bounded coalescer queue: beyond
    #                                    this, the OLDEST pending
    #                                    admission is shed with a "shed"
    #                                    reply (0 = unbounded)
    admit_shed_deadline: float = 2.0   # pending admissions older than
    #                                    this are shed at drain time
    #                                    (0 = no deadline shedding)
    # VM-type specific (qemu)
    kernel: str = ""
    image: str = ""
    initrd: str = ""
    cmdline: str = ""
    sshkey: str = ""
    qemu: str = ""
    mem: int = 1024
    cpu: int = 1
    image_9p: bool = False
    boot_timeout: float = 600.0
    # VM-type specific (lkvm)
    lkvm: str = ""                     # lkvm binary override
    # VM-type specific (adb)
    devices: str = ""                  # comma-separated device serials
    console: str = ""                  # USB serial console (/dev/ttyUSB*)
    adb: str = ""                      # adb binary override
    # VM-type specific (gce)
    gce_image: str = ""
    gce_zone: str = ""
    machine_type: str = ""
    gcloud: str = ""
    # repro
    reproduce: bool = True
    # federation (syz-hub)
    hub_addr: str = ""
    hub_key: str = ""
    hub_sync_interval: float = 60.0    # Hub.Sync cadence in seconds
    hub_sketch: bool = True            # publish the covered-block
    #                                    sketch so the hub ships only
    #                                    programs plausibly carrying
    #                                    new signal (False = naive full
    #                                    exchange)

    _BUILTIN_SUPPRESSIONS = [
        rb"panic: failed to start executor binary",
        rb"panic: executor failed: pthread_create failed",
        rb"panic: failed to create temp dir",
        rb"Out of memory: Kill process .* \(syz-fuzzer\)",
        rb"lowmemorykiller: Killing 'syz-fuzzer'",
    ]

    def compiled_suppressions(self) -> list:
        pats = [re.compile(p) for p in self._BUILTIN_SUPPRESSIONS]
        for s in self.suppressions:
            pats.append(re.compile(s.encode() if isinstance(s, str) else s))
        return pats

    def validate(self) -> None:
        from syzkaller_tpu.vm import types as vm_types

        # count=0 = no managed VMs: external fuzzers attach over RPC
        # (the chaos harness and hub-only deployments); ref
        # config.go:137-138 caps the top end
        if not 0 <= self.count <= 1000:
            raise ConfigError(f"invalid count {self.count} (0..1000)")
        if not 1 <= self.procs <= 32:     # ref config.go:147-151
            raise ConfigError(f"invalid procs {self.procs} (1..32)")
        if self.type not in vm_types():
            raise ConfigError(f"unknown VM type {self.type!r}")
        if self.sandbox not in ("none", "setuid", "namespace"):
            raise ConfigError(f"unknown sandbox {self.sandbox!r}")
        if self.type == "qemu" and not (self.kernel or self.image):
            raise ConfigError("qemu requires kernel or image")
        if self.type == "adb":
            devs = [d for d in self.devices.split(",") if d.strip()]
            if not devs:
                raise ConfigError("adb requires devices")
            if self.count > len(devs):
                raise ConfigError(f"count {self.count} > {len(devs)} devices")
        if self.type == "gce" and not self.gce_image:
            raise ConfigError("gce requires gce_image")
        if self.type in ("lkvm", "kvm") and not self.kernel:
            raise ConfigError("lkvm requires kernel")
        if self.mesh < 0:
            raise ConfigError(f"invalid mesh {self.mesh}")
        if self.mesh_hosts < 1:
            raise ConfigError(
                f"invalid mesh_hosts {self.mesh_hosts} (>= 1)")
        if self.mesh_devices_per_host < 0:
            raise ConfigError(
                f"invalid mesh_devices_per_host "
                f"{self.mesh_devices_per_host}")
        if self.mesh_hosts > 1 or self.mesh_devices_per_host:
            if self.mesh < 2:
                raise ConfigError(
                    "mesh_hosts/mesh_devices_per_host require mesh >= 2")
            if self.mesh_devices_per_host:
                if self.mesh != self.mesh_hosts * self.mesh_devices_per_host:
                    raise ConfigError(
                        f"mesh {self.mesh} != mesh_hosts {self.mesh_hosts}"
                        f" * mesh_devices_per_host "
                        f"{self.mesh_devices_per_host}")
            elif self.mesh % self.mesh_hosts:
                raise ConfigError(
                    f"mesh {self.mesh} not divisible by mesh_hosts "
                    f"{self.mesh_hosts}; set mesh_devices_per_host "
                    "explicitly for uneven slices")
        if self.hub_sync_interval <= 0:
            raise ConfigError(
                f"invalid hub_sync_interval {self.hub_sync_interval}")
        if not 0 <= self.admit_batch <= 4096:
            raise ConfigError(
                f"invalid admit_batch {self.admit_batch} (0..4096)")
        if self.telemetry_interval <= 0:
            raise ConfigError(
                f"invalid telemetry_interval {self.telemetry_interval}")
        # campaign knobs: an unknown campaign name is a STARTUP error —
        # silently degrading to flat mode would defeat the whole point
        # of configuring a steered run.  Pure file listing (no table
        # compile, no accelerator init).
        if self.campaigns:
            from syzkaller_tpu.sys.campaigns import available_campaigns
            have = set(available_campaigns())
            unknown = [c for c in self.campaigns if c not in have]
            if unknown:
                raise ConfigError(
                    f"unknown campaigns {unknown} (have: {sorted(have)})")
            if len(set(self.campaigns)) != len(self.campaigns):
                raise ConfigError(
                    f"duplicate campaign names in {self.campaigns}")
        if self.campaign_rotation < 0:
            raise ConfigError(
                f"invalid campaign_rotation {self.campaign_rotation}")
        if self.campaign_rotation > 0 and not self.campaigns:
            raise ConfigError(
                "campaign_rotation set but no campaigns configured")
        if self.campaign_min_execs < 0:
            raise ConfigError(
                f"invalid campaign_min_execs {self.campaign_min_execs}")
        if self.snapshot_interval < 0:
            raise ConfigError(
                f"invalid snapshot_interval {self.snapshot_interval}")
        if self.snapshot_keep < 1:
            raise ConfigError(
                f"invalid snapshot_keep {self.snapshot_keep} (>= 1)")
        if self.conn_timeout < 0:
            raise ConfigError(
                f"invalid conn_timeout {self.conn_timeout}")
        if self.autopilot_interval <= 0:
            raise ConfigError(
                f"invalid autopilot_interval {self.autopilot_interval}")
        if not 0 <= self.autopilot_min_vms <= 1000:
            raise ConfigError(
                f"invalid autopilot_min_vms {self.autopilot_min_vms}")
        if not 0 <= self.autopilot_max_vms <= 1000:
            raise ConfigError(
                f"invalid autopilot_max_vms {self.autopilot_max_vms}")
        if 0 < self.autopilot_max_vms < self.autopilot_min_vms:
            raise ConfigError(
                f"autopilot_min_vms {self.autopilot_min_vms} > "
                f"autopilot_max_vms {self.autopilot_max_vms}")
        if self.autopilot_actions_per_min <= 0:
            raise ConfigError(
                "invalid autopilot_actions_per_min "
                f"{self.autopilot_actions_per_min}")
        if self.autopilot_burst < 1:
            raise ConfigError(
                f"invalid autopilot_burst {self.autopilot_burst} (>= 1)")
        if self.autopilot_cooldown < 0:
            raise ConfigError(
                f"invalid autopilot_cooldown {self.autopilot_cooldown}")
        if self.admit_queue_cap < 0:
            raise ConfigError(
                f"invalid admit_queue_cap {self.admit_queue_cap}")
        if self.admit_shed_deadline < 0:
            raise ConfigError(
                f"invalid admit_shed_deadline {self.admit_shed_deadline}")
        # NOTE: device availability for `mesh` is checked when the
        # manager builds the engine (cover.engine.pc_mesh raises) —
        # config linting must not initialize an accelerator runtime.

    def enabled_calls(self, table: SyscallTable) -> list[str]:
        """Apply enable/disable globs (ref config.go:183-229)."""
        names = [c.name for c in table.calls]
        if self.enable_syscalls:
            enabled = set()
            for pat in self.enable_syscalls:
                hits = fnmatch.filter(names, pat)
                if not hits:
                    raise ConfigError(f"enable_syscalls: {pat!r} matches nothing")
                enabled.update(hits)
        else:
            enabled = set(names)
        for pat in self.disable_syscalls:
            enabled -= set(fnmatch.filter(names, pat))
        return sorted(enabled)


def load(path: str) -> Config:
    with open(path) as f:
        return loads(f.read())


def loads(text: str) -> Config:
    data = json.loads(text)
    known = set(Config.__dataclass_fields__)
    unknown = set(data) - known
    if unknown:
        raise ConfigError(f"unknown config fields: {sorted(unknown)}")
    cfg = Config(**data)
    cfg.validate()
    return cfg
