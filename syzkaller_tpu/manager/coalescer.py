"""Manager-side admission coalescer: batched, pipelined NewInput.

The serial admission path holds the manager's admission lock across one
host↔device round-trip PER input (`rpc_new_input`), which serializes the
whole fleet's admission plane — the same fixed-dispatch-cost economics
AFL-style fuzzers and batched-inference servers both exploit.  Here
concurrent `Manager.NewInput` RPC handler threads enqueue into an
admission queue and block on a per-input ticket (the submit/resolve
pattern of fuzzer/device_signal.py); a drainer thread aggregates up to
`max_batch` pending inputs, maps them through the vectorized PcMap in
ONE call, and issues ONE fused device dispatch that (a) runs the
dedup-safe diff-vs-corpus gate for the whole batch — sequenced
on-device in submission order, so the serial path's TOCTOU guarantee
(two concurrent duplicates admit exactly once) is preserved exactly —
(b) merges admitted rows into the corpus matrix, and (c) draws a batch
of ChoiceTable decisions into a pre-drawn ring that feeds Poll
responses without their own `sample_next_calls` dispatch.

The wire protocol and admission semantics are byte-identical to the
serial path: callers see the same empty reply, duplicates and
no-new-signal inputs count as "rejected inputs", admitted inputs
broadcast to the other fuzzers and persist to disk.

Overload protection: the queue is BOUNDED (`queue_cap`) with
deadline-based load shedding (`shed_deadline`).  When concurrent
NewInputs outrun the drain rate, the OLDEST pending admission is shed —
resolved immediately with `{"shed": True}` and counted in
`syz_admission_shed_total` — instead of growing the queue toward an
OOM or blocking callers unboundedly.  Shed callers (fuzzers) keep the
input in their local corpus and degrade to local-only triage with
backoff, so overload degrades throughput gracefully: fresh inputs keep
flowing at the drain rate, p99 admit latency stays bounded by the
deadline, and nothing blocks forever.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from syzkaller_tpu.utils import log
from syzkaller_tpu.utils.shapes import pow2_bucket


@dataclass
class _Pending:
    name: str
    sig: bytes
    data: bytes
    call: str
    call_index: int
    call_id: int
    cover: np.ndarray
    wire_prog: str
    wire_cover: list
    trace: object = None          # telemetry.trace.SpanContext | None
    enqueued: float = field(default_factory=time.monotonic)
    done: threading.Event = field(default_factory=threading.Event)
    result: dict = field(default_factory=dict)


class AdmissionCoalescer:
    """Batches concurrent NewInput admissions into fused device steps."""

    # PC cap per admission cover (matches the serial path's map_batch K)
    K = 256
    # smallest padded shapes: dispatch shapes are pow2-bucketed so the
    # compiled-shape set stays O(log^2) while small batches don't pay
    # full-batch kernel cost (on CPU-class backends per-row work, not
    # dispatch overhead, dominates)
    MIN_B, MIN_K = 8, 32

    # the reply a shed admission resolves with: the fuzzer keeps the
    # input local-only and backs off deliveries
    SHED_REPLY = {"shed": True}

    def __init__(self, manager, max_batch: int = 64,
                 choices_per_step: int = 256, choice_ring_cap: int = 4096,
                 gather_ms: float = 1.0, queue_cap: int = 0,
                 shed_deadline: float = 0.0):
        self.mgr = manager
        self.max_batch = max_batch
        self.choices_per_step = choices_per_step
        self.choice_ring_cap = choice_ring_cap
        self.gather_ms = gather_ms
        self.queue_cap = int(queue_cap)
        self.shed_deadline = float(shed_deadline)
        self._q: deque[_Pending] = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._choices: deque[int] = deque()
        self._choice_mu = threading.Lock()
        self.stat_batches = 0
        self.stat_coalesced = 0          # inputs that shared a dispatch
        self.stat_shed = 0               # admissions shed under overload
        self._thread = threading.Thread(target=self._drain_loop,
                                        name="admission-coalescer",
                                        daemon=True)
        self._thread.start()

    # -- RPC-handler side --------------------------------------------------

    def submit(self, name: str, sig: bytes, data: bytes, call: str,
               call_index: int, call_id: int, cover: np.ndarray,
               wire_prog: str, wire_cover: list, trace=None) -> dict:
        """Enqueue one admission and block until its batch resolves.
        Called from many RPC handler threads concurrently."""
        p = _Pending(name=name, sig=sig, data=data, call=call,
                     call_index=call_index, call_id=call_id, cover=cover,
                     wire_prog=wire_prog, wire_cover=wire_cover,
                     trace=trace)
        shed: "list[_Pending]" = []
        with self._cv:
            if self._stop:
                return {}
            # bounded queue: shed the OLDEST pending admissions to make
            # room (they have waited longest and are most likely past
            # any useful deadline) instead of growing without bound
            while self.queue_cap > 0 and len(self._q) >= self.queue_cap:
                shed.append(self._q.popleft())
            self._q.append(p)
            self._cv.notify()
        self._resolve_shed(shed)
        p.done.wait()
        return p.result

    def _resolve_shed(self, shed: "list[_Pending]") -> None:
        if not shed:
            return
        for s in shed:
            s.result = dict(self.SHED_REPLY)
            s.done.set()
        self.stat_shed += len(shed)
        self.mgr._c_shed.inc(len(shed))

    def pop_choices(self, n: int) -> list[int]:
        """Up to n pre-drawn ChoiceTable decisions (may return fewer —
        the caller tops up via the direct sampling path)."""
        out = []
        with self._choice_mu:
            while self._choices and len(out) < n:
                out.append(self._choices.popleft())
        return out

    def stop(self) -> bool:
        """Stop the drainer; idempotent under double-close.  Returns
        False when the drainer thread failed to join (wedged mid-batch
        — the manager counts the leak instead of hanging shutdown)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        t, self._thread = self._thread, None
        joined = True
        if t is not None:
            t.join(timeout=10.0)
            if t.is_alive():
                log.logf(0, "admission coalescer failed to stop "
                         "(thread leaked)")
                joined = False
        # unblock anyone still waiting (their entries were drained or
        # the drainer exited before reaching them)
        with self._cv:
            while self._q:
                self._q.popleft().done.set()
        return joined

    # -- drainer -----------------------------------------------------------

    def _drain_loop(self) -> None:
        import time

        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait(timeout=0.5)
                if self._stop and not self._q:
                    return
                # adaptive gather window: concurrent submitters land in
                # ONE fused dispatch instead of a trickle of partial
                # ones.  Wait in short slices only while the queue is
                # still GROWING (a resolved batch's callers resubmit
                # within a few hundred µs) and stop as soon as it
                # plateaus — a fixed window would over-wait every cycle.
                # gather_ms caps the total; ~1ms is noise next to an
                # admission round trip.
                deadline = time.monotonic() + self.gather_ms / 1000.0
                prev_len = len(self._q)
                while (len(self._q) < self.max_batch and not self._stop):
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cv.wait(timeout=min(left, 0.00025))
                    if len(self._q) == prev_len:
                        break                      # plateaued
                    prev_len = len(self._q)
                # deadline-based shedding: entries that waited past the
                # deadline are stale (the drain is not keeping up —
                # genuine overload); resolve them shed instead of
                # spending the fused dispatch on them.  Oldest first:
                # the queue is FIFO, so the expired prefix IS the
                # oldest work.
                expired: "list[_Pending]" = []
                if self.shed_deadline > 0:
                    now = time.monotonic()
                    while self._q and now - self._q[0].enqueued \
                            > self.shed_deadline:
                        expired.append(self._q.popleft())
                batch = [self._q.popleft()
                         for _ in range(min(len(self._q), self.max_batch))]
            self._resolve_shed(expired)
            try:
                self._process(batch)
            except Exception as e:  # resolve tickets even on engine bugs
                log.logf(0, "admission batch failed: %s", e)
            finally:
                for p in batch:
                    p.done.set()

    def _process(self, batch: list[_Pending]) -> None:
        mgr = self.mgr
        if len(batch) > 1:
            self.stat_coalesced += len(batch)
            mgr._c_coal_inputs.inc(len(batch))
        self.stat_batches += 1
        mgr._c_coal_batches.inc()
        # shared side of the admission gate: excludes corpus
        # maintenance (row compaction) only — the fused dispatch itself
        # is serialized inside the engine, no mutex held across it
        with mgr._admit_gate.admitting():
            # host-side dedup FIRST (same early-out as the serial path):
            # already-in-corpus or repeated-in-batch sigs resolve to the
            # empty reply without touching the device
            fresh: list[_Pending] = []
            with mgr._mu:
                seen: set[bytes] = set()
                for p in batch:
                    if p.sig in mgr.corpus or p.sig in seen:
                        continue
                    seen.add(p.sig)
                    fresh.append(p)
            if not fresh:
                return
            # zero-copy ingest plane: the sparse→dense translation runs
            # ON DEVICE inside the fused admission dispatch (binary
            # search over the PcMap's sorted key mirror) — the host
            # keeps only a slab pack at pow2-bucketed dispatch shapes
            # plus ONE vectorized first-sight probe (mirror.ensure,
            # which IS PcMap.map_flat: steady state is a pure lookup
            # pass, and new keys insert in exact first-seen order so
            # export_keys/snapshots stay bit-exact)
            n = len(fresh)
            maxlen = max(min(len(p.cover), self.K) for p in fresh)
            kb = pow2_bucket(maxlen, self.MIN_K, self.K)
            B = pow2_bucket(n, self.MIN_B, self.max_batch)
            win = np.zeros((B, kb), np.uint32)
            counts = np.zeros((B,), np.int32)
            call_ids = np.zeros((B,), np.int32)
            wide = False            # >u32 PCs can't ride the u32 slab wire
            for i, p in enumerate(fresh):
                cov = np.asarray(p.cover)[: kb]
                if len(cov) and int(cov.max()) >> 32:
                    wide = True
                    break
                win[i, : len(cov)] = cov.astype(np.uint32)
                counts[i] = len(cov)
            call_ids[:n] = [p.call_id for p in fresh]
            prev = np.full((self.choices_per_step,), -1, np.int32)
            t_disp = time.monotonic()
            if wide:
                # legacy host-mapped path (64-bit preseed-style covers)
                idx, valid = mgr.pcmap.map_batch(
                    [p.cover for p in fresh], K=kb)
                pidx = np.zeros((B, kb), np.int32)
                pval = np.zeros((B, kb), bool)
                pidx[:n] = idx
                pval[:n] = valid
                has_new, rows, choices, new_bits = mgr.engine.admit_batch(
                    call_ids, pidx, pval, choice_prev=prev,
                    with_new_bits=True)
            else:
                live = np.arange(kb)[None, :] < counts[:n, None]
                mgr.pc_mirror.ensure(win[:n][live])
                # single-dispatch fuzz tick: admission gate + corpus
                # merge + choice draws PLUS the max-cover signal merge
                # the replay path would otherwise pay as a separate
                # dispatch — one host→device crossing per batch.  The
                # ResilientEngine facade forwards fuzz_tick; older/
                # minimal engines without it keep the admit_slabs pair.
                tick = getattr(mgr.engine, "fuzz_tick", None)
                if tick is not None:
                    res = tick(win, counts, call_ids, choice_prev=prev,
                               mirror=mgr.pc_mirror)
                    has_new, rows = res.has_new, res.rows
                    choices, new_bits = res.choices, res.new_bits
                else:
                    (has_new, rows, choices,
                     new_bits) = mgr.engine.admit_slabs(
                        win, counts, call_ids, choice_prev=prev,
                        mirror=mgr.pc_mirror, with_new_bits=True)
            t_done = time.monotonic()
            ds = mgr.device_stats
            if ds is not None:
                # one lock acquisition for the whole batch's latencies
                ds.observe_batch("admission_latency",
                                 [t_done - p.enqueued for p in fresh])
            for p in fresh:
                if p.trace is not None:
                    p.trace.add_hop("coalescer:gather",
                                    t_disp - p.enqueued)
                    p.trace.add_hop("coalescer:device dispatch",
                                    t_done - t_disp)
                    mgr.tracer.record(p.trace, final_hop="manager:admit",
                                      dur=t_done - p.enqueued)
            self._refill_choices(choices)
            admitted: list[tuple[_Pending, int]] = []
            cursor = 0
            for j, p in enumerate(fresh):
                if has_new[j]:
                    # campaign attribution: new-bit counts feed the
                    # per-campaign new_cov_per_1k_exec EWMA + corpus tag
                    mgr.campaign_sched.note_new_cov(
                        p.name, int(new_bits[j]), sig_hex=p.sig.hex())
            with mgr._mu:
                for j, p in enumerate(fresh):
                    if not has_new[j]:
                        continue
                    # rows[k] is the corpus row of the k-th admitted
                    # entry in submission order (None: matrix full,
                    # nothing merged — the serial path records -1 too)
                    row = int(rows[cursor]) if rows is not None else -1
                    cursor += 1
                    mgr._record_admitted(p, row)
                    admitted.append((p, row))
            # stat-plane bookkeeping ONCE per batch, not per input
            if len(admitted) < len(fresh):
                mgr._record_rejected(len(fresh) - len(admitted))
            if admitted:
                mgr._record_admit_rate(len(admitted))
        # persistence BEFORE ticket resolution: an acked NewInput must
        # be durable — the chaos harness SIGKILLs the manager right
        # after replies land and asserts zero corpus loss, which the
        # old resolve-then-persist order failed (the ack'd program
        # existed only in memory for one batch window).  The writes are
        # batched tmp+rename appends, noise next to the fused dispatch.
        for p, _row in admitted:
            mgr.persistent.add(p.data)
        for p in batch:
            p.done.set()
        if admitted:
            mgr._maybe_update_prios()

    def _refill_choices(self, choices) -> None:
        if choices is None:
            return
        with self._choice_mu:
            room = self.choice_ring_cap - len(self._choices)
            for c in np.asarray(choices)[:room]:
                self._choices.append(int(c))
