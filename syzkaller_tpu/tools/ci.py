"""Continuous-deployment daemon: run the manager in a loop, restarting
it whenever the kernel artifact or the framework source updates.

Capability analog of reference syz-gce/syz-gce.go:4-8 + gce/gce.go,
re-grounded for this build: instead of GCS archives + a Go rebuild, the
pollers watch (a) the kernel/image files the manager boots (mtime/sha),
and (b) the framework source tree (git HEAD when available, tree hash
otherwise).  On change: stop the manager, re-run the presubmit gate,
and start a fresh manager on the same workdir — the persistent corpus
re-seeds it (SURVEY §5 checkpoint/resume).

    python -m syzkaller_tpu.tools.ci -config manager.json [-poll 60]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import time

from syzkaller_tpu.manager import config as config_mod
from syzkaller_tpu.utils import log


def file_fingerprint(path: str) -> str:
    """Cheap change detector: size+mtime (content hash for small files)."""
    try:
        st = os.stat(path)
    except OSError:
        return "missing"
    if st.st_size < (1 << 20):
        with open(path, "rb") as f:
            return hashlib.sha1(f.read()).hexdigest()
    return f"{st.st_size}:{st.st_mtime_ns}"


def source_fingerprint(root: str) -> str:
    """Framework-version detector: git HEAD if the tree is a checkout,
    else a hash over source file mtimes."""
    try:
        r = subprocess.run(["git", "-C", root, "rev-parse", "HEAD"],
                           capture_output=True, text=True, timeout=30)
        if r.returncode == 0:
            return r.stdout.strip()
    except (OSError, subprocess.TimeoutExpired):
        pass
    h = hashlib.sha1()
    for dirpath, _dirs, files in sorted(os.walk(root)):
        if any(part.startswith(".") or part == "__pycache__"
               for part in dirpath.split(os.sep)):
            continue
        for fn in sorted(files):
            if fn.endswith((".py", ".cc", ".h", ".txt", ".const")):
                p = os.path.join(dirpath, fn)
                try:
                    h.update(f"{p}:{os.stat(p).st_mtime_ns}".encode())
                except OSError:
                    pass
    return h.hexdigest()


class CiDaemon:
    """start → watch → (on change) stop → gate → restart loop."""

    def __init__(self, config_path: str, poll: float = 60.0,
                 gate: bool = True):
        self.config_path = config_path
        self.cfg = config_mod.load(config_path)
        self.poll = poll
        self.gate = gate
        self.root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        self._proc: "subprocess.Popen | None" = None
        self.restarts = 0

    def fingerprints(self) -> dict:
        fp = {"source": source_fingerprint(self.root),
              "config": file_fingerprint(self.config_path)}
        for field in ("kernel", "image", "initrd", "vmlinux"):
            path = getattr(self.cfg, field, "")
            if path:
                fp[field] = file_fingerprint(path)
        return fp

    def run_gate(self) -> bool:
        # static analysis first, in --json mode: cheap fast-fail, and
        # the finding counts land in the deploy log either way
        r = subprocess.run(
            [sys.executable, "-m", "syzkaller_tpu.vet", "--json"],
            cwd=self.root, capture_output=True, text=True)
        try:
            counts = json.loads(r.stdout)["counts"]
            log.logf(0, "ci: vet: %d finding(s) (%d P0, %d P1), "
                     "%d unbaselined P0", counts["total"], counts["p0"],
                     counts["p1"], counts["p0_unbaselined"])
        except (ValueError, KeyError):
            log.logf(0, "ci: vet report unparseable (rc=%d)", r.returncode)
        if r.returncode != 0:
            return False
        r = subprocess.run(
            [sys.executable, "-m", "syzkaller_tpu.presubmit", "--quick"],
            cwd=self.root)
        return r.returncode == 0

    def start_manager(self) -> None:
        cmd = [sys.executable, "-m", "syzkaller_tpu.manager",
               "-config", self.config_path]
        log.logf(0, "ci: starting manager: %s", " ".join(cmd))
        self._proc = subprocess.Popen(cmd, start_new_session=True)

    def stop_manager(self) -> None:
        if self._proc is None:
            return
        log.logf(0, "ci: stopping manager (pid %d)", self._proc.pid)
        try:
            os.killpg(self._proc.pid, 15)
            self._proc.wait(timeout=60)
        except (ProcessLookupError, subprocess.TimeoutExpired,
                PermissionError):
            try:
                os.killpg(self._proc.pid, 9)
            except (ProcessLookupError, PermissionError):
                self._proc.kill()
            self._proc.wait()
        self._proc = None

    def step(self, last_fp: dict) -> dict:
        """One poll tick: restart on artifact change or manager death.
        Returns the new fingerprint set."""
        fp = self.fingerprints()
        died = self._proc is not None and self._proc.poll() is not None
        if fp != last_fp or died or self._proc is None:
            why = ("manager died" if died else
                   "first start" if self._proc is None and not self.restarts
                   else "artifacts changed: " + ", ".join(
                       k for k in fp if fp[k] != last_fp.get(k)))
            log.logf(0, "ci: (re)deploying — %s", why)
            self.stop_manager()
            self.cfg = config_mod.load(self.config_path)  # pick up edits
            if self.gate and not self.run_gate():
                log.logf(0, "ci: presubmit gate FAILED; retrying next poll")
                return fp
            self.start_manager()
            self.restarts += 1
        return fp

    def run(self, duration: "float | None" = None) -> None:
        deadline = time.time() + duration if duration else None
        fp: dict = {}
        try:
            while deadline is None or time.time() < deadline:
                fp = self.step(fp)
                time.sleep(self.poll)
        finally:
            self.stop_manager()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-config", required=True)
    ap.add_argument("-poll", type=float, default=60.0)
    ap.add_argument("-nogate", action="store_true",
                    help="skip the presubmit gate on redeploy")
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)
    log.set_verbosity(args.v)
    CiDaemon(args.config, poll=args.poll, gate=not args.nogate).run()


if __name__ == "__main__":
    main()
