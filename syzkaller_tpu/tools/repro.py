"""CLI for crash reproduction (ref tools/syz-repro, repro.go:85).

    python -m syzkaller_tpu.tools.repro -config mgr.cfg crash.log
"""

from __future__ import annotations

import argparse
import sys

from syzkaller_tpu import prog as P
from syzkaller_tpu import repro as repro_pkg
from syzkaller_tpu.manager import config as config_mod
from syzkaller_tpu.repro.repro import vm_test_fn
from syzkaller_tpu.sys.table import load_table
from syzkaller_tpu.utils import log


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("log", help="crash log file")
    ap.add_argument("-config", required=True)
    ap.add_argument("-vms", type=int, default=4,
                    help="instances to use (ref manager peels off 4)")
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)
    log.set_verbosity(args.v)
    cfg = config_mod.load(args.config)
    table = load_table(files=None if cfg.descriptions in ("all", "linux")
                       else [cfg.descriptions])
    with open(args.log, "rb") as f:
        crash_log = f.read()
    test_fn = vm_test_fn(cfg, table, list(range(args.vms)),
                         suppressions=cfg.compiled_suppressions())
    result = repro_pkg.run(crash_log, table, test_fn)
    if result is None or result.prog is None:
        log.logf(0, "reproduction failed (%d attempts)",
                 result.attempts if result else 0)
        sys.exit(1)
    sys.stdout.buffer.write(P.serialize(result.prog))
    if result.c_repro:
        sys.stdout.write("\n// ---- C reproducer ----\n" + result.c_repro)


if __name__ == "__main__":
    main()
