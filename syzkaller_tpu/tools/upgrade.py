"""Corpus format migration: re-serialize a corpus dir with the current
table/format (ref tools/syz-upgrade, upgrade.go:4-7). Programs that no
longer parse are moved aside rather than deleted.

    python -m syzkaller_tpu.tools.upgrade -corpus workdir/corpus
"""

from __future__ import annotations

import argparse
import hashlib
import os

from syzkaller_tpu import prog as P
from syzkaller_tpu.sys.table import load_table
from syzkaller_tpu.utils import log


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-corpus", required=True)
    ap.add_argument("-descriptions", default="all")
    args = ap.parse_args(argv)
    table = load_table(files=None if args.descriptions in ("all", "linux")
                       else [args.descriptions])
    broken_dir = os.path.join(args.corpus, "broken")
    migrated = broken = kept = 0
    for name in sorted(os.listdir(args.corpus)):
        path = os.path.join(args.corpus, name)
        if not os.path.isfile(path):
            continue
        with open(path, "rb") as f:
            data = f.read()
        try:
            p = P.deserialize(data, table)
            new_data = P.serialize(p)
        except P.DeserializeError:
            os.makedirs(broken_dir, exist_ok=True)
            os.replace(path, os.path.join(broken_dir, name))
            broken += 1
            continue
        if new_data == data:
            kept += 1
            continue
        sig = hashlib.sha1(new_data).hexdigest()
        with open(os.path.join(args.corpus, sig), "wb") as f:
            f.write(new_data)
        if sig != name:
            os.unlink(path)
        migrated += 1
    log.logf(0, "upgrade: %d kept, %d migrated, %d broken",
             kept, migrated, broken)


if __name__ == "__main__":
    main()
