"""Offline report processing (ref tools/syz-report + syz-symbolize,
report.go:36, symbolize.go:41): parse a console log, print the crash
description, optionally symbolize the stack trace against vmlinux.

    python -m syzkaller_tpu.tools.symbolize crash.log -vmlinux ./vmlinux
"""

from __future__ import annotations

import argparse
import sys

from syzkaller_tpu import report as report_pkg


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("log")
    ap.add_argument("-vmlinux", default="")
    args = ap.parse_args(argv)
    with open(args.log, "rb") as f:
        data = f.read()
    rep = report_pkg.parse(data)
    if rep is None:
        print("no crash found", file=sys.stderr)
        sys.exit(1)
    print(f"description: {rep.description}\n")
    text = rep.text
    if args.vmlinux:
        text = report_pkg.symbolize_report(text, args.vmlinux)
    sys.stdout.buffer.write(text)


if __name__ == "__main__":
    main()
