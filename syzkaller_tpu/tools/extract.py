"""Const extractor: resolve symbolic constants against kernel/libc headers.

Capability parity with the reference syz-extract (syz-extract/extract.go,
extract.sh): generates a C program that includes the headers referenced by
`include` directives in the description files, prints the value of every
symbolic constant the descriptions mention, and writes the results to
`descriptions/consts/<arch>.const`.  Unresolvable names are dropped
iteratively by parsing compiler diagnostics (the reference does the same
dance by recompiling with undefined symbols removed).

Usage: python -m syzkaller_tpu.tools.extract [-arch amd64] [files...]
"""

from __future__ import annotations

import argparse
import glob
import os
import re
import subprocess
import sys
import tempfile

from syzkaller_tpu.sys import parser, compiler
from syzkaller_tpu.sys.table import DESC_DIR

BASE_INCLUDES = [
    "unistd.h", "fcntl.h", "errno.h", "sys/types.h", "sys/stat.h",
    "sys/syscall.h", "sys/mman.h", "sys/ipc.h", "sys/shm.h",
    "sys/personality.h", "linux/falloc.h", "sys/file.h",
    "sys/random.h", "sys/time.h", "linux/netlink.h", "sys/ioctl.h",
]

# The probe compiles against glibc headers, which redefine a few kernel
# ABI constants for userspace convenience; the fuzzer needs the kernel
# values (the reference extracts against a kernel checkout and gets
# these right by construction).
OVERRIDES = {
    "O_LARGEFILE": 0o100000,
}


def collect_names(desc: parser.Description) -> tuple[set[str], set[str]]:
    """Return (symbolic constant names, kernel call names needing __NR_)."""
    # Give every kernel call a fake NR so its args get compiled (and their
    # symbolic consts collected) instead of being skipped as unsupported.
    fake_nrs = {
        f"__NR_{s.name.split('$', 1)[0]}": 0
        for s in desc.syscalls
        if not s.name.startswith("syz_")
    }
    comp = compiler.Compiler(desc, consts=fake_nrs, collect_only=True)
    comp.compile()
    consts = set(comp._missing)
    # Flags/resource definitions can be unreferenced by any call; sweep the
    # raw AST for their symbolic values too.
    for fdef in desc.flags.values():
        consts.update(v for v in fdef.values if isinstance(v, str))
    for rdef in desc.resources.values():
        consts.update(v for v in rdef.values if isinstance(v, str))
    nrs = {
        s.name.split("$", 1)[0]
        for s in desc.syscalls
        if not s.name.startswith("syz_")
    }
    return consts, nrs


def make_prog(includes: list[str], defines: list[tuple[str, str]],
              consts: list[str], nrs: list[str]) -> str:
    lines = ["#define _GNU_SOURCE"]
    for inc in includes:
        lines.append(f"#include <{inc}>")
    for name, val in defines:
        lines.append(f"#ifndef {name}\n#define {name} {val}\n#endif")
    lines.append("#include <stdio.h>")
    lines.append("int main(void) {")
    for c in consts:
        lines.append(f'    printf("{c} = %llu\\n", (unsigned long long)({c}));')
    for nr in nrs:
        lines.append(f'#ifdef __NR_{nr}')
        lines.append(f'    printf("__NR_{nr} = %llu\\n", (unsigned long long)(__NR_{nr}));')
        lines.append("#endif")
    lines.append("    return 0;\n}")
    return "\n".join(lines)


_UNDECLARED = re.compile(r"[‘']([A-Za-z_]\w*)[’'] undeclared")


def extract(files: list[str], arch: str = "amd64", cc: str = "gcc",
            out_path: str | None = None) -> dict[str, int]:
    desc = parser.Description()
    for p in files:
        desc.merge(parser.parse_file(p))
    consts, nrs = collect_names(desc)
    includes = BASE_INCLUDES + [i for i in desc.includes if i not in BASE_INCLUDES]

    unresolved: set[str] = set()
    values: dict[str, int] = {}
    remaining = sorted(consts)
    with tempfile.TemporaryDirectory() as td:
        src = os.path.join(td, "extract.c")
        binp = os.path.join(td, "extract")
        for _ in range(10):
            with open(src, "w") as f:
                f.write(make_prog(includes, desc.defines, remaining, sorted(nrs)))
            r = subprocess.run([cc, "-w", "-O0", src, "-o", binp],
                               capture_output=True, text=True)
            if r.returncode == 0:
                break
            bad = set(_UNDECLARED.findall(r.stderr))
            if not bad:
                sys.stderr.write(r.stderr)
                raise RuntimeError("const extraction failed with unparseable errors")
            unresolved |= bad
            remaining = [c for c in remaining if c not in bad]
        else:
            raise RuntimeError("const extraction did not converge")
        out = subprocess.run([binp], capture_output=True, text=True, check=True)
        for line in out.stdout.splitlines():
            name, _, val = line.partition(" = ")
            values[name.strip()] = int(val)
    for name, val in OVERRIDES.items():
        if name in values:
            values[name] = val

    if unresolved:
        print(f"unresolved ({len(unresolved)}): {', '.join(sorted(unresolved))}",
              file=sys.stderr)
    if out_path is None:
        out_path = os.path.join(DESC_DIR, "consts", f"{arch}.const")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        f.write("# Generated by syzkaller_tpu.tools.extract; do not edit.\n")
        for name in sorted(values):
            f.write(f"{name} = {values[name]}\n")
    print(f"wrote {len(values)} consts to {out_path}")
    return values


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-arch", default="amd64")
    ap.add_argument("-cc", default="gcc")
    ap.add_argument("files", nargs="*")
    args = ap.parse_args()
    files = args.files or sorted(
        glob.glob(os.path.join(os.path.abspath(DESC_DIR), "**", "*.txt"), recursive=True))
    extract(files, arch=args.arch, cc=args.cc)


if __name__ == "__main__":
    main()
