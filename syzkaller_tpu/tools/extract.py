"""Const extractor: resolve symbolic constants against kernel/libc headers.

Capability parity with the reference syz-extract (syz-extract/extract.go,
extract.sh): generates a C program that includes the headers referenced by
`include` directives in the description files, prints the value of every
symbolic constant the descriptions mention, and writes the results to
`descriptions/consts/<arch>.const`.  Unresolvable names are dropped
iteratively by parsing compiler diagnostics (the reference does the same
dance by recompiling with undefined symbols removed).

Usage: python -m syzkaller_tpu.tools.extract [-arch amd64] [files...]
"""

from __future__ import annotations

import argparse
import glob
import os
import re
import subprocess
import sys
import tempfile

from syzkaller_tpu.sys import parser, compiler
from syzkaller_tpu.sys.table import DESC_DIR

BASE_INCLUDES = [
    "unistd.h", "fcntl.h", "errno.h", "sys/types.h", "sys/stat.h",
    "sys/syscall.h", "sys/mman.h", "sys/ipc.h", "sys/shm.h",
    "sys/personality.h", "linux/falloc.h", "sys/file.h",
    "sys/random.h", "sys/time.h", "linux/netlink.h", "sys/ioctl.h",
]

# The probe compiles against glibc headers, which redefine a few kernel
# ABI constants for userspace convenience; the fuzzer needs the kernel
# values (the reference extracts against a kernel checkout and gets
# these right by construction).
OVERRIDES = {
    "O_LARGEFILE": 0o100000,
}


def collect_names(desc: parser.Description) -> tuple[set[str], set[str]]:
    """Return (symbolic constant names, kernel call names needing __NR_)."""
    # Give every kernel call a fake NR so its args get compiled (and their
    # symbolic consts collected) instead of being skipped as unsupported.
    fake_nrs = {
        f"__NR_{s.name.split('$', 1)[0]}": 0
        for s in desc.syscalls
        if not s.name.startswith("syz_")
    }
    comp = compiler.Compiler(desc, consts=fake_nrs, collect_only=True)
    comp.compile()
    consts = set(comp._missing)
    # Flags/resource definitions can be unreferenced by any call; sweep the
    # raw AST for their symbolic values too.
    for fdef in desc.flags.values():
        consts.update(v for v in fdef.values if isinstance(v, str))
    for rdef in desc.resources.values():
        consts.update(v for v in rdef.values if isinstance(v, str))
    nrs = {
        s.name.split("$", 1)[0]
        for s in desc.syscalls
        if not s.name.startswith("syz_")
    }
    return consts, nrs


def make_prog(includes: list[str], defines: list[tuple[str, str]],
              consts: list[str], nrs: list[str]) -> str:
    lines = ["#define _GNU_SOURCE"]
    for inc in includes:
        lines.append(f"#include <{inc}>")
    for name, val in defines:
        lines.append(f"#ifndef {name}\n#define {name} {val}\n#endif")
    lines.append("#include <stdio.h>")
    lines.append("int main(void) {")
    for c in consts:
        lines.append(f'    printf("{c} = %llu\\n", (unsigned long long)({c}));')
    for nr in nrs:
        lines.append(f'#ifdef __NR_{nr}')
        lines.append(f'    printf("__NR_{nr} = %llu\\n", (unsigned long long)(__NR_{nr}));')
        lines.append("#endif")
    lines.append("    return 0;\n}")
    return "\n".join(lines)


_UNDECLARED = re.compile(r"[‘']([A-Za-z_]\w*)[’'] undeclared")

# arm64 (aarch64) speaks the asm-generic kernel ABI.  Without a cross
# compiler on the build host, its consts are DERIVED: start from the
# host (amd64) extraction for arch-independent userspace constants,
# drop every __NR_* (the amd64 table does not apply), then overlay
# everything the generic ABI defines — syscall numbers from
# asm-generic/unistd.h and the generic file/tty/mman/socket constant
# set — via a second probe compiled against ONLY those uapi headers.
# The reference gets per-arch consts by extracting against a kernel
# checkout per arch (extract.sh); asm-generic/unistd.h IS arm64's
# table, so the derivation is exact for everything it covers.
# Verify on real arm64 hardware with: python -m syzkaller_tpu.tools.extract -arch arm64-native
GENERIC_ABI_HEADERS = [
    "asm-generic/fcntl.h",
    "asm-generic/ioctls.h",
    "asm-generic/mman.h",       # pulls mman-common.h
    "asm-generic/socket.h",
]

# __ARCH_WANT_* toggles arm64 sets in arch/arm64/include/(uapi/)asm/unistd.h
ARM64_WANTS = [
    "__ARCH_WANT_RENAMEAT",
    "__ARCH_WANT_NEW_STAT",
    "__ARCH_WANT_SET_GET_RLIMIT",
    "__ARCH_WANT_SYS_CLONE3",
    "__ARCH_WANT_MEMFD_SECRET",
]

# arch/arm64/include/uapi/asm/fcntl.h OVERRIDES the asm-generic fcntl
# defaults (the arm legacy layout) — the generic header alone gets these
# four swapped around, which would silently break every O_DIRECTORY/
# O_DIRECT open the fuzzer generates on the target.
ARM64_FCNTL = {
    "O_DIRECTORY": 0o40000,
    "O_NOFOLLOW": 0o100000,
    "O_DIRECT": 0o200000,
    "O_LARGEFILE": 0o400000,
    "O_TMPFILE": 0o20000000 | 0o40000,
}

# amd64-only constants that must NOT leak into the arm64 table (their
# flags simply lose that value, matching the arch reality)
ARM64_ABSENT = {
    "MAP_32BIT",
    "ARCH_SET_FS", "ARCH_SET_GS", "ARCH_GET_FS", "ARCH_GET_GS",
    "ARCH_GET_CPUID", "ARCH_SET_CPUID",
}


def make_generic_probe(names: list[str], nrs: list[str]) -> str:
    lines = ["#include <stdio.h>"]
    for w in ARM64_WANTS:
        lines.append(f"#define {w} 1")
    for inc in GENERIC_ABI_HEADERS:
        lines.append(f"#include <{inc}>")
    lines.append("#include <asm-generic/unistd.h>")
    lines.append("int main(void) {")
    for c in names:
        lines.append(f"#ifdef {c}")
        lines.append(f'    printf("{c} = %llu\\n", (unsigned long long)({c}));')
        lines.append("#endif")
    for nr in nrs:
        lines.append(f"#ifdef __NR_{nr}")
        lines.append(f'    printf("__NR_{nr} = %llu\\n", '
                     f'(unsigned long long)(__NR_{nr}));')
        lines.append("#endif")
    lines.append("    return 0;\n}")
    return "\n".join(lines)


def extract_generic_abi(consts: "set[str]", nrs: "set[str]",
                        cc: str = "gcc") -> dict[str, int]:
    """Values the asm-generic ABI defines, for the requested names."""
    values: dict[str, int] = {}
    with tempfile.TemporaryDirectory() as td:
        src = os.path.join(td, "generic.c")
        binp = os.path.join(td, "generic")
        with open(src, "w") as f:
            f.write(make_generic_probe(sorted(consts), sorted(nrs)))
        r = subprocess.run([cc, "-w", "-O0", src, "-o", binp],
                           capture_output=True, text=True)
        if r.returncode != 0:
            sys.stderr.write(r.stderr)
            raise RuntimeError("generic-ABI probe failed to compile")
        out = subprocess.run([binp], capture_output=True, text=True,
                             check=True)
        for line in out.stdout.splitlines():
            name, _, val = line.partition(" = ")
            values[name.strip()] = int(val)
    return values


def _resolve_host(desc: parser.Description, consts: "set[str]",
                  nrs: "set[str]", cc: str) -> dict[str, int]:
    """Resolve names against the build host's headers (iteratively
    dropping undeclared ones by parsing compiler diagnostics)."""
    includes = BASE_INCLUDES + [i for i in desc.includes
                                if i not in BASE_INCLUDES]
    unresolved: set[str] = set()
    values: dict[str, int] = {}
    remaining = sorted(consts)
    with tempfile.TemporaryDirectory() as td:
        src = os.path.join(td, "extract.c")
        binp = os.path.join(td, "extract")
        for _ in range(10):
            with open(src, "w") as f:
                f.write(make_prog(includes, desc.defines, remaining,
                                  sorted(nrs)))
            r = subprocess.run([cc, "-w", "-O0", src, "-o", binp],
                               capture_output=True, text=True)
            if r.returncode == 0:
                break
            bad = set(_UNDECLARED.findall(r.stderr))
            if not bad:
                sys.stderr.write(r.stderr)
                raise RuntimeError(
                    "const extraction failed with unparseable errors")
            unresolved |= bad
            remaining = [c for c in remaining if c not in bad]
        else:
            raise RuntimeError("const extraction did not converge")
        out = subprocess.run([binp], capture_output=True, text=True,
                             check=True)
        for line in out.stdout.splitlines():
            name, _, val = line.partition(" = ")
            values[name.strip()] = int(val)
    for name, val in OVERRIDES.items():
        if name in values:
            values[name] = val
    if unresolved:
        print(f"unresolved ({len(unresolved)}): "
              f"{', '.join(sorted(unresolved))}", file=sys.stderr)
    return values


def _write_consts(values: dict[str, int], arch: str,
                  out_path: "str | None", header: str) -> None:
    if out_path is None:
        out_path = os.path.join(DESC_DIR, "consts", f"{arch}.const")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        f.write(header)
        for name in sorted(values):
            f.write(f"{name} = {values[name]}\n")
    print(f"wrote {len(values)} consts to {out_path}")


def extract(files: list[str], arch: str = "amd64", cc: str = "gcc",
            out_path: str | None = None) -> dict[str, int]:
    desc = parser.Description()
    for p in files:
        desc.merge(parser.parse_file(p))
    consts, nrs = collect_names(desc)
    host = _resolve_host(desc, consts, nrs, cc)
    if arch == "arm64":
        # host extraction for arch-independent values + generic-ABI
        # overlay (see GENERIC_ABI_HEADERS note) + arm64's own fcntl
        # override set, minus the amd64-only names
        over = extract_generic_abi(consts, nrs, cc=cc)
        values = {k: v for k, v in host.items()
                  if not k.startswith("__NR_") and k not in ARM64_ABSENT}
        values.update(over)
        for name, val in ARM64_FCNTL.items():
            if name in values:
                values[name] = val
        _write_consts(
            values, arch, out_path,
            "# Generated by syzkaller_tpu.tools.extract -arch arm64; "
            "do not edit.\n"
            "# Derived on an x86-64 host: arch-independent values from "
            "the host extraction,\n"
            "# syscall NRs and tty/mman/socket constants overlaid from "
            "the asm-generic uapi\n"
            "# headers, fcntl flags from arm64's own uapi override set "
            "(ARM64_FCNTL).\n")
        return values
    _write_consts(host, arch, out_path,
                  "# Generated by syzkaller_tpu.tools.extract; "
                  "do not edit.\n")
    return host


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-arch", default="amd64")
    ap.add_argument("-cc", default="gcc")
    ap.add_argument("files", nargs="*")
    args = ap.parse_args()
    files = args.files or sorted(
        glob.glob(os.path.join(os.path.abspath(DESC_DIR), "**", "*.txt"), recursive=True))
    extract(files, arch=args.arch, cc=args.cc)


if __name__ == "__main__":
    main()
