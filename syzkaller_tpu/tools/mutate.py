"""One-shot program mutator (ref tools/syz-mutate, mutate.go:49).

    python -m syzkaller_tpu.tools.mutate prog.txt -seed 1
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from syzkaller_tpu import prog as P
from syzkaller_tpu.sys.table import load_table


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("file", nargs="?", help="program file (default stdin)")
    ap.add_argument("-descriptions", default="all")
    ap.add_argument("-seed", type=int, default=0)
    ap.add_argument("-ncalls", type=int, default=30)
    args = ap.parse_args(argv)
    table = load_table(files=None if args.descriptions in ("all", "linux")
                       else [args.descriptions])
    data = (open(args.file, "rb").read() if args.file
            else sys.stdin.buffer.read())
    p = P.deserialize(data, table)
    rand = P.Rand(np.random.default_rng(args.seed))
    P.mutate(p, rand, table, args.ncalls)
    sys.stdout.buffer.write(P.serialize(p))


if __name__ == "__main__":
    main()
