"""Program → standalone C source (ref tools/syz-prog2c, prog2c.go:60).

    python -m syzkaller_tpu.tools.prog2c prog.txt -threaded -build
"""

from __future__ import annotations

import argparse
import sys

from syzkaller_tpu import csource
from syzkaller_tpu import prog as P
from syzkaller_tpu.sys.table import load_table


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("file", nargs="?", help="program file (default stdin)")
    ap.add_argument("-descriptions", default="all")
    ap.add_argument("-threaded", action="store_true")
    ap.add_argument("-collide", action="store_true")
    ap.add_argument("-repeat", action="store_true")
    ap.add_argument("-procs", type=int, default=1)
    ap.add_argument("-sandbox", default="none")
    ap.add_argument("-build", action="store_true",
                    help="also compile; prints the binary path to stderr")
    args = ap.parse_args(argv)
    table = load_table(files=None if args.descriptions in ("all", "linux")
                       else [args.descriptions])
    data = (open(args.file, "rb").read() if args.file
            else sys.stdin.buffer.read())
    p = P.deserialize(data, table)
    opts = csource.Options(threaded=args.threaded, collide=args.collide,
                           repeat=args.repeat, procs=args.procs,
                           sandbox=args.sandbox)
    src = csource.generate(p, opts)
    sys.stdout.write(src)
    if args.build:
        path = csource.build(src)
        print(f"built: {path}", file=sys.stderr)


if __name__ == "__main__":
    main()
