"""Replay serialized programs through the executor.

Capability parity with reference tools/syz-execprog (execprog.go:4-5,
119-138): execute programs from a file (corpus dir or single log),
optionally repeatedly, printing per-call errno and coverage summaries.
Used by the repro pipeline inside test machines.

    python -m syzkaller_tpu.tools.execprog -file prog.txt -repeat 3
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

from syzkaller_tpu import ipc
from syzkaller_tpu import prog as P
from syzkaller_tpu.sys.table import load_table
from syzkaller_tpu.utils import log


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-file", required=True,
                    help="program file, corpus dir, or execution log")
    ap.add_argument("-descriptions", default="all")
    ap.add_argument("-repeat", type=int, default=1,
                    help="0 = forever")
    ap.add_argument("-procs", type=int, default=1)
    ap.add_argument("-threaded", action="store_true")
    ap.add_argument("-collide", action="store_true")
    ap.add_argument("-sandbox", default="none")
    ap.add_argument("-cover", action="store_true", default=True)
    ap.add_argument("-real-cover", action="store_true")
    ap.add_argument("-output", action="store_true",
                    help="echo each program before executing")
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)
    log.set_verbosity(args.v)

    table = load_table(files=None if args.descriptions in ("all", "linux")
                       else [args.descriptions])
    progs: list[bytes] = []
    if os.path.isdir(args.file):
        for path in sorted(glob.glob(os.path.join(args.file, "*"))):
            with open(path, "rb") as f:
                progs.append(f.read())
    else:
        with open(args.file, "rb") as f:
            data = f.read()
        entries = P.parse_log(data, table)
        if entries:
            progs = [P.serialize(e.prog) for e in entries]
        else:
            progs = [data]

    flags = ipc.FLAG_COVER | ipc.FLAG_DEDUP_COVER
    if not args.real_cover:
        flags |= ipc.FLAG_FAKE_COVER
    if args.threaded:
        flags |= ipc.FLAG_THREADED
    if args.collide:
        flags |= ipc.FLAG_COLLIDE
    if args.sandbox == "setuid":
        flags |= ipc.FLAG_SANDBOX_SETUID
    elif args.sandbox == "namespace":
        flags |= ipc.FLAG_SANDBOX_NAMESPACE

    env = ipc.Env(flags=flags)
    try:
        iteration = 0
        while args.repeat == 0 or iteration < args.repeat:
            iteration += 1
            for i, data in enumerate(progs):
                try:
                    p = P.deserialize(data, table)
                except P.DeserializeError as e:
                    log.logf(0, "prog %d: parse error: %s", i, e)
                    continue
                if args.output:
                    sys.stdout.write(f"executing program {i}:\n"
                                     f"{data.decode(errors='replace')}\n")
                    sys.stdout.flush()
                res = env.exec(p)
                total_cov = sum(len(c.cover) for c in res.calls)
                log.logf(1, "prog %d: %d/%d calls, %d cover PCs%s", i,
                         len(res.calls), len(p.calls), total_cov,
                         " [hanged]" if res.hanged else "")
        log.logf(0, "executed %d programs x%d", len(progs), iteration)
    finally:
        env.close()


if __name__ == "__main__":
    main()
