"""syz-stress equivalent: standalone gen/mutate/execute loop, no manager.

Capability parity with reference tools/syz-stress/stress.go:42-88, wired
the TPU way (SURVEY §7 step 6 / BASELINE config #1): programs run
through the native executor; per-call coverage streams to the JAX
engine, which does signal-diff + corpus admission + choice-table
sampling in batched device steps.

    python -m syzkaller_tpu.tools.stress -descriptions fixture -execs 2000
"""

from __future__ import annotations

import argparse
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from syzkaller_tpu import ipc
from syzkaller_tpu import prog as P
from syzkaller_tpu.cover.engine import CoverageEngine
from syzkaller_tpu.fuzzer import DeviceChoiceTable, PcMap
from syzkaller_tpu.prog import model as M
from syzkaller_tpu.sys.table import SyscallTable, load_table
from syzkaller_tpu.utils import log

DESC_SETS = {
    "fixture": ["probe.txt"],
    "linux": None,  # all descriptions
    "all": None,
}


@dataclass
class StressOptions:
    descriptions: str = "fixture"
    procs: int = 2
    execs: int = 2000
    ncalls: int = 12
    seed: int = 0
    threaded: bool = False
    collide: bool = False
    fake_cover: bool = True
    npcs: int = 1 << 16
    max_pcs_per_call: int = 256
    flush_batch: int = 256        # exec-calls per device step (amortizes
                                  # the ~100ms tunnel latency per jit call)
    corpus_cap: int = 4096
    log_every: float = 5.0
    output: bool = False          # echo each program before executing
    device_rand: bool = False     # draw program randomness on device


@dataclass
class StressStats:
    execs: int = 0
    exec_calls: int = 0
    new_inputs: int = 0
    corpus: list = field(default_factory=list)  # (serialized prog, call idx)
    cover_pcs: int = 0


class Stress:
    def __init__(self, opts: StressOptions, table: "SyscallTable | None" = None):
        self.opts = opts
        self.table = table or load_table(files=DESC_SETS.get(
            opts.descriptions, [opts.descriptions]))
        self.engine = CoverageEngine(
            npcs=opts.npcs, ncalls=self.table.count,
            corpus_cap=opts.corpus_cap, batch=opts.flush_batch,
            max_pcs_per_exec=opts.max_pcs_per_call, seed=opts.seed)
        self.engine.set_priorities(P.calculate_priorities(self.table))
        enabled = self.table.transitively_enabled_calls()
        self.engine.set_enabled([c.id for c in enabled])
        self.ct = DeviceChoiceTable(self.engine)
        self.pcmap = PcMap(opts.npcs)
        self.stats = StressStats()
        self.corpus_progs: list[M.Prog] = []
        self._lock = threading.Lock()
        self._pending: list[tuple[bytes, int, int, np.ndarray]] = []
        # (serialized prog, call_index, call_id, cover)
        self._stop = False

    def flags(self) -> int:
        f = ipc.FLAG_COVER | ipc.FLAG_DEDUP_COVER
        if self.opts.fake_cover:
            f |= ipc.FLAG_FAKE_COVER
        if self.opts.threaded:
            f |= ipc.FLAG_THREADED
        if self.opts.collide:
            f |= ipc.FLAG_COLLIDE
        return f

    # -- the per-proc loop (ref stress.go:62-88) ---------------------------

    def proc_loop(self, pid: int) -> None:
        try:
            self._proc_loop(pid)
        except Exception as e:  # a dead proc must be visible, not silent
            log.logf(0, "stress proc %d died: %r", pid, e)
            raise

    def _proc_loop(self, pid: int) -> None:
        rand = P.Rand(np.random.default_rng(self.opts.seed * 1000 + pid))
        if self.opts.device_rand:
            rand.refill(self.engine.random_words(1 << 16))
        env = ipc.Env(flags=self.flags(), pid=pid)
        try:
            while not self._stop:
                with self._lock:
                    if self.stats.execs >= self.opts.execs:
                        break
                    self.stats.execs += 1
                    corpus = list(self.corpus_progs)
                p = self.make_prog(rand, corpus)
                if self.opts.output:
                    log.logf(0, "executing program %d:\n%s", pid,
                             P.serialize(p).decode())
                try:
                    res = env.exec(p)
                except ipc.ExecutorFailure as e:
                    log.logf(0, "executor failure: %s", e)
                    continue
                self.ingest(p, res)
                if self.opts.device_rand and rand._pos >= len(rand._pool):
                    rand.refill(self.engine.random_words(1 << 16))
        finally:
            env.close()

    def make_prog(self, rand: P.Rand, corpus: list[M.Prog]) -> M.Prog:
        if corpus and not rand.one_of(3):
            p = M.clone_prog(corpus[rand.intn(len(corpus))])
            P.mutate(p, rand, self.table, self.opts.ncalls, self.ct, corpus)
            return p
        return P.generate(rand, self.table, self.opts.ncalls, self.ct)

    def ingest(self, p: M.Prog, res: ipc.ExecResult) -> None:
        data = P.serialize(p)
        batches = []
        with self._lock:
            self.stats.exec_calls += len(res.calls)
            for c in res.calls:
                if c.index < len(p.calls) and len(c.cover):
                    call_id = p.calls[c.index].meta.id
                    self._pending.append((data, c.index, call_id, c.cover))
            B = self.opts.flush_batch
            while len(self._pending) >= B:
                batches.append(self._pending[:B])
                self._pending = self._pending[B:]
        # device steps run OUTSIDE _lock (syz-vet device-sync-under-
        # lock): the engine serializes its own state mutation, so the
        # host lock only needs to guard pending/stats
        for pend in batches:
            self._flush(pend)

    def flush(self) -> None:
        """Drain everything still pending (shutdown path)."""
        with self._lock:
            pend, self._pending = self._pending, []
        B = self.opts.flush_batch
        while pend:
            head, pend = pend[:B], pend[B:]
            self._flush(head)

    def _flush(self, pend) -> None:
        """One fixed-shape device step for up to flush_batch exec calls
        (no host lock held). Short batches are padded — a varying batch
        shape would trigger an XLA recompile per flush."""
        if not pend:
            return
        B = self.opts.flush_batch
        covers = [cov for (_, _, _, cov) in pend]
        covers += [np.zeros(0, np.uint32)] * (B - len(covers))
        call_ids = np.zeros((B,), np.int32)
        call_ids[: len(pend)] = [cid for (_, _, cid, _) in pend]
        idx, valid = self.pcmap.map_batch(covers, self.opts.max_pcs_per_call)
        result = self.engine.update_batch(call_ids, idx, valid)
        new_rows = np.nonzero(result.has_new[: len(pend)])[0]
        if len(new_rows) == 0:
            return
        if self.engine.admit_rows(result, call_ids, new_rows) is None:
            # device corpus full: drop on the host side too so the two
            # stay consistent (a manager-driven minimize frees space)
            with self._lock:
                warned, self._warned_full = \
                    getattr(self, "_warned_full", False), True
            if not warned:
                log.logf(0, "corpus capacity %d reached; new inputs dropped",
                         self.engine.cap)
            return
        progs = []
        for i in new_rows:
            data, call_index, _cid, _cov = pend[i]
            try:
                progs.append((data, call_index,
                              P.deserialize(data, self.table)))
            except P.DeserializeError:
                progs.append((data, call_index, None))
        with self._lock:
            for data, call_index, prog in progs:
                self.stats.new_inputs += 1
                self.stats.corpus.append((data, call_index))
                if prog is not None:
                    self.corpus_progs.append(prog)

    def run(self) -> StressStats:
        threads = [threading.Thread(target=self.proc_loop, args=(pid,),
                                    daemon=True)
                   for pid in range(self.opts.procs)]
        t0 = time.time()
        last_log = t0
        for t in threads:
            t.start()
        try:
            while any(t.is_alive() for t in threads):
                for t in threads:
                    t.join(timeout=0.2)
                now = time.time()
                if now - last_log > self.opts.log_every:
                    last_log = now
                    # device sync outside _lock (syz-vet)
                    cover = int(self.engine.cover_counts().sum())
                    with self._lock:
                        rate = self.stats.execs / max(now - t0, 1e-9)
                        execs, ncorp = self.stats.execs, \
                            len(self.stats.corpus)
                    log.logf(0, "execs %d (%.0f/sec) corpus %d cover %d",
                             execs, rate, ncorp, cover)
        except KeyboardInterrupt:
            self._stop = True
            for t in threads:
                t.join(timeout=2.0)
        self.flush()        # workers have exited; drains without _lock
        self.stats.cover_pcs = int(self.engine.cover_counts().sum())
        return self.stats


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-descriptions", default="fixture",
                    help="fixture|linux|all or a description file name")
    ap.add_argument("-procs", type=int, default=2)
    ap.add_argument("-execs", type=int, default=2000)
    ap.add_argument("-ncalls", type=int, default=12)
    ap.add_argument("-seed", type=int, default=0)
    ap.add_argument("-threaded", action="store_true")
    ap.add_argument("-collide", action="store_true")
    ap.add_argument("-real-cover", action="store_true",
                    help="require KCOV instead of synthetic coverage")
    ap.add_argument("-output", action="store_true")
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)
    log.set_verbosity(args.v)
    opts = StressOptions(
        descriptions=args.descriptions, procs=args.procs, execs=args.execs,
        ncalls=args.ncalls, seed=args.seed, threaded=args.threaded,
        collide=args.collide, fake_cover=not args.real_cover,
        output=args.output)
    st = Stress(opts)
    stats = st.run()
    log.logf(0, "done: execs %d calls %d new inputs %d covered PCs %d",
             stats.execs, stats.exec_calls, stats.new_inputs, stats.cover_pcs)
    return stats


if __name__ == "__main__":
    main()
