"""Replay a crash log on many machines in parallel, hunting a flaky
reproducer (ref tools/syz-crush, crush.go:4-6,135).

    python -m syzkaller_tpu.tools.crush -config mgr.cfg crash.log
"""

from __future__ import annotations

import argparse
import shlex
import sys
import threading

from syzkaller_tpu import vm
from syzkaller_tpu.manager import config as config_mod
from syzkaller_tpu.utils import log
from syzkaller_tpu.vm.monitor import monitor_execution


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("log", help="crash log with programs to replay")
    ap.add_argument("-config", required=True)
    ap.add_argument("-restart-time", type=float, default=3600.0)
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)
    log.set_verbosity(args.v)
    cfg = config_mod.load(args.config)
    suppressions = cfg.compiled_suppressions()

    def crush_loop(index: int) -> None:
        while True:
            inst = None
            try:
                inst = vm.create(cfg.type, cfg, index)
                guest_log = inst.copy(args.log)
                cmd = [sys.executable, "-m", "syzkaller_tpu.tools.execprog",
                       "-file", guest_log, "-repeat", "0", "-threaded",
                       "-collide"]
                handle = inst.run(" ".join(shlex.quote(c) for c in cmd),
                                  args.restart_time)
                outcome = monitor_execution(handle, args.restart_time,
                                            ignores=suppressions,
                                            need_executing=False)
                handle.stop()
                if outcome.crashed:
                    log.logf(0, "vm-%d: CRASHED: %s", index, outcome.title)
                else:
                    log.logf(0, "vm-%d: %s", index, outcome.title)
            except Exception as e:
                log.logf(0, "vm-%d error: %s", index, e)
            finally:
                if inst is not None:
                    inst.close()

    threads = [threading.Thread(target=crush_loop, args=(i,), daemon=True)
               for i in range(cfg.count)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


if __name__ == "__main__":
    main()
