"""Execution environment: shared memory + pipe protocol to the native
executor.

Capability parity with reference ipc/ipc.go: Env with 2MB-in/16MB-out
file-backed shm (:105-137), the flag bitmask (:41-50), 1-byte pipe
request/reply with timeout kill (:187-293, :501-560), per-call coverage
parsing from shm-out (:224-292), transparent env teardown/relaunch
(:206-218), and the magic exit-status taxonomy 67/68/69 (:538-557).
"""

from __future__ import annotations

import os
import select
import signal
import struct
import subprocess
import tempfile
import time
from dataclasses import dataclass, field

import numpy as np

from syzkaller_tpu.native import build as native_build
from syzkaller_tpu.prog import model as M
from syzkaller_tpu.prog.encodingexec import serialize_for_exec
from syzkaller_tpu.utils import log

IN_SHM_SIZE = 2 << 20
OUT_SHM_SIZE = 16 << 20

# flag bits (mirrored in native/executor.cc)
FLAG_DEBUG = 1 << 0
FLAG_COVER = 1 << 1
FLAG_THREADED = 1 << 2
FLAG_COLLIDE = 1 << 3
FLAG_DEDUP_COVER = 1 << 4
FLAG_SANDBOX_SETUID = 1 << 5
FLAG_SANDBOX_NAMESPACE = 1 << 6
FLAG_FAKE_COVER = 1 << 7
FLAG_ENABLE_TUN = 1 << 8
FLAG_RING_SKIP = 1 << 9   # don't write this exec's covers to the PC ring
FLAG_PROG_RING = 1 << 10  # read the program from the program slab ring
#                           instead of shm-in (device→executor path)

# program ring geometry: one program slab = a whole exec image in u32
# words (u64 wire words as lo/hi pairs).  min_bucket spans the synth
# plane's program cap so every slab shares ONE bucket — a synth batch
# is a single contiguous vectorized write.
PROG_RING_DATA_WORDS = 1 << 18          # 1MB of program slabs
PROG_RING_INDEX_SLOTS = 1 << 10
PROG_RING_SLAB_CAP = 4096               # u32 words = 16KB program cap
PROG_RING_MIN_BUCKET = 512

# executor exit statuses (ref common.h:46-48)
STATUS_OK = 0
STATUS_FAIL = 67     # executor logic failure -> hard error
STATUS_ERROR = 68    # kernel bug detected
STATUS_RETRY = 69    # transient -> relaunch env


class ExecutorFailure(Exception):
    """The executor itself misbehaved (protocol/logic error, status 67)."""


_EMPTY_COVER = np.zeros(0, np.uint32)   # shared sentinel: covers skipped


@dataclass
class CallResult:
    index: int
    errno: int
    cover: np.ndarray  # uint32 PCs, sorted+deduped when FLAG_DEDUP_COVER


@dataclass
class ExecResult:
    calls: list[CallResult] = field(default_factory=list)
    failed: bool = False    # executor reported failure
    hanged: bool = False    # worker killed on timeout
    restarted: bool = False # env was relaunched
    status: int = 0         # raw worker status byte (positive: 67/68/69)
    #                         or, when the executor process itself died,
    #                         a negative code (-exitcode or -signum)

    def per_call(self, ncalls: int) -> "list[CallResult | None]":
        out: "list[CallResult | None]" = [None] * ncalls
        for c in self.calls:
            if 0 <= c.index < ncalls:
                out[c.index] = c
        return out


class Env:
    """One executor instance: spawn, feed programs, parse results."""

    def __init__(self, flags: int = FLAG_COVER | FLAG_DEDUP_COVER,
                 pid: int = 0, executor: "str | None" = None,
                 workdir: "str | None" = None, timeout: float = 10.0,
                 ring: bool = False, prog_ring: bool = False):
        self.flags = flags
        self.pid = pid
        self.timeout = timeout
        self.executor = executor or native_build.build_executor()
        self.workdir = workdir or tempfile.mkdtemp(prefix="syz-env-")
        os.makedirs(self.workdir, exist_ok=True)
        self._in_file = os.path.join(self.workdir, f"shm-in-{pid}")
        self._out_file = os.path.join(self.workdir, f"shm-out-{pid}")
        self._proc: "subprocess.Popen | None" = None
        self._in_mm = None
        self._out_mm = None
        self.stat_execs = 0
        self.stat_restarts = 0
        # zero-copy PC slab ring: the executor writes raw covers into a
        # third shm region (ipc/ring.py layout) and the ingest side
        # consumes batched zero-copy views — no per-call frombuffer
        # copies on the hot path.  The ring survives executor restarts
        # (header state lives in the file); after a kill the reader
        # resyncs past any torn slab.
        self.ring = None
        self.ring_reader = None
        if ring:
            from syzkaller_tpu.ipc import ring as ring_mod
            self._ring_file = os.path.join(self.workdir, f"shm-ring-{pid}")
            # min_bucket=64 quantizes typical covers into ONE bucket so
            # committed runs (= zero-copy dispatch batches) stay long
            self.ring = ring_mod.PcRing.create(self._ring_file,
                                               min_bucket=64)
            self.ring_reader = ring_mod.RingReader(self.ring)
        # device→executor program slab ring: the executor reads whole
        # exec images straight off shared memory (FLAG_PROG_RING execs
        # skip the shm-in program write entirely); one bucket spans a
        # program so synth batches land as one contiguous write
        self.prog_ring = None
        if prog_ring:
            from syzkaller_tpu.ipc import ring as ring_mod
            self._prog_ring_file = os.path.join(self.workdir,
                                                f"shm-prog-{pid}")
            self.prog_ring = ring_mod.PcRing.create(
                self._prog_ring_file, data_words=PROG_RING_DATA_WORDS,
                index_slots=PROG_RING_INDEX_SLOTS,
                slab_cap=PROG_RING_SLAB_CAP,
                min_bucket=PROG_RING_MIN_BUCKET)
            self.prog_writer = ring_mod.RingWriter(self.prog_ring)
        self._open_shm()

    def _open_shm(self) -> None:
        import mmap

        for path, size in ((self._in_file, IN_SHM_SIZE),
                           (self._out_file, OUT_SHM_SIZE)):
            with open(path, "wb") as f:
                f.truncate(size)
        self._in_fd = os.open(self._in_file, os.O_RDWR)
        self._out_fd = os.open(self._out_file, os.O_RDWR)
        self._in_mm = mmap.mmap(self._in_fd, IN_SHM_SIZE)
        self._out_mm = mmap.mmap(self._out_fd, OUT_SHM_SIZE)

    def _start(self) -> None:
        req_r, req_w = os.pipe()
        rep_r, rep_w = os.pipe()
        # executor sees: 3=shm-in 4=shm-out 5=req-read 6=rep-write
        self._proc = self._spawn(req_r, rep_w)
        os.close(req_r)
        os.close(rep_w)
        self._req_w = req_w
        self._rep_r = rep_r

    def _spawn(self, req_r: int, rep_w: int) -> subprocess.Popen:
        # fd numbers go via argv: subprocess keeps pass_fds at their
        # original numbers (dup2-in-preexec would be undone by close_fds).
        fds = (self._in_fd, self._out_fd, req_r, rep_w)
        argv = [*map(str, fds)]
        if self.ring is not None:
            fds = fds + (self.ring.fd,)
            argv.append(str(self.ring.fd))
        elif self.prog_ring is not None:
            argv.append("-1")               # no PC ring, argv slot kept
        if self.prog_ring is not None:
            fds = fds + (self.prog_ring.fd,)
            argv.append(str(self.prog_ring.fd))
        return subprocess.Popen(
            [self.executor, *argv],
            pass_fds=fds,
            stdin=subprocess.DEVNULL,
            stdout=subprocess.DEVNULL,
            stderr=None if (self.flags & FLAG_DEBUG) else subprocess.DEVNULL,
            cwd=self.workdir,
            start_new_session=True,
        )

    def _close_pipes(self) -> None:
        for fd in (getattr(self, "_req_w", -1), getattr(self, "_rep_r", -1)):
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
        self._req_w = self._rep_r = -1

    def _kill(self) -> None:
        if self._proc is not None:
            try:
                os.killpg(self._proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                self._proc.kill()
            except ProcessLookupError:
                pass
            self._proc.wait()
            self._proc = None
        self._close_pipes()

    def close(self) -> None:
        self._kill()
        for mm in (self._in_mm, self._out_mm):
            if mm is not None:
                mm.close()
        for fd in (self._in_fd, self._out_fd):
            try:
                os.close(fd)
            except OSError:
                pass
        if self.ring is not None:
            self.ring.close()
        if self.prog_ring is not None:
            self.prog_ring.close()

    def ring_resync(self) -> int:
        """Skip any torn (reserved-uncommitted) slab the executor left
        behind when it was killed mid-slab-write.  Only valid after the
        executor process is down (exec() kills before relaunch)."""
        if self.ring_reader is None:
            return 0
        return self.ring_reader.resync()

    # -- execution ---------------------------------------------------------

    def exec(self, p: "M.Prog | bytes | None", parse_covers: bool = True,
             extra_flags: int = 0,
             from_prog_ring: bool = False) -> ExecResult:
        """Run one program; relaunches the executor transparently on
        hang/retryable failure (ref ipc.go:206-218).

        parse_covers=False skips the per-call cover `frombuffer().copy()`
        from shm-out (errno/index records are still parsed) — the ring
        ingest path reads covers as zero-copy slab views instead, so
        copying them here would pay the host packing twice.
        extra_flags ORs per-exec flag bits into the request header
        (FLAG_RING_SKIP keeps triage/minimize re-executions out of the
        slab ring, so hot-loop attribution stays 1:1).

        from_prog_ring=True is the device→executor slab-attach path:
        the program was already committed to the program ring (one
        vectorized batch write), so nothing is copied into shm-in —
        the executor reads the next committed slab straight off the
        shared mapping and consumes it after the run.  `p` may be None
        then (a serialized fallback is not required)."""
        self._parse_covers = parse_covers
        if from_prog_ring:
            if self.prog_ring is None:
                raise ExecutorFailure("no program ring attached")
            data = b""
            extra_flags |= FLAG_PROG_RING
        else:
            data = p if isinstance(p, bytes) \
                else serialize_for_exec(p, self.pid)
        res = ExecResult()
        if self._proc is None or self._proc.poll() is not None:
            self._kill()
            self._start()
            res.restarted = True
            self.stat_restarts += 1

        header = struct.pack("<QQQ", self.flags | extra_flags, self.pid,
                             len(data) // 8)
        if len(header) + len(data) > IN_SHM_SIZE:
            raise ExecutorFailure(
                f"program exec image too large for shm-in: "
                f"{len(header) + len(data)} > {IN_SHM_SIZE} bytes")
        self._in_mm.seek(0)
        self._in_mm.write(header + data)
        self._out_mm.seek(0)
        self._out_mm.write(b"\x00" * 8)

        try:
            os.write(self._req_w, b"r")
        except BrokenPipeError:
            self._kill()
            raise ExecutorFailure("executor died before request")

        ready, _, _ = select.select([self._rep_r], [], [], self.timeout)
        if not ready:
            # hung executor: kill + relaunch next time
            self._kill()
            res.hanged = True
            self._parse_output(res)
            return res
        reply = os.read(self._rep_r, 1)
        self.stat_execs += 1
        if len(reply) == 0:
            # executor exited; classify by status (ref ipc.go:538-557)
            code = self._proc.wait() if self._proc else -1
            self._proc = None
            self._close_pipes()
            if code == STATUS_FAIL:
                raise ExecutorFailure("executor failed (status 67)")
            res.restarted = True
            # process-death domain is strictly NEGATIVE: exit(N) -> -N,
            # signal death (wait() = -signum) stays negative, and a
            # clean exit-0 before replying gets the sentinel -256 —
            # never collides with positive worker-reply status bytes
            res.status = -code if code > 0 else (code if code < 0 else -256)
            self._parse_output(res)
            return res
        status = reply[0]
        res.status = status
        if status == STATUS_FAIL:
            res.failed = True
        elif status == STATUS_ERROR:
            # worker saw a kernel-bug indicator
            res.failed = True
        elif status == STATUS_RETRY:
            # transient worker failure: tear the env down so the next
            # exec relaunches it cleanly
            self._kill()
            res.restarted = True
        self._parse_output(res)
        return res

    def _parse_output(self, res: ExecResult) -> None:
        # zero-copy view over the shm: only the consumed region is touched
        # (a full .read() would memcpy all 16MB per exec)
        buf = memoryview(self._out_mm)
        (count,) = struct.unpack_from("<I", buf, 0)
        pos = 8
        for _ in range(min(count, 4096)):
            if pos + 16 > len(buf):
                break
            idx, _resv, err, ncov = struct.unpack_from("<IIiI", buf, pos)
            pos += 16
            if ncov > (len(buf) - pos) // 4:
                break
            if getattr(self, "_parse_covers", True):
                cover = np.frombuffer(buf, dtype=np.uint32, count=ncov,
                                      offset=pos).copy()
            else:
                cover = _EMPTY_COVER
            pos += ncov * 4
            res.calls.append(CallResult(index=idx, errno=err, cover=cover))
        buf.release()


class Gate:
    """Bounded concurrency window + epoch callback (ref ipc/gate.go:10-77):
    at most `size` sections in flight; when the section that closes a
    window of `size` leaves AND everything before it has left, `callback`
    runs exclusively — new entries block until it finishes (used for
    leak-check scans between execution batches)."""

    def __init__(self, size: int, callback=None):
        import threading

        self.size = size
        self.callback = callback
        self._busy = 0
        self._pos = 0
        self._running = [False] * size
        self._stopping = False
        self._in_callback = False
        self._cv = threading.Condition()

    def enter(self) -> int:
        with self._cv:
            while (self._busy >= self.size or self._stopping
                   or self._in_callback):
                self._cv.wait()
            idx = self._pos
            self._pos = (self._pos + 1) % self.size
            self._busy += 1
            self._running[idx] = True
            return idx

    def leave(self, idx: int) -> None:
        run_cb = False
        with self._cv:
            self._running[idx] = False
            self._busy -= 1
            self._cv.notify_all()
            if idx == self.size - 1 and self.callback is not None:
                # Window closed: block new entries and drain every section
                # still in flight, then run the callback exclusively
                # (ref ipc/gate.go — without the drain, with >=2 procs the
                # callback would almost never get a quiet instant to run).
                # Window closings themselves serialize: a second closer
                # (pos can wrap while the first drain is pending) waits
                # until the first closer's callback has finished.
                while self._stopping or self._in_callback:
                    self._cv.wait()
                self._stopping = True
                while self._busy > 0:
                    self._cv.wait()
                self._stopping = False
                self._in_callback = True
                run_cb = True
        if run_cb:
            try:
                self.callback()
            finally:
                with self._cv:
                    self._in_callback = False
                    self._cv.notify_all()

    def section(self):
        """Context manager for one gated section (thread-safe — the slot
        token lives in the manager object, not on the shared Gate)."""
        gate = self

        class _Section:
            def __enter__(self_s):
                self_s.idx = gate.enter()
                return self_s

            def __exit__(self_s, *exc):
                gate.leave(self_s.idx)
                return False

        return _Section()

    def __enter__(self):
        raise TypeError("use Gate.section(): 'with gate.section(): ...'")

    def __exit__(self, *exc):  # pragma: no cover
        return False
