"""Pinned PC slab ring: the zero-copy executor→device ingest transport.

The executor→engine path used to cross the host boundary per exec:
KCOV PCs traveled shm → per-call `np.frombuffer().copy()` → Python
lists → PcMap hash lookups → numpy padding → device transfer — which is
why device replay lost to the CPU path outright (BENCH_r02: 4.3k/s
device vs 17.7k/s CPU).  This module is the transport half of the fix:
a shared-memory ring the executor (native/executor.cc mirrors this
layout word for word) writes raw fixed-layout PC slabs into, and the
ingest side reads back as zero-copy numpy views shaped for direct
device dispatch — no per-exec host packing, no Python list
materialization.

Wire layout (all little-endian, one file):

    header (128 bytes):
        u64 magic     'SYZRING1'
        u32 version   (1)
        u32 slab_cap  max PCs per slab (longer covers truncate, like the
                      reference's per-call KCOV cap)
        u64 index_slots, u64 data_words
        u64 resv_idx     [writer] slabs reserved, monotonic
        u64 head_words   [writer] data words reserved, monotonic
        u64 consumed_idx [reader] slabs consumed, monotonic
        u64 tail_words   [reader] data words consumed, monotonic
        u64 dropped_full [writer] slabs dropped: ring full
        u64 wasted_words [writer] wrap padding burned
        u64 skipped_uncommitted [reader] torn slabs skipped on resync
    index ring: index_slots × 16-byte records
        u32 commit, u32 tag (call index/id), u32 npcs, u32 off_words
    data ring:  data_words × u32 raw PCs

Slab sizes are pow2-bucketed (min 8 words): a run of same-bucket slabs
is perfectly contiguous in the data ring, so a whole batch reshapes to
a (B, bucket) numpy VIEW — the device transfer consumes it directly
(dlpack/zero-copy on CPU, one DMA elsewhere) with no gather and no
padding copy.

Commit protocol (seqlock-style, single writer):

    1. store commit=0 + {tag, npcs, off} into the index record
    2. release-store resv_idx+1, head_words+bucket  (reservation visible)
    3. write the PC payload into the data ring
    4. release-store commit=1

A reader never sees a torn slab: it consumes only the committed prefix.
A writer SIGKILLed between (2) and (4) leaves one reserved-uncommitted
slab; `RingReader.resync()` skips it BY ITS LENGTH PREFIX (the npcs
field landed before the reservation was published), counts it in
`skipped_uncommitted`, and the ring keeps flowing — crash-only, like
the rest of the plane.  Ring-full is a counted drop (`dropped_full`),
never a blocked executor.

The ring is bidirectional by construction — single writer, single
reader, direction-agnostic.  The PROGRAM ring runs it the other way
(device→executor): slabs are complete exec-bytecode programs, u64
words stored as little-endian u32 pairs (lo then hi — a plain memory
view of the `encodingexec` wire format), `npcs` = live u32 words, and
the executor is the reader (native/executor.cc `prog_ring_*`).  The
commit protocol, pow2 buckets and resync semantics carry over
unchanged; `min_bucket` is sized to one program cap so a whole synth
batch lands as one contiguous same-bucket run (one vectorized
`write_batch`).  The writer-side recovery primitive for this
direction is `skip_committed`: when an executor dies before consuming
its slab, the fuzzer advances the read cursor past it so the next
ringed exec reads its OWN program.
"""

from __future__ import annotations

import mmap
import os
import struct
import time

import numpy as np

MAGIC = 0x53595A52494E4731        # 'SYZRING1' (little-endian bytes)
VERSION = 1
HDR_SIZE = 128
REC_WORDS = 4                     # index record size in u32 words
MIN_BUCKET = 8                    # smallest slab allocation, words

# header u64-slot indices (the header is viewed as 16 uint64 words;
# version/slab_cap share slot 1 as two u32 halves)
H_MAGIC, H_VER_CAP, H_INDEX_SLOTS, H_DATA_WORDS = 0, 1, 2, 3
H_RESV, H_HEAD, H_CONSUMED, H_TAIL = 4, 5, 6, 7
H_DROPPED, H_WASTED, H_SKIPPED, H_MIN_BUCKET = 8, 9, 10, 11

DEFAULT_DATA_WORDS = 1 << 20      # 4MB of raw PCs
DEFAULT_INDEX_SLOTS = 1 << 13
DEFAULT_SLAB_CAP = 512


def bucket_words(n: int, cap: int, min_bucket: int = MIN_BUCKET) -> int:
    """Pow2 slab allocation bucket for an n-PC cover (n clipped to cap).

    `min_bucket` quantizes small slabs up to one common bucket: mixed
    real-world cover sizes would otherwise fragment the ring into short
    same-bucket runs, and a run IS the zero-copy dispatch batch — a
    few padding words per slab buys full-width fused dispatches."""
    n = min(int(n), cap)
    b = max(MIN_BUCKET, int(min_bucket) or MIN_BUCKET)
    while b < n:
        b <<= 1
    return b


class PcRing:
    """One mapped ring file: header + index ring + data ring views.

    `create` initializes a fresh file (the Python side always owns
    initialization — the executor only ever attaches); `attach` maps an
    existing one.  All numpy views alias the mmap, so header mutations
    are immediately visible across processes (same coherence contract
    as the existing shm-out count word)."""

    def __init__(self, path: str, mm: mmap.mmap, fd: int):
        self.path = path
        self.mm = mm
        self.fd = fd
        hdr = np.frombuffer(mm, np.uint64, count=HDR_SIZE // 8, offset=0)
        if int(hdr[H_MAGIC]) != MAGIC:
            raise ValueError(f"{path}: bad ring magic")
        self.hdr = hdr
        ver_cap = int(hdr[H_VER_CAP])
        self.version = ver_cap & 0xFFFFFFFF
        self.slab_cap = ver_cap >> 32
        self.index_slots = int(hdr[H_INDEX_SLOTS])
        self.data_words = int(hdr[H_DATA_WORDS])
        self.min_bucket = max(MIN_BUCKET, int(hdr[H_MIN_BUCKET]))
        self.index = np.frombuffer(
            mm, np.uint32, count=self.index_slots * REC_WORDS,
            offset=HDR_SIZE).reshape(self.index_slots, REC_WORDS)
        self.data = np.frombuffer(
            mm, np.uint32, count=self.data_words,
            offset=HDR_SIZE + self.index_slots * REC_WORDS * 4)

    @staticmethod
    def file_size(data_words: int, index_slots: int) -> int:
        return HDR_SIZE + index_slots * REC_WORDS * 4 + data_words * 4

    @classmethod
    def create(cls, path: str, data_words: int = DEFAULT_DATA_WORDS,
               index_slots: int = DEFAULT_INDEX_SLOTS,
               slab_cap: int = DEFAULT_SLAB_CAP,
               min_bucket: int = MIN_BUCKET) -> "PcRing":
        size = cls.file_size(data_words, index_slots)
        with open(path, "wb") as f:
            f.truncate(size)
        fd = os.open(path, os.O_RDWR)
        mm = mmap.mmap(fd, size)
        struct.pack_into("<Q", mm, 0, MAGIC)
        # version/slab_cap packed as one u64 slot: low u32 version,
        # high u32 slab cap
        struct.pack_into("<Q", mm, 8, VERSION | (slab_cap << 32))
        struct.pack_into("<QQ", mm, 16, index_slots, data_words)
        struct.pack_into("<Q", mm, H_MIN_BUCKET * 8, min_bucket)
        return cls(path, mm, fd)

    @classmethod
    def attach(cls, path: str) -> "PcRing":
        fd = os.open(path, os.O_RDWR)
        size = os.fstat(fd).st_size
        mm = mmap.mmap(fd, size)
        return cls(path, mm, fd)

    def close(self) -> None:
        try:
            self.mm.close()
        except (BufferError, ValueError):
            pass                    # live views keep the map alive
        try:
            os.close(self.fd)
        except OSError:
            pass

    # -- header accessors (u64 loads/stores through the shared map) -------

    def load(self, slot: int) -> int:
        return int(self.hdr[slot])

    def store(self, slot: int, val: int) -> None:
        self.hdr[slot] = np.uint64(val)

    def stats(self) -> dict:
        return {"resv_idx": self.load(H_RESV),
                "consumed_idx": self.load(H_CONSUMED),
                "head_words": self.load(H_HEAD),
                "tail_words": self.load(H_TAIL),
                "dropped_full": self.load(H_DROPPED),
                "wasted_words": self.load(H_WASTED),
                "skipped_uncommitted": self.load(H_SKIPPED)}


class RingWriter:
    """Reference Python writer — the protocol twin of the executor's
    `ring_write` (native/executor.cc).  Production slabs come from the
    native side; this one feeds tests, bench replay, and the chaos
    harness.  `pause_before_commit` freezes a write between reservation
    and commit so the chaos harness can SIGKILL a writer mid-slab and
    prove the reader resyncs."""

    def __init__(self, ring: PcRing, pause_before_commit: bool = False):
        self.ring = ring
        self.pause_before_commit = pause_before_commit
        self.stat_written = 0

    def write(self, tag: int, pcs: np.ndarray) -> bool:
        """Append one slab; False = dropped (ring full)."""
        r = self.ring
        pcs = np.asarray(pcs, np.uint32).ravel()[: r.slab_cap]
        n = len(pcs)
        if n == 0:
            return True
        bucket = bucket_words(n, r.slab_cap, r.min_bucket)
        resv = r.load(H_RESV)
        if resv - r.load(H_CONSUMED) >= r.index_slots:
            r.store(H_DROPPED, r.load(H_DROPPED) + 1)
            return False
        head, tail, dw = r.load(H_HEAD), r.load(H_TAIL), r.data_words
        rem = dw - head % dw
        skip = rem if bucket > rem else 0
        if head + skip + bucket - tail > dw:
            r.store(H_DROPPED, r.load(H_DROPPED) + 1)
            return False
        off = (head + skip) % dw
        rec = r.index[resv % r.index_slots]
        rec[0] = 0                              # commit=0 first
        rec[1] = np.uint32(tag)
        rec[2] = np.uint32(n)
        rec[3] = np.uint32(off)
        r.store(H_WASTED, r.load(H_WASTED) + skip)
        r.store(H_HEAD, head + skip + bucket)
        r.store(H_RESV, resv + 1)               # reservation visible
        if self.pause_before_commit:
            # chaos hook: the slab is reserved but the payload/commit
            # never lands — the parent SIGKILLs us here
            while True:
                time.sleep(0.05)
        r.data[off: off + n] = pcs
        rec[0] = 1                              # commit
        self.stat_written += 1
        return True

    def write_batch(self, win: np.ndarray, counts) -> np.ndarray:
        """Append a whole (B, K) u32 slab matrix (row i live in
        [:counts[i]]) — the device→executor program-batch write.  When
        every row shares one bucket and the ring has room, the payload
        lands as ONE contiguous block copy (the reverse-direction twin
        of the reader's zero-copy batch view); otherwise rows fall back
        to per-slab writes.  Returns (B,) bool written-mask (False =
        dropped, ring full — counted, never blocking).  Tags are the
        writer's running slab sequence (attribution/debug)."""
        win = np.asarray(win, np.uint32)
        counts = np.asarray(counts, np.int64)
        r = self.ring
        B = len(counts)
        out = np.zeros((B,), bool)
        if B == 0:
            return out
        base_tag = self.stat_written
        clipped = np.clip(counts, 1, r.slab_cap)
        buckets = np.maximum(
            r.min_bucket,
            1 << np.ceil(np.log2(clipped)).astype(np.int64))
        bucket = int(buckets[0])
        n = 0
        if bool((buckets == bucket).all()) and not \
                self.pause_before_commit:
            resv = r.load(H_RESV)
            head, tail, dw = r.load(H_HEAD), r.load(H_TAIL), r.data_words
            rem = dw - head % dw
            skip = rem if bucket > rem else 0
            fits_idx = r.index_slots - (resv - r.load(H_CONSUMED))
            fits_data = (dw - (head + skip - tail)) // bucket
            contig = (dw - (head + skip) % dw) // bucket
            n = max(min(B, int(fits_idx), int(fits_data),
                        int(contig)), 0)
            if n > 0:
                off0 = (head + skip) % dw
                slots = (resv + np.arange(n)) % r.index_slots
                r.index[slots, 0] = 0            # commit=0 first
                r.index[slots, 1] = (base_tag
                                     + np.arange(n)) & 0xFFFFFFFF
                r.index[slots, 2] = np.minimum(
                    counts[:n], r.slab_cap).astype(np.uint32)
                r.index[slots, 3] = (off0 + np.arange(n) * bucket
                                     ).astype(np.uint32)
                r.store(H_WASTED, r.load(H_WASTED) + skip)
                r.store(H_HEAD, head + skip + n * bucket)
                r.store(H_RESV, resv + n)        # reservation visible
                dst = r.data[off0: off0 + n * bucket].reshape(n, bucket)
                k = min(bucket, win.shape[1])
                dst[:, :k] = win[:n, :k]
                r.index[slots, 0] = 1            # commit
                self.stat_written += n
                out[:n] = True
        # leftover rows (mixed buckets / ring wrap / ring full): the
        # per-slab writer handles wrap padding and counted drops
        for i in range(n, B):
            out[i] = self.write(self.stat_written, win[i, : counts[i]])
        return out


class SlabBatch:
    """One bucket-homogeneous committed run, as zero-copy views.

    `win` is a (n, bucket) uint32 VIEW over the data ring (row i's live
    prefix is `win[i, :counts[i]]`), safe to read until `consume()` —
    the writer cannot reuse the region before tail_words advances."""

    __slots__ = ("win", "counts", "tags", "start_idx", "n", "bucket")

    def __init__(self, win, counts, tags, start_idx, n, bucket):
        self.win = win
        self.counts = counts
        self.tags = tags
        self.start_idx = start_idx
        self.n = n
        self.bucket = bucket

    def cover(self, i: int) -> np.ndarray:
        """Materialize one slab's PCs (rare paths only — triage items)."""
        return np.array(self.win[i, : self.counts[i]], np.uint32)


class RingReader:
    """Batched consumer.  `read_batch` returns the largest power-of-two
    prefix of the committed same-bucket run (so dispatch shapes stay in
    the pow2 × pow2 closed set and the window is a contiguous reshape);
    the read cursor runs ahead of consumption so batches pipeline —
    `consume()` (after the device is done with the view) is what frees
    the region for the writer."""

    def __init__(self, ring: PcRing):
        self.ring = ring
        self.read_idx = ring.load(H_CONSUMED)
        self.stat_batches = 0
        self.stat_slabs = 0

    def pending(self) -> int:
        """Slabs reserved but not yet read (committed or not)."""
        return self.ring.load(H_RESV) - self.read_idx

    def unconsumed(self) -> int:
        return self.read_idx - self.ring.load(H_CONSUMED)

    def read_batch(self, max_slabs: "int | None" = None
                   ) -> "SlabBatch | None":
        r = self.ring
        resv = r.load(H_RESV)
        avail = resv - self.read_idx
        if avail <= 0:
            return None
        slot0 = self.read_idx % r.index_slots
        n = min(avail, r.index_slots - slot0)
        if max_slabs:
            n = min(n, max_slabs)
        recs = r.index[slot0: slot0 + n]
        commit = recs[:, 0]
        if not commit.all():
            n = int(np.argmin(commit != 0))      # committed prefix only
            if n == 0:
                return None
            recs = recs[:n]
        counts = recs[:, 2].astype(np.int64)
        # one pow2 bucket per batch: cap the run at the first bucket
        # change so the window is a dense (n, bucket) reshape
        buckets = np.maximum(
            self.ring.min_bucket,
            (1 << np.ceil(np.log2(np.maximum(counts, 1))).astype(np.int64)))
        change = np.nonzero(buckets != buckets[0])[0]
        if len(change):
            n = int(change[0])
        # cap at a data-ring wrap inside the run (offset decreases)
        offs = recs[:n, 3].astype(np.int64)
        wrap = np.nonzero(np.diff(offs) < 0)[0]
        if len(wrap):
            n = int(wrap[0]) + 1
        # largest pow2 prefix: dispatch batch shapes stay a closed set
        b = 1
        while b * 2 <= n:
            b *= 2
        n = b
        bucket = int(buckets[0])
        off0 = int(offs[0])
        win = r.data[off0: off0 + n * bucket].reshape(n, bucket)
        batch = SlabBatch(win=win,
                          counts=recs[:n, 2].astype(np.int32).copy(),
                          tags=recs[:n, 1].astype(np.int32).copy(),
                          start_idx=self.read_idx, n=n, bucket=bucket)
        self.read_idx += n
        self.stat_batches += 1
        self.stat_slabs += n
        return batch

    def consume(self, batch: SlabBatch) -> None:
        """Release a batch's region back to the writer.  Batches must be
        consumed in read order (the pipeline resolves them in order)."""
        r = self.ring
        cons = r.load(H_CONSUMED)
        if batch.start_idx != cons:
            raise ValueError(
                f"out-of-order consume: batch {batch.start_idx} != "
                f"consumed {cons}")
        tail, dw = r.load(H_TAIL), r.data_words
        off0 = int(batch.win.ctypes.data
                   - r.data.ctypes.data) // 4 if batch.n else tail % dw
        delta = (off0 - tail % dw) % dw          # wrap padding, if any
        r.store(H_TAIL, tail + delta + batch.n * batch.bucket)
        r.store(H_CONSUMED, cons + batch.n)

    def resync(self) -> int:
        """Skip reserved-but-uncommitted slabs at the front (a writer
        died mid-slab-write).  Only call when the writer is known dead —
        a live writer commits in bounded time.  Discards any read-ahead
        (those views may straddle the torn region) and returns how many
        slabs were skipped (also counted in the shared header)."""
        r = self.ring
        self.read_idx = r.load(H_CONSUMED)
        skipped = 0
        while r.load(H_RESV) > self.read_idx:
            rec = r.index[self.read_idx % r.index_slots]
            if rec[0] != 0:
                break
            npcs = int(rec[2])
            bucket = bucket_words(max(npcs, 1), r.slab_cap, r.min_bucket)
            tail, dw = r.load(H_TAIL), r.data_words
            off = int(rec[3])
            delta = (off - tail % dw) % dw
            r.store(H_TAIL, tail + delta + bucket)
            r.store(H_CONSUMED, self.read_idx + 1)
            self.read_idx += 1
            skipped += 1
        if skipped:
            r.store(H_SKIPPED, r.load(H_SKIPPED) + skipped)
        return skipped


def skip_committed(ring: PcRing, n: int = 1) -> int:
    """Advance the read cursor past up to n COMMITTED slabs without
    reading them — the reverse-direction (program ring) recovery: the
    writer (fuzzer) skips a slab whose reader (executor) died before
    consuming it, so reader/writer alignment is restored for the next
    exec.  Only call when the reader process is known dead.  Returns
    how many slabs were skipped (counted in `skipped_uncommitted` —
    same header slot, same 'lost to a crash' meaning)."""
    skipped = 0
    while skipped < n and ring.load(H_RESV) > ring.load(H_CONSUMED):
        cons = ring.load(H_CONSUMED)
        rec = ring.index[cons % ring.index_slots]
        npcs = int(rec[2])
        bucket = bucket_words(max(npcs, 1), ring.slab_cap,
                              ring.min_bucket)
        tail, dw = ring.load(H_TAIL), ring.data_words
        delta = (int(rec[3]) - tail % dw) % dw
        ring.store(H_TAIL, tail + delta + bucket)
        ring.store(H_CONSUMED, cons + 1)
        skipped += 1
    if skipped:
        ring.store(H_SKIPPED, ring.load(H_SKIPPED) + skipped)
    return skipped
