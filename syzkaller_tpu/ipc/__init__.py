"""IPC: shared-memory + pipe protocol between fuzzer and executor."""

from syzkaller_tpu.ipc.env import (  # noqa: F401
    FLAG_COLLIDE, FLAG_COVER, FLAG_DEBUG, FLAG_DEDUP_COVER, FLAG_ENABLE_TUN,
    FLAG_FAKE_COVER, FLAG_RING_SKIP, FLAG_SANDBOX_NAMESPACE,
    FLAG_SANDBOX_SETUID, FLAG_THREADED,
    CallResult, Env, ExecResult, ExecutorFailure, Gate,
)
from syzkaller_tpu.ipc.ring import (  # noqa: F401
    PcRing, RingReader, RingWriter, SlabBatch,
)
