"""Tiered corpus hierarchy: hot device tables / warm mmap'd segment
log / cold persistent corpus.  See segments.py for the wire format and
tiers.py for the promotion/eviction contract."""

from syzkaller_tpu.corpus.segments import (
    MAGIC,
    MAX_SEGMENTS,
    MIN_STRIDE,
    REC_COMMIT,
    UNOWNED,
    VERSION,
    SegmentError,
    WarmStore,
    decode_segment,
    encode_segment,
)
from syzkaller_tpu.corpus.tiers import TierManager

__all__ = [
    "MAGIC",
    "MAX_SEGMENTS",
    "MIN_STRIDE",
    "REC_COMMIT",
    "UNOWNED",
    "VERSION",
    "SegmentError",
    "WarmStore",
    "TierManager",
    "decode_segment",
    "encode_segment",
]
