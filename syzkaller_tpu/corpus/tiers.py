"""Three-tier corpus hierarchy: hot (device tables) / warm (mmap'd
segment log) / cold (persistent disk/hub corpus).

SURVEY §5 frames the device signal matrix as "a cache, rebuilt by
replays"; this module makes that literal and continuous.  The hot tier
keeps today's fixed-cap device tables and their zero-recompile dispatch
shapes; when admission runs past `corpus_cap`, the fused fuzz tick's
eviction-score kernel (kernels/oracles.py `evict_score` — per-row
shadowed-signal count decayed by admit recency, the device analog of
the reference's corpus minimization, manager.go:504-527) picks the
victims IN the same dispatch, and the host swaps the evicted rows'
contents out to the warm store.  Promotion is the reverse contents-only
swap (the `DeviceKeyMirror` growth pattern): warm rows ride back into
victim slots through a fixed-shape `swap_rows` dispatch, so warm-path
traffic never changes a dispatch signature and never recompiles.

The cold tier stays what it always was — the manager's persistent
corpus / hub exchange; this module only needs to know it exists (a
warm record's `owner` is the corpus item id both tiers key on).

Host index kept here (flat numpy, so the resolve path is loop-free):
  * row_owner (cap,)        — hot row -> corpus item id (-1 unowned)
  * _loc_kind/_loc_val (N,) — corpus item id -> tier (HOT/WARM/absent)
                              and its row / warm record id

`resolve_rows` is the warm-tier resolve path the hotpath vet pass
pins: one batched index lookup, at most ONE segment-store read and ONE
swap dispatch per batch — never a per-item read.
"""

from __future__ import annotations

import threading

import numpy as np

from syzkaller_tpu.corpus.segments import WarmStore

ABSENT, HOT, WARM = -1, 0, 1


class TierManager:
    """Glue between a CoverageEngine's hot tables and a WarmStore.

    Attach with `engine.attach_tiers(tm)`; from then on the engine's
    fused fuzz tick demotes instead of falling back unfused, and
    `merge_corpus` demotes instead of dropping.  All counters are plain
    ints mirrored into the engine's DeviceStats slots when telemetry is
    enabled (`syz_corpus_tier_*`)."""

    def __init__(self, store: "WarmStore | str", engine=None,
                 telemetry=None):
        self.store = (store if isinstance(store, WarmStore)
                      else WarmStore(store))
        self.engine = None
        self.tstats = telemetry
        self._mu = threading.RLock()
        self.row_owner: "np.ndarray | None" = None
        self._loc_kind = np.full(1024, ABSENT, np.int8)
        self._loc_val = np.zeros(1024, np.int64)
        self.stat_evictions = 0
        self.stat_promotions = 0
        self.stat_hot_hits = 0
        self.stat_hot_misses = 0
        if engine is not None:
            engine.attach_tiers(self)

    # -- engine attach ----------------------------------------------------

    def bind(self, engine) -> None:
        """Called by CoverageEngine.attach_tiers."""
        with self._mu:
            self.engine = engine
            if self.tstats is None:
                self.tstats = engine.tstats
            if self.row_owner is None or len(self.row_owner) != engine.cap:
                self.row_owner = np.full((engine.cap,), -1, np.int64)

    def _inc(self, key: str, n: int = 1) -> None:
        ts = self.tstats
        if ts is not None and n:
            ts.inc(key, n)

    # -- bookkeeping from the admission path ------------------------------

    def _grow_loc(self, top: int) -> None:
        if top < len(self._loc_kind):
            return
        n = len(self._loc_kind)
        while n <= top:
            n *= 2
        kind = np.full(n, ABSENT, np.int8)
        val = np.zeros(n, np.int64)
        kind[:len(self._loc_kind)] = self._loc_kind
        val[:len(self._loc_val)] = self._loc_val
        self._loc_kind, self._loc_val = kind, val

    def set_owners(self, rows, owners) -> None:
        """Record which corpus item each hot row currently holds
        (DeviceSignal calls this right after admission)."""
        rows = np.asarray(rows, np.int64)
        owners = np.asarray(owners, np.int64)
        if len(rows) == 0:
            return
        with self._mu:
            old = self.row_owner[rows]
            self.row_owner[rows] = owners
            stale = old[(old >= 0) & (old != owners)]
            if len(stale):
                self._loc_kind[stale] = ABSENT
            known = owners >= 0
            if known.any():
                self._grow_loc(int(owners[known].max()))
                self._loc_kind[owners[known]] = HOT
                self._loc_val[owners[known]] = rows[known]

    def on_evicted(self, victims, bitmaps, call_ids, admit_ticks) -> None:
        """Engine callback: hot rows whose contents were just replaced
        in-dispatch.  Their old contents append to the warm log; the
        victims' slots now belong to the incoming inputs (the caller
        follows up with set_owners)."""
        victims = np.asarray(victims, np.int64)
        n = len(victims)
        if n == 0:
            return
        with self._mu:
            owners = self.row_owner[victims]
            ids = self.store.append_rows(call_ids, bitmaps, admit_ticks,
                                         owners)
            known = owners >= 0
            if known.any():
                self._grow_loc(int(owners[known].max()))
                self._loc_kind[owners[known]] = WARM
                self._loc_val[owners[known]] = ids[known]
            self.row_owner[victims] = -1
            self.stat_evictions += n
        self._inc("tier_evictions", n)
        self._inc("tier_warm_rows", n)

    def on_compacted(self, mapping: dict) -> None:
        """Engine compaction moved hot rows (old row -> new row);
        unmapped rows were dropped — their owners fall out of the hot
        index (back to cold: re-discoverable through the persistent
        corpus, same as before tiers existed)."""
        with self._mu:
            if self.row_owner is None:
                return
            old = np.fromiter(mapping.keys(), np.int64, len(mapping))
            new = np.fromiter(mapping.values(), np.int64, len(mapping))
            owners = self.row_owner.copy()
            self.row_owner[:] = -1
            if len(old):
                self.row_owner[new] = owners[old]
            self._loc_kind[self._loc_kind == HOT] = ABSENT
            surv = self.row_owner >= 0
            o = self.row_owner[surv]
            if len(o):
                self._grow_loc(int(o.max()))
                self._loc_kind[o] = HOT
                self._loc_val[o] = np.nonzero(surv)[0]

    # -- the warm-tier resolve path (hotpath-vet pinned) ------------------

    def resolve_rows(self, owners) -> np.ndarray:
        """Corpus item ids -> hot row indices, promoting warm-resident
        items first.  Hot hits are an index lookup; misses cost ONE
        batched segment-store read + ONE fixed-shape swap dispatch for
        the whole batch (per-batch mmap reads only — never per-exec).
        Items in neither tier come back -1 (cold: the caller replays
        through the persistent corpus)."""
        owners = np.asarray(owners, np.int64)
        out = np.full(len(owners), -1, np.int64)
        with self._mu:
            inrange = (owners >= 0) & (owners < len(self._loc_kind))
            kind = np.full(len(owners), ABSENT, np.int8)
            kind[inrange] = self._loc_kind[owners[inrange]]
            val = np.zeros(len(owners), np.int64)
            val[inrange] = self._loc_val[owners[inrange]]
            hot = kind == HOT
            warm = kind == WARM
            out[hot] = val[hot]
            nhit = int(hot.sum())
            nmiss = int(warm.sum())
            self.stat_hot_hits += nhit
            self.stat_hot_misses += nmiss
            if nmiss:
                out[warm] = self.promote(val[warm])
        self._inc("tier_hot_hits", nhit)
        self._inc("tier_hot_misses", nmiss)
        return out

    def promote(self, rec_ids) -> np.ndarray:
        """Warm record ids -> hot rows.  Reads the records (one mmap
        gather), swaps them into the lowest-retention hot rows through
        the engine's fixed-shape swap dispatch (contents-only — zero
        warm recompiles), and demotes the displaced rows' contents back
        to the log.  Returns the hot rows now holding the records."""
        rec_ids = np.asarray(rec_ids, np.int64)
        n = len(rec_ids)
        if n == 0:
            return np.zeros((0,), np.int64)
        eng = self.engine
        with self._mu:
            calls, bitmaps, _pops, _ticks, owners = self.store.read_rows(
                rec_ids, eng.W)
            scores = eng.evict_scores()
            # victims: highest eviction score (most shadowed, oldest) —
            # never a row we are about to install into in this batch
            victims = np.argsort(scores, kind="stable")[::-1][:n]
            victims = victims.astype(np.int64)
            old_calls = eng.corpus_call[victims].copy()
            old_rows = eng.swap_rows(victims, bitmaps, calls)
            self.on_evicted(victims, old_rows, old_calls,
                            np.full((n,), eng.tick, np.int64))
            self.set_owners(victims, owners)
            self.stat_promotions += n
        self._inc("tier_promotions", n)
        return victims

    # -- snapshot integration ---------------------------------------------

    def flush(self) -> None:
        self.store.flush()

    def segment_refs(self) -> list[dict]:
        self.store.flush()
        return self.store.segment_refs()

    def snapshot_counters(self) -> dict:
        return {
            "rows_warm": self.store.rows_warm,
            "bytes_warm": self.store.bytes_warm,
            "evictions": self.stat_evictions,
            "promotions": self.stat_promotions,
            "hot_hits": self.stat_hot_hits,
            "hot_misses": self.stat_hot_misses,
            "segments_corrupt_skipped": self.store.corrupt_skipped,
        }
