"""Warm-tier corpus store: mmap'd slab-format append-log segments.

The hot tier (the engine's fixed-cap device tables) is a cache; this
store is its backing level.  Every row demoted from the device signal
matrix lands here as one slab-format record and stays addressable by a
monotonically increasing record id until compaction drops superseded
generations.  The on-disk layout generalizes two existing formats:

  * records reuse the PR-11 ring slab idiom (ipc/ring.py): fixed u32
    words, a commit tag up front, explicit lengths, pow2-bucketed
    strides — so a torn/garbage record is detectable from the record
    alone;
  * segments reuse the PR-9 `SYZSNAP1` envelope idiom
    (resilience/checkpoint.py): magic + JSON header + payload sha256,
    written crash-safe via tmp+rename (fileutil.write_file).  A
    segment is immutable once renamed into place; crash recovery is
    "load every segment that validates, newest compaction generation
    wins" — no write-ahead log, no fsync ordering games.

Segment wire format (little-endian):

    offset  size  field
    0       8     MAGIC  b"SYZWARM1"
    8       4     u32 header length H
    12      H     JSON header {"version": 2, "seq": int,
                   "count": int, "stride": int (u32 words/record),
                   "sha256": hex(payload), "supersedes": [seq, ...],
                   "meta": {...}}
    12+H    4*count*stride   payload: count records of stride u32 words

Record layout (stride u32 words, stride = pow2 bucket of the widest
record in the segment; the signal row rides in COO — word indices +
word values — because demoted rows are sparse by construction):

    word 0            REC_COMMIT (0x53595A43 'SYZC')
    word 1            record id (global, monotonically increasing)
    word 2            call id
    word 3            nnz (number of COO entries)
    word 4            popcount of the signal row (promotion score hint)
    word 5            admit tick (device recency at demotion)
    word 6            owner (corpus item id; 0xFFFFFFFF = unowned)
    word 7            reserved (0)
    word 8..8+nnz     COO word indices (columns into the W-word row)
    word 8+nnz..8+2nnz  COO word values
    ...               zero padding to stride

Reads are per-BATCH mmap gathers (np.memmap fancy indexing), never
per-record Python loops: the only loop in the read path is the
const-range sweep over the MAX_SEGMENTS segment slots (compaction
keeps the live segment count at or under that bound), which the
hotpath vet pass recognizes as constant-trip.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading

import numpy as np

from syzkaller_tpu.utils import fileutil
from syzkaller_tpu.utils.shapes import pow2_bucket

MAGIC = b"SYZWARM1"
VERSION = 2                     # rides the snapshot codec's v2 bump
REC_COMMIT = 0x53595A43         # 'SYZC'
HDR_WORDS = 8
MIN_STRIDE = 16
# live segment bound: read_rows sweeps exactly this many segment slots
# per batch (const-range — hotpath-vet clean) and maybe_compact folds
# the log back to one segment before the bound is hit
MAX_SEGMENTS = 16
UNOWNED = 0xFFFFFFFF


class SegmentError(Exception):
    pass


def encode_segment(seq: int, recs: np.ndarray, stride: int,
                   supersedes: "list[int]", meta: "dict | None" = None
                   ) -> bytes:
    """(count, stride) u32 record block -> one segment blob."""
    payload = np.ascontiguousarray(recs, dtype="<u4").tobytes()
    header = {
        "version": VERSION, "seq": int(seq), "count": int(recs.shape[0]),
        "stride": int(stride), "sha256": hashlib.sha256(payload).hexdigest(),
        "supersedes": [int(s) for s in supersedes], "meta": meta or {},
    }
    hb = json.dumps(header, sort_keys=True).encode()
    return MAGIC + np.uint32(len(hb)).tobytes() + hb + payload


def decode_segment(blob: bytes) -> tuple[dict, np.ndarray]:
    """Validate one segment blob -> (header, (count, stride) u32).
    Raises SegmentError on any corruption (magic, version, checksum,
    truncation) — the loader skips-and-counts, never bricks."""
    if len(blob) < len(MAGIC) + 4 or blob[:len(MAGIC)] != MAGIC:
        raise SegmentError("bad segment magic")
    hlen = int(np.frombuffer(blob[8:12], "<u4")[0])
    if len(blob) < 12 + hlen:
        raise SegmentError("truncated segment header")
    try:
        header = json.loads(blob[12:12 + hlen])
    except ValueError as e:
        raise SegmentError(f"bad segment header: {e}") from e
    if header.get("version") != VERSION:
        raise SegmentError(f"segment version {header.get('version')!r} "
                           f"!= {VERSION}")
    count, stride = int(header["count"]), int(header["stride"])
    payload = blob[12 + hlen:]
    if len(payload) != 4 * count * stride:
        raise SegmentError("segment payload length mismatch")
    if hashlib.sha256(payload).hexdigest() != header["sha256"]:
        raise SegmentError("segment checksum mismatch")
    recs = np.frombuffer(payload, "<u4").reshape(count, stride)
    if count and not (recs[:, 0] == REC_COMMIT).all():
        raise SegmentError("uncommitted record in segment")
    return header, recs


def _seg_name(seq: int) -> str:
    return f"seg-{seq:08d}.warm"


class WarmStore:
    """Append-log of demoted corpus rows with mmap'd batch reads.

    Thread-safe.  Appends buffer in memory until `flush()` (or the
    seg_records high-water mark) writes one immutable segment; readers
    see a record only after its segment is durable, which is exactly
    the crash contract the manager's persistence-before-resolve rule
    needs (flush before acking a demotion batch externally)."""

    def __init__(self, dirpath: str, seg_records: int = 8192,
                 expect_refs: "list[dict] | None" = None):
        self.dir = dirpath
        self.seg_records = seg_records
        self._mu = threading.RLock()
        # pending (not yet durable) records, as (count, width) blocks
        self._pending: list[np.ndarray] = []
        self._pending_n = 0
        # fixed segment slots (const-range read sweep): parallel lists
        # padded to MAX_SEGMENTS with None/zeros
        self._maps: list["np.memmap | None"] = [None] * MAX_SEGMENTS
        self._seqs = [0] * MAX_SEGMENTS
        self._nseg = 0
        # record directory: id -> (segment slot, row) — grown
        # geometrically, -1 = unknown id
        self._dir_seg = np.full(1024, -1, np.int32)
        self._dir_row = np.zeros(1024, np.int32)
        self.next_id = 0
        self.next_seq = 1
        self.corrupt_skipped = 0        # segments skipped on load
        self.ref_mismatches = 0         # snapshot refs that didn't check out
        self.bytes_warm = 0
        self.stat_flushes = 0
        self.stat_compactions = 0
        self._fault = None              # test hook: called at compaction stages
        os.makedirs(dirpath, exist_ok=True)
        self._load(expect_refs)

    # -- load / recovery -------------------------------------------------

    def _load(self, expect_refs: "list[dict] | None") -> None:
        names = sorted(n for n in os.listdir(self.dir)
                       if n.startswith("seg-") and n.endswith(".warm"))
        loaded: dict[int, tuple[str, dict]] = {}
        for name in names:
            path = os.path.join(self.dir, name)
            try:
                with open(path, "rb") as f:
                    header, _recs = decode_segment(f.read())
                loaded[int(header["seq"])] = (name, header)
            except (OSError, SegmentError):
                self.corrupt_skipped += 1
        # newest valid compaction generation wins: a validated segment
        # shadows every seq it supersedes; a corrupt compacted segment
        # simply never shadows, so its sources restore (zero loss)
        dead: set[int] = set()
        for seq in sorted(loaded, reverse=True):
            if seq in dead:
                continue
            dead.update(int(s) for s in loaded[seq][1]["supersedes"])
        live = [s for s in sorted(loaded) if s not in dead]
        if expect_refs is not None:
            have = {loaded[s][1]["sha256"] for s in live}
            self.ref_mismatches += sum(
                1 for r in expect_refs if r.get("sha256") not in have)
        for seq in live:
            name, header = loaded[seq]
            self._mount(os.path.join(self.dir, name), header)
        if loaded:
            self.next_seq = max(loaded) + 1

    def _mount(self, path: str, header: dict) -> None:
        count, stride = int(header["count"]), int(header["stride"])
        hlen = len(json.dumps(header, sort_keys=True).encode())
        mm = np.memmap(path, dtype="<u4", mode="r", offset=12 + hlen,
                       shape=(count, stride))
        slot = self._nseg
        if slot >= MAX_SEGMENTS:
            raise SegmentError("warm store segment slots exhausted "
                               "(compaction required)")
        self._maps[slot] = mm
        self._seqs[slot] = int(header["seq"])
        self._nseg += 1
        ids = np.asarray(mm[:, 1], np.int64)
        self._index(ids, slot, np.arange(count, dtype=np.int32))
        if count:
            self.next_id = max(self.next_id, int(ids.max()) + 1)
        self.bytes_warm += int(mm.nbytes)

    def _index(self, ids: np.ndarray, slot: int, rows: np.ndarray) -> None:
        if len(ids) == 0:
            return
        top = int(ids.max())
        if top >= len(self._dir_seg):
            n = len(self._dir_seg)
            while n <= top:
                n *= 2
            seg = np.full(n, -1, np.int32)
            row = np.zeros(n, np.int32)
            seg[:len(self._dir_seg)] = self._dir_seg
            row[:len(self._dir_row)] = self._dir_row
            self._dir_seg, self._dir_row = seg, row
        self._dir_seg[ids] = slot
        self._dir_row[ids] = rows

    # -- append (demotion) -----------------------------------------------

    def append_rows(self, call_ids, rows, admit_ticks, owners) -> np.ndarray:
        """Buffer a batch of demoted rows ((n, W) u32 bitmaps) as COO
        records; returns the assigned record ids.  Fully vectorized —
        the COO split is one np.nonzero over the whole batch."""
        rows = np.asarray(rows, np.uint32)
        n, _W = rows.shape
        if n == 0:
            return np.zeros((0,), np.int64)
        call_ids = np.asarray(call_ids, np.int64)
        admit_ticks = np.asarray(admit_ticks, np.int64)
        owners = np.asarray(owners, np.int64)
        r, c = np.nonzero(rows)
        nnz = np.bincount(r, minlength=n).astype(np.int64)
        width = HDR_WORDS + 2 * int(nnz.max(initial=0))
        pop = _popcount_rows_np(rows)
        with self._mu:
            ids = np.arange(self.next_id, self.next_id + n, dtype=np.int64)
            self.next_id += n
            block = np.zeros((n, width), np.uint32)
            block[:, 0] = REC_COMMIT
            block[:, 1] = ids.astype(np.uint32)
            block[:, 2] = call_ids.astype(np.uint32)
            block[:, 3] = nnz.astype(np.uint32)
            block[:, 4] = pop.astype(np.uint32)
            block[:, 5] = admit_ticks.astype(np.uint32)
            block[:, 6] = np.where(owners < 0, UNOWNED,
                                   owners).astype(np.uint32)
            start = np.concatenate([[0], np.cumsum(nnz)[:-1]])
            pos = np.arange(len(r)) - start[r]
            block[r, HDR_WORDS + pos] = c.astype(np.uint32)
            block[r, HDR_WORDS + nnz[r] + pos] = rows[r, c]
            self._pending.append(block)
            self._pending_n += n
            if self._pending_n >= self.seg_records:
                self._flush_locked()
        return ids

    def flush(self) -> None:
        """Make every buffered record durable (one tmp+rename segment)."""
        with self._mu:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._pending:
            return
        width = max(b.shape[1] for b in self._pending)
        stride = pow2_bucket(width, MIN_STRIDE, 1 << 16)
        recs = np.zeros((self._pending_n, stride), np.uint32)
        at = 0
        for b in self._pending:
            recs[at:at + b.shape[0], :b.shape[1]] = b
            at += b.shape[0]
        self._pending, self._pending_n = [], 0
        seq = self.next_seq
        self.next_seq += 1
        blob = encode_segment(seq, recs, stride, supersedes=[])
        path = os.path.join(self.dir, _seg_name(seq))
        fileutil.write_file(path, blob)
        header, _ = decode_segment(blob)
        self._mount(path, header)
        self.stat_flushes += 1
        self.maybe_compact()

    # -- read (resolve / promotion) --------------------------------------

    def read_rows(self, ids, W: int):
        """Per-BATCH mmap gather: record ids -> (call_ids (n,),
        bitmaps (n, W) u32, popcounts (n,), admit_ticks (n,),
        owners (n,)).  Unknown ids raise KeyError.  The only loop is
        the const-range sweep over the MAX_SEGMENTS segment slots."""
        ids = np.asarray(ids, np.int64)
        n = len(ids)
        call_ids = np.zeros((n,), np.int64)
        bitmaps = np.zeros((n, W), np.uint32)
        pops = np.zeros((n,), np.int64)
        ticks = np.zeros((n,), np.int64)
        owners = np.full((n,), -1, np.int64)
        with self._mu:
            if n == 0:
                return call_ids, bitmaps, pops, ticks, owners
            if int(ids.min()) < 0 or int(ids.max()) >= self.next_id:
                raise KeyError("unknown warm record id")
            if self._pending_n and int(ids.max()) >= \
                    self.next_id - self._pending_n:
                # a requested record is still buffered: make the batch
                # durable first so ONE mmap path serves every read
                self._flush_locked()
            if int(ids.max()) >= len(self._dir_seg):
                raise KeyError("unknown warm record id")
            seg = self._dir_seg[ids]
            row = self._dir_row[ids]
            if (seg < 0).any():
                raise KeyError("unknown warm record id")
            for slot in range(MAX_SEGMENTS):
                mm = self._maps[slot]
                here = seg == slot
                if mm is None or not here.any():
                    continue
                recs = np.asarray(mm[row[here]])       # ONE mmap gather
                call_ids[here] = recs[:, 2]
                pops[here] = recs[:, 4]
                ticks[here] = recs[:, 5]
                own = recs[:, 6].astype(np.int64)
                owners[here] = np.where(own == UNOWNED, -1, own)
                nnz = recs[:, 3].astype(np.int64)
                K = (recs.shape[1] - HDR_WORDS) // 2
                k = np.arange(K)
                valid = k[None, :] < nnz[:, None]
                cols = recs[:, HDR_WORDS:HDR_WORDS + K]
                base = HDR_WORDS + nnz[:, None] + k[None, :]
                vals = np.take_along_axis(recs, np.minimum(
                    base, recs.shape[1] - 1), axis=1)
                dst = np.nonzero(here)[0]
                rr = np.broadcast_to(dst[:, None], valid.shape)[valid]
                cc = cols[valid].astype(np.int64)
                ok = cc < W
                bitmaps[rr[ok], cc[ok]] = vals[valid][ok]
        return call_ids, bitmaps, pops, ticks, owners

    def known(self, ids) -> np.ndarray:
        """(n,) bool — which record ids are resolvable."""
        ids = np.asarray(ids, np.int64)
        with self._mu:
            ok = (ids >= 0) & (ids < len(self._dir_seg))
            out = np.zeros(len(ids), bool)
            out[ok] = self._dir_seg[ids[ok]] >= 0
            # buffered-but-not-yet-durable records are resolvable too
            # (read_rows flushes on demand)
            if self._pending_n:
                out |= (ids >= self.next_id - self._pending_n) \
                    & (ids < self.next_id)
        return out

    @property
    def rows_warm(self) -> int:
        with self._mu:
            return int((self._dir_seg >= 0).sum()) + self._pending_n

    # -- compaction ------------------------------------------------------

    def maybe_compact(self) -> bool:
        with self._mu:
            if self._nseg < MAX_SEGMENTS - 1:
                return False
            self.compact()
            return True

    def compact(self) -> None:
        """Fold every live segment into one: keep the newest record per
        owner (a re-demoted row supersedes its older generation; id
        order IS recency) plus every unowned record.  Crash-safe in
        every window: the new segment lands via tmp+rename and lists
        the seqs it supersedes, so a SIGKILL before the rename leaves
        the old chain untouched, and one after it makes the old chain
        shadowed-but-harmless until the unlinks finish."""
        with self._mu:
            self._flush_pending_for_compact()
            slots = [s for s in range(MAX_SEGMENTS)
                     if self._maps[s] is not None]
            if not slots:
                return
            if self._fault is not None:
                self._fault("pre-write")
            blocks = [np.asarray(self._maps[s]) for s in slots]
            stride = max(b.shape[1] for b in blocks)
            recs = np.zeros((sum(b.shape[0] for b in blocks), stride),
                            np.uint32)
            at = 0
            for b in blocks:
                recs[at:at + b.shape[0], :b.shape[1]] = b
                at += b.shape[0]
            ids = recs[:, 1].astype(np.int64)
            order = np.argsort(ids, kind="stable")
            recs = recs[order]
            own = recs[:, 6].astype(np.int64)
            # newest record per owner: last occurrence in id order
            last = np.zeros(len(recs), bool)
            if len(recs):
                uniq, first = np.unique(own[::-1], return_index=True)
                keep_pos = len(recs) - 1 - first
                last[keep_pos] = True
                last[own == UNOWNED] = True
            recs = recs[last]
            seq = self.next_seq
            self.next_seq += 1
            supersedes = [self._seqs[s] for s in slots]
            blob = encode_segment(seq, recs, stride, supersedes=supersedes)
            path = os.path.join(self.dir, _seg_name(seq))
            fileutil.write_file(path, blob)
            if self._fault is not None:
                self._fault("post-write")
            for s in slots:
                try:
                    os.unlink(os.path.join(self.dir,
                                           _seg_name(self._seqs[s])))
                except OSError:
                    pass
                if self._fault is not None:
                    self._fault("mid-unlink")
            # remount from the compacted generation
            self._maps = [None] * MAX_SEGMENTS
            self._seqs = [0] * MAX_SEGMENTS
            self._nseg = 0
            self._dir_seg = np.full(len(self._dir_seg), -1, np.int32)
            self.bytes_warm = 0
            header, _ = decode_segment(blob)
            self._mount(path, header)
            self.stat_compactions += 1

    def _flush_pending_for_compact(self) -> None:
        if self._pending:
            self._flush_locked()

    # -- snapshot integration --------------------------------------------

    def segment_refs(self) -> list[dict]:
        """Durable-segment references for the v2 snapshot header —
        refs, never inline blobs (the segments ARE the warm tier's
        durability; the snapshot only has to name them)."""
        with self._mu:
            return [{"file": _seg_name(self._seqs[s]),
                     "seq": int(self._seqs[s]),
                     "count": int(self._maps[s].shape[0]),
                     "sha256": hashlib.sha256(
                         np.ascontiguousarray(self._maps[s]).tobytes()
                     ).hexdigest()}
                    for s in range(MAX_SEGMENTS)
                    if self._maps[s] is not None]


def _popcount_rows_np(rows: np.ndarray) -> np.ndarray:
    """(n, W) u32 -> (n,) per-row set-bit counts."""
    return np.unpackbits(
        np.ascontiguousarray(rows).view(np.uint8),
        axis=1).sum(axis=1, dtype=np.int64)
