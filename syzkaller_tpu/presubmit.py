"""One-command CI gate (ref Makefile:61-69 `make presubmit` =
generate + build + vet + test): compile the description table, run the
syz-vet static analyzer (lock discipline, device hot-path purity,
retrace hazards, RPC schema drift, stats lint), build the native
executor, run the full pytest suite on the 8-virtual-device CPU mesh,
and smoke the device engine.

    python -m syzkaller_tpu.presubmit [--quick]
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time


def step(name: str, fn) -> float:
    t0 = time.time()
    print(f"[presubmit] {name}...", flush=True)
    fn()
    dt = time.time() - t0
    print(f"[presubmit] {name} ok ({dt:.1f}s)", flush=True)
    return dt


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="skip the slow integration tests")
    args = ap.parse_args(argv)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    def gen_tables():
        from syzkaller_tpu.sys.table import load_table
        table = load_table()
        assert table.count >= 250, f"only {table.count} syscalls described"
        print(f"[presubmit]   {table.count} syscalls, "
              f"{len(table.resources)} resources")

    def build_executor():
        from syzkaller_tpu.native.build import build_executor as be
        path = be()
        assert os.path.exists(path)

    def pytest_run():
        cmd = [sys.executable, "-m", "pytest", "tests/", "-x", "-q"]
        if args.quick:
            cmd += ["-k", "not integration"]
        r = subprocess.run(cmd, cwd=root, env=env)
        if r.returncode != 0:
            raise SystemExit(f"pytest failed ({r.returncode})")

    def engine_smoke():
        r = subprocess.run(
            [sys.executable, "-c",
             "import __graft_entry__ as g, jax; "
             "fn, a = g.entry(); jax.block_until_ready(jax.jit(fn)(*a)); "
             "g.dryrun_multichip(8); print('engine ok')"],
            cwd=root, env=env)
        if r.returncode != 0:
            raise SystemExit("engine smoke failed")

    def vet():
        # single static-analysis entry point (syzkaller_tpu/vet): lock
        # discipline, device hot-path purity, retrace hazards, RPC
        # schema drift, stats lint, and the buffer-lifetime passes
        # (donation flow / host aliasing / epoch staleness).  --ratchet
        # makes unbaselined P1s block too: the tree's P1 count can only
        # go down, or each new one gets a justified baseline entry.
        r = subprocess.run(
            [sys.executable, "-m", "syzkaller_tpu.vet", "--ratchet"],
            cwd=root, env=env)
        if r.returncode != 0:
            raise SystemExit(f"vet failed ({r.returncode})")

    # a live manager must serve /metrics with the core series on every
    # plane — the contract dashboards and bench scrape against.  Runs in
    # a subprocess (like engine_smoke) so the presubmit process itself
    # never initializes an accelerator runtime.
    _TELEMETRY_SMOKE = r"""
import tempfile, urllib.request
from syzkaller_tpu.manager import html
from syzkaller_tpu.manager.config import Config
from syzkaller_tpu.manager.manager import Manager
from syzkaller_tpu.telemetry import expo

cfg = Config(workdir=tempfile.mkdtemp(prefix="syz-presubmit-"),
             type="local", count=1, descriptions="probe.txt",
             npcs=1 << 12, corpus_cap=64, http="")
mgr = Manager(cfg)
srv = html.serve(mgr, "127.0.0.1", 0)
host, port = srv.server_address
with urllib.request.urlopen(
        "http://%s:%d/metrics" % (host, port), timeout=10) as resp:
    assert resp.status == 200
    series = expo.parse_prometheus_text(resp.read().decode())
assert len(series) >= 20, "only %d series" % len(series)
for must in ("syz_admission_inputs_total",
             "syz_admission_new_inputs_total",
             'syz_cover_dispatches_total{kind="dense"}',
             "syz_exec_rate", "syz_crash_total",
             'syz_rpc_requests_total{method="Manager.Poll"}',
             "syz_corpus_size", "syz_uptime_seconds"):
    assert must in series, "/metrics missing " + must
srv.shutdown()
mgr.stop()
print("telemetry ok: %d series" % len(series))
"""

    def telemetry_smoke():
        r = subprocess.run([sys.executable, "-c", _TELEMETRY_SMOKE],
                           cwd=root, env=env)
        if r.returncode != 0:
            raise SystemExit("telemetry smoke failed")

    # fleet console over a live manager + an in-process hub: the fleet
    # JSON must carry the summary/SLO/flag structure, the HTML must
    # render, and both /metrics bodies must pass the STRICT Prometheus
    # text-format parser with the exact exposition content-type.
    _CONSOLE_SMOKE = r"""
import tempfile, urllib.request
from syzkaller_tpu.hub import http as hub_http
from syzkaller_tpu.hub.hub import Hub
from syzkaller_tpu.manager import html
from syzkaller_tpu.manager.config import Config
from syzkaller_tpu.manager.manager import Manager
from syzkaller_tpu.observe import FleetConsole
from syzkaller_tpu.telemetry import expo

cfg = Config(workdir=tempfile.mkdtemp(prefix="syz-presubmit-"),
             type="local", count=1, descriptions="probe.txt",
             npcs=1 << 12, corpus_cap=64, http="")
mgr = Manager(cfg)
srv = html.serve(mgr, "127.0.0.1", 0)
hub = Hub(tempfile.mkdtemp(prefix="syz-presubmit-hub-"), key="k")
hub.serve_background()
hsrv = hub_http.serve(hub, "127.0.0.1", 0)
murl = "http://%s:%d" % srv.server_address
hurl = "http://%s:%d" % hsrv.server_address
for url in (murl, hurl):
    with urllib.request.urlopen(url + "/metrics", timeout=10) as resp:
        assert resp.status == 200
        ct = resp.headers.get("Content-Type")
        assert ct == expo.CONTENT_TYPE, "bad /metrics content-type: " + str(ct)
        expo.parse_prometheus_text_strict(resp.read().decode())
console = FleetConsole([("m0", murl)], hub_url=hurl)
fleet = console.scrape()
m0 = fleet["managers"]["m0"]
assert not m0["host_down"] and "summary" in m0 and "slo" in m0, m0
assert "syz_slo_coverage_stall_seconds" in m0["slo"], m0["slo"]
assert fleet["hub"] is not None and not fleet["hub"]["host_down"]
page = console.render_html()
assert "fleet console" in page and "m0" in page
hsrv.shutdown(); srv.shutdown(); hub.close(); mgr.stop()
print("console ok: %d managers, hub corpus %s"
      % (len(fleet["managers"]), fleet["hub"]["corpus"]))
"""

    def console_smoke():
        r = subprocess.run([sys.executable, "-c", _CONSOLE_SMOKE],
                           cwd=root, env=env)
        if r.returncode != 0:
            raise SystemExit("console smoke failed")

    # syz-san armed end-to-end: a tick storm through the full stack
    # (DeviceSignal fused ticks + DecisionStream prefetch + a mid-storm
    # injected failover on a ResilientEngine) must finish with ZERO
    # sanitizer findings — the runtime plane agrees the production
    # idioms are clean, not just the static plane.
    _SAN_SMOKE = r"""
import os
os.environ["SYZ_SAN"] = "1"
import numpy as np
from syzkaller_tpu import san
from syzkaller_tpu.cover.engine import CoverageEngine
from syzkaller_tpu.fuzzer.device_ct import DecisionStream
from syzkaller_tpu.fuzzer.device_signal import DeviceSignal
from syzkaller_tpu.resilience import ResilientEngine

def mk():
    return CoverageEngine(npcs=1 << 10, ncalls=8, corpus_cap=64,
                          batch=4, max_pcs_per_exec=16)

sig = DeviceSignal(ncalls=8, npcs=1 << 13, flush_batch=4, max_pcs=16)
ds = DecisionStream(sig.engine, per_row=8, hot_slots=64, corpus_rows=32,
                    entropy_words=1024, autostart=False)
rng = np.random.default_rng(7)
for i in range(8):
    win = rng.integers(1, 1 << 20, (4, 16)).astype(np.uint32)
    counts = rng.integers(1, 16, (4,)).astype(np.int32)
    cids = rng.integers(0, 8, (4,)).astype(np.int32)
    ep = ds.epoch()
    ticket, _res = sig.submit_tick(
        win, counts, cids,
        decision_sink=lambda d, epoch=None: ds.feed(-1, d, epoch=epoch),
        decision_epoch=ep)
    sig.resolve(ticket)
    ds.refill_once()
    ds.choose(prev_call_id=-1)
    ds.take_entropy(64)
    if i % 3 == 2:
        ds.invalidate()

# mid-storm failover, armed: the supervisor re-attaches the checker on
# the fallback and the storm continues finding nothing
eng = ResilientEngine(mk(), mk, probe_interval=0.0)
stream = DecisionStream(eng, per_row=8, hot_slots=64, corpus_rows=32,
                        entropy_words=1024, autostart=False)
eng._on_swap = lambda d: stream.rebind()
stream.refill_once()
eng.injector.arm(1)
for _ in range(4):
    stream.refill_once()
    stream.choose(prev_call_id=-1)
assert eng.degraded and eng.stat_failovers == 1, \
    (eng.degraded, eng.stat_failovers)
eng.probe()
assert not eng.degraded
stream.refill_once()
stream.stop(); ds.stop()
s = san.summary()
assert s["armed"] and s["total"] == 0, s
print("san smoke ok: armed storm + failover, 0 findings")
"""

    def san_smoke():
        r = subprocess.run([sys.executable, "-c", _SAN_SMOKE],
                           cwd=root, env=env)
        if r.returncode != 0:
            raise SystemExit("san smoke failed")

    def chaos_smoke():
        # one SIGKILL/restore cycle against a real manager subprocess
        # (mid-admission-storm kill, snapshot restore + tail replay,
        # frontier bit-exact vs a never-crashed serial run) PLUS the
        # autopilot compound-failure cycle (2 VM threads killed +
        # backend flap + wedged campaign, remediated with zero
        # operator input)
        import json

        r = subprocess.run(
            [sys.executable, "tools/chaos.py", "--smoke"],
            cwd=root, env=env, capture_output=True, text=True,
            timeout=600)
        if r.returncode != 0:
            sys.stderr.write(r.stdout[-2000:] + r.stderr[-2000:])
            raise SystemExit(f"chaos smoke failed ({r.returncode})")
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["frontier_bit_exact"] and out["corpus_lost"] == 0, out
        # zero-copy ingest fold-in: the mid-slab-write SIGKILL cycle
        # must skip the torn slab (counted) and resync the ring
        ringc = out["ring"]
        assert ringc["ring_resynced"] and ringc["ring_torn_skipped"] == 1, \
            ringc
        # synth fold-in, reverse direction: executor killed mid-
        # program-slab-READ re-reads on relaunch; fuzzer killed
        # mid-WRITE leaves exactly one torn slab, skipped + resynced
        prc = out["prog_ring"]
        assert prc["prog_ring_reader_reread"] \
            and prc["prog_ring_torn_skipped"] == 1 \
            and prc["prog_ring_resynced"], prc
        # mesh-plane fold-in: one of two hub-federated managers is
        # SIGKILLed mid-sync; the survivor must keep admitting, the
        # restart must reconverge to the full union corpus (a sketch
        # false negative would leave a hole), and the sketch must have
        # withheld real traffic (filtered > 0 = strictly-fewer-than-
        # naive exchange)
        hubc = out["hub"]
        assert hubc["survivor_kept_fuzzing"] \
            and hubc["exchange_false_negatives"] == 0 \
            and hubc["hub_sketch_filtered"] > 0, hubc
        # fleet-observatory fold-in: the console must see the killed
        # manager as host_down with series FROZEN, raise the sync-stall
        # SLO flag the autopilot's own verdict function agrees with,
        # and stitch cross-host lineage for ≥1 hub-shipped program
        assert hubc["console_host_down"] \
            and hubc["console_series_frozen"] \
            and hubc["console_slo_matches_autopilot"] \
            and hubc["console_lineage"] >= 1, hubc
        auto = out["autopilot"]
        assert auto["recovered"] and auto["frontier_bit_exact"] \
            and auto["corpus_lost"] == 0 \
            and auto["post_promotion_recompiles"] == 0, auto
        print(f"[presubmit]   recovery {out['recovery_seconds']}s, "
              f"corpus {out['corpus_size']}, 0 lost; autopilot "
              f"detect {auto['autopilot_detect_seconds']}s / recover "
              f"{auto['autopilot_recover_seconds']}s; hub fleet "
              f"reconverge {hubc['reconverge_seconds']}s")

    def mesh_smoke():
        # two-process pod-topology seam: loopback jax.distributed
        # handshake (2 procs x 4 local = 8 global devices), process-
        # local slice math, and sharded==serial bit-exactness at 0 warm
        # recompiles in every process + the 8-device parent mesh
        import json

        r = subprocess.run(
            [sys.executable, "tools/mesh_smoke.py", "--smoke"],
            cwd=root, env=env, capture_output=True, text=True,
            timeout=600)
        if r.returncode != 0:
            sys.stderr.write(r.stdout[-2000:] + r.stderr[-2000:])
            raise SystemExit(f"mesh smoke failed ({r.returncode})")
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["ok"] and out["parent"]["bit_exact"], out
        print(f"[presubmit]   2-process handshake ok, "
              f"{out['parent']['devices']}-device parent mesh, "
              f"{out['parent']['bits_lit']} bits bit-exact")

    def bench_smoke():
        # seconds-scale CPU-only bench pass on tiny shapes: catches
        # bench.py import/shape regressions here instead of in the next
        # full bench round (which historically surfaced them as rc=1).
        # Runs with the backend-init probe FORCED to fail: bench must
        # exit 0 through the CPU fallback with the default backend
        # unavailable (the BENCH_r05 regression, pinned here)
        import json

        benv = dict(env)
        benv["SYZ_BENCH_FORCE_BACKEND_FAIL"] = "1"
        benv.pop("JAX_PLATFORMS", None)
        r = subprocess.run(
            [sys.executable, "bench.py", "--smoke"],
            cwd=root, env=benv, capture_output=True, text=True)
        if r.returncode != 0:
            sys.stderr.write(r.stderr[-2000:])
            raise SystemExit(f"bench smoke failed ({r.returncode})")
        line = r.stdout.strip().splitlines()[-1]
        out = json.loads(line)            # the JSON line must parse
        assert out["metric"] and out["extras"], out
        assert out["extras"].get("backend") == "cpu-fallback", \
            "forced backend failure did not take the CPU fallback"
        assert out["extras"].get("ingest_dispatches_const"), \
            "ingest per-exec dispatch count not constant"
        dev = out["extras"]["replay_execs_per_sec_device"]
        cpu = out["extras"]["replay_execs_per_sec_cpu"]
        assert dev >= cpu, \
            f"zero-copy replay lost to CPU on the same backend: " \
            f"{dev} < {cpu}"
        # device program synthesis acceptance: ≥10x the host generator
        # on the same backend at zero warm recompiles
        sd = out["extras"]["programs_per_sec_device"]
        sh = out["extras"]["programs_per_sec_host"]
        assert sd >= 10 * sh, \
            f"synth megakernel under 10x host generator: {sd} vs {sh}"
        assert out["extras"]["synth_recompiles_warm"] == 0, \
            "synth megakernel recompiled warm"
        # mesh-plane acceptance: the sharded signal-diff path must
        # stay recompile-free warm, and the hub exchange bench must
        # prove 0 sketch false negatives while filtering > 0 programs
        assert out["extras"]["sharded_recompiles_warm"] == 0, \
            "sharded engine recompiled warm"
        assert out["extras"]["signal_diff_prio_updates_per_sec_sharded"] > 0
        assert out["extras"]["hub_sync_programs_per_sec"] > 0
        assert out["extras"]["hub_sketch_fn"] == 0, \
            "hub sketch produced exchange false negatives"
        assert out["extras"]["hub_sketch_filtered"] > 0, \
            "hub sketch never filtered (naive-equivalent exchange)"
        # fleet-observatory acceptance: the coalesced admission path
        # with full telemetry must stay within the overhead envelope
        # (the full bench tracks the real <5% figure; the smoke gate is
        # loose because tiny-shape CPU runs are noisy), and the tsdb
        # rollup must never recompile warm
        overhead = out["extras"]["telemetry_overhead_pct"]
        assert overhead < 50, \
            f"telemetry overhead {overhead}% out of envelope"
        assert out["extras"]["tsdb_recompiles_warm"] == 0, \
            "tsdb rollup kernel recompiled warm"
        # kernel-plane acceptance: the fused fuzz tick must stay
        # bit-exact vs the unfused ingest+admit pair, cross the host
        # boundary ONCE per batch (counted via /profile/dispatches),
        # and the dispatch_top table must ride the JSON
        assert out["extras"]["fuzz_tick_parity"], \
            "fused fuzz_tick diverged from the unfused pair"
        fused = out["extras"]["dispatches_per_tick_fused"]
        unfused = out["extras"]["dispatches_per_tick_unfused"]
        assert fused == 1, \
            f"fused fuzz tick is {fused} dispatches/batch, want 1"
        assert fused < unfused, \
            f"fusion did not reduce dispatches: {fused} vs {unfused}"
        top = out["extras"]["dispatch_top"]
        assert top and all(
            set(d) == {"name", "calls", "seconds_sum", "recompiles"}
            for d in top), "malformed dispatch_top table"
        # tiered-corpus acceptance: fuzzing ≥100x past corpus_cap must
        # keep the recency-skewed working set ≥90% hot-tier resident,
        # compile NOTHING on the warm promote/demote paths
        # (contents-only swaps behind fixed dispatch signatures), and
        # stay frontier bit-exact vs an unbounded-table oracle
        hr = out["extras"]["tier_hot_hit_rate"]
        assert hr >= 0.9, \
            f"hot-tier hit rate {hr} under the 90% working-set gate"
        assert out["extras"]["tier_recompiles_warm"] == 0, \
            "tiered corpus promote/demote path recompiled warm"
        assert out["extras"]["tier_frontier_bit_exact"], \
            "tiered frontier diverged from the unbounded oracle"
        # syz-san acceptance: the smoke must measure the armed-vs-
        # unarmed fuzz-tick cost so overhead drift is visible per run
        # (tiny CPU shapes are noisy, so only sanity-bound it)
        sanpct = out["extras"]["san_overhead_pct"]
        assert isinstance(sanpct, (int, float)) and sanpct < 500, \
            f"san overhead {sanpct}% out of envelope"

    total = 0.0
    total += step("description tables", gen_tables)
    total += step("vet (static analysis + stats lint)", vet)
    total += step("native executor build", build_executor)
    total += step("engine + multichip smoke", engine_smoke)
    total += step("telemetry smoke", telemetry_smoke)
    total += step("console smoke (fleet observatory)", console_smoke)
    total += step("san smoke (runtime sanitizer, armed)", san_smoke)
    total += step("chaos smoke (kill/restore cycle)", chaos_smoke)
    total += step("mesh smoke (two-process pod seam)", mesh_smoke)
    total += step("bench smoke", bench_smoke)
    total += step("pytest", pytest_run)
    print(f"[presubmit] PASS in {total:.0f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
