"""ifuzz equivalent: mode-aware machine-code generation and mutation.

Capability parity with reference ifuzz/ (ifuzz.go:16-57 modes + opcode
metadata, encode.go/decode.go, pseudo.go:10-50 pseudo-op sequences):
TEXT buffer args become valid-ish instruction streams instead of random
bytes, unlocking KVM guest fuzzing (`syz_kvm_setup_cpu` text payloads).

Four x86 modes (real16/prot16/prot32/long64) share one curated table
(insns.py) with exact ModRM/SIB/displacement/immediate length rules, so
`insn_len` decodes exactly what `gen_insn` emits — the roundtrip
property the tests pin.  ARM64 text is 4-byte words from a small
pattern set (the reference's snapshot has no arm64 table either).
"""

from __future__ import annotations

from syzkaller_tpu.ifuzz.insns import (
    ALL, IMM_OPSIZE, IMM_OPSIZE64, LONG64, NOT64, PROT16, PROT32, REAL16,
    Insn, TABLE, by_mode, opcode_index)

MODES = (REAL16, PROT16, PROT32, LONG64)

# mode -> table subset, computed once (gen_insn runs per generated
# instruction in the fuzzing hot loop; rebuilding the filtered pool per
# call scans the whole ~600-entry table each time)
_POOLS = {m: tuple(by_mode(m)) for m in MODES}

_PREFIXES = frozenset(
    (0x26, 0x2E, 0x36, 0x3E, 0x64, 0x65, 0x66, 0x67, 0xF0, 0xF2, 0xF3))
_SEG_PREFIXES = (0x26, 0x2E, 0x36, 0x3E, 0x64, 0x65)

_IDX = opcode_index()
_MAX_OP_LEN = max(len(op) for op in _IDX)


def _imm_len(imm: int, mode: int, has66: bool, rexw: bool) -> int:
    if imm >= 0:
        return imm
    if mode == LONG64:
        if imm == IMM_OPSIZE64 and rexw:
            return 8
        return 2 if (has66 and not rexw) else 4
    if mode == PROT32:
        return 2 if has66 else 4
    return 4 if has66 else 2            # real16 / prot16


def _modrm_tail_len(modrm: int, addr16: bool, regonly: bool) -> int:
    """Bytes following the ModRM byte (SIB + displacement)."""
    if regonly:
        return 0
    mod, rm = modrm >> 6, modrm & 7
    if mod == 3:
        return 0
    if addr16:
        if mod == 0:
            return 2 if rm == 6 else 0
        return 1 if mod == 1 else 2
    n = 0
    sib = rm == 4
    if sib:
        n += 1
    if mod == 0:
        if rm == 5:
            n += 4
        elif sib:
            n += 0  # base!=5 assumed by encoder; decoder peeks SIB below
    elif mod == 1:
        n += 1
    else:
        n += 4
    return n


# -- encoding ---------------------------------------------------------------


def gen_insn(r, mode: int, insn: "Insn | None" = None) -> bytes:
    """One encoded instruction valid for `mode` (random table pick if
    `insn` is None), with randomized prefixes/REX/ModRM/imm."""
    if insn is None:
        pool = _POOLS[mode]
        insn = pool[r.intn(len(pool))]
    out = bytearray()
    # VEX2-wrapped form of a plain 0F-map instruction (long mode only:
    # C5 is LDS elsewhere).  NP (pp=00) payloads only; no REX/66 mixes.
    vex = (mode == LONG64 and len(insn.op) == 2 and insn.op[0] == 0x0F
           and not insn.plusr and insn.imm in (0, 1) and r.one_of(12))
    if not vex and r.one_of(8):
        out.append(_SEG_PREFIXES[r.intn(len(_SEG_PREFIXES))])
    has66 = (not vex and insn.imm in (IMM_OPSIZE, IMM_OPSIZE64)
             and r.one_of(6))
    if has66:
        out.append(0x66)
    rexw = False
    if not vex and mode == LONG64 and r.one_of(3):
        rex = 0x40 | r.intn(16)
        rexw = bool(rex & 8)
        out.append(rex)
    op = bytearray(insn.op)
    if insn.plusr:
        op[-1] |= r.intn(8)
    if vex:
        out.append(0xC5)
        out.append((r.intn(256) & 0xFC))   # R/vvvv/L random, pp = 00
        out += op[1:]                      # the 0F escape is implied
    else:
        out += op
    if insn.modrm:
        regonly = insn.regonly
        mem_only = insn.memonly
        while True:
            modrm = r.intn(256)
            if insn.digit >= 0:
                modrm = (modrm & 0xC7) | (insn.digit << 3)
            if regonly:
                modrm |= 0xC0
            mod, rm = modrm >> 6, modrm & 7
            if mem_only and mod == 3:
                continue
            addr16 = mode in (REAL16, PROT16)
            if not addr16 and not regonly and mod == 0 and rm == 4:
                # SIB with base=5 adds disp32; avoid that variant so the
                # tail length is a function of (modrm, sib-presence) only
                sib = r.intn(256)
                while sib & 7 == 5:
                    sib = r.intn(256)
                out.append(modrm)
                out.append(sib)
                break
            out.append(modrm)
            tail = _modrm_tail_len(modrm, addr16, regonly)
            if not addr16 and mod in (1, 2) and rm == 4:
                out.append(r.intn(256))   # SIB (any base fine: disp follows)
                tail -= 1
            out += r.bytes(tail)
            break
    out += r.bytes(_imm_len(insn.imm, mode, has66, rexw))
    return bytes(out)


def insn_len(code: bytes, mode: int) -> "int | None":
    """Length of the instruction at code[0:], or None if unknown —
    the exact inverse of gen_insn's emission rules."""
    i, has66, has67 = 0, False, False
    while i < len(code) and code[i] in _PREFIXES and i < 8:
        has66 |= code[i] == 0x66
        has67 |= code[i] == 0x67
        i += 1
    rexw = False
    vexed = False
    if mode == LONG64 and i < len(code) and code[i] in (0xC4, 0xC5):
        # VEX (C4/C5 are always VEX in 64-bit mode).  Only the NP
        # (pp=00) 0F-map forms the encoder emits are decodable.
        if code[i] == 0xC5:
            if i + 1 >= len(code) or (code[i + 1] & 3) != 0:
                return None
            i += 2
        else:
            if (i + 2 >= len(code) or (code[i + 1] & 0x1F) != 1
                    or (code[i + 2] & 3) != 0):
                return None
            rexw = bool(code[i + 2] & 0x80)
            i += 3
        vexed = True
        if i >= len(code):
            return None
        code = code[:i] + b"\x0f" + code[i:]   # re-insert the implied map
    elif mode == LONG64 and i < len(code) and 0x40 <= code[i] <= 0x4F:
        rexw = bool(code[i] & 8)
        i += 1
    entry = None
    for oplen in range(min(_MAX_OP_LEN, len(code) - i), 0, -1):
        cands = _IDX.get(bytes(code[i: i + oplen]))
        if cands:
            valid = [c for c in cands if c.modes & mode]
            if not valid:
                return None
            if valid[0].modrm and valid[0].digit >= 0:
                if i + oplen >= len(code):
                    return None
                digit = (code[i + oplen] >> 3) & 7
                match = [c for c in valid if c.digit == digit]
                if not match:
                    return None
                entry = match[0]
            else:
                entry = valid[0]
            i += oplen
            break
    if entry is None:  # plusr forms: masked match (1-byte and 0F-map)
        b0 = code[i: i + 1]
        if not b0:
            return None
        keys = [bytes([b0[0] & 0xF8])]
        if b0[0] == 0x0F and i + 1 < len(code):
            keys.append(bytes([0x0F, code[i + 1] & 0xF8]))
        for key in keys:
            for c in _IDX.get(key, ()):
                if c.plusr and c.modes & mode:
                    entry = c
                    i += len(key)
                    break
            if entry:
                break
        if entry is None:
            return None
    if vexed and (len(entry.op) != 2 or entry.op[0] != 0x0F or entry.plusr
                  or entry.imm not in (0, 1)):
        return None                      # not a VEX-encodable table form
    if entry.modrm:
        if i >= len(code):
            return None
        modrm = code[i]
        i += 1
        regonly = entry.regonly
        addr16 = (mode in (REAL16, PROT16)) != has67
        mod, rm = modrm >> 6, modrm & 7
        if not regonly and mod != 3 and not addr16 and rm == 4:
            if i >= len(code):
                return None
            sib = code[i]
            i += 1
            i += 4 if (mod == 0 and sib & 7 == 5) else 0
            i += 1 if mod == 1 else (4 if mod == 2 else 0)
        else:
            i += _modrm_tail_len(modrm, addr16, regonly)
    i += _imm_len(entry.imm, mode, has66, rexw)
    # the VEX path re-inserted the implied 0F map byte into `code`; the
    # caller's buffer is one byte shorter than what we just walked
    i -= 1 if vexed else 0
    return i if i <= len(code) - (1 if vexed else 0) else None


def decode_stream(code: bytes, mode: int) -> "list[int] | None":
    """Instruction start offsets, or None if any byte fails to decode."""
    offs, i = [], 0
    while i < len(code):
        n = insn_len(code[i:], mode)
        if n is None or n == 0:
            return None
        offs.append(i)
        i += n
    return offs


# -- pseudo-op sequences (ref pseudo.go:10-50) ------------------------------

_MSRS = (0xC0000080, 0xC0000081, 0xC0000082, 0xC0000100, 0xC0000101,
         0x10, 0x1B, 0x174, 0x175, 0x176, 0x277, 0x8B, 0xFE, 0x179)
_PORTS = (0xCF8, 0xCFC, 0x60, 0x64, 0x70, 0x71, 0x3F8, 0x80)


def _mov_r32_imm(reg: int, val: int, mode: int) -> bytes:
    """mov r32, imm32 — needs the operand-size override in 16-bit modes
    so the immediate really is 4 bytes."""
    pre = b"\x66" if mode in (REAL16, PROT16) else b""
    return pre + bytes([0xB8 | reg]) + (val & 0xFFFFFFFF).to_bytes(4, "little")


def pseudo_wrmsr(r, mode: int) -> bytes:
    msr = _MSRS[r.intn(len(_MSRS))]
    lo, hi = r.rand64() & 0xFFFFFFFF, r.rand64() & 0xFFFFFFFF
    return (_mov_r32_imm(1, msr, mode) + _mov_r32_imm(0, lo, mode)
            + _mov_r32_imm(2, hi, mode) + b"\x0f\x30")


def pseudo_rdmsr(r, mode: int) -> bytes:
    return _mov_r32_imm(1, _MSRS[r.intn(len(_MSRS))], mode) + b"\x0f\x32"


def pseudo_pci_probe(r, mode: int) -> bytes:
    """out 0xCF8, <cfg addr>; in from 0xCFC — PCI config space pokes."""
    addr = 0x80000000 | (r.intn(1 << 16) << 8) | (r.intn(64) << 2)
    return (_mov_r32_imm(2, 0xCF8, mode) + _mov_r32_imm(0, addr, mode)
            + b"\xef" + _mov_r32_imm(2, 0xCFC, mode) + b"\xed")


def pseudo_port_io(r, mode: int) -> bytes:
    port = _PORTS[r.intn(len(_PORTS))]
    out = _mov_r32_imm(2, port, mode)
    out += bytes([(0xEC, 0xED, 0xEE, 0xEF)[r.intn(4)]])
    return out


def pseudo_cpuid(r, mode: int) -> bytes:
    return (_mov_r32_imm(0, r.intn(32) if r.bin() else 0x80000000 + r.intn(9),
                         mode)
            + _mov_r32_imm(1, r.intn(4), mode) + b"\x0f\xa2")


PSEUDOS = (pseudo_wrmsr, pseudo_rdmsr, pseudo_pci_probe, pseudo_port_io,
           pseudo_cpuid)


# -- public API --------------------------------------------------------------


def generate(r, mode: int, ninsns: "int | None" = None) -> bytes:
    """An instruction stream for `mode`: table picks with an occasional
    pseudo-op sequence mixed in (ref ifuzz generate + pseudo tables)."""
    if ninsns is None:
        ninsns = 2 + r.intn(12)
    out = bytearray()
    for _ in range(ninsns):
        if r.one_of(10):
            out += PSEUDOS[r.intn(len(PSEUDOS))](r, mode)
        else:
            out += gen_insn(r, mode)
    return bytes(out)


def mutate(r, code: bytes, mode: int) -> bytes:
    """Instruction-aware mutation: insert/replace/delete whole
    instructions when the stream decodes, byte-level tweaks otherwise
    (mirrors the reference's mutate-over-decode design)."""
    code = bytearray(code)
    offs = decode_stream(bytes(code), mode)
    if offs:
        bounds = offs + [len(code)]
        k = r.intn(len(offs))
        lo, hi = bounds[k], bounds[k + 1]
        which = r.intn(3)
        if which == 0:    # replace one instruction
            code[lo:hi] = gen_insn(r, mode)
        elif which == 1:  # insert before it
            code[lo:lo] = (PSEUDOS[r.intn(len(PSEUDOS))](r, mode)
                           if r.one_of(6) else gen_insn(r, mode))
        else:             # delete it
            del code[lo:hi]
    else:
        if len(code) == 0 or r.bin():
            code += gen_insn(r, mode)
        else:
            code[r.intn(len(code))] = r.intn(256)
    return bytes(code)


# arm64: fixed-width 4-byte words; emit from a tiny pattern set so
# streams are mostly-decodable (nop/mov/svc/ret/mrs plus random words)
_ARM64_PATTERNS = (0xD503201F, 0xD2800000, 0xD4000001, 0xD65F03C0,
                   0xD5300000, 0x8B000000, 0xF9400000)


def generate_arm64(r, nwords: "int | None" = None) -> bytes:
    if nwords is None:
        nwords = 4 + r.intn(28)
    out = bytearray()
    for _ in range(nwords):
        out += _arm64_word(r)
    return bytes(out)


def _arm64_word(r) -> bytes:
    w = (_ARM64_PATTERNS[r.intn(len(_ARM64_PATTERNS))]
         | (r.rand64() & 0x001F03E0))
    if r.one_of(8):
        w = r.rand64() & 0xFFFFFFFF
    return int(w).to_bytes(4, "little")


def mutate_arm64(r, code: bytes) -> bytes:
    """Incremental word-aligned mutation: replace/insert/delete one
    instruction word or tweak its register fields — corpus text that
    earned coverage is refined, not discarded."""
    code = bytearray(code[: len(code) & ~3])
    if len(code) < 4:
        return bytes(code) + _arm64_word(r)
    k = r.intn(len(code) // 4) * 4
    which = r.intn(4)
    if which == 0:    # replace one word
        code[k: k + 4] = _arm64_word(r)
    elif which == 1:  # insert a word
        code[k:k] = _arm64_word(r)
    elif which == 2 and len(code) > 4:  # delete a word
        del code[k: k + 4]
    else:             # tweak register/imm fields, keep the opcode class
        w = int.from_bytes(code[k: k + 4], "little")
        w ^= int(r.rand64()) & 0x001FFFE0
        code[k: k + 4] = w.to_bytes(4, "little")
    return bytes(code)
