"""x86 instruction table for the ifuzz equivalent.

The reference generates its ~2k-entry table from Intel XED dumps
(ifuzz/ifuzz.go:4-7, insns.go); this build derives its table from the
architectural one-byte/two-byte opcode maps (Intel SDM vol 2 appendix A
— public ABI): systematic families (the 8×ALU block, Jcc/SETcc/CMOVcc
runs, the shift/unary/inc-dec groups, MMX/SSE NP rows, x87 escapes) are
EMITTED BY LOOPS over the map structure, and the system/KVM payload set
(MSR/CR/DR/descriptor-table/VMX/SVM/SMM) is curated on top.  Every
entry carries full ModRM/SIB/displacement and operand-size metadata so
encode and decode agree byte-for-byte.
"""

from __future__ import annotations

from dataclasses import dataclass

# mode bits
REAL16, PROT16, PROT32, LONG64 = 1, 2, 4, 8
ALL = REAL16 | PROT16 | PROT32 | LONG64
NOT64 = REAL16 | PROT16 | PROT32

# imm field values
IMM_OPSIZE = -1   # 2 or 4 bytes by operand size (imm32 in long64)
IMM_OPSIZE64 = -2  # like IMM_OPSIZE but REX.W takes it to 8 (mov r64, imm64)


@dataclass(frozen=True)
class Insn:
    name: str
    op: bytes                # opcode bytes (0x0F escapes included)
    modrm: bool = False      # has a ModRM byte
    digit: int = -1          # fixed reg-field /digit, -1 = any register
    imm: int = 0             # immediate length (IMM_OPSIZE* special)
    plusr: bool = False      # low 3 opcode bits encode a register
    modes: int = ALL
    priv: bool = False       # ring-0 (useful: the target IS a kernel)
    regonly: bool = False    # ModRM is register-only (no SIB/disp)
    memonly: bool = False    # ModRM never takes mod=3 (group shares
    #                          /digit space with exact 3-byte forms)


TABLE: list[Insn] = []
_T = TABLE.append

# -- the 8×ALU block (00-3F): op r/m,r | r,r/m | al/eax,imm ------------------
for i, nm in enumerate(("add", "or", "adc", "sbb", "and", "sub", "xor",
                        "cmp")):
    base = i * 8
    _T(Insn(f"{nm}_rm8_r8", bytes([base + 0]), modrm=True))
    _T(Insn(f"{nm}_rm_r", bytes([base + 1]), modrm=True))
    _T(Insn(f"{nm}_r8_rm8", bytes([base + 2]), modrm=True))
    _T(Insn(f"{nm}_r_rm", bytes([base + 3]), modrm=True))
    _T(Insn(f"{nm}_al_imm8", bytes([base + 4]), imm=1))
    _T(Insn(f"{nm}_eax_imm", bytes([base + 5]), imm=IMM_OPSIZE))

# -- immediate groups 80/81/83 (/0../7 = the same 8 ALU ops) -----------------
for d, nm in enumerate(("add", "or", "adc", "sbb", "and", "sub", "xor",
                        "cmp")):
    _T(Insn(f"grp1_{nm}_rm8_imm8", b"\x80", modrm=True, digit=d, imm=1))
    _T(Insn(f"grp1_{nm}_rm_imm", b"\x81", modrm=True, digit=d,
            imm=IMM_OPSIZE))
    _T(Insn(f"grp1_{nm}_rm_imm8", b"\x83", modrm=True, digit=d, imm=1))

# -- shift/rotate groups C0/C1 (imm8), D0-D3 (1 / cl) ------------------------
for d, nm in enumerate(("rol", "ror", "rcl", "rcr", "shl", "shr", "sal",
                        "sar")):
    _T(Insn(f"{nm}_rm8_imm8", b"\xc0", modrm=True, digit=d, imm=1))
    _T(Insn(f"{nm}_rm_imm8", b"\xc1", modrm=True, digit=d, imm=1))
    _T(Insn(f"{nm}_rm8_1", b"\xd0", modrm=True, digit=d))
    _T(Insn(f"{nm}_rm_1", b"\xd1", modrm=True, digit=d))
    _T(Insn(f"{nm}_rm8_cl", b"\xd2", modrm=True, digit=d))
    _T(Insn(f"{nm}_rm_cl", b"\xd3", modrm=True, digit=d))

# -- unary groups F6/F7, FE/FF -----------------------------------------------
_T(Insn("grp3_test_rm8_imm8", b"\xf6", modrm=True, digit=0, imm=1))
_T(Insn("grp3_test_rm8_imm8b", b"\xf6", modrm=True, digit=1, imm=1))
for d, nm in ((2, "not"), (3, "neg"), (4, "mul"), (5, "imul"),
              (6, "div"), (7, "idiv")):
    _T(Insn(f"grp3_{nm}_rm8", b"\xf6", modrm=True, digit=d))
_T(Insn("grp3_test_rm_imm", b"\xf7", modrm=True, digit=0, imm=IMM_OPSIZE))
_T(Insn("grp3_test_rm_immb", b"\xf7", modrm=True, digit=1,
        imm=IMM_OPSIZE))
for d, nm in ((2, "not"), (3, "neg"), (4, "mul"), (5, "imul"),
              (6, "div"), (7, "idiv")):
    _T(Insn(f"grp3_{nm}_rm", b"\xf7", modrm=True, digit=d))
_T(Insn("inc_rm8", b"\xfe", modrm=True, digit=0))
_T(Insn("dec_rm8", b"\xfe", modrm=True, digit=1))
for d, nm in ((0, "inc"), (1, "dec"), (2, "call"), (4, "jmp"), (6, "push")):
    _T(Insn(f"grp5_{nm}_rm", b"\xff", modrm=True, digit=d))
_T(Insn("grp5_callf_m", b"\xff", modrm=True, digit=3, memonly=True))
_T(Insn("grp5_jmpf_m", b"\xff", modrm=True, digit=5, memonly=True))

# -- mov / lea / xchg / stack -------------------------------------------------
_T(Insn("mov_rm_r", b"\x89", modrm=True))
_T(Insn("mov_r_rm", b"\x8b", modrm=True))
_T(Insn("mov_rm8_r8", b"\x88", modrm=True))
_T(Insn("mov_r8_rm8", b"\x8a", modrm=True))
_T(Insn("mov_rm_seg", b"\x8c", modrm=True))
_T(Insn("mov_seg_rm", b"\x8e", modrm=True))
_T(Insn("mov_r_imm", b"\xb8", plusr=True, imm=IMM_OPSIZE64))
_T(Insn("mov_r8_imm", b"\xb0", plusr=True, imm=1))
_T(Insn("mov_rm_imm", b"\xc7", modrm=True, digit=0, imm=IMM_OPSIZE))
_T(Insn("mov_rm8_imm8", b"\xc6", modrm=True, digit=0, imm=1))
_T(Insn("lea", b"\x8d", modrm=True, memonly=True))
_T(Insn("test_rm_r", b"\x85", modrm=True))
_T(Insn("test_rm8_r8", b"\x84", modrm=True))
_T(Insn("xchg_rm_r", b"\x87", modrm=True))
_T(Insn("xchg_rm8_r8", b"\x86", modrm=True))
_T(Insn("xchg_eax_r", b"\x90", plusr=True))
_T(Insn("push_r", b"\x50", plusr=True))
_T(Insn("pop_r", b"\x58", plusr=True))
_T(Insn("push_imm8", b"\x6a", imm=1))
_T(Insn("push_imm", b"\x68", imm=IMM_OPSIZE))
_T(Insn("pop_rm", b"\x8f", modrm=True, digit=0))
_T(Insn("imul_r_rm_imm", b"\x69", modrm=True, imm=IMM_OPSIZE))
_T(Insn("imul_r_rm_imm8", b"\x6b", modrm=True, imm=1))
_T(Insn("inc_r", b"\x40", plusr=True, modes=NOT64))
_T(Insn("dec_r", b"\x48", plusr=True, modes=NOT64))
_T(Insn("movsxd", b"\x63", modrm=True, modes=LONG64))
_T(Insn("arpl", b"\x63", modrm=True, modes=NOT64))
_T(Insn("bound", b"\x62", modrm=True, memonly=True, modes=NOT64))

# -- one-byte misc -----------------------------------------------------------
_T(Insn("nop", b"\x90"))
_T(Insn("cwde", b"\x98"))
_T(Insn("cdq", b"\x99"))
_T(Insn("wait", b"\x9b"))
_T(Insn("pushf", b"\x9c"))
_T(Insn("popf", b"\x9d"))
_T(Insn("sahf", b"\x9e", modes=NOT64))
_T(Insn("lahf", b"\x9f", modes=NOT64))
_T(Insn("xlat", b"\xd7"))
_T(Insn("cmc", b"\xf5"))
_T(Insn("clc", b"\xf8"))
_T(Insn("stc", b"\xf9"))
_T(Insn("cli", b"\xfa", priv=True))
_T(Insn("sti", b"\xfb", priv=True))
_T(Insn("cld", b"\xfc"))
_T(Insn("std", b"\xfd"))
_T(Insn("salc", b"\xd6", modes=NOT64))
_T(Insn("icebp", b"\xf1"))
_T(Insn("daa", b"\x27", modes=NOT64))
_T(Insn("das", b"\x2f", modes=NOT64))
_T(Insn("aaa", b"\x37", modes=NOT64))
_T(Insn("aas", b"\x3f", modes=NOT64))
_T(Insn("aam", b"\xd4", imm=1, modes=NOT64))
_T(Insn("aad", b"\xd5", imm=1, modes=NOT64))
_T(Insn("pusha", b"\x60", modes=NOT64))
_T(Insn("popa", b"\x61", modes=NOT64))
for op, nm in ((0x06, "push_es"), (0x07, "pop_es"), (0x0e, "push_cs"),
               (0x16, "push_ss"), (0x17, "pop_ss"), (0x1e, "push_ds"),
               (0x1f, "pop_ds")):
    _T(Insn(nm, bytes([op]), modes=NOT64))

# -- string ops (rep-prefixable) ---------------------------------------------
for op, nm in ((0xa4, "movsb"), (0xa5, "movs"), (0xa6, "cmpsb"),
               (0xa7, "cmps"), (0xaa, "stosb"), (0xab, "stos"),
               (0xac, "lodsb"), (0xad, "lods"), (0xae, "scasb"),
               (0xaf, "scas")):
    _T(Insn(nm, bytes([op])))
_T(Insn("test_al_imm8", b"\xa8", imm=1))
_T(Insn("test_eax_imm", b"\xa9", imm=IMM_OPSIZE))
for op, nm in ((0x6c, "insb"), (0x6d, "ins"), (0x6e, "outsb"),
               (0x6f, "outs")):
    _T(Insn(nm, bytes([op]), priv=True))

# -- control flow ------------------------------------------------------------
_CCS = ("o", "no", "b", "ae", "e", "ne", "be", "a",
        "s", "ns", "p", "np", "l", "ge", "le", "g")
for i, cc in enumerate(_CCS):
    _T(Insn(f"j{cc}_rel8", bytes([0x70 + i]), imm=1))
    _T(Insn(f"j{cc}_rel", bytes([0x0f, 0x80 + i]), imm=IMM_OPSIZE))
    _T(Insn(f"set{cc}_rm8", bytes([0x0f, 0x90 + i]), modrm=True))
    _T(Insn(f"cmov{cc}", bytes([0x0f, 0x40 + i]), modrm=True))
_T(Insn("jmp_rel8", b"\xeb", imm=1))
_T(Insn("jmp_rel", b"\xe9", imm=IMM_OPSIZE))
_T(Insn("call_rel", b"\xe8", imm=IMM_OPSIZE))
_T(Insn("loopne_rel8", b"\xe0", imm=1))
_T(Insn("loope_rel8", b"\xe1", imm=1))
_T(Insn("loop_rel8", b"\xe2", imm=1))
_T(Insn("jcxz_rel8", b"\xe3", imm=1))
_T(Insn("ret", b"\xc3"))
_T(Insn("ret_imm16", b"\xc2", imm=2))
_T(Insn("retf", b"\xcb"))
_T(Insn("retf_imm16", b"\xca", imm=2))
_T(Insn("enter", b"\xc8", imm=3))
_T(Insn("leave", b"\xc9"))
_T(Insn("int3", b"\xcc"))
_T(Insn("int_imm8", b"\xcd", imm=1))
_T(Insn("into", b"\xce", modes=NOT64))
_T(Insn("iret", b"\xcf"))

# -- port I/O (PCI config space probing, ref pseudo.go) ----------------------
_T(Insn("in_al_imm8", b"\xe4", imm=1, priv=True))
_T(Insn("in_eax_imm8", b"\xe5", imm=1, priv=True))
_T(Insn("out_imm8_al", b"\xe6", imm=1, priv=True))
_T(Insn("out_imm8_eax", b"\xe7", imm=1, priv=True))
_T(Insn("in_al_dx", b"\xec", priv=True))
_T(Insn("in_eax_dx", b"\xed", priv=True))
_T(Insn("out_dx_al", b"\xee", priv=True))
_T(Insn("out_dx_eax", b"\xef", priv=True))

# -- x87 escapes (full modrm space: register and memory forms both decode
#    as opcode+modrm(+tail), which is exactly the generic rule) --------------
for op in range(0xd8, 0xe0):
    _T(Insn(f"x87_{op:02x}", bytes([op]), modrm=True))

# -- two-byte map: bit ops, wide mov, atomics --------------------------------
_T(Insn("bt_rm_r", b"\x0f\xa3", modrm=True))
_T(Insn("bts_rm_r", b"\x0f\xab", modrm=True))
_T(Insn("btr_rm_r", b"\x0f\xb3", modrm=True))
_T(Insn("btc_rm_r", b"\x0f\xbb", modrm=True))
for d, nm in ((4, "bt"), (5, "bts"), (6, "btr"), (7, "btc")):
    _T(Insn(f"grp8_{nm}_rm_imm8", b"\x0f\xba", modrm=True, digit=d, imm=1))
_T(Insn("bsf", b"\x0f\xbc", modrm=True))
_T(Insn("bsr", b"\x0f\xbd", modrm=True))
_T(Insn("movzx_r_rm8", b"\x0f\xb6", modrm=True))
_T(Insn("movzx_r_rm16", b"\x0f\xb7", modrm=True))
_T(Insn("movsx_r_rm8", b"\x0f\xbe", modrm=True))
_T(Insn("movsx_r_rm16", b"\x0f\xbf", modrm=True))
_T(Insn("imul_r_rm", b"\x0f\xaf", modrm=True))
_T(Insn("cmpxchg_rm8_r8", b"\x0f\xb0", modrm=True))
_T(Insn("cmpxchg_rm_r", b"\x0f\xb1", modrm=True))
_T(Insn("cmpxchg8b", b"\x0f\xc7", modrm=True, digit=1, memonly=True))
_T(Insn("xadd_rm8_r8", b"\x0f\xc0", modrm=True))
_T(Insn("xadd_rm_r", b"\x0f\xc1", modrm=True))
_T(Insn("bswap_r", b"\x0f\xc8", plusr=True))
_T(Insn("shld_imm8", b"\x0f\xa4", modrm=True, imm=1))
_T(Insn("shld_cl", b"\x0f\xa5", modrm=True))
_T(Insn("shrd_imm8", b"\x0f\xac", modrm=True, imm=1))
_T(Insn("shrd_cl", b"\x0f\xad", modrm=True))
_T(Insn("movnti", b"\x0f\xc3", modrm=True, memonly=True))
_T(Insn("push_fs", b"\x0f\xa0"))
_T(Insn("pop_fs", b"\x0f\xa1"))
_T(Insn("push_gs", b"\x0f\xa8"))
_T(Insn("pop_gs", b"\x0f\xa9"))
_T(Insn("ud0", b"\x0f\xff", modrm=True))
_T(Insn("ud1", b"\x0f\xb9", modrm=True))
_T(Insn("ud2", b"\x0f\x0b"))
_T(Insn("prefetch_grp", b"\x0f\x18", modrm=True, memonly=True))
_T(Insn("nop_rm", b"\x0f\x1f", modrm=True))
_T(Insn("prefetch_3dnow", b"\x0f\x0d", modrm=True, memonly=True))
# 0F AE: memory fxsave group as mem-only digits; fences as exact 3-byte
for d, nm in ((0, "fxsave"), (1, "fxrstor"), (2, "ldmxcsr"),
              (3, "stmxcsr"), (4, "xsave"), (5, "xrstor"), (6, "xsaveopt"),
              (7, "clflush")):
    _T(Insn(f"grpae_{nm}", b"\x0f\xae", modrm=True, digit=d, memonly=True))
_T(Insn("lfence", b"\x0f\xae\xe8"))
_T(Insn("mfence", b"\x0f\xae\xf0"))
_T(Insn("sfence", b"\x0f\xae\xf8"))

# -- MMX/SSE no-prefix rows (NP forms only: mandatory-prefix variants are
#    a different decode dimension this table does not model) -----------------
for op, nm in ((0x10, "movups_x_rm"), (0x11, "movups_rm_x"),
               (0x12, "movlps_ld"), (0x13, "movlps_st"),
               (0x14, "unpcklps"), (0x15, "unpckhps"),
               (0x16, "movhps_ld"), (0x17, "movhps_st"),
               (0x28, "movaps_x_rm"), (0x29, "movaps_rm_x"),
               (0x2a, "cvtpi2ps"), (0x2b, "movntps"),
               (0x2c, "cvttps2pi"), (0x2d, "cvtps2pi"),
               (0x2e, "ucomiss"), (0x2f, "comiss")):
    _T(Insn(nm, bytes([0x0f, op]), modrm=True))
_T(Insn("movmskps", b"\x0f\x50", modrm=True, regonly=True))
for op, nm in ((0x51, "sqrtps"), (0x52, "rsqrtps"), (0x53, "rcpps"),
               (0x54, "andps"), (0x55, "andnps"), (0x56, "orps"),
               (0x57, "xorps"), (0x58, "addps"), (0x59, "mulps"),
               (0x5a, "cvtps2pd"), (0x5b, "cvtdq2ps"), (0x5c, "subps"),
               (0x5d, "minps"), (0x5e, "divps"), (0x5f, "maxps")):
    _T(Insn(nm, bytes([0x0f, op]), modrm=True))
for op, nm in ((0x60, "punpcklbw"), (0x61, "punpcklwd"),
               (0x62, "punpckldq"), (0x63, "packsswb"),
               (0x64, "pcmpgtb"), (0x65, "pcmpgtw"), (0x66, "pcmpgtd"),
               (0x67, "packuswb"), (0x68, "punpckhbw"),
               (0x69, "punpckhwd"), (0x6a, "punpckhdq"),
               (0x6b, "packssdw"), (0x6e, "movd_m_rm"), (0x6f, "movq_m_rm"),
               (0x74, "pcmpeqb"), (0x75, "pcmpeqw"), (0x76, "pcmpeqd"),
               (0x7e, "movd_rm_m"), (0x7f, "movq_rm_m")):
    _T(Insn(nm, bytes([0x0f, op]), modrm=True))
_T(Insn("pshufw", b"\x0f\x70", modrm=True, imm=1))
for opc, digs in ((0x71, (2, 4, 6)), (0x72, (2, 4, 6)), (0x73, (2, 6))):
    for d in digs:
        _T(Insn(f"grp12_{opc:02x}_{d}", bytes([0x0f, opc]), modrm=True,
                digit=d, imm=1, regonly=True))
_T(Insn("emms", b"\x0f\x77"))
_T(Insn("cmpps", b"\x0f\xc2", modrm=True, imm=1))
_T(Insn("pinsrw", b"\x0f\xc4", modrm=True, imm=1))
_T(Insn("pextrw", b"\x0f\xc5", modrm=True, imm=1, regonly=True))
_T(Insn("shufps", b"\x0f\xc6", modrm=True, imm=1))
for op, nm in ((0xd1, "psrlw"), (0xd2, "psrld"), (0xd3, "psrlq"),
               (0xd4, "paddq"), (0xd5, "pmullw"), (0xd8, "psubusb"),
               (0xd9, "psubusw"), (0xda, "pminub"), (0xdb, "pand"),
               (0xdc, "paddusb"), (0xdd, "paddusw"), (0xde, "pmaxub"),
               (0xdf, "pandn"), (0xe0, "pavgb"), (0xe1, "psraw"),
               (0xe2, "psrad"), (0xe3, "pavgw"), (0xe4, "pmulhuw"),
               (0xe5, "pmulhw"), (0xe8, "psubsb"), (0xe9, "psubsw"),
               (0xea, "pminsw"), (0xeb, "por"), (0xec, "paddsb"),
               (0xed, "paddsw"), (0xee, "pmaxsw"), (0xef, "pxor"),
               (0xf1, "psllw"), (0xf2, "pslld"), (0xf3, "psllq"),
               (0xf4, "pmuludq"), (0xf5, "pmaddwd"), (0xf6, "psadbw"),
               (0xf8, "psubb"), (0xf9, "psubw"), (0xfa, "psubd"),
               (0xfb, "psubq"), (0xfc, "paddb"), (0xfd, "paddw"),
               (0xfe, "paddd")):
    _T(Insn(nm, bytes([0x0f, op]), modrm=True))

# -- system / privileged (the KVM-fuzzing payload) ---------------------------
_T(Insn("hlt", b"\xf4", priv=True))
_T(Insn("cpuid", b"\x0f\xa2"))
_T(Insn("rdtsc", b"\x0f\x31"))
_T(Insn("rdpmc", b"\x0f\x33", priv=True))
_T(Insn("rdmsr", b"\x0f\x32", priv=True))
_T(Insn("wrmsr", b"\x0f\x30", priv=True))
_T(Insn("wbinvd", b"\x0f\x09", priv=True))
_T(Insn("invd", b"\x0f\x08", priv=True))
_T(Insn("clts", b"\x0f\x06", priv=True))
_T(Insn("rsm", b"\x0f\xaa", priv=True))
_T(Insn("mov_r_cr", b"\x0f\x20", modrm=True, priv=True, regonly=True))
_T(Insn("mov_cr_r", b"\x0f\x22", modrm=True, priv=True, regonly=True))
_T(Insn("mov_r_dr", b"\x0f\x21", modrm=True, priv=True, regonly=True))
_T(Insn("mov_dr_r", b"\x0f\x23", modrm=True, priv=True, regonly=True))
for d, nm in ((0, "sgdt"), (1, "sidt"), (2, "lgdt"), (3, "lidt"),
              (4, "smsw"), (6, "lmsw"), (7, "invlpg")):
    _T(Insn(nm, b"\x0f\x01", modrm=True, digit=d, priv=True, memonly=True))
for d, nm in ((0, "sldt"), (1, "str"), (2, "lldt"), (3, "ltr"),
              (4, "verr"), (5, "verw")):
    _T(Insn(nm, b"\x0f\x00", modrm=True, digit=d, priv=True, memonly=True))
_T(Insn("lar", b"\x0f\x02", modrm=True, priv=True))
_T(Insn("lsl", b"\x0f\x03", modrm=True, priv=True))
_T(Insn("sysenter", b"\x0f\x34", modes=PROT32 | LONG64))
_T(Insn("sysexit", b"\x0f\x35", priv=True, modes=PROT32 | LONG64))
_T(Insn("syscall", b"\x0f\x05", modes=LONG64))
_T(Insn("sysret", b"\x0f\x07", priv=True, modes=LONG64))
# 0F 01 exact 3-byte system forms (VMX/SVM/TSX/PKU/SMAP/SGX surface)
for b3, nm in ((0xc1, "vmcall"), (0xc2, "vmlaunch"), (0xc3, "vmresume"),
               (0xc4, "vmxoff"), (0xc8, "monitor"), (0xc9, "mwait"),
               (0xca, "clac"), (0xcb, "stac"), (0xcf, "encls"),
               (0xd0, "xgetbv"), (0xd1, "xsetbv"), (0xd4, "vmfunc"),
               (0xd5, "xend"), (0xd6, "xtest"), (0xd7, "enclu"),
               (0xd8, "vmrun"), (0xd9, "vmmcall"), (0xda, "vmload"),
               (0xdb, "vmsave"), (0xdc, "stgi"), (0xdd, "clgi"),
               (0xde, "skinit"), (0xdf, "invlpga"), (0xee, "rdpkru"),
               (0xef, "wrpkru"), (0xf8, "swapgs"), (0xf9, "rdtscp")):
    priv = nm not in ("vmcall", "vmmcall", "xgetbv", "xtest", "rdtscp",
                      "rdpkru", "enclu")
    modes = LONG64 if nm == "swapgs" else ALL
    _T(Insn(nm, bytes([0x0f, 0x01, b3]), priv=priv, modes=modes))


def by_mode(mode_bit: int) -> list[Insn]:
    return [i for i in TABLE if i.modes & mode_bit]


def opcode_index() -> dict[bytes, list[Insn]]:
    """opcode bytes -> entries (entries sharing an opcode differ by
    /digit; 3-byte 0F 01 xx forms are keyed on all 3 bytes)."""
    idx: dict[bytes, list[Insn]] = {}
    for i in TABLE:
        idx.setdefault(i.op, []).append(i)
    return idx
