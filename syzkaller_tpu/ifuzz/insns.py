"""Curated x86 instruction table for the ifuzz equivalent.

The reference generates its ~2k-entry table from Intel XED dumps
(ifuzz/ifuzz.go:4-7, insns.go); this build hand-curates the encodings
that matter for kernel/KVM fuzzing — privileged and system instructions,
MSR/port/descriptor-table access, plus enough ordinary ALU/mov/branch
traffic to make streams realistic — with full ModRM/SIB/displacement
and operand-size metadata so encode and decode agree byte-for-byte.
"""

from __future__ import annotations

from dataclasses import dataclass

# mode bits
REAL16, PROT16, PROT32, LONG64 = 1, 2, 4, 8
ALL = REAL16 | PROT16 | PROT32 | LONG64
NOT64 = REAL16 | PROT16 | PROT32

# imm field values
IMM_OPSIZE = -1   # 2 or 4 bytes by operand size (imm32 in long64)
IMM_OPSIZE64 = -2  # like IMM_OPSIZE but REX.W takes it to 8 (mov r64, imm64)


@dataclass(frozen=True)
class Insn:
    name: str
    op: bytes                # opcode bytes (0x0F escapes included)
    modrm: bool = False      # has a ModRM byte
    digit: int = -1          # fixed reg-field /digit, -1 = any register
    imm: int = 0             # immediate length (IMM_OPSIZE* special)
    plusr: bool = False      # low 3 opcode bits encode a register
    modes: int = ALL
    priv: bool = False       # ring-0 (useful: the target IS a kernel)


# fmt: off
TABLE: list[Insn] = [
    # -- ordinary data/ALU traffic ------------------------------------------
    Insn("mov_rm_r",    b"\x89", modrm=True),
    Insn("mov_r_rm",    b"\x8b", modrm=True),
    Insn("mov_rm8_r8",  b"\x88", modrm=True),
    Insn("mov_r_imm",   b"\xb8", plusr=True, imm=IMM_OPSIZE64),
    Insn("mov_r8_imm",  b"\xb0", plusr=True, imm=1),
    Insn("mov_rm_imm",  b"\xc7", modrm=True, digit=0, imm=IMM_OPSIZE),
    Insn("add_rm_r",    b"\x01", modrm=True),
    Insn("add_r_rm",    b"\x03", modrm=True),
    Insn("adc_rm_r",    b"\x11", modrm=True),
    Insn("sub_rm_r",    b"\x29", modrm=True),
    Insn("cmp_rm_r",    b"\x39", modrm=True),
    Insn("and_rm_r",    b"\x21", modrm=True),
    Insn("or_rm_r",     b"\x09", modrm=True),
    Insn("xor_rm_r",    b"\x31", modrm=True),
    Insn("test_rm_r",   b"\x85", modrm=True),
    Insn("xchg_rm_r",   b"\x87", modrm=True),
    Insn("lea",         b"\x8d", modrm=True),
    Insn("grp1_add_imm", b"\x81", modrm=True, digit=0, imm=IMM_OPSIZE),
    Insn("grp1_or_imm",  b"\x81", modrm=True, digit=1, imm=IMM_OPSIZE),
    Insn("grp1_and_imm", b"\x81", modrm=True, digit=4, imm=IMM_OPSIZE),
    Insn("grp1_cmp_imm", b"\x81", modrm=True, digit=7, imm=IMM_OPSIZE),
    Insn("grp1_add_imm8", b"\x83", modrm=True, digit=0, imm=1),
    Insn("grp1_xor_imm8", b"\x83", modrm=True, digit=6, imm=1),
    Insn("grp3_test_imm", b"\xf7", modrm=True, digit=0, imm=IMM_OPSIZE),
    Insn("grp3_not",    b"\xf7", modrm=True, digit=2),
    Insn("grp3_neg",    b"\xf7", modrm=True, digit=3),
    Insn("grp3_mul",    b"\xf7", modrm=True, digit=4),
    Insn("grp3_div",    b"\xf7", modrm=True, digit=6),
    Insn("inc_rm",      b"\xff", modrm=True, digit=0),
    Insn("dec_rm",      b"\xff", modrm=True, digit=1),
    Insn("push_rm",     b"\xff", modrm=True, digit=6),
    Insn("push_r",      b"\x50", plusr=True),
    Insn("pop_r",       b"\x58", plusr=True),
    Insn("push_imm8",   b"\x6a", imm=1),
    Insn("movzx_r_rm8", b"\x0f\xb6", modrm=True),
    Insn("movsx_r_rm8", b"\x0f\xbe", modrm=True),
    Insn("imul_r_rm",   b"\x0f\xaf", modrm=True),
    Insn("shl_rm_imm",  b"\xc1", modrm=True, digit=4, imm=1),
    Insn("shr_rm_imm",  b"\xc1", modrm=True, digit=5, imm=1),
    Insn("sar_rm_imm",  b"\xc1", modrm=True, digit=7, imm=1),
    Insn("nop",         b"\x90"),
    Insn("cwde",        b"\x98"),
    Insn("cdq",         b"\x99"),
    Insn("sahf",        b"\x9e", modes=NOT64),
    Insn("lahf",        b"\x9f", modes=NOT64),
    # -- control flow --------------------------------------------------------
    Insn("jmp_rel8",    b"\xeb", imm=1),
    Insn("jz_rel8",     b"\x74", imm=1),
    Insn("jnz_rel8",    b"\x75", imm=1),
    Insn("jc_rel8",     b"\x72", imm=1),
    Insn("loop_rel8",   b"\xe2", imm=1),
    Insn("call_rel",    b"\xe8", imm=IMM_OPSIZE),
    Insn("jmp_rel",     b"\xe9", imm=IMM_OPSIZE),
    Insn("ret",         b"\xc3"),
    Insn("int3",        b"\xcc"),
    Insn("int_imm8",    b"\xcd", imm=1),
    Insn("into",        b"\xce", modes=NOT64),
    Insn("iret",        b"\xcf"),
    # -- flags / string / misc user-level system interplay -------------------
    Insn("cli",         b"\xfa", priv=True),
    Insn("sti",         b"\xfb", priv=True),
    Insn("clc",         b"\xf8"),
    Insn("stc",         b"\xf9"),
    Insn("cld",         b"\xfc"),
    Insn("std",         b"\xfd"),
    Insn("cpuid",       b"\x0f\xa2"),
    Insn("rdtsc",       b"\x0f\x31"),
    Insn("rdpmc",       b"\x0f\x33", priv=True),
    Insn("pushf",       b"\x9c"),
    Insn("popf",        b"\x9d"),
    # -- port I/O (PCI config space probing, ref pseudo.go) ------------------
    Insn("in_al_imm8",  b"\xe4", imm=1, priv=True),
    Insn("in_eax_imm8", b"\xe5", imm=1, priv=True),
    Insn("out_imm8_al", b"\xe6", imm=1, priv=True),
    Insn("out_imm8_eax", b"\xe7", imm=1, priv=True),
    Insn("in_al_dx",    b"\xec", priv=True),
    Insn("in_eax_dx",   b"\xed", priv=True),
    Insn("out_dx_al",   b"\xee", priv=True),
    Insn("out_dx_eax",  b"\xef", priv=True),
    # -- privileged / system (the KVM-fuzzing payload) -----------------------
    Insn("hlt",         b"\xf4", priv=True),
    Insn("rdmsr",       b"\x0f\x32", priv=True),
    Insn("wrmsr",       b"\x0f\x30", priv=True),
    Insn("wbinvd",      b"\x0f\x09", priv=True),
    Insn("invd",        b"\x0f\x08", priv=True),
    Insn("clts",        b"\x0f\x06", priv=True),
    Insn("rsm",         b"\x0f\xaa", priv=True),
    Insn("ud2",         b"\x0f\x0b"),
    Insn("mov_r_cr",    b"\x0f\x20", modrm=True, priv=True),
    Insn("mov_cr_r",    b"\x0f\x22", modrm=True, priv=True),
    Insn("mov_r_dr",    b"\x0f\x21", modrm=True, priv=True),
    Insn("mov_dr_r",    b"\x0f\x23", modrm=True, priv=True),
    Insn("sgdt",        b"\x0f\x01", modrm=True, digit=0, priv=True),
    Insn("sidt",        b"\x0f\x01", modrm=True, digit=1, priv=True),
    Insn("lgdt",        b"\x0f\x01", modrm=True, digit=2, priv=True),
    Insn("lidt",        b"\x0f\x01", modrm=True, digit=3, priv=True),
    Insn("smsw",        b"\x0f\x01", modrm=True, digit=4, priv=True),
    Insn("lmsw",        b"\x0f\x01", modrm=True, digit=6, priv=True),
    Insn("invlpg",      b"\x0f\x01", modrm=True, digit=7, priv=True),
    Insn("sldt",        b"\x0f\x00", modrm=True, digit=0, priv=True),
    Insn("str",         b"\x0f\x00", modrm=True, digit=1, priv=True),
    Insn("lldt",        b"\x0f\x00", modrm=True, digit=2, priv=True),
    Insn("ltr",         b"\x0f\x00", modrm=True, digit=3, priv=True),
    Insn("verr",        b"\x0f\x00", modrm=True, digit=4, priv=True),
    Insn("verw",        b"\x0f\x00", modrm=True, digit=5, priv=True),
    Insn("lar",         b"\x0f\x02", modrm=True, priv=True),
    Insn("lsl",         b"\x0f\x03", modrm=True, priv=True),
    Insn("sysenter",    b"\x0f\x34", modes=PROT32 | LONG64),
    Insn("sysexit",     b"\x0f\x35", priv=True, modes=PROT32 | LONG64),
    Insn("syscall",     b"\x0f\x05", modes=LONG64),
    Insn("sysret",      b"\x0f\x07", priv=True, modes=LONG64),
    Insn("swapgs",      b"\x0f\x01\xf8", modes=LONG64, priv=True),
    Insn("rdtscp",      b"\x0f\x01\xf9"),
    Insn("monitor",     b"\x0f\x01\xc8", priv=True),
    Insn("mwait",       b"\x0f\x01\xc9", priv=True),
    Insn("vmcall",      b"\x0f\x01\xc1"),
    Insn("xgetbv",      b"\x0f\x01\xd0"),
    Insn("xsetbv",      b"\x0f\x01\xd1", priv=True),
]
# fmt: on


def by_mode(mode_bit: int) -> list[Insn]:
    return [i for i in TABLE if i.modes & mode_bit]


def opcode_index() -> dict[bytes, list[Insn]]:
    """opcode bytes -> entries (entries sharing an opcode differ by
    /digit; 3-byte 0F 01 xx forms are keyed on all 3 bytes)."""
    idx: dict[bytes, list[Insn]] = {}
    for i in TABLE:
        idx.setdefault(i.op, []).append(i)
    return idx
