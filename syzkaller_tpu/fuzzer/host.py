"""Host capability detection: which calls can this kernel execute.

Capability parity with reference host/host.go:19-157: kallsyms scan for
syscall entry points, with pseudo-call knowledge (syz_probe* are
executor no-ops, so always "supported"; real syz_* helpers depend on
device files).  When kallsyms is unreadable (non-root/containers) the
fallback is PROBING, like the reference's issue-and-classify approach:
each syscall number is invoked with all-invalid arguments inside a
forked child (full isolation from fuzzer state) and ENOSYS marks it
unsupported — round-2 verdict: the old all-supported fallback silently
enabled everything in containers.
"""

from __future__ import annotations

import functools
import os

from syzkaller_tpu.sys import types as T
from syzkaller_tpu.sys.table import SyscallTable
from syzkaller_tpu.utils import log

# never probed (side effects even with invalid args: process control,
# tty hangup, blocking); treated as supported when probing
_PROBE_SKIP = {
    "exit", "exit_group", "fork", "vfork", "clone", "clone3",
    "execve", "execveat", "pause", "rt_sigsuspend", "sigsuspend",
    "rt_sigreturn", "sigreturn", "restart_syscall", "vhangup",
    "reboot", "kexec_load", "kexec_file_load", "setsid", "personality",
    "ptrace", "unshare", "setns", "sync",
}

_ENOSYS = 38
_PROBE_TIMEOUT = 10.0


def _probe_nrs(nrs: "list[int]") -> "dict[int, bool]":
    """Invoke each NR with all-invalid args in a forked child; a result
    of -1/ENOSYS means the kernel has no such entry point.  The child is
    sacrificial: whatever a probe does to process state dies with it.
    Any infrastructure failure (fork refusal, child wedged — the parent
    is JAX-threaded, so the child must not dlopen/malloc after fork)
    degrades to {} and the caller falls back to all-supported."""
    import ctypes
    import select

    # dlopen BEFORE fork: the child only calls the already-resolved
    # function pointer, never the loader/allocator
    libc = ctypes.CDLL(None, use_errno=True)
    libc.syscall.restype = ctypes.c_long
    try:
        r, w = os.pipe()
        pid = os.fork()
    except OSError:
        return {}
    if pid == 0:
        code = 1
        try:
            os.close(r)
            bad = ctypes.c_long(-1)
            out = bytearray()
            for nr in nrs:
                ctypes.set_errno(0)
                res = libc.syscall(ctypes.c_long(nr), bad, bad, bad,
                                   bad, bad, bad)
                err = ctypes.get_errno()
                out.append(0 if (res == -1 and err == _ENOSYS) else 1)
            os.write(w, bytes(out))
            code = 0
        except Exception:
            pass
        finally:
            os._exit(code)
    os.close(w)
    data = b""
    import time as _time
    deadline = _time.monotonic() + _PROBE_TIMEOUT
    try:
        while len(data) < len(nrs):
            left = deadline - _time.monotonic()
            if left <= 0:
                break
            ready, _, _ = select.select([r], [], [], left)
            if not ready:
                break
            chunk = os.read(r, len(nrs) - len(data))
            if not chunk:
                break
            data += chunk
    finally:
        os.close(r)
        try:
            import signal
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        os.waitpid(pid, 0)
    if len(data) != len(nrs):     # child died/hung mid-probe: no verdicts
        return {}
    return {nr: bool(b) for nr, b in zip(nrs, data)}

_PSEUDO_DEVICES = {
    "syz_open_dev": None,       # checked per-arg at generation time
    "syz_open_pts": "/dev/ptmx",
    "syz_fuse_mount": "/dev/fuse",
    "syz_fuseblk_mount": "/dev/fuse",
    "syz_emit_ethernet": "/dev/net/tun",
    "syz_kvm_setup_cpu": "/dev/kvm",
}


@functools.lru_cache(maxsize=None)
def _kallsyms() -> "frozenset[str] | None":
    try:
        with open("/proc/kallsyms", "rb") as f:
            data = f.read()
    except OSError:
        return None
    if not data:
        return None
    syms = set()
    for line in data.splitlines():
        parts = line.split()
        if len(parts) >= 3:
            syms.add(parts[2].decode(errors="replace"))
    return frozenset(syms)


def _syscall_supported(name: str, syms: "frozenset[str] | None") -> bool:
    if syms is None:
        return True
    for pat in (f"sys_{name}", f"__x64_sys_{name}", f"__se_sys_{name}",
                f"__arm64_sys_{name}", f"ksys_{name}"):
        if pat in syms:
            return True
    # compat/indirect entries (socketcall etc.) or inlined wrappers:
    # absence in kallsyms is not definitive, be permissive for common ones
    return name in ("mmap", "munmap", "read", "write", "open", "close",
                    "exit", "exit_group")


def detect_supported(table: SyscallTable,
                     registry=None) -> set[T.Syscall]:
    """`registry` (a telemetry.Registry; None = the process default)
    gets the probe-outcome counters: how this host's call list was
    derived is production-debuggable from /metrics instead of one log
    line at startup."""
    from syzkaller_tpu.telemetry import registry as reg_mod

    reg = registry if registry is not None else reg_mod.default_registry()
    probe_c = reg.counter(
        "syz_host_probe_total",
        "probe-based capability fallback outcomes by verdict",
        labels=("verdict",))
    source_c = reg.counter(
        "syz_host_detect_total", "capability detection runs by source",
        labels=("source",))
    syms = _kallsyms()
    probed: "dict[int, bool]" = {}
    if syms is None:
        nrs = sorted({c.nr for c in table.calls
                      if not c.call_name.startswith("syz_")
                      and c.call_name not in _PROBE_SKIP
                      and c.nr < T.PSEUDO_NR_BASE})
        probed = _probe_nrs(nrs)
        if probed:
            n_off = sum(1 for v in probed.values() if not v)
            probe_c.labels(verdict="supported").inc(len(probed) - n_off)
            probe_c.labels(verdict="enosys").inc(n_off)
            source_c.labels(source="probe").inc()
            log.logf(0, "host: kallsyms unreadable; probed %d syscall "
                     "NRs, %d ENOSYS", len(probed), n_off)
        else:
            probe_c.labels(verdict="failed").inc()
            source_c.labels(source="permissive").inc()
            log.logf(0, "host: kallsyms unreadable and probing failed; "
                     "assuming all calls supported")
    else:
        source_c.labels(source="kallsyms").inc()
    out: set[T.Syscall] = set()
    for call in table.calls:
        name = call.call_name
        if name.startswith("syz_"):
            dev = _PSEUDO_DEVICES.get(name)
            if dev is not None and not os.path.exists(dev):
                continue
            out.add(call)  # executor handles unknown pseudo-calls as no-ops
        elif syms is None:
            if probed.get(call.nr, True):   # skip-listed/unprobed: keep
                out.add(call)
        elif _syscall_supported(name, syms):
            out.add(call)
    return out
