"""Host capability detection: which calls can this kernel execute.

Capability parity with reference host/host.go:19-157: kallsyms scan for
syscall entry points, with pseudo-call knowledge (syz_probe* are
executor no-ops, so always "supported"; real syz_* helpers depend on
device files). Falls back to "everything supported" when kallsyms is
unreadable (non-root/containers), as the closure pass still prunes
uncreatable resources.
"""

from __future__ import annotations

import functools
import os

from syzkaller_tpu.sys import types as T
from syzkaller_tpu.sys.table import SyscallTable

_PSEUDO_DEVICES = {
    "syz_open_dev": None,       # checked per-arg at generation time
    "syz_open_pts": "/dev/ptmx",
    "syz_fuse_mount": "/dev/fuse",
    "syz_fuseblk_mount": "/dev/fuse",
    "syz_emit_ethernet": "/dev/net/tun",
    "syz_kvm_setup_cpu": "/dev/kvm",
}


@functools.lru_cache(maxsize=None)
def _kallsyms() -> "frozenset[str] | None":
    try:
        with open("/proc/kallsyms", "rb") as f:
            data = f.read()
    except OSError:
        return None
    if not data:
        return None
    syms = set()
    for line in data.splitlines():
        parts = line.split()
        if len(parts) >= 3:
            syms.add(parts[2].decode(errors="replace"))
    return frozenset(syms)


def _syscall_supported(name: str, syms: "frozenset[str] | None") -> bool:
    if syms is None:
        return True
    for pat in (f"sys_{name}", f"__x64_sys_{name}", f"__se_sys_{name}",
                f"__arm64_sys_{name}", f"ksys_{name}"):
        if pat in syms:
            return True
    # compat/indirect entries (socketcall etc.) or inlined wrappers:
    # absence in kallsyms is not definitive, be permissive for common ones
    return name in ("mmap", "munmap", "read", "write", "open", "close",
                    "exit", "exit_group")


def detect_supported(table: SyscallTable) -> set[T.Syscall]:
    syms = _kallsyms()
    out: set[T.Syscall] = set()
    for call in table.calls:
        name = call.call_name
        if name.startswith("syz_"):
            dev = _PSEUDO_DEVICES.get(name)
            if dev is not None and not os.path.exists(dev):
                continue
            out.add(call)  # executor handles unknown pseudo-calls as no-ops
        elif _syscall_supported(name, syms):
            out.add(call)
    return out
