"""The fuzzing engine: executor-facing loop around the device core."""

from syzkaller_tpu.fuzzer.device_ct import DeviceChoiceTable  # noqa: F401
from syzkaller_tpu.fuzzer.device_signal import DeviceSignal  # noqa: F401
from syzkaller_tpu.fuzzer.pcmap import PcMap  # noqa: F401
