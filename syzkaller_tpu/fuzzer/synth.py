"""Device-resident program synthesis: the fuzzer-side table owner and
the prefetching program stream over `engine.synth_block`.

`DeviceSynth` owns the synth tables — a fixed-capacity corpus of
pre-encoded programs plus a single-call template bank — as host numpy
canonicals mirrored into fixed-shape device operands (the
`DeviceKeyMirror` growth pattern: capacity is allocated once, growth
rewrites CONTENTS, a dispatch signature never changes, so table growth
costs zero warm recompiles).  Growth follows the miss→host-fix-up→
append loop: programs the triage plane admits are host-encoded through
the `prog.synth.encode_program` eligibility gate (segment contract +
decode/csource round trip) and appended; ineligible programs simply
stay on the host path.

`SynthStream` is the proc loop's consumer plane: a submit/resolve
pipeline (dispatch block N+1, resolve N — the `_RingIngest` pattern)
that turns each resolved block into a queue of ready-to-exec programs,
writes their slabs into the device→executor program ring in one
vectorized batch, and hands the proc loop O(1) work per exec: pop an
entry, fire the exec request, note the watermark.  Programs
materialize to `M.Prog` ONLY on the rare paths that need them (triage
items, crash logging) via provenance replay — `prog.synth.materialize`
reconstructs the exact program whose `serialize_for_exec` equals the
slab bit for bit.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from syzkaller_tpu.prog import model as M
from syzkaller_tpu.prog import synth as PS
from syzkaller_tpu.utils import log


class DeviceSynth:
    """Synth table owner + megakernel dispatcher (thread-safe)."""

    def __init__(self, engine, table, rows_cap: int = 128,
                 tmpl_cap: int = 128, max_words: int = 192,
                 max_calls: int = 12, max_slots: int = 32,
                 tmpl_words: int = 96, gen_max: int = 6,
                 batch: int = 64, telemetry=None):
        self.engine = engine
        self.table = table
        self.tstats = telemetry if telemetry is not None else engine.tstats
        self.R = rows_cap
        self.T = tmpl_cap
        self.L = max_words
        self.CO = max_calls
        self.A = max_slots
        self.LT = tmpl_words
        self.GMAX = min(gen_max, max_calls)
        self.B = batch
        C = engine.ncalls
        self._mu = threading.Lock()
        # host canonicals (fixed capacity; contents grow)
        self._rows: list[PS.EncodedProgram] = []
        self._tmpls: list[PS.EncodedProgram] = []
        # score-driven bank replacement (tiered-corpus fold-in): each
        # row carries rank = score·2^20 + admit-seq and the LOWEST rank
        # is replaced first, so score-less admission degenerates to
        # oldest-first instead of the old always-slot-0 rewrite
        self._row_rank = np.full((self.R,), np.inf)
        self._row_seq = 0
        self._h = {
            "rows_lo": np.zeros((self.R, self.L), np.uint32),
            "rows_hi": np.zeros((self.R, self.L), np.uint32),
            "call_off": np.zeros((self.R, self.CO + 1), np.int32),
            "ncalls": np.ones((self.R,), np.int32),
            "slot_off": np.zeros((self.R, self.A), np.int32),
            "slot_size": np.full((self.R, self.A), 8, np.int32),
            "nslots": np.zeros((self.R,), np.int32),
            "call_ids": np.zeros((self.R, self.CO), np.int32),
            "t_lo": np.zeros((self.T, self.LT), np.uint32),
            "t_hi": np.zeros((self.T, self.LT), np.uint32),
            "t_len": np.zeros((self.T,), np.int32),
            "call2tmpl": np.full((C,), -1, np.int32),
            "meta": np.zeros((2,), np.int32),
            "op_weights": PS.OPERATOR_WEIGHTS.astype(np.float32),
        }
        self._dev: "dict | None" = None
        self.stat_rows_rejected = 0
        self.stat_tmpl_rejected = 0

    # -- growth (host fix-up → incremental append) -----------------------

    def build_templates(self, enabled_ids, rand, tries: int = 3) -> int:
        """Populate the template bank: one eligible single-call
        pre-encoding per enabled call (retried — generation is random,
        a call can draw an ineligible instance first).  Returns the
        bank size."""
        from syzkaller_tpu.prog.analysis import State
        from syzkaller_tpu.prog.rand import Gen

        for cid in enabled_ids:
            meta = self.table.calls[cid]
            for _ in range(tries):
                state = State(self.table)
                gen = Gen(rand, state, self.table, None)
                try:
                    calls = gen.generate_particular_call(meta)
                except Exception:
                    continue
                if self._admit_template(cid, M.Prog(calls=calls)):
                    break
            else:
                self.stat_tmpl_rejected += 1
        return len(self._tmpls)

    def _admit_template(self, cid: int, p: M.Prog) -> bool:
        enc = PS.encode_program(p, self.table)
        if enc is None or enc.nwords == 0 or enc.nwords > self.LT:
            return False
        with self._mu:
            if len(self._tmpls) >= self.T:
                return False
            t = len(self._tmpls)
            self._tmpls.append(enc)
            h = self._h
            w = enc.words
            h["t_lo"][t, : len(w)] = (w & np.uint64(0xFFFFFFFF)
                                      ).astype(np.uint32)
            h["t_hi"][t, : len(w)] = (w >> np.uint64(32)
                                      ).astype(np.uint32)
            h["t_len"][t] = len(w)
            h["call2tmpl"][cid] = t
            h["meta"][1] = len(self._tmpls)
            self._dev = None
        return True

    def add_program(self, p: M.Prog, score: "float | None" = None
                    ) -> bool:
        """Admit a triaged program into the device corpus table (the
        growth loop's host fix-up).  Once the table is full,
        replacement is score-driven: the lowest-rank row (rank =
        signal score · 2^20 + admit sequence — the eviction-score
        retention order; score-less callers degenerate to
        oldest-first) rewrites its contents, never shapes.
        Returns False for ineligible programs (they stay host-side)."""
        enc = PS.encode_program(p, self.table)
        if enc is None or enc.nwords == 0 or enc.nwords > self.L - 1 \
                or enc.ncalls > self.CO or len(enc.slots) > self.A:
            self.stat_rows_rejected += 1
            return False
        with self._mu:
            self._row_seq += 1
            rank = ((0.0 if score is None else float(score)) * 2.0**20
                    + self._row_seq)
            if len(self._rows) < self.R:
                r = len(self._rows)
                self._rows.append(enc)
            else:
                r = int(np.argmin(self._row_rank))
                self._rows[r] = enc
            self._row_rank[r] = rank
            h = self._h
            w = enc.words
            h["rows_lo"][r] = 0
            h["rows_hi"][r] = 0
            h["rows_lo"][r, : len(w)] = (w & np.uint64(0xFFFFFFFF)
                                         ).astype(np.uint32)
            h["rows_hi"][r, : len(w)] = (w >> np.uint64(32)
                                         ).astype(np.uint32)
            off = np.full((self.CO + 1,), enc.nwords, np.int32)
            off[: len(enc.call_off)] = enc.call_off
            h["call_off"][r] = off
            h["ncalls"][r] = enc.ncalls
            h["call_ids"][r] = 0
            h["call_ids"][r, : enc.ncalls] = enc.call_ids
            h["nslots"][r] = len(enc.slots)
            h["slot_off"][r] = 0
            h["slot_size"][r] = 8
            for a, (woff, size, _ci) in enumerate(enc.slots):
                h["slot_off"][r, a] = woff
                h["slot_size"][r, a] = size
            h["meta"][0] = max(int(h["meta"][0]), len(self._rows))
            self._dev = None
        if self.tstats is not None:
            self.tstats.inc("synth_table_rows")
        return True

    # corpus-row-axis tables: sharded over the engine mesh's "pc" axis
    # (R rows split across devices); the template bank and scalar meta
    # stay replicated — every device draws from the full bank
    _ROW_AXIS = ("rows_lo", "rows_hi", "call_off", "ncalls", "slot_off",
                 "slot_size", "nslots", "call_ids")

    def operands(self) -> dict:
        """Fixed-shape device operands, re-put only after growth."""
        with self._mu:
            if self._dev is None:
                rep = self.engine.put_replicated
                row = getattr(self.engine, "put_row_sharded", rep)
                self._dev = {
                    k: (row(v) if k in self._ROW_AXIS else rep(v))
                    for k, v in self._h.items()}
            return self._dev

    def invalidate_device(self) -> None:
        """Drop cached device operands (backend failover re-homes)."""
        with self._mu:
            self._dev = None

    def snapshot(self):
        """Immutable table snapshot for provenance replay: dispatches
        resolve against the tables AS OF submit time, so a FIFO row
        replacement racing a resolve cannot misattribute."""
        with self._mu:
            return tuple(self._rows), tuple(self._tmpls)

    @property
    def n_rows(self) -> int:
        with self._mu:
            return len(self._rows)

    @property
    def n_templates(self) -> int:
        with self._mu:
            return len(self._tmpls)

    # -- dispatch --------------------------------------------------------

    def dispatch(self, overlay=None):
        """One async synth_block dispatch; returns an opaque ticket.
        The ticket freezes EVERY table the resolve reads — rows, the
        template bank, and the call→template map — as of submit time
        (syz-vet epoch/resolve-reads-live-table: a template admitted
        between submit and resolve must not re-map this block's
        provenance)."""
        blk = self.engine.synth_block(self.operands(), self.B,
                                      self.GMAX, overlay=overlay)
        with self._mu:
            snap = (tuple(self._rows), tuple(self._tmpls),
                    self._h["call2tmpl"].copy())
        return (blk, snap, time.monotonic())

    def resolve(self, ticket) -> "SynthBatch":
        """Fetch one dispatched block: B ready programs as one slab
        matrix plus per-program provenance views (call ids and Prog
        factories derive lazily from provenance + the submit-time
        table snapshot)."""
        blk, (rows, tmpls, c2t), t0 = ticket
        out32 = np.asarray(blk.out32)
        lens32 = np.asarray(blk.lens32)
        op = np.asarray(blk.op)
        r1 = np.asarray(blk.r1)
        r2 = np.asarray(blk.r2)
        cut = np.asarray(blk.cut)
        pos = np.asarray(blk.pos)
        dele = np.asarray(blk.dele)
        k = np.asarray(blk.k)
        gen_cids = np.asarray(blk.gen_cids)
        ins_cid = np.asarray(blk.ins_cid)
        slot = np.asarray(blk.slot)
        mkind = np.asarray(blk.mut_kind)
        mval = (np.asarray(blk.mut_hi).astype(np.uint64) << np.uint64(32)
                ) | np.asarray(blk.mut_lo).astype(np.uint64)
        nent = np.asarray(blk.n_entries)
        if self.tstats is not None:
            self.tstats.observe("synth_block_consume_latency",
                                time.monotonic() - t0)
        gen_tmpls = np.maximum(c2t[gen_cids], 0)
        ins_tmpl = np.maximum(c2t[ins_cid], 0)
        progs = []
        for i in range(len(op)):
            prov = PS.Provenance(
                op=int(op[i]), r1=int(r1[i]), r2=int(r2[i]),
                cut=int(cut[i]), pos=int(pos[i]), dele=int(dele[i]),
                k=int(k[i]),
                gen_tmpls=tuple(gen_tmpls[i][: int(k[i])].tolist()),
                ins_tmpl=int(ins_tmpl[i]),
                slot=int(slot[i]), mut_kind=int(mkind[i]),
                mut_val=int(mval[i]), n_entries=int(nent[i]))
            progs.append(SynthProgram(
                self, prov, rows, tmpls, out32[i], int(lens32[i])))
        return SynthBatch(out32=out32, lens32=lens32, progs=progs)


class SynthBatch:
    """One resolved synth block: the slab matrix (ring write operand)
    + per-program handles (views into it)."""

    __slots__ = ("out32", "lens32", "progs")

    def __init__(self, out32, lens32, progs):
        self.out32 = out32
        self.lens32 = lens32
        self.progs = progs


class SynthProgram:
    """One device-synthesized program: slab words now, Prog on demand."""

    __slots__ = ("synth", "prov", "rows", "tmpls", "words32", "len32",
                 "_ids")

    def __init__(self, synth, prov, rows, tmpls, words32, len32):
        self.synth = synth
        self.prov = prov
        self.rows = rows
        self.tmpls = tmpls
        self.words32 = words32
        self.len32 = len32
        self._ids = None

    def exec_bytes(self) -> bytes:
        """The exec wire image (shm fallback path when the program
        ring is full): the slab IS the wire format."""
        return self.words32[: self.len32].tobytes()

    def call_ids(self) -> np.ndarray:
        """Per-call table ids, derived from the segment plan (no Prog
        materialization): slab tag → call id for ring attribution."""
        if self._ids is None:
            # bounded by max_calls (CO) entries — not data-proportional
            ent = PS.plan_entries(self.prov, self.rows, self.tmpls,
                                  self.synth.L, self.synth.CO)
            parts = tuple(
                (self.tmpls[idx].call_ids if tbl
                 else self.rows[idx].call_ids[call: call + 1])
                for tbl, idx, call in ent)
            self._ids = (np.concatenate(parts).astype(np.int32)
                         if parts else np.zeros(0, np.int32))
        return self._ids

    def materialize(self) -> M.Prog:
        """Provenance replay → the exact M.Prog whose exec encoding is
        this slab (rare path: triage items, crash logging)."""
        return PS.materialize(self.prov, self.rows, self.tmpls,
                              self.synth.L, self.synth.CO)


class SynthStream:
    """The proc loop's program source: pipelined dispatch + ring write.

    `next_program()` is the per-exec entry point: a deque pop.  When
    the queue drains below B the stream dispatches a new block and
    resolves the previously in-flight one (double-buffered, so the
    device round trip overlaps executor work).  Resolved programs are
    written to the device→executor program ring in ONE vectorized
    batch; entries that could not be ringed (ring full — counted)
    carry their bytes for the shm fallback path."""

    def __init__(self, synth: DeviceSynth, ring_writer=None,
                 max_queue: "int | None" = None):
        self.synth = synth
        self.writer = ring_writer       # ipc.ring.RingWriter | None
        self._q: deque[tuple] = deque()   # (SynthProgram, ringed)
        self._inflight = None
        self._mu = threading.Lock()
        self.max_queue = max_queue or 4 * synth.B
        self.stat_served = 0
        self.stat_ring_written = 0
        self.stat_ring_full = 0
        self.stat_underruns = 0

    def ready(self) -> bool:
        return self.synth.n_templates > 0

    def next_program(self) -> "tuple | None":
        """(SynthProgram, ringed) or None when the plane cannot serve
        (no templates yet / dispatch failure) — the caller falls back
        to host generation, counted as an underrun."""
        with self._mu:
            if self._q:
                self.stat_served += 1
                return self._q.popleft()
        if not self.ready():
            return None
        try:
            self._refill()
        except Exception as e:
            log.logf(0, "synth refill failed: %r", e)
            self._note_underrun()
            return None
        with self._mu:
            if self._q:
                self.stat_served += 1
                return self._q.popleft()
        self._note_underrun()
        return None

    def _note_underrun(self) -> None:
        self.stat_underruns += 1
        if self.synth.tstats is not None:
            self.synth.tstats.inc("synth_underrun")

    def _refill(self) -> None:
        """Dispatch a fresh block, then resolve the previous one into
        the queue (submit-N+1-resolve-N pipelining).  The FIRST refill
        resolves synchronously so the caller gets programs now."""
        with self._mu:
            prev, self._inflight = self._inflight, None
        nxt = self.synth.dispatch()
        if prev is None:
            self._publish(self.synth.resolve(nxt))
            return
        with self._mu:
            self._inflight = nxt
        self._publish(self.synth.resolve(prev))

    def _publish(self, batch) -> None:
        ringed = self._write_ring(batch)
        with self._mu:
            if len(self._q) < self.max_queue:
                self._q.extend(zip(batch.progs, ringed))

    def _write_ring(self, batch) -> np.ndarray:
        """One vectorized ring write per block — the resolved slab
        matrix IS the write operand (same-bucket slabs land as one
        contiguous block copy); a full ring degrades those entries to
        per-entry shm bytes.  Returns the (B,) written-mask."""
        n = len(batch.progs)
        if self.writer is None:
            return np.zeros((n,), bool)
        ok = self.writer.write_batch(batch.out32, batch.lens32)
        wrote = int(np.sum(ok))
        self.stat_ring_written += wrote
        self.stat_ring_full += n - wrote
        ts = self.synth.tstats
        if ts is not None:
            if wrote:
                ts.inc("synth_slabs", wrote)
            if wrote < n:
                ts.inc("synth_ring_full", n - wrote)
        return ok
