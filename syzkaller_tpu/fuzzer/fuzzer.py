"""The in-VM fuzzer process: gen/mutate/triage/minimize loop.

Capability parity with reference syz-fuzzer/fuzzer.go: RPC Connect +
call-list construction (enabled ∩ host-supported ∩ transitive closure,
:126,307-342), per-proc loops with corpus mutation vs generation split
(:174-232), per-call signal diff against max cover (:456-478), triage
with 3× re-execution, flake subtraction and minimization (:377-454),
the 3s poll loop exchanging stats/new inputs/candidates (:235-305), and
"log the program before you run it" crash attribution (:499-523).

TPU-native split (SURVEY §2 "TPU-native equivalent"): the fuzzer keeps
cheap numpy sorted-set caches locally (per-VM fast path); the manager
owns the device-resident global coverage matrix + choice tables and
streams back batched device-drawn mutation decisions via Poll.

    python -m syzkaller_tpu.fuzzer.fuzzer -name vm0 -manager 127.0.0.1:NNNN
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass

import numpy as np

from syzkaller_tpu import ipc
from syzkaller_tpu import prog as P
from syzkaller_tpu import rpc, telemetry
from syzkaller_tpu.cover import sets
from syzkaller_tpu.fuzzer import host as host_mod
from syzkaller_tpu.prog import model as M
from syzkaller_tpu.sys.table import load_table
from syzkaller_tpu.utils import log

PROG_NCALLS = 30  # ref fuzzer.go:47


@dataclass
class TriageItem:
    prog: M.Prog
    call_index: int
    cover: np.ndarray
    from_candidate: bool = False
    minimized: bool = False


def _exec_call_ids(p: M.Prog) -> np.ndarray:
    """Table call ids per program call, cached on the prog (computed
    once per executed program — executed progs are immutable, and the
    slab→call-id mapping must not rebuild Python lists per exec)."""
    ids = getattr(p, "_exec_ids", None)
    if ids is None or len(ids) != len(p.calls):
        ids = np.fromiter((c.meta.id for c in p.calls), np.int32,
                          len(p.calls))
        try:
            p._exec_ids = ids
        except AttributeError:
            pass
    return ids


class _RingIngest:
    """Per-proc zero-copy ingest: the executor's pinned PC ring →
    fused translate+update device dispatches.

    Per exec the host does O(1) work: one header read to watermark the
    exec's slab span (`note_exec`).  `flush` turns committed slab runs
    into zero-copy (B, K) window views, maps each slab to its source
    program with one vectorized searchsorted over the watermarks,
    submits the fused dispatch WITHOUT a sync, and resolves the
    previous batch — the submit/resolve pipeline of the legacy path,
    minus all its per-exec Python list packing.  Covers materialize
    host-side ONLY for slabs that earn a new-signal verdict (the rare
    triage candidates)."""

    def __init__(self, fuzzer: "Fuzzer", env: "ipc.Env"):
        self.f = fuzzer
        self.env = env
        self.reader = env.ring_reader
        # (prog | None, cached call-id vector, resv watermark): a slab
        # with global index < watermark belongs to the LAST exec whose
        # watermark exceeds it; None progs (triage/minimize/candidate
        # re-executions) discard their slabs
        self._marks: deque = deque()
        self._inflight = None
        self._last_force = time.monotonic()
        self._last_dropped = 0

    def note_exec(self, prog: "M.Prog | None") -> None:
        ids = _exec_call_ids(prog) if prog is not None else None
        self.note_exec_ids(ids, prog)

    def note_exec_ids(self, ids, owner) -> None:
        """Watermark one exec with a pre-computed call-id vector.
        `owner` is a Prog, a zero-arg Prog factory (device-synthesized
        programs materialize lazily — only new-signal slabs pay the
        provenance replay), or None (slabs discarded)."""
        from syzkaller_tpu.ipc import ring as ring_mod
        self._marks.append(
            (owner, ids, self.reader.ring.load(ring_mod.H_RESV)))

    def on_restart(self) -> None:
        """The executor died (hang/kill/retry): drain the committed
        slabs it did land, resolve what's in flight, then skip any torn
        slab it left reserved-uncommitted — counted, never crashed."""
        self.maybe_flush(force=True)
        skipped = self.reader.resync()
        if skipped and self.f.signal.tstats is not None:
            self.f.signal.tstats.inc("ingest_resync", skipped)

    def pending(self) -> int:
        return self.reader.pending()

    def maybe_flush(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_force > 2.0:
            force = True        # low-throughput runs must not strand slabs
        if force:
            self._last_force = now
        sig = self.f.signal
        while self.pending() >= (1 if force else sig.B):
            batch = self.reader.read_batch(max_slabs=max(sig.B, 1))
            if batch is None:
                break
            self._submit(batch)
        if force:
            self._resolve(self._take_inflight())
        self._count_drops()

    def _count_drops(self) -> None:
        from syzkaller_tpu.ipc import ring as ring_mod
        dropped = self.reader.ring.load(ring_mod.H_DROPPED)
        if dropped > self._last_dropped and self.f.signal.tstats is not None:
            self.f.signal.tstats.inc("ingest_ring_full",
                                     dropped - self._last_dropped)
        self._last_dropped = dropped

    def _take_inflight(self):
        prev, self._inflight = self._inflight, None
        return prev

    def _submit(self, batch) -> None:
        # vectorized slab→exec attribution: one searchsorted over the
        # live watermarks, then call ids through the concatenated
        # per-prog id vectors (cached on each prog)
        marks = self._marks
        W = np.fromiter((m[2] for m in marks), np.int64, len(marks))
        idsets = [m[1] if m[1] is not None else _EMPTY_IDS for m in marks]
        lens = np.fromiter((len(x) for x in idsets), np.int64,
                           len(idsets))
        base = np.concatenate([[0], np.cumsum(lens)[:-1]]) \
            if len(idsets) else np.zeros(1, np.int64)
        cat = (np.concatenate(idsets) if len(idsets)
               else _EMPTY_IDS)
        slab_idx = batch.start_idx + np.arange(batch.n, dtype=np.int64)
        j = np.searchsorted(W, slab_idx, side="right")
        # slabs past every watermark (mid-exec read) wait for their
        # exec's note; slabs from discarded execs mask to no-ops
        live = j < len(marks)
        tags = batch.tags.astype(np.int64)
        ok = live & (tags < lens[np.minimum(j, max(len(marks) - 1, 0))])
        call_ids = np.zeros((batch.n,), np.int32)
        if ok.any():
            call_ids[ok] = cat[base[j[ok]] + tags[ok]]
        counts = np.where(ok, batch.counts, 0).astype(np.int32)
        if not ok.any():
            # a batch of discarded slabs only: nothing to dispatch —
            # resolve what's in flight so this batch can be consumed
            # in order, then release it
            self._resolve(self._take_inflight())
            self.reader.consume(batch)
            done = batch.start_idx + batch.n
            while self._marks and self._marks[0][2] <= done:
                self._marks.popleft()
            return
        ticket = self.f.signal.submit_slabs(batch.win, counts, call_ids)
        owners = [marks[int(jj)][0] if o else None
                  for jj, o in zip(j, ok)]
        prev = self._inflight
        self._inflight = (batch, ticket, owners)
        self._resolve(prev)

    def _resolve(self, inflight) -> None:
        if inflight is None:
            return
        batch, ticket, owners = inflight
        has_new = self.f.signal.resolve(ticket)
        items = []
        for i in np.nonzero(has_new[: batch.n])[0]:
            # cover (and, for synth programs, the Prog itself via the
            # provenance-replay factory) materializes ONLY for
            # new-signal slabs — the rare path feeding triage
            own = owners[i]
            if own is not None:
                items.append(TriageItem(
                    prog=own() if callable(own) else M.clone_prog(own),
                    call_index=int(batch.tags[i]),
                    cover=batch.cover(i)))
        self.reader.consume(batch)
        # prune watermarks everything before the batch end has passed
        done = batch.start_idx + batch.n
        while self._marks and self._marks[0][2] <= done:
            self._marks.popleft()
        if items:
            with self.f._mu:
                self.f.triage_q.extend(items)


_EMPTY_IDS = np.zeros(0, np.int32)


class Fuzzer:
    def __init__(self, name: str, manager_addr: str, procs: int = 1,
                 descriptions: str = "all", flags: "int | None" = None,
                 output_mode: str = "none", leak: bool = False,
                 table=None, seed: int = 0, use_device: bool = False,
                 npcs: int = 1 << 16, flush_batch: int = 32,
                 corpus_cap: int = 1 << 14, synth: bool = False):
        self.name = name
        self.procs = procs
        self.output_mode = output_mode
        self.table = table or load_table(
            files=None if descriptions in ("all", "linux") else [descriptions])
        self.flags = (flags if flags is not None else
                      ipc.FLAG_COVER | ipc.FLAG_DEDUP_COVER | ipc.FLAG_FAKE_COVER)
        self.leak = leak and os.path.exists("/sys/kernel/debug/kmemleak")
        self.seed = seed
        # typed stat plane (ref fuzzer.go ships counter deltas on every
        # Poll): counters replace the raw stats dict; keys here ARE the
        # legacy Poll wire names, so the manager-side aggregation and
        # its html view are byte-identical
        self.registry = telemetry.Registry()
        self.tracer = telemetry.Tracer(name=name)
        # RPC fault envelope: a mid-call socket break reconnects and
        # retries with backoff inside the client (counted) instead of
        # killing the proc loop — the manager dedups replayed NewInputs
        # by idempotency key
        self._c_rpc_retries = self.registry.counter(
            "syz_rpc_retries_total",
            "RPC attempts retried after a transport fault")
        self._c_rpc_failures = self.registry.counter(
            "syz_rpc_failures_total",
            "RPC calls abandoned after exhausting retries")
        # manager overload backpressure: a NewInput answered with the
        # "shed" reply keeps the input local-only and opens a doubling
        # backoff window during which triage skips the delivery RPC
        # entirely (the manager asked us to stop hammering it)
        self._c_shed_replies = self.registry.counter(
            "syz_fuzzer_admission_shed_total",
            "NewInputs the manager shed under overload")
        self._c_local_only = self.registry.counter(
            "syz_fuzzer_local_only_total",
            "triaged inputs kept local-only during a shed backoff window")
        self._shed_until = 0.0
        self._shed_backoff = 1.0
        self.client = rpc.RpcClient(manager_addr,
                                    retry_counter=self._c_rpc_retries)
        self._ts_shipped = None          # poll-delta watermark for the
        #                                  device stat vector (if any)
        f_exec = self.registry.counter(
            "syz_exec_total", "executed programs by stat class",
            labels=("stat",))
        self._stat_counters = {
            "exec total": f_exec.labels(stat="total"),
            "exec gen": f_exec.labels(stat="gen"),
            "exec fuzz": f_exec.labels(stat="fuzz"),
            "exec candidate": f_exec.labels(stat="candidate"),
            "exec triage": f_exec.labels(stat="triage"),
            "exec minimize": f_exec.labels(stat="minimize"),
            "new inputs": self.registry.counter(
                "syz_fuzzer_new_inputs_total",
                "triaged inputs sent to the manager"),
        }
        self._h_exec = self.registry.histogram(
            "syz_exec_seconds", "executor round-trip latency")
        # Device-resident signal path (VERDICT r1 #3): per-exec diffs,
        # flakes and corpus membership run on the CoverageEngine; falls
        # back to the numpy sorted-set path when JAX is unavailable.
        self.signal = None
        if use_device:
            try:
                # jax logs platform chatter at WARNING; our stdout/stderr
                # is a VM console stream scanned for kernel oopses
                import logging
                logging.getLogger("jax._src.xla_bridge").setLevel(
                    logging.ERROR)
                from syzkaller_tpu.fuzzer.device_signal import DeviceSignal
                self.signal = DeviceSignal(
                    ncalls=self.table.count, npcs=npcs,
                    flush_batch=flush_batch, corpus_cap=corpus_cap,
                    seed=seed, telemetry=telemetry.DeviceStats())
            except Exception as e:  # no jax / no backend: degrade to host
                log.logf(0, "device signal unavailable (%s); using host sets", e)
        # (prog, call_index, canonical cover) awaiting a device verdict
        self._pending_sig: list[tuple] = []
        self._sig_mu = threading.Lock()          # submit-order pipeline
        self._inflight_sig: "tuple | None" = None
        self._corpus_rows: deque[int] = deque()  # device-drawn mutate picks
        # per-env zero-copy ring ingests (keyed by env identity; each
        # proc owns one env + one ring)
        self._ingests: dict[int, _RingIngest] = {}
        # device program synthesis: the synth tables are shared across
        # procs (built in build_call_list once the enabled set is
        # known); each proc runs its own SynthStream over its own
        # program ring.  Requires the device signal plane.
        self._synth_requested = synth and self.signal is not None
        self.synthdev = None

        n = self.table.count
        self.max_cover: list[np.ndarray] = [np.zeros(0, np.uint32)] * n
        self.corpus_cover: list[np.ndarray] = [np.zeros(0, np.uint32)] * n
        self.flakes: list[np.ndarray] = [np.zeros(0, np.uint32)] * n
        self.corpus: list[M.Prog] = []
        self.corpus_hashes: set[bytes] = set()
        self.triage_q: deque[TriageItem] = deque()
        self.candidate_q: deque[tuple[bytes, bool]] = deque()
        self.device_choices: deque[int] = deque()
        self._mu = threading.Lock()
        self._stop = False
        self.ct: "P.ChoiceTable | None" = None
        self.enabled_ids: list[int] = []
        # campaign plane: the manager assigns a campaign at Connect and
        # may rotate it via Poll; the fuzzer applies it as a choice-
        # table overlay (device: epoch-path swap on the decision
        # stream; host: a boosted ChoiceTable rebuild), a protocol
        # machine for stateful gen/mutation, and a transition-coverage
        # view (word-block-sparse over the dense transition-id space)
        self.campaign = None
        self.transition_cov = None
        self._campaign_name: "str | None" = None
        self._prios: "np.ndarray | None" = None
        self._tcov_shipped = 0          # poll-delta watermark
        # ONE gate shared by all procs: the leak-scan callback must run
        # with every proc's executions drained (ref fuzzer.go:153-162)
        self.gate = ipc.Gate(2 * max(1, procs),
                             callback=self.leak_scan if self.leak else None)

    # -- startup -----------------------------------------------------------

    def connect(self) -> None:
        r = self.client.call("Manager.Connect", {"name": self.name},
                             span=self.tracer.new_trace(origin=self.name))
        prios = None
        if r.get("prios"):
            raw = np.frombuffer(rpc.unb64(r["prios"]), np.float32)
            n = self.table.count
            if len(raw) == n * n:
                prios = raw.reshape(n, n)
        enabled_names = r.get("enabled") or [c.name for c in self.table.calls]
        for cp in r.get("candidates", []):
            self.candidate_q.append((rpc.unb64(cp["prog"]),
                                     bool(cp.get("minimized"))))
        self.build_call_list(enabled_names, prios)
        self._apply_campaign(r.get("campaign"))
        self.client.call("Manager.Check", {
            "name": self.name,
            "calls": [self.table.calls[i].name for i in self.enabled_ids]})

    def build_call_list(self, enabled_names, prios) -> None:
        """enabled ∩ host-supported ∩ transitive closure (ref :307-342)."""
        enabled = {self.table.call_map[n] for n in enabled_names
                   if n in self.table.call_map}
        supported = host_mod.detect_supported(self.table,
                                              registry=self.registry)
        enabled &= supported
        closed = self.table.transitively_enabled_calls(enabled)
        dropped = enabled - closed
        if dropped:
            log.logf(1, "disabling %d calls without ctors: %s...",
                     len(dropped), sorted(c.name for c in dropped)[:5])
        self.enabled_ids = sorted(c.id for c in closed)
        if not self.enabled_ids:
            log.fatalf("no enabled calls after closure")
        if prios is None:
            prios = P.calculate_priorities(self.table)
        self._prios = prios
        if self.signal is not None:
            # The decision-stream plane (ref prog/prio.go:230-249, fused):
            # one megakernel feeds choice draws, corpus-row picks AND
            # Rand entropy through a double-buffered async prefetcher —
            # the per-path sampling dispatches are retired.
            from syzkaller_tpu.fuzzer.device_ct import DeviceChoiceTable
            self.signal.engine.set_priorities(prios)
            self.signal.engine.set_enabled(self.enabled_ids)
            self.ct = DeviceChoiceTable(self.signal.engine,
                                        telemetry=self.signal.tstats)
            if self._synth_requested:
                # device program synthesis: pre-encode one template per
                # enabled call (the eligibility gate filters); corpus
                # rows grow from triage admissions (add_program)
                from syzkaller_tpu.fuzzer.synth import DeviceSynth
                self.synthdev = DeviceSynth(
                    self.signal.engine, self.table,
                    telemetry=self.signal.tstats)
                trand = P.Rand(np.random.default_rng(
                    self.seed * 131 + 17))
                nt = self.synthdev.build_templates(self.enabled_ids,
                                                   trand)
                log.logf(0, "synth templates: %d/%d calls eligible",
                         nt, len(self.enabled_ids))
        else:
            self.ct = P.ChoiceTable(prios, set(self.enabled_ids),
                                    ncalls=self.table.count)

    # -- campaign plane ----------------------------------------------------

    def _apply_campaign(self, name: "str | None") -> None:
        """Apply (or clear) the manager-assigned campaign.  Device
        path: the overlay swaps through DecisionStream.set_overlay —
        the invalidate() epoch path, fixed-shape operands, zero warm
        recompiles.  Host path: rebuild the ChoiceTable with boosted
        columns + the restricted enabled set.  Idempotent per name, so
        every Poll can re-send the current assignment."""
        if name == self._campaign_name:
            return
        camp = None
        if name is not None:
            try:
                from syzkaller_tpu.campaign import load_campaign
                camp = load_campaign(name, self.table)
            except Exception as e:
                log.logf(0, "campaign %r unavailable, staying flat: %s",
                         name, e)
                return
        with self._mu:
            self.campaign = camp
            self._campaign_name = name if camp is not None else None
            self.transition_cov = (camp.transition_coverage()
                                   if camp is not None else None)
            self._tcov_shipped = 0
        if self.signal is not None:
            ov = None
            if camp is not None:
                ov = self.signal.engine.make_overlay(
                    camp.name, camp.boost,
                    camp.restrict_enabled(self.enabled_ids))
            self.ct.set_overlay(ov)
            # per-campaign frontier over the shared device bitmap: new
            # signal from here on is attributed to this campaign
            self.signal.set_frontier(
                self.signal.engine.frontier_view(camp.name)
                if camp is not None else None)
        else:
            prios = (self._prios if self._prios is not None
                     else P.calculate_priorities(self.table))
            if camp is not None:
                self.ct = camp.host_choice_table(prios, self.enabled_ids)
            else:
                self.ct = P.ChoiceTable(prios, set(self.enabled_ids),
                                        ncalls=self.table.count)
        log.logf(0, "campaign: %s", name if camp is not None else "flat")

    def _campaign_generate(self, rand: P.Rand) -> "M.Prog | None":
        """Stateful generation under the active campaign (seed
        prologue + protocol-machine walk); records transition coverage.
        None when no campaign is active."""
        with self._mu:
            camp, tcov = self.campaign, self.transition_cov
        if camp is None:
            return None
        p = camp.generate(rand, PROG_NCALLS, self.ct)
        if tcov is not None:
            tcov.observe(p.calls)
        return p

    def _campaign_mutate(self, p: M.Prog, rand: P.Rand,
                         corpus) -> bool:
        """Protocol-order-respecting mutation under the active
        campaign; False = caller should run the flat mutator."""
        with self._mu:
            camp, tcov = self.campaign, self.transition_cov
        if camp is None or camp.machine is None:
            return False
        camp.mutate(p, rand, PROG_NCALLS, self.ct, corpus)
        if tcov is not None:
            tcov.observe(p.calls)
        return True

    # -- signal helpers ----------------------------------------------------

    def _diff_max(self, call_id: int, cover: np.ndarray) -> np.ndarray:
        return sets.difference(sets.canonicalize(cover),
                               self.max_cover[call_id])

    def _merge_max(self, call_id: int, cover: np.ndarray) -> None:
        self.max_cover[call_id] = sets.union(self.max_cover[call_id],
                                             sets.canonicalize(cover))

    # -- execution ---------------------------------------------------------

    def log_program(self, pid: int, p: M.Prog) -> None:
        if self.output_mode == "stdout":
            # the crash-attribution invariant: program text precedes its
            # execution in the console log (ref fuzzer.go:499-523)
            sys.stdout.write(f"executing program {pid}:\n"
                             f"{P.serialize(p).decode()}\n")
            sys.stdout.flush()

    def execute(self, env: ipc.Env, p: M.Prog, stat: str, pid: int,
                ring_prog: "M.Prog | None" = None
                ) -> "ipc.ExecResult | None":
        """ring_prog non-None marks a HOT-loop exec whose covers flow
        through the zero-copy ring (shm-out cover copies skipped);
        triage/minimize/candidate re-executions keep parsed covers and
        their ring slabs are discarded at ingest."""
        self.log_program(pid, p)
        self._stat_counters["exec total"].inc()
        self._stat_counters[stat].inc()
        ingest = self._ingests.get(id(env))
        hot = ring_prog is not None and ingest is not None
        for attempt in range(3):
            try:
                t0 = time.monotonic()
                res = env.exec(p, parse_covers=not hot,
                               extra_flags=0 if hot else (
                                   ipc.FLAG_RING_SKIP
                                   if ingest is not None else 0))
                dt = time.monotonic() - t0
                self._h_exec.observe(dt)
                if self.signal is not None and self.signal.tstats is not None:
                    self.signal.tstats.observe("exec_latency", dt)
                if ingest is not None:
                    if hot:
                        ingest.note_exec(ring_prog)
                    if res.restarted or res.hanged:
                        ingest.on_restart()
                return res
            except ipc.ExecutorFailure as e:
                log.logf(0, "executor failure (try %d): %s", attempt, e)
                if ingest is not None:
                    ingest.note_exec(None)
                    ingest.on_restart()
                time.sleep(0.5 * (attempt + 1))
        return None

    def execute_synth(self, env: ipc.Env, entry, pid: int) -> None:
        """Run one device-synthesized program: ringed entries take the
        slab-attach path (the program never crosses shm-in), ring-full
        entries fall back to shm bytes (the slab IS the wire image).
        Per exec the host does O(1) work: the exec request, one
        watermark note, and — only on failure — the reverse-direction
        ring resync (skip the slab a dead executor never consumed)."""
        from syzkaller_tpu.ipc import ring as ring_mod
        sp, ringed = entry
        if self.output_mode == "stdout":
            # crash-attribution invariant: text precedes execution —
            # the one synth path that pays a materialize per exec
            self.log_program(pid, sp.materialize())
        self._stat_counters["exec total"].inc()
        self._stat_counters["exec fuzz"].inc()
        ingest = self._ingests.get(id(env))
        cons0 = (env.prog_ring.load(ring_mod.H_CONSUMED)
                 if ringed and env.prog_ring is not None else -1)
        try:
            t0 = time.monotonic()
            if ringed:
                res = env.exec(None, from_prog_ring=True,
                               parse_covers=ingest is None)
            else:
                res = env.exec(sp.exec_bytes(),
                               parse_covers=ingest is None)
            self._h_exec.observe(time.monotonic() - t0)
        except ipc.ExecutorFailure as e:
            log.logf(0, "synth exec failure: %s", e)
            res = None
        if ingest is not None:
            if res is not None:
                ingest.note_exec_ids(sp.call_ids(), sp.materialize)
            else:
                ingest.note_exec(None)
            if res is None or res.restarted or res.hanged:
                ingest.on_restart()
        if ringed and env.prog_ring is not None and (
                res is None or res.hanged or res.status < 0):
            # the executor died without replying: if it never consumed
            # the slab, skip it so the next ringed exec reads its own
            if env.prog_ring.load(ring_mod.H_CONSUMED) == cons0:
                ring_mod.skip_committed(env.prog_ring, 1)

    def check_new_signal(self, p: M.Prog, res: ipc.ExecResult) -> None:
        if self.signal is not None:
            # Device path: buffer exec calls and flush them through one
            # fixed-shape update_batch step (diff vs max cover + merge,
            # in-batch dedup) — the BASELINE hot loop on device.  The
            # prog is immutable once executed, so no clone here: items
            # that get a new-signal verdict are cloned at flush time.
            items = [(p, c.index, sets.canonicalize(c.cover))
                     for c in res.calls
                     if c.index < len(p.calls) and len(c.cover)]
            with self._mu:
                self._pending_sig.extend(items)
                full = len(self._pending_sig) >= self.signal.B
            if full:
                self.flush_signal()
            return
        for c in res.calls:
            if c.index >= len(p.calls) or not len(c.cover):
                continue
            call_id = p.calls[c.index].meta.id
            with self._mu:
                diff = self._diff_max(call_id, c.cover)
                diff = sets.difference(diff, self.flakes[call_id])
                if len(diff) == 0:
                    continue
                self._merge_max(call_id, c.cover)
                self.triage_q.append(TriageItem(
                    prog=M.clone_prog(p), call_index=c.index,
                    cover=sets.canonicalize(c.cover)))

    def flush_signal(self, force: bool = False) -> None:
        """Drain pending exec covers through device update steps; execs
        with new signal enter the triage queue (ref fuzzer.go:460-478).
        Pipelined: each batch is SUBMITTED (async dispatch) and the
        verdict of the previously submitted batch is resolved afterwards,
        so the tunnel round-trip overlaps with executor work — triage
        admission lags by one flush, which the reference's async triage
        queue already tolerates."""
        if self.signal is None:
            return
        while True:
            with self._mu:
                if not self._pending_sig:
                    break
                if len(self._pending_sig) < self.signal.B and not force:
                    break
                batch = self._pending_sig[: self.signal.B]
                self._pending_sig = self._pending_sig[self.signal.B:]
            entries = [(p.calls[ci].meta.id, cov) for p, ci, cov in batch]
            with self._sig_mu:
                ticket = self.signal.submit_batch(entries)
                prev, self._inflight_sig = self._inflight_sig, (batch, ticket)
            self._resolve_flush(prev)
        if force:
            with self._sig_mu:
                prev, self._inflight_sig = self._inflight_sig, None
            self._resolve_flush(prev)

    def _resolve_flush(self, inflight) -> None:
        if inflight is None:
            return
        batch, ticket = inflight
        has_new = self.signal.resolve(ticket)
        with self._mu:
            for (p, ci, cov), new in zip(batch, has_new):
                if new:
                    self.triage_q.append(TriageItem(
                        prog=M.clone_prog(p), call_index=ci, cover=cov))

    # -- triage (ref fuzzer.go:377-454) ------------------------------------

    def _triage_new(self, call_id: int, cover: np.ndarray) -> np.ndarray:
        """cover − corpus_cover[call] − flakes[call] (ref fuzzer.go:384)."""
        if self.signal is not None:
            return self.signal.triage_new(call_id, cover)
        with self._mu:
            return sets.difference(
                sets.difference(cover, self.corpus_cover[call_id]),
                self.flakes[call_id])

    def _add_flakes(self, call_id: int, pcs: np.ndarray) -> None:
        if self.signal is not None:
            self.signal.add_flakes(call_id, pcs)
            return
        with self._mu:
            self.flakes[call_id] = sets.union(self.flakes[call_id], pcs)

    def triage(self, env: ipc.Env, item: TriageItem, rand: P.Rand,
               pid: int) -> None:
        call_id = item.prog.calls[item.call_index].meta.id
        new_cover = self._triage_new(call_id, item.cover)
        if len(new_cover) == 0 and not item.from_candidate:
            return
        # one trace per admission attempt: hops accumulate here
        # (re-exec, minimize), ride the NewInput params, and finish
        # manager-side (coalescer queue + device dispatch)
        span = self.tracer.new_trace(origin=self.name)
        t_triage = time.monotonic()
        # 3× re-execution: intersect stable cover, accumulate flakes
        min_cover = item.cover
        for _ in range(3):
            res = self.execute(env, item.prog, "exec triage", pid)
            if res is None:
                return
            per = res.per_call(len(item.prog.calls))
            got = per[item.call_index]
            if got is None or not len(got.cover):
                return  # didn't reproduce at all
            cov = sets.canonicalize(got.cover)
            self._add_flakes(call_id,
                             sets.symmetric_difference(min_cover, cov))
            min_cover = sets.intersection(min_cover, cov)
        stable_new = self._triage_new(call_id, min_cover)
        if len(stable_new) == 0 and not item.from_candidate:
            return

        if not item.minimized:
            item.prog, item.call_index = self.minimize_input(
                env, item, stable_new, pid)

        data = P.serialize(item.prog)
        with self._mu:
            h = __import__("hashlib").sha1(data).digest()
            if h in self.corpus_hashes:
                return
            self.corpus_hashes.add(h)
            self.corpus.append(item.prog)
            cid = item.prog.calls[item.call_index].meta.id
            if self.signal is None:
                self.corpus_cover[cid] = sets.union(self.corpus_cover[cid],
                                                    min_cover)
            else:
                # the device row records its corpus index so the
                # weighted corpus-row sampler maps back to the right
                # program even after chunked/full-matrix admissions
                self.signal.merge_corpus(cid, min_cover,
                                         corpus_index=len(self.corpus) - 1)
        if self.synthdev is not None:
            # synth-table growth (the host fix-up → append loop):
            # triaged programs that satisfy the segment contract join
            # the device corpus; the rest stay host-side
            self.synthdev.add_program(item.prog)
        self._stat_counters["new inputs"].inc()
        span.add_hop("fuzzer:triage+minimize", time.monotonic() - t_triage)
        if self._shed_active():
            # overloaded manager asked for backpressure: local-only
            # triage — the input is already in the local corpus, and
            # skipping the RPC is exactly the relief it needs
            return
        try:
            r = self.client.call("Manager.NewInput", {
                "name": self.name,
                "call": item.prog.calls[item.call_index].meta.name,
                "prog": rpc.b64(data),
                "call_index": item.call_index,
                "cover": [int(x) for x in min_cover],
            }, span=span)
        except (rpc.RpcError, OSError, ConnectionError) as e:
            # the client already retried with backoff; a manager still
            # down must not kill this proc loop — the input stays in
            # the local corpus and fuzzing continues
            self._c_rpc_failures.inc()
            log.logf(0, "NewInput delivery failed after retries: %s", e)
            return
        self._note_delivery_reply(r)

    def _shed_active(self) -> bool:
        """True while inside a shed backoff window (delivery skipped,
        counted local-only)."""
        if time.monotonic() < self._shed_until:
            self._c_local_only.inc()
            return True
        return False

    def _note_delivery_reply(self, r) -> None:
        """Fold one NewInput reply into the backpressure state: a
        "shed" reply opens a doubling local-only backoff window (the
        manager is overloaded — re-sending into the storm is the one
        thing that cannot help); a clean ack resets the backoff."""
        if isinstance(r, dict) and r.get("shed"):
            self._c_shed_replies.inc()
            self._shed_until = time.monotonic() + self._shed_backoff
            self._shed_backoff = min(self._shed_backoff * 2.0, 30.0)
            log.logf(1, "manager shed NewInput; local-only triage for "
                     "%.1fs", self._shed_until - time.monotonic())
        else:
            self._shed_backoff = 1.0

    def minimize_input(self, env: ipc.Env, item: TriageItem,
                       stable_new: np.ndarray, pid: int
                       ) -> tuple[M.Prog, int]:
        def pred(q: M.Prog, ci: int) -> bool:
            res = self.execute(env, q, "exec minimize", pid)
            if res is None:
                return False
            got = res.per_call(len(q.calls))[ci]
            if got is None:
                return False
            cov = sets.canonicalize(got.cover)
            return len(sets.difference(stable_new, cov)) == 0

        return P.minimize(item.prog, item.call_index, pred)

    # -- proc loop (ref fuzzer.go:174-232) ---------------------------------

    def proc_loop(self, pid: int) -> None:
        try:
            self._proc_loop(pid)
        except Exception as e:  # a dead proc must be visible, not silent
            log.logf(0, "fuzzer proc %d died: %r", pid, e)
            raise

    def _proc_loop(self, pid: int) -> None:
        rand = P.Rand(np.random.default_rng(self.seed * 4096 + pid))
        if self.signal is not None:
            # device PRNG feeds gen/mutation draws through the decision
            # stream's pre-drawn entropy slabs: ~8k decisions per pull,
            # refilled by the prefetcher's fused megakernel dispatch
            # (SURVEY §7 batching economics) — the pool auto-refills
            # mid-draw, so no per-iteration exhausted() polling
            rand.attach_source(self.ct.take_entropy, 1 << 13)
        # zero-copy ingest: the executor writes raw PC slabs into a
        # pinned ring; the proc loop's per-exec host work collapses to
        # one watermark note — translation, packing and diffing all
        # ride fused device dispatches (narrow-bitmap configs only:
        # the word-block-sparse path needs host-computed blocks)
        use_ring = (self.signal is not None
                    and getattr(self.signal, "_slab_hot_path", False))
        use_synth = self.synthdev is not None
        env = ipc.Env(flags=self.flags, pid=pid, ring=use_ring,
                      prog_ring=use_synth)
        ingest = None
        if use_ring and env.ring is not None:
            ingest = _RingIngest(self, env)
            self._ingests[id(env)] = ingest
        synth_stream = None
        if use_synth:
            from syzkaller_tpu.fuzzer.synth import SynthStream
            synth_stream = SynthStream(
                self.synthdev,
                ring_writer=getattr(env, "prog_writer", None))
        gate = self.gate
        try:
            while not self._stop:
                item = None
                candidate = None
                with self._mu:
                    if self.triage_q:
                        item = self.triage_q.popleft()
                    elif self.candidate_q:
                        candidate = self.candidate_q.popleft()
                if item is not None:
                    with gate.section():
                        self.triage(env, item, rand, pid)
                    if ingest is not None:
                        ingest.maybe_flush()   # keep draining mid-triage
                    continue
                if candidate is not None:
                    self.run_candidate(env, candidate, rand, pid)
                    if ingest is not None:
                        ingest.maybe_flush()
                    continue
                if synth_stream is not None and self.campaign is None:
                    # the device-resident exec pipeline: program
                    # assembly happened on device (synth_block), the
                    # slab is already in the program ring, covers come
                    # back through the PC ring — O(1) host dispatches
                    # per exec in BOTH directions.  An underrun (no
                    # templates yet / dispatch failure) falls through
                    # to the host generator below, counted.
                    entry = synth_stream.next_program()
                    if entry is not None:
                        with gate.section():
                            self.execute_synth(env, entry, pid)
                        if ingest is not None:
                            ingest.maybe_flush()
                        continue
                with self._mu:
                    corpus = list(self.corpus)
                    choice = (self.device_choices.popleft()
                              if self.device_choices else None)
                if corpus and not rand.one_of(10):
                    # device mode: which program to mutate is a batched
                    # popcount-weighted categorical over the corpus
                    # signal matrix (BASELINE config #3); host mode:
                    # uniform pick (ref fuzzer.go:224)
                    row = self._pick_corpus_row(len(corpus), rand)
                    p = M.clone_prog(corpus[row])
                    # under a campaign with a protocol machine, the
                    # sequence mutator keeps protocol order (extend /
                    # repair / trim); flat mutation otherwise
                    if not self._campaign_mutate(p, rand, corpus):
                        P.mutate(p, rand, self.table, PROG_NCALLS,
                                 self.ct, corpus)
                    stat = "exec fuzz"
                else:
                    p = self._campaign_generate(rand)
                    if p is None:
                        p = self.generate_seeded(rand, choice)
                    stat = "exec gen"
                with gate.section():
                    res = self.execute(env, p, stat, pid,
                                       ring_prog=p if ingest else None)
                if ingest is not None:
                    ingest.maybe_flush()
                elif res is not None:
                    self.check_new_signal(p, res)
        finally:
            if ingest is not None:
                try:
                    ingest.maybe_flush(force=True)
                finally:
                    self._ingests.pop(id(env), None)
            env.close()

    def _pick_corpus_row(self, ncorpus: int, rand: P.Rand) -> int:
        """Corpus pick for mutation: the decision stream's pre-drawn
        signal-weighted rows (a deque pop, zero dispatches) with the
        legacy cached batched sampler behind it and a uniform host
        fallback at the bottom.  The legacy refill draw is a device
        round trip, so it runs OUTSIDE self._mu — holding the
        proc-shared mutex across it would stall every other proc thread
        for the tunnel latency (syz-vet lock pass); a concurrent
        double-refill just buffers extra draws."""
        if self.signal is not None:
            dev_row = self.ct.next_corpus_row() \
                if hasattr(self.ct, "next_corpus_row") else None
            if dev_row is not None:
                idx = self.signal.row_to_corpus(int(dev_row))
                if idx is not None and idx < ncorpus:
                    return idx
            with self._mu:
                if self._corpus_rows:
                    row = self._corpus_rows.popleft()
                    if row < ncorpus:
                        return row
                    return rand.intn(ncorpus)
            try:
                rows = self.signal.sample_corpus_indices(256)
            except Exception:
                rows = []
            if len(rows):
                with self._mu:
                    self._corpus_rows.extend(int(x) for x in rows)
                    if self._corpus_rows:
                        row = self._corpus_rows.popleft()
                        if row < ncorpus:
                            return row
        return rand.intn(ncorpus)

    def generate_seeded(self, rand: P.Rand, choice: "int | None") -> M.Prog:
        """Generation; a device-drawn first call (from Poll) biases what
        the program explores — the manager's TPU choice table in action."""
        p = P.generate(rand, self.table, PROG_NCALLS, self.ct)
        if choice is not None and choice in set(self.enabled_ids):
            state = P.State(self.table)
            for c in p.calls:
                state.analyze_call(c)
            gen = P.Gen(rand, state, self.table, self.ct)
            try:
                p.calls.extend(gen.generate_particular_call(
                    self.table.calls[choice]))
                while len(p.calls) > PROG_NCALLS:
                    M.remove_call(p, 0)
            except Exception:
                pass
        return p

    def run_candidate(self, env: ipc.Env, cand: tuple[bytes, bool],
                      rand: P.Rand, pid: int) -> None:
        data, minimized = cand
        try:
            p = P.deserialize(data, self.table)
        except P.DeserializeError:
            return
        res = self.execute(env, p, "exec candidate", pid)
        if res is None:
            return
        if self.signal is not None:
            calls = [c for c in res.calls
                     if c.index < len(p.calls) and len(c.cover)]
            for lo in range(0, len(calls), self.signal.B):
                chunk = calls[lo: lo + self.signal.B]
                has_new = self.signal.check_batch(
                    [(p.calls[c.index].meta.id, c.cover) for c in chunk])
                for c, new in zip(chunk, has_new):
                    if new:
                        self.triage_q.append(TriageItem(
                            prog=M.clone_prog(p), call_index=c.index,
                            cover=sets.canonicalize(c.cover),
                            from_candidate=True, minimized=minimized))
            return
        for c in res.calls:
            if c.index < len(p.calls) and len(c.cover):
                call_id = p.calls[c.index].meta.id
                with self._mu:
                    diff = self._diff_max(call_id, c.cover)
                if len(diff):
                    with self._mu:
                        self._merge_max(call_id, c.cover)
                    self.triage_q.append(TriageItem(
                        prog=M.clone_prog(p), call_index=c.index,
                        cover=sets.canonicalize(c.cover),
                        from_candidate=True, minimized=minimized))

    # -- leak checking (ref fuzzer.go:554-625) -----------------------------

    def leak_scan(self) -> None:
        try:
            with open("/sys/kernel/debug/kmemleak", "r+b", buffering=0) as f:
                f.write(b"scan")
                time.sleep(1)
                f.write(b"scan")
                out = f.read(1 << 20)
                if out and b"unreferenced object" in out:
                    sys.stdout.write(out.decode(errors="replace"))
                    sys.stdout.flush()
                f.write(b"clear")
        except OSError:
            pass

    # -- poll loop (ref fuzzer.go:235-305) ---------------------------------

    def poll_once(self) -> None:
        # periodic flush so low-throughput runs don't strand signal in
        # the pending buffer past the batch boundary
        self.flush_signal(force=True)
        # ship counter DELTAS under the legacy wire keys (ref
        # fuzzer.go:246-252's grab-and-reset, now a drain watermark)
        stats = {k: c.drain() for k, c in self._stat_counters.items()}
        if self.signal is not None and self.signal.tstats is not None:
            # the fuzzer-side device stat vector flows to the manager's
            # stat plane as Poll deltas too (one small readback per
            # poll — cadence-bound, not per-exec)
            ds = self.signal.tstats
            vals = ds.values()
            if self._ts_shipped is None:
                self._ts_shipped = np.zeros_like(vals)
            delta, self._ts_shipped = vals - self._ts_shipped, vals
            for key, wire in (("dense_batches", "cover dense dispatches"),
                              ("sparse_batches", "cover sparse dispatches"),
                              ("sparse_fallback", "cover sparse fallbacks"),
                              ("synth_batches", "synth dispatches"),
                              ("synth_programs", "synth programs"),
                              ("synth_slabs", "synth ring slabs"),
                              ("synth_underrun", "synth underruns")):
                d = int(delta[ds.slot(key)])
                if d:
                    stats[wire] = d
        with self._mu:
            need = len(self.candidate_q) == 0
            tcov = self.transition_cov
        if tcov is not None:
            # protocol-transition coverage rides the legacy stat wire
            # as deltas (the manager's StatsView sums across VMs)
            cov = tcov.popcount()
            if cov > self._tcov_shipped:
                stats["campaign transitions"] = cov - self._tcov_shipped
                self._tcov_shipped = cov
        r = self.client.call("Manager.Poll", {
            "name": self.name, "stats": stats, "need_candidates": need},
            span=self.tracer.new_trace(origin=self.name))
        for cp in r.get("candidates", []):
            self.candidate_q.append((rpc.unb64(cp["prog"]),
                                     bool(cp.get("minimized"))))
        for inp in r.get("new_inputs", []):
            self.add_input(inp)
        # campaign rotation rides the Poll response: applying the same
        # name is a no-op, a new one swaps the overlay epoch-style
        self._apply_campaign(r.get("campaign"))
        choices = r.get("choices") or []
        with self._mu:
            self.device_choices.extend(int(x) for x in choices)

    def add_input(self, inp: dict) -> None:
        """Input from another fuzzer via the manager (ref :344-375)."""
        try:
            p = P.deserialize(rpc.unb64(inp["prog"]), self.table)
        except P.DeserializeError:
            return
        ci = int(inp.get("call_index", 0))
        if ci >= len(p.calls):
            return
        call_id = p.calls[ci].meta.id
        cover = sets.canonicalize(np.array(inp.get("cover", []), np.uint32))
        if self.signal is not None:
            if len(self.signal.triage_new(call_id, cover)) == 0:
                return
            data = P.serialize(p)
            h = __import__("hashlib").sha1(data).digest()
            with self._mu:
                if h in self.corpus_hashes:
                    return
                self.corpus_hashes.add(h)
                self.corpus.append(p)
                self.signal.merge_corpus(call_id, cover,
                                         corpus_index=len(self.corpus) - 1)
            self.signal.merge_max(call_id, cover)
            return
        with self._mu:
            diff = sets.difference(cover, self.corpus_cover[call_id])
            if len(diff) == 0:
                return
            data = P.serialize(p)
            h = __import__("hashlib").sha1(data).digest()
            if h in self.corpus_hashes:
                return
            self.corpus_hashes.add(h)
            self.corpus.append(p)
            self.corpus_cover[call_id] = sets.union(
                self.corpus_cover[call_id], cover)
            self._merge_max(call_id, cover)

    def run(self, duration: "float | None" = None) -> None:
        self.connect()
        threads = [threading.Thread(target=self.proc_loop, args=(pid,),
                                    daemon=True)
                   for pid in range(self.procs)]
        for t in threads:
            t.start()
        deadline = time.time() + duration if duration else None
        try:
            while not self._stop:
                if deadline and time.time() > deadline:
                    break
                time.sleep(3.0)
                try:
                    self.poll_once()
                except (rpc.RpcError, OSError) as e:
                    log.logf(0, "poll failed: %s", e)
        finally:
            self._stop = True
            leaked = 0
            for t in threads:
                # join with a bound, but don't silently abandon a
                # wedged proc thread — log + count the leak so fleet
                # health shows it instead of a quiet fd/memory drip
                t.join(timeout=5.0)
                if t.is_alive():
                    leaked += 1
            if leaked:
                self.registry.counter(
                    "syz_thread_leak_total",
                    "shutdown joins that abandoned a wedged thread",
                    labels=("thread",)).labels(thread="proc-loop").inc(
                        leaked)
                log.logf(0, "shutdown leaked %d wedged proc thread(s)",
                         leaked)
            self.flush_signal(force=True)
            if self.ct is not None and hasattr(self.ct, "stop"):
                self.ct.stop()   # decision-stream prefetcher (idempotent)

    def stop(self) -> None:
        self._stop = True


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-name", default="fuzzer")
    ap.add_argument("-manager", required=True)
    ap.add_argument("-procs", type=int, default=1)
    ap.add_argument("-descriptions", default="all")
    ap.add_argument("-output", default="stdout",
                    choices=["none", "stdout"])
    ap.add_argument("-threaded", action="store_true")
    ap.add_argument("-collide", action="store_true")
    ap.add_argument("-real-cover", action="store_true")
    ap.add_argument("-sandbox", default="none",
                    choices=["none", "setuid", "namespace"])
    ap.add_argument("-leak", action="store_true")
    ap.add_argument("-seed", type=int, default=0)
    ap.add_argument("-device", action="store_true",
                    help="run signal diffs/sampling on the JAX device")
    ap.add_argument("-synth", action="store_true",
                    help="device-resident program synthesis: assemble "
                         "exec bytecode on device, feed the executor "
                         "through the program slab ring (needs -device)")
    ap.add_argument("-npcs", type=int, default=1 << 16)
    ap.add_argument("-flush-batch", type=int, default=32, dest="flush_batch")
    ap.add_argument("-corpus-cap", type=int, default=1 << 14,
                    dest="corpus_cap")
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)
    log.set_verbosity(args.v)

    flags = ipc.FLAG_COVER | ipc.FLAG_DEDUP_COVER
    if not args.real_cover:
        flags |= ipc.FLAG_FAKE_COVER
    if args.threaded:
        flags |= ipc.FLAG_THREADED
    if args.collide:
        flags |= ipc.FLAG_COLLIDE
    if args.sandbox == "setuid":
        flags |= ipc.FLAG_SANDBOX_SETUID
    elif args.sandbox == "namespace":
        flags |= ipc.FLAG_SANDBOX_NAMESPACE

    f = Fuzzer(name=args.name, manager_addr=args.manager, procs=args.procs,
               descriptions=args.descriptions, flags=flags,
               output_mode=args.output, leak=args.leak, seed=args.seed,
               use_device=args.device, npcs=args.npcs,
               flush_batch=args.flush_batch, corpus_cap=args.corpus_cap,
               synth=args.synth)

    def on_sigint(sig, frame):
        # GCE preemption path (ref fuzzer.go:102-109, vm/vm.go:118-120)
        sys.stdout.write("PREEMPTED\n")
        sys.stdout.flush()
        f.stop()

    signal.signal(signal.SIGINT, on_sigint)
    f.run()


if __name__ == "__main__":
    main()
