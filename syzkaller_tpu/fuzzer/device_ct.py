"""Device-backed ChoiceTable adapter.

Bridges the per-decision interface the program generator wants
(choose(rand, prev) — ref prog/prio.go:230) to batched device sampling:
one jit call draws a whole batch of decisions conditioned on the same
previous call, cached and handed out one by one. This is the
"amortize the device round-trip" pattern from SURVEY §7.
"""

from __future__ import annotations

import threading
from collections import deque

import numpy as np


class DeviceChoiceTable:
    """Thread-safe: stress/fuzzer proc threads share one instance."""

    def __init__(self, engine, per_row: int = 64):
        self.engine = engine
        self.per_row = per_row
        self._cache: dict[int, deque] = {}
        self._mu = threading.Lock()

    def _refill_all(self) -> None:
        """ONE device call draws `per_row` decisions for every possible
        previous call (plus the no-context row): (ncalls+1)*per_row
        categorical draws, amortizing tunnel latency over thousands of
        choose() calls.  Rows that still hold unused draws keep them
        (topped up, never discarded) so hot rows draining doesn't throw
        away the cold rows' cache."""
        n = self.engine.ncalls
        prev = np.repeat(np.arange(-1, n, dtype=np.int32), self.per_row)
        draws = self.engine.sample_next_calls(prev)
        for row in range(-1, n):
            lo = (row + 1) * self.per_row
            q = self._cache.setdefault(row, deque())
            need = self.per_row - len(q)
            if need > 0:
                q.extend(int(x) for x in draws[lo: lo + need])

    def choose(self, r, prev_call_id: int = -1) -> int:
        with self._mu:
            q = self._cache.get(prev_call_id)
            if not q:
                self._refill_all()
                q = self._cache[prev_call_id]
            return q.popleft()

    def invalidate(self) -> None:
        """Drop cached draws (call after the priority matrix changes)."""
        with self._mu:
            self._cache.clear()
