"""Device-backed decision stream: the fused async sampling plane.

The old DeviceChoiceTable blocked every choose() caller behind a
synchronous refill dispatch under one lock and drew nothing but call
choices — corpus-row picks and Rand entropy refills were separate
dispatches on separate paths.  This module replaces all three with ONE
decision-stream megakernel (cover/engine.py `decision_block`) consumed
through a double-buffered async prefetcher:

  * each block carries per-context choice draws for EVERY prev row, a
    hot-row extension, a batch of signal-weighted corpus-row picks, and
    a slab of raw uint64 entropy — the "amortize the device round-trip"
    pattern from SURVEY §7 taken to its fixed point;
  * a background thread dispatches block N+1 while consumers drain
    block N (JAX async dispatch hides the tunnel latency), so choose()
    is a deque pop, never a device wait;
  * per-row ring targets adapt to telemetry-observed drain rates: hot
    rows earn slots in the block's hot-prev composition (a cached
    device operand, re-uploaded only when the allocation shifts —
    steady-state refills move zero host operands in);
  * invalidate() (on priority-matrix / enabled-set updates) bumps an
    epoch that discards in-flight stale blocks and kicks an EAGER
    background redraw, instead of making the next choose() eat the full
    cold-refill latency;
  * a ring miss (underrun) falls back to one fixed-shape direct draw
    outside every lock — consumers never block on the prefetcher, so an
    invalidation storm cannot deadlock the draw path.

Lock discipline (syz-vet): `_mu` guards ring state only — device
dispatches, host syncs (np.asarray) and the prefetcher condition are
always taken OUTSIDE it, and the two locks are never nested.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from syzkaller_tpu import san as _san
from syzkaller_tpu.utils import log
from syzkaller_tpu.utils.shapes import pow2_bucket


class DecisionStream:
    """Thread-safe decision-block consumer plane over a CoverageEngine.

    Consumers: `choose()` / `take()` (choice draws per prev context),
    `next_corpus_row()` (signal-weighted mutation picks), and
    `take_entropy()` (uint64 slabs for prog.rand.Rand.refill).
    """

    # fixed-shape direct-draw batch for ring underruns (one compiled
    # sampling kernel, reused by every miss)
    UNDERRUN_BATCH = 64

    def __init__(self, engine, per_row: int = 64, hot_slots: int = 1024,
                 corpus_rows: int = 256, entropy_words: int = 1 << 13,
                 ring_mult: int = 4, adapt_every: int = 4,
                 warm_after: int = 2, telemetry=None,
                 autostart: bool = True):
        self.engine = engine
        self.tstats = telemetry if telemetry is not None else engine.tstats
        # dispatch shapes live in a pow2-bucketed closed set: the
        # megakernel compiles once per (per_row, H, n_rows, n_entropy)
        # and ring-size adaptation only changes OPERAND CONTENTS
        self.per_row = pow2_bucket(per_row, 8, 1024)
        self.hot_slots = pow2_bucket(hot_slots, 64, 1 << 14)
        self.n_rows = pow2_bucket(corpus_rows, 32, 1 << 12)
        self.n_entropy = pow2_bucket(entropy_words, 1024, 1 << 16)
        self.ring_mult = ring_mult
        self.adapt_every = adapt_every
        # the prefetcher engages only after this many direct fallback
        # dispatches: cold one-shot consumers (a single Poll, unit
        # tests) keep paying the cheap direct path instead of compiling
        # the megakernel for draws nobody will drain
        self.warm_after = warm_after
        self._R = engine.ncalls + 1          # prev contexts incl. -1
        self.draws_per_block = self._R * self.per_row + self.hot_slots

        # ring state — guarded by _mu, never held across device work
        self._mu = threading.Lock()
        self._rings: dict[int, deque] = {}
        self._crows: deque = deque()
        self._ent: deque = deque()           # np.uint64 slabs
        self._ent_len = 0
        self._inv_total = 0
        self._epoch = 0
        self._drained = np.zeros((self._R,), np.int64)
        self._targets = np.full((self._R,), self.per_row, np.int64)
        self._targets[0] += self.hot_slots   # initial hot composition: -1
        self._hot_host = np.full((self.hot_slots,), -1, np.int32)
        self._hot_dev = engine.put_replicated(self._hot_host)
        # campaign overlay (cover.engine.DeviceOverlay | None): cached
        # fixed-shape device operands the megakernel consumes — a swap
        # changes operand contents only, so it rides the invalidate()
        # epoch path and compiles nothing warm
        self._overlay = None
        self._warmed = False
        self._starved = False
        # health counters (host-side; the device stat vector carries the
        # exposition series)
        self.stat_served = 0
        self.stat_underruns = 0
        self.stat_blocks = 0
        self.stat_discarded = 0
        self._direct_dispatches = 0
        self._last_adapt = 0

        # prefetcher control — its own condition lock; _mu and _cv are
        # NEVER nested (no lock-order edge either way)
        self._cv = threading.Condition(threading.Lock())
        self._kicked = False
        self._stop = False
        self._inflight = None
        if _san.armed():
            # syz-san: _mu must never be held across device work — the
            # lockset audit turns a violation into a hard error
            _san.audit_lock(self, "_mu", "decision_stream._mu")
        self._thread: "threading.Thread | None" = None
        if autostart:
            self._thread = threading.Thread(
                target=self._loop, name="decision-stream", daemon=True)
            self._thread.start()

    # -- consumer side -----------------------------------------------------

    def choose(self, r=None, prev_call_id: int = -1) -> int:
        """One ChoiceTable decision conditioned on prev_call_id (-1 = no
        context).  Fast path is a deque pop; a miss falls back to one
        fixed-shape direct draw outside every lock."""
        kick = False
        v = None
        with self._mu:
            q = self._rings.get(prev_call_id)
            if q:
                v = q.popleft()
                self._inv_total -= 1
                self._drained[prev_call_id + 1] += 1
                self.stat_served += 1
                if len(q) * 4 < self._targets[prev_call_id + 1]:
                    self._starved = True
                    kick = self._warmed
        if v is not None:
            if kick:
                self._kick()
            return v
        return self._underrun_draw(prev_call_id, 1)[0]

    def take(self, prev_call_id: int, n: int) -> list[int]:
        """Exactly n decisions for one context (the manager's Poll
        top-up shape): ring first, direct-draw remainder."""
        out: list[int] = []
        kick = False
        with self._mu:
            q = self._rings.get(prev_call_id)
            while q and len(out) < n:
                out.append(q.popleft())
            got = len(out)
            self._inv_total -= got
            self._drained[prev_call_id + 1] += got
            self.stat_served += got
            if got and q is not None and \
                    len(q) * 4 < self._targets[prev_call_id + 1]:
                self._starved = True
                kick = self._warmed
        if kick:
            self._kick()
        short = n - len(out)
        if short > 0:
            out += self._underrun_draw(prev_call_id, short)
        return out

    def next_corpus_row(self) -> "int | None":
        """One pre-drawn signal-weighted corpus row, or None when the
        ring is dry (caller falls back to its legacy sampler)."""
        kick = False
        with self._mu:
            v = self._crows.popleft() if self._crows else None
            if len(self._crows) * 4 < self.n_rows:
                if v is None:
                    self._direct_dispatches += 1
                    if self._direct_dispatches >= self.warm_after:
                        self._warmed = True
                kick = self._warmed
        if kick:
            self._kick()
        return v

    def take_entropy(self, n: int) -> np.ndarray:
        """n uint64 words for Rand.refill — pre-drawn slabs first, one
        bucketed direct draw for any remainder."""
        chunks: list[np.ndarray] = []
        got = 0
        kick = False
        with self._mu:
            while self._ent and got < n:
                a = self._ent.popleft()
                if len(a) > n - got:
                    self._ent.appendleft(a[n - got:])
                    a = a[: n - got]
                chunks.append(a)
                got += len(a)
            self._ent_len -= got
            if self._ent_len < self.n_entropy // 2:
                kick = self._warmed
        if kick:
            self._kick()
        if got < n:
            nb = pow2_bucket(n - got, 1024, 1 << 16)
            w = self.engine.random_words(nb)
            chunks.append(w[: n - got])
            self._note_direct()
        if len(chunks) == 1:
            return chunks[0]
        if not chunks:
            return np.zeros((0,), np.uint64)
        return np.concatenate(chunks)

    def _underrun_draw(self, prev: int, want: int) -> list[int]:
        """Ring miss: one fixed-shape sampling dispatch OUTSIDE every
        lock (blocking a choose() caller on the prefetcher could
        deadlock an invalidation storm; a direct draw cannot)."""
        nb = pow2_bucket(max(want, self.UNDERRUN_BATCH),
                         self.UNDERRUN_BATCH, 1024)
        with self._mu:
            epoch = self._epoch
            overlay = self._overlay
        draws = self.engine.sample_next_calls(
            np.full((nb,), prev, np.int32), overlay=overlay)
        if self.tstats is not None:
            self.tstats.inc("ring_underrun")
        with self._mu:
            self.stat_underruns += 1
            self.stat_served += want
            self._drained[prev + 1] += want
            if epoch == self._epoch:
                # bank the leftover draws — they were paid for; skip
                # when an invalidate() raced the dispatch (banking
                # would leave stale draws in the ring after it returned)
                q = self._rings.setdefault(prev, deque())
                leftovers = draws[want:]
                q.extend(int(x) for x in leftovers)
                self._inv_total += len(leftovers)
        self._note_direct()
        return [int(x) for x in draws[:want]]

    def _note_direct(self) -> None:
        kick = False
        with self._mu:
            self._direct_dispatches += 1
            if self._direct_dispatches >= self.warm_after:
                self._warmed = True
                kick = True
        if kick:
            self._kick()

    # -- invalidation ------------------------------------------------------

    def invalidate(self) -> None:
        """Call after a priority-matrix or enabled-set update: every
        cached choice draw is dropped, any in-flight block is marked
        stale (epoch bump — it is discarded at publish), and the
        prefetcher is kicked for an EAGER background redraw so the next
        choose() finds a warm ring instead of paying the cold-refill
        latency.  Corpus-row and entropy rings are unaffected (their
        distributions do not depend on the priority matrix)."""
        with self._mu:
            self._epoch += 1
            for q in self._rings.values():
                q.clear()
            self._inv_total = 0
            warmed = self._warmed
        if warmed:
            self._kick()

    def set_overlay(self, overlay) -> None:
        """Retarget the stream at a campaign: install the overlay's
        cached device operands and ride the SAME epoch path as a
        priority update — stale rings drop, in-flight blocks discard at
        publish, and the eager background redraw repopulates from the
        steered distribution.  The overlay operands are fixed (C,)
        shapes, so a warm rotate-through-campaigns storm compiles
        nothing (CompileCounter-pinned in tests).  None restores the
        flat (neutral) overlay."""
        with self._mu:
            if overlay is self._overlay:
                return
            self._overlay = overlay
        self.invalidate()

    def overlay(self):
        with self._mu:
            return self._overlay

    def rebind(self) -> None:
        """Re-home cached device operands after a backend swap (the
        resilience plane's failover/promotion): the hot-prev
        composition is re-uploaded through the CURRENT engine (the old
        buffers may live on a dead backend) and every pre-drawn block
        is invalidated — the eager redraw repopulates from the new
        backend.  put_replicated runs outside _mu (device work under a
        lock is a syz-vet P0)."""
        with self._mu:
            hot = self._hot_host
        dev = self.engine.put_replicated(hot)
        with self._mu:
            self._hot_dev = dev
        self.invalidate()

    def stop(self) -> bool:
        """Stop the prefetcher; idempotent under double-close (the
        manager's stop path and a failover teardown may both call it).
        Returns False when the thread failed to join (wedged — the
        caller logs/counts the leak)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        t, self._thread = self._thread, None
        if t is None:
            return True
        t.join(timeout=10.0)
        if t.is_alive():
            log.logf(0, "decision-stream prefetcher failed to stop "
                     "(thread leaked)")
            return False
        return True

    # -- prefetcher --------------------------------------------------------

    def _kick(self) -> None:
        with self._cv:
            self._kicked = True
            self._cv.notify()

    def _demand(self) -> bool:
        with self._mu:
            if self._starved:
                self._starved = False
                return True
            total_target = int(self._targets.sum())
            if self._inv_total < total_target // 2:
                return True
            if len(self._crows) < self.n_rows // 2:
                return True
            if self._ent_len < self.n_entropy // 2:
                return True
        return False

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._kicked and not self._stop:
                    self._cv.wait(timeout=0.5)
                if self._stop:
                    return
                self._kicked = False
            try:
                self._cycle()
            except Exception as e:  # a dead prefetcher must be visible
                log.logf(0, "decision-stream prefetch error: %r", e)
                time.sleep(0.05)

    def _cycle(self) -> None:
        """Double-buffered refill: dispatch block N+1, THEN resolve and
        publish block N — the host transfer of one block overlaps the
        device compute of the next."""
        while not self._stop and self._demand():
            self._maybe_adapt()
            with self._mu:
                epoch = self._epoch
                hot_host, hot_dev = self._hot_host, self._hot_dev
                overlay = self._overlay
            blk = self.engine.decision_block(
                hot_dev, self.per_row, self.n_rows, self.n_entropy,
                overlay=overlay)
            tok = _san.stamp(hot_host, "decision hot_host") \
                if _san.armed() else None
            prev, self._inflight = self._inflight, (
                epoch, time.monotonic(), hot_host, blk, tok)
            self._publish(prev)
        prev, self._inflight = self._inflight, None
        self._publish(prev)

    def _publish(self, inflight) -> None:
        if inflight is None:
            return
        epoch, t0, hot_host, blk, tok = inflight
        # syz-san: the hot composition handed to the dispatch must not
        # have mutated while the block was in flight
        _san.verify(tok)
        # the host syncs — outside every lock
        base = np.asarray(blk.base)
        hot = np.asarray(blk.hot)
        crows = np.asarray(blk.corpus_rows)
        ent = np.asarray(blk.entropy)
        words = (ent[0].astype(np.uint64) << np.uint64(32)) \
            | ent[1].astype(np.uint64)
        if self.tstats is not None:
            self.tstats.observe("block_consume_latency",
                                time.monotonic() - t0)
        with self._mu:
            if epoch != self._epoch:
                self.stat_discarded += 1
                return
            self.stat_blocks += 1
            for row in range(-1, self._R - 1):
                q = self._rings.setdefault(row, deque())
                need = self.ring_mult * int(self._targets[row + 1]) - len(q)
                if need > 0:
                    add = base[row + 1, :need].tolist()
                    q.extend(add)
                    self._inv_total += len(add)
            for p, v in zip(hot_host.tolist(), hot.tolist()):
                q = self._rings.setdefault(p, deque())
                if len(q) < self.ring_mult * int(self._targets[p + 1]):
                    q.append(v)
                    self._inv_total += 1
            if len(self._crows) < 2 * self.n_rows:
                self._crows.extend(crows.tolist())
            if self._ent_len < 2 * self.n_entropy:
                self._ent.append(words)
                self._ent_len += len(words)

    def feed(self, prev_call_id: int, draws, epoch: "int | None" = None
             ) -> int:
        """Bank externally pre-drawn decisions — the fused fuzz tick's
        ride-along choice draws (engine.fuzz_tick /
        DeviceSignal.submit_tick decision_sink) — into one context's
        ring, under the same rules as a prefetched block: ring caps
        (ring_mult × target) are respected and, when the caller
        snapshotted `epoch()` before dispatching the tick, a stale
        epoch discards instead of publishing pre-invalidation draws.
        Returns the number of decisions banked."""
        vals = np.asarray(draws, np.int64).ravel()
        if vals.size == 0:
            return 0
        with self._mu:
            if epoch is not None and epoch != self._epoch:
                self.stat_discarded += 1
                return 0
            q = self._rings.setdefault(prev_call_id, deque())
            room = self.ring_mult * int(self._targets[prev_call_id + 1]) \
                - len(q)
            if room <= 0:
                return 0
            add = vals[:room].tolist()
            q.extend(add)
            self._inv_total += len(add)
            return len(add)

    def epoch(self) -> int:
        """Current invalidation epoch — snapshot before dispatching a
        fused tick whose draws will be feed()-banked."""
        with self._mu:
            return self._epoch

    def _maybe_adapt(self) -> None:
        """Re-split the hot-slot budget by observed drain rates so hot
        rows stop starving: the prev composition (operand CONTENTS, not
        shape) is re-uploaded only when the allocation actually shifts —
        the megakernel never recompiles for an adaptation step."""
        with self._mu:
            if self.stat_blocks - self._last_adapt < self.adapt_every:
                return
            self._last_adapt = self.stat_blocks
            drained = self._drained.copy()
            self._drained[:] = 0
        total = int(drained.sum())
        if total <= 0:
            return
        share = np.floor(drained * (self.hot_slots / total)).astype(np.int64)
        reps = np.repeat(np.arange(-1, self._R - 1, dtype=np.int32), share)
        comp = np.full((self.hot_slots,), -1, np.int32)
        comp[: len(reps)] = reps[: self.hot_slots]
        comp.sort()
        with self._mu:
            unchanged = np.array_equal(comp, self._hot_host)
        if unchanged:
            return
        dev = self.engine.put_replicated(comp)
        cnt = np.bincount(comp.astype(np.int64) + 1, minlength=self._R)
        with self._mu:
            self._hot_host = comp
            self._hot_dev = dev
            self._targets = self.per_row + cnt

    # -- introspection (tests/bench) --------------------------------------

    def refill_once(self) -> None:
        """Synchronous dispatch+publish of one block (tests, warm-up,
        and the bench smoke path); production uses the prefetcher."""
        self._maybe_adapt()
        with self._mu:
            epoch = self._epoch
            hot_host, hot_dev = self._hot_host, self._hot_dev
            overlay = self._overlay
        blk = self.engine.decision_block(
            hot_dev, self.per_row, self.n_rows, self.n_entropy,
            overlay=overlay)
        tok = _san.stamp(hot_host, "decision hot_host") \
            if _san.armed() else None
        self._publish((epoch, time.monotonic(), hot_host, blk, tok))

    def inventory(self) -> int:
        with self._mu:
            return self._inv_total


class DeviceChoiceTable(DecisionStream):
    """Back-compat facade: the per-decision choose(rand, prev) interface
    the program generator consumes (ref prog/prio.go:230), now backed by
    the decision-stream prefetcher instead of a blocking refill-all."""
