"""Device-resident signal backend for the fuzzer's own hot loop.

Round-1 verdict: the CoverageEngine existed but the production fuzzer
still did per-exec signal diffs with numpy sorted sets, touching the
device only through the manager.  This backend puts the engine in the
fuzzer's loop (BASELINE configs #3/#5): per-exec new-signal verdicts are
batched fused device steps, triage membership (corpus-cover minus
flakes, ref syz-fuzzer/fuzzer.go:384-386) and flake accumulation
(:399-416) are device bitmap ops, and corpus admission appends rows to
the device signal matrix.

Zero-copy ingest (the PR-11 plane): the hot path speaks raw SLABS —
(B, K) uint32 windows straight off the executor's pinned PC ring
(ipc/ring.py), with the PcMap sparse→dense translation run ON DEVICE
(a sorted-mirror binary search fused into the update dispatch,
cover/engine.py translate_slab_rows).  Per batch the host does O(1)
work: one dispatch in, one verdict fetch out.  First-sight PCs come
back in a per-row miss mask; `resolve` maps just those rows through
the host PcMap (exact first-seen insertion order, so `export_keys`
and the PR 9 snapshots stay bit-exact), refreshes the device mirror,
and fixes up with one bounded extra dispatch — new-key batches are
rare after warmup, so the steady state is translation-free on the
host.  The legacy cover-list APIs (`submit_batch`, `triage_new`,
`merge_corpus`, `add_flakes`) now slabify and ride the same kernels.

The hot path is pipelined: `submit_slabs` dispatches the device step
without a host sync and returns a ticket; `resolve` fetches the verdict
later, so the ~100ms+ tunnel round-trip overlaps with the next batch's
execution instead of serializing the loop.
"""

from __future__ import annotations

import threading
import time

import numpy as np

import operator

from syzkaller_tpu import san as _san
from syzkaller_tpu.cover import sets
from syzkaller_tpu.fuzzer.pcmap import DeviceKeyMirror, PcMap, _dedup_rows
from syzkaller_tpu.utils import log
from syzkaller_tpu.utils.shapes import pow2_bucket


def _u32cover(c) -> np.ndarray:
    return np.asarray(c, np.uint32).ravel()


def _stamp_slab(win, counts, what: str):
    """syz-san generation stamps for the host buffers a pipelined
    ticket keeps referencing while its dispatch is in flight (None
    when unarmed — the unarmed cost is one branch)."""
    if not _san.armed():
        return None
    return (_san.stamp(win, f"{what} win"),
            _san.stamp(counts, f"{what} counts"))


def _verify_slab(toks) -> None:
    if toks is not None:
        _san.verify(toks[0])
        _san.verify(toks[1])


class DeviceSignal:
    """Raw-PC facade over a CoverageEngine + PcMap (thread-safe)."""

    def __init__(self, ncalls: int, npcs: int = 1 << 16,
                 flush_batch: int = 32, max_pcs: int = 512,
                 corpus_cap: int = 1 << 14, seed: int = 0,
                 telemetry=None):
        from syzkaller_tpu.cover.engine import CoverageEngine

        # wide bitmaps (≥128k PCs) get the word-block-sparse hot step:
        # per-batch device work follows the signal footprint instead of
        # the full width; narrow bitmaps keep the plain dense step
        # (the sparse gather/scatter wouldn't pay for itself)
        sparse_blocks = 512 if npcs >= (1 << 17) else 0
        # telemetry (a telemetry.device.DeviceStats) rides the engine's
        # fused dispatches: dense/sparse/ingest dispatch counts, ring
        # drops, and the exec-latency histogram the fuzzer feeds
        self.tstats = telemetry
        self.engine = CoverageEngine(
            npcs=npcs, ncalls=ncalls, corpus_cap=corpus_cap,
            batch=flush_batch, max_pcs_per_exec=max_pcs, seed=seed,
            max_touched_blocks=sparse_blocks, telemetry=telemetry)
        self.pcmap = PcMap(npcs)
        # the device-resident half of the PcMap: sorted key mirror the
        # ingest kernels binary-search (refreshed incrementally on
        # first-sight insertions, fixed shapes — never a recompile)
        self.mirror = DeviceKeyMirror(self.pcmap,
                                      put=self.engine.put_replicated)
        self.B = flush_batch
        self.K = max_pcs
        self.stat_corpus_full = 0
        self.stat_ingest_dispatches = 0     # fused slab dispatches
        self.stat_ingest_fixups = 0         # host-resolved new-key rows
        # device corpus row -> caller's corpus index (rows are admitted
        # one per program, but the matrix can fill while the host corpus
        # keeps growing, so the identity mapping is not guaranteed)
        self._row2corpus: list[int] = []
        self._row_mu = threading.Lock()
        # tiered corpus hierarchy (corpus.TierManager | None): attached
        # via attach_tiers — over-cap admission then demotes warm
        # instead of dropping, and warm rows promote back on resolve
        self.tiers = None
        # active campaign frontier (cover.engine.SparseView | None):
        # resolve() absorbs each batch's new-signal diffs into it, so
        # per-campaign coverage rides the dispatches the hot loop
        # already pays for.  Plain attribute swap (None = flat).
        self._frontier = None
        # the word-block-sparse engine path computes touched blocks
        # host-side per batch — incompatible with zero-copy ingest, so
        # wide-bitmap configs keep the legacy host-mapped submit path
        self._slab_hot_path = sparse_blocks == 0

    def set_frontier(self, view) -> None:
        """Install the campaign frontier view new signal is attributed
        to from now on (None = stop attributing)."""
        self._frontier = view

    def attach_tiers(self, tiers) -> None:
        """Wire a corpus.TierManager through the engine: admission past
        corpus_cap demotes the lowest-retention rows to the warm store
        (in the fused tick dispatch) instead of falling back unfused,
        and resolve_corpus_rows promotes warm-resident entries back."""
        self.tiers = tiers
        self.engine.attach_tiers(tiers)

    def resolve_corpus_rows(self, corpus_indices) -> np.ndarray:
        """Corpus indices -> hot device rows, promoting warm-resident
        entries first (at most ONE batched segment read + ONE swap
        dispatch); -1 = cold (replay through the persistent corpus)."""
        if self.tiers is None:
            return np.full((len(corpus_indices),), -1, np.int64)
        return self.tiers.resolve_rows(corpus_indices)

    def _record_rows(self, rows, owners) -> None:
        """Bind device corpus rows to caller corpus indices.  The map
        is positional, not append-only: tiered admission replaces row
        CONTENTS in place, so a row index can be rebound."""
        rows = np.asarray(rows, np.int64)
        owners = np.asarray(owners, np.int64)
        with self._row_mu:
            r2c = self._row2corpus
            top = int(rows.max()) + 1
            if top > len(r2c):
                r2c.extend([-1] * (top - len(r2c)))
            for r, o in zip(rows, owners):
                r2c[int(r)] = int(o)
        if self.tiers is not None:
            self.tiers.set_owners(rows, owners)

    # -- mapping helpers ---------------------------------------------------

    def _map_rows(self, covers: "list[np.ndarray]"):
        """Canonicalized covers → fixed-shape (B, K) index rows + mask +
        per-row owner, spreading covers longer than K over several rows
        and padding the row count to a multiple of the flush batch (the
        vectorized pipeline lives in PcMap.map_rows)."""
        return self.pcmap.map_rows(covers, self.K, chunk=True,
                                   pad_rows=self.B)

    def _slabify(self, covers: "list[np.ndarray]"):
        """Covers → one (B, K) uint32 slab window + counts + per-row
        owner (source cover index), the shape the fused translate
        kernels consume.  A cover longer than K spreads over several
        rows of the same owner (the legacy chunk semantics — no PC is
        dropped).  Fully vectorized (one concat + one scatter — the
        per-cover Python loops this replaces were audited hotpath
        remnants); it serves the LEGACY cover-list entry points, the
        hot path hands ring views straight through submit_slabs."""
        covs = tuple(map(_u32cover, covers))
        ncov = len(covs)
        lens = np.fromiter(map(len, covs), np.int64, ncov)
        maxlen = min(int(lens.max()), self.K) if ncov else 1
        K = pow2_bucket(max(maxlen, 8), 8, self.K)
        nch = np.maximum(1, -(-lens // K)) if ncov else \
            np.zeros(0, np.int64)
        rows = int(nch.sum())
        B = pow2_bucket(max(rows, 1), 1, 1 << 16)
        win = np.zeros((B, K), np.uint32)
        counts = np.zeros((B,), np.int32)
        owner = np.full((B,), -1, np.int32)
        if ncov == 0:
            return win, counts, owner
        row_start = np.cumsum(nch) - nch
        rcov = np.repeat(np.arange(ncov), nch)
        rchunk = np.arange(rows) - np.repeat(row_start, nch)
        counts[:rows] = np.clip(lens[rcov] - rchunk * K, 0, K)
        owner[:rows] = rcov
        total = int(lens.sum())
        if total:
            flat = np.concatenate(covs)
            cover_id = np.repeat(np.arange(ncov), lens)
            pos = np.arange(total) - np.repeat(np.cumsum(lens) - lens,
                                               lens)
            r = row_start[cover_id] + pos // K
            c = pos % K
            win[r, c] = flat
        return win, counts, owner

    # -- hot path ----------------------------------------------------------

    def submit_slabs(self, win: np.ndarray, counts: np.ndarray,
                     call_ids: np.ndarray):
        """Dispatch ONE fused translate+diff+merge step for a raw slab
        window ((B, K) uint32 — typically a zero-copy ring view) WITHOUT
        waiting for the result.  Returns an opaque ticket for `resolve`.
        State mutation (the max-cover merge) is sequenced on-device in
        submission order; first-sight PCs are masked out of the update
        and resolved at `resolve` time."""
        res = self.engine.ingest_update_slabs(win, counts, call_ids,
                                              self.mirror)
        self.stat_ingest_dispatches += 1
        return ("slab", res, win, counts, np.asarray(call_ids, np.int32),
                self._frontier, time.monotonic(),
                _stamp_slab(win, counts, "slab"))

    def _resolve_slab(self, ticket) -> np.ndarray:
        _kind, res, win, counts, call_ids, frontier, t0, toks = ticket
        _verify_slab(toks)
        has_new = np.asarray(res.has_new)            # the host sync
        miss = np.asarray(res.miss_rows)
        if miss.any():
            has_new = self._fixup_misses(win, counts, call_ids, miss,
                                         has_new, frontier)
        if frontier is not None:
            frontier.absorb(call_ids, res)
        if self.tstats is not None:
            self.tstats.observe("ingest_translate_latency",
                                time.monotonic() - t0)
        return has_new[: len(counts)]

    def submit_tick(self, win: np.ndarray, counts: np.ndarray,
                    call_ids: np.ndarray, choice_prev=None,
                    corpus_indices=None, decision_sink=None,
                    decision_epoch=None):
        """ONE whole fuzz tick for a slab window: signal diff/merge +
        admission gate/corpus merge + pre-drawn decision draws in a
        single host→device dispatch (engine.fuzz_tick) — the fused
        successor of submit_slabs-then-admission.  Admission results
        (has_new/rows/choices) land synchronously in the returned
        FuzzTickResult; the signal-plane verdict stays a device array
        behind the ticket, preserving the pipelined resolve/absorb
        contract.

        Unlike submit_slabs, first-sight keys are pre-resolved here
        with ONE vectorized mirror.ensure probe (a pure lookup pass in
        steady state) — the admission gate cannot defer misses without
        changing the admitted set.  `corpus_indices` (per slab row)
        feeds the device-row→corpus map for admitted rows;
        `decision_sink` (e.g. DecisionStream.feed bound to a prev
        context) receives the tick's pre-drawn next-call ids; pass
        `decision_epoch` (the stream's epoch(), snapshotted BEFORE this
        call) so a stream invalidation racing the tick discards the
        stale draws instead of banking them (syz-vet
        epoch/feed-missing-epoch).

        Returns (ticket, FuzzTickResult)."""
        win = np.asarray(win)
        counts = np.asarray(counts, np.int32)
        call_ids = np.asarray(call_ids, np.int32)
        live = np.arange(win.shape[1])[None, :] < counts[:, None]
        self.mirror.ensure(win[live])
        if choice_prev is None:
            choice_prev = np.full((self.B,), -1, np.int32)
        res = self.engine.fuzz_tick(win, counts, call_ids,
                                    choice_prev=choice_prev,
                                    mirror=self.mirror)
        self.stat_ingest_dispatches += 1
        if res.rows is not None and len(res.rows):
            owners = (np.full(len(res.rows), -1, np.int64)
                      if corpus_indices is None
                      else np.asarray(corpus_indices)[res.has_new])
            self._record_rows(res.rows, owners)
        elif res.rows is None:
            self.stat_corpus_full += 1
        if decision_sink is not None:
            if decision_epoch is not None:
                decision_sink(res.choices, epoch=decision_epoch)
            else:
                decision_sink(res.choices)
        ticket = ("tick", res, win, counts, call_ids, self._frontier,
                  time.monotonic(), _stamp_slab(win, counts, "tick"))
        return ticket, res

    def _resolve_tick(self, ticket) -> np.ndarray:
        _kind, res, _win, counts, call_ids, frontier, t0, toks = ticket
        _verify_slab(toks)
        has_new = np.asarray(res.sig_has_new)        # the host sync
        if frontier is not None:
            frontier.absorb(call_ids, res.signal_view())
        if self.tstats is not None:
            self.tstats.observe("ingest_translate_latency",
                                time.monotonic() - t0)
        return has_new[: len(counts)]

    def _fixup_misses(self, win, counts, call_ids, miss, has_new,
                      frontier) -> np.ndarray:
        """Host-resolve first-sight keys for the flagged rows (exact
        first-seen insertion order — only missed rows can carry new
        keys, so insertion order over them IS the batch's occurrence
        order) and re-run those rows through one bounded update
        dispatch.  Known-key bits were already merged by the slab
        dispatch; re-merging is idempotent, and the two has_new halves
        OR (a new-key PC is by definition new signal)."""
        rows = np.nonzero(miss)[0]
        K = win.shape[1]
        sub = np.asarray(win)[rows].astype(np.uint64)
        cnts = np.asarray(counts)[rows]
        inmask = np.arange(K)[None, :] < cnts[:, None]
        before = len(self.pcmap)
        # row-major masked flatten preserves occurrence order, so
        # first-seen insertion order (export_keys/snapshots) is exactly
        # the legacy per-row map_rows semantics — vectorized
        vals = self.pcmap.map_flat(sub[inmask])
        added = len(self.pcmap) - before
        idx = np.zeros((len(rows), K), np.int32)
        idx[inmask] = vals
        valid = inmask.copy()
        _dedup_rows(idx, valid)
        if added and self.tstats is not None:
            self.tstats.inc("ingest_new_keys", added)
        self.mirror.refresh()
        B = pow2_bucket(len(rows), 1, 1 << 16)
        pidx = np.zeros((B, win.shape[1]), np.int32)
        pval = np.zeros((B, win.shape[1]), bool)
        pids = np.zeros((B,), np.int32)
        pidx[: len(rows)] = idx[: len(rows)]
        pval[: len(rows)] = valid[: len(rows)]
        pids[: len(rows)] = call_ids[rows]
        fix = self.engine.update_batch_async(pids, pidx, pval)
        self.stat_ingest_dispatches += 1
        self.stat_ingest_fixups += len(rows)
        fix_new = np.asarray(fix.has_new)
        if frontier is not None:
            frontier.absorb(pids, fix)
        out = has_new.copy()
        out[rows] |= fix_new[: len(rows)]
        return out

    def submit_batch(self, entries: "list[tuple[int, np.ndarray]]"):
        """Dispatch one fused device step for up to B (call_id, raw_cover)
        execs WITHOUT waiting for the result: per-entry new-signal verdict
        vs max cover, max cover merged (dedup-safe within the batch).
        Returns an opaque ticket for `resolve`.

        Narrow-bitmap configs slabify and ride the zero-copy translate
        kernels (one host pack, zero host translation); word-block-
        sparse configs keep the legacy host-mapped path — their sparse
        fast path needs host-computed touched blocks."""
        # vectorized unpack of the (call_id, cover) entry list: the
        # canonicalize map + one id vector — the per-entry list
        # comprehensions this replaces were audited hotpath remnants
        covers = tuple(map(sets.canonicalize,
                           map(operator.itemgetter(1), entries)))
        entry_ids = np.fromiter(map(operator.itemgetter(0), entries),
                                np.int32, len(entries))
        if self._slab_hot_path:
            win, counts, owner = self._slabify(covers)
            call_ids = np.zeros((win.shape[0],), np.int32)
            m = owner >= 0
            call_ids[m] = entry_ids[owner[m]]
            ticket = self.submit_slabs(win, counts, call_ids)
            return ("wrap", ticket, owner, len(entries))
        idx, valid, owner = self._map_rows(covers)
        call_ids = np.zeros((idx.shape[0],), np.int32)
        m = owner >= 0
        call_ids[m] = entry_ids[owner[m]]
        # sparse when configured and the batch's footprint fits; the
        # engine falls back to the dense step with identical verdicts
        res = self.engine.update_batch_sparse(call_ids, idx, valid)
        return ("rows", res, owner, len(entries), call_ids,
                self._frontier)

    def resolve(self, ticket) -> np.ndarray:
        """Fetch a submit ticket's verdict: (n_entries,) bool has-new.
        The active campaign frontier (snapshotted at submit, so a
        mid-flight campaign swap can't misattribute) absorbs the
        batch's new-signal diffs here — outside the engine lock."""
        kind = ticket[0]
        if kind == "slab":
            return self._resolve_slab(ticket)
        if kind == "tick":
            return self._resolve_tick(ticket)
        if kind == "wrap":
            _k, inner, owner, n = ticket
            has_new = self._resolve_slab(inner)
            out = np.zeros((n,), bool)
            m = (owner >= 0) & has_new[: len(owner)]
            np.logical_or.at(out, owner[m], True)
            return out
        _kind, res, owner, n, call_ids, frontier = ticket
        has_new = np.asarray(res.has_new)        # the host sync
        if frontier is not None:
            frontier.absorb(call_ids, res)
        out = np.zeros((n,), bool)
        m = (owner >= 0) & has_new[: len(owner)]
        np.logical_or.at(out, owner[m], True)
        return out

    def check_batch(self, entries: "list[tuple[int, np.ndarray]]"
                    ) -> np.ndarray:
        """Synchronous submit+resolve (tests and cold paths)."""
        return self.resolve(self.submit_batch(entries))

    # -- triage path -------------------------------------------------------

    def triage_new(self, call_id: int, cover: np.ndarray) -> np.ndarray:
        """Subset of `cover` new vs corpus cover minus flakes (ref
        fuzzer.go:384-386) — the admission gate, device-evaluated via
        the slab translate kernel.  Each PC's verdict is read through
        its OWN dense index (returned by the dispatch — no second host
        translation), so hash-overflow aliasing degrades to a shared
        verdict instead of misattributing positions."""
        cover = sets.canonicalize(cover)
        if len(cover) == 0:
            return cover
        win, counts, owner = self._slabify([cover])
        self.mirror.ensure(cover)       # triage is rare: resolve up front
        call_ids = np.full((win.shape[0],), call_id, np.int32)
        _has, new, _bm, idx, _miss = self.engine.triage_diff_slabs(
            win, counts, call_ids, self.mirror)
        new = np.asarray(new)
        K = win.shape[1]
        rows = np.arange(len(cover)) // K     # the chunk row per PC
        cols = np.arange(len(cover)) % K
        pc_idx = np.asarray(idx)[rows, cols].astype(np.int64)
        keep = ((new[rows, pc_idx >> 5] >> (pc_idx & 31)) & 1).astype(bool)
        return cover[keep]

    def add_flakes(self, call_id: int, pcs: np.ndarray) -> None:
        """Fold unstable PCs into the device flakes bitmap (ref
        fuzzer.go:399-416's SymmetricDifference accumulation)."""
        if len(pcs) == 0:
            return
        cover = sets.canonicalize(pcs)
        win, counts, _owner = self._slabify([cover])
        self.mirror.ensure(cover)
        bitmaps = self.engine.pack_slabs(win, counts, self.mirror)
        call_ids = np.full((win.shape[0],), call_id, np.int32)
        self.engine.add_flakes(call_ids, bitmaps)

    def merge_corpus(self, call_id: int, pcs: np.ndarray,
                     corpus_index: "int | None" = None) -> None:
        """Admit a triaged input's stable cover into corpus cover and the
        device corpus signal matrix as ONE row (the slab window OR-folds
        on device — rows are full-width bitmaps, so they compose),
        recording the caller's corpus index for the row so the
        signal-weighted sampler maps device rows back to the right
        programs.  When the matrix is full the cover bitmap STILL merges
        (the admission gate must keep rejecting what the corpus already
        has) — only the minimize-matrix row is lost."""
        pcs = sets.canonicalize(pcs)
        win, counts, _owner = self._slabify([pcs])
        self.mirror.ensure(pcs)
        bitmap = self.engine.pack_or_slabs(win, counts, self.mirror)
        call_ids = np.full((1,), call_id, np.int32)
        rows = self.engine.merge_corpus(call_ids, bitmap,
                                        cover_only_when_full=True)
        if rows is not None:
            # ALWAYS record the row (placeholder -1 when the caller
            # tracks no corpus index): the positional map must stay
            # truthful for rows with no owner too.  With tiers the
            # returned row may be a reused (demoted) slot — the
            # positional write rebinds it.
            self._record_rows(
                np.asarray(rows, np.int64),
                np.full((len(rows),),
                        -1 if corpus_index is None else int(corpus_index),
                        np.int64))
        if rows is None:
            self.stat_corpus_full += 1
            if self.stat_corpus_full == 1:
                log.logf(0, "device corpus matrix full (%d rows); "
                         "cover still merges, minimize rows dropped",
                         self.engine.cap)

    def row_to_corpus(self, row: int) -> "int | None":
        """Translate ONE device corpus row (e.g. a decision-stream
        pre-drawn pick) to the caller's corpus index; None when the row
        was never recorded or carries no owner."""
        with self._row_mu:
            r2c = self._row2corpus
            if 0 <= row < len(r2c) and r2c[row] >= 0:
                return r2c[row]
        return None

    def sample_corpus_indices(self, n: int) -> np.ndarray:
        """Signal-weighted corpus picks, translated from device rows to
        the caller's corpus indices via the row map (rows whose owner
        was never recorded are dropped)."""
        rows = self.engine.sample_corpus_rows(n)
        with self._row_mu:
            r2c = self._row2corpus
            out = [r2c[int(r)] for r in rows
                   if int(r) < len(r2c) and r2c[int(r)] >= 0]
        return np.asarray(out, np.int64)

    def merge_max(self, call_id: int, pcs: np.ndarray) -> None:
        """Fold externally-sourced cover (Poll inputs from other fuzzers)
        into max cover so it is not rediscovered as new."""
        self.check_batch([(call_id, pcs)])
