"""Device-resident signal backend for the fuzzer's own hot loop.

Round-1 verdict: the CoverageEngine existed but the production fuzzer
still did per-exec signal diffs with numpy sorted sets, touching the
device only through the manager.  This backend puts the engine in the
fuzzer's loop (BASELINE configs #3/#5): per-exec new-signal verdicts are
batched `update_batch` steps, triage membership (corpus-cover minus
flakes, ref syz-fuzzer/fuzzer.go:384-386) and flake accumulation
(:399-416) are device bitmap ops, and corpus admission appends rows to
the device signal matrix.

The API speaks raw kernel-PC arrays (what IPC hands back) so the
fuzzer's triage/minimize/RPC semantics stay byte-identical with the host
path; PcMap does the sparse→dense translation at the boundary (fully
vectorized — round-2 verdict found the per-PC Python loops here made
the device path lose to CPU), and results come back as membership masks
over the caller's own PC array.  A cover longer than the per-row K is
spread over several rows of the same call id for diff purposes, and
OR-folded into a single row for corpus admission so device corpus rows
stay 1:1 with admitted programs (round-2 advisor finding).

The hot path is pipelined: `submit_batch` dispatches the device step
without a host sync and returns a ticket; `resolve` fetches the verdict
later, so the ~100ms+ tunnel round-trip overlaps with the next batch's
execution instead of serializing the loop.
"""

from __future__ import annotations

import threading

import numpy as np

from syzkaller_tpu.cover import sets
from syzkaller_tpu.fuzzer.pcmap import PcMap
from syzkaller_tpu.utils import log


class DeviceSignal:
    """Raw-PC facade over a CoverageEngine + PcMap (thread-safe)."""

    def __init__(self, ncalls: int, npcs: int = 1 << 16,
                 flush_batch: int = 32, max_pcs: int = 512,
                 corpus_cap: int = 1 << 14, seed: int = 0,
                 telemetry=None):
        from syzkaller_tpu.cover.engine import CoverageEngine

        # wide bitmaps (≥128k PCs) get the word-block-sparse hot step:
        # per-batch device work follows the signal footprint instead of
        # the full width; narrow bitmaps keep the plain dense step
        # (the sparse gather/scatter wouldn't pay for itself)
        sparse_blocks = 512 if npcs >= (1 << 17) else 0
        # telemetry (a telemetry.device.DeviceStats) rides the engine's
        # fused dispatches: dense/sparse dispatch counts, fallback rate,
        # and the exec-latency histogram the fuzzer feeds
        self.tstats = telemetry
        self.engine = CoverageEngine(
            npcs=npcs, ncalls=ncalls, corpus_cap=corpus_cap,
            batch=flush_batch, max_pcs_per_exec=max_pcs, seed=seed,
            max_touched_blocks=sparse_blocks, telemetry=telemetry)
        self.pcmap = PcMap(npcs)
        self.B = flush_batch
        self.K = max_pcs
        self.stat_corpus_full = 0
        # device corpus row -> caller's corpus index (rows are admitted
        # one per program, but the matrix can fill while the host corpus
        # keeps growing, so the identity mapping is not guaranteed)
        self._row2corpus: list[int] = []
        self._row_mu = threading.Lock()
        # active campaign frontier (cover.engine.SparseView | None):
        # resolve() absorbs each batch's new-signal diffs into it, so
        # per-campaign coverage rides the dispatches the hot loop
        # already pays for.  Plain attribute swap (None = flat).
        self._frontier = None

    def set_frontier(self, view) -> None:
        """Install the campaign frontier view new signal is attributed
        to from now on (None = stop attributing)."""
        self._frontier = view

    # -- mapping helpers ---------------------------------------------------

    def _map_rows(self, covers: "list[np.ndarray]"):
        """Canonicalized covers → fixed-shape (B, K) index rows + mask +
        per-row owner, spreading covers longer than K over several rows
        and padding the row count to a multiple of the flush batch (the
        vectorized pipeline lives in PcMap.map_rows)."""
        return self.pcmap.map_rows(covers, self.K, chunk=True,
                                   pad_rows=self.B)

    # -- hot path ----------------------------------------------------------

    def submit_batch(self, entries: "list[tuple[int, np.ndarray]]"):
        """Dispatch one fused device step for up to B (call_id, raw_cover)
        execs WITHOUT waiting for the result: per-entry new-signal verdict
        vs max cover, max cover merged (dedup-safe within the batch).
        Returns an opaque ticket for `resolve`.  State mutation (the max
        cover merge) is sequenced on-device in submission order."""
        covers = [sets.canonicalize(cov) for _, cov in entries]
        idx, valid, owner = self._map_rows(covers)
        call_ids = np.zeros((idx.shape[0],), np.int32)
        m = owner >= 0
        call_ids[m] = np.array([entries[o][0] for o in owner[m]], np.int32)
        # sparse when configured and the batch's footprint fits; the
        # engine falls back to the dense step with identical verdicts
        res = self.engine.update_batch_sparse(call_ids, idx, valid)
        return (res, owner, len(entries), call_ids, self._frontier)

    def resolve(self, ticket) -> np.ndarray:
        """Fetch a submit_batch verdict: (n_entries,) bool has-new.
        The active campaign frontier (snapshotted at submit, so a
        mid-flight campaign swap can't misattribute) absorbs the
        batch's new-signal diffs here — outside the engine lock."""
        res, owner, n, call_ids, frontier = ticket
        has_new = np.asarray(res.has_new)        # the host sync
        if frontier is not None:
            frontier.absorb(call_ids, res)
        out = np.zeros((n,), bool)
        m = (owner >= 0) & has_new[: len(owner)]
        np.logical_or.at(out, owner[m], True)
        return out

    def check_batch(self, entries: "list[tuple[int, np.ndarray]]"
                    ) -> np.ndarray:
        """Synchronous submit+resolve (tests and cold paths)."""
        return self.resolve(self.submit_batch(entries))

    # -- triage path -------------------------------------------------------

    def triage_new(self, call_id: int, cover: np.ndarray) -> np.ndarray:
        """Subset of `cover` new vs corpus cover minus flakes (ref
        fuzzer.go:384-386) — the admission gate, device-evaluated.
        Each PC's verdict is read through its OWN dense index, so
        hash-overflow aliasing (two PCs sharing an index) degrades to a
        shared verdict instead of misattributing positions."""
        cover = sets.canonicalize(cover)
        idx, valid, owner = self._map_rows([cover])
        call_ids = np.full((idx.shape[0],), call_id, np.int32)
        _has, new, _bm = self.engine.triage_diff(call_ids, idx, valid)
        new = np.asarray(new)
        pc_idx = self.pcmap.indices_of(cover)
        rows = np.arange(len(cover)) // self.K    # the chunk row per PC
        keep = ((new[rows, pc_idx >> 5] >> (pc_idx & 31)) & 1).astype(bool)
        return cover[keep]

    def add_flakes(self, call_id: int, pcs: np.ndarray) -> None:
        """Fold unstable PCs into the device flakes bitmap (ref
        fuzzer.go:399-416's SymmetricDifference accumulation)."""
        if len(pcs) == 0:
            return
        idx, valid, owner = self._map_rows([sets.canonicalize(pcs)])
        bitmaps = self.engine.pack_batch(idx, valid)
        call_ids = np.full((idx.shape[0],), call_id, np.int32)
        self.engine.add_flakes(call_ids, bitmaps)

    def merge_corpus(self, call_id: int, pcs: np.ndarray,
                     corpus_index: "int | None" = None) -> None:
        """Admit a triaged input's stable cover into corpus cover and the
        device corpus signal matrix as ONE row (chunks OR-fold — rows are
        full-width bitmaps, so they compose bitwise), recording the
        caller's corpus index for the row so the signal-weighted sampler
        maps device rows back to the right programs.  When the matrix is
        full the cover bitmap STILL merges (the admission gate must keep
        rejecting what the corpus already has) — only the minimize-matrix
        row is lost."""
        pcs = sets.canonicalize(pcs)
        idx, valid, owner = self._map_rows([pcs])
        bitmap = self.engine.pack_or_rows(idx, valid, owner == 0)
        call_ids = np.full((1,), call_id, np.int32)
        with self._row_mu:
            rows = self.engine.merge_corpus(call_ids, bitmap,
                                            cover_only_when_full=True)
            if rows is not None:
                # ALWAYS record the row (placeholder -1 when the caller
                # tracks no corpus index) — skipping would shift every
                # later row's mapping by one
                self._row2corpus.append(
                    -1 if corpus_index is None else int(corpus_index))
        if rows is None:
            self.stat_corpus_full += 1
            if self.stat_corpus_full == 1:
                log.logf(0, "device corpus matrix full (%d rows); "
                         "cover still merges, minimize rows dropped",
                         self.engine.cap)

    def row_to_corpus(self, row: int) -> "int | None":
        """Translate ONE device corpus row (e.g. a decision-stream
        pre-drawn pick) to the caller's corpus index; None when the row
        was never recorded or carries no owner."""
        with self._row_mu:
            r2c = self._row2corpus
            if 0 <= row < len(r2c) and r2c[row] >= 0:
                return r2c[row]
        return None

    def sample_corpus_indices(self, n: int) -> np.ndarray:
        """Signal-weighted corpus picks, translated from device rows to
        the caller's corpus indices via the row map (rows whose owner
        was never recorded are dropped)."""
        rows = self.engine.sample_corpus_rows(n)
        with self._row_mu:
            r2c = self._row2corpus
            out = [r2c[int(r)] for r in rows
                   if int(r) < len(r2c) and r2c[int(r)] >= 0]
        return np.asarray(out, np.int64)

    def merge_max(self, call_id: int, pcs: np.ndarray) -> None:
        """Fold externally-sourced cover (Poll inputs from other fuzzers)
        into max cover so it is not rediscovered as new."""
        self.check_batch([(call_id, pcs)])
