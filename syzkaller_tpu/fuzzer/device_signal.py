"""Device-resident signal backend for the fuzzer's own hot loop.

Round-1 verdict: the CoverageEngine existed but the production fuzzer
still did per-exec signal diffs with numpy sorted sets, touching the
device only through the manager.  This backend puts the engine in the
fuzzer's loop (BASELINE configs #3/#5): per-exec new-signal verdicts are
batched `update_batch` steps, triage membership (corpus-cover minus
flakes, ref syz-fuzzer/fuzzer.go:384-386) and flake accumulation
(:399-416) are device bitmap ops, and corpus admission appends rows to
the device signal matrix.

The API speaks raw kernel-PC arrays (what IPC hands back) so the
fuzzer's triage/minimize/RPC semantics stay byte-identical with the host
path; PcMap does the sparse→dense translation at the boundary, and
results come back as membership masks over the caller's own PC array.
A cover longer than the per-row K is spread over several rows of the
same call id (diff/merge are per-call, so rows compose) — no silent
truncation up to B*K PCs per cover, chunked loops beyond.
"""

from __future__ import annotations

import numpy as np

from syzkaller_tpu.cover import sets
from syzkaller_tpu.fuzzer.pcmap import PcMap
from syzkaller_tpu.utils import log


class DeviceSignal:
    """Raw-PC facade over a CoverageEngine + PcMap (thread-safe)."""

    def __init__(self, ncalls: int, npcs: int = 1 << 16,
                 flush_batch: int = 32, max_pcs: int = 512,
                 corpus_cap: int = 1 << 14, seed: int = 0):
        from syzkaller_tpu.cover.engine import CoverageEngine

        self.engine = CoverageEngine(
            npcs=npcs, ncalls=ncalls, corpus_cap=corpus_cap,
            batch=flush_batch, max_pcs_per_exec=max_pcs, seed=seed)
        self.pcmap = PcMap(npcs)
        self.B = flush_batch
        self.K = max_pcs
        self.stat_corpus_full = 0

    # -- mapping helpers ---------------------------------------------------

    def _map_rows(self, covers: "list[np.ndarray]"):
        """Canonicalized covers → fixed-shape (B, K) index rows + mask,
        spreading covers longer than K over several rows.  Returns
        (idx, valid, owner) where owner[r] = source cover of row r
        (-1 = padding).  The mask comes from map_batch itself — it can
        compact rows when hash-overflow collisions dedup, so recomputing
        counts from cover lengths would mark stale slots valid."""
        idx_rows, owners = [], []
        for i, cov in enumerate(covers):
            chunks = [cov[lo: lo + self.K]
                      for lo in range(0, max(len(cov), 1), self.K)]
            mapped, mvalid = self.pcmap.map_batch(chunks, self.K)
            for r in range(len(chunks)):
                idx_rows.append((mapped[r], mvalid[r]))
                owners.append(i)
        # round the row count up to a multiple of the flush batch so the
        # number of distinct compiled shapes stays O(1) in steady state
        B = max(self.B, (len(idx_rows) + self.B - 1) // self.B * self.B)
        idx = np.zeros((B, self.K), np.int32)
        valid = np.zeros((B, self.K), bool)
        owner = np.full((B,), -1, np.int32)
        for r, (row, va) in enumerate(idx_rows):
            idx[r] = row
            valid[r] = va
            owner[r] = owners[r]
        return idx, valid, owner

    # -- hot path ----------------------------------------------------------

    def check_batch(self, entries: "list[tuple[int, np.ndarray]]"
                    ) -> np.ndarray:
        """One fused device step for up to B (call_id, raw_cover) execs:
        per-entry new-signal verdict vs max cover, max cover merged
        (dedup-safe within the batch).  Returns (len(entries),) bool."""
        covers = [sets.canonicalize(cov) for _, cov in entries]
        idx, valid, owner = self._map_rows(covers)
        call_ids = np.zeros((idx.shape[0],), np.int32)
        for r in range(idx.shape[0]):
            if owner[r] >= 0:
                call_ids[r] = entries[owner[r]][0]
        res = self.engine.update_batch(call_ids, idx, valid)
        out = np.zeros((len(entries),), bool)
        for r in range(idx.shape[0]):
            if owner[r] >= 0 and res.has_new[r]:
                out[owner[r]] = True
        return out

    # -- triage path -------------------------------------------------------

    def triage_new(self, call_id: int, cover: np.ndarray) -> np.ndarray:
        """Subset of `cover` new vs corpus cover minus flakes (ref
        fuzzer.go:384-386) — the admission gate, device-evaluated.
        Each PC's verdict is read through its OWN dense index, so
        hash-overflow aliasing (two PCs sharing an index) degrades to a
        shared verdict instead of misattributing positions."""
        cover = sets.canonicalize(cover)
        idx, valid, owner = self._map_rows([cover])
        call_ids = np.full((idx.shape[0],), call_id, np.int32)
        _has, new, _bm = self.engine.triage_diff(call_ids, idx, valid)
        new = np.asarray(new)
        pc_idx = self.pcmap.indices_of(cover)
        rows = np.arange(len(cover)) // self.K    # the chunk row per PC
        keep = ((new[rows, pc_idx >> 5] >> (pc_idx & 31)) & 1).astype(bool)
        return cover[keep]

    def add_flakes(self, call_id: int, pcs: np.ndarray) -> None:
        """Fold unstable PCs into the device flakes bitmap (ref
        fuzzer.go:399-416's SymmetricDifference accumulation)."""
        if len(pcs) == 0:
            return
        idx, valid, owner = self._map_rows([sets.canonicalize(pcs)])
        bitmaps = self.engine.pack_batch(idx, valid)
        call_ids = np.full((idx.shape[0],), call_id, np.int32)
        self.engine.add_flakes(call_ids, bitmaps)

    def merge_corpus(self, call_id: int, pcs: np.ndarray) -> None:
        """Admit a triaged input's stable cover into corpus cover and the
        device corpus signal matrix.  When the matrix is full the cover
        bitmap STILL merges (the admission gate must keep rejecting what
        the corpus already has) — only the minimize-matrix row is lost."""
        pcs = sets.canonicalize(pcs)
        idx, valid, owner = self._map_rows([pcs])
        nrows = int((owner == 0).sum())
        bitmaps = self.engine.pack_batch(idx, valid)[:nrows]
        call_ids = np.full((nrows,), call_id, np.int32)
        rows = self.engine.merge_corpus(call_ids, bitmaps,
                                        cover_only_when_full=True)
        if rows is None:
            self.stat_corpus_full += 1
            if self.stat_corpus_full == 1:
                log.logf(0, "device corpus matrix full (%d rows); "
                         "cover still merges, minimize rows dropped",
                         self.engine.cap)

    def merge_max(self, call_id: int, pcs: np.ndarray) -> None:
        """Fold externally-sourced cover (Poll inputs from other fuzzers)
        into max cover so it is not rediscovered as new."""
        self.check_batch([(call_id, pcs)])
