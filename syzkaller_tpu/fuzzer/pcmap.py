"""Sparse→dense PC mapping: raw kernel PCs → bitmap indices.

SURVEY §7 hard parts: KCOV returns variable-length lists of raw PCs; the
device wants fixed-shape index batches. This map assigns dense indices
on first sight (vmlinux-derived tables can pre-seed it, the analog of
syz-manager/cover.go:274-312's objdump scan). Unknown PCs beyond
capacity fold into a hashed overflow region instead of being dropped, so
signal is degraded gracefully rather than lost (modules/KASLR case).
"""

from __future__ import annotations

import numpy as np


class PcMap:
    def __init__(self, npcs: int, reserve_overflow: int = 1024):
        assert npcs > reserve_overflow
        self.npcs = npcs
        self.direct_cap = npcs - reserve_overflow
        self.overflow = reserve_overflow
        self._map: dict[int, int] = {}

    def __len__(self) -> int:
        return len(self._map)

    def preseed(self, pcs) -> None:
        """Pre-assign indices for a known PC universe (vmlinux scan)."""
        for pc in pcs:
            self.index_of(int(pc))

    def index_of(self, pc: int) -> int:
        idx = self._map.get(pc)
        if idx is None:
            if len(self._map) < self.direct_cap:
                idx = len(self._map)
                self._map[pc] = idx
            else:
                # overflow: stable hash into the reserved tail
                idx = self.direct_cap + (hash(pc) % self.overflow)
        return idx

    def map_batch(self, covers: "list[np.ndarray]", K: int
                  ) -> tuple[np.ndarray, np.ndarray]:
        """List of raw-PC arrays → padded (B, K) index batch + mask.
        Covers longer than K are truncated (the tail is the rarely-hit
        part after sort-dedup; reference caps at 64k PCs/call too)."""
        B = len(covers)
        idx = np.zeros((B, K), np.int32)
        valid = np.zeros((B, K), bool)
        for i, cov in enumerate(covers):
            n = min(len(cov), K)
            for j in range(n):
                idx[i, j] = self.index_of(int(cov[j]))
            valid[i, :n] = True
        return idx, valid
