"""Sparse→dense PC mapping: raw kernel PCs → bitmap indices.

SURVEY §7 hard parts: KCOV returns variable-length lists of raw PCs; the
device wants fixed-shape index batches. This map assigns dense indices
on first sight; `preseed` loads a vmlinux-derived PC universe (the
analog of syz-manager/cover.go:274-312's objdump scan) so indices are
stable across restarts. Unknown PCs beyond capacity fold into a hashed
overflow region instead of being dropped, so signal is degraded
gracefully rather than lost (modules/KASLR case) — `overflow_hits`
counts how often, so the degradation is visible in stats instead of
silently aliasing (round-1 verdict weak item #5).
"""

from __future__ import annotations

import threading

import numpy as np


class PcMap:
    """Thread-safe: the manager's async vmlinux scan preseeds while RPC
    handler threads map exec covers concurrently."""

    def __init__(self, npcs: int, reserve_overflow: int = 1024):
        assert npcs > reserve_overflow
        self.npcs = npcs
        self.direct_cap = npcs - reserve_overflow
        self.overflow = reserve_overflow
        self._map: dict[int, int] = {}
        self._rev: list[int] = []          # direct index -> PC
        self._mu = threading.Lock()
        self.overflow_hits = 0             # lookups landing in overflow

    def __len__(self) -> int:
        return len(self._map)

    def preseed(self, pcs) -> None:
        """Pre-assign indices for a known PC universe (vmlinux scan):
        restart-stable, and real-kernel PCs never overflow."""
        with self._mu:
            for pc in pcs:
                self._index_of_locked(int(pc))

    def index_of(self, pc: int) -> int:
        with self._mu:
            return self._index_of_locked(pc)

    def _index_of_locked(self, pc: int) -> int:
        idx = self._map.get(pc)
        if idx is None:
            if len(self._rev) < self.direct_cap:
                idx = len(self._rev)
                self._map[pc] = idx
                self._rev.append(pc)
            else:
                # overflow: stable hash into the reserved tail
                self.overflow_hits += 1
                idx = self.direct_cap + (hash(pc) % self.overflow)
        return idx

    def indices_of(self, pcs) -> np.ndarray:
        """Per-PC indices (duplicates NOT removed — aliased PCs share)."""
        with self._mu:
            return np.array([self._index_of_locked(int(pc)) for pc in pcs],
                            dtype=np.int64)

    def pc_of(self, idx: int) -> "int | None":
        """Direct index -> PC (None for overflow/unassigned indices)."""
        with self._mu:
            return self._rev[idx] if 0 <= idx < len(self._rev) else None

    def pcs_of(self, indices) -> np.ndarray:
        """Bitmap indices -> known PCs (overflow indices dropped)."""
        with self._mu:
            return np.array([self._rev[i] for i in indices
                             if 0 <= i < len(self._rev)], dtype=np.uint64)

    def map_batch(self, covers: "list[np.ndarray]", K: int
                  ) -> tuple[np.ndarray, np.ndarray]:
        """List of raw-PC arrays → padded (B, K) index batch + mask.
        Covers longer than K are truncated (the tail is the rarely-hit
        part after sort-dedup; reference caps at 64k PCs/call too).
        Rows are guaranteed duplicate-free — distinct PCs can collide in
        the hashed overflow region, and the engine's MXU bit-packing
        requires unique indices per row (duplicates would carry)."""
        B = len(covers)
        idx = np.zeros((B, K), np.int32)
        valid = np.zeros((B, K), bool)
        with self._mu:
            for i, cov in enumerate(covers):
                seen: set[int] = set()
                n = 0
                for pc in cov[:K]:
                    j = self._index_of_locked(int(pc))
                    if j in seen:
                        continue
                    seen.add(j)
                    idx[i, n] = j
                    n += 1
                valid[i, :n] = True
        return idx, valid
