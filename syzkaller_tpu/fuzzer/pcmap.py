"""Sparse→dense PC mapping: raw kernel PCs → bitmap indices.

SURVEY §7 hard parts: KCOV returns variable-length lists of raw PCs; the
device wants fixed-shape index batches. This map assigns dense indices
on first sight; `preseed` loads a vmlinux-derived PC universe (the
analog of syz-manager/cover.go:274-312's objdump scan) so indices are
stable across restarts. Unknown PCs beyond capacity fold into a hashed
overflow region instead of being dropped, so signal is degraded
gracefully rather than lost (modules/KASLR case) — `overflow_hits`
counts how often, so the degradation is visible in stats instead of
silently aliasing (round-1 verdict weak item #5).

The map is a vectorized open-addressing hash table in numpy: lookups
and first-sight assignment for a whole batch of covers are a handful of
array passes (linear probing, each round fully vectorized), not a
per-PC Python loop — the round-2 verdict found the dict loop here was
the host boundary that made the device pipeline lose to CPU end-to-end.
"""

from __future__ import annotations

import threading

import numpy as np

_MULT = np.uint64(0x9E3779B97F4A7C15)   # Fibonacci hashing multiplier
_EMPTY = np.uint64(0xFFFFFFFFFFFFFFFF)  # hash-slot empty sentinel


def _mix(keys: np.ndarray) -> np.ndarray:
    h = keys * _MULT
    return h ^ (h >> np.uint64(31))


class PcMap:
    """Thread-safe: the manager's async vmlinux scan preseeds while RPC
    handler threads map exec covers concurrently."""

    def __init__(self, npcs: int, reserve_overflow: int = 1024):
        assert npcs > reserve_overflow
        self.npcs = npcs
        self.direct_cap = npcs - reserve_overflow
        self.overflow = reserve_overflow
        self._mu = threading.Lock()
        self.overflow_hits = 0             # lookups landing in overflow
        # open-addressing table, ≥2x direct capacity → load factor ≤ 0.5
        # (only direct-mapped PCs are stored; overflow PCs are computed
        # per lookup, exactly like the original dict-based map)
        size = 1024
        while size < 2 * npcs:
            size <<= 1
        self._mask = np.uint64(size - 1)
        self._keys = np.full(size, _EMPTY, np.uint64)
        self._vals = np.zeros(size, np.int32)
        self._rev = np.zeros(self.direct_cap, np.uint64)  # idx -> PC
        self._n = 0
        # bumped on every first-sight insertion batch: device mirrors
        # (DeviceKeyMirror) compare against it to know when their
        # sorted key snapshot is stale
        self.version = 0

    def __len__(self) -> int:
        return self._n

    # -- vectorized core (all under self._mu) ------------------------------

    def _lookup(self, uniq: np.ndarray) -> np.ndarray:
        """Existing vals for unique keys; -1 where absent."""
        n = len(uniq)
        vals = np.full(n, -1, np.int32)
        if n == 0 or self._n == 0:
            return vals
        h = _mix(uniq)
        pend = np.arange(n)
        r = np.zeros(n, np.uint64)
        while len(pend):
            slot = ((h[pend] + r[pend]) & self._mask).astype(np.int64)
            k = self._keys[slot]
            hit = k == uniq[pend]
            vals[pend[hit]] = self._vals[slot[hit]]
            cont = ~(hit | (k == _EMPTY))
            r[pend[cont]] += np.uint64(1)
            pend = pend[cont]
        return vals

    def _insert(self, keys: np.ndarray) -> np.ndarray:
        """Assign sequential direct indices to unique absent keys (given
        in first-seen order) and hash-insert them.  Caller guarantees
        room: len(keys) <= direct_cap - _n."""
        n = len(keys)
        vals = np.arange(self._n, self._n + n, dtype=np.int32)
        self._rev[self._n:self._n + n] = keys
        self._n += n
        self.version += 1
        h = _mix(keys)
        pend = np.arange(n)
        r = np.zeros(n, np.uint64)
        while len(pend):
            slot = ((h[pend] + r[pend]) & self._mask).astype(np.int64)
            empty = self._keys[slot] == _EMPTY
            es, ep = slot[empty], pend[empty]
            if len(ep):
                # two keys can race for one empty slot: first wins, the
                # rest re-probe after the write
                uslot, first = np.unique(es, return_index=True)
                win = ep[first]
                self._keys[uslot] = keys[win]
                self._vals[uslot] = vals[win]
            placed = self._keys[slot] == keys[pend]
            cont = ~placed
            r[pend[cont]] += np.uint64(1)
            pend = pend[cont]
        return vals

    def _map_flat_locked(self, pcs: np.ndarray) -> np.ndarray:
        """Per-occurrence indices for a flat raw-PC array (vectorized
        lookup-or-assign; duplicates preserved).  Steady state (all PCs
        already mapped) is a pure probe pass — the np.unique sort runs
        only over first-sight misses."""
        if len(pcs) == 0:
            return np.empty(0, np.int32)
        pcs = np.where(pcs == _EMPTY, _EMPTY - np.uint64(1), pcs)
        out = self._lookup(pcs)
        miss = out < 0
        if miss.any():
            mpcs = pcs[miss]
            uniq, first = np.unique(mpcs, return_index=True)
            order = np.argsort(first, kind="stable")        # first-seen
            mkeys = uniq[order]
            room = max(self.direct_cap - self._n, 0)
            mvals = np.empty(len(mkeys), np.int32)
            mvals[:room] = self._insert(mkeys[:room])
            if len(mkeys) > room:
                # overflow: stable hash into the reserved tail, not
                # memoized (matches the original map's behavior; hits
                # are counted per occurrence below)
                ov = mkeys[room:]
                mvals[room:] = (self.direct_cap
                                + (ov % np.uint64(self.overflow))
                                ).astype(np.int32)
            # scatter back through each miss occurrence
            back = np.empty(len(uniq), np.int32)
            back[order] = mvals
            pos = np.searchsorted(uniq, mpcs)
            out[miss] = back[pos]
        self.overflow_hits += int((out >= self.direct_cap).sum())
        return out

    # -- public API --------------------------------------------------------

    def map_flat(self, pcs) -> np.ndarray:
        """Flat raw-PC array → per-occurrence bitmap indices."""
        with self._mu:
            return self._map_flat_locked(np.asarray(pcs, np.uint64))

    def preseed(self, pcs) -> int:
        """Pre-assign indices for a known PC universe (vmlinux scan):
        restart-stable.  Returns how many of THESE pcs landed in the
        hashed overflow region (computed from this call's own results —
        the shared overflow_hits counter also moves under concurrent
        RPC-path lookups, so a before/after delta would lie)."""
        if not isinstance(pcs, np.ndarray):
            pcs = np.array(list(pcs), np.uint64)   # C-speed conversion
        out = self.map_flat(pcs)
        return int((out >= self.direct_cap).sum())

    def export_keys(self) -> np.ndarray:
        """Direct-mapped PCs in first-seen order — the whole mapping
        state: `preseed`ing these into a fresh map reassigns the exact
        same dense indices (vals are sequential in insertion order,
        overflow hashing is stateless).  The resilience snapshot
        carries this so restored coverage bitmaps keep meaning the same
        PCs."""
        with self._mu:
            return self._rev[:self._n].copy()

    def index_of(self, pc: int) -> int:
        return int(self.map_flat(np.array([pc], np.uint64))[0])

    def indices_of(self, pcs) -> np.ndarray:
        """Per-PC indices (duplicates NOT removed — aliased PCs share)."""
        return self.map_flat(pcs).astype(np.int64)

    def pc_of(self, idx: int) -> "int | None":
        """Direct index -> PC (None for overflow/unassigned indices)."""
        with self._mu:
            return int(self._rev[idx]) if 0 <= idx < self._n else None

    def pcs_of(self, indices) -> np.ndarray:
        """Bitmap indices -> known PCs (overflow indices dropped)."""
        idx = np.asarray(indices, np.int64)
        with self._mu:
            idx = idx[(idx >= 0) & (idx < self._n)]
            return self._rev[idx].astype(np.uint64)

    def map_rows(self, covers: "list[np.ndarray]", K: int,
                 chunk: bool = False, pad_rows: int = 1
                 ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """List of raw-PC arrays → padded (R, K) index rows + mask +
        (R,) owner (source cover per row, -1 = padding).  With
        chunk=False covers longer than K are truncated at K (the tail is
        the rarely-hit part after sort-dedup; reference caps at 64k
        PCs/call too) and R = len(covers); with chunk=True each cover
        spreads over ceil(len/K) rows of the same owner and R rounds up
        to a multiple of pad_rows (keeps the set of compiled batch
        shapes O(1)).  Valid entries are guaranteed duplicate-free per
        row — distinct PCs can collide in the hashed overflow region,
        and the engine's MXU bit-packing requires unique indices per row
        (duplicates would carry).  One vectorized pipeline serves both
        call modes: map_flat over the concatenation, one (row, col)
        scatter, one sort-based in-row dedup."""
        ncov = len(covers)
        if chunk:
            flat = [np.asarray(c, np.uint64).ravel() for c in covers]
        else:
            flat = [np.asarray(c[:K], np.uint64).ravel() for c in covers]
        lens = np.array([len(t) for t in flat], np.int64)
        nch = (np.maximum(1, -(-lens // K)) if chunk
               else np.ones(ncov, np.int64))
        rows = int(nch.sum()) if ncov else 0
        R = max(pad_rows, (rows + pad_rows - 1) // pad_rows * pad_rows)
        idx = np.zeros((R, K), np.int32)
        valid = np.zeros((R, K), bool)
        owner = np.full((R,), -1, np.int32)
        if ncov == 0:
            return idx, valid, owner
        owner[:rows] = np.repeat(np.arange(ncov, dtype=np.int32), nch)
        total = int(lens.sum())
        if total:
            vals = self.map_flat(np.concatenate(flat))
            cover_id = np.repeat(np.arange(ncov), lens)
            pos = np.arange(total) - np.repeat(
                np.concatenate([[0], np.cumsum(lens)[:-1]]), lens)
            row_start = np.concatenate([[0], np.cumsum(nch)[:-1]])
            r = row_start[cover_id] + pos // K
            c = pos % K
            idx[r, c] = vals
            valid[r, c] = True
            _dedup_rows(idx, valid)
        return idx, valid, owner

    def map_batch(self, covers: "list[np.ndarray]", K: int
                  ) -> tuple[np.ndarray, np.ndarray]:
        """List of raw-PC arrays → padded (B, K) index batch + mask,
        one row per cover (truncating at K)."""
        if len(covers) == 0:
            return np.zeros((0, K), np.int32), np.zeros((0, K), bool)
        idx, valid, _owner = self.map_rows(covers, K)
        return idx, valid


class DeviceKeyMirror:
    """Device-resident sorted mirror of a PcMap's direct-mapped keys —
    the sparse→dense translation table the ingest kernels binary-search
    on device (cover/engine.py translate_slab_rows), retiring the
    per-batch host `_lookup`/scatter/dedup/pad packing.

    Layout: two fixed-capacity device arrays (capacity = direct_cap, so
    incremental appends never change a dispatch signature):
      skeys (D,) uint32  sorted live keys, 0xFFFFFFFF sentinel padding
      svals (D,) int32   dense index of skeys[i] (first-seen order ids)
    plus a tiny (2,) int32 meta operand [n_live_keys, table_full].

    Only keys that fit uint32 are mirrored: slab PCs arrive as u32 (the
    executor's wire format), and a 64-bit preseeded vmlinux key can
    never equal a u32 probe — excluding it changes no lookup result.
    When the direct table is full, the kernel computes the stateless
    hashed-overflow index itself (same formula as `_map_flat_locked`),
    so a saturated map never round-trips through the host.  A probe
    missing while the table still has room IS a new key: the ingest
    caller resolves those host-side once per batch (PcMap.map_flat on
    the missed rows — exact first-seen order, so `export_keys` and the
    PR 9 snapshots stay bit-exact) and `refresh()`es the mirror.

    Thread-safe; `put` is the engine's put_replicated so the arrays
    live on the engine's device/mesh.  `invalidate()` drops the cached
    device arrays (backend failover re-homes them on next use)."""

    def __init__(self, pcmap: PcMap, put=None):
        self.pcmap = pcmap
        self._put = put
        self._mu = threading.Lock()
        self._version = -1
        self._skeys = None
        self._svals = None
        self._meta = None
        self.stat_refreshes = 0

    def _put_fn(self):
        if self._put is not None:
            return self._put
        import jax.numpy as jnp
        return jnp.asarray

    def invalidate(self) -> None:
        with self._mu:
            self._version = -1
            self._skeys = self._svals = self._meta = None

    def refresh(self) -> None:
        """Rebuild the sorted device snapshot if the map grew."""
        pm = self.pcmap
        with self._mu:
            if self._version == pm.version and self._skeys is not None:
                return
            with pm._mu:
                ver = pm.version
                rev = pm._rev[: pm._n].copy()
                full = pm._n >= pm.direct_cap
            D = pm.direct_cap
            m = rev < np.uint64(1) << np.uint64(32)
            keys = rev[m].astype(np.uint32)
            vals = np.nonzero(m)[0].astype(np.int32)
            order = np.argsort(keys, kind="stable")
            skeys = np.full((D,), 0xFFFFFFFF, np.uint32)
            svals = np.zeros((D,), np.int32)
            skeys[: len(keys)] = keys[order]
            svals[: len(keys)] = vals[order]
            put = self._put_fn()
            self._skeys = put(skeys)
            self._svals = put(svals)
            self._meta = put(np.array([len(keys), int(full)], np.int32))
            self._version = ver
            self.stat_refreshes += 1

    def operands(self):
        """(skeys, svals, meta) device operands for a translate kernel
        dispatch, refreshed if stale."""
        self.refresh()
        with self._mu:
            return self._skeys, self._svals, self._meta

    def ensure(self, pcs) -> int:
        """Insert any first-sight keys in `pcs` (occurrence order — the
        exact host `map_flat` semantics, overflow-hit counting included)
        and refresh the mirror if that grew the map.  Returns the
        number of keys added.  This is the admission-path pre-resolve:
        after it, a translate dispatch over `pcs` cannot miss."""
        pm = self.pcmap
        before = len(pm)
        pm.map_flat(np.asarray(pcs, np.uint64))
        added = len(pm) - before
        if added or self._version != pm.version:
            self.refresh()
        return added


def _dedup_rows(idx: np.ndarray, valid: np.ndarray) -> None:
    """Mask duplicate indices within each row (in place), vectorized:
    sort each row with invalids pushed to +inf, mark repeats, scatter the
    dup mask back to original positions."""
    s = np.where(valid, idx, np.int32(0x7FFFFFFF))
    order = np.argsort(s, axis=1, kind="stable")
    ss = np.take_along_axis(s, order, axis=1)
    dup_sorted = np.zeros_like(valid)
    dup_sorted[:, 1:] = (ss[:, 1:] == ss[:, :-1]) & (ss[:, 1:] != 0x7FFFFFFF)
    dup = np.zeros_like(valid)
    np.put_along_axis(dup, order, dup_sorted, axis=1)
    valid &= ~dup
