"""Crash intelligence plane: device-batched dedup/clustering + the
batched-bisection repro service.

A million-user fleet produces crash *streams*, not crash files.  This
package turns the L5 report/repro tier into services:

* `signature` — crash identity as fixed-width feature vectors (title
  char n-grams + stack-PC frame signature), dedup/clustering as ONE
  fused batched similarity dispatch on device with a label-propagation
  union-find, and the incremental `CrashIndex` the manager's
  `save_crash` dedups through.
* `scheduler` — `ReproScheduler` packs candidate simplifications of
  MANY crashes into the same Oracle VM-pool round; per-crash bisection
  state machines (suspect narrowing → call minimization → option
  simplification) advance as results return, so repro throughput
  scales with VM workers instead of crash count.
* `synth` — oops-corpus-shaped synthetic report generator (bench +
  load tests).
"""

from syzkaller_tpu.triage.signature import (  # noqa: F401
    CrashIndex, SignatureKernel, stable_cluster_id,
)
from syzkaller_tpu.triage.scheduler import ReproScheduler  # noqa: F401
