"""Oops-corpus-shaped synthetic report generator.

Produces parsed reports — (title, frames) tuples, the signature
kernel's input — distributionally shaped like the 43-log regression
corpus (tests/test_oops_corpus.py): a bounded set of crash classes
(KASAN/KMSAN access reports, GPFs, deadlocks, hangs, BUG_ONs, leaks)
instantiated over a pool of kernel function names, with the per-report
noise a real fleet stream carries (sizes, line numbers, slightly
jittered frame tails).  Reports generated from the same (class,
function) template are the same crash and must dedup together; bench
uses the known template count as the expected cluster cardinality.
"""

from __future__ import annotations

import numpy as np

_FUNCS = [
    "tcp_v4_connect", "skb_release_data", "ext4_mark_inode_dirty",
    "sk_psock_init", "snd_pcm_period_elapsed", "copy_process",
    "pipe_lock", "rb_erase", "kfree_skb", "tcp_close", "sock_has_perm",
    "__list_del_entry", "relay_switch_subbuf", "__tcp_select_window",
    "sk_stream_kill_queues", "ksys_write", "timerqueue_del", "memcpy",
    "__schedule", "strlen",
]

_TRACE_FUNCS = [
    "do_syscall_64", "entry_SYSCALL_64", "sock_sendmsg", "vfs_write",
    "ksys_write", "do_sys_open", "path_openat", "link_path_walk",
    "security_socket_sendmsg", "release_sock", "lock_sock_nested",
    "tcp_sendmsg", "inet_release", "__sock_release", "sock_close",
    "__fput", "task_work_run", "exit_to_user_mode",
]

# (title template, has size noise, has frames) — {f}: function name
_CLASSES = [
    ("KASAN: use-after-free Read in {f}", True, True),
    ("KASAN: use-after-free Write in {f}", True, True),
    ("KASAN: slab-out-of-bounds Read in {f}", True, True),
    ("KMSAN: uninit-value in {f}", False, True),
    ("KCSAN: data-race in {f}", False, False),
    ("general protection fault in {f}", False, True),
    ("possible deadlock in {f}", False, True),
    ("WARNING in {f}", False, True),
    ("BUG: unable to handle kernel NULL pointer dereference in {f}",
     False, True),
    ("memory leak in {f} (size {n})", False, True),
    ("INFO: task hung", False, False),
    ("INFO: rcu detected stall", False, False),
]


def templates(n_templates: int, seed: int = 0):
    """n distinct crash templates: (title_fmt, func, frames)."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_templates):
        cls, noisy_size, has_frames = _CLASSES[i % len(_CLASSES)]
        func = _FUNCS[(i // len(_CLASSES)) % len(_FUNCS)]
        frames = []
        if has_frames:
            start = int(rng.integers(0, len(_TRACE_FUNCS) - 4))
            frames = [func] + _TRACE_FUNCS[start:start + 4]
        out.append((cls, func, frames, noisy_size))
    return out


def reports(rng, n: int, n_templates: int = 40
            ) -> "list[tuple[str, list[str]]]":
    """n synthetic parsed reports drawn over `n_templates` distinct
    crashes.  Same-template reports vary only in noise a real console
    stream carries (sizes in the title where the class embeds one, a
    jittered frame tail) — they must land in one cluster."""
    tpls = templates(n_templates)
    out = []
    for _ in range(n):
        cls, func, frames, noisy_size = tpls[int(rng.integers(len(tpls)))]
        title = cls.replace("{f}", func)
        if "{n}" in title:
            title = title.replace("{n}", str(1 << int(rng.integers(5, 12))))
        fr = list(frames)
        if fr and rng.random() < 0.3:
            fr = fr[:-1]          # truncated unwind tail
        out.append((title, fr))
    return out
