"""Signature kernel: vectorized crash identity + device-batched
dedup/clustering.

A crash's identity is a fixed-width L2-normalized feature vector:
character 4-gram hashes of the oops title (the description
`report.parse` extracts — the reference's dedup key) concatenated with
a down-weighted bag of stack-PC frame-name hashes (`report.Report
.frames`).  Clustering a batch is then ONE fused device dispatch:
cosine similarity as a blocked matmul, threshold to an adjacency
matrix, and a min-label propagation loop (the device-native
union-find) that converges to per-component representative indices —
batch shapes pow2-bucketed so warm batches never recompile, telemetry
stat bumps folded in INSIDE the jit (cover-engine idiom).

Parameter provenance (pinned by tests/test_triage.py golden corpus):
on the 43-log oops regression corpus, 4-gram title cosine between
DISTINCT crash classes peaks at 0.853 (`nr_ptes` vs `nr_pmds` — one
letter apart, genuinely different kernel bugs), while identical titles
score 1.0.  With the 0.3-weighted frame block appended, inter-class
similarity is bounded by (0.853 + 0.09)/1.09 ≈ 0.865 and same-title
pairs by 1/1.09 ≈ 0.917 (disjoint frames) — THRESHOLD 0.89 separates
both with margin, tolerating title noise (addresses, truncation) that
string-equality dedup fragments into duplicate buckets.
"""

from __future__ import annotations

import hashlib
import re
import threading
import zlib
from dataclasses import dataclass

import numpy as np

from syzkaller_tpu.utils.shapes import pow2_bucket

D_TITLE = 512        # title 4-gram hash buckets
D_FRAME = 256        # frame-name hash buckets
NGRAM = 4
FRAME_WEIGHT = 0.3   # frame block scale vs the unit-norm title block
THRESHOLD = 0.89

# decimal runs collapse to one token before n-gramming: sizes, line
# numbers and pids are per-instance noise ("Read of size 8" vs "of
# size 16" is one bug), while identifier spellings (nr_ptes/nr_pmds)
# stay intact and keep distinct classes apart
_DIGIT_RUN = re.compile(rb"[0-9]+")


def stable_cluster_id(title: str) -> str:
    """Cluster id minted from the founding member's title — the same
    sha1-prefix scheme the manager's crash dirs always used, so
    restarts (and pre-triage workdirs) resolve to identical ids."""
    return hashlib.sha1(title.encode()).hexdigest()[:40]


def featurize_one(title: str, frames: "list[str] | None" = None
                  ) -> np.ndarray:
    """(D_TITLE + D_FRAME,) float32, L2-normalized."""
    v = np.zeros((D_TITLE + D_FRAME,), np.float32)
    t = _DIGIT_RUN.sub(b"#", title.lower().encode())
    if len(t) < NGRAM:
        if t:
            v[zlib.crc32(t) % D_TITLE] += 1.0
    else:
        for i in range(len(t) - NGRAM + 1):
            v[zlib.crc32(t[i:i + NGRAM]) % D_TITLE] += 1.0
    tn = float(np.linalg.norm(v[:D_TITLE]))
    if tn > 0:
        v[:D_TITLE] /= tn
    if frames:
        f = v[D_TITLE:]
        for name in frames:
            f[zlib.crc32(name.encode()) % D_FRAME] += 1.0
        fn = float(np.linalg.norm(f))
        if fn > 0:
            f *= FRAME_WEIGHT / fn
    n = float(np.linalg.norm(v))
    if n > 0:
        v /= n
    return v


class SignatureKernel:
    """The batched dedup/clustering dispatch.

    `cluster(feats)` pads the batch to a pow2 bucket and runs ONE
    jitted call: blocked similarity matmul → thresholded adjacency →
    min-label propagation to a fixpoint.  Returns per-row component
    labels (the min row index of each connected component).  Telemetry
    (a telemetry.device.DeviceStats) is bumped inside the jit —
    batches, live rows, above-threshold edges — plus a host-observed
    end-to-end latency histogram.
    """

    D = D_TITLE + D_FRAME

    def __init__(self, threshold: float = THRESHOLD, telemetry=None,
                 min_batch: int = 64, max_batch: int = 1 << 14,
                 row_block: int = 1024):
        self.threshold = float(threshold)
        self.tstats = telemetry
        self.min_batch = min_batch
        self.max_batch = max_batch
        self.row_block = row_block
        self._mu = threading.Lock()
        self._cluster_fn = None      # built lazily on first device use
        self._ts_dummy = None
        self._mesh = None            # optional report-batch sharding

    def shard(self, mesh) -> None:
        """Shard the similarity dispatch's report batch over the
        engine's PC-axis mesh: the padded (B, D) feature matrix is
        placed row-sharded (B is always a pow2 bucket, so it divides
        the mesh evenly whenever B >= mesh size) and GSPMD partitions
        the blocked matmul.  Labels are unchanged — the min-label
        fixpoint is order-free — so sharded and serial clustering are
        bit-exact."""
        self._mesh = mesh

    # -- featurization (host) ---------------------------------------------

    def featurize(self, reports: "list[tuple[str, list[str]]]"
                  ) -> np.ndarray:
        """(n, D) feature matrix for [(title, frames), ...]."""
        if not reports:
            return np.zeros((0, self.D), np.float32)
        return np.stack([featurize_one(t, f) for t, f in reports])

    # -- the fused dispatch ------------------------------------------------

    def _build(self) -> None:
        import jax
        import jax.numpy as jnp

        ds = self.tstats
        thr = self.threshold
        rb = self.row_block
        self._ts_dummy = jnp.zeros((1,), jnp.int32)

        @jax.jit
        def _cluster(feats, svec, hinc):
            B = feats.shape[0]
            blk = rb if B >= rb else B
            nb = B // blk                 # both pow2 → exact

            def sim_block(i):
                f = jax.lax.dynamic_slice_in_dim(feats, i * blk, blk)
                return (f @ feats.T) >= thr

            adj = jax.lax.map(sim_block, jnp.arange(nb)).reshape(B, B)
            adj = adj | adj.T | jnp.eye(B, dtype=bool)

            def prop(state):
                labels, _ = state

                def row_min(i):
                    a = jax.lax.dynamic_slice_in_dim(adj, i * blk, blk)
                    return jnp.min(jnp.where(a, labels[None, :], B),
                                   axis=1)

                new = jax.lax.map(row_min, jnp.arange(nb)) \
                    .reshape(B).astype(jnp.int32)
                return jnp.minimum(labels, new), labels

            init = jnp.arange(B, dtype=jnp.int32)
            labels, _ = jax.lax.while_loop(
                lambda s: jnp.any(s[0] != s[1]), prop, (init, init - 1))
            if ds is not None:
                svec = svec + hinc
                svec = svec.at[ds.slot("triage_batches")].add(1)
                svec = svec.at[ds.slot("triage_reports")].add(
                    jnp.sum(jnp.any(feats != 0, axis=1),
                            dtype=jnp.int32))
                svec = svec.at[ds.slot("triage_edges")].add(
                    (jnp.sum(adj, dtype=jnp.int32) - B) // 2)
            return labels, svec

        self._cluster_fn = _cluster

    def _ts_in(self):
        if self.tstats is None:
            return self._ts_dummy, self._ts_dummy
        return self.tstats.vec, self.tstats.take_pending_device()

    def cluster(self, feats: np.ndarray) -> np.ndarray:
        """(n,) int32 cluster labels for an (n, D) feature batch; label
        = min member row index per connected component.  One fused
        dispatch; batches above max_batch must be chunked through a
        CrashIndex (whose representatives carry identity across
        chunks)."""
        import time

        n = int(feats.shape[0])
        if n == 0:
            return np.zeros((0,), np.int32)
        if n > self.max_batch:
            raise ValueError(
                f"batch {n} > max_batch {self.max_batch}; chunk via "
                "CrashIndex.assign")
        t0 = time.monotonic()
        B = pow2_bucket(n, self.min_batch, self.max_batch)
        padded = np.zeros((B, self.D), np.float32)
        padded[:n] = feats
        if self._mesh is not None \
                and B % self._mesh.devices.size == 0:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            padded = jax.device_put(
                padded, NamedSharding(self._mesh,
                                      PartitionSpec("pc", None)))
        with self._mu:
            if self._cluster_fn is None:
                self._build()
            svec, hinc = self._ts_in()
            labels, svec = self._cluster_fn(padded, svec, hinc)
            if self.tstats is not None:
                self.tstats.commit(svec)
        # the label fetch is the only host sync — outside the lock
        out = np.asarray(labels)[:n]
        if self.tstats is not None:
            self.tstats.observe("triage_latency", time.monotonic() - t0)
        return out


# -- incremental cluster index ----------------------------------------------


@dataclass
class Cluster:
    cid: str                # stable id (founding member's title sha1)
    title: str              # representative (founding) title
    feat: np.ndarray        # founding member's feature vector
    count: int = 0          # crashes assigned


class CrashIndex:
    """Incremental clustering over the signature kernel: cluster
    representatives persist across batches, so ids are stable while
    arbitrary batch sizes stream through.  `assign` runs ONE fused
    dispatch over [representatives ++ batch]; a report landing in a
    component that contains a representative joins that cluster, a
    representative-free component founds a new one.

    The internal lock guards host bookkeeping only — the device
    dispatch runs outside it; the representative-set-moved-underneath
    race is resolved host-side with a handful of exact dot products
    against representatives added since the snapshot."""

    def __init__(self, kernel: "SignatureKernel | None" = None,
                 telemetry=None):
        self.kernel = kernel or SignatureKernel(telemetry=telemetry)
        self._mu = threading.Lock()
        self._clusters: "list[Cluster]" = []
        self._by_id: "dict[str, Cluster]" = {}
        self.assigned_total = 0

    def __len__(self) -> int:
        with self._mu:
            return len(self._clusters)

    def clusters(self) -> "list[Cluster]":
        with self._mu:
            return list(self._clusters)

    def counts(self) -> "dict[str, int]":
        with self._mu:
            return {c.cid: c.count for c in self._clusters}

    def export_state(self) -> "tuple[list, np.ndarray]":
        """Snapshot serialization: (cid, title, count) per cluster plus
        the representative feature matrix (stacked in the same order) —
        restoring the EXACT feature vectors keeps post-restore
        assignments identical to the never-crashed index (rebuild()
        from crash dirs re-featurizes from report0, which is the
        fallback path)."""
        with self._mu:
            entries = [(c.cid, c.title, c.count) for c in self._clusters]
            feats = (np.stack([c.feat for c in self._clusters])
                     if self._clusters else np.zeros((0, 0), np.float32))
        return entries, feats

    def import_state(self, entries, feats) -> None:
        """Restore an `export_state` cut; existing cluster ids win (the
        crash-dir rebuild is authoritative when both ran)."""
        with self._mu:
            for (cid, title, count), f in zip(entries, feats):
                if cid in self._by_id:
                    continue
                c = Cluster(cid=cid, title=title,
                            feat=np.asarray(f, np.float32),
                            count=int(count))
                self._clusters.append(c)
                self._by_id[cid] = c

    def rebuild(self, entries: "list[tuple[str, str, list[str], int]]"
                ) -> None:
        """Restore representatives from persisted crash state:
        (cluster_id, title, frames, count) per cluster dir.  Trusts the
        stored ids — no device work, so manager startup stays cheap."""
        with self._mu:
            for cid, title, frames, count in entries:
                if cid in self._by_id:
                    self._by_id[cid].count += count
                    continue
                c = Cluster(cid=cid, title=title,
                            feat=featurize_one(title, frames),
                            count=count)
                self._clusters.append(c)
                self._by_id[cid] = c

    def assign(self, reports: "list[tuple[str, list[str]]]",
               counts: "list[int] | None" = None) -> "list[str]":
        """Cluster ids for a batch of parsed reports (title, frames).
        Chunks transparently when representatives + batch exceed the
        kernel's max batch."""
        if not reports:
            return []
        out: "list[str]" = []
        cap = self.kernel.max_batch
        step = max(1, cap - len(self._clusters) - 64)
        for lo in range(0, len(reports), step):
            chunk = reports[lo:lo + step]
            cc = counts[lo:lo + step] if counts is not None else None
            out.extend(self._assign_chunk(chunk, cc))
        return out

    def _assign_chunk(self, reports, counts) -> "list[str]":
        feats = self.kernel.featurize(reports)
        with self._mu:
            reps = list(self._clusters)
        nreps = len(reps)
        if reps:
            allf = np.concatenate(
                [np.stack([c.feat for c in reps]), feats])
        else:
            allf = feats
        labels = self.kernel.cluster(allf)          # device, lock-free
        comp: "dict[int, list[int]]" = {}
        for i, lab in enumerate(labels):
            comp.setdefault(int(lab), []).append(i)
        out: "list[str | None]" = [None] * len(reports)
        with self._mu:
            added_since = self._clusters[nreps:]
            for members in comp.values():
                new = [i - nreps for i in members if i >= nreps]
                if not new:
                    continue
                old = [i for i in members if i < nreps]
                if old:
                    # joins an existing cluster; if the batch bridged
                    # two historical clusters, keep both and file under
                    # the older one (id stability beats merging)
                    cl = reps[min(old)]
                else:
                    cl = self._resolve_new(reports[new[0]][0],
                                           feats[new[0]], added_since)
                for j in new:
                    cl.count += counts[j] if counts is not None else 1
                    out[j] = cl.cid
            self.assigned_total += len(reports)
        return out                                   # type: ignore

    def _resolve_new(self, title: str, feat: np.ndarray,
                     added_since: "list[Cluster]") -> Cluster:
        """Under _mu: found a cluster for a representative-free
        component, first re-checking representatives a concurrent
        assign added after our snapshot (exact same cosine metric,
        host-side — a few dot products)."""
        for c in added_since:
            if float(np.dot(feat, c.feat)) >= self.kernel.threshold:
                return c
        cid = stable_cluster_id(title)
        c = self._by_id.get(cid)
        if c is None:
            c = Cluster(cid=cid, title=title, feat=feat.copy())
            self._clusters.append(c)
            self._by_id[cid] = c
        return c
