"""Batched-bisection repro service: many crashes, one VM pool.

The legacy path bisected one crash per dedicated thread+VM-block —
repro latency scaled with crash count.  Here every crash is a
*bisection state machine* (the `repro.run_steps` generator: suspect
narrowing → call minimization → option simplification) and the
scheduler packs the currently-runnable candidate tests of ALL active
crashes into rounds over ONE shared `Oracle` pool: each round is a
`first_crasher`-style fan-out of up to `workers` mixed work units, and
state machines advance as their answers resolve.  Total wall rounds
approach ceil(total-candidates / workers) + the deepest single
machine's sequential depth, instead of the sum of every crash's serial
chain.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

from syzkaller_tpu import repro as repro_mod
from syzkaller_tpu.utils import log


@dataclass
class _Job:
    title: str
    crash_dir: str
    gen: object                       # repro.run_steps generator
    links: tuple = ()
    pending: object = None            # TestBatch | TestOne in flight
    answers: dict = field(default_factory=dict)
    sent: int = 0                     # next pending item not yet packed
    rounds: int = 0
    tests: int = 0
    phase_time: dict = field(default_factory=dict)
    started: float = field(default_factory=time.monotonic)
    result: object = None
    failed: "str | None" = None


class ReproScheduler:
    """Drives many `repro.run_steps` machines against one Oracle pool.

    `submit` is non-blocking (dedups on title); a background loop packs
    rounds while any job is active and invokes `on_done(title,
    crash_dir, result, job)` as each finishes.  Candidate answers and
    generator advancement happen on the loop thread — the only
    concurrency is the per-round worker fan-out, which reuses the
    Oracle's worker-id pinning (`_test_on`) so unit k of a round runs
    on pool machine k, exactly like `first_crasher`.
    """

    def __init__(self, oracle, table, *, quick: float = 10.0,
                 thorough: float = 300.0, with_c_repro: bool = True,
                 c_test_fn=None, on_done=None, tracer=None,
                 metrics: "dict | None" = None):
        self.oracle = repro_mod._as_oracle(oracle)
        self.table = table
        self.quick = quick
        self.thorough = thorough
        self.with_c_repro = with_c_repro
        self.c_test_fn = c_test_fn
        self.on_done = on_done
        self.tracer = tracer
        self.metrics = metrics or {}
        self._cv = threading.Condition()
        self._queue: "deque[_Job]" = deque()
        self._active: "list[_Job]" = []
        self._titles: "set[str]" = set()
        self._stopped = False
        self.stat_rounds = 0
        self.stat_tests = 0
        self.stat_jobs_done = 0
        self._thread = threading.Thread(target=self._loop,
                                        name="repro-scheduler",
                                        daemon=True)
        self._thread.start()

    # -- intake ------------------------------------------------------------

    def submit(self, crash_log: bytes, title: str, crash_dir: str,
               links: tuple = ()) -> bool:
        """Queue one crash for batched bisection; False if a job for
        this title is already queued/active or the service stopped."""
        with self._cv:
            if self._stopped or title in self._titles:
                return False
            gen = repro_mod.run_steps(
                crash_log, self.table, with_c_repro=self.with_c_repro,
                c_test_fn=self.c_test_fn, quick=self.quick,
                thorough=self.thorough)
            job = _Job(title=title, crash_dir=crash_dir, gen=gen,
                       links=tuple(links))
            self._titles.add(title)
            self._queue.append(job)
            self._cv.notify()
        return True

    @property
    def depth(self) -> int:
        with self._cv:
            return len(self._queue) + len(self._active)

    def join(self, timeout: "float | None" = None) -> bool:
        """Wait until no job is queued or active."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._queue or self._active:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._cv.wait(timeout=left if left is not None else 0.5)
        return True

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._thread.join(timeout=10.0)

    # -- the round loop ----------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._active \
                        and not self._stopped:
                    self._cv.wait(timeout=0.5)
                if self._stopped:
                    return
                while self._queue:
                    self._active.append(self._queue.popleft())
            # prime newly admitted machines to their first request
            for job in list(self._active):
                if job.pending is None and job.result is None:
                    self._advance(job, _prime=True)
            self._reap()
            units = self._gather()
            if not units:
                continue
            self._run_round(units)
            self._resolve()
            self._reap()

    def _advance(self, job: _Job, answer=None, _prime=False) -> None:
        try:
            req = next(job.gen) if _prime else job.gen.send(answer)
            job.pending, job.answers, job.sent = req, {}, 0
        except StopIteration as s:
            job.pending, job.result = None, s.value
            job.failed = None
        except Exception as e:            # a broken machine or log must
            log.logf(0, "repro job %r error: %s", job.title, e)
            job.pending, job.result = None, None
            job.failed = str(e)

    def _gather(self) -> list:
        """Pack up to `workers` units breadth-first across active jobs
        (one unit per job per sweep, so a wide TestBatch cannot starve
        the other machines)."""
        W = self.oracle.workers
        units: list = []                  # (job, item_idx, data, opts, dur)
        progressed = True
        while len(units) < W and progressed:
            progressed = False
            for job in self._active:
                if len(units) >= W:
                    break
                req = job.pending
                if req is None:
                    continue
                if isinstance(req, repro_mod.TestBatch):
                    if job.sent < len(req.items):
                        data, opts = req.items[job.sent]
                        units.append((job, job.sent, data, opts,
                                      req.duration))
                        job.sent += 1
                        progressed = True
                elif job.sent == 0:       # TestOne
                    units.append((job, 0, req.data, req.opts,
                                  req.duration))
                    job.sent = 1
                    progressed = True
        return units

    def _run_round(self, units: list) -> None:
        """One VM-pool fan-out: unit k on oracle worker k."""
        t0 = time.monotonic()
        results = self.oracle.test_many(
            [(d, o, dur) for _j, _i, d, o, dur in units])
        dt = time.monotonic() - t0
        self.stat_rounds += 1
        self.stat_tests += len(units)
        m = self.metrics.get("rounds")
        if m is not None:
            m.inc()
        m = self.metrics.get("tests")
        if m is not None:
            m.inc(len(units))
        touched = set()
        for (job, i, _d, _o, _dur), hit in zip(units, results):
            job.answers[i] = bool(hit)
            job.tests += 1
            if id(job) not in touched:
                touched.add(id(job))
                job.rounds += 1
                phase = getattr(job.pending, "phase", "") or "?"
                job.phase_time[phase] = job.phase_time.get(phase, 0.0) + dt

    def _resolve(self) -> None:
        for job in self._active:
            req = job.pending
            if req is None:
                continue
            if isinstance(req, repro_mod.TestBatch):
                ans, final = self._batch_verdict(job, req)
                if final:
                    # early-cancel: once the earliest remaining
                    # candidate is confirmed, unsent later items are
                    # never packed into a round
                    self._advance(job, ans)
            else:
                if 0 in job.answers:
                    self._advance(job, job.answers[0])

    @staticmethod
    def _batch_verdict(job: _Job, req) -> "tuple[int | None, bool]":
        """first_crasher semantics over incrementally arriving answers:
        final once the earliest crasher has no unanswered earlier item,
        or every item answered False."""
        for i in range(len(req.items)):
            r = job.answers.get(i)
            if r is True:
                return i, True
            if r is None:
                return None, False
        return None, True

    def _reap(self) -> None:
        done = [j for j in self._active if j.pending is None]
        if not done:
            return
        for job in done:
            self.stat_jobs_done += 1
            self._record_trace(job)
            m = self.metrics.get("jobs")
            if m is not None:
                out = "error" if job.failed else (
                    "found" if job.result is not None
                    and getattr(job.result, "prog", None) is not None
                    else "failed")
                m.labels(outcome=out).inc()
            if self.on_done is not None:
                try:
                    self.on_done(job.title, job.crash_dir, job.result, job)
                except Exception as e:
                    log.logf(0, "repro on_done for %r failed: %s",
                             job.title, e)
        # jobs leave _active only AFTER their artifacts callback ran,
        # so join() returning implies every completed job is persisted
        with self._cv:
            self._active = [j for j in self._active
                            if j.pending is not None]
            for job in done:
                self._titles.discard(job.title)
            self._cv.notify_all()

    def _record_trace(self, job: _Job) -> None:
        """crash → repro lineage: one span per bisection phase, linked
        back to the crash trace (which links to the admitting input)."""
        if self.tracer is None:
            return
        ctx = self.tracer.new_trace(origin=f"repro:{job.title}")
        ctx.links = list(job.links)
        for phase, t in job.phase_time.items():
            ctx.add_hop(f"repro:{phase}", t)
        self.tracer.record(
            ctx, final_hop=(f"repro:done rounds={job.rounds} "
                            f"tests={job.tests}"),
            dur=time.monotonic() - job.started)
