"""syzkaller_tpu: a TPU-native coverage-guided syscall fuzzing framework.

A ground-up rebuild of the capabilities of syzkaller (reference: an early
snapshot of google/syzkaller, see SURVEY.md) with the fuzzing hot loops --
coverage signal-diff / corpus merge / corpus minimization and
priority-table / choice-table sampling -- implemented as device-resident
JAX/XLA array programs, and the surrounding runtime (executor, IPC,
manager, VM fleet, crash intelligence) as native C++ + Python.

Layer map (mirrors reference SURVEY.md section 1):
  L1 execution   : syzkaller_tpu.ipc + syzkaller_tpu/native (C++ executor)
  L2 type system : syzkaller_tpu.sys (+ descriptions/ DSL)
  L3 core algos  : syzkaller_tpu.prog (tree logic) + syzkaller_tpu.ops (device)
  L4 fuzz engine : syzkaller_tpu.fuzzer
  L5 crash intel : syzkaller_tpu.report / .repro / .csource
  L6 machines    : syzkaller_tpu.vm
  L7 orchestrator: syzkaller_tpu.manager
  L8 federation  : syzkaller_tpu.hub
Device state    : syzkaller_tpu.models.fuzz_state (the flagship array program)
Multi-chip      : syzkaller_tpu.parallel (mesh / shardings / collectives)
"""

__version__ = "0.1.0"
