"""RPC plane: manager ↔ fuzzer (and manager ↔ hub) wire protocol.

Capability parity with the reference's net/rpc JSON codec over TCP
(syz-manager/manager.go:163-182, syz-fuzzer/fuzzer.go:116-120) and the
rpctype message shapes (rpctype/rpctype.go:8-63): Connect, Check, Poll,
NewInput, Hub.Connect, Hub.Sync. The wire format is length-free
JSON-lines: one request/response object per line.

    request:  {"id": N, "method": "Manager.Connect", "params": {...}}
    response: {"id": N, "result": {...}} | {"id": N, "error": "..."}

Binary payloads (serialized programs, coverage arrays) ride as base64 /
integer lists inside params — same spirit as the reference's JSON codec.
"""

from __future__ import annotations

import base64
import json
import socket
import socketserver
import threading
from typing import Any, Callable


def b64(data: bytes) -> str:
    return base64.b64encode(data).decode()


def unb64(s: str) -> bytes:
    return base64.b64decode(s)


class RpcError(Exception):
    pass


class RpcServer:
    """Threaded JSON-lines RPC server. Handlers: dict method -> fn(params)
    -> result dict. One thread per connection (keep-alive, many calls)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.handlers: dict[str, Callable[[dict], dict]] = {}
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        req = json.loads(line)
                        method = req.get("method", "")
                        fn = outer.handlers.get(method)
                        if fn is None:
                            resp = {"id": req.get("id"),
                                    "error": f"unknown method {method}"}
                        else:
                            resp = {"id": req.get("id"),
                                    "result": fn(req.get("params") or {})}
                    except Exception as e:  # handler bug -> error reply
                        resp = {"id": req.get("id") if isinstance(req, dict) else None,
                                "error": f"{type(e).__name__}: {e}"}
                    try:
                        self.wfile.write(json.dumps(resp).encode() + b"\n")
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError):
                        return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.addr = self._server.server_address
        self._thread: "threading.Thread | None" = None

    def register(self, method: str, fn: Callable[[dict], dict]) -> None:
        self.handlers[method] = fn

    def serve_background(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        # shutdown() blocks forever unless serve_forever is running
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()


class RpcClient:
    """Blocking JSON-lines RPC client with keep-alive reconnect."""

    def __init__(self, addr: "tuple[str, int] | str", timeout: float = 60.0):
        if isinstance(addr, str):
            host, _, port = addr.rpartition(":")
            addr = (host or "127.0.0.1", int(port))
        self.addr = addr
        self.timeout = timeout
        self._sock: "socket.socket | None" = None
        self._file = None
        self._id = 0
        self._mu = threading.Lock()

    def _connect(self):
        s = socket.create_connection(self.addr, timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        self._sock = s
        self._file = s.makefile("rwb")

    def call(self, method: str, params: "dict | None" = None) -> dict:
        with self._mu:
            for attempt in (0, 1):
                if self._sock is None:
                    self._connect()
                try:
                    self._id += 1
                    req = {"id": self._id, "method": method,
                           "params": params or {}}
                    self._file.write(json.dumps(req).encode() + b"\n")
                    self._file.flush()
                    line = self._file.readline()
                    if not line:
                        raise ConnectionError("server closed connection")
                    resp = json.loads(line)
                    if resp.get("error"):
                        raise RpcError(resp["error"])
                    return resp.get("result") or {}
                except (OSError, ConnectionError, json.JSONDecodeError):
                    self.close_socket()
                    if attempt == 1:
                        raise
            raise RpcError("unreachable")

    def close_socket(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._file = None

    def close(self) -> None:
        with self._mu:
            self.close_socket()
