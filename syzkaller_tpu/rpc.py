"""RPC plane: manager ↔ fuzzer (and manager ↔ hub) wire protocol.

Capability parity with the reference's net/rpc JSON codec over TCP
(syz-manager/manager.go:163-182, syz-fuzzer/fuzzer.go:116-120) and the
rpctype message shapes (rpctype/rpctype.go:8-63): Connect, Check, Poll,
NewInput, Hub.Connect, Hub.Sync. The wire format is length-free
JSON-lines: one request/response object per line.

    request:  {"id": N, "method": "Manager.Connect", "params": {...}}
    response: {"id": N, "result": {...}} | {"id": N, "error": "..."}

Binary payloads (serialized programs, coverage arrays) ride as base64 /
integer lists inside params — same spirit as the reference's JSON codec.
"""

from __future__ import annotations

import base64
import itertools
import json
import os
import random
import socket
import socketserver
import threading
import time
from typing import Any, Callable


def b64(data: bytes) -> str:
    return base64.b64encode(data).decode()


def unb64(s: str) -> bytes:
    return base64.b64decode(s)


class RpcError(Exception):
    pass


class RpcServer:
    """Threaded JSON-lines RPC server. Handlers: dict method -> fn(params)
    -> result dict. One thread per connection (keep-alive, many calls).

    `observer`, when set, is called as observer(method, seconds, params)
    after every handled request — the telemetry tap for per-method
    request counters/latency histograms and RPC trace spans (the
    `trace` param rides inside `params` untouched)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.handlers: dict[str, Callable[[dict], dict]] = {}
        self.observer: "Callable[[str, float, dict], None] | None" = None
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    method = ""
                    params: dict = {}
                    t0 = time.monotonic()
                    try:
                        req = json.loads(line)
                        method = req.get("method", "")
                        params = req.get("params") or {}
                        fn = outer.handlers.get(method)
                        if fn is None:
                            resp = {"id": req.get("id"),
                                    "error": f"unknown method {method}"}
                        else:
                            resp = {"id": req.get("id"),
                                    "result": fn(params)}
                    except Exception as e:  # handler bug -> error reply
                        resp = {"id": req.get("id") if isinstance(req, dict) else None,
                                "error": f"{type(e).__name__}: {e}"}
                    obs = outer.observer
                    if obs is not None:
                        try:
                            obs(method, time.monotonic() - t0, params)
                        except Exception:
                            pass   # telemetry must never break the wire
                    try:
                        self.wfile.write(json.dumps(resp).encode() + b"\n")
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError):
                        return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self.addr = self._server.server_address
        self._thread: "threading.Thread | None" = None

    def register(self, method: str, fn: Callable[[dict], dict]) -> None:
        self.handlers[method] = fn

    def serve_background(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        # shutdown() blocks forever unless serve_forever is running
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()


class RpcClient:
    """Blocking JSON-lines RPC client with keep-alive reconnect and a
    fault envelope: a mid-call socket break reconnects and retries with
    jittered exponential backoff instead of raising straight through
    (the old behavior killed the fuzzer proc loop on any transient
    manager restart).  Every call carries a per-call idempotency key
    (`idem` param, like the injected `trace`) so the server can dedup a
    replayed side-effecting request — the manager does this for
    NewInput.  Retries are counted into `retry_counter` (a telemetry
    Counter: `syz_rpc_retries_total`) when provided."""

    RETRIES = 4                   # attempts per call (1 + 3 retries)
    BACKOFF = 0.05                # base backoff, full jitter
    MAX_BACKOFF = 1.0

    def __init__(self, addr: "tuple[str, int] | str", timeout: float = 60.0,
                 retries: "int | None" = None, retry_counter=None):
        if isinstance(addr, str):
            host, _, port = addr.rpartition(":")
            addr = (host or "127.0.0.1", int(port))
        self.addr = addr
        self.timeout = timeout
        self.retries = self.RETRIES if retries is None else max(1, retries)
        self.retry_counter = retry_counter
        self._sock: "socket.socket | None" = None
        self._file = None
        self._id = 0
        self._mu = threading.Lock()
        # idempotency-key prefix: unique per client process+object so a
        # replayed request is recognizable server-side across reconnects
        self._client_id = f"{os.getpid():x}-{id(self) & 0xffffff:x}"
        self._seq = itertools.count(1)

    def _connect_unlocked(self) -> None:
        """Establish the TCP connection OUTSIDE `_mu`: connect can block
        for the full timeout, and holding the call mutex across it would
        stall every other caller on this client for the duration
        (syz-vet lock pass, P0 blocking-under-lock).  The fresh socket
        is installed under the lock only if no concurrent caller won the
        race; the loser's socket is discarded."""
        s = socket.create_connection(self.addr, timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        with self._mu:
            if self._sock is None:
                self._sock = s
                self._file = s.makefile("rwb")
                return
        s.close()

    def call(self, method: str, params: "dict | None" = None,
             span=None, idempotent: bool = True) -> dict:
        """One RPC round trip.  `span` (a telemetry.trace.SpanContext)
        is injected into params as the `trace` field and gets an
        `rpc:<method>` hop with the client-observed duration — this is
        how trace context propagates Connect → Poll → NewInput.

        Transport faults (socket break, server restart mid-call)
        reconnect and retry up to `retries` times with full-jitter
        exponential backoff; the SAME `idem` key rides every attempt so
        the server can dedup a request whose first reply was lost.
        `idempotent=False` disables the retry (first transport fault
        raises) for callers whose replay the server cannot dedup.
        Server-side errors (RpcError) never retry — the server already
        processed the request."""
        params = dict(params or {})
        params["idem"] = f"{self._client_id}:{next(self._seq)}"
        if span is not None:
            span.sent_at = time.time()
            params["trace"] = span.to_wire()
        t0 = time.monotonic()
        try:
            return self._call_retrying(method, params, idempotent)
        finally:
            if span is not None:
                span.add_hop(f"rpc:{method}", time.monotonic() - t0)

    def _call_retrying(self, method: str, params: dict,
                       idempotent: bool) -> dict:
        attempts = self.retries if idempotent else 1
        for attempt in range(attempts):
            try:
                return self._call_once(method, params)
            except (OSError, ConnectionError, json.JSONDecodeError):
                if attempt + 1 >= attempts:
                    raise
                if self.retry_counter is not None:
                    try:
                        self.retry_counter.inc()
                    except Exception:
                        pass     # telemetry must never break the wire
                # full-jitter exponential backoff: desynchronizes a
                # fleet of fuzzers re-attacking a restarting manager
                cap = min(self.MAX_BACKOFF, self.BACKOFF * (2 ** attempt))
                time.sleep(random.uniform(0, cap))
        raise RpcError("unreachable")

    def _call_once(self, method: str, params: dict) -> dict:
        if self._sock is None:
            self._connect_unlocked()
        with self._mu:
            if self._sock is None:
                raise ConnectionError("connection raced with close()")
            try:
                self._id += 1
                req = {"id": self._id, "method": method,
                       "params": params or {}}
                self._file.write(json.dumps(req).encode() + b"\n")
                self._file.flush()
                line = self._file.readline()
                if not line:
                    raise ConnectionError("server closed connection")
                resp = json.loads(line)
                if resp.get("error"):
                    raise RpcError(resp["error"])
                return resp.get("result") or {}
            except (OSError, ConnectionError, json.JSONDecodeError):
                self.close_socket()
                raise

    def close_socket(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._file = None

    def close(self) -> None:
        with self._mu:
            self.close_socket()
