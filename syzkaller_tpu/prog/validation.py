"""Program invariant checking (debug builds / tests).

Capability parity with reference prog/validation.go:17-30: arg shape vs
type, bidirectional uses-links, result refs only point backward, page
ranges, fixed-size data lengths.
"""

from __future__ import annotations

from syzkaller_tpu.prog import model as M
from syzkaller_tpu.sys import types as T


class ValidationError(Exception):
    pass


def validate(p: M.Prog) -> None:
    seen: set[int] = set()  # ids of args defined so far (for backward refs)
    for ci, c in enumerate(p.calls):
        if len(c.args) != len(c.meta.args):
            raise ValidationError(f"call {ci} {c.meta.name}: arg count")
        for a, t in zip(c.args, c.meta.args):
            _validate_arg(a, t, ci, seen)
        if c.ret is not None:
            if not isinstance(c.ret, M.ReturnArg):
                raise ValidationError(f"call {ci}: ret is {type(c.ret)}")
            _check_uses(c.ret, ci)
            seen.add(id(c.ret))
        elif c.meta.ret is not None:
            raise ValidationError(f"call {ci} {c.meta.name}: missing ret")


def _check_uses(a: M.Arg, ci: int) -> None:
    for u in a.uses:
        if not isinstance(u, M.ResultArg):
            raise ValidationError(f"call {ci}: non-result arg in uses")
        if u.res is not a:
            raise ValidationError(f"call {ci}: uses link not bidirectional")


def _validate_arg(a: M.Arg, t: T.Type, ci: int, seen: set[int]) -> None:
    if a.typ is not t and a.typ.name != t.name:
        # Union options / ptr elems share declarations; require same object
        # except for directional struct copies, where name equality holds.
        raise ValidationError(
            f"call {ci}: arg type {a.typ.name} != decl {t.name}")
    _check_uses(a, ci)
    if isinstance(a, M.ResultArg):
        if a.res is not None and id(a.res) not in seen:
            raise ValidationError(f"call {ci}: forward/dangling result ref")
    elif isinstance(a, M.PointerArg):
        if a.page < 0 or a.page + max(a.npages, 1) > M.MAX_PAGES:
            raise ValidationError(f"call {ci}: page {a.page} out of range")
        if a.res is not None:
            if not isinstance(t, T.PtrType):
                raise ValidationError(f"call {ci}: pointee under {t.name}")
            elem = t.elem if t.elem is not None else a.res.typ
            _validate_arg(a.res, elem, ci, seen)
    elif isinstance(a, M.DataArg):
        if isinstance(t, T.BufferType):
            fs = t.fixed_size()
            if fs is not None and len(a.data) != fs:
                raise ValidationError(
                    f"call {ci}: fixed buffer {t.name} len {len(a.data)} != {fs}")
    elif isinstance(a, M.GroupArg):
        if isinstance(t, T.StructType):
            if len(a.inner) != len(t.fields):
                raise ValidationError(f"call {ci}: struct {t.name} field count")
            for x, f in zip(a.inner, t.fields):
                _validate_arg(x, f, ci, seen)
        elif isinstance(t, T.ArrayType):
            if t.kind == T.ArrayKind.RANGE_LEN and t.range_begin == t.range_end \
                    and len(a.inner) != t.range_begin:
                raise ValidationError(f"call {ci}: fixed array {t.name} count")
            for x in a.inner:
                _validate_arg(x, t.elem, ci, seen)
        else:
            raise ValidationError(f"call {ci}: group under {t.name}")
    elif isinstance(a, M.UnionArg):
        if not isinstance(t, T.UnionType):
            raise ValidationError(f"call {ci}: union under {t.name}")
        if all(o is not a.option_typ and o.field_name() != a.option_typ.field_name()
               for o in t.options):
            raise ValidationError(f"call {ci}: unknown union option")
        _validate_arg(a.option, a.option_typ, ci, seen)
    seen.add(id(a))
