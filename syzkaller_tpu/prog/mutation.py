"""Program mutation and minimization.

Capability parity with reference prog/mutation.go: corpus splice (:17-22),
weighted insert-call/mutate-arg/remove-call loop (:26-208), per-type arg
mutation (:71-180), the byte/word `mutateData` operator set (:505-662),
`Minimize` with call removal + per-arg recursive simplification and a
tried-paths memo (:223-405), and `TrimAfter` (:407).
"""

from __future__ import annotations

from typing import Callable

from syzkaller_tpu.prog import analysis
from syzkaller_tpu.prog import encoding
from syzkaller_tpu.prog import model as M
from syzkaller_tpu.prog.analysis import State
from syzkaller_tpu.prog.rand import Gen, Rand
from syzkaller_tpu.sys import types as T
from syzkaller_tpu.sys.table import SyscallTable


def mutate(p: M.Prog, rand: Rand, table: SyscallTable, ncalls: int = 30,
           choice_table=None, corpus: "list[M.Prog] | None" = None,
           pid: int = 0) -> None:
    """Mutate p in place.  The original must be cloned by the caller if it
    needs preserving (the fuzzer clones corpus programs before mutating,
    ref syz-fuzzer/fuzzer.go:224-229)."""
    r = rand
    first = True
    while first or r.one_of(2):
        first = False
        if corpus and r.one_of(100):
            _splice(p, rand, corpus, ncalls)
            continue
        which = r.choose_weighted([20, 10, 1])
        if which == 0 and len(p.calls) < ncalls:
            _insert_call(p, rand, table, choice_table, pid)
        elif which == 1 and p.calls:
            _mutate_arg(p, rand, table, choice_table, pid)
        elif which == 2 and len(p.calls) > 1:
            M.remove_call(p, r.intn(len(p.calls)))
    while len(p.calls) > ncalls:
        M.remove_call(p, len(p.calls) - 1)
    if not p.calls:
        # Never leave an empty program behind.
        state = State(table)
        gen = Gen(rand, state, table, choice_table, pid)
        p.calls.extend(gen.generate_call(-1))


def mutate_sequence(p: M.Prog, rand: Rand, table: SyscallTable, machine,
                    ncalls: int = 30, choice_table=None,
                    pid: int = 0) -> None:
    """State-machine sequence mutation: mutate p while RESPECTING the
    campaign's protocol order.  `machine` is duck-typed (campaign.
    ProtocolMachine): walk(calls) -> Walk, enabled_transitions(state),
    build_call(gen, transition).

    Three protocol-preserving operators, weighted like the flat
    mutator's insert/mutate/remove split:

      * extend — append a call that takes an enabled transition from
        the program's CURRENT final protocol state (deepens the
        sequence: handshake grows toward teardown instead of emitting
        another uncorrelated SYN);
      * mutate-arg — per-arg mutation on one call, then REPAIR: if the
        mutation knocked the call out of its transition (flag word
        changed), the protocol suffix no longer replays, so trim the
        tail back to the longest prefix whose walk is unchanged;
      * trim — drop the protocol tail (the teardown half of a
        sequence), letting the extender regrow a different suffix.

    Non-protocol calls interleaved in the program are left to the flat
    arg mutator — the machine's classify() ignores them, so they never
    perturb the walk."""
    r = rand
    base_walk = machine.walk(p.calls)
    first = True
    while first or r.one_of(2):
        first = False
        which = r.choose_weighted([20, 10, 2])
        if which == 0 and len(p.calls) < ncalls:
            # extend along the machine from the current final state
            nexts = machine.enabled_transitions(base_walk.final_state)
            if not nexts:
                # terminal protocol state: restart the protocol tail
                nexts = machine.enabled_transitions(machine.initial)
            if not nexts:
                continue
            t = nexts[r.intn(len(nexts))]
            state = State(table)
            for c in p.calls:
                state.analyze_call(c)
            gen = Gen(rand, state, table, choice_table, pid)
            try:
                p.calls.extend(machine.build_call(gen, t))
            except Exception:
                continue
            base_walk = machine.walk(p.calls)
        elif which == 1 and p.calls:
            before = machine.walk(p.calls).transitions
            _mutate_arg(p, rand, table, choice_table, pid)
            after = machine.walk(p.calls).transitions
            if after[: len(before)] != before[: len(after)] or \
                    len(after) < len(before):
                # the mutation broke a transition mid-sequence: keep it
                # (the flag word itself is fuzz-worthy) but trim the
                # now-unreachable protocol tail so order stays honest
                _trim_to_prefix(p, machine, len(after))
            base_walk = machine.walk(p.calls)
        elif which == 2 and len(base_walk.transitions) > 1:
            keep = r.intn(len(base_walk.transitions))
            _trim_to_prefix(p, machine, keep)
            base_walk = machine.walk(p.calls)
    while len(p.calls) > ncalls:
        M.remove_call(p, len(p.calls) - 1)
    if not p.calls:
        state = State(table)
        gen = Gen(rand, state, table, choice_table, pid)
        p.calls.extend(gen.generate_call(-1))


def _trim_to_prefix(p: M.Prog, machine, keep_transitions: int) -> None:
    """Remove trailing calls until the walk takes at most
    `keep_transitions` transitions (protocol-order-preserving trim:
    only whole tail calls go, so the remaining prefix replays
    identically)."""
    while len(p.calls) > 1 and \
            len(machine.walk(p.calls).transitions) > keep_transitions:
        M.remove_call(p, len(p.calls) - 1)


def _splice(p: M.Prog, rand: Rand, corpus: list[M.Prog], ncalls: int) -> None:
    other = M.clone_prog(corpus[rand.intn(len(corpus))])
    idx = rand.intn(len(p.calls) + 1)
    p.calls[idx:idx] = other.calls
    while len(p.calls) > ncalls:
        M.remove_call(p, len(p.calls) - 1)


def _insert_call(p: M.Prog, rand: Rand, table: SyscallTable,
                 choice_table, pid: int) -> None:
    idx = rand.biased_rand(len(p.calls) + 1, 5)  # bias toward the tail
    state = State(table)
    for c in p.calls[:idx]:
        state.analyze_call(c)
    gen = Gen(rand, state, table, choice_table, pid)
    prev = p.calls[idx - 1].meta.id if idx > 0 else -1
    M.insert_before(p, idx, gen.generate_call(prev))


def _mutable_args(c: M.Call) -> list[M.Arg]:
    """Args worth pointing the mutator at (ref mutationArgs
    prog/mutation.go:422-460): skip immutable consts/lens/pads and
    zero-information nodes."""
    out: list[M.Arg] = []

    def visit(a: M.Arg, _p):
        t = a.typ
        if T.is_pad(t) or isinstance(t, (T.ConstType, T.LenType)):
            return
        if isinstance(a, (M.ReturnArg, M.PageSizeArg)):
            return
        if isinstance(a, M.GroupArg) and not isinstance(t, T.ArrayType):
            return  # mutate struct fields individually, not the struct
        if t.dir == T.Dir.OUT and not isinstance(t, T.ResourceType):
            return
        out.append(a)

    M.foreach_arg(c, visit)
    return out


def _mutate_arg(p: M.Prog, rand: Rand, table: SyscallTable,
                choice_table, pid: int) -> None:
    r = rand
    for _ in range(10):
        ci = r.intn(len(p.calls))
        c = p.calls[ci]
        cands = _mutable_args(c)
        if cands:
            break
    else:
        return
    a = cands[r.intn(len(cands))]
    state = State(table)
    for cc in p.calls[:ci]:
        state.analyze_call(cc)
    gen = Gen(rand, state, table, choice_table, pid)
    extra = _mutate_one(a, c, gen)
    if extra:
        M.insert_before(p, ci, extra)
    analysis.assign_sizes_call(c)
    analysis.sanitize_call(c)


def _mutate_one(a: M.Arg, c: M.Call, gen: Gen) -> list[M.Call]:
    """Mutate one arg node; returns prerequisite calls to insert before c
    (ref per-type mutation prog/mutation.go:71-180)."""
    r = gen.r
    t = a.typ
    if isinstance(a, M.ConstArg):
        if isinstance(t, T.FlagsType):
            a.val = gen.flags_value(t.vals)
        elif isinstance(t, T.ProcType):
            a.val = r.intn(max(1, t.values_per_proc))
        elif isinstance(t, T.IntType) and t.kind == T.IntKind.RANGE:
            a.val = gen._signed_range(t)
        else:
            which = r.intn(3)
            if which == 0:
                a.val = gen.rand_int(getattr(t, "type_size", 8))
            elif which == 1:
                delta = r.intn(16) + 1
                a.val = (a.val + (delta if r.bin() else -delta)) % (1 << 64)
            else:
                a.val ^= 1 << r.intn(64)
        return []
    if isinstance(a, M.DataArg):
        if getattr(t, "kind", None) == T.BufferKind.TEXT:
            # instruction-aware mutation (ifuzz, ref ifuzz/mutate path)
            from syzkaller_tpu import ifuzz as IF
            from syzkaller_tpu.prog.rand import text_mode
            mode = text_mode(t)
            if mode is None:
                a.data = IF.mutate_arm64(r, a.data)
            else:
                a.data = IF.mutate(r, a.data, mode)
            return []
        data = bytearray(a.data)
        mutate_data(r, data, t)
        a.data = bytes(data)
        return []
    if isinstance(a, M.ResultArg):
        na, calls = gen.resource_arg(t)  # type: ignore[arg-type]
        M.replace_arg(c, a, na)
        return calls
    if isinstance(a, M.UnionArg):
        ut = t
        assert isinstance(ut, T.UnionType)
        opt = ut.options[r.intn(len(ut.options))]
        na, calls = gen.generate_arg(opt)
        M.replace_arg(c, a, M.UnionArg(ut, na, opt))
        return calls
    if isinstance(a, M.PointerArg):
        if a.npages:  # vma
            page, calls = gen.alloc_vma(a.npages)
            a.page, a.offset = page, 0
            return calls
        na, calls = gen.generate_arg(t)
        M.replace_arg(c, a, na)
        return calls
    if isinstance(a, M.GroupArg) and isinstance(t, T.ArrayType):
        calls: list[M.Call] = []
        lo, hi = 0, 10
        if t.kind == T.ArrayKind.RANGE_LEN:
            lo, hi = t.range_begin, t.range_end
        if lo == hi and a.inner:  # fixed count: mutate an element instead
            i = r.intn(len(a.inner))
            return _mutate_one(a.inner[i], c, gen)
        if a.inner and len(a.inner) > lo and r.bin():
            i = r.intn(len(a.inner))
            M._detach_subtree(a.inner[i])
            del a.inner[i]
        elif len(a.inner) < hi:
            na, calls = gen.generate_arg(t.elem)
            a.inner.insert(r.intn(len(a.inner) + 1), na)
        return calls
    # Fallback: regenerate wholesale.
    na, calls = gen.generate_arg(t)
    M.replace_arg(c, a, na)
    return calls


# ---------------------------------------------------------------------------
# Buffer data mutation (ref mutateData prog/mutation.go:505-662).


def mutate_data(r: Rand, data: bytearray, t: "T.Type | None" = None) -> None:
    retry = True
    while retry or r.one_of(2):
        retry = False
        if not data:
            data.extend(r.bytes(r.intn(16) + 1))
            continue
        op = r.intn(10)
        i = r.intn(len(data))
        if op == 0:    # flip bit
            data[i] ^= 1 << r.intn(8)
        elif op == 1:  # random byte
            data[i] = r.intn(256)
        elif op == 2:  # special byte
            data[i] = (0, 0xFF, 0x7F, 0x80)[r.intn(4)]
        elif op == 3:  # add/sub small delta on a byte
            data[i] = (data[i] + r.intn(35) - 17) % 256
        elif op == 4 and len(data) >= 2:  # swap two bytes
            j = r.intn(len(data))
            data[i], data[j] = data[j], data[i]
        elif op == 5:  # add/sub on a word/dword/qword (LE)
            w = (2, 4, 8)[r.intn(3)]
            if i + w <= len(data):
                v = int.from_bytes(data[i:i + w], "little")
                v = (v + r.intn(35) - 17) % (1 << (8 * w))
                data[i:i + w] = v.to_bytes(w, "little")
        elif op == 6:  # insert random bytes
            ins = r.bytes(r.intn(8) + 1)
            data[i:i] = ins
        elif op == 7 and len(data) > 1:  # remove a span
            n = r.intn(len(data) - 1) + 1
            del data[i:i + n]
        elif op == 8:  # duplicate a span
            n = r.intn(min(len(data) - i, 16)) + 1
            data[i:i] = data[i:i + n]
        elif op == 9:  # append
            data.extend(r.bytes(r.intn(16) + 1))
        # Respect fixed-size buffers: restore length.
        if isinstance(t, T.BufferType):
            fs = t.fixed_size()
            if fs is not None:
                if len(data) > fs:
                    del data[fs:]
                else:
                    data.extend(bytes(fs - len(data)))


# ---------------------------------------------------------------------------
# Minimization (ref Minimize prog/mutation.go:223-405).

Pred = Callable[[M.Prog, int], bool]


def minimize(p: M.Prog, call_index: int, pred: Pred,
             crash_mode: bool = False) -> tuple[M.Prog, int]:
    """Shrink p while pred(p, call_index) stays true.  pred re-executes the
    candidate (dozens of kernel round-trips — ref fuzzer.go:421-435); the
    tried-paths memo keeps the number of attempts linear-ish.
    call_index == -1 (crash mode, ref repro.go:193-200): no call is
    pinned — any call may go as long as the predicate holds.

    Callback driver over `minimize_steps` — schedulers that batch many
    minimizations across a shared execution pool drive the generator
    directly."""
    gen = minimize_steps(p, call_index, crash_mode)
    try:
        q, ci = next(gen)
        while True:
            q, ci = gen.send(pred(q, ci))
    except StopIteration as s:
        return s.value


def minimize_steps(p: M.Prog, call_index: int, crash_mode: bool = False):
    """Generator form of `minimize`: yields candidate (prog,
    call_index) pairs, receives via send() whether the predicate held,
    and returns the final (prog, call_index) as StopIteration.value.
    The inversion lets a repro scheduler interleave MANY bisections'
    predicate executions into shared VM-pool rounds instead of blocking
    one thread per minimization."""
    p = M.clone_prog(p)
    # 1. Call removal, from the end (later calls can't be depended on).
    i = len(p.calls) - 1
    while i >= 0:
        if i != call_index and len(p.calls) > 1:
            q = M.clone_prog(p)
            M.remove_call(q, i)
            ni = call_index - 1 if 0 <= i < call_index else call_index
            if (yield q, ni):
                p, call_index = q, ni
        i -= 1
    # 2. Per-arg simplification on every remaining call.  The tried memo
    # is cleared whenever a simplification lands (the tree changed, so
    # positional keys enumerated against the old tree are stale and must
    # not mask retries); a simplification that leaves the tree
    # byte-identical is skipped before it burns a pred execution and its
    # key stays memoized, so the restart cannot loop forever.
    tried: set[tuple] = set()
    progress = True
    while progress:
        progress = False
        content = encoding.serialize(p)
        for ci in range(len(p.calls)):
            # Paths are enumerated against the current p; as soon as a
            # simplification lands, restart enumeration — the old paths
            # are stale against the new tree.
            for path, simplify in _simplifications(p.calls[ci]):
                key = (ci, path, simplify.__name__)
                if key in tried:
                    continue
                tried.add(key)
                q = M.clone_prog(p)
                if not simplify(q.calls[ci], _arg_at(q.calls[ci], path)):
                    continue
                analysis.assign_sizes_call(q.calls[ci])
                if encoding.serialize(q) == content:
                    continue  # no-op simplification: don't burn a pred exec
                if (yield q, call_index):
                    p = q
                    progress = True
                    tried.clear()
                    break
            if progress:
                break
    return p, call_index


def _arg_paths(c: M.Call):
    """Yield (path, arg) for every node; path = child-index tuple."""

    def rec(a: M.Arg, path: tuple):
        yield path, a
        if isinstance(a, M.PointerArg) and a.res is not None:
            yield from rec(a.res, path + (0,))
        elif isinstance(a, M.GroupArg):
            for i, x in enumerate(a.inner):
                yield from rec(x, path + (i,))
        elif isinstance(a, M.UnionArg):
            yield from rec(a.option, path + (0,))

    for i, a in enumerate(c.args):
        yield from rec(a, (i,))


def _arg_at(c: M.Call, path: tuple) -> M.Arg:
    a: M.Arg = c.args[path[0]]
    for idx in path[1:]:
        if isinstance(a, M.PointerArg):
            a = a.res  # type: ignore[assignment]
        elif isinstance(a, M.GroupArg):
            a = a.inner[idx]
        elif isinstance(a, M.UnionArg):
            a = a.option
    return a


def _simplify_default(c: M.Call, a: M.Arg) -> bool:
    if isinstance(a, (M.ReturnArg, M.PageSizeArg)):
        return False
    if isinstance(a, M.ConstArg) and a.val == a.typ.default():
        return False
    if isinstance(a, M.PointerArg) and a.is_null:
        return False
    M.replace_arg(c, a, M.default_arg(a.typ))
    return True


def _simplify_halve_data(c: M.Call, a: M.Arg) -> bool:
    if not isinstance(a, M.DataArg) or len(a.data) <= 1:
        return False
    if isinstance(a.typ, T.BufferType) and a.typ.fixed_size() is not None:
        return False
    a.data = a.data[: len(a.data) // 2]
    return True


def _simplify_halve_array(c: M.Call, a: M.Arg) -> bool:
    if not isinstance(a, M.GroupArg) or not isinstance(a.typ, T.ArrayType):
        return False
    t = a.typ
    lo = t.range_begin if t.kind == T.ArrayKind.RANGE_LEN else 0
    if len(a.inner) <= max(lo, 1) - (0 if lo else 1) or len(a.inner) <= lo:
        return False
    keep = max(lo, len(a.inner) // 2)
    if keep >= len(a.inner):
        return False
    for x in a.inner[keep:]:
        M._detach_subtree(x)
    del a.inner[keep:]
    return True


def _simplifications(c: M.Call):
    for path, a in list(_arg_paths(c)):
        for fn in (_simplify_default, _simplify_halve_data, _simplify_halve_array):
            yield path, fn


def trim_after(p: M.Prog, idx: int) -> None:
    """Drop all calls after idx (ref TrimAfter prog/mutation.go:407)."""
    for i in range(len(p.calls) - 1, idx, -1):
        M.remove_call(p, i)
