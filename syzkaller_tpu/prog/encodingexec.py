"""Flat exec bytecode: the wire format between fuzzer and executor.

Capability parity with reference prog/encodingexec.go:15-129 (the
copyin/call/copyout uint64 instruction stream + physical addressing).
The format here is this framework's own — the native executor
(native/executor.cc) implements the identical decoder, and
tests/test_exec roundtrips golden byte sequences against it.

All words are uint64 little-endian:

    instr  := COPYIN addr arg
            | COPYOUT result_idx addr size
            | CALL nr result_idx nargs arg*
            | EOF
    arg    := ARG_CONST size value          (value pre-encoded: BE types
                                             are byte-swapped here)
            | ARG_RESULT size result_idx op_div op_add
            | ARG_DATA size data_word*      (ceil(size/8) words)

    EOF = 2^64-1, COPYIN = 2^64-2, COPYOUT = 2^64-3; any smaller first
    word starts a CALL.  result_idx of NO_RESULT (2^64-1) means the
    call's return value is unused.  Addresses are physical: DATA_OFFSET +
    page*PAGE_SIZE + offset (ref physicalAddr encodingexec.go:118-129).
"""

from __future__ import annotations

import struct

from syzkaller_tpu.prog import model as M
from syzkaller_tpu.sys import types as T

INSTR_EOF = (1 << 64) - 1
INSTR_COPYIN = (1 << 64) - 2
INSTR_COPYOUT = (1 << 64) - 3
ARG_CONST = 0
ARG_RESULT = 1
ARG_DATA = 2
NO_RESULT = (1 << 64) - 1


class ExecEncodeError(Exception):
    pass


def physical_addr(a: M.PointerArg) -> int:
    return M.DATA_OFFSET + a.address()


def _encode_scalar(a: "M.ConstArg | M.ResultArg", pid: int) -> int:
    """Scalar value as the executor should write it to memory: per-proc
    biasing applied and big-endian types byte-swapped within their width
    (ref prog/prog.go:71-103)."""
    if isinstance(a, M.ConstArg):
        v = a.value(pid)
    else:
        v = a.val
    t = a.typ
    size = getattr(t, "type_size", 8)
    v &= (1 << (8 * size)) - 1
    if getattr(t, "big_endian", False):
        v = int.from_bytes(v.to_bytes(size, "little"), "big")
    return v


def serialize_for_exec(p: M.Prog, pid: int = 0) -> bytes:
    w: list[int] = []
    result_idx: dict[int, int] = {}

    def idx_of(a: M.Arg) -> int:
        key = id(a)
        if key not in result_idx:
            result_idx[key] = len(result_idx)
        return result_idx[key]

    def emit_arg(a: M.Arg) -> None:
        if isinstance(a, M.ConstArg):
            w.extend([ARG_CONST, a.size(), _encode_scalar(a, pid)])
        elif isinstance(a, M.ResultArg):
            if a.res is None:
                w.extend([ARG_CONST, a.size(), _encode_scalar(a, pid)])
            else:
                w.extend([ARG_RESULT, a.size(), idx_of(a.res),
                          a.op_div, a.op_add])
        elif isinstance(a, M.PointerArg):
            w.extend([ARG_CONST, 8, physical_addr(a) if not a.is_null else 0])
        elif isinstance(a, M.PageSizeArg):
            w.extend([ARG_CONST, a.size() if not isinstance(a.typ, T.LenType)
                      else a.typ.size(), a.npages * M.PAGE_SIZE])
        elif isinstance(a, M.DataArg):
            n = len(a.data)
            w.extend([ARG_DATA, n])
            pad = a.data + b"\x00" * (-n % 8)
            for i in range(0, len(pad), 8):
                w.append(int.from_bytes(pad[i:i + 8], "little"))
        else:
            raise ExecEncodeError(f"cannot emit {type(a)} as call arg")

    def emit_copyin(a: M.Arg, addr: int) -> None:
        """Copy the pointee subtree into the data window, leaf by leaf."""
        if isinstance(a, M.GroupArg):
            off = 0
            for x in a.inner:
                emit_copyin(x, addr + off)
                off += x.size()
            return
        if isinstance(a, M.UnionArg):
            emit_copyin(a.option, addr)
            return
        if a.typ.dir == T.Dir.OUT and isinstance(a, M.DataArg):
            return  # kernel writes it; skip the copyin
        if isinstance(a, M.DataArg) and not a.data:
            return
        w.append(INSTR_COPYIN)
        w.append(addr)
        emit_arg(a)
        if isinstance(a, M.PointerArg) and a.res is not None:
            emit_copyin(a.res, physical_addr(a))

    def emit_copyout(a: M.Arg, addr: int) -> None:
        """COPYOUT for every used out-resource in the pointee (so later
        ARG_RESULT refs see kernel-written ids)."""
        if isinstance(a, M.GroupArg):
            off = 0
            for x in a.inner:
                emit_copyout(x, addr + off)
                off += x.size()
            return
        if isinstance(a, M.UnionArg):
            emit_copyout(a.option, addr)
            return
        if isinstance(a, M.PointerArg) and a.res is not None:
            emit_copyout(a.res, physical_addr(a))
            return
        if isinstance(a, M.ResultArg) and a.uses:
            w.extend([INSTR_COPYOUT, idx_of(a), addr, a.size()])

    for c in p.calls:
        for a in c.args:
            if isinstance(a, M.PointerArg) and a.res is not None:
                emit_copyin(a.res, physical_addr(a))
        ridx = idx_of(c.ret) if (c.ret is not None and c.ret.uses) else NO_RESULT
        w.append(c.meta.nr)
        w.append(ridx)
        w.append(len(c.args))
        for a in c.args:
            emit_arg(a)
        for a in c.args:
            if isinstance(a, M.PointerArg) and a.res is not None:
                emit_copyout(a.res, physical_addr(a))
    w.append(INSTR_EOF)
    try:
        return struct.pack(f"<{len(w)}Q", *w)
    except struct.error as e:
        raise ExecEncodeError(str(e)) from e
