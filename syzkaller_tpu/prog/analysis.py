"""Program state analysis: prefix replay, len-field solving, call sanitizing.

Capability parity with reference prog/analysis.go: the `state` struct
(pages/resources/files/strings, :21-27), `analyze` prefix replay (:30-39),
mmap/munmap page accounting (:70-113), the `assignSizes` length-field
solver (:173-214), and `sanitizeCall` safety rewrites (:216-282).
"""

from __future__ import annotations

from syzkaller_tpu.prog import model as M
from syzkaller_tpu.sys import types as T
from syzkaller_tpu.sys.table import SyscallTable


class State:
    """Accumulated state of a program prefix: which resources exist, which
    files/strings were used, and which pages of the data window are mapped."""

    def __init__(self, table: SyscallTable):
        self.table = table
        # resource kind-name -> list of args that produce a value of it
        self.resources: dict[str, list[M.Arg]] = {}
        self.files: set[bytes] = set()
        self.strings: set[bytes] = set()
        self.pages = [False] * M.MAX_PAGES

    # -- page accounting ----------------------------------------------------

    def mark_pages(self, page: int, npages: int, mapped: bool) -> None:
        for i in range(page, min(page + npages, M.MAX_PAGES)):
            self.pages[i] = mapped

    def alloc_pages(self, npages: int) -> "int | None":
        """First-fit span of npages mapped pages; None if no mapped span."""
        run = 0
        for i, m in enumerate(self.pages):
            run = run + 1 if m else 0
            if run >= npages:
                return i - npages + 1
        return None

    # -- call replay ----------------------------------------------------

    def analyze_call(self, c: M.Call) -> None:
        def note(a: M.Arg, _p):
            t = a.typ
            if isinstance(t, T.ResourceType) and t.dir != T.Dir.IN:
                self.resources.setdefault(t.desc.name, []).append(a)
            if isinstance(a, M.DataArg) and isinstance(t, T.BufferType):
                if t.kind == T.BufferKind.FILENAME:
                    self.files.add(a.data)
                elif t.kind == T.BufferKind.STRING:
                    self.strings.add(a.data)

        M.foreach_arg(c, note)
        if c.ret is not None and isinstance(c.meta.ret, T.ResourceType):
            self.resources.setdefault(c.meta.ret.desc.name, []).append(c.ret)

        name = c.meta.call_name
        if name == "mmap" and len(c.args) >= 2:
            self._pages_op(c.args[0], c.args[1], True)
        elif name == "munmap" and len(c.args) >= 2:
            self._pages_op(c.args[0], c.args[1], False)
        elif name == "mremap" and len(c.args) >= 5:
            self._pages_op(c.args[4], c.args[2], True)

    def _pages_op(self, addr: M.Arg, length: M.Arg, mapped: bool) -> None:
        if not isinstance(addr, M.PointerArg):
            return
        n = 0
        if isinstance(length, M.PageSizeArg):
            n = length.npages
        elif isinstance(length, M.ConstArg):
            n = (length.val + M.PAGE_SIZE - 1) // M.PAGE_SIZE
        if n > 0:
            self.mark_pages(addr.page, n, mapped)


def analyze(table: SyscallTable, p: M.Prog, upto: "M.Call | None" = None) -> State:
    """Replay the prefix of p before `upto` (all calls if None) into a State
    (ref prog/analysis.go:30-39)."""
    s = State(table)
    for c in p.calls:
        if c is upto:
            break
        s.analyze_call(c)
    return s


# ---------------------------------------------------------------------------
# Length-field solving (ref prog/analysis.go:173-214).


def _node_size(a: M.Arg) -> int:
    return a.size()


def _elem_count(a: M.Arg) -> int:
    if isinstance(a, M.GroupArg):
        return len(a.inner)
    if isinstance(a, M.DataArg):
        return len(a.data)
    if isinstance(a, M.PointerArg) and a.npages:
        return a.npages * M.PAGE_SIZE
    return 1


def _len_value(lt: T.LenType, target: M.Arg) -> int:
    t = target.typ
    if isinstance(t, T.VmaType):
        npages = target.npages if isinstance(target, M.PointerArg) else 0
        return npages * M.PAGE_SIZE // (lt.byte_size or 1)
    if isinstance(target, M.PointerArg):
        # len of a pointer measures the pointee.
        if target.res is None:
            return 0
        target, t = target.res, target.res.typ
    if lt.byte_size:
        return _node_size(target) // lt.byte_size
    # len[] counts elements of arrays/buffers, bytes otherwise.
    if isinstance(t, T.ArrayType) or isinstance(target, M.DataArg):
        return _elem_count(target)
    return _node_size(target)


def _assign_sizes(args: list[M.Arg], parent_fields: "list[M.Arg] | None" = None) -> None:
    """Resolve every LenType among `args` against its sibling by field name
    ('parent' refers to the struct enclosing the len field)."""
    by_name: dict[str, M.Arg] = {}
    for a in args:
        fname = a.typ.field_name()
        if fname:
            by_name.setdefault(fname, a)

    def len_node(a: M.Arg) -> "M.ConstArg | None":
        # A len can sit directly among the siblings, or one pointer deref
        # down (`n ptr[inout, len[p, int64]]` — ref assignSizesCall).
        if isinstance(a, M.ConstArg) and isinstance(a.typ, T.LenType):
            return a
        if (isinstance(a, M.PointerArg) and a.res is not None
                and isinstance(a.res, M.ConstArg)
                and isinstance(a.res.typ, T.LenType)):
            return a.res
        return None

    for a in args:
        ln = len_node(a)
        if ln is None:
            continue
        lt = ln.typ
        assert isinstance(lt, T.LenType)
        if lt.buf == "parent":
            continue  # handled by the caller with the parent group
        tgt = by_name.get(lt.buf)
        if tgt is None:
            continue  # dangling len: description bug, keep current value
        ln.val = _len_value(lt, tgt)


def assign_sizes_call(c: M.Call) -> None:
    """Solve len fields at the top level of the call and inside every
    struct (a len field refers to its siblings)."""
    _assign_sizes(c.args)

    def rec(a: M.Arg, _p):
        if isinstance(a, M.GroupArg) and isinstance(a.typ, T.StructType):
            _assign_sizes(a.inner)
            # len[parent] = byte size of the enclosing struct.
            for f in a.inner:
                if (isinstance(f, M.ConstArg) and isinstance(f.typ, T.LenType)
                        and f.typ.buf == "parent"):
                    f.val = a.size() // (f.typ.byte_size or 1)

    M.foreach_arg(c, rec)


# ---------------------------------------------------------------------------
# Call sanitizing (ref prog/analysis.go:216-282): rewrite generated values
# that would break the fuzzer itself rather than test the kernel.

MAP_FIXED = 0x10


def sanitize_call(c: M.Call) -> None:
    name = c.meta.call_name
    if name == "mmap" and len(c.args) >= 4:
        # Always MAP_FIXED so the page-accounting model matches reality.
        flags = c.args[3]
        if isinstance(flags, M.ConstArg):
            flags.val |= MAP_FIXED
    elif name == "mknod" and len(c.args) >= 2:
        mode = c.args[1]
        if isinstance(mode, M.ConstArg) and mode.val % 8 not in (0, 1, 2, 4, 6):
            mode.val = 0o10000 | 0o666  # S_IFIFO
    elif name == "exit" or name == "exit_group":
        # Reserved magic statuses signal executor control flow, not a test
        # outcome (ref executor taxonomy; common.h:46-48).
        if c.args and isinstance(c.args[0], M.ConstArg):
            if c.args[0].val % 128 in (67, 68, 69):
                c.args[0].val = 1
    elif name == "ptrace" and c.args:
        # PTRACE_TRACEME freezes the executor under its own supervision.
        req = c.args[0]
        if isinstance(req, M.ConstArg) and req.val == 0:
            req.val = 0xFFFFFFFF
    elif name == "ioctl" and len(c.args) >= 2:
        req = c.args[1]
        if isinstance(req, M.ConstArg) and req.val == 0xC0045877:  # FIFREEZE
            req.val = 0xC0045878  # FITHAW
