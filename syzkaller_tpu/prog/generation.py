"""Whole-program generation.

Capability parity with reference prog/generation.go:12-27: grow a
program call-by-call under a choice table until the target length,
replaying state so later calls can consume earlier calls' resources.
"""

from __future__ import annotations

from syzkaller_tpu.prog import model as M
from syzkaller_tpu.prog.analysis import State
from syzkaller_tpu.prog.rand import Gen, Rand
from syzkaller_tpu.sys.table import SyscallTable


def generate(rand: Rand, table: SyscallTable, ncalls: int,
             choice_table=None, pid: int = 0) -> M.Prog:
    p = M.Prog()
    state = State(table)
    gen = Gen(rand, state, table, choice_table, pid)
    while len(p.calls) < ncalls:
        prev = p.calls[rand.intn(len(p.calls))].meta.id if p.calls else -1
        p.calls.extend(gen.generate_call(prev))
    # Growing by >1 call at a time (resource ctors) can overshoot.
    if len(p.calls) > ncalls:
        for i in range(len(p.calls) - 1, -1, -1):
            if len(p.calls) <= ncalls:
                break
            # Only drop calls whose results nothing references.
            c = p.calls[i]
            used = (c.ret is not None and c.ret.uses)
            if not used:
                for a in list(M.all_args(c)):
                    if a.uses:
                        used = True
                        break
            if not used:
                M.remove_call(p, i)
    return p
