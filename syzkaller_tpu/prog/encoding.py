"""Human-readable program serialization (the corpus interchange format).

Capability parity with reference prog/encoding.go:29-120 (Serialize /
Deserialize roundtrip, CallSet).  The surface syntax follows the
reference's style:

    r0 = open(&(0x20001000)="2e2f66696c653000", 0x2, 0x0)
    read(r0, &(0x20002000)="00", 0x1)
    mmap(&(0x20000000/0x3000)=nil, (0x3000), 0x3, 0x32, 0xffffffffffffffff, 0x0)

    const            0x1f
    result ref       r0, r0/0x3+0x1   (value = r0 / 0x3 + 0x1)
    pointer          &(0xaddr)=pointee ;  null pointer: nil
    vma              &(0xaddr/0xlen)=nil
    page-size len    (0xlen)
    data             "hex bytes"
    struct           {a, b}
    array            [a, b]
    union            @option_field=arg
    out-resource     <r1=>0x0         (names an inner arg for later refs)

Deserialization is type-directed: the call signature drives which arg
node each token becomes, so a program only parses against the table it
was written with (corpus verify-on-load discards stale programs, like
the reference syz-manager/persistent.go:22-102).
"""

from __future__ import annotations

from syzkaller_tpu.prog import analysis
from syzkaller_tpu.prog import model as M
from syzkaller_tpu.sys import types as T
from syzkaller_tpu.sys.table import SyscallTable


class DeserializeError(Exception):
    pass


# ---------------------------------------------------------------------------
# Serialize


def serialize(p: M.Prog) -> bytes:
    ids: dict[int, int] = {}   # id(arg) -> rN
    next_id = [0]

    def name_of(a: M.Arg) -> int:
        key = id(a)
        if key not in ids:
            ids[key] = next_id[0]
            next_id[0] += 1
        return ids[key]

    # Pre-assign indices in program order so refs are always backward.
    for c in p.calls:
        for a in M.all_args(c):
            if a.uses:
                name_of(a)
        if c.ret is not None and c.ret.uses:
            name_of(c.ret)

    lines = []
    for c in p.calls:
        s = ""
        if c.ret is not None and id(c.ret) in ids:
            s += f"r{ids[id(c.ret)]} = "
        s += c.meta.name + "(" + ", ".join(_ser_arg(a, ids) for a in c.args) + ")"
        lines.append(s)
    return ("\n".join(lines) + "\n").encode()


def _ser_arg(a: M.Arg, ids: dict[int, int]) -> str:
    prefix = f"<r{ids[id(a)]}=>" if id(a) in ids and not isinstance(a, M.ReturnArg) else ""
    if isinstance(a, M.ConstArg):
        return prefix + hex(a.val)
    if isinstance(a, M.ResultArg):
        if a.res is None:
            return prefix + hex(a.val)
        s = f"r{ids[id(a.res)]}"
        if a.op_div:
            s += f"/{hex(a.op_div)}"
        if a.op_add:
            s += f"+{hex(a.op_add)}"
        return prefix + s
    if isinstance(a, M.PointerArg):
        va = M.DATA_OFFSET + a.address()
        if a.npages:
            return prefix + f"&({hex(va)}/{hex(a.npages * M.PAGE_SIZE)})=nil"
        if a.res is None:
            return prefix + "nil"
        return prefix + f"&({hex(va)})=" + _ser_arg(a.res, ids)
    if isinstance(a, M.PageSizeArg):
        return prefix + f"({hex(a.npages * M.PAGE_SIZE)})"
    if isinstance(a, M.DataArg):
        return prefix + '"' + a.data.hex() + '"'
    if isinstance(a, M.GroupArg):
        op, cl = ("[", "]") if isinstance(a.typ, T.ArrayType) else ("{", "}")
        return prefix + op + ", ".join(_ser_arg(x, ids) for x in a.inner) + cl
    if isinstance(a, M.UnionArg):
        return prefix + "@" + a.option_typ.field_name() + "=" + _ser_arg(a.option, ids)
    if isinstance(a, M.ReturnArg):
        return prefix + "0x0"
    raise TypeError(f"serialize: unknown arg {type(a)}")


def call_set(data: bytes) -> set[str]:
    """Set of call names in a serialized program without a full parse
    (ref prog/encoding.go CallSet)."""
    out = set()
    for line in data.decode(errors="replace").splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "=" in line.split("(", 1)[0]:
            line = line.split("=", 1)[1].strip()
        name = line.split("(", 1)[0].strip()
        if name:
            out.add(name)
    return out


# ---------------------------------------------------------------------------
# Deserialize


class _P:
    def __init__(self, s: str, line_no: int):
        self.s = s
        self.i = 0
        self.line_no = line_no

    def err(self, msg: str):
        raise DeserializeError(f"line {self.line_no}: {msg} (at {self.s[self.i:self.i+25]!r})")

    def ws(self):
        while self.i < len(self.s) and self.s[self.i] in " \t":
            self.i += 1

    def peek(self) -> str:
        self.ws()
        return self.s[self.i] if self.i < len(self.s) else ""

    def eat(self, ch: str):
        if self.peek() != ch:
            self.err(f"expected {ch!r}")
        self.i += 1

    def ident(self) -> str:
        self.ws()
        st = self.i
        while self.i < len(self.s) and (self.s[self.i].isalnum() or self.s[self.i] in "_$"):
            self.i += 1
        if st == self.i:
            self.err("expected identifier")
        return self.s[st:self.i]

    def num(self) -> int:
        self.ws()
        neg = False
        if self.i < len(self.s) and self.s[self.i] == "-":
            neg = True
            self.i += 1
        v = self._unum()
        return -v if neg else v

    def _unum(self) -> int:
        st = self.i
        if self.s[self.i:self.i + 2].lower() == "0x":
            self.i += 2
            while self.i < len(self.s) and self.s[self.i] in "0123456789abcdefABCDEF":
                self.i += 1
            if self.i == st + 2:
                self.err("bare 0x with no hex digits")
            return int(self.s[st + 2:self.i], 16)
        while self.i < len(self.s) and self.s[self.i].isdigit():
            self.i += 1
        if st == self.i:
            self.err("expected number")
        return int(self.s[st:self.i])


def deserialize(data: bytes, table: SyscallTable) -> M.Prog:
    p = M.Prog()
    refs: dict[int, M.Arg] = {}
    for line_no, raw in enumerate(data.decode().splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        pr = _P(line, line_no)
        ret_ref: "int | None" = None
        # Optional "rN = " prefix.
        save = pr.i
        if pr.peek() == "r":
            tok = pr.ident()
            if tok[1:].isdigit() and pr.peek() == "=":
                pr.eat("=")
                ret_ref = int(tok[1:])
            else:
                pr.i = save
        name = pr.ident()
        meta = table.call_map.get(name)
        if meta is None:
            raise DeserializeError(f"line {line_no}: unknown call {name}")
        pr.eat("(")
        args: list[M.Arg] = []
        for i, at in enumerate(meta.args):
            if i > 0:
                pr.eat(",")
            args.append(_parse_arg(pr, at, refs))
        pr.eat(")")
        c = M.Call(meta, args)
        if meta.ret is not None:
            c.ret = M.ReturnArg(meta.ret)
            if ret_ref is not None:
                refs[ret_ref] = c.ret
        elif ret_ref is not None:
            raise DeserializeError(f"line {line_no}: {name} has no return resource")
        analysis.assign_sizes_call(c)
        p.calls.append(c)
    return p


def _parse_arg(pr: _P, t: T.Type, refs: dict[int, M.Arg]) -> M.Arg:
    ref_id: "int | None" = None
    if pr.peek() == "<":
        pr.eat("<")
        tok = pr.ident()
        if not tok.startswith("r") or not tok[1:].isdigit():
            pr.err("expected <rN=>")
        ref_id = int(tok[1:])
        pr.eat("=")
        pr.eat(">")
    a = _parse_arg_inner(pr, t, refs)
    if ref_id is not None:
        refs[ref_id] = a
    return a


def _parse_arg_inner(pr: _P, t: T.Type, refs: dict[int, M.Arg]) -> M.Arg:
    ch = pr.peek()
    if ch == "n":  # nil
        if pr.ident() != "nil":
            pr.err("expected nil")
        if isinstance(t, (T.PtrType, T.VmaType)):
            return M.PointerArg(t, 0, 0, 0, None)
        pr.err(f"nil for non-pointer {t.name}")
    if ch == "&":
        pr.eat("&")
        pr.eat("(")
        addr = pr.num()
        if addr >= M.DATA_OFFSET:
            addr -= M.DATA_OFFSET
        page, off = divmod(addr, M.PAGE_SIZE)
        if pr.peek() == "/":
            pr.eat("/")
            ln = pr.num()
            pr.eat(")")
            pr.eat("=")
            if pr.ident() != "nil":
                pr.err("vma pointee must be nil")
            return M.PointerArg(t, page, off, ln // M.PAGE_SIZE, None)
        pr.eat(")")
        pr.eat("=")
        if not isinstance(t, T.PtrType):
            pr.err(f"pointer value for {t.name}")
        elem_t = t.elem if t.elem is not None else T.BufferType(
            name="blob", dir=t.dir, kind=T.BufferKind.BLOB_RAND)
        elem = _parse_arg(pr, elem_t, refs)
        return M.PointerArg(t, page, off, 0, elem)
    if ch == "(":
        pr.eat("(")
        v = pr.num()
        pr.eat(")")
        return M.PageSizeArg(t, v // M.PAGE_SIZE)
    if ch == '"':
        pr.eat('"')
        st = pr.i
        while pr.i < len(pr.s) and pr.s[pr.i] != '"':
            pr.i += 1
        hexs = pr.s[st:pr.i]
        pr.eat('"')
        try:
            data = bytes.fromhex(hexs)
        except ValueError:
            pr.err("bad hex data")
        return M.DataArg(t, data)
    if ch in "{[":
        close = "}" if ch == "{" else "]"
        pr.eat(ch)
        inner: list[M.Arg] = []
        if isinstance(t, T.StructType):
            for i, f in enumerate(t.fields):
                if i > 0:
                    pr.eat(",")
                inner.append(_parse_arg(pr, f, refs))
        elif isinstance(t, T.ArrayType):
            while pr.peek() != close:
                if inner:
                    pr.eat(",")
                inner.append(_parse_arg(pr, t.elem, refs))
        else:
            pr.err(f"group value for scalar {t.name}")
        pr.eat(close)
        return M.GroupArg(t, inner)
    if ch == "@":
        pr.eat("@")
        fname = pr.ident()
        pr.eat("=")
        if not isinstance(t, T.UnionType):
            pr.err(f"union value for {t.name}")
        for opt in t.options:
            if opt.field_name() == fname:
                a = _parse_arg(pr, opt, refs)
                return M.UnionArg(t, a, opt)
        pr.err(f"unknown union option {fname}")
    if ch == "r":
        save = pr.i
        tok = pr.ident()
        if tok[1:].isdigit():
            n = int(tok[1:])
            target = refs.get(n)
            if target is None:
                pr.err(f"undefined result r{n}")
            op_div = op_add = 0
            if pr.peek() == "/":
                pr.eat("/")
                op_div = pr.num()
            if pr.peek() == "+":
                pr.eat("+")
                op_add = pr.num()
            return M.ResultArg(t, target, 0, op_div, op_add)
        pr.i = save
        pr.err("bad token")
    # Plain number: const scalar, or a literal-valued resource.
    v = pr.num()
    if isinstance(t, T.ResourceType):
        return M.ResultArg(t, None, v)
    return M.ConstArg(t, v)
