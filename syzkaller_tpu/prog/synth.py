"""Program-synthesis tables: pre-encoding, decoding, and the host
reference synthesizer behind `engine.synth_block`.

The device megakernel assembles complete exec-bytecode programs by
gathering CALL-LEVEL SEGMENTS out of two fixed-capacity tables (a
corpus of admitted programs and a bank of single-call templates) and
editing const-arg value words in place.  That only works if every
table row satisfies the *segment contract*:

  * each call's exec encoding is position-independent — no ARG_RESULT
    references, no COPYOUTs, no used return values — so any
    concatenation of call segments is itself valid exec bytecode and
    equals `serialize_for_exec` of the concatenated Prog;
  * the row's encoding is *decodable*: `decode_words(encode(p)) == p`
    up to byte-identical re-encoding AND byte-identical text
    serialization, so a program slab coming back from the executor (a
    crash! a triage item!) can be lifted to an `M.Prog` for csource
    repro generation without any provenance side channel.

`encode_program` enforces both as an admission gate: a program that
fails either is simply not eligible for the device tables and stays on
the host path — eligibility is a fast-path filter, never a semantics
change.

The module also carries the OPERATOR mix (derived from the host
mutator's weights in prog/mutation.py) and `HostSynth`, a numpy
reference implementation of the five device operators over the same
tables.  The device kernel and `HostSynth` share `plan_entries` (the
segment plan incl. the output-length truncation rule) and
`materialize` (provenance → Prog replay), so the chi-square
equivalence tests and the slab→prog→csource round trip compare two
implementations of ONE written-down spec.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from syzkaller_tpu.prog import encoding
from syzkaller_tpu.prog import model as M
from syzkaller_tpu.prog.encodingexec import (
    ARG_CONST, ARG_DATA, INSTR_COPYIN, INSTR_COPYOUT, INSTR_EOF,
    NO_RESULT, physical_addr, serialize_for_exec, _encode_scalar)
from syzkaller_tpu.sys import types as T

# ---------------------------------------------------------------------------
# Operator catalog.  One synth output applies ONE operator; the mix is
# the host mutator's split (prog/mutation.py): the proc loop generates
# 1/10 of the time, and a mutation round splices 1/100 else draws
# insert/mutate/remove at [20, 10, 1].

OP_GENERATE, OP_SPLICE, OP_INSERT, OP_MUTATE, OP_SQUASH = range(5)
OP_NAMES = ("generate", "splice", "insert", "mutate", "squash")

_mut = 0.9 * 0.99 / 31.0
OPERATOR_WEIGHTS = np.array(
    [0.1, 0.9 * 0.01, _mut * 20.0, _mut * 10.0, _mut * 1.0], np.float64)


@dataclass
class EncodedProgram:
    """One table row: a program pre-encoded to exec words (NO trailing
    EOF) with call-segment offsets and mutable const-arg slots."""
    prog: M.Prog
    words: np.ndarray       # (nwords,) uint64
    call_off: np.ndarray    # (ncalls+1,) int32; call_off[-1] == nwords
    call_ids: np.ndarray    # (ncalls,) int32 table call ids
    slots: list             # [(word_off, size_bytes, call_idx)]

    @property
    def nwords(self) -> int:
        return len(self.words)

    @property
    def ncalls(self) -> int:
        return len(self.call_ids)


class SynthEncodeError(Exception):
    pass


# -- encoding with slot tracking --------------------------------------------
#
# Mirrors prog/encodingexec.py serialize_for_exec word for word for the
# result-free subset, recording the stream index of every mutable
# const-arg VALUE word.  `encode_program` verifies the mirror against
# serialize_for_exec before admitting a row, so a drift between the two
# encoders rejects the program instead of corrupting the tables.


def _slot_eligible(a: M.Arg) -> bool:
    """ConstArgs the device mutate-arg operator may edit: the plain-int
    arm of _mutate_one (no flags/proc/range resampling on device), non
    big-endian (the value word must equal the stored val), not padding
    and not a length field (mirrors mutation._mutable_args)."""
    if not isinstance(a, M.ConstArg):
        return False
    t = a.typ
    if T.is_pad(t) or isinstance(t, (T.ConstType, T.LenType, T.FlagsType,
                                     T.ProcType)):
        return False
    if isinstance(t, T.IntType) and t.kind == T.IntKind.RANGE:
        return False
    if getattr(t, "big_endian", False):
        return False
    if t.dir == T.Dir.OUT:
        return False
    return True


def _encode_call(c: M.Call, pid: int = 0):
    """One call's exec words + mutable slot offsets.  Raises
    SynthEncodeError for anything outside the segment contract."""
    w: list[int] = []
    slots: list[tuple[int, int]] = []     # (value word index, size)

    def emit_arg(a: M.Arg) -> None:
        if isinstance(a, M.ConstArg):
            if _slot_eligible(a):
                slots.append((len(w) + 2, getattr(a.typ, "type_size", 8)))
            w.extend([ARG_CONST, a.size(), _encode_scalar(a, pid)])
        elif isinstance(a, M.ResultArg):
            if a.res is not None:
                raise SynthEncodeError("cross-call result reference")
            w.extend([ARG_CONST, a.size(), _encode_scalar(a, pid)])
        elif isinstance(a, M.PointerArg):
            w.extend([ARG_CONST, 8,
                      physical_addr(a) if not a.is_null else 0])
        elif isinstance(a, M.PageSizeArg):
            w.extend([ARG_CONST, a.size() if not isinstance(a.typ, T.LenType)
                      else a.typ.size(), a.npages * M.PAGE_SIZE])
        elif isinstance(a, M.DataArg):
            n = len(a.data)
            w.extend([ARG_DATA, n])
            pad = a.data + b"\x00" * (-n % 8)
            for i in range(0, len(pad), 8):
                w.append(int.from_bytes(pad[i:i + 8], "little"))
        else:
            raise SynthEncodeError(f"cannot emit {type(a)} as call arg")

    def emit_copyin(a: M.Arg, addr: int) -> None:
        if isinstance(a, M.GroupArg):
            off = 0
            for x in a.inner:
                emit_copyin(x, addr + off)
                off += x.size()
            return
        if isinstance(a, M.UnionArg):
            emit_copyin(a.option, addr)
            return
        if a.typ.dir == T.Dir.OUT and isinstance(a, M.DataArg):
            return
        if isinstance(a, M.DataArg) and not a.data:
            return
        w.append(INSTR_COPYIN)
        w.append(addr)
        emit_arg(a)
        if isinstance(a, M.PointerArg) and a.res is not None:
            emit_copyin(a.res, physical_addr(a))

    def check_no_copyout(a: M.Arg) -> None:
        def visit(x, _p):
            if isinstance(x, M.ResultArg) and x.uses:
                raise SynthEncodeError("out-resource with uses")
        M.foreach_subarg(a, visit)

    if c.ret is not None and c.ret.uses:
        raise SynthEncodeError("used return value")
    for a in c.args:
        check_no_copyout(a)
        if isinstance(a, M.PointerArg) and a.res is not None:
            emit_copyin(a.res, physical_addr(a))
    w.append(c.meta.nr)
    w.append(NO_RESULT)
    w.append(len(c.args))
    for a in c.args:
        emit_arg(a)
    return np.array(w, np.uint64), slots


def encode_program(p: M.Prog, table=None, pid: int = 0,
                   verify: bool = True) -> "EncodedProgram | None":
    """Pre-encode a program into a table row, or None if it violates
    the segment contract.  With `table` given (and verify=True) the
    decode gate also runs: the row must lift back to a Prog whose
    exec AND text serializations are byte-identical — csource repro
    round trips by construction for everything in the tables."""
    words_parts: list[np.ndarray] = []
    call_off = [0]
    slots: list[tuple[int, int, int]] = []
    try:
        for ci, c in enumerate(p.calls):
            cw, cslots = _encode_call(c, pid)
            slots.extend((call_off[-1] + off, size, ci)
                         for off, size in cslots)
            words_parts.append(cw)
            call_off.append(call_off[-1] + len(cw))
    except SynthEncodeError:
        return None
    words = (np.concatenate(words_parts) if words_parts
             else np.zeros(0, np.uint64))
    if verify:
        # mirror check: segments + EOF must equal the production encoder
        ref = np.frombuffer(serialize_for_exec(p, pid), np.uint64)
        full = np.concatenate([words, [np.uint64(INSTR_EOF)]])
        if not np.array_equal(full, ref):
            return None
    enc = EncodedProgram(
        prog=p, words=words, call_off=np.array(call_off, np.int32),
        call_ids=np.array([c.meta.id for c in p.calls], np.int32),
        slots=slots)
    if verify and table is not None:
        try:
            q = decode_words(np.concatenate(
                [words, [np.uint64(INSTR_EOF)]]), table)
        except SynthDecodeError:
            return None
        if serialize_for_exec(q, pid) != serialize_for_exec(p, pid):
            return None
        # the round-trip criterion: a slab built from this row must
        # lift back to a byte-identical C repro.  Wire-ambiguous
        # variants (same encoding, same kernel call) pass — csource
        # output is identical by construction.
        from syzkaller_tpu import csource
        try:
            if csource.generate(q) != csource.generate(p):
                return None
        except Exception:
            return None
    return enc


# ---------------------------------------------------------------------------
# Slab → Prog decoding.  Candidate metas are tried by syscall nr; a
# candidate wins iff the rebuilt call RE-ENCODES to the identical word
# segment — decode is verified-by-construction, never heuristic.


class SynthDecodeError(Exception):
    pass


def _inv_scalar(t: T.Type, enc: int) -> int:
    """Invert _encode_scalar for pid=0 (byte-order + proc bias)."""
    size = getattr(t, "type_size", 8)
    v = enc & ((1 << (8 * size)) - 1)
    if getattr(t, "big_endian", False):
        v = int.from_bytes(v.to_bytes(size, "big"), "little")
    if isinstance(t, T.ProcType):
        v -= t.values_start
        if v < 0:
            raise SynthDecodeError("proc value below values_start")
    return v


class _SegDecoder:
    """Decode ONE call segment: its copyins + the CALL record."""

    def __init__(self, copyins: dict, nr: int, raw_args: list):
        # copyins: DATA-WINDOW-RELATIVE addr -> (kind, size, payload)
        self.copyins = copyins
        self.nr = nr
        self.raw_args = raw_args   # [(kind, size, value_or_bytes)]

    def build(self, meta: T.Syscall) -> M.Call:
        if meta.nr != self.nr or len(meta.args) != len(self.raw_args):
            raise SynthDecodeError("signature mismatch")
        args = [self._top_arg(t, raw)
                for t, raw in zip(meta.args, self.raw_args)]
        c = M.Call(meta, args)
        if meta.ret is not None:
            c.ret = M.ReturnArg(meta.ret)
        self._fix_len_args(c, meta)
        return c

    def _top_arg(self, t: T.Type, raw) -> M.Arg:
        kind, size, val = raw
        if isinstance(t, T.BufferType):
            if kind != ARG_DATA:
                raise SynthDecodeError("expected data arg")
            return M.DataArg(t, val)
        if kind != ARG_CONST:
            raise SynthDecodeError("unsupported arg kind")
        if isinstance(t, (T.PtrType, T.VmaType)):
            return self._pointer(t, val)
        if isinstance(t, T.ResourceType):
            return M.ResultArg(t, None, _inv_scalar(t, val))
        return M.ConstArg(t, _inv_scalar(t, val))

    def _pointer(self, t: T.Type, enc_addr: int) -> M.PointerArg:
        if enc_addr == 0:
            if isinstance(t, T.VmaType):
                return M.PointerArg(t, 0, 0, 1, None)
            return M.PointerArg(t, 0, 0, 0, None)
        addr = enc_addr - M.DATA_OFFSET
        if addr < 0:
            raise SynthDecodeError("address below data window")
        page, off = divmod(addr, M.PAGE_SIZE)
        if isinstance(t, T.VmaType):
            return M.PointerArg(t, page, off, 1, None)
        elem = t.elem
        if elem is None:
            elem = T.BufferType(name="blob", dir=t.dir,
                                kind=T.BufferKind.BLOB_RAND)
        res = self._pointee(elem, addr)
        return M.PointerArg(t, page, off, 0, res)

    def _pointee(self, t: T.Type, addr: int) -> M.Arg:
        if isinstance(t, T.StructType):
            inner = []
            cur = addr
            for ft in t.fields:
                a = self._pointee(ft, cur)
                inner.append(a)
                cur += a.size()
            return M.GroupArg(t, inner)
        if isinstance(t, T.UnionType):
            errs = None
            for opt in t.options:
                try:
                    return M.UnionArg(t, self._pointee(opt, addr), opt)
                except SynthDecodeError as e:
                    errs = e
            raise SynthDecodeError(f"no union option decodes: {errs}")
        if isinstance(t, T.ArrayType):
            inner = []
            cur = addr
            lo, hi = 0, 64
            if t.kind == T.ArrayKind.RANGE_LEN:
                lo, hi = t.range_begin, min(t.range_end, 64)
            while len(inner) < hi:
                try:
                    a = self._pointee(t.elem, cur)
                except SynthDecodeError:
                    if len(inner) < lo:
                        raise
                    break
                if a.size() == 0 and len(inner) >= lo:
                    break          # empty leaf: no progress possible
                inner.append(a)
                cur += a.size()
            return M.GroupArg(t, inner)
        if isinstance(t, T.PtrType):
            kind, size, val = self._leaf(addr)
            if kind != ARG_CONST:
                raise SynthDecodeError("pointer field not const")
            return self._pointer(t, val)
        if isinstance(t, T.VmaType):
            kind, size, val = self._leaf(addr)
            return self._pointer(t, val)
        if isinstance(t, T.BufferType):
            if t.dir == T.Dir.OUT:
                # OUT data is never copied in; only fixed-size buffers
                # reconstruct (varlen OUT lengths are unrecoverable —
                # the encode gate rejects those rows)
                fs = t.fixed_size()
                if fs is None:
                    raise SynthDecodeError("varlen OUT buffer")
                return M.DataArg(t, bytes(fs))
            if addr not in self.copyins:
                return M.DataArg(t, b"")    # empty data: copyin skipped
            kind, size, val = self.copyins[addr]
            if kind != ARG_DATA:
                raise SynthDecodeError("buffer field not data")
            return M.DataArg(t, val)
        # scalar leaf; the wire carries the emitted size — a mismatch
        # (e.g. the wrong union option) rejects this reconstruction
        kind, size, val = self._leaf(addr)
        if kind != ARG_CONST:
            raise SynthDecodeError("scalar field not const")
        if size != t.size():
            raise SynthDecodeError(
                f"scalar size {size} != {t.size()} for {t.name}")
        if isinstance(t, T.ResourceType):
            return M.ResultArg(t, None, _inv_scalar(t, val))
        return M.ConstArg(t, _inv_scalar(t, val))

    def _leaf(self, addr: int):
        if addr not in self.copyins:
            raise SynthDecodeError(f"no copyin at {addr:#x}")
        return self.copyins[addr]

    def _fix_len_args(self, c: M.Call, meta: T.Syscall) -> None:
        """LenType args whose referent is a vma sibling become
        PageSizeArgs (the generator builds vma lengths that way; the
        wire carries only the byte length, npages = len/PAGE_SIZE).
        Field names are positional on the wire, so the pairing is the
        sibling-VmaType heuristic — a wrong guess re-encodes
        differently and rejects the candidate, never corrupts."""
        vma_idx = [j for j, t in enumerate(meta.args)
                   if isinstance(t, T.VmaType)]
        if not vma_idx:
            return
        for i, t in enumerate(meta.args):
            if not isinstance(t, T.LenType) or t.byte_size:
                continue
            a = c.args[i]
            if isinstance(a, M.ConstArg) and a.val % M.PAGE_SIZE == 0:
                npages = a.val // M.PAGE_SIZE
                c.args[i] = M.PageSizeArg(t, npages)
                tgt = c.args[vma_idx[0]]
                if npages >= 1 and isinstance(tgt, M.PointerArg) \
                        and not tgt.is_null:
                    tgt.npages = npages


def _parse_stream(words: np.ndarray):
    """Split an exec word stream into per-call segments.  Each segment
    is (copyins, copyin_order, nr, raw_args): `copyins` keys DATA-
    WINDOW-RELATIVE addresses for pointee lookup, `copyin_order` keeps
    the emitted (physical addr, raw) sequence for verification.
    Copyins attach to the NEXT call (the emit order)."""
    segs = []
    copyins: dict[int, tuple] = {}
    order: list[tuple[int, tuple]] = []
    i = 0
    n = len(words)

    def read_arg(i):
        kind = int(words[i])
        if kind == ARG_CONST:
            return (ARG_CONST, int(words[i + 1]), int(words[i + 2])), i + 3
        if kind == ARG_DATA:
            nbytes = int(words[i + 1])
            nw = (nbytes + 7) // 8
            data = words[i + 2: i + 2 + nw].tobytes()[:nbytes]
            return (ARG_DATA, nbytes, data), i + 2 + nw
        raise SynthDecodeError(f"unsupported arg kind {kind}")

    while i < n:
        w = int(words[i])
        if w == INSTR_EOF:
            break
        if w == INSTR_COPYIN:
            phys = int(words[i + 1])
            raw, i = read_arg(i + 2)
            copyins[phys - M.DATA_OFFSET] = raw
            order.append((phys, raw))
            continue
        if w == INSTR_COPYOUT:
            raise SynthDecodeError("copyout outside segment contract")
        nr = w
        ridx = int(words[i + 1])
        if ridx != NO_RESULT:
            raise SynthDecodeError("used result outside segment contract")
        nargs = int(words[i + 2])
        i += 3
        raw_args = []
        for _ in range(nargs):
            raw, i = read_arg(i)
            raw_args.append(raw)
        segs.append((copyins, order, nr, raw_args))
        copyins = {}
        order = []
    return segs


def decode_words(words: np.ndarray, table) -> M.Prog:
    """Lift an exec word stream (uint64, EOF-terminated or not) back to
    an M.Prog.  Each call tries every meta sharing the syscall nr and
    keeps the first whose reconstruction RE-ENCODES byte-identically —
    so a successful decode is self-verifying."""
    words = np.asarray(words, np.uint64)
    by_nr: dict[int, list] = {}
    for meta in table.calls:
        by_nr.setdefault(meta.nr, []).append(meta)
    p = M.Prog()
    for copyins, order, nr, raw_args in _parse_stream(words):
        cands = by_nr.get(nr)
        if not cands:
            raise SynthDecodeError(f"unknown syscall nr {nr}")
        dec = _SegDecoder(copyins, nr, raw_args)
        want = _segment_words(order, nr, raw_args)
        call = None
        for meta in cands:
            try:
                c = dec.build(meta)
                got, _slots = _encode_call(c)
            except (SynthDecodeError, SynthEncodeError):
                continue
            if np.array_equal(got, want):
                call = c
                break
        if call is None:
            raise SynthDecodeError(
                f"no meta for nr {nr} re-encodes identically")
        p.calls.append(call)
    return p


def _segment_words(order, nr, raw_args) -> np.ndarray:
    """Re-emit one parsed segment's words (the decode-verification
    reference): copyins in their original emitted order + the CALL."""
    w: list[int] = []
    for phys, raw in order:
        w.extend([INSTR_COPYIN, phys])
        _emit_raw(w, *raw)
    w.extend([nr, NO_RESULT, len(raw_args)])
    for raw in raw_args:
        _emit_raw(w, *raw)
    return np.array(w, np.uint64)


def _emit_raw(w: list, kind: int, size: int, val) -> None:
    if kind == ARG_CONST:
        w.extend([ARG_CONST, size, val])
    else:
        w.extend([ARG_DATA, size])
        pad = val + b"\x00" * (-size % 8)
        for i in range(0, len(pad), 8):
            w.append(int.from_bytes(pad[i:i + 8], "little"))


# ---------------------------------------------------------------------------
# The shared operator spec: segment planning + provenance replay.


@dataclass
class Provenance:
    """Everything needed to replay one synth output host-side."""
    op: int
    r1: int = 0
    r2: int = 0
    cut: int = 0            # splice insertion call index
    pos: int = 0            # insert-call position
    dele: int = -1          # squash: removed call (-1 = degenerate no-op)
    k: int = 0              # generate: drawn call count
    gen_tmpls: tuple = ()   # generate: template indices (k live)
    ins_tmpl: int = -1      # insert: template index
    slot: int = -1          # mutate: slot ordinal (-1 = no slots, no-op)
    mut_kind: int = 0
    mut_val: int = 0        # final masked 64-bit value
    n_entries: int = 0      # kept entries after the length cap


def plan_entries(prov: Provenance, rows: list, tmpls: list,
                 max_words: int, max_entries: int) -> list:
    """The single written-down segment plan both implementations
    follow: the operator's (table, index, call) entry list, truncated
    to `max_entries` entries and then to the longest prefix whose word
    total fits max_words-1 (one word reserved for EOF).  rows/tmpls are
    EncodedProgram lists."""
    op = prov.op
    ent: list[tuple[int, int, int]] = []   # (tbl, idx, call)
    if op == OP_GENERATE:
        ent = [(1, t, 0) for t in prov.gen_tmpls[: prov.k]]
    elif op == OP_SPLICE:
        n1 = rows[prov.r1].ncalls
        n2 = rows[prov.r2].ncalls
        ent = ([(0, prov.r1, j) for j in range(prov.cut)]
               + [(0, prov.r2, j) for j in range(n2)]
               + [(0, prov.r1, j) for j in range(prov.cut, n1)])
    elif op == OP_INSERT:
        n1 = rows[prov.r1].ncalls
        ent = ([(0, prov.r1, j) for j in range(prov.pos)]
               + [(1, prov.ins_tmpl, 0)]
               + [(0, prov.r1, j) for j in range(prov.pos, n1)])
    elif op == OP_MUTATE:
        ent = [(0, prov.r1, j) for j in range(rows[prov.r1].ncalls)]
    elif op == OP_SQUASH:
        n1 = rows[prov.r1].ncalls
        ent = [(0, prov.r1, j) for j in range(n1) if j != prov.dele]
    ent = ent[:max_entries]
    out = []
    total = 0
    for tbl, idx, call in ent:
        enc = tmpls[idx] if tbl else rows[idx]
        seglen = (enc.nwords if tbl
                  else int(enc.call_off[call + 1] - enc.call_off[call]))
        if total + seglen > max_words - 1:
            break
        total += seglen
        out.append((tbl, idx, call))
    return out


def emit_words(prov: Provenance, rows: list, tmpls: list,
               max_words: int, max_entries: int) -> np.ndarray:
    """Host-reference word emission: gather the planned segments,
    apply the mutate edit, append EOF — the numpy twin of the device
    assembly gather."""
    ent = plan_entries(prov, rows, tmpls, max_words, max_entries)
    parts = []
    for tbl, idx, call in ent:
        enc = tmpls[idx] if tbl else rows[idx]
        if tbl:
            parts.append(enc.words)
        else:
            parts.append(enc.words[enc.call_off[call]:
                                   enc.call_off[call + 1]])
    words = (np.concatenate(parts) if parts
             else np.zeros(0, np.uint64))
    if prov.op == OP_MUTATE and prov.slot >= 0:
        woff, _size, _ci = rows[prov.r1].slots[prov.slot]
        words = words.copy()
        words[woff] = np.uint64(prov.mut_val)
    return np.concatenate([words, [np.uint64(INSTR_EOF)]])


def materialize(prov: Provenance, rows: list, tmpls: list,
                max_words: int, max_entries: int) -> M.Prog:
    """Provenance → M.Prog replay: clone the planned source calls and
    apply the mutate edit on the cloned const arg.  serialize_for_exec
    of the result equals the emitted slab bit for bit (the round-trip
    tests pin this per operator)."""
    ent = plan_entries(prov, rows, tmpls, max_words, max_entries)
    p = M.Prog()
    for tbl, idx, call in ent:
        enc = tmpls[idx] if tbl else rows[idx]
        if tbl:
            p.calls.extend(M.clone_prog(enc.prog).calls)
        else:
            p.calls.extend(M.clone_prog(
                M.Prog(calls=[enc.prog.calls[call]])).calls)
    if prov.op == OP_MUTATE and prov.slot >= 0:
        _woff, size, _ci = rows[prov.r1].slots[prov.slot]
        _set_slot(p, prov.slot, prov.mut_val, size)
    return p


def _set_slot(p: M.Prog, slot: int, val: int, size: int) -> None:
    """Apply a mutate edit to the cloned prog: re-enumerate the clone's
    eligible const args in encode order (deterministic — same walk as
    _encode_call) and set the slot'th one."""
    found = [0]

    def walk_call(c: M.Call):
        order: list[M.ConstArg] = []

        def visit_copyin(a: M.Arg):
            if isinstance(a, M.GroupArg):
                for x in a.inner:
                    visit_copyin(x)
                return
            if isinstance(a, M.UnionArg):
                visit_copyin(a.option)
                return
            if a.typ.dir == T.Dir.OUT and isinstance(a, M.DataArg):
                return
            if isinstance(a, M.DataArg) and not a.data:
                return
            if _slot_eligible(a):
                order.append(a)           # the emit_arg inside copyin
            if isinstance(a, M.PointerArg) and a.res is not None:
                visit_copyin(a.res)

        for a in c.args:
            if isinstance(a, M.PointerArg) and a.res is not None:
                visit_copyin(a.res)
        for a in c.args:
            if _slot_eligible(a):
                order.append(a)
        return order

    want = slot
    for c in p.calls:
        order = walk_call(c)
        if want < len(order):
            a = order[want]
            a.val = val & ((1 << (8 * size)) - 1)
            return
        want -= len(order)
    # slot beyond the truncated output: the edit fell off with its
    # call — a legal no-op (the kernel's edit lands inside the row's
    # identity prefix, which mutate never truncates, so this only
    # happens for degenerate hand-built provenance)


# ---------------------------------------------------------------------------
# Host reference synthesizer (the distribution spec the device kernel
# must match; numpy RNG).


class HostSynth:
    """Numpy reference for the five operators over shared tables.

    Index draws are floor(u * n) over real uniforms and the insert
    position is floor(u^(1/5) * n) (biased_rand k=5) — the exact
    formulas the device kernel computes, so per-operator chi-square
    tests compare two implementations of one spec."""

    def __init__(self, rows: list, tmpls: list, call2tmpl: np.ndarray,
                 probs: np.ndarray, enabled: np.ndarray,
                 max_words: int = 192, max_entries: int = 12,
                 gen_max: int = 6, rng=None):
        self.rows = rows
        self.tmpls = tmpls
        self.call2tmpl = np.asarray(call2tmpl, np.int64)
        self.probs = np.asarray(probs, np.float64)
        self.enabled = np.asarray(enabled, bool)
        self.max_words = max_words
        self.max_entries = max_entries
        self.gen_max = gen_max
        self.rng = rng or np.random.default_rng(0)

    def _draw_call(self, prev: int) -> int:
        C = self.probs.shape[0]
        row = self.probs[prev] if prev >= 0 else np.ones(C)
        w = np.where(self.enabled & (self.call2tmpl >= 0), row, 0.0)
        tot = w.sum()
        if tot <= 0:
            return int(np.argmax(self.call2tmpl >= 0))
        cdf = np.cumsum(w)
        u = self.rng.random() * tot
        return int(np.searchsorted(cdf, u, side="right").clip(0, C - 1))

    def _intn(self, n: int) -> int:
        return int(self.rng.random() * n) if n > 0 else 0

    def synth_one(self) -> Provenance:
        nrows = len(self.rows)
        if nrows == 0:
            op = OP_GENERATE
        else:
            w = OPERATOR_WEIGHTS
            u = self.rng.random() * w.sum()
            op = int(np.searchsorted(np.cumsum(w), u, side="right")
                     .clip(0, len(w) - 1))
        prov = Provenance(op=op)
        if op == OP_GENERATE:
            prov.k = 1 + self._intn(self.gen_max)
            prev = -1
            tg = []
            for _ in range(prov.k):
                cid = self._draw_call(prev)
                tg.append(int(max(self.call2tmpl[cid], 0)))
                prev = cid
            prov.gen_tmpls = tuple(tg)
        else:
            prov.r1 = self._intn(nrows)
            n1 = self.rows[prov.r1].ncalls
            if op == OP_SPLICE:
                prov.r2 = self._intn(nrows)
                prov.cut = self._intn(n1 + 1)
            elif op == OP_INSERT:
                u = self.rng.random()
                prov.pos = min(int((n1 + 1) * u ** 0.2), n1)
                prev = (int(self.rows[prov.r1].call_ids[prov.pos - 1])
                        if prov.pos > 0 else -1)
                prov.ins_tmpl = int(max(
                    self.call2tmpl[self._draw_call(prev)], 0))
            elif op == OP_MUTATE:
                nslots = len(self.rows[prov.r1].slots)
                if nslots > 0:
                    prov.slot = self._intn(nslots)
                    woff, size, _ci = self.rows[prov.r1].slots[prov.slot]
                    old = int(self.rows[prov.r1].words[woff])
                    prov.mut_kind = self._intn(3)
                    mask = (1 << (8 * size)) - 1
                    if prov.mut_kind == 0:
                        v = int(self.rng.integers(0, 1 << 32)) | (
                            int(self.rng.integers(0, 1 << 32)) << 32)
                    elif prov.mut_kind == 1:
                        delta = 1 + self._intn(16)
                        sign = 1 if self.rng.random() < 0.5 else -1
                        v = (old + sign * delta) % (1 << 64)
                    else:
                        v = old ^ (1 << self._intn(64))
                    prov.mut_val = v & mask
            elif op == OP_SQUASH:
                prov.dele = self._intn(n1) if n1 > 1 else -1
        prov.n_entries = len(plan_entries(
            prov, self.rows, self.tmpls, self.max_words,
            self.max_entries))
        return prov

    def emit(self, prov: Provenance) -> np.ndarray:
        return emit_words(prov, self.rows, self.tmpls, self.max_words,
                          self.max_entries)
