"""The program model: a Prog is a sequence of Calls over an Arg tree.

Capability parity with the reference program model (prog/prog.go:12-245):
the same arg taxonomy (const / result / pointer / page-size / data /
group / union / return — prog/prog.go:41-52), result cross-links with
use-tracking, value encoding incl. big-endian and per-proc values
(prog/prog.go:71-103), and tree surgery that keeps the uses-links
consistent (insertBefore/replaceArg/removeArg/removeCall,
prog/prog.go:174-245).

Design differences: args are typed subclasses instead of a kind-tagged
struct; addresses are explicit (page, offset) pairs resolved against
DATA_OFFSET only at exec-serialization time, keeping the model
position-independent for the device-side corpus store.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from syzkaller_tpu.sys import types as T

PAGE_SIZE = T.PAGE_SIZE
MAX_PAGES = 4 << 10            # 16MB program address space (ref prog/analysis.go:18)
DATA_OFFSET = 512 << 20        # virtual base of the data window (ref prog/encodingexec.go:27-31)


class Arg:
    """Base of all argument nodes.

    typ   -- the sys.Type this node instantiates.
    uses  -- set of ResultArg nodes whose value refers to this node.
    """

    __slots__ = ("typ", "uses")

    def __init__(self, typ: T.Type):
        self.typ = typ
        self.uses: set[ResultArg] = set()

    def size(self) -> int:
        return self.typ.size()


class ConstArg(Arg):
    """Scalar immediate: const/int/flags/len/proc/csum values."""

    __slots__ = ("val",)

    def __init__(self, typ: T.Type, val: int):
        super().__init__(typ)
        self.val = val

    def value(self, pid: int = 0) -> int:
        """The encoded scalar as the kernel should see it (before
        byte-order encoding).  ProcType values are biased per-process so
        concurrent fuzzer procs touch disjoint ids (ref prog/prog.go:98-100,
        sys/decl.go:242-256)."""
        t = self.typ
        if isinstance(t, T.ProcType):
            return t.values_start + t.values_per_proc * pid + self.val
        return self.val


class ResultArg(Arg):
    """Reference to the result of a previous call (or an out-resource arg).

    res is the referenced arg (its .uses contains self); if None, val is
    used as a literal fallback.  op_div/op_add post-process the runtime
    value: v = v / op_div + op_add (div first — ref prog/prog.go:30-33).
    """

    __slots__ = ("res", "val", "op_div", "op_add")

    def __init__(self, typ: T.Type, res: "Arg | None", val: int,
                 op_div: int = 0, op_add: int = 0):
        super().__init__(typ)
        self.res = res
        self.val = val
        self.op_div = op_div
        self.op_add = op_add
        if res is not None:
            res.uses.add(self)


class PointerArg(Arg):
    """Pointer into the data window: page*PAGE_SIZE + offset.

    res is the pointee (None for vma regions and null pointers);
    npages > 0 marks a vma region of that many pages.
    """

    __slots__ = ("page", "offset", "npages", "res")

    def __init__(self, typ: T.Type, page: int, offset: int,
                 npages: int, res: "Arg | None"):
        super().__init__(typ)
        self.page = page
        self.offset = offset
        self.npages = npages
        self.res = res

    def address(self) -> int:
        return self.page * PAGE_SIZE + self.offset

    @property
    def is_null(self) -> bool:
        return self.res is None and self.npages == 0 and self.page == 0 and self.offset == 0


class PageSizeArg(Arg):
    """A length expressed in pages (vma sizes, mmap len — ref ArgPageSize
    prog/prog.go:44-45): value = npages * PAGE_SIZE."""

    __slots__ = ("npages",)

    def __init__(self, typ: T.Type, npages: int):
        super().__init__(typ)
        self.npages = npages


class DataArg(Arg):
    """In-memory byte blob (buffers, strings, filenames, text)."""

    __slots__ = ("data",)

    def __init__(self, typ: T.Type, data: bytes):
        super().__init__(typ)
        self.data = bytes(data)

    def size(self) -> int:
        return len(self.data)


class GroupArg(Arg):
    """Struct or array: ordered child args."""

    __slots__ = ("inner",)

    def __init__(self, typ: T.Type, inner: list["Arg"]):
        super().__init__(typ)
        self.inner = inner

    def size(self) -> int:
        if isinstance(self.typ, T.StructType) and not self.typ.is_varlen():
            return self.typ.size()
        return sum(a.size() for a in self.inner)


class UnionArg(Arg):
    """One selected option of a union."""

    __slots__ = ("option", "option_typ")

    def __init__(self, typ: T.Type, option: "Arg", option_typ: T.Type):
        super().__init__(typ)
        self.option = option
        self.option_typ = option_typ

    def size(self) -> int:
        u = self.typ
        if isinstance(u, T.UnionType) and not u.is_varlen():
            return u.size()
        return self.option.size()


class ReturnArg(Arg):
    """Placeholder for a call's return value; target of ResultArg links."""

    __slots__ = ()


@dataclass
class Call:
    meta: T.Syscall
    args: list[Arg]
    ret: Optional[ReturnArg] = None


@dataclass
class Prog:
    calls: list[Call] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.calls)


# ---------------------------------------------------------------------------
# Tree walking


def foreach_subarg(arg: Arg, fn: Callable[[Arg, "Arg | None"], None],
                   parent: "Arg | None" = None) -> None:
    """Depth-first visit of arg and everything beneath it.
    fn(node, parent); pointees/options/children are all visited."""
    fn(arg, parent)
    if isinstance(arg, PointerArg):
        if arg.res is not None:
            foreach_subarg(arg.res, fn, arg)
    elif isinstance(arg, GroupArg):
        for a in arg.inner:
            foreach_subarg(a, fn, arg)
    elif isinstance(arg, UnionArg):
        foreach_subarg(arg.option, fn, arg)


def foreach_arg(call: Call, fn: Callable[[Arg, "Arg | None"], None]) -> None:
    for a in call.args:
        foreach_subarg(a, fn)


def all_args(call: Call) -> Iterator[Arg]:
    out: list[Arg] = []
    foreach_arg(call, lambda a, _p: out.append(a))
    return iter(out)


# ---------------------------------------------------------------------------
# Default (simplest) args — used by minimization and as mutation fallback.


def default_arg(t: T.Type) -> Arg:
    """The simplest well-formed arg for a type (ref prog.defaultArg)."""
    if isinstance(t, T.PtrType):
        if t.optional:
            return PointerArg(t, 0, 0, 0, None)  # null
        return PointerArg(t, 0, 0, 0, default_arg(t.elem) if t.elem is not None
                          else DataArg(_blob_type(t), b""))
    if isinstance(t, T.VmaType):
        return PointerArg(t, 0, 0, 1, None)
    if isinstance(t, T.BufferType):
        sz = t.fixed_size()
        if t.kind == T.BufferKind.STRING and t.values and len(t.values) == 1:
            data = t.values[0].encode()
            if t.str_length:
                data = data.ljust(t.str_length, b"\x00")[: t.str_length]
            else:
                data += b"\x00"
            return DataArg(t, data)
        return DataArg(t, bytes(sz or 0))
    if isinstance(t, T.ArrayType):
        if t.kind == T.ArrayKind.RANGE_LEN and t.range_begin == t.range_end:
            return GroupArg(t, [default_arg(t.elem) for _ in range(t.range_begin)])
        return GroupArg(t, [])
    if isinstance(t, T.StructType):
        return GroupArg(t, [default_arg(f) for f in t.fields])
    if isinstance(t, T.UnionType):
        opt = t.options[0]
        return UnionArg(t, default_arg(opt), opt)
    if isinstance(t, T.ResourceType):
        return ResultArg(t, None, t.default())
    # Scalars: const/int/flags/proc/len.
    return ConstArg(t, t.default())


def _blob_type(ptr: T.PtrType) -> T.BufferType:
    return T.BufferType(name="blob", dir=ptr.dir, kind=T.BufferKind.BLOB_RAND)


def default_call(meta: T.Syscall) -> Call:
    c = Call(meta, [default_arg(a) for a in meta.args])
    if meta.ret is not None:
        c.ret = ReturnArg(meta.ret)
    return c


# ---------------------------------------------------------------------------
# Tree surgery (ref prog/prog.go:174-245).  All of these keep uses-links
# consistent: removing a subtree detaches every ResultArg in it from its
# target, and rewrites every external reference INTO it to a literal.


def _detach_subtree(arg: Arg) -> None:
    """Sever all cross-links of a subtree being removed from a prog."""

    def fix(a: Arg, _p):
        # References FROM the removed subtree to surviving args.
        if isinstance(a, ResultArg) and a.res is not None:
            a.res.uses.discard(a)
            a.res = None
        # References INTO the removed subtree from surviving args.
        for user in list(a.uses):
            user.res = None
            user.val = user.typ.default() if hasattr(user.typ, "default") else 0
        a.uses.clear()

    foreach_subarg(arg, fix)
    if isinstance(arg, ReturnArg):
        for user in list(arg.uses):
            user.res = None
            user.val = 0
        arg.uses.clear()


def replace_arg(call: Call, old: Arg, new: Arg) -> None:
    """Replace old with new anywhere in call's arg tree; old's subtree is
    detached, and uses of old transfer to new."""
    for user in list(old.uses):
        user.res = new
        new.uses.add(user)
        old.uses.discard(user)
    _detach_subtree(old)

    def sub(args: list[Arg]) -> bool:
        for i, a in enumerate(args):
            if a is old:
                args[i] = new
                return True
            if isinstance(a, PointerArg) and a.res is old:
                a.res = new
                return True
            if isinstance(a, UnionArg):
                if a.option is old:
                    a.option = new
                    return True
                if sub([a.option]):
                    return True
            if isinstance(a, PointerArg) and a.res is not None:
                if sub([a.res]):
                    return True
            if isinstance(a, GroupArg) and sub(a.inner):
                return True
        return False

    if not sub(call.args):
        raise ValueError("replace_arg: old arg not found in call")


def remove_call(p: Prog, idx: int) -> None:
    """Remove call idx, rewriting all references to its results."""
    c = p.calls[idx]
    for a in c.args:
        _detach_subtree(a)
    if c.ret is not None:
        _detach_subtree(c.ret)
    del p.calls[idx]


def insert_before(p: Prog, idx: int, calls: list[Call]) -> None:
    p.calls[idx:idx] = calls


# ---------------------------------------------------------------------------
# Clone (ref prog/clone.go:6-50): deep copy preserving result cross-links.


def clone_prog(p: Prog) -> Prog:
    argmap: dict[int, Arg] = {}
    fixups: list[ResultArg] = []

    def cl(a: Arg) -> Arg:
        if isinstance(a, ConstArg):
            n: Arg = ConstArg(a.typ, a.val)
        elif isinstance(a, ResultArg):
            n = ResultArg.__new__(ResultArg)
            Arg.__init__(n, a.typ)
            n.res, n.val, n.op_div, n.op_add = a.res, a.val, a.op_div, a.op_add
            fixups.append(n)
        elif isinstance(a, PointerArg):
            n = PointerArg(a.typ, a.page, a.offset, a.npages,
                           cl(a.res) if a.res is not None else None)
        elif isinstance(a, PageSizeArg):
            n = PageSizeArg(a.typ, a.npages)
        elif isinstance(a, DataArg):
            n = DataArg(a.typ, a.data)
        elif isinstance(a, GroupArg):
            n = GroupArg(a.typ, [cl(x) for x in a.inner])
        elif isinstance(a, UnionArg):
            n = UnionArg(a.typ, cl(a.option), a.option_typ)
        elif isinstance(a, ReturnArg):
            n = ReturnArg(a.typ)
        else:
            raise TypeError(f"clone: unknown arg {type(a)}")
        argmap[id(a)] = n
        return n

    np_ = Prog()
    for c in p.calls:
        nc = Call(c.meta, [cl(a) for a in c.args])
        if c.ret is not None:
            nc.ret = cl(c.ret)  # type: ignore[assignment]
        np_.calls.append(nc)
    for ra in fixups:
        if ra.res is not None:
            tgt = argmap.get(id(ra.res))
            if tgt is None:
                raise ValueError("clone: dangling result reference")
            ra.res = tgt
            tgt.uses.add(ra)
    return np_
