"""The program layer: model, generation, mutation, serialization.

Host-side structured core (SURVEY §7): program trees are branchy CPU
work; all *sampling decisions* flow through prog.rand.Rand, which can be
batch-refilled from device-generated randomness.
"""

from syzkaller_tpu.prog.model import (  # noqa: F401
    Arg, Call, ConstArg, DataArg, GroupArg, PageSizeArg, PointerArg, Prog,
    ResultArg, ReturnArg, UnionArg, clone_prog, default_arg, default_call,
    foreach_arg, foreach_subarg, insert_before, remove_call, replace_arg,
)
from syzkaller_tpu.prog.analysis import (  # noqa: F401
    State, analyze, assign_sizes_call, sanitize_call,
)
from syzkaller_tpu.prog.encoding import (  # noqa: F401
    DeserializeError, call_set, deserialize, serialize,
)
from syzkaller_tpu.prog.encodingexec import serialize_for_exec  # noqa: F401
from syzkaller_tpu.prog.generation import generate  # noqa: F401
from syzkaller_tpu.prog.mutation import (  # noqa: F401
    minimize, minimize_steps, mutate, mutate_sequence, trim_after,
)
from syzkaller_tpu.prog.parse import parse_log  # noqa: F401
from syzkaller_tpu.prog.prio import ChoiceTable, calculate_priorities  # noqa: F401
from syzkaller_tpu.prog.rand import Gen, Rand  # noqa: F401
from syzkaller_tpu.prog.validation import ValidationError, validate  # noqa: F401
