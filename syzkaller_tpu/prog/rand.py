"""Randomness + per-type argument generation.

Capability parity with reference prog/rand.go: weighted choose
(:498-519), biasedRand (:88), special ints (:50-58), flags/buffers/
filenames/strings (:95-208), page-aware address allocation incl. mmap
call creation (:292-381), recursive resource construction (:383-454),
and per-type generateArg (:569-723).

TPU-first design difference: all randomness flows through `Rand`, which
consumes from a refillable batch of uniform draws.  The hot fuzzing loop
refills the batch from device-generated tensors (one jit call produces
randomness for thousands of decisions — the reference draws one number
at a time, prog/rand.go:498), while tests/tools can seed it from numpy
directly.  Draw order is deterministic given the seed, which keeps
minimization/repro replayable (SURVEY §7 hard parts).
"""

from __future__ import annotations

import numpy as np

from syzkaller_tpu.prog import model as M
from syzkaller_tpu.prog.analysis import State
from syzkaller_tpu.sys import types as T
from syzkaller_tpu.sys.table import SyscallTable


def text_mode(t) -> "int | None":
    """TextKind → ifuzz x86 mode bit (None = arm64/unknown)."""
    from syzkaller_tpu import ifuzz as IF
    from syzkaller_tpu.sys import types as TT

    return {
        TT.TextKind.X86_REAL: IF.REAL16,
        TT.TextKind.X86_16: IF.PROT16,
        TT.TextKind.X86_32: IF.PROT32,
        TT.TextKind.X86_64: IF.LONG64,
    }.get(getattr(t, "text_kind", None))


class Rand:
    """Uniform-uint64 stream with fuzzing-flavored helpers.

    Backed by a numpy Generator by default; `refill(words)` lets a device
    PRNG (jax.random) push batches of raw uint64s that are consumed before
    any host-side draws happen.
    """

    def __init__(self, seed: "int | np.random.Generator" = 0):
        self._g = seed if isinstance(seed, np.random.Generator) else np.random.default_rng(seed)
        self._pool: np.ndarray = np.empty(0, dtype=np.uint64)
        self._pos = 0
        self._source = None
        self._source_batch = 8192

    def refill(self, words: np.ndarray) -> None:
        """Push a batch of device-generated uint64 randomness.
        Unconsumed words from the previous batch are kept — they cost a
        device draw, and discarding them would skew the refill economy
        toward the host fallback."""
        words = np.asarray(words, dtype=np.uint64)
        if self._pos < len(self._pool):
            words = np.concatenate([self._pool[self._pos:], words])
        self._pool = words
        self._pos = 0

    def attach_source(self, fn, batch: int = 8192) -> None:
        """Attach a pull-based entropy source (the decision stream's
        `take_entropy`): when the pool drains mid-draw, the next slab is
        pulled automatically — callers no longer poll exhausted() at
        every refill site.  A failing source detaches itself so a dead
        device degrades to the host generator instead of raising per
        draw."""
        self._source = fn
        self._source_batch = batch

    def exhausted(self) -> bool:
        """True when the device pool has drained (time to refill)."""
        return self._pos >= len(self._pool)

    def rand64(self) -> int:
        if self._pos >= len(self._pool) and self._source is not None:
            try:
                self.refill(self._source(self._source_batch))
            except Exception:
                self._source = None
        if self._pos < len(self._pool):
            v = int(self._pool[self._pos])
            self._pos += 1
            return v
        return int(self._g.integers(0, 1 << 64, dtype=np.uint64))

    def intn(self, n: int) -> int:
        """Uniform in [0, n)."""
        if n <= 1:
            return 0
        return self.rand64() % n

    def one_of(self, n: int) -> bool:
        return self.intn(n) == 0

    def bin(self) -> bool:
        return self.intn(2) == 0

    def rand_range(self, lo: int, hi: int) -> int:
        if hi <= lo:
            return lo
        return lo + self.intn(hi - lo + 1)

    def bytes(self, n: int) -> bytes:
        # 8 bytes per drawn word — a 4KB blob must not drain a whole
        # device-refilled pool (one refill batch feeds thousands of draws).
        out = bytearray()
        while len(out) < n:
            out += self.rand64().to_bytes(8, "little")
        return bytes(out[:n])

    def biased_rand(self, n: int, k: int) -> int:
        """Pick in [0,n) with bias toward 0; k=1 flat, k=2 quadratic...
        (ref prog/rand.go:88)."""
        nf, kf = float(n), float(k)
        u = (self.rand64() >> 11) / float(1 << 53)
        v = nf * (u ** (1.0 / kf))
        return min(int(v), n - 1)

    def choose_weighted(self, weights: list[int]) -> int:
        total = sum(weights)
        x = self.intn(total)
        for i, w in enumerate(weights):
            if x < w:
                return i
            x -= w
        return len(weights) - 1


SPECIAL_INTS = [
    0, 1, 0xFFFFFFFFFFFFFFFF, 1 << 15, 1 << 16, 1 << 31, 1 << 32,
    0xFF, 0x7F, 0x80, 0xFFFF, 0x7FFF, 0x8000, 0xFFFFFFFF, 0x7FFFFFFF,
    0x80000000, 4096, 4097,
]


class Gen:
    """One program-generation context: rand + replayed state + tables.

    Produces (arg, extra_calls) pairs the way the reference generateArg
    does — extra_calls are resource constructors / mmaps that must run
    before the call under construction.
    """

    RECURSION_LIMIT = 3

    def __init__(self, rand: Rand, state: State, table: SyscallTable,
                 choice_table=None, pid: int = 0):
        self.r = rand
        self.s = state
        self.table = table
        self.ct = choice_table
        self.pid = pid
        self._res_depth = 0

    # -- scalar values -------------------------------------------------------

    def rand_int(self, width: int = 8) -> int:
        r = self.r
        if r.one_of(3):
            v = SPECIAL_INTS[r.intn(len(SPECIAL_INTS))]
        elif r.one_of(2):
            v = r.intn(256)
        else:
            v = r.rand64()
        return v & ((1 << (8 * width)) - 1)

    def flags_value(self, vals: tuple[int, ...]) -> int:
        r = self.r
        if not vals:
            return self.rand_int()
        if r.one_of(10):
            return 0
        if r.one_of(10):
            return self.rand_int()
        v = vals[r.intn(len(vals))]
        while r.one_of(3):
            v |= vals[r.intn(len(vals))]
        return v

    def filename(self) -> bytes:
        files = sorted(self.s.files)
        if files and not self.r.one_of(3):
            return files[self.r.intn(len(files))]
        return b"./file%d\x00" % self.r.intn(3)

    def rand_string(self, t: T.BufferType) -> bytes:
        r = self.r
        if t.values:
            data = t.values[r.intn(len(t.values))].encode()
        else:
            strs = sorted(self.s.strings)
            if strs and r.bin():
                data = strs[r.intn(len(strs))]
            else:
                punct = b"!@#$%^&*()-=+\\/.,-_0x"
                out = bytearray()
                while not r.one_of(4):
                    if r.one_of(3):
                        out.append(punct[r.intn(len(punct))])
                    else:
                        out.append(r.intn(256))
                data = bytes(out)
        if t.str_length:
            data = data.ljust(t.str_length, b"\x00")[: t.str_length]
        elif not data.endswith(b"\x00"):
            data += b"\x00"
        return data

    # -- address allocation (ref prog/rand.go:292-381) -----------------------

    def alloc_addr(self, size: int) -> tuple[int, int, list[M.Call]]:
        """Bump-allocate `size` bytes in the data window; returns
        (page, offset, mmap_calls).  Unmapped pages in the span get an
        mmap call created (ref createMmapCall rand.go:355-381).
        Sequential allocation keeps distinct pointees non-overlapping
        within one program."""
        npages = max(1, (size + M.PAGE_SIZE - 1) // M.PAGE_SIZE)
        cursor = getattr(self.s, "_alloc_cursor", 0)
        if cursor + npages > M.MAX_PAGES:
            cursor = 0
        page = cursor
        self.s._alloc_cursor = cursor + npages  # type: ignore[attr-defined]
        calls: list[M.Call] = []
        unmapped = [i for i in range(page, page + npages) if not self.s.pages[i]]
        if unmapped:
            lo, hi = min(unmapped), max(unmapped)
            calls.append(self.mmap_call(lo, hi - lo + 1))
            self.s.mark_pages(lo, hi - lo + 1, True)
        return page, 0, calls

    def alloc_vma(self, npages: int) -> tuple[int, list[M.Call]]:
        page = self.s.alloc_pages(npages)
        if page is not None and not self.r.one_of(5):
            return page, []
        page = self.r.intn(M.MAX_PAGES - npages) if M.MAX_PAGES > npages else 0
        call = self.mmap_call(page, npages)
        self.s.mark_pages(page, npages, True)
        return page, [call]

    def mmap_call(self, page: int, npages: int) -> M.Call:
        meta = self.table.call_map.get("mmap")
        if meta is None:
            raise RuntimeError("description set has no mmap call")
        PROT_RW, MAP_AF = 0x3, 0x32  # PROT_READ|WRITE, ANON|PRIVATE|FIXED
        args: list[M.Arg] = []
        for i, at in enumerate(meta.args):
            if i == 0:
                args.append(M.PointerArg(at, page, 0, npages, None))
            elif i == 1:
                args.append(M.PageSizeArg(at, npages))
            elif i == 2:
                args.append(M.ConstArg(at, PROT_RW))
            elif i == 3:
                args.append(M.ConstArg(at, MAP_AF))
            else:
                args.append(M.default_arg(at))
        c = M.Call(meta, args)
        if meta.ret is not None:
            c.ret = M.ReturnArg(meta.ret)
        return c

    # -- resources (ref prog/rand.go:383-454) --------------------------------

    def resource_arg(self, t: T.ResourceType) -> tuple[M.Arg, list[M.Call]]:
        r = self.r
        existing: list[M.Arg] = []
        for kname, produced in self.s.resources.items():
            src = self.table.resources.get(kname)
            if src is not None and T.kind_compatible(t.desc.kind, src.kind):
                existing.extend(produced)
        # Mostly reuse, sometimes construct fresh, rarely a literal.
        if existing and not r.one_of(3):
            return M.ResultArg(t, existing[r.intn(len(existing))], 0), []
        if self._res_depth < self.RECURSION_LIMIT:
            ctors = self.table.resource_constructors(t.desc.name)
            if ctors and not r.one_of(4):
                self._res_depth += 1
                try:
                    meta = ctors[r.intn(len(ctors))]
                    calls = self.generate_particular_call(meta)
                finally:
                    self._res_depth -= 1
                # Find what the new calls produced.
                produced = self.s.resources.get(t.desc.name, [])
                if not produced:
                    for kname, args in self.s.resources.items():
                        src = self.table.resources.get(kname)
                        if src is not None and T.kind_compatible(t.desc.kind, src.kind):
                            produced = args
                            break
                if produced:
                    return M.ResultArg(t, produced[-1], 0), calls
                return M.ResultArg(t, None, t.default()), calls
        vals = t.special_values()
        return M.ResultArg(t, None, vals[r.intn(len(vals))]), []

    # -- per-type generation (ref prog/rand.go:569-723) ----------------------

    def generate_arg(self, t: T.Type) -> tuple[M.Arg, list[M.Call]]:
        r = self.r
        if t.optional and t.dir != T.Dir.OUT and r.one_of(5):
            return M.default_arg(t), []
        # Output-only scalars carry no interesting value.
        if t.dir == T.Dir.OUT and isinstance(
                t, (T.IntType, T.FlagsType, T.ConstType, T.ProcType, T.LenType)):
            return M.ConstArg(t, 0), []

        if isinstance(t, T.ConstType):
            return M.ConstArg(t, t.val), []
        if isinstance(t, T.IntType):
            if t.kind == T.IntKind.RANGE:
                return M.ConstArg(t, self._signed_range(t)), []
            if t.kind == T.IntKind.SIGNALNO:
                return M.ConstArg(t, r.intn(33)), []
            if t.kind == T.IntKind.FILEOFF:
                return M.ConstArg(t, r.intn(M.MAX_PAGES) * M.PAGE_SIZE
                                  if r.one_of(2) else r.intn(100)), []
            return M.ConstArg(t, self.rand_int(t.type_size)), []
        if isinstance(t, T.FlagsType):
            return M.ConstArg(t, self.flags_value(t.vals)), []
        if isinstance(t, T.LenType):
            return M.ConstArg(t, 0), []  # solved by assign_sizes_call
        if isinstance(t, T.ProcType):
            return M.ConstArg(t, r.intn(max(1, t.values_per_proc))), []
        if isinstance(t, T.ResourceType):
            return self.resource_arg(t)
        if isinstance(t, T.VmaType):
            npages = (r.rand_range(t.range_begin, t.range_end)
                      if t.range_end else 1 + r.biased_rand(4, 2))
            npages = max(1, npages)
            page, calls = self.alloc_vma(npages)
            return M.PointerArg(t, page, 0, npages, None), calls
        if isinstance(t, T.BufferType):
            return self._buffer_arg(t)
        if isinstance(t, T.PtrType):
            elem_t = t.elem
            if elem_t is None:
                elem_t = T.BufferType(name="blob", dir=t.dir, kind=T.BufferKind.BLOB_RAND)
            elem, calls = self.generate_arg(elem_t)
            page, off, mcalls = self.alloc_addr(elem.size())
            return M.PointerArg(t, page, off, 0, elem), mcalls + calls
        if isinstance(t, T.ArrayType):
            if t.kind == T.ArrayKind.RANGE_LEN:
                n = r.rand_range(t.range_begin, t.range_end)
            else:
                n = r.biased_rand(10, 3)
            inner: list[M.Arg] = []
            calls: list[M.Call] = []
            for _ in range(n):
                a, cs = self.generate_arg(t.elem)
                inner.append(a)
                calls.extend(cs)
            return M.GroupArg(t, inner), calls
        if isinstance(t, T.StructType):
            special = self._special_struct(t)
            if special is not None:
                return special
            inner = []
            calls = []
            for f in t.fields:
                a, cs = self.generate_arg(f)
                inner.append(a)
                calls.extend(cs)
            return M.GroupArg(t, inner), calls
        if isinstance(t, T.UnionType):
            opt = t.options[r.intn(len(t.options))]
            a, calls = self.generate_arg(opt)
            return M.UnionArg(t, a, opt), calls
        raise TypeError(f"generate_arg: unknown type {type(t)}")

    def _signed_range(self, t: T.IntType) -> int:
        v = self.r.rand_range(t.range_begin, t.range_end)
        return v & ((1 << (8 * t.type_size)) - 1)  # two's complement wrap

    def _buffer_arg(self, t: T.BufferType) -> tuple[M.Arg, list[M.Call]]:
        r = self.r
        if t.dir == T.Dir.OUT:
            # Out buffers only need a size; contents are kernel-written.
            sz = t.fixed_size()
            if sz is None:
                sz = (r.rand_range(t.range_begin, t.range_end)
                      if t.kind == T.BufferKind.BLOB_RANGE else r.intn(256))
            return M.DataArg(t, bytes(sz)), []
        if t.kind == T.BufferKind.BLOB_RAND:
            n = r.intn(256) if not r.one_of(20) else r.intn(4096)
            return M.DataArg(t, r.bytes(n)), []
        if t.kind == T.BufferKind.BLOB_RANGE:
            n = r.rand_range(t.range_begin, t.range_end)
            return M.DataArg(t, r.bytes(n)), []
        if t.kind == T.BufferKind.STRING:
            return M.DataArg(t, self.rand_string(t)), []
        if t.kind == T.BufferKind.FILENAME:
            return M.DataArg(t, self.filename()), []
        if t.kind == T.BufferKind.TEXT:
            # mode-aware instruction streams (ifuzz equivalent,
            # ref ifuzz/ifuzz.go:16-22 + prog/rand.go TEXT path)
            from syzkaller_tpu import ifuzz as IF
            mode = text_mode(t)
            if mode is None:
                return M.DataArg(t, IF.generate_arm64(r)), []
            return M.DataArg(t, IF.generate(r, mode)), []
        raise TypeError(f"buffer kind {t.kind}")

    def _special_struct(self, t: T.StructType) -> "tuple[M.Arg, list[M.Call]] | None":
        """timespec/timeval get small realistic values so timeout-taking
        syscalls actually return (ref prog/rand.go:210-290)."""
        if t.name not in ("timespec", "timeval") or len(t.fields) != 2:
            return None
        r = self.r
        sec = M.ConstArg(t.fields[0], r.intn(2))
        usec = M.ConstArg(t.fields[1], r.intn(1000))
        return M.GroupArg(t, [sec, usec]), []

    # -- whole calls ---------------------------------------------------------

    def generate_particular_call(self, meta: T.Syscall) -> list[M.Call]:
        """Build one call (plus any prerequisite calls) and replay it into
        the state so later calls see its resources."""
        from syzkaller_tpu.prog import analysis

        c = M.Call(meta, [])
        calls: list[M.Call] = []
        for at in meta.args:
            a, extra = self.generate_arg(at)
            c.args.append(a)
            calls.extend(extra)
        if meta.ret is not None:
            c.ret = M.ReturnArg(meta.ret)
        analysis.assign_sizes_call(c)
        analysis.sanitize_call(c)
        out = calls + [c]
        for cc in out:
            self.s.analyze_call(cc)
        return out

    def generate_call(self, prev_call_id: int = -1) -> list[M.Call]:
        if self.ct is not None:
            idx = self.ct.choose(self.r, prev_call_id)
            meta = self.table.calls[idx]
        else:
            meta = self.table.calls[self.r.intn(len(self.table.calls))]
        return self.generate_particular_call(meta)
