"""Execution-log parsing: recover programs from fuzzer/crash logs.

Capability parity with reference prog/parse.go:19-68 (ParseLog): split a
console/crash log on "executing program N:" markers, deserialize each
block, and keep the per-proc attribution so repro can identify suspects
(ref repro/repro.go:136-148).
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from syzkaller_tpu.prog import encoding
from syzkaller_tpu.prog import model as M
from syzkaller_tpu.sys.table import SyscallTable

_MARKER = re.compile(rb"executing program (\d+):")


@dataclass
class LogEntry:
    prog: M.Prog
    proc: int      # which fuzzer proc executed it
    start: int     # byte offset of the marker in the log
    end: int       # byte offset just past the program text


def parse_log(data: bytes, table: SyscallTable) -> list[LogEntry]:
    out: list[LogEntry] = []
    matches = list(_MARKER.finditer(data))
    for i, m in enumerate(matches):
        start = m.end()
        end = matches[i + 1].start() if i + 1 < len(matches) else len(data)
        block = data[start:end]
        lines = []
        consumed = start
        for raw in block.splitlines(keepends=True):
            line = raw.strip()
            if line and not _looks_like_prog_line(line):
                break
            consumed += len(raw)
            if line:
                lines.append(line.decode(errors="replace"))
        if not lines:
            continue
        try:
            prog = encoding.deserialize("\n".join(lines).encode(), table)
        except encoding.DeserializeError:
            continue
        if prog.calls:
            out.append(LogEntry(prog=prog, proc=int(m.group(1)),
                                start=m.start(), end=consumed))
    return out


def _looks_like_prog_line(line: bytes) -> bool:
    # call lines are "name(...)" or "rN = name(...)"; console noise isn't.
    head = line.split(b"(", 1)[0]
    if b"(" not in line:
        return False
    if b"=" in head:
        lhs, _, rhs = head.partition(b"=")
        head = rhs.strip()
        if not re.fullmatch(rb"r\d+", lhs.strip()):
            return False
    return re.fullmatch(rb"[a-zA-Z_][\w$]*", head.strip()) is not None
