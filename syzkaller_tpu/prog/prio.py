"""Call-pair priorities and the choice table (host reference version).

Capability parity with reference prog/prio.go: CalculatePriorities =
static ⊙ dynamic (:29-38), static priorities from shared resource /
pointer / filename usage (:40-135), dynamic priorities from pairwise
corpus co-occurrence (:137-154), normalization to [0.1, 1] (:158-192),
prefix-sum choice-table rows ×1000 (:202-228) and binary-search Choose
with rejection of disabled calls (:230-249).

This numpy implementation is the semantic reference; the device version
(syzkaller_tpu/cover/engine.py) holds the same prefix-sum matrix
device-resident and draws whole batches of (prev_call → next_call)
decisions in one jit call — prio.go:230-249 vectorized, per the
BASELINE north star.
"""

from __future__ import annotations

import numpy as np

from syzkaller_tpu.prog import model as M
from syzkaller_tpu.prog.rand import Rand
from syzkaller_tpu.sys import types as T
from syzkaller_tpu.sys.table import SyscallTable


def static_priorities(table: SyscallTable) -> np.ndarray:
    """Pairwise affinity from shared type usage.  Uses are weighted like
    the reference (prio.go:40-135): writing a resource is worth more than
    reading one; generic types (pointers, filenames) are weak signals."""
    n = table.count
    # kind-chain-prefix -> accumulated [uses_as_input, produces] per call
    uses: dict[tuple, np.ndarray] = {}

    def note(cid: int, key: tuple, w_in: float, w_out: float):
        m = uses.setdefault(key, np.zeros((n, 2), dtype=np.float32))
        m[cid, 0] += w_in
        m[cid, 1] += w_out

    for c in table.calls:
        def visit(t: T.Type, cid=c.id):
            if isinstance(t, T.ResourceType):
                # every prefix of the kind chain creates affinity, weaker
                # for more generic prefixes
                chain = t.desc.kind
                for plen in range(1, len(chain) + 1):
                    w = 0.3 + 0.7 * plen / len(chain)
                    if t.dir == T.Dir.IN:
                        note(cid, chain[:plen], w, 0.0)
                    else:
                        note(cid, chain[:plen], 0.0, w)
            elif isinstance(t, T.BufferType) and t.kind == T.BufferKind.FILENAME:
                note(cid, ("<filename>",), 0.5, 0.5)
            elif isinstance(t, T.VmaType):
                note(cid, ("<vma>",), 0.3, 0.3)

        T.foreach_type(c, visit)

    prios = np.zeros((n, n), dtype=np.float32)
    for m in uses.values():
        # call i producing what call j consumes (and vice versa) => affinity
        prios += np.outer(m[:, 1], m[:, 0])
        prios += np.outer(m[:, 0], m[:, 1]) * 0.5
        prios += np.outer(m[:, 0], m[:, 0]) * 0.3
    # Same call-name variants attract each other.
    by_name: dict[str, list[int]] = {}
    for c in table.calls:
        by_name.setdefault(c.call_name, []).append(c.id)
    for ids in by_name.values():
        for i in ids:
            for j in ids:
                prios[i, j] += 1.0
    return _normalize(prios)


def dynamic_priorities(corpus: "list[M.Prog]", ncalls: int) -> np.ndarray:
    """Co-occurrence counts over the corpus (prio.go:137-154)."""
    prios = np.zeros((ncalls, ncalls), dtype=np.float32)
    for p in corpus:
        ids = sorted({c.meta.id for c in p.calls})
        for i in ids:
            for j in ids:
                if i != j:
                    prios[i, j] += 1.0
    # Dampen: sqrt keeps a few hot pairs from dominating.
    return _normalize(np.sqrt(prios))


def _normalize(prios: np.ndarray) -> np.ndarray:
    """Row-normalize to [0.1, 1] (prio.go:158-192): every pair keeps a
    floor probability so nothing is starved."""
    out = np.empty_like(prios)
    for i in range(prios.shape[0]):
        row = prios[i]
        mx = row.max()
        out[i] = 0.1 + 0.9 * (row / mx) if mx > 0 else 1.0
    return out


def calculate_priorities(table: SyscallTable,
                         corpus: "list[M.Prog] | None" = None) -> np.ndarray:
    st = static_priorities(table)
    if corpus:
        dyn = dynamic_priorities(corpus, table.count)
        return st * dyn
    return st


class ChoiceTable:
    """Prefix-sum sampling table (prio.go:202-249)."""

    def __init__(self, prios: np.ndarray, enabled: "set[int] | None" = None,
                 ncalls: "int | None" = None):
        n = ncalls if ncalls is not None else prios.shape[0]
        self.enabled = set(range(n)) if enabled is None else set(enabled)
        mask = np.zeros(n, dtype=np.float32)
        for i in self.enabled:
            mask[i] = 1.0
        scaled = np.round(prios * 1000.0) * mask[None, :]
        self.run = np.cumsum(scaled, axis=1).astype(np.int64)  # (n, n) prefix sums
        self.enabled_list = sorted(self.enabled)

    def choose(self, r: Rand, prev_call_id: int = -1) -> int:
        if prev_call_id < 0 or self.run[prev_call_id, -1] == 0:
            return self.enabled_list[r.intn(len(self.enabled_list))]
        row = self.run[prev_call_id]
        for _ in range(100):
            x = r.intn(int(row[-1])) + 1
            idx = int(np.searchsorted(row, x))
            if idx in self.enabled:
                return idx
        return self.enabled_list[r.intn(len(self.enabled_list))]
