"""Filesystem helpers: atomic writes, temp dirs, recursive copy.

Capability parity with the reference fileutil package.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import tempfile


def write_file(path: str, data: bytes) -> None:
    """Atomically write data to path (write temp + rename)."""
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def copy_tree(src: str, dst: str) -> None:
    shutil.copytree(src, dst, dirs_exist_ok=True)


def process_temp_dir(prefix: str = "syz-tpu-") -> str:
    """Create a temp dir owned by this process; caller removes it."""
    return tempfile.mkdtemp(prefix=prefix)


def umount_all(path: str) -> None:
    """Best-effort recursive unmount under path (sandbox teardown helper).

    Directory names come from the fuzzed workload, so no shell is involved.
    """
    for root, dirs, _files in os.walk(path, topdown=False):
        for d in dirs:
            subprocess.run(["umount", "-f", os.path.join(root, d)],
                           capture_output=True, check=False)
