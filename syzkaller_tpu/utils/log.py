"""Leveled logging with an in-memory ring cache.

Equivalent capability to the reference's log package (log/log.go:4-44):
global verbosity, Logf-style calls, and an optional bounded in-memory
cache of recent lines that the manager HTTP UI can serve.
"""

from __future__ import annotations

import collections
import sys
import threading
import time

_lock = threading.Lock()
_verbosity = 0
_cache: collections.deque[str] | None = None


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = v


def enable_log_caching(max_lines: int = 1000) -> None:
    global _cache
    with _lock:
        _cache = collections.deque(maxlen=max_lines)


def cached_log() -> str:
    with _lock:
        return "\n".join(_cache) if _cache else ""


def logf(level: int, fmt: str, *args) -> None:
    if level > _verbosity:
        return
    msg = (fmt % args) if args else fmt
    line = f"{time.strftime('%Y/%m/%d %H:%M:%S')} {msg}"
    with _lock:
        if _cache is not None:
            _cache.append(line)
    print(line, file=sys.stderr, flush=True)


def fatalf(fmt: str, *args) -> None:
    logf(0, "FATAL: " + fmt, *args)
    raise SystemExit(1)
