"""Shape bucketing for jitted dispatch call sites.

Every distinct argument shape at a jit call site compiles a fresh XLA
executable, so data-dependent sizes must be padded to a small closed
set of shapes before dispatch.  `pow2_bucket` is the canonical helper:
round up to a power of two within [lo, hi], keeping the compiled-shape
set O(log(hi/lo)) while small batches avoid full-size kernel cost.
The static analyzer (syzkaller_tpu/vet, retrace pass) recognizes it as
a shape cleanser — route raw `len(...)` sizes through here.
"""

from __future__ import annotations


def pow2_bucket(n: int, lo: int, hi: int) -> int:
    """Smallest power-of-two multiple of nothing-fancy ≥ n, clamped to
    [lo, hi].  lo must be a power of two for the result to stay one."""
    b = max(1, lo)
    while b < min(n, hi):
        b *= 2
    return min(b, hi)
