"""Step profiling: on-demand JAX profiler traces of the device hot loop.

SURVEY §5 names this as a required addition over the reference (whose
observability is stats counters + logs): real step profiling of the
device engine.  A capture wraps whatever device work runs during the
window — the fuzzing pipeline keeps executing, so traces show the real
production interleaving (and the log-before-run invariant is untouched:
profiling changes no execution order)."""

from __future__ import annotations

import os
import threading
import time

from syzkaller_tpu.utils import log

_mu = threading.Lock()


def _capture_locked(run_dir: str, seconds: float) -> bool:
    """One trace window, if no other capture is running.  The JAX
    profiler supports a single trace at a time, so captures serialize —
    but by SKIPPING, not by queueing: sleeping the window out while
    holding the lock would stack every concurrent /profile request into
    a blocked thread (syz-vet lock pass, P0 blocking-under-lock)."""
    import jax

    if not _mu.acquire(blocking=False):
        log.logf(0, "profiler: a capture is already running; skipped")
        return False
    try:
        jax.profiler.start_trace(run_dir)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
    finally:
        _mu.release()
    return True


def capture(out_dir: str, seconds: float = 3.0) -> str:
    """Trace all JAX activity for `seconds`; returns the trace dir
    (tensorboard-loadable).  Raises RuntimeError when another capture
    is already in flight."""
    run_dir = os.path.join(out_dir, time.strftime("trace-%Y%m%d-%H%M%S"))
    os.makedirs(run_dir, exist_ok=True)
    log.logf(0, "profiler: capturing %gs into %s", seconds, run_dir)
    if not _capture_locked(run_dir, seconds):
        raise RuntimeError("a profiler capture is already running")
    return run_dir


def capture_async(out_dir: str, seconds: float = 3.0) -> str:
    """Fire-and-forget capture (for HTTP handlers); returns the dir the
    trace will land in.  A capture already in flight makes this a no-op
    (logged), matching the one-trace-at-a-time profiler."""
    run_dir = os.path.join(out_dir, time.strftime("trace-%Y%m%d-%H%M%S"))

    def work():
        os.makedirs(run_dir, exist_ok=True)
        if _capture_locked(run_dir, seconds):
            log.logf(0, "profiler: trace written to %s", run_dir)

    threading.Thread(target=work, daemon=True).start()
    return run_dir
