"""Step profiling: on-demand JAX profiler traces of the device hot loop.

SURVEY §5 names this as a required addition over the reference (whose
observability is stats counters + logs): real step profiling of the
device engine.  A capture wraps whatever device work runs during the
window — the fuzzing pipeline keeps executing, so traces show the real
production interleaving (and the log-before-run invariant is untouched:
profiling changes no execution order)."""

from __future__ import annotations

import os
import threading
import time

from syzkaller_tpu.utils import log

_mu = threading.Lock()


def capture(out_dir: str, seconds: float = 3.0) -> str:
    """Trace all JAX activity for `seconds`; returns the trace dir
    (tensorboard-loadable).  Serialized: one capture at a time."""
    import jax

    run_dir = os.path.join(out_dir, time.strftime("trace-%Y%m%d-%H%M%S"))
    os.makedirs(run_dir, exist_ok=True)
    with _mu:
        log.logf(0, "profiler: capturing %gs into %s", seconds, run_dir)
        jax.profiler.start_trace(run_dir)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
    return run_dir


def capture_async(out_dir: str, seconds: float = 3.0) -> str:
    """Fire-and-forget capture (for HTTP handlers); returns the dir the
    trace will land in."""
    run_dir = os.path.join(out_dir, time.strftime("trace-%Y%m%d-%H%M%S"))

    def work():
        import jax

        os.makedirs(run_dir, exist_ok=True)
        with _mu:
            jax.profiler.start_trace(run_dir)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
        log.logf(0, "profiler: trace written to %s", run_dir)

    threading.Thread(target=work, daemon=True).start()
    return run_dir
