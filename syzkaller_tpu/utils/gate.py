"""Shared/exclusive gate without a mutex held across device work.

Extracted from the manager's AdmissionGate (PR 7) so the resilience
plane can reuse the same pattern: hot-path operations enter *shared*
(an in-flight count); rare maintenance operations (corpus compaction,
backend failover/promotion, snapshotting) enter *exclusive* — they wait
for in-flight shared work to drain and block new shared entries while
they run.  No lock is held inside either region, so device syncs under
the gate never serialize unrelated threads (syz-vet lock discipline).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager


class SharedExclusiveGate:
    def __init__(self):
        self._cv = threading.Condition()
        self._inflight = 0
        self._exclusive = False

    @contextmanager
    def shared(self):
        with self._cv:
            while self._exclusive:
                self._cv.wait()
            self._inflight += 1
        try:
            yield
        finally:
            with self._cv:
                self._inflight -= 1
                if self._inflight == 0:
                    self._cv.notify_all()

    @contextmanager
    def exclusive(self):
        with self._cv:
            while self._exclusive:
                self._cv.wait()
            self._exclusive = True
            while self._inflight:
                self._cv.wait()
        try:
            yield
        finally:
            with self._cv:
                self._exclusive = False
                self._cv.notify_all()

    # admission-plane aliases (the manager's historical vocabulary)
    admitting = shared
    maintenance = exclusive
