"""Content-addressed signatures for corpus programs.

Capability parity with the reference hash package (hash/hash.go:12-35):
short stable hex signatures used as corpus file names and dedup keys.
SHA1 is what the reference uses; we keep it for the same non-cryptographic
content-addressing purpose.
"""

from __future__ import annotations

import hashlib


def sig(data: bytes) -> str:
    """Hex signature of a serialized program (corpus file name / dedup key)."""
    return hashlib.sha1(data).hexdigest()


def sig_bytes(data: bytes) -> bytes:
    return hashlib.sha1(data).digest()
